#!/usr/bin/env python3
"""Benchmark: TPU network-plane packet throughput vs the CPU object plane.

Workload: a PHOLD-style closed loop (the classic PDES benchmark Shadow
ships configs for, `src/test/phold/`) — every delivered packet spawns a new
packet to a pseudorandom destination, so the event population is constant
and every round does real routing/loss/rate-limit work.

- TPU side: N_HOSTS hosts as SoA arrays; R rounds of `window_step` +
  on-device respawn, driven by one `jax.lax.scan` (a single compiled
  program; no host transfers inside the loop).
- Baseline: the same PHOLD logic on the CPU object plane (Host/Worker/
  EventQueue, the faithful Shadow-architecture path) — the stand-in for the
  reference's per-packet CPU cost on this machine.

Prints ONE JSON line:
  {"metric": "packet_events_per_sec", "value": ..., "unit": "events/s",
   "vs_baseline": <tpu_rate / cpu_object_plane_rate>}
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

MS = 1_000_000

N_HOSTS = int(os.environ.get("BENCH_HOSTS", "32768"))
ROUNDS = int(os.environ.get("BENCH_ROUNDS", "192"))
N_NODES = int(os.environ.get("BENCH_NODES", "64"))  # graph nodes (GML-like)
# "xla" (default), "pallas" (two-dispatch egress+route fusion), or
# "pallas_fused" (the single rank→place→egress pipeline) — the
# experimental.plane_kernel flag's bench-side twin (docs/performance.md)
PLANE_KERNEL = os.environ.get("BENCH_PLANE_KERNEL", "xla")
# BENCH_TELEMETRY=1 threads the PlaneMetrics counters through every
# window and harvests heartbeat JSONL + a Perfetto trace into
# BENCH_TELEMETRY_DIR every BENCH_HARVEST_EVERY windows
# (docs/observability.md; the acceptance bar is throughput within 5%
# of the metrics-off path)
TELEMETRY = os.environ.get("BENCH_TELEMETRY", "0") == "1"
# BENCH_HIST=1 (telemetry mode only) additionally threads the
# log2-bucketed latency/depth histograms (telemetry/histo.py) through
# the scan carry: heartbeats gain per-host `hist` bucket vectors and
# the JSON records fleet latency percentiles
# (docs/observability.md "Distributions and the flight recorder")
HIST = os.environ.get("BENCH_HIST", "0") == "1"
# BENCH_FAULTS=1 threads NEUTRAL FaultArrays masks through every window
# (docs/robustness.md): the chaos-smoke CI job compares this against the
# faults-off run — the fault plane's presence switch must stay within 5%
# when nothing fails (same bar as telemetry). Neutral masks are
# bitwise-identity, so the measured delta is pure mask arithmetic.
FAULTS = os.environ.get("BENCH_FAULTS", "0") == "1"
# BENCH_SECTIONS=0 skips the per-section profile rep appended to the
# JSON output: by default one rep of each window-step section
# (profiling.BENCH_SECTIONS) is timed AFTER the measured run, so every
# BENCH_r*.json records WHERE the per-window budget went, not just the
# headline events/s (tools/compare_runs.py --bench diffs two such
# records section by section)
SECTIONS = os.environ.get("BENCH_SECTIONS", "1") == "1"
TELEMETRY_DIR = os.environ.get("BENCH_TELEMETRY_DIR", "telemetry-bench")
HARVEST_EVERY = int(os.environ.get("BENCH_HARVEST_EVERY", "32"))
EGRESS_CAP = int(os.environ.get("BENCH_EGRESS_CAP", "16"))
INGRESS_CAP = int(os.environ.get("BENCH_INGRESS_CAP", "32"))
# BENCH_CAPACITY=elastic|strict drives the capacity policy plane
# (docs/robustness.md "Elastic capacity"): the run proceeds in chunks of
# BENCH_GROW_EVERY windows; a chunk with ring-full overflow is DISCARDED,
# the offending ring doubles (next power of two, bounded by
# BENCH_MAX_DOUBLINGS), and the chunk re-executes from its start
# snapshot — so a run started with tiny rings ends bitwise-identical to
# one pre-provisioned at the final capacity. strict raises CapacityError
# on the first overflow instead. The JSON records the trajectory.
CAPACITY_MODE = os.environ.get("BENCH_CAPACITY", "fixed")
MAX_DOUBLINGS = int(os.environ.get("BENCH_MAX_DOUBLINGS", "4"))
GROW_EVERY = int(os.environ.get("BENCH_GROW_EVERY", "16"))
# BENCH_WORLDS=W (0=off) appends an ensemble rep after the solo run:
# the SAME PHOLD chain vmapped over W worlds with per-world fold_in
# keys (tpu/elastic.drive_ensemble — the SL701/SL702-proven driver,
# docs/determinism.md "Worlds are theorems"), one host sync per chain
# for the whole ensemble. The JSON gains a `worlds` record with the
# summed events/s and the amortization ratio vs W sequential solo
# runs (docs/performance.md "Ensemble amortization"). xla kernel,
# fixed capacity, no telemetry — per-world ring growth would diverge
# array shapes across the batch, so the modes are exclusive by
# construction.
N_WORLDS = int(os.environ.get("BENCH_WORLDS", "0"))
# BENCH_MEMO=1 appends a steady-state memoization rep (tpu/memo.py,
# docs/performance.md "Steady-state memoization"): a ring-allreduce
# scenario whose window budget runs far past collective completion,
# timed cold vs memoized in the same process (shared jit cache, both
# pre-warmed, fresh memo table per run). The JSON gains a `memo`
# record with both wall times, the EFFECTIVE events/s of each (same
# event total, so the ratio is pure fast-forward win), the cache
# stats, and the canonical-digest parity bit — a memo speedup with
# parity=false is a bug report, not a result. Sized by
# BENCH_MEMO_HOSTS/BENCH_MEMO_WINDOWS/BENCH_MEMO_CHAIN so CI smokes
# can run a small twin of the recorded number.
MEMO = os.environ.get("BENCH_MEMO", "0") == "1"
MEMO_HOSTS = int(os.environ.get("BENCH_MEMO_HOSTS", "16"))
MEMO_WINDOWS = int(os.environ.get("BENCH_MEMO_WINDOWS", "4096"))
MEMO_CHAIN = int(os.environ.get("BENCH_MEMO_CHAIN", "64"))
# BENCH_TRACE=PATH writes the shadowscope run ledger (telemetry/
# tracer.RunTracer JSONL, docs/observability.md "Run ledger") for the
# TIMED solo run; a BENCH_WORLDS rep appends its own ensemble ledger
# next to it at PATH.worlds.jsonl. The tracer only samples the clock
# at the chain-boundary host syncs the driver already takes, so the
# measured rate is the same rate CI gates untraced (the <=1.05x
# traced-overhead gate pins that claim).
TRACE_PATH = os.environ.get("BENCH_TRACE", "")
SPAWN_PER_DELIVERY = 1


def bench_tpu() -> tuple[float, int, dict | None, dict, dict | None,
                         dict]:
    import jax
    import jax.numpy as jnp

    from shadow_tpu.tpu import (donating_jit, ingest_rows, unpack_planes,
                                window_step)
    from shadow_tpu.tpu import profiling
    from shadow_tpu.workloads.phold import respawn_batch

    if CAPACITY_MODE not in ("fixed", "strict", "elastic"):
        raise SystemExit(
            f"BENCH_CAPACITY={CAPACITY_MODE!r}: expected "
            f"fixed|strict|elastic")
    if PLANE_KERNEL != "xla" and EGRESS_CAP & (EGRESS_CAP - 1):
        # bench-side twin of the config-time ConfigError: the fused
        # Pallas kernels' bitonic row sorts need power-of-two rings
        # (shadow_tpu/tpu/pallas_egress.py / pallas_pipeline.py) — fail
        # before tracing
        raise SystemExit(
            f"BENCH_PLANE_KERNEL={PLANE_KERNEL} needs a power-of-two "
            f"BENCH_EGRESS_CAP, got {EGRESS_CAP}; pick a power of two "
            f"or use the xla kernel")
    if PLANE_KERNEL == "pallas_fused" and INGRESS_CAP & (INGRESS_CAP - 1):
        raise SystemExit(
            f"BENCH_PLANE_KERNEL=pallas_fused needs a power-of-two "
            f"BENCH_INGRESS_CAP (the fused in-kernel compaction), got "
            f"{INGRESS_CAP}; pick a power of two or use xla/pallas")

    N, M = N_HOSTS, N_NODES
    # ONE definition of the PHOLD world, shared with the per-section
    # profiler (tpu/profiling.build_world): node-level path tables, 4 seed
    # packets per host — so profiler section times correspond to this
    # bench's end-to-end line by construction
    world = profiling.build_world(N, n_nodes=M, egress_cap=EGRESS_CAP,
                                  ingress_cap=INGRESS_CAP, seed=0,
                                  warmup_windows=0)
    state, params = world["state"], world["params"]
    key = world["rng_root"]
    CI = INGRESS_CAP
    window = world["window"]

    # neutral fault masks when BENCH_FAULTS=1 (bitwise-identity; the
    # measured delta is the presence-switch cost, docs/robustness.md)
    _faults = None
    if FAULTS:
        from shadow_tpu.faults import neutral_faults

        _faults = neutral_faults(N, M)

    # ONE window body for every mode (fixed / telemetry / elastic): the
    # presence planes ride the scan carry, the per-ring overflow deltas
    # the capacity policy reads accumulate alongside (idle cost gated in
    # CI as window_step_elastic), and the whole thing is driven in
    # device-resident chains by the SHARED driver loop
    # (`shadow_tpu.tpu.elastic.drive_chained_windows`) — the same loop
    # tools/chaos_smoke.py and the scenario corpus runner use, so every
    # kernel fusion lands in all three at once.
    def make_round_fn(kernel: str):
        def round_fn(carry, round_idx):
            state, spawn_seq, metrics, hist, eg_acc, in_acc = carry
            state0 = state
            shift = jnp.where(round_idx == 0, jnp.int32(0), window)
            out = window_step(state, params, key, shift, window,
                              rr_enabled=False, kernel=kernel,
                              faults=_faults, metrics=metrics,
                              hist=hist)
            ((state, delivered, _next_ev), metrics, _g, hist,
             _fr) = unpack_planes(out, metrics=metrics, hist=hist)
            # ingress-ring overflow (the routing stage's drops) — the
            # elastic capacity driver reads this back per chain
            in_acc = in_acc + (state.n_overflow_dropped
                               - state0.n_overflow_dropped)
            state1 = state
            # respawn: each delivered packet triggers one new packet from
            # the receiving host to a hashed destination (deterministic).
            # The delivered arrays are already row-shaped (row =
            # receiving host), so the row-local ingest needs no flat
            # cross-host sort.
            mask, new_dst, nbytes, seq_vals, ctrl = respawn_batch(
                delivered, spawn_seq, round_idx, N,
                state.in_src.shape[1])
            out = ingest_rows(
                state, new_dst, nbytes,
                seq_vals,  # priority: reuse seq (FIFO-ish)
                seq_vals, ctrl,
                valid=mask,
                metrics=metrics,
                hist=hist,
            )
            (state,), metrics, _g, hist, _fr = unpack_planes(
                out, metrics=metrics, hist=hist, n_lead=1)
            # egress-ring overflow (the respawn append's drops)
            eg_acc = eg_acc + (state.n_overflow_dropped
                               - state1.n_overflow_dropped)
            spawn_seq = spawn_seq + mask.sum(axis=1, dtype=jnp.int32)
            carry = (state, spawn_seq, metrics, hist, eg_acc, in_acc)
            return carry, mask.sum(dtype=jnp.int32)
        return round_fn

    # the state pytree is donated in fixed/telemetry mode: XLA reuses
    # the input buffers for the scan carry instead of materializing a
    # second copy of ~20 [N, C] arrays (donation contract: `state` /
    # `state2` are dead after the call). The elastic driver compiles
    # WITHOUT donation — the chain-start snapshot must stay valid so an
    # overflowing chain can be discarded and re-executed against grown
    # rings (jit retraces once per ring shape, log2-bounded by the
    # power-of-two growth).
    def make_chain(kernel: str):
        round_fn = make_round_fn(kernel)
        wrap = jax.jit if CAPACITY_MODE != "fixed" else donating_jit

        @wrap
        def chain(state, spawn_seq, metrics, hist, round_ids):
            zeros = jnp.zeros((N,), jnp.int32)
            carry, delivered_counts = jax.lax.scan(
                round_fn,
                (state, spawn_seq, metrics, hist, zeros, zeros),
                round_ids)
            state, spawn_seq, metrics, hist, eg, inn = carry
            return (state, spawn_seq, metrics, hist, eg, inn,
                    delivered_counts.sum())
        return chain

    # self-healing (faults/healing.py): a Pallas kernel that fails to
    # lower/compile on this backend demotes the bench to the
    # bitwise-identical XLA path LOUDLY instead of killing the run; the
    # JSON records the fallback so a perf line from the wrong kernel can
    # never masquerade as a healthy pallas measurement
    from shadow_tpu.faults import KernelFallback

    chain_call = KernelFallback(PLANE_KERNEL, make_chain)
    capacity_info: dict | None = None
    if CAPACITY_MODE != "fixed" and TELEMETRY:
        raise SystemExit(
            "BENCH_CAPACITY=elastic/strict and BENCH_TELEMETRY=1 "
            "are mutually exclusive (each owns the chain cadence); "
            "run them separately")
    # windows per host sync: the whole run in fixed mode, the harvest
    # cadence under telemetry, the growth-snapshot cadence under the
    # capacity policy (recorded in the JSON `driver` field)
    CHAIN_LEN = (HARVEST_EVERY if TELEMETRY
                 else GROW_EVERY if CAPACITY_MODE != "fixed" else ROUNDS)

    def run_driver(state, harvester=None, collect=None, tracer=None):
        nonlocal capacity_info
        from shadow_tpu.telemetry import make_histograms, make_metrics
        from shadow_tpu.tpu import elastic

        policy = None
        if CAPACITY_MODE != "fixed":
            policy = elastic.RingPolicy(
                mode=CAPACITY_MODE, max_doublings=MAX_DOUBLINGS,
                egress_cap=EGRESS_CAP, ingress_cap=INGRESS_CAP,
                plane="bench")
        spawn_seq = jnp.full((N,), 10_000, jnp.int32)
        metrics = make_metrics(N) if TELEMETRY else None
        hist = make_histograms(N) if (TELEMETRY and HIST) else None

        def chain_fn(state, extras, rids, _pr):
            spawn_seq, metrics, hist, total = extras
            state, spawn_seq, metrics, hist, eg, inn, nd = chain_call(
                state, spawn_seq, metrics, hist, rids)
            return state, (spawn_seq, metrics, hist, total + nd), eg, inn

        def on_chain(r1, state, extras):
            if harvester is not None:
                if tracer is not None:
                    tracer.annotate("harvest", r=int(r1),
                                    time_ns=int(r1) * int(window))
                _sp, metrics, hist, _t = extras
                device = (dict(metrics._asdict(), **hist._asdict())
                          if hist is not None else metrics)
                harvester.tick(r1 * int(window), device=device)

        state, extras = elastic.drive_chained_windows(
            state, (spawn_seq, metrics, hist, jnp.int32(0)), chain_fn,
            n_rounds=ROUNDS, chain_len=CHAIN_LEN, policy=policy,
            window_ns=int(window),
            on_chain=on_chain if harvester is not None else None,
            tracer=tracer)
        _spawn_seq, metrics, hist, total = extras
        if collect is not None and hist is not None:
            collect["hist"] = hist
        if policy is not None:
            capacity_info = policy.trajectory.as_dict()
            capacity_info["initial"] = {"egress_cap": EGRESS_CAP,
                                        "ingress_cap": INGRESS_CAP}
            capacity_info["final"] = {"egress_cap": policy.egress_cap,
                                      "ingress_cap": policy.ingress_cap}
        return state, total

    driver = run_driver

    # compile
    t0 = time.monotonic()
    state_out, ndel = driver(state)
    jax.block_until_ready(state_out)
    compile_and_first = time.monotonic() - t0

    # timed run (fresh state, compiled): rebuild the identical world —
    # the first state was donated into the compile run
    state2 = profiling.build_world(N, n_nodes=M, egress_cap=EGRESS_CAP,
                                   ingress_cap=INGRESS_CAP, seed=0,
                                   warmup_windows=0)["state"]
    jax.block_until_ready(state2)
    tracer = None
    if TRACE_PATH:
        from shadow_tpu.telemetry import RunTracer

        # the ledger covers the TIMED run only — the compile run's
        # wall time is already reported as compile_and_first
        tracer = RunTracer(
            "bench", backend=backend_fingerprint(),
            meta={"hosts": N, "rounds": ROUNDS, "chain_len": CHAIN_LEN,
                  "kernel": PLANE_KERNEL, "capacity": CAPACITY_MODE,
                  "telemetry": TELEMETRY})
    telemetry_info = None
    if TELEMETRY:
        from shadow_tpu.telemetry import TelemetryHarvester

        os.makedirs(TELEMETRY_DIR, exist_ok=True)
        sink = os.path.join(TELEMETRY_DIR, "heartbeats.jsonl")
        harvester = TelemetryHarvester(
            interval_ns=HARVEST_EVERY * int(window), sink=sink,
            slot_capacity=N * (EGRESS_CAP + INGRESS_CAP))
        collect: dict = {}
        t0 = time.monotonic()
        state_out, ndel = driver(state2, harvester, collect, tracer)
        ndel = int(ndel)
        jax.block_until_ready(state_out)
        wall = time.monotonic() - t0
        # harvest bookkeeping happens OUTSIDE the timed loop's budget
        # claims but inside the wall measurement above — the 5% bar is
        # end-to-end, including the async pulls
        harvester.finalize()
        from shadow_tpu.telemetry import export

        trace = export.write_perfetto_trace(
            harvester.heartbeats,
            os.path.join(TELEMETRY_DIR, "trace.json"))
        telemetry_info = {
            "heartbeats": harvester.emitted,
            "harvests": harvester.harvests,
            "sink": sink,
            "trace": trace["path"],
            "trace_events": trace["events"],
        }
        if "hist" in collect:
            from shadow_tpu.telemetry.histo import (HIST_PREFIX,
                                                    percentiles)

            h = jax.device_get(collect["hist"])
            telemetry_info["latency"] = {
                name[len(HIST_PREFIX):]: percentiles(
                    np.asarray(arr, np.int64).sum(axis=0))
                for name, arr in h._asdict().items()}
    else:
        t0 = time.monotonic()
        state_out, ndel = driver(state2, tracer=tracer)
        ndel = int(ndel)
        jax.block_until_ready(state_out)
        wall = time.monotonic() - t0
    if tracer is not None:
        tracer.close(wall_s=round(wall, 6))
        tracer.write(TRACE_PATH)

    sent = int(np.asarray(state_out.n_sent).sum())
    events = ndel + sent  # send + deliver events, like Shadow's event count
    kernel_info = {
        "requested": PLANE_KERNEL,
        "used": chain_call.kernel,
        "fell_back": chain_call.fell_back,
        "faults_threaded": FAULTS,
    }
    # the chained-driver amortization record (docs/performance.md "The
    # driver loop"): how many windows execute device-resident per host
    # sync — the satellite metric next to the headline events/s
    from shadow_tpu.tpu.elastic import chain_spans

    n_chains = len(chain_spans(ROUNDS, CHAIN_LEN))
    driver_info = {
        "loop": "drive_chained_windows",
        "chain_len": CHAIN_LEN,
        "chains": n_chains,
        "windows_per_sync": round(ROUNDS / max(n_chains, 1), 2),
    }
    return events / wall, events, telemetry_info, kernel_info, \
        capacity_info, driver_info


def bench_tpu_worlds(solo_rate: float) -> dict:
    """The BENCH_WORLDS ensemble rep: the PHOLD chain vmapped over
    N_WORLDS worlds via `drive_ensemble`, per-world keys from the
    proven `world_keys` fold chain, one compiled batched program per
    chain. Returns the `worlds` JSON record — summed delivered+sent
    events/s across the ensemble and the amortization ratio vs
    running the same W worlds as sequential solo runs (approximated
    by W x the solo run's measured rate on this container)."""
    import jax
    import jax.numpy as jnp

    from shadow_tpu.tpu import (ingest_rows, profiling, unpack_planes,
                                window_step)
    from shadow_tpu.tpu import elastic
    from shadow_tpu.workloads.phold import respawn_batch

    W, N, M = N_WORLDS, N_HOSTS, N_NODES
    world = profiling.build_world(N, n_nodes=M, egress_cap=EGRESS_CAP,
                                  ingress_cap=INGRESS_CAP, seed=0,
                                  warmup_windows=0)
    state, params = world["state"], world["params"]
    window = world["window"]
    keys = elastic.world_keys(world["rng_root"],
                              jnp.arange(W, dtype=jnp.int32))
    chain_len = min(GROW_EVERY, ROUNDS) if CAPACITY_MODE != "fixed" \
        else ROUNDS

    def chain_fn(state, extras, rids, _pr):
        key, spawn_seq, total = extras

        def round_fn(carry, round_idx):
            state, spawn_seq = carry
            shift = jnp.where(round_idx == 0, jnp.int32(0), window)
            out = window_step(state, params, key, shift, window,
                              rr_enabled=False)
            (state, delivered, _nx), _m, _g, _h, _fr = \
                unpack_planes(out)
            mask, new_dst, nbytes, seq_vals, ctrl = respawn_batch(
                delivered, spawn_seq, round_idx, N,
                state.in_src.shape[1])
            out = ingest_rows(state, new_dst, nbytes, seq_vals,
                              seq_vals, ctrl, valid=mask)
            (state,), _m, _g, _h, _fr = unpack_planes(out, n_lead=1)
            spawn_seq = spawn_seq + mask.sum(axis=1, dtype=jnp.int32)
            return (state, spawn_seq), mask.sum(dtype=jnp.int32)

        (state, spawn_seq), nd = jax.lax.scan(
            round_fn, (state, spawn_seq), rids)
        zeros = jnp.zeros((N,), jnp.int32)
        return state, (key, spawn_seq, total + nd.sum()), zeros, zeros

    def stacked(tree):
        return jax.tree.map(lambda x: jnp.stack([x] * W), tree)

    def run(states, tracer=None):
        extras = (keys, stacked(jnp.full((N,), 10_000, jnp.int32)),
                  jnp.zeros((W,), jnp.int32))
        states, extras = elastic.drive_ensemble(
            states, extras, chain_fn, n_rounds=ROUNDS,
            chain_len=chain_len, tracer=tracer)
        return states, extras[2]

    # compile run, then the timed run on a fresh replicated state
    states_out, totals = run(stacked(state))
    jax.block_until_ready(states_out)
    state2 = profiling.build_world(
        N, n_nodes=M, egress_cap=EGRESS_CAP, ingress_cap=INGRESS_CAP,
        seed=0, warmup_windows=0)["state"]
    states2 = stacked(state2)
    jax.block_until_ready(states2)
    tracer = None
    if TRACE_PATH:
        from shadow_tpu.telemetry import RunTracer

        tracer = RunTracer(
            "bench-worlds", backend=backend_fingerprint(),
            meta={"worlds": W, "hosts": N, "rounds": ROUNDS,
                  "chain_len": chain_len})
    t0 = time.monotonic()
    states_out, totals = run(states2, tracer)
    totals = np.asarray(jax.device_get(totals), np.int64)
    jax.block_until_ready(states_out)
    wall = time.monotonic() - t0
    if tracer is not None:
        tracer.close(wall_s=round(wall, 6))
        tracer.write(TRACE_PATH + ".worlds.jsonl")

    sent = np.asarray(jax.device_get(states_out.n_sent),
                      np.int64).sum(axis=tuple(range(
                          1, states_out.n_sent.ndim)))
    per_world_events = (totals + sent).tolist()
    events = int(sum(per_world_events))
    rate = events / wall
    return {
        "n_worlds": W,
        "driver": "drive_ensemble",
        "chain_len": chain_len,
        "events": events,
        "min_world_events": int(min(per_world_events)),
        "events_per_sec_sum": round(rate, 1),
        # summed ensemble throughput vs W sequential solo runs (which
        # deliver solo_rate in aggregate): >1 means the world axis
        # amortizes dispatch + compilation across the ensemble
        "amortization_vs_solo": (round(rate / solo_rate, 2)
                                 if solo_rate > 0 else None),
    }


def bench_memo() -> dict:
    """The BENCH_MEMO rep: the same compiled chain driven cold vs
    memoized through `drive_chained_windows`.

    The workload is a MEMO_HOSTS-host ring allreduce (the corpus
    family, real workload plane) driven for MEMO_WINDOWS windows —
    the collective completes early and the drained steady-state tail
    dominates, exactly the traffic shape arxiv 2602.10615 targets.
    Both runs share ONE jitted chain at the SAME span length
    (MEMO_CHAIN) and run after a warm-up pass, so the timed delta is
    execution vs replay — not compilation, not dispatch-pattern
    skew. The memo table is rebuilt from scratch inside the timed
    memoized run: key digests and recording cost are IN the
    measurement, replay hits pay for them."""
    import jax
    import jax.numpy as jnp

    from shadow_tpu.telemetry import make_metrics
    from shadow_tpu.tpu import elastic, unpack_planes, window_step
    from shadow_tpu.workloads import device as wdevice
    from shadow_tpu.workloads import runner as wrunner
    from shadow_tpu.workloads.compile import compile_program
    from shadow_tpu.workloads.spec import parse_scenario

    spec = parse_scenario({
        "name": f"memo-bench-ring-{MEMO_HOSTS}",
        "family": "ring_allreduce",
        "seed": 7,
        "hosts": MEMO_HOSTS,
        "windows": MEMO_WINDOWS,
        "patterns": [{"kind": "ring_allreduce", "first": 0,
                      "count": MEMO_HOSTS, "bytes": 4096,
                      "rounds": 1}],
    })
    prog = compile_program(spec)
    state0, params = wrunner.build_scenario_world(spec)
    wl = wdevice.to_device(prog)
    ws0 = wdevice.make_workload_state(prog)
    metrics0 = make_metrics(spec.n_hosts)
    state0, ws0, metrics0 = wdevice.prime(wl, ws0, state0,
                                          metrics=metrics0)
    rng_root = jax.random.key(spec.seed)
    window = jnp.int32(spec.window_ns)

    def round_fn(carry, ridx):
        state, ws, metrics = carry
        shift = jnp.where(ridx == 0, jnp.int32(0), window)
        out = window_step(state, params, rng_root, shift, window,
                          rr_enabled=False, metrics=metrics)
        (state, delivered, _nx), metrics, _g, _h, _fr = \
            unpack_planes(out, metrics=metrics)
        state, ws, metrics = wdevice.workload_step(
            wl, ws, state, delivered, ridx, window,
            max_advance=wdevice.MAX_ADVANCE, metrics=metrics)
        return (state, ws, metrics), None

    @jax.jit
    def chain(state, ws, metrics, rids):
        carry, _ = jax.lax.scan(round_fn, (state, ws, metrics), rids)
        return carry

    def chain_fn(state, extras, rids, _pr):
        ws, metrics = extras[0], extras[1]
        state, ws, metrics = chain(state, ws, metrics, rids)
        # runner-shaped extras (6 slots) so the runner's memo
        # key_extra indexes the workload/flow planes the same way
        return state, (ws, metrics, None, None, None, None), 0, 0

    def drive(memo_obj):
        state, extras = elastic.drive_chained_windows(
            state0, (ws0, metrics0, None, None, None, None), chain_fn,
            n_rounds=spec.windows, chain_len=MEMO_CHAIN,
            window_ns=spec.window_ns, memo=memo_obj)
        jax.block_until_ready(state)
        return state, extras

    def fresh_memo():
        memo_obj, _salt, _cl = wrunner._build_memo(
            {"chain_len": MEMO_CHAIN}, spec=spec, prog=prog,
            schedule=None, mesh_devices=None,
            adv=wdevice.MAX_ADVANCE, emit_cap=0, recv_wnd=0,
            guards=False, histograms=False, sample_every=None,
            trace_ring=0)
        return memo_obj

    drive(None)  # warm-up: compiles the one shared chain trace
    t0 = time.monotonic()
    state_c, extras_c = drive(None)
    cold_s = time.monotonic() - t0
    memo_obj = fresh_memo()
    t0 = time.monotonic()
    state_m, extras_m = drive(memo_obj)
    memo_s = time.monotonic() - t0

    events = int(np.asarray(jax.device_get(extras_c[1].events)))
    parity = (wrunner.digest_pytrees(
        elastic.canonical_state(state_c), extras_c[0])
        == wrunner.digest_pytrees(
            elastic.canonical_state(state_m), extras_m[0]))
    return {
        "scenario": spec.name,
        "hosts": MEMO_HOSTS,
        "windows": MEMO_WINDOWS,
        "chain_len": MEMO_CHAIN,
        "events": events,
        "cold_s": round(cold_s, 3),
        "memo_s": round(memo_s, 3),
        "effective_evps_cold": round(events / cold_s, 1),
        "effective_evps_memo": round(events / memo_s, 1),
        # same event total on both sides, so this IS the effective
        # ev/s multiplier on steady-state traffic
        "speedup": round(cold_s / memo_s, 2),
        "digest_parity": parity,
        "memo": memo_obj.stats(),
    }


def bench_cpu_baseline() -> float:
    """PHOLD on the object plane (Host/EventQueue/Worker path)."""
    from shadow_tpu.core.config import load_config_str
    from shadow_tpu.core.event import TaskRef
    from shadow_tpu.core.manager import Manager
    from shadow_tpu.net.packet import Packet, Protocol

    n_hosts = 64
    hosts_yaml = "\n".join(
        f"  peer{i}:\n    network_node_id: 0" for i in range(n_hosts)
    )
    cfg = load_config_str(
        f"general:\n  stop_time: 2s\n  seed: 1\n"
        f"network:\n  graph:\n    type: 1_gbit_switch\nhosts:\n{hosts_yaml}"
    )
    mgr = Manager(cfg)
    peer_ips = [h.ip for h in mgr.hosts]
    events = [0]

    class App:
        PORT = 9000

        def __init__(self, host):
            self.host = host
            self.outq = []
            host.netns.associate(self, Protocol.UDP, "0.0.0.0", self.PORT)

        def pull_out_packet(self):
            return self.outq.pop(0) if self.outq else None

        def peek_next_priority(self):
            return self.outq[0].priority if self.outq else None

        def push_in_packet(self, packet):
            events[0] += 1
            self.send_one()

        def send_one(self):
            events[0] += 1
            dst = peer_ips[self.host.rng.randrange(0, len(peer_ips))]
            self.outq.append(
                Packet(Protocol.UDP, (self.host.ip, self.PORT), (dst, self.PORT),
                       b"x" * 1400, priority=self.host.get_next_packet_priority())
            )
            self.host.notify_socket_has_packets(self.host.ip, self)

        def start(self, host):
            for _ in range(4):
                self.send_one()

    for host in mgr.hosts:
        app = App(host)
        host.add_application(MS, app.start)
    t0 = time.monotonic()
    mgr.run()
    wall = time.monotonic() - t0
    return events[0] / wall


def bench_compiled_baseline() -> float:
    """Compiled-Shadow-class per-event floor: build and run the ~120-line
    C++ PHOLD microbench (tools/phold_compiled.cc). Optimistic for the
    reference (no sockets/qdiscs/refcounting), so vs_compiled can only
    UNDERSTATE this rebuild. Returns events/s, or 0.0 when no g++."""
    import shutil
    import subprocess

    try:
        if shutil.which("g++") is None:
            return 0.0
        here = os.path.dirname(os.path.abspath(__file__))
        src = os.path.join(here, "tools", "phold_compiled.cc")
        # repo-local (self-owned) build target — never a fixed name in a
        # shared world-writable tempdir
        exe = os.path.join(here, "tools", ".phold_compiled")
        if not os.path.exists(exe) or \
                os.path.getmtime(exe) < os.path.getmtime(src):
            subprocess.run(["g++", "-O2", "-o", exe, src], check=True,
                           capture_output=True)
        out = subprocess.run([exe, "64", "64", "20"], check=True,
                             capture_output=True, text=True).stdout
        return float(json.loads(out)["events_per_sec"])
    except (OSError, subprocess.CalledProcessError, ValueError, KeyError):
        # auxiliary baseline: never let it eat the primary metric
        return 0.0


def backend_fingerprint() -> dict:
    """The container/backend identity a throughput number is only
    comparable within: JAX platform + device kind. PR 7's false
    regression — a CPU container measured against the accelerator-
    backed BENCH_r05 — is exactly the comparison this stamp makes
    impossible to repeat silently (both the `prior_round` guard below
    and `tools/compare_runs.py --bench` refuse to gate across
    mismatched fingerprints)."""
    import jax

    dev = jax.devices()[0]
    return {
        "platform": str(dev.platform),
        "device_kind": str(getattr(dev, "device_kind", dev.platform)),
    }


def _regression_guard(value: float, fingerprint: dict):
    """Compare against the newest recorded BENCH_r*.json (same shape
    only): a silent -7% crept through round 4 unbisected; now any drop
    past 20% is flagged in the output (tunnel noise stays quiet).

    A prior record whose backend fingerprint differs from this run's —
    or predates the stamp — is NOT comparable: the guard then warns
    loudly on stderr and reports `skipped_mismatched_backend` instead
    of a regression verdict (the PR-7 false-regression rule)."""
    import glob
    import re
    import sys

    best = None
    for path in glob.glob(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)", path)
        if not m:
            continue
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except (OSError, ValueError):
            continue
        rec = rec.get("parsed", rec)  # driver wraps the JSON line
        if not rec or rec.get("hosts") != N_HOSTS:
            continue
        rnd = int(m.group(1))
        if best is None or rnd > best[0]:
            best = (rnd, rec.get("value"), rec.get("backend"))
    if best is None or not best[1] or float(best[1]) <= 0:
        return None
    best = (best[0], float(best[1]), best[2])
    prior_backend = best[2]
    if prior_backend != fingerprint:
        print(
            f"bench: WARNING: prior round BENCH_r{best[0]:02d} was "
            f"measured on backend {prior_backend} but this run is on "
            f"{fingerprint} — cross-container throughput ratios are "
            f"meaningless, so the prior_round regression gate is "
            f"SKIPPED. Re-measure both rounds on one container "
            f"(docs/performance.md).", file=sys.stderr)
        return {"vs_round": best[0],
                "skipped_mismatched_backend": True,
                "prior_backend": prior_backend,
                "regressed": False}
    ratio = value / best[1]
    return {"vs_round": best[0], "ratio": round(ratio, 3),
            "regressed": ratio < 0.8}


def bench_sections(kernel: str) -> dict | None:
    """One profiled rep of each window-step section at the bench shape
    (outside the timed run): section name -> min ms. The same
    measurement substrate as tools/profile_plane.py, at reps=1 — a
    trend line for the BENCH_r*.json trajectory, not a benchmark."""
    from shadow_tpu.tpu import profiling

    rep = profiling.profile_sections(
        N_HOSTS, reps=1, rr_enabled=False, kernel=kernel,
        n_nodes=N_NODES, egress_cap=EGRESS_CAP, ingress_cap=INGRESS_CAP,
        sections=profiling.BENCH_SECTIONS)
    return {name: vals["min_ms"] for name, vals in rep["sections"].items()}


def main():
    (tpu_rate, events, telemetry_info, kernel_info, capacity_info,
     driver_info) = bench_tpu()
    # sections are recorded for the default XLA kernel only: a pallas
    # run off-TPU would re-time every section in interpret mode (slow
    # and not the trajectory being tracked)
    sections = (bench_sections("xla")
                if SECTIONS and kernel_info["used"] == "xla" else None)
    if sections is not None:
        # surface the chained-driver amortization next to the section
        # times so compare_runs --bench diffs it like any other cost
        sections["windows_per_sync"] = driver_info["windows_per_sync"]
    worlds_info = bench_tpu_worlds(tpu_rate) if N_WORLDS > 0 else None
    memo_info = bench_memo() if MEMO else None
    cpu_rate = bench_cpu_baseline()
    compiled_rate = bench_compiled_baseline()
    fingerprint = backend_fingerprint()
    guard = _regression_guard(tpu_rate, fingerprint)
    print(
        json.dumps(
            {
                "metric": "packet_events_per_sec",
                "value": round(tpu_rate, 1),
                "unit": "events/s",
                "backend": fingerprint,
                "driver": driver_info,
                "telemetry": telemetry_info,
                "kernel": kernel_info,
                "capacity": capacity_info,
                "worlds": worlds_info,
                "memo": memo_info,
                "vs_baseline": round(tpu_rate / cpu_rate, 2),
                "vs_compiled": (round(tpu_rate / compiled_rate, 3)
                                if compiled_rate else None),
                "compiled_events_per_sec": round(compiled_rate, 1),
                "hosts": N_HOSTS,
                "sections": sections,
                "prior_round": guard,
                "baseline": (
                    "vs_baseline: this repo's Python object plane (64-host "
                    "PHOLD on the Host/EventQueue path). vs_compiled: the "
                    "in-tree C++ PHOLD microbench (tools/phold_compiled.cc) "
                    "pricing compiled-Shadow-class per-event cost on one "
                    "core — an optimistic floor for the reference, so the "
                    "ratio understates this rebuild; methodology in "
                    "BASELINE.md. See tools/bench_ladder.py for the "
                    "end-to-end rung measurements"
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
