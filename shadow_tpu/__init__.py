"""shadow_tpu: a TPU-native discrete-event network simulation framework.

Capabilities modeled on Shadow (the hybrid emulation/simulation tool): execute
real applications, interpose on their syscalls, and connect them through a
deterministic simulated network. The network/transport plane runs as batched
JAX/XLA kernels over hosts-as-SoA arrays on TPU; the syscall plane runs
natively on CPU.

Layout:
  core/       time, units, RNG, events, config, round loop (controller/manager/worker)
  host/       simulated machine: processes, threads, descriptors, syscalls, timers
  net/        graph, routing, packets, router (CoDel), relay (token bucket), NIC
  tcp/        pure dependency-injected TCP state machine + Reno congestion control
  tpu/        the TPU network plane: SoA state, vmap'd round step, mesh sharding
  interpose/  native C++ plane: shmem IPC, preload shim, seccomp interposition
  utils/      byte queues, interval maps, counters, pcap
"""

__version__ = "0.1.0"
