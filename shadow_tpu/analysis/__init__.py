"""Static analysis for the determinism + jit-cache contracts.

Two passes (driven by ``tools/shadowlint.py``):

- ``astlint`` — AST determinism rules (SL1xx) over the whole package:
  wall-clock reads, global randomness, unordered iteration feeding
  event scheduling, mutable default arguments, Python branches on
  traced values in kernels.
- ``jaxpr_audit`` — jaxpr rules (SL2xx) over the jitted ``tpu/`` entry
  points: x64 leaks, convert churn, host callbacks, transfers inside
  loop bodies, baked constants.
- ``dataflow`` + ``proofs`` — the SL5xx dataflow proofs over the same
  traced graphs: SL501 presence-invisibility taint theorems, the SL502
  op-budget ledger (``op_budgets.json``), the SL503 donation-safety
  AST checks (in ``astlint``), and the SL504 shardability report +
  row-local fence.
- ``condeq`` — the SL505 branch-equivalence prover for the gated
  ``lax.cond``s (structural sort-of-sorted/selection-witness proofs
  with an exhaustive boundary-lattice fallback).
- ``ranges`` — the SL506 integer range / bit-budget abstract
  interpretation with its checked-in input-domain registry.
- ``costmodel`` — the SL6xx shadowcost fences over the COMPILED
  artifacts: SL601 platform-keyed cost budgets
  (``cost_budgets.json``) + two-shape watermark extrapolation, the
  SL602 fusion-boundary census and ranked worklist, and the SL603
  driver-loop host-sync fence.

Plus ``recompile`` — the jit-cache-miss counter harness swept over the
bench-ladder shapes. All traced passes share one per-process jaxpr
cache (``jaxpr_audit.traced``); the cost pass shares one
lower+compile memo on top of it (``jaxpr_audit.compiled``).

Rule IDs, invariants, and the suppression syntax live in ``rules`` and
are documented in ``docs/determinism.md``.
"""

from .astlint import lint_file, lint_source, rule_applies
from .condeq import GateObligation, check_all_gates, gate_obligations
from .costmodel import (CostEntry, build_cost_report, check_cost_budgets,
                        check_host_sync, check_watermarks,
                        default_cost_entries, fusion_boundaries,
                        write_cost_budgets)
from .dataflow import leaf_paths, op_census, propagate_taint, shard_census
from .jaxpr_audit import (AuditEntry, audit_all, audit_entry, audit_jaxpr,
                          compiled, default_entries, traced)
from .proofs import (InvisibilitySpec, build_shard_report,
                     check_all_invisibility, check_invisibility,
                     check_op_budgets, check_row_local_fence,
                     compute_censuses, invisibility_specs,
                     write_op_budgets)
from .ranges import RangeSpec, check_all_ranges, range_specs
from .recompile import (CompileCounter, LadderShape, ladder_shapes,
                        sweep_window_step)
from .rules import RULES, Finding, RuleInfo, parse_suppressions

__all__ = [
    "RULES",
    "Finding",
    "RuleInfo",
    "parse_suppressions",
    "lint_source",
    "lint_file",
    "rule_applies",
    "AuditEntry",
    "audit_all",
    "audit_entry",
    "audit_jaxpr",
    "compiled",
    "default_entries",
    "traced",
    "CostEntry",
    "build_cost_report",
    "check_cost_budgets",
    "check_host_sync",
    "check_watermarks",
    "default_cost_entries",
    "fusion_boundaries",
    "write_cost_budgets",
    "leaf_paths",
    "op_census",
    "propagate_taint",
    "shard_census",
    "InvisibilitySpec",
    "build_shard_report",
    "check_all_invisibility",
    "check_invisibility",
    "check_op_budgets",
    "check_row_local_fence",
    "compute_censuses",
    "invisibility_specs",
    "write_op_budgets",
    "GateObligation",
    "check_all_gates",
    "gate_obligations",
    "RangeSpec",
    "check_all_ranges",
    "range_specs",
    "CompileCounter",
    "LadderShape",
    "ladder_shapes",
    "sweep_window_step",
]
