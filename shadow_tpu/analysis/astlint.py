"""shadowlint pass 1: AST determinism rules over the whole package.

Each checker resolves names through the file's import table (so
``import time as _walltime`` and ``from time import perf_counter_ns as
_perf_ns`` are both seen as the ``time`` module), then walks the AST
once collecting findings. Rules scope by repo-relative path:

- SL101 (wall-clock) applies to ``shadow_tpu/`` only — ``tools/``
  benchmarks measure wall time on purpose.
- SL102 (global randomness) applies everywhere except ``core/rng.py``,
  the one sanctioned randomness module.
- SL103 (unordered iteration) applies where iteration order can feed
  event scheduling: ``core/``, ``net/``, ``host/``, ``kernel/``,
  ``process/``, ``tcp/``, ``apps/``.
- SL104 (mutable default args) applies everywhere.
- SL105 (traced-value branching) applies to ``shadow_tpu/tpu/`` kernel
  modules.
- SL401 (swallowed-error) applies to ``shadow_tpu/``: a broad handler
  (bare ``except:``, ``except Exception``, ``except BaseException``)
  whose body neither re-raises nor logs — ``except Exception: pass``
  swallows, and bare ``except:`` additionally eats KeyboardInterrupt.
  Narrow-typed silent handlers (``except OSError: pass``) are a
  deliberate judgement call and are not flagged.
- SL301 (sync-in-kernel) applies to ``shadow_tpu/tpu/``: device_get /
  block_until_ready inside a KERNEL BODY — a function that is
  jit-decorated, passed to a jit wrapper (``jax.jit``,
  ``donating_jit``), or used as a ``lax`` control-flow body
  (scan/while_loop/cond/...). Syncs outside kernel bodies (transport
  release barriers, the profiler's measurement loop, telemetry drains)
  are the sanctioned pattern and are not flagged.
- SL402 (assert-in-kernel) applies to ``shadow_tpu/tpu/``: a Python
  ``assert`` inside a kernel body (same detection as SL301) traces
  once against abstract values and vanishes under ``-O`` — runtime
  invariants go through the guard plane (``shadow_tpu/guards/``);
  trace-time static checks use an explicit raise. Host-side asserts
  outside kernel bodies are untouched.
- SL405 (sync-telemetry-read) applies to ``shadow_tpu/`` EXCEPT
  ``shadow_tpu/telemetry/`` (the harvest boundary is the one
  sanctioned reader): a host-side ``float(...)`` call or ``.item()``
  method read whose target mentions a device telemetry array — a
  `PlaneMetrics`/`PlaneHistograms`/transport counter field or a
  conventionally-named local (``metrics``, ``hist``, ``flightrec``) —
  is a blocking D2H sync outside the asynchronous harvester
  (docs/observability.md no-host-sync rule). Detection is lexical
  (field/receiver names), so it forces NEW observability reads through
  the drain without type inference; justified exceptions use the
  standard suppression comment.
- SL403 (variadic-sort) applies to ``shadow_tpu/tpu/``: a
  ``jax.lax.sort`` call (or a call to the ``_row_sort`` wrapper) whose
  statically-countable operand tuple carries more than 3 payload
  operands (operands beyond ``num_keys``/``keys``) — the variadic
  anti-pattern the sort diet removed (docs/performance.md): payload
  belongs on a packed-key permutation or a bucketed counting
  placement, not in the comparator network. Calls whose operand count
  or key count is not statically countable (starred args, computed
  key counts) are skipped; the compiled-in ``packed_sort=False``
  parity-reference paths carry justified suppressions.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .rules import Finding, parse_suppressions

__all__ = ["lint_source", "lint_file", "rule_applies"]

# time/datetime entry points that read the real clock
_WALL_CLOCK = {
    "time.time", "time.time_ns",
    "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "time.clock_gettime", "time.clock_gettime_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

# np.random attributes that are fine: explicitly seeded generator
# construction, not draws from the hidden global stream
_NP_RANDOM_OK = {
    "default_rng", "Generator", "SeedSequence", "BitGenerator",
    "PCG64", "PCG64DXSM", "MT19937", "Philox", "SFC64",
}

# builtins that preserve (lack of) ordering of a set argument
_ORDER_PRESERVING = {"list", "tuple", "iter", "enumerate", "reversed"}

# jax entry points that are *intentional* host syncs, not kernel branches
_SYNC_OK = {"jax.device_get", "jax.block_until_ready"}

# SL405: leaf names of the device telemetry pytrees — a float()/.item()
# read of one of these outside shadow_tpu/telemetry/ is a blocking D2H
# sync bypassing the asynchronous harvester. The set mirrors
# PlaneMetrics / PlaneHistograms / TransportHist / FlightRecArrays /
# TransportState's telemetry counters; tests/test_shadowlint.py pins it
# against the live pytree definitions so a new counter field cannot
# silently escape the rule.
_TELEMETRY_FIELD_ATTRS = frozenset({
    # telemetry/metrics.PlaneMetrics
    "pkts_out", "bytes_out", "pkts_in", "bytes_in", "drop_ring_full",
    "drop_qdisc", "drop_loss", "drop_fault", "retransmits",
    "max_eg_depth", "max_in_depth", "windows", "events", "sort_slots",
    # telemetry/histo.PlaneHistograms + tpu/transport.TransportHist
    "hist_delivery_ns", "hist_sojourn_ns", "hist_qdepth",
    # telemetry/flightrec.FlightRecArrays ring columns
    "ev_kind", "ev_src", "ev_seq", "ev_dst", "ev_t", "ev_win",
    # tpu/transport.TransportState telemetry counters
    "n_out", "n_released",
})

# conventional local/parameter names for the telemetry pytrees — a bare
# `float(metrics.x)` resolves through these even when the field name is
# computed
_TELEMETRY_NAMES = frozenset({
    "metrics", "hist", "hists", "histograms", "flightrec",
    "plane_metrics",
})


def _mentions_telemetry(node: ast.AST) -> bool:
    """True when the expression touches a telemetry array by field
    name or conventional receiver name (lexical — the SL405 net)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) \
                and sub.attr in _TELEMETRY_FIELD_ATTRS:
            return True
        if isinstance(sub, ast.Name) and sub.id in _TELEMETRY_NAMES:
            return True
    return False

_REDUCTION_METHODS = {"any", "all", "sum", "min", "max", "item",
                      "argmax", "argmin"}


def rule_applies(rule: str, relpath: str) -> bool:
    """Path scoping for pass-1 rules; `relpath` is repo-relative with
    forward slashes (e.g. ``shadow_tpu/core/scheduler.py``)."""
    p = relpath.replace("\\", "/")
    if rule == "SL101":
        return p.startswith("shadow_tpu/")
    if rule == "SL102":
        return not p.endswith("core/rng.py")
    if rule == "SL103":
        return any(
            p.startswith(f"shadow_tpu/{d}/")
            for d in ("core", "net", "host", "kernel", "process",
                      "tcp", "apps")
        )
    if rule == "SL104":
        return True
    if rule in ("SL105", "SL301", "SL402", "SL403"):
        return p.startswith("shadow_tpu/tpu/")
    if rule == "SL401":
        return p.startswith("shadow_tpu/")
    if rule == "SL503":
        # donation hazards live wherever kernels are wrapped or driven:
        # the package, the tools/ drivers, and the bench entry point
        return (p.startswith("shadow_tpu/") or p.startswith("tools/")
                or p == "bench.py" or p.endswith("/bench.py"))
    if rule == "SL405":
        # the telemetry package IS the harvest boundary — its drain is
        # the sanctioned place to materialize device counters
        return (p.startswith("shadow_tpu/")
                and not p.startswith("shadow_tpu/telemetry/"))
    return False


@dataclass
class _Imports:
    """Resolved import table: local name -> dotted module/object path."""

    names: dict[str, str] = field(default_factory=dict)

    def add_import(self, node: ast.Import) -> None:
        for alias in node.names:
            self.names[alias.asname or alias.name.split(".")[0]] = (
                alias.name if alias.asname else alias.name.split(".")[0]
            )

    def add_from(self, node: ast.ImportFrom) -> None:
        if node.level or not node.module:
            return  # relative imports stay package-local
        for alias in node.names:
            self.names[alias.asname or alias.name] = (
                f"{node.module}.{alias.name}"
            )

    def resolve(self, node: ast.expr) -> str | None:
        """Dotted path of a Name/Attribute chain through the table,
        e.g. ``np.random.rand`` -> ``numpy.random.rand``."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.names.get(node.id)
        if root is None:
            if parts:
                # attribute access on a non-imported name (a local,
                # parameter, or self) — not a module path; resolving it
                # to the bare name would mistake e.g. a parameter named
                # `random` for the stdlib module
                return None
            root = node.id  # bare builtins: list(), set(), ...
        parts.append(root)
        return ".".join(reversed(parts))


class _SetTracker:
    """Flow-insensitive local inference: which names are set-typed.

    Tracks ``x = set(...)`` / ``x = {a, b}`` / ``x = a | b`` (of sets)
    assignments per scope so ``for h in x`` can be flagged."""

    def __init__(self) -> None:
        self._scopes: list[set[str]] = [set()]

    def push(self) -> None:
        self._scopes.append(set())

    def pop(self) -> None:
        self._scopes.pop()

    def mark(self, name: str) -> None:
        self._scopes[-1].add(name)

    def unmark(self, name: str) -> None:
        for scope in self._scopes:
            scope.discard(name)

    def is_set(self, name: str) -> bool:
        return any(name in scope for scope in self._scopes)


def _is_set_expr(node: ast.expr, sets: _SetTracker) -> bool:
    """True when `node` statically evaluates to a set/frozenset (after
    peeling order-preserving wrappers like list()/enumerate())."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Name):
        return sets.is_set(node.id)
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.BitOr, ast.BitAnd, ast.BitXor, ast.Sub)
    ):
        return _is_set_expr(node.left, sets) or _is_set_expr(node.right, sets)
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name):
            if fn.id in ("set", "frozenset"):
                return True
            if fn.id in _ORDER_PRESERVING and node.args:
                return _is_set_expr(node.args[0], sets)
            return False
        if isinstance(fn, ast.Attribute):
            if fn.attr in ("union", "intersection", "difference",
                           "symmetric_difference", "copy"):
                return _is_set_expr(fn.value, sets)
    return False


def _calls_outside_sync(node: ast.AST, imports: _Imports):
    """Yield every Call in `node` that is not nested inside a _SYNC_OK
    call — reads routed through jax.device_get are intentional syncs,
    but only for that subexpression, not for the whole test."""
    if isinstance(node, ast.Call):
        if imports.resolve(node.func) in _SYNC_OK:
            return
        yield node
    for child in ast.iter_child_nodes(node):
        yield from _calls_outside_sync(child, imports)


def _contains_traced_read(node: ast.expr, imports: _Imports,
                          host_arrays: _SetTracker) -> bool:
    """True when the expression contains a jnp/lax call or an array
    reduction method — the signature of branching on a traced value.
    Exempt: subexpressions routed through jax.device_get (an
    intentional sync) and reductions on locals inferred to be host-side
    numpy arrays (assigned from a resolved ``numpy.*`` call)."""
    for sub in _calls_outside_sync(node, imports):
        resolved = imports.resolve(sub.func)
        if resolved and resolved.startswith(("jax.numpy.", "jax.lax.")):
            return True
        if isinstance(sub.func, ast.Attribute) \
                and sub.func.attr in _REDUCTION_METHODS:
            # method reductions on resolvable *module* attrs (np.sum is
            # host-side numpy) or numpy-derived locals don't count;
            # bare `x.any()` on anything else does
            recv_node = sub.func.value
            recv = imports.resolve(recv_node)
            if recv in ("numpy", "math", "builtins"):
                continue
            if isinstance(recv_node, ast.Name) \
                    and host_arrays.is_set(recv_node.id):
                continue
            return True
    return False


# -- SL301: host syncs inside kernel bodies ------------------------------

#: callables whose function argument becomes jitted/traced device code
_JIT_WRAPPER_LEAVES = {"jit", "donating_jit"}
_LAX_BODY_LEAVES = {"scan", "while_loop", "cond", "fori_loop", "switch",
                    "map", "associative_scan"}
_SYNC_LEAVES = {"device_get", "block_until_ready"}


def _callee_leaf(node: ast.expr, imports: _Imports) -> str:
    """Last dotted component of a callable reference, resolved through
    the import table when possible (``donating_jit`` arrives via a
    relative import the table can't follow, so the bare leaf matters)."""
    resolved = imports.resolve(node)
    if resolved:
        return resolved.rsplit(".", 1)[-1]
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _kernel_bodies(tree: ast.AST, imports: _Imports) -> list[ast.AST]:
    """Function/lambda nodes whose bodies compile into device kernels:
    jit-decorated defs, function-valued arguments to jit wrappers, and
    `lax` control-flow bodies. Name arguments resolve against every def
    of that name in the file (flow-insensitive, like the rest of the
    linter)."""
    defs_by_name: dict[str, list[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    kernels: list[ast.AST] = []
    seen: set[int] = set()

    def mark(node: ast.AST) -> None:
        if id(node) not in seen:
            seen.add(id(node))
            kernels.append(node)

    def decorator_is_jit(dec: ast.expr) -> bool:
        if isinstance(dec, ast.Call):  # @partial(jax.jit, ...) etc.
            return decorator_is_jit(dec.func) or any(
                _callee_leaf(a, imports) in _JIT_WRAPPER_LEAVES
                for a in dec.args)
        return _callee_leaf(dec, imports) in _JIT_WRAPPER_LEAVES

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(decorator_is_jit(d) for d in node.decorator_list):
                mark(node)
        elif isinstance(node, ast.Call):
            leaf = _callee_leaf(node.func, imports)
            resolved = imports.resolve(node.func) or ""
            if leaf in _JIT_WRAPPER_LEAVES:
                fn_args = node.args[:1]  # jit(fun, ...)
            elif leaf in _LAX_BODY_LEAVES and (
                    ".lax." in resolved or resolved.startswith("lax.")):
                # the resolved-path requirement keeps builtins and local
                # helpers that happen to be named map/cond/switch from
                # marking their callees as kernels
                fn_args = node.args  # lax.while_loop(cond, body, init)
            else:
                continue
            for arg in fn_args:
                if isinstance(arg, ast.Lambda):
                    mark(arg)
                elif isinstance(arg, ast.Name):
                    for d in defs_by_name.get(arg.id, ()):
                        mark(d)
    return kernels


def _sl301_findings(tree: ast.AST, imports: _Imports,
                    relpath: str) -> list[Finding]:
    if not rule_applies("SL301", relpath):
        return []
    findings: list[Finding] = []
    flagged: set[tuple[int, int]] = set()
    for kernel in _kernel_bodies(tree, imports):
        for node in ast.walk(kernel):
            if not isinstance(node, ast.Call):
                continue
            resolved = imports.resolve(node.func)
            is_sync = resolved in ("jax.device_get",
                                   "jax.block_until_ready")
            if not is_sync and isinstance(node.func, ast.Attribute):
                # self._jax.device_get(...) / arr.block_until_ready()
                is_sync = node.func.attr in _SYNC_LEAVES
            if not is_sync:
                continue
            loc = (node.lineno, node.col_offset)
            if loc in flagged:
                continue
            flagged.add(loc)
            what = (resolved or f"...{node.func.attr}"
                    if isinstance(node.func, ast.Attribute)
                    else resolved)
            findings.append(Finding(
                "SL301", relpath, node.lineno, node.col_offset,
                f"host sync `{what}` inside a jitted kernel body; "
                "harvest/read device values OUTSIDE jitted code "
                "(telemetry no-host-sync rule, docs/observability.md)"))
    return findings


# -- SL402: Python assert inside kernel bodies ---------------------------


def _sl402_findings(tree: ast.AST, imports: _Imports,
                    relpath: str) -> list[Finding]:
    """`assert` in a kernel body runs ONCE at trace time against
    abstract values — it cannot check runtime data (and vanishes under
    -O), so it reads as an invariant check that silently is not one.
    Runtime invariants belong in the guard plane (shadow_tpu/guards/);
    trace-time static checks use an explicit raise. Shares the kernel
    detection with SL301."""
    if not rule_applies("SL402", relpath):
        return []
    findings: list[Finding] = []
    flagged: set[tuple[int, int]] = set()
    for kernel in _kernel_bodies(tree, imports):
        for node in ast.walk(kernel):
            if not isinstance(node, ast.Assert):
                continue
            loc = (node.lineno, node.col_offset)
            if loc in flagged:
                continue
            flagged.add(loc)
            findings.append(Finding(
                "SL402", relpath, node.lineno, node.col_offset,
                "Python `assert` inside a jitted kernel body: it traces "
                "once against abstract values and vanishes under -O — "
                "route runtime invariants through the guard plane "
                "(shadow_tpu/guards/, docs/robustness.md) and use an "
                "explicit raise for trace-time static checks"))
    return findings


# -- SL503: buffer-donation safety ---------------------------------------
#
# Two hazards around `tpu.donating_jit` (docs/performance.md donation
# contract), sharing SL301's callee-resolution machinery:
#
# (a) a raw ``jax.jit(..., donate_argnums=...)`` call: it bypasses the
#     wrapper's CPU-backend no-op, so tests exercise different aliasing
#     than production, and it forks the donate_argnums convention the
#     unified drivers share. (The wrapper's own forwarding call inside
#     a def named ``donating_jit`` is exempt — it IS the one sanctioned
#     site.)
# (b) use-after-donation: a bare-Name argument passed at a donated
#     position of a donating-jit-wrapped callable, then READ again
#     later in the same statement list before being rebound. On a
#     donating backend that read sees aliased/deleted buffers — and
#     only there, which is why it must be caught statically.
#
# Detection is flow-insensitive like the rest of the linter: donated
# callables are names/attributes bound to ``donating_jit(fn, ...)``
# results (possibly through a single outer wrapper call), defs
# decorated with ``donating_jit`` or an alias of it (``wrap = jax.jit
# if cpu else donating_jit``), with donate_argnums read off the
# wrapping site when statically countable (default ``(0,)``).


def _static_argnums(call: ast.Call) -> tuple[int, ...] | None:
    """donate_argnums of a donating_jit call site; (0,) when omitted,
    None when present but not statically countable."""
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)) and all(
                    isinstance(e, ast.Constant)
                    and isinstance(e.value, int) for e in v.elts):
                return tuple(e.value for e in v.elts)
            return None
    return (0,)


def _donation_registry(tree: ast.AST, imports: _Imports):
    """(aliases, donated): names that ARE the donating wrapper, and
    name/attr-leaf -> donate_argnums for callables wrapped by it."""
    aliases = {"donating_jit"}

    def mentions_wrapper(node: ast.expr) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in aliases:
                return True
            if isinstance(sub, ast.Attribute) and sub.attr in aliases:
                return True
        return False

    def wrapping_call(node: ast.expr) -> ast.Call | None:
        """The donating_jit(...) Call inside `node`, looked through one
        outer wrapper call (self._retrying(donating_jit(fn), ...))."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and _callee_leaf(sub.func, imports) in aliases:
                return sub
        return None

    # pass A: plain aliases (`wrap = donating_jit`, conditional picks)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and not isinstance(node.value, ast.Call) \
                and mentions_wrapper(node.value):
            aliases.add(node.targets[0].id)

    donated: dict[str, tuple[int, ...] | None] = {}

    def decorator_argnums(dec: ast.expr):
        """argnums when `dec` makes the def a donated kernel."""
        if _callee_leaf(dec, imports) in aliases \
                and not isinstance(dec, ast.Call):
            return (0,)
        if isinstance(dec, ast.Call) \
                and _callee_leaf(dec.func, imports) in aliases:
            return _static_argnums(dec)
        return "no"

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                argnums = decorator_argnums(dec)
                if argnums != "no":
                    donated[node.name] = argnums
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            call = wrapping_call(node.value) \
                if isinstance(node.value, ast.Call) else None
            if call is None or not call.args:
                continue  # partial form (no fn yet) stays an alias
            target = node.targets[0]
            if isinstance(target, ast.Name):
                donated[target.id] = _static_argnums(call)
            elif isinstance(target, ast.Attribute):
                donated[target.attr] = _static_argnums(call)
    return aliases, donated


def _walk_scope(node: ast.AST):
    """ast.walk that does NOT descend into nested function/class/lambda
    definitions — their names live in their own scope, so their loads
    and calls must not leak into the enclosing block's donation flow."""
    yield node
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda, ast.ClassDef)):
        return  # opaque scope boundary (its body scans as its own block)
    for child in ast.iter_child_nodes(node):
        yield from _walk_scope(child)


def _stmt_rebinds(stmt: ast.stmt, name: str) -> bool:
    targets: list[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        targets = [stmt.target]  # `state: T = step(state, ...)`
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        targets = [item.optional_vars for item in stmt.items
                   if item.optional_vars is not None]
    elif isinstance(stmt, ast.Delete):
        targets = list(stmt.targets)
    for target in targets:
        for sub in ast.walk(target):
            if isinstance(sub, ast.Name) and sub.id == name:
                return True
    return False


def _first_load(stmt: ast.stmt, name: str) -> ast.Name | None:
    for sub in _walk_scope(stmt):
        if isinstance(sub, ast.Name) and sub.id == name \
                and isinstance(sub.ctx, ast.Load):
            return sub
        # x += 1 reads x even though the target ctx is Store
        if isinstance(sub, ast.AugAssign) \
                and isinstance(sub.target, ast.Name) \
                and sub.target.id == name:
            return sub.target
    return None


def _sl503_findings(tree: ast.AST, imports: _Imports,
                    relpath: str) -> list[Finding]:
    if not rule_applies("SL503", relpath):
        return []
    findings: list[Finding] = []

    # (a) raw jax.jit with donation, outside the wrapper's own body
    wrapper_defs = [n for n in ast.walk(tree)
                    if isinstance(n, ast.FunctionDef)
                    and n.name == "donating_jit"]
    exempt = {id(sub) for n in wrapper_defs for sub in ast.walk(n)}
    for node in ast.walk(tree):
        if id(node) in exempt or not isinstance(node, ast.Call):
            continue
        if imports.resolve(node.func) == "jax.jit" and any(
                kw.arg in ("donate_argnums", "donate_argnames")
                for kw in node.keywords):
            findings.append(Finding(
                "SL503", relpath, node.lineno, node.col_offset,
                "raw jax.jit(donate_argnums=...) bypasses the "
                "tpu.donating_jit wrapper: tests lose the CPU-backend "
                "no-op and the drivers fork their donation convention "
                "— route donation through donating_jit "
                "(docs/performance.md donation contract)"))

    aliases, donated = _donation_registry(tree, imports)

    def donated_argnums(func: ast.expr) -> tuple[int, ...] | None:
        leaf = _callee_leaf(func, imports)
        return donated.get(leaf)

    # (b) use-after-donation, per statement list (flow follows source
    # order within one block; nested blocks analyze independently)
    def scan_block(stmts: list[ast.stmt]) -> None:
        for idx, stmt in enumerate(stmts):
            for call in _walk_scope(stmt):
                if not isinstance(call, ast.Call):
                    continue
                argnums = donated_argnums(call.func)
                if argnums is None:
                    continue
                for an in argnums:
                    if an >= len(call.args) \
                            or not isinstance(call.args[an], ast.Name):
                        continue
                    name = call.args[an].id
                    if _stmt_rebinds(stmt, name):
                        # `state = step(state, ...)`: the donating
                        # statement itself rebinds — the sanctioned
                        # consume-and-rebind pattern
                        continue
                    for later in stmts[idx + 1:]:
                        load = _first_load(later, name)
                        if load is not None:
                            findings.append(Finding(
                                "SL503", relpath, load.lineno,
                                load.col_offset,
                                f"`{name}` read after being donated to "
                                f"`{_callee_leaf(call.func, imports)}` "
                                f"(arg {an}): the donated buffers may "
                                "be aliased/deleted on a donating "
                                "backend — rebind the returned state "
                                "and never touch the input again "
                                "(docs/performance.md donation "
                                "contract)"))
                            break
                        if _stmt_rebinds(later, name):
                            break
    scan_block(getattr(tree, "body", []))
    for node in ast.walk(tree):
        for attr in ("body", "orelse", "finalbody"):
            block = getattr(node, attr, None)
            if isinstance(block, list) and block \
                    and isinstance(block[0], ast.stmt) \
                    and block is not getattr(tree, "body", None):
                scan_block(block)
    return findings


# -- SL401: swallowed broad exceptions -----------------------------------

_BROAD_EXC = {"Exception", "BaseException"}

#: call leaves that count as "the error was at least logged"
_LOG_LEAVES = {"debug", "info", "warning", "error", "exception",
               "critical", "log", "warn", "print", "print_exc"}


def _exc_leaf(node: ast.expr, imports: _Imports) -> str:
    resolved = imports.resolve(node)
    if resolved:
        return resolved.rsplit(".", 1)[-1]
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _handler_is_broad(handler: ast.ExceptHandler, imports: _Imports) -> bool:
    """bare `except:`, `except Exception`, `except BaseException`, or a
    tuple containing one of those."""
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Tuple):
        return any(_exc_leaf(e, imports) in _BROAD_EXC for e in t.elts)
    return _exc_leaf(t, imports) in _BROAD_EXC


def _body_only_swallows(body: list[ast.stmt]) -> bool:
    """True when the handler does literally nothing: only pass /
    `...` / continue statements."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) \
                and isinstance(stmt.value, ast.Constant):
            continue  # a bare string/ellipsis expression
        return False
    return True


def _body_reraises_or_logs(body: list[ast.stmt], imports: _Imports) -> bool:
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call) \
                    and _exc_leaf(node.func, imports) in _LOG_LEAVES:
                return True
    return False


class _Linter(ast.NodeVisitor):
    def __init__(self, relpath: str, imports: _Imports):
        self.relpath = relpath
        self.imports = imports
        self.sets = _SetTracker()
        self.host_arrays = _SetTracker()  # locals assigned from numpy.*
        self.findings: list[Finding] = []

    # -- bookkeeping -----------------------------------------------------

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        if rule_applies(rule, self.relpath):
            self.findings.append(Finding(
                rule, self.relpath, node.lineno, node.col_offset, message
            ))

    def visit_Import(self, node: ast.Import) -> None:
        self.imports.add_import(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        self.imports.add_from(node)

    def _visit_scope(self, node) -> None:
        self._check_defaults(node)
        self.sets.push()
        self.host_arrays.push()
        self.generic_visit(node)
        self.host_arrays.pop()
        self.sets.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_scope(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_scope(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        is_set = _is_set_expr(node.value, self.sets)
        value_src = None
        if isinstance(node.value, ast.Call):
            value_src = self.imports.resolve(node.value.func)
        is_np = bool(value_src) and value_src.startswith("numpy.")
        for target in node.targets:
            if isinstance(target, ast.Name):
                (self.sets.mark if is_set else self.sets.unmark)(target.id)
                (self.host_arrays.mark if is_np
                 else self.host_arrays.unmark)(target.id)
        self.generic_visit(node)

    # -- SL403: variadic sorts past the payload diet ----------------------

    #: sort-diet payload budget: a sort may carry up to this many
    #: non-key operands before it reads as the variadic anti-pattern
    _SORT_PAYLOAD_BUDGET = 3

    def _check_sort_diet(self, node: ast.Call, resolved) -> None:
        leaf = _callee_leaf(node.func, self.imports)
        if resolved and resolved.endswith("lax.sort"):
            # jax.lax.sort((a, b, ...), num_keys=k): count the operand
            # tuple; non-tuple first args (a Name forwarding *arrays)
            # are not statically countable
            if not node.args or not isinstance(node.args[0], ast.Tuple):
                return
            elts = node.args[0].elts
            keys_kw, default_keys = "num_keys", 1
        elif leaf == "_row_sort":
            # the plane's row-sort wrapper: _row_sort(*arrays, keys=k)
            elts = list(node.args)
            keys_kw, default_keys = "keys", None
        else:
            return
        if any(isinstance(e, ast.Starred) for e in elts):
            return  # e.g. _row_perm_sort's *extra_keys: uncountable
        num_keys = default_keys
        for kw in node.keywords:
            if kw.arg == keys_kw:
                if isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, int):
                    num_keys = kw.value.value
                else:
                    return  # computed key count: uncountable
        if num_keys is None:
            return
        payload = len(elts) - num_keys
        if payload > self._SORT_PAYLOAD_BUDGET:
            self._emit(
                "SL403", node,
                f"variadic sort carries {payload} payload operands "
                f"(> {self._SORT_PAYLOAD_BUDGET}) through the comparator "
                "network; pack the keys and move payload to a "
                "permutation/bucketed placement (sort diet, "
                "docs/performance.md) — parity-reference paths need a "
                "justified suppression")

    # -- SL101 / SL102: calls --------------------------------------------

    # -- SL405: blocking telemetry reads outside the harvest boundary ----

    def _check_telemetry_read(self, node: ast.Call, resolved) -> None:
        if resolved == "float" and node.args \
                and _mentions_telemetry(node.args[0]):
            self._emit(
                "SL405", node,
                "host-side float(...) read of a device telemetry array "
                "outside the harvest boundary: this is a blocking D2H "
                "sync — route observability reads through the "
                "asynchronous TelemetryHarvester/FlightRecorder drain "
                "(docs/observability.md no-host-sync rule)")
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr == "item" and not node.args \
                and _mentions_telemetry(node.func.value):
            self._emit(
                "SL405", node,
                "host-side .item() read of a device telemetry array "
                "outside the harvest boundary: this is a blocking D2H "
                "sync — route observability reads through the "
                "asynchronous TelemetryHarvester/FlightRecorder drain "
                "(docs/observability.md no-host-sync rule)")

    def visit_Call(self, node: ast.Call) -> None:
        resolved = self.imports.resolve(node.func)
        self._check_sort_diet(node, resolved)
        self._check_telemetry_read(node, resolved)
        if resolved in _WALL_CLOCK:
            self._emit("SL101", node,
                       f"wall-clock read `{resolved}` in simulation code; "
                       "simulated time must come from the event clock")
        elif resolved and resolved.startswith("random."):
            self._emit("SL102", node,
                       f"global-stream randomness `{resolved}`; draw from "
                       "the seeded streams in core/rng.py instead")
        elif resolved and resolved.startswith("numpy.random."):
            leaf = resolved.rsplit(".", 1)[1]
            if leaf not in _NP_RANDOM_OK:
                self._emit(
                    "SL102", node,
                    f"legacy global `{resolved}`; use a seeded "
                    "np.random.default_rng(...) or core/rng.py")
        self.generic_visit(node)

    # -- SL103: unordered iteration --------------------------------------

    def _check_iter(self, node: ast.AST, iter_expr: ast.expr) -> None:
        if _is_set_expr(iter_expr, self.sets):
            self._emit("SL103", node,
                       "iteration over a set: order is insertion/"
                       "hash-dependent; sort it (or use a list/dict) "
                       "before it can feed event scheduling")

    def visit_For(self, node: ast.For) -> None:
        self._check_iter(node, node.iter)
        self.generic_visit(node)

    def visit_comprehension_generators(self, node) -> None:
        for gen in node.generators:
            self._check_iter(node, gen.iter)
        self.generic_visit(node)

    visit_ListComp = visit_comprehension_generators
    visit_DictComp = visit_comprehension_generators
    visit_GeneratorExp = visit_comprehension_generators

    # building a set is fine; only iterating one is hazardous, so
    # SetComp gets the same generator check as the other comprehensions
    visit_SetComp = visit_comprehension_generators

    # -- SL104: mutable defaults -----------------------------------------

    def _check_defaults(self, node) -> None:
        args = node.args
        for default in list(args.defaults) + [
            d for d in args.kw_defaults if d is not None
        ]:
            bad = isinstance(default, (ast.List, ast.Dict, ast.Set,
                                       ast.ListComp, ast.DictComp,
                                       ast.SetComp))
            if isinstance(default, ast.Call):
                callee = self.imports.resolve(default.func)
                bad = callee in ("list", "dict", "set",
                                 "collections.defaultdict",
                                 "collections.deque",
                                 "collections.OrderedDict")
            if bad:
                self._emit("SL104", default,
                           "mutable default argument; default to None "
                           "and construct inside the function")

    # -- SL401: swallowed broad exceptions --------------------------------

    def visit_Try(self, node: ast.Try) -> None:
        for h in node.handlers:
            if h.type is None:
                # bare except: catches KeyboardInterrupt/SystemExit too;
                # acceptable only when the error is re-raised or logged
                if not _body_reraises_or_logs(h.body, self.imports):
                    self._emit(
                        "SL401", h,
                        "bare `except:` without re-raise or log swallows "
                        "every error (including KeyboardInterrupt); "
                        "catch a concrete exception type, or re-raise/"
                        "log (fault-plane error discipline, "
                        "docs/robustness.md)")
            elif _handler_is_broad(h, self.imports) \
                    and _body_only_swallows(h.body):
                self._emit(
                    "SL401", h,
                    "broad exception swallowed (`except Exception: "
                    "pass`): a real fault disappears instead of "
                    "surfacing as a structured error; narrow the type "
                    "or log it (docs/robustness.md)")
        self.generic_visit(node)

    # -- SL105: traced-value branching -----------------------------------

    def _check_branch(self, node: ast.AST, test: ast.expr,
                      what: str) -> None:
        if _contains_traced_read(test, self.imports, self.host_arrays):
            self._emit("SL105", node,
                       f"Python {what} on a traced/device value; use "
                       "lax.cond/select or jax.device_get at an explicit "
                       "sync point")

    def visit_If(self, node: ast.If) -> None:
        self._check_branch(node, node.test, "`if`")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_branch(node, node.test, "`while`")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_branch(node, node.test, "conditional expression")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._check_branch(node, node.test, "`assert`")
        self.generic_visit(node)


def lint_source(source: str, relpath: str,
                suppressions=None) -> list[Finding]:
    """Lint one file's text under the scoping rules for `relpath`.

    Returns ALL findings, with suppressed ones marked (so reports can
    show suppression coverage); malformed disable comments (missing the
    ``-- justification``) leave their findings unsuppressed. Pass a
    pre-parsed ``Suppressions`` to avoid re-scanning the source when the
    caller already needs it (e.g. for malformed-comment reporting).
    """
    tree = ast.parse(source, filename=relpath)
    linter = _Linter(relpath, _Imports())
    linter.visit(tree)
    # SL301/SL402 run as post-passes: the import table is complete after
    # the main visit, and kernel detection needs the whole-file def map
    linter.findings.extend(
        _sl301_findings(tree, linter.imports, relpath))
    linter.findings.extend(
        _sl402_findings(tree, linter.imports, relpath))
    linter.findings.extend(
        _sl503_findings(tree, linter.imports, relpath))
    sup = suppressions if suppressions is not None \
        else parse_suppressions(source)
    for f in linter.findings:
        just = sup.lookup(f.rule, f.line)
        if just is not None:
            f.suppressed = True
            f.justification = just
    return sorted(linter.findings, key=lambda f: (f.path, f.line, f.col))


def lint_file(path: str, relpath: str | None = None) -> list[Finding]:
    with open(path, encoding="utf-8") as fh:
        return lint_source(fh.read(), relpath or path)
