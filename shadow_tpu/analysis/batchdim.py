"""shadowlint pass 5 (shadowbatch): world-axis independence proofs.

ROADMAP item 4's ensemble contract — "world b of a batched run is
bitwise-equal to its solo run" — was only checkable by running every
world twice. This module retires that 2x-run trap the same way
shadowprove retired presence-invisibility sampling: the batched jaxpr
of every registered plane entry (``jaxpr_audit.traced`` gains
``@vmapW{w}`` variants) is abstract-interpreted once, statically, and
three rule families gate on the result:

- **SL701 world-isolation** — axis-provenance tracking over the
  batched jaxpr. Every input leaf of the vmapped entry carries the
  world axis at dim 0; the walk transfers "where does world w's data
  live" through every primitive (broadcast moves it by
  ``broadcast_dimensions``, transpose permutes it, reshape must keep
  it a standalone dim, gather/scatter must carry it in the explicit
  ``operand_batching_dims``/``*_indices_batching_dims``) and emits a
  finding for any primitive that reduces, gathers, scatters, sorts,
  concatenates, or pads ACROSS it — op + ``file:line`` + the
  offending axis. Zero findings is the world-isolation theorem: no
  dataflow path mixes two worlds, so world b's outputs are a function
  of world b's inputs alone.

- **SL702 RNG stream disjointness** — the per-world key derivation
  (``tpu/elastic.world_key``: ``fold_in(root, seed)``) is walked
  symbolically, proving the derived key is INJECTIVE in the seed:
  mod-2^32 bijections (add/sub/xor const, mul odd const) preserve
  injectivity outright, non-bijective affine steps fall back to a
  wrap-free interval argument over the declared seed domain (the
  SL506 machinery on fold-in arithmetic), and a threefry invocation
  under a FIXED key is a block-cipher bijection of its counter block.
  Distinct seeds => distinct derived keys => the per-world cipher
  invocation sets ``{(key_b, counter)}`` are pairwise disjoint — the
  counter-stream disjointness every per-world draw inherits.

- **SL703 vmap-traceability census** — every registered entry either
  vmaps cleanly at TWO world counts with a stable primitive census
  (same graph, wider arrays — the shape-polymorphism witness), or
  carries a written refusal rationale in ``VMAP_REFUSALS``. The
  pallas kernels refuse (their ``pallas_call`` bodies are opaque to
  the provenance walk), exactly like they refuse faults/guards
  threading — registered, not silent; a refusal naming a
  no-longer-registered entry is itself a finding.

Soundness caveat (mirrors ``ranges.py``): SL701 proves DATAFLOW
isolation over the jaxpr. Two constructs sit outside pure dataflow and
are handled by jax's own vmap contract, with the worlds-parity test
(tests/test_ensemble.py) as the runtime witness: a batched while-loop
predicate (the batching rule freezes finished worlds per-world in the
lowering) and the trip-count sharing it implies. Everything the jaxpr
CAN express is proven, not sampled.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import numpy as np

try:
    from jax.extend import core as _core
except ImportError:  # older jax spells it jax.core
    from jax import core as _core

from . import jaxpr_audit
from .rules import Finding

__all__ = [
    "BATCH_ALLOWED",
    "BATCH_WORLD_COUNTS",
    "BatchEntry",
    "RngObligation",
    "VMAP_REFUSALS",
    "batch_entries",
    "check_all_batch",
    "check_rng_disjoint",
    "check_vmap_census",
    "check_world_axis",
    "prove_fold_chain",
    "rng_obligations",
    "world_axis_findings",
]

#: the two audited world counts: tracing the same entry at both and
#: comparing the primitive census is the cheap witness that the
#: batched graph is world-count-polymorphic (wider arrays, same ops)
BATCH_WORLD_COUNTS = (2, 3)

#: entries that REFUSE the vmap surface, with the written rationale
#: SL703 requires (a refusal is a registered engineering decision, not
#: a silent skip; one naming a de-registered entry is a finding)
VMAP_REFUSALS: dict[str, str] = {
    "shadow_tpu.tpu.plane:window_step[pallas]":
        "pallas_call bodies are opaque to the axis-provenance walk "
        "(the kernel is not a jaxpr at this level, and the batching "
        "rule folds the world axis into the pallas grid), so no "
        "world-isolation theorem exists for this entry; the xla twin "
        "window_step[lean] proves the identical plane math, and "
        "ensemble runs dispatch the xla kernel "
        "(faults/guards refuse pallas the same way)",
    "shadow_tpu.tpu.plane:window_step[pallas_fused]":
        "same as window_step[pallas]: the fused rank->place->egress "
        "pipeline is one opaque pallas_call; its bitwise parity with "
        "the proven xla path is pinned by tests/test_pallas_*.py, "
        "and drive_ensemble is documented xla-only",
}

#: (entry key, rule) -> justification for a deliberately-accepted
#: finding — the pass-5 analogue of the jaxpr-audit allow-lists
#: (batched findings have no source comment to anchor a suppression)
BATCH_ALLOWED: dict[tuple[str, str], str] = {}


# --------------------------------------------------------------------------
# SL701: the axis-provenance interpreter
# --------------------------------------------------------------------------

#: shape-preserving lane-wise primitives: the world axis passes through
#: untouched as long as every world-batched operand agrees on where it
#: is (two different positions would lane-wise combine world i with
#: world j — a cross-world mix, flagged)
_ELEMENTWISE = frozenset({
    "abs", "acos", "add", "and", "asin", "atan", "atan2", "cbrt",
    "ceil", "clamp", "clz", "complex", "conj", "convert_element_type",
    "copy", "cos", "cosh", "device_put", "div", "eq", "erf", "erfc",
    "erf_inv", "exp", "exp2", "expm1", "floor", "ge", "ge_to", "gt",
    "gt_to", "imag",
    "integer_pow", "is_finite", "le", "le_to", "lt_to", "log",
    "log1p", "log2",
    "logistic", "lt", "max", "min", "mul", "ne", "neg", "nextafter",
    "not", "or", "population_count", "pow", "random_fold_in",
    "random_seed", "real", "reduce_precision", "rem", "round",
    "rsqrt", "select_n", "shift_left", "shift_right_arithmetic",
    "shift_right_logical", "sign", "sin", "sinh", "sqrt", "square",
    "stop_gradient", "sub", "tan", "tanh", "threefry2x32", "xor",
})

#: primitives whose outputs keep the operand's LEADING dims (the key
#: dims) and append/expand trailing implementation dims
_PASS_LEADING = frozenset({"random_bits", "random_split",
                           "random_unwrap"})

_REDUCES = frozenset({
    "argmax", "argmin", "reduce", "reduce_and", "reduce_max",
    "reduce_min", "reduce_or", "reduce_prod", "reduce_sum",
    "reduce_xor",
})

_CUMULATIVE = frozenset({"cumlogsumexp", "cummax", "cummin", "cumprod",
                         "cumsum"})

_CALL_LIKE = ("pjit", "closed_call", "core_call", "remat", "checkpoint",
              "custom_jvp_call", "custom_vjp_call",
              "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr")

_SUB_JAXPR_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr")


def _shape(atom) -> tuple:
    return tuple(getattr(getattr(atom, "aval", None), "shape", ()) or ())


def _source_of(eqn) -> str:
    from .ranges import _source_line

    return _source_line(eqn)


class _WorldWalk:
    """One axis-provenance pass over a batched jaxpr.

    Per-var lattice value: ``None`` (world-free — identical across
    worlds, whatever its shape) or ``int d`` (world w's data lives at
    index w of axis d). Loop carries run a quiet fixpoint first
    (``None -> d`` is the only upward move, so ``len(carry)+1`` rounds
    suffice) and findings are emitted on one final loud pass."""

    def __init__(self, where: str, w: int):
        self.where = where
        self.w = w
        self.findings: list[Finding] = []
        self.batched_census: dict[str, int] = {}
        self.quiet = 0

    # -- findings ----------------------------------------------------------

    def _find(self, eqn, msg: str):
        if self.quiet:
            return
        src = _source_of(eqn)
        loc = f" at {src}" if src else ""
        text = f"{msg}{loc}"
        if any(f.message == text for f in self.findings):
            return  # one finding per distinct (op, reason, line)
        self.findings.append(Finding("SL701", self.where, 0, 0, text))

    def _agree(self, eqn, wds, what: str):
        """The single world-axis position among `wds`, flagging a mix."""
        ds = sorted({d for d in wds if d is not None})
        if len(ds) > 1:
            self._find(
                eqn, f"cross-world `{eqn.primitive.name}`: {what} "
                f"operands carry the world axis at different dims "
                f"{ds} (lane-wise combine of two worlds)")
        return ds[0] if ds else None

    # -- jaxpr walk --------------------------------------------------------

    def run(self, jaxpr_like, in_wds) -> list:
        raw = getattr(jaxpr_like, "jaxpr", jaxpr_like)
        env: dict = {}

        def read(v):
            if isinstance(v, _core.Literal):
                return None
            return env.get(v)

        if len(raw.invars) != len(in_wds):
            raise ValueError(
                f"jaxpr arity mismatch in {self.where}: "
                f"{len(raw.invars)} invars, {len(in_wds)} world dims")
        for var, d in zip(raw.invars, in_wds):
            if d is not None:
                env[var] = d

        for eqn in raw.eqns:
            wds = [read(v) for v in eqn.invars]
            if not self.quiet and any(d is not None for d in wds):
                name = eqn.primitive.name
                self.batched_census[name] = \
                    self.batched_census.get(name, 0) + 1
            outs = self.eval_eqn(eqn, wds)
            for var, d in zip(eqn.outvars, outs):
                if d is not None:
                    env[var] = d

        return [read(v) for v in raw.outvars]

    # -- transfer functions ------------------------------------------------

    def eval_eqn(self, eqn, wds) -> list:
        name = eqn.primitive.name
        params = eqn.params
        n_out = len(eqn.outvars)

        if all(d is None for d in wds):
            # world-free inputs => world-free outputs, for ANY
            # primitive (deterministic ops replicate identically
            # across worlds); control flow still needs no descent
            return [None] * n_out

        if name in _ELEMENTWISE:
            d = self._agree(eqn, wds, "elementwise")
            return [d] * n_out

        if name in _PASS_LEADING:
            return [wds[0]] * n_out

        if name == "random_wrap":
            d = wds[0]
            if d is not None and d >= len(_shape(eqn.invars[0])) - 1:
                self._find(
                    eqn, "cross-world `random_wrap`: the world axis "
                    f"(dim {d}) is packed into the key impl words")
                return [None] * n_out
            return [d] * n_out

        if name == "broadcast_in_dim":
            d = wds[0]
            bcd = tuple(params["broadcast_dimensions"])
            return [bcd[d] if d is not None else None] * n_out

        if name == "transpose":
            d = wds[0]
            perm = tuple(params["permutation"])
            return [perm.index(d) if d is not None else None] * n_out

        if name == "reshape":
            return self._reshape(eqn, wds)

        if name == "squeeze":
            d = wds[0]
            dims = tuple(params.get("dimensions") or ())
            if d is None:
                return [None] * n_out
            return [d - sum(1 for r in dims if r < d)] * n_out

        if name == "rev":
            d = wds[0]
            dims = tuple(params.get("dimensions") or ())
            if d is not None and d in dims:
                self._find(
                    eqn, "cross-world `rev`: reverses along the world "
                    f"axis (dim {d}) — world b reads world W-1-b")
            return [d] * n_out

        if name in _REDUCES:
            axes = tuple(params.get("axes", params.get("dimensions"))
                         or ())
            d = self._agree(eqn, wds, "reduction")
            if d is None:
                return [None] * n_out
            if d in axes:
                self._find(
                    eqn, f"cross-world `{name}`: reduces over the "
                    f"world axis (dim {d}) — one output mixes every "
                    "world")
                return [None] * n_out
            return [d - sum(1 for a in axes if a < d)] * n_out

        if name in _CUMULATIVE:
            d = wds[0]
            if d is not None and params.get("axis") == d:
                self._find(
                    eqn, f"cross-world `{name}`: accumulates along "
                    f"the world axis (dim {d})")
            return [d] * n_out

        if name == "sort":
            d = self._agree(eqn, wds, "sort")
            if d is not None and params.get("dimension") == d:
                self._find(
                    eqn, "cross-world `sort`: sorts along the world "
                    f"axis (dim {d}) — worlds exchange lanes")
            # ONE key-derived permutation applies to every operand, so
            # any batched key makes every output world-dependent
            return [d] * n_out

        if name == "concatenate":
            d = self._agree(eqn, wds, "concatenate")
            if d is not None and params.get("dimension") == d:
                self._find(
                    eqn, "cross-world `concatenate`: concatenates "
                    f"along the world axis (dim {d})")
            return [d] * n_out

        if name == "pad":
            d = wds[0]
            cfg = tuple(params.get("padding_config") or ())
            if d is not None and d < len(cfg) and \
                    tuple(cfg[d]) != (0, 0, 0):
                self._find(
                    eqn, "cross-world `pad`: pads the world axis "
                    f"(dim {d}, config {tuple(cfg[d])}) — the world "
                    "count changes mid-graph")
            return [d] * n_out

        if name == "slice":
            return self._slice(eqn, wds)

        if name == "dynamic_slice":
            return self._dynamic_slice(eqn, wds)

        if name == "dynamic_update_slice":
            return self._dynamic_update_slice(eqn, wds)

        if name == "split":
            d = self._agree(eqn, wds, "split")
            if d is not None and params.get("axis") == d:
                self._find(
                    eqn, "cross-world `split`: splits the world axis "
                    f"(dim {d})")
            return [d] * n_out

        if name == "top_k":
            d = wds[0]
            rank = len(_shape(eqn.invars[0]))
            if d is not None and d == rank - 1:
                self._find(
                    eqn, "cross-world `top_k`: selects along the "
                    f"world axis (dim {d})")
            return [d] * n_out

        if name == "gather":
            return self._gather(eqn, wds)

        if name.startswith("scatter"):
            return self._scatter(eqn, wds)

        if name == "dot_general":
            return self._dot_general(eqn, wds)

        if name == "iota":
            return [None] * n_out

        if name in _CALL_LIKE:
            return self._call_like(eqn, wds)

        if name == "cond":
            return self._cond(eqn, wds)

        if name == "while":
            return self._while(eqn, wds)

        if name == "scan":
            return self._scan(eqn, wds)

        if name == "pallas_call":
            self._find(
                eqn, "cross-world hazard: opaque `pallas_call` with a "
                "world-batched operand — the kernel body is invisible "
                "to the provenance walk (register a VMAP_REFUSALS "
                "rationale for pallas entries instead)")
            return [None] * n_out

        self._find(
            eqn, f"unmodeled primitive `{name}` with a world-batched "
            "operand: the axis-provenance walk has no transfer rule "
            "for it, so world isolation is unproven here")
        d = self._agree(eqn, wds, "unmodeled")
        return [d] * n_out

    # -- structural handlers -----------------------------------------------

    def _reshape(self, eqn, wds):
        d = wds[0]
        n_out = len(eqn.outvars)
        if d is None:
            return [None] * n_out
        if eqn.params.get("dimensions") is not None:
            self._find(
                eqn, "cross-world `reshape`: a transposing reshape "
                f"(dimensions=...) moves the world axis (dim {d}) "
                "unanalyzably")
            return [None] * n_out
        in_shape = _shape(eqn.invars[0])
        out_shape = tuple(eqn.params["new_sizes"])
        before = int(np.prod(in_shape[:d], dtype=np.int64))
        prefix = 1
        for dp, size in enumerate(out_shape):
            if prefix == before and size == in_shape[d]:
                return [dp] * n_out
            prefix *= size
        self._find(
            eqn, "cross-world `reshape`: the world axis (dim "
            f"{d} of {list(in_shape)}) does not survive as a "
            f"standalone dim of {list(out_shape)} — worlds are "
            "folded together")
        return [None] * n_out

    def _slice(self, eqn, wds):
        d = wds[0]
        n_out = len(eqn.outvars)
        if d is None:
            return [None] * n_out
        p = eqn.params
        shape = _shape(eqn.invars[0])
        start = tuple(p["start_indices"])[d]
        limit = tuple(p["limit_indices"])[d]
        stride = tuple(p["strides"] or [1] * len(shape))[d]
        if (start, limit, stride) != (0, shape[d], 1):
            self._find(
                eqn, "cross-world `slice`: slices the world axis "
                f"(dim {d}: [{start}:{limit}:{stride}] of "
                f"{shape[d]}) — worlds are dropped or renumbered")
        return [d] * n_out

    def _dynamic_slice(self, eqn, wds):
        d = wds[0]
        n_out = len(eqn.outvars)
        if any(x is not None for x in wds[1:]):
            self._find(
                eqn, "cross-world `dynamic_slice`: a world-batched "
                "start index survived batching (expected a gather)")
        if d is None:
            return [None] * n_out
        sizes = tuple(eqn.params["slice_sizes"])
        shape = _shape(eqn.invars[0])
        if sizes[d] != shape[d]:
            self._find(
                eqn, "cross-world `dynamic_slice`: takes a strict "
                f"subset of the world axis (dim {d}: {sizes[d]} of "
                f"{shape[d]} worlds)")
        return [d] * n_out

    def _dynamic_update_slice(self, eqn, wds):
        d, du = wds[0], wds[1]
        n_out = len(eqn.outvars)
        if any(x is not None for x in wds[2:]):
            self._find(
                eqn, "cross-world `dynamic_update_slice`: a "
                "world-batched start index survived batching")
        if d is None and du is None:
            return [None] * n_out
        shape = _shape(eqn.invars[0])
        ushape = _shape(eqn.invars[1])
        start_d = eqn.invars[2 + (d if d is not None else du)]
        full = (d is not None and du == d
                and ushape[d] == shape[d]
                and isinstance(start_d, _core.Literal)
                and int(start_d.val) == 0)
        if not full:
            self._find(
                eqn, "cross-world `dynamic_update_slice`: the update "
                "does not cover the whole world axis aligned at 0 "
                f"(operand dim {d}, update dim {du})")
        return [d if d is not None else du] * n_out

    def _gather(self, eqn, wds):
        wo, wi = wds[0], wds[1]
        n_out = len(eqn.outvars)
        p = eqn.params
        dn = p["dimension_numbers"]
        obd = tuple(int(d) for d in
                    (getattr(dn, "operand_batching_dims", ()) or ()))
        sibd = tuple(int(d) for d in
                     (getattr(dn, "start_indices_batching_dims", ())
                      or ()))
        op_shape = _shape(eqn.invars[0])
        idx_rank = len(_shape(eqn.invars[1]))
        out_rank = len(_shape(eqn.outvars[0]))
        offset = tuple(dn.offset_dims)
        collapsed = set(dn.collapsed_slice_dims)
        start_map = set(dn.start_index_map)
        sizes = tuple(p["slice_sizes"])
        batch_out = [dp for dp in range(out_rank) if dp not in offset]
        idx_batch = [i for i in range(idx_rank) if i != idx_rank - 1]

        def out_from_idx_dim(sib):
            # indices dims (minus the trailing coordinate-vector dim)
            # map IN ORDER onto the non-offset output dims
            return [batch_out[idx_batch.index(sib)]] * n_out

        if wi is not None and wi == idx_rank - 1:
            self._find(
                eqn, "cross-world `gather`: the world axis (indices "
                f"dim {wi}) feeds the coordinate vector — one lookup "
                "mixes coordinates from every world")
            wi = None
        if wo is not None and wo in obd:
            # the structural proof: a declared operand batching dim is
            # blocked per-world by gather semantics — output block w
            # reads ONLY operand block w, whatever the index values
            sib = sibd[obd.index(wo)]
            if wi is not None and wi != sib:
                self._find(
                    eqn, "cross-world `gather`: world-batched indices "
                    f"(dim {wi}) not aligned with the operand's "
                    f"batching dim pairing (expected indices dim "
                    f"{sib})")
            return out_from_idx_dim(sib)
        if wo is not None:
            # no batching-dim declaration for the world axis: it may
            # still ride through wholesale as an un-indexed full slice
            ops_kept = [d for d in range(len(op_shape))
                        if d not in collapsed and d not in obd]
            if wo not in start_map and wo in ops_kept and \
                    sizes[wo] == op_shape[wo]:
                return [offset[ops_kept.index(wo)]] * n_out
            self._find(
                eqn, "cross-world `gather`: indexes across the world "
                f"axis (operand dim {wo}: not in "
                f"operand_batching_dims={list(obd)}, and not a full "
                f"un-indexed slice — slice_sizes[{wo}]={sizes[wo]} "
                f"of {op_shape[wo]}) — world b can read world c's "
                "lanes")
            return [None] * n_out
        if wi is not None:
            if wi in sibd:
                return out_from_idx_dim(wi)
            # shared-table lookup with per-world indices: safe
            return out_from_idx_dim(wi)
        return [None] * n_out

    def _scatter(self, eqn, wds):
        name = eqn.primitive.name
        wo, wi, wu = wds[0], wds[1], wds[2]
        n_out = len(eqn.outvars)
        dn = eqn.params["dimension_numbers"]
        obd = tuple(int(d) for d in
                    (getattr(dn, "operand_batching_dims", ()) or ()))
        sibd = tuple(int(d) for d in
                     (getattr(dn, "scatter_indices_batching_dims", ())
                      or ()))
        op_rank = len(_shape(eqn.invars[0]))
        idx_rank = len(_shape(eqn.invars[1]))
        upd_rank = len(_shape(eqn.invars[2]))
        uwd = tuple(int(d) for d in dn.update_window_dims)
        inserted = set(int(d) for d in dn.inserted_window_dims)
        upd_batch = [dp for dp in range(upd_rank) if dp not in uwd]
        ops_window = [d for d in range(op_rank)
                      if d not in inserted and d not in obd]

        if wi is not None and wi == idx_rank - 1:
            self._find(
                eqn, f"cross-world `{name}`: the world axis (indices "
                f"dim {wi}) feeds the coordinate vector — one write "
                "mixes coordinates from every world")
            wi = None
        # the structural proof: a declared batching-dim pairing blocks
        # the scatter per-world — update/index block w writes ONLY
        # operand block w, whatever the index VALUES are (replicated
        # world-free indices included)
        if wo is not None and wo in obd:
            sib = sibd[obd.index(wo)]
            if wi is not None and wi != sib:
                self._find(
                    eqn, f"cross-world `{name}`: world-batched "
                    f"indices (dim {wi}) not aligned with the "
                    f"operand's batching dim pairing (expected "
                    f"indices dim {sib})")
            return [wo] * n_out
        if wo is None and wi is not None and wi in sibd:
            return [obd[sibd.index(wi)]] * n_out
        if wi is None:
            # world-free indices (static-slice updates like
            # `x.at[:, 0].set(v)`): the batching rule carries the
            # world axis as a WINDOW dim — one scatter, and within
            # its window the updates' world dim maps elementwise onto
            # the operand's, so world w's row lands in world w's lane
            if wu is not None and wu in uwd:
                owd = ops_window[uwd.index(wu)]
                if wo is None or wo == owd:
                    return [owd] * n_out
                self._find(
                    eqn, f"cross-world `{name}`: the updates' world "
                    f"window dim maps to operand dim {owd} but the "
                    f"operand's world axis is dim {wo} — worlds are "
                    "transposed by the write")
                return [wo] * n_out
            if wu is None and wo is not None:
                if wo in ops_window:
                    # replicated world-free update written into every
                    # world's window slice: per-world isolated
                    return [wo] * n_out
                self._find(
                    eqn, f"cross-world `{name}`: a world-free index "
                    "selects a single lane ALONG the world axis "
                    f"(operand dim {wo} is scattered, not a window "
                    "dim) — one world's lane receives the write")
                return [wo] * n_out
        if wo is None and (wi is not None or wu is not None):
            self._find(
                eqn, f"cross-world `{name}`: per-world indices/"
                "updates scattered into a world-SHARED operand (no "
                "batching-dim pairing) — one array receives every "
                "world's writes")
            return [None] * n_out
        if wo is not None:
            self._find(
                eqn, f"cross-world `{name}`: writes across the world "
                f"axis (operand dim {wo} not carried in "
                f"operand_batching_dims={list(obd)}) — world b can "
                "write world c's lanes")
            return [wo] * n_out
        return [None] * n_out

    def _dot_general(self, eqn, wds):
        wl, wr = wds[0], wds[1]
        n_out = len(eqn.outvars)
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lhs_rank = len(_shape(eqn.invars[0]))
        rhs_rank = len(_shape(eqn.invars[1]))
        if (wl is not None and wl in lc) or \
                (wr is not None and wr in rc):
            self._find(
                eqn, "cross-world `dot_general`: contracts over the "
                f"world axis (lhs dim {wl}, rhs dim {wr})")
            return [None] * n_out
        if wl is not None and wl in lb:
            if wr is not None and wr in rb and \
                    list(lb).index(wl) == list(rb).index(wr):
                return [list(lb).index(wl)] * n_out
            self._find(
                eqn, "cross-world `dot_general`: lhs world batch dim "
                f"{wl} has no matching rhs batch dim (rhs {wr})")
            return [None] * n_out
        if wr is not None and wr in rb:
            self._find(
                eqn, "cross-world `dot_general`: rhs world batch dim "
                f"{wr} has no matching lhs batch dim (lhs {wl})")
            return [None] * n_out
        if wl is not None and wr is not None:
            self._find(
                eqn, "cross-world `dot_general`: both operands carry "
                f"free world axes (lhs {wl}, rhs {wr}) — the product "
                "pairs every world with every other")
            return [None] * n_out
        lhs_free = [dp for dp in range(lhs_rank)
                    if dp not in lc and dp not in lb]
        rhs_free = [dp for dp in range(rhs_rank)
                    if dp not in rc and dp not in rb]
        if wl is not None:
            return [len(lb) + lhs_free.index(wl)] * n_out
        return [len(lb) + len(lhs_free) + rhs_free.index(wr)] * n_out

    # -- control flow ------------------------------------------------------

    def _sub(self, params):
        for key in _SUB_JAXPR_KEYS:
            sub = params.get(key)
            if sub is not None:
                return sub
        return None

    def _call_like(self, eqn, wds):
        n_out = len(eqn.outvars)
        sub = self._sub(eqn.params)
        raw = getattr(sub, "jaxpr", sub) if sub is not None else None
        if raw is None or len(raw.invars) != len(wds):
            self._find(
                eqn, f"unmodeled call-like `{eqn.primitive.name}` "
                "with a world-batched operand (no aligned sub-jaxpr)")
            return [None] * n_out
        outs = self.run(sub, wds)
        return outs[:n_out] + [None] * (n_out - len(outs))

    def _join_carry(self, eqn, old, new, what: str):
        joined, changed = [], False
        for a, b in zip(old, new):
            if a is None and b is not None:
                joined.append(b)
                changed = True
            elif a is not None and b is not None and a != b:
                self._find(
                    eqn, f"cross-world `{eqn.primitive.name}`: "
                    f"{what} carry slot moves the world axis per "
                    f"iteration (dim {a} -> {b})")
                joined.append(a)
            else:
                joined.append(a)
        return joined, changed

    def _while(self, eqn, wds):
        p = eqn.params
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        cond_c, body_c = wds[:cn], wds[cn:cn + bn]
        carry = list(wds[cn + bn:])
        self.quiet += 1
        try:
            for _ in range(len(carry) + 1):
                new = self.run(p["body_jaxpr"], list(body_c) + carry)
                carry, changed = self._join_carry(
                    eqn, carry, new, "while")
                if not changed:
                    break
        finally:
            self.quiet -= 1
        # loud final passes: body findings surface once, and the cond
        # is analyzed too (its output may legitimately stay batched —
        # the vmap batching rule owns per-world termination)
        final = self.run(p["body_jaxpr"], list(body_c) + carry)
        carry, _ = self._join_carry(eqn, carry, final, "while")
        self.run(p["cond_jaxpr"], list(cond_c) + carry)
        return carry

    def _scan(self, eqn, wds):
        p = eqn.params
        nc, ncar = p["num_consts"], p["num_carry"]
        consts = wds[:nc]
        carry = list(wds[nc:nc + ncar])
        xs_body = []
        for i, d in enumerate(wds[nc + ncar:]):
            if d == 0:
                self._find(
                    eqn, "cross-world `scan`: iterates OVER the world "
                    "axis (xs leading dim is the world dim) — worlds "
                    "execute sequentially through one carry")
                xs_body.append(None)
            else:
                xs_body.append(None if d is None else d - 1)
        self.quiet += 1
        try:
            for _ in range(len(carry) + 1):
                outs = self.run(p["jaxpr"],
                                list(consts) + carry + xs_body)
                carry, changed = self._join_carry(
                    eqn, carry, outs[:ncar], "scan")
                if not changed:
                    break
        finally:
            self.quiet -= 1
        outs = self.run(p["jaxpr"], list(consts) + carry + xs_body)
        carry, _ = self._join_carry(eqn, carry, outs[:ncar], "scan")
        ys = [None if d is None else d + 1 for d in outs[ncar:]]
        return carry + ys

    def _cond(self, eqn, wds):
        n_out = len(eqn.outvars)
        pred, ops = wds[0], wds[1:]
        if pred is not None:
            self._find(
                eqn, "cross-world `cond`: the branch index is "
                "world-batched (escaped the select_n batching rule) — "
                "one branch choice would serve every world")
        outs = [None] * n_out
        for branch in eqn.params["branches"]:
            raw = getattr(branch, "jaxpr", branch)
            if len(raw.invars) != len(ops):
                self._find(eqn, "unmodeled `cond`: branch arity "
                                "mismatch with world-batched operands")
                return [None] * n_out
            b_outs = self.run(branch, list(ops))
            joined = []
            for a, b in zip(outs, b_outs):
                if a is not None and b is not None and a != b:
                    self._find(
                        eqn, "cross-world `cond`: branches return "
                        f"the world axis at different dims ({a} vs "
                        f"{b})")
                joined.append(a if a is not None else b)
            outs = joined
        return outs


def world_axis_findings(closed_jaxpr, where: str, w: int
                        ) -> tuple[list[Finding], dict]:
    """SL701 over one batched jaxpr whose every invar carries the
    world axis at dim 0 (constvars — closed-over params/roots — are
    world-free by construction). Returns (findings, entry_row)."""
    walk = _WorldWalk(where, w)
    raw = closed_jaxpr.jaxpr
    out_wds = walk.run(closed_jaxpr, [0] * len(raw.invars))
    row = {
        "entry": where,
        "world_count": w,
        "proved": not walk.findings,
        "batched_ops": dict(sorted(walk.batched_census.items())),
        "out_world_dims": [d for d in out_wds],
        "findings": len(walk.findings),
    }
    return walk.findings, row


# --------------------------------------------------------------------------
# SL702: the fold-chain injectivity prover
# --------------------------------------------------------------------------

_CONST, _INJ, _DEP = "const", "inj", "dep"


@dataclass
class RngObligation:
    """One registered per-world key-derivation chain.

    ``build`` returns ``(fn, args, seed_argnum, (lo, hi))`` — the
    traced chain, its example args, which argument is the per-world
    seed, and the declared seed domain the interval fallbacks assume
    (recorded in the report like the SL506 domain registry)."""

    name: str
    build: Callable[[], tuple]


def rng_obligations() -> list[RngObligation]:
    """The registered derivation surface: every function that turns a
    per-world seed into that world's RNG key. One entry today —
    ``tpu/elastic.world_key``, the chain ``drive_ensemble`` consumers
    and the ensemble audit entry both use."""
    def _world_key():
        import jax
        import jax.numpy as jnp

        from ..tpu import elastic

        root = jax.random.key(0)

        def fn(seed):
            return elastic.world_key(root, seed)

        return fn, (jnp.int32(0),), 0, (0, 2**31 - 1)

    return [RngObligation("shadow_tpu.tpu.elastic:world_key",
                          _world_key)]


def _bits(dtype) -> int:
    try:
        return int(np.dtype(dtype).itemsize) * 8
    except TypeError:
        return 32  # extended dtypes (PRNG keys): word-sized payload


def _lit_int(atom):
    if isinstance(atom, _core.Literal):
        val = np.asarray(atom.val)
        if val.size == 1 and np.issubdtype(val.dtype, np.integer):
            return int(val)
    return None


def _fits(iv, dtype) -> bool:
    if iv is None:
        return False
    lo, hi = iv
    try:
        info = np.iinfo(np.dtype(dtype))
    except (TypeError, ValueError):
        return False
    return info.min <= lo and hi <= info.max


def prove_fold_chain(ob: RngObligation) -> tuple[list[Finding], dict]:
    """Walk one derivation chain's jaxpr, proving the output key is
    injective in the seed argument. Statuses: ``const`` (seed-free),
    ``inj`` (provably injective in the seed over its domain), ``dep``
    (seed-dependent, injectivity lost), and pair tags for raw
    threefry outputs that are injective only JOINTLY."""
    fn, args, seed_ix, domain = ob.build()
    closed, _shape_, _args = jaxpr_audit.traced(f"{ob.name}@rng",
                                                lambda: (fn, args))
    raw = closed.jaxpr
    status: dict = {}
    ivs: dict = {}
    chain: list[dict] = []
    demoted: list[str] = []
    pair_n = 0

    for i, v in enumerate(raw.invars):
        status[v] = _INJ if i == seed_ix else _CONST
        if i == seed_ix:
            ivs[v] = tuple(domain)
    for v in raw.constvars:
        status[v] = _CONST

    def read(atom):
        if isinstance(atom, _core.Literal):
            return _CONST
        return status.get(atom, _CONST)

    def note(eqn, outs, why):
        chain.append({"prim": eqn.primitive.name,
                      "status": outs[0] if outs else _CONST,
                      "why": why})
        if outs and outs[0] == _DEP and not demoted:
            src = _source_of(eqn)
            demoted.append(f"`{eqn.primitive.name}` ({why})"
                           + (f" at {src}" if src else ""))

    def descend(eqn, sts):
        """Inline a call-like eqn (pjit wrappers around jnp ops —
        `seed % 4` arrives as a pjit'd `remainder`): bind statuses
        and intervals through the sub-jaxpr and walk it in place."""
        sub = next((eqn.params[k] for k in _SUB_JAXPR_KEYS
                    if eqn.params.get(k) is not None), None)
        sraw = getattr(sub, "jaxpr", sub) if sub is not None else None
        if sraw is None or len(sraw.invars) != len(eqn.invars):
            return False
        for sv, at in zip(sraw.invars, eqn.invars):
            status[sv] = read(at)
            if not isinstance(at, _core.Literal) and at in ivs:
                ivs[sv] = ivs[at]
        for sv in sraw.constvars:
            status[sv] = _CONST
        walk(sraw.eqns)
        for ov, sv in zip(eqn.outvars, sraw.outvars):
            status[ov] = read(sv)
            if not isinstance(sv, _core.Literal) and sv in ivs:
                ivs[ov] = ivs[sv]
        return True

    def eval_one(eqn):
        nonlocal pair_n
        name = eqn.primitive.name
        sts = [read(v) for v in eqn.invars]
        out_dtype = getattr(getattr(eqn.outvars[0], "aval", None),
                            "dtype", None)
        outs = None
        why = ""
        iv0 = (ivs.get(eqn.invars[0])
               if not isinstance(eqn.invars[0], _core.Literal)
               else None)

        if name in _CALL_LIKE and any(s != _CONST for s in sts):
            if descend(eqn, sts):
                return
            outs = [_DEP] * len(eqn.outvars)
            why = (f"call-like `{name}` with no aligned sub-jaxpr on "
                   "a seed-dependent value")
        elif all(s == _CONST for s in sts):
            outs, why = [_CONST] * len(eqn.outvars), "seed-free"
        elif name == "convert_element_type":
            in_dt = getattr(getattr(eqn.invars[0], "aval", None),
                            "dtype", None)
            if sts[0] == _INJ and (_bits(out_dtype) >= _bits(in_dt)
                                   or _fits(iv0, out_dtype)):
                outs = [_INJ]
                why = (f"width-preserving convert "
                       f"({in_dt}->{out_dtype}): bijective mod 2^n")
                if iv0 is not None:
                    ivs[eqn.outvars[0]] = iv0
            elif sts[0] == _INJ:
                outs = [_DEP]
                why = (f"narrowing convert {in_dt}->{out_dtype} "
                       "without a domain-fit proof")
            else:
                outs, why = [sts[0]], "pass-through"
        elif name in ("add", "sub", "xor") and \
                sorted(sts) == [_CONST, _INJ]:
            outs = [_INJ]
            why = f"`{name}` with a constant: bijective mod 2^n"
            c = _lit_int(eqn.invars[1 if sts[0] == _INJ else 0])
            iv = ivs.get(eqn.invars[0 if sts[0] == _INJ else 1])
            if name == "add" and c is not None and iv is not None:
                ivs[eqn.outvars[0]] = (iv[0] + c, iv[1] + c)
        elif name == "neg" and sts[0] == _INJ:
            outs, why = [_INJ], "negation: bijective mod 2^n"
        elif name == "mul" and sorted(sts) == [_CONST, _INJ]:
            inj_ix = sts.index(_INJ)
            c = _lit_int(eqn.invars[1 - inj_ix])
            iv = ivs.get(eqn.invars[inj_ix])
            if c is not None and c % 2 == 1:
                outs = [_INJ]
                why = f"`mul` by odd constant {c}: bijective mod 2^n"
            elif c not in (None, 0) and iv is not None and _fits(
                    (iv[0] * c, iv[1] * c) if c > 0
                    else (iv[1] * c, iv[0] * c), out_dtype):
                outs = [_INJ]
                why = (f"`mul` by {c}: wrap-free on the declared "
                       f"seed domain {list(iv)} (interval argument)")
                ivs[eqn.outvars[0]] = (min(iv[0] * c, iv[1] * c),
                                       max(iv[0] * c, iv[1] * c))
            else:
                outs = [_DEP]
                why = (f"`mul` by {'unknown' if c is None else c}: "
                       "not a mod-2^n bijection and no wrap-free "
                       "interval proof")
        elif name == "shift_left" and sts[0] == _INJ:
            k = _lit_int(eqn.invars[1])
            if k is not None and iv0 is not None and _fits(
                    (iv0[0] << k, iv0[1] << k), out_dtype):
                outs = [_INJ]
                why = (f"`shift_left` by {k}: wrap-free on the "
                       f"declared seed domain {list(iv0)}")
                ivs[eqn.outvars[0]] = (iv0[0] << k, iv0[1] << k)
            else:
                outs = [_DEP]
                why = "`shift_left` drops high bits (no domain proof)"
        elif name == "random_fold_in":
            if sts[0] == _CONST and sts[1] == _INJ:
                outs = [_INJ]
                why = ("fold_in under a FIXED root key: threefry with "
                       "a constant key is a bijection of its counter "
                       "block, so distinct data -> distinct keys")
            else:
                outs = [_DEP]
                why = ("fold_in with a seed-dependent root key: a "
                       "cipher is not injective in its KEY input")
        elif name in ("random_wrap", "random_unwrap"):
            outs, why = [sts[0]], "key<->u32 repack: bijective"
        elif name == "threefry2x32":
            k_const = sts[0] == _CONST and sts[1] == _CONST
            if k_const and _INJ in (sts[2], sts[3]):
                pair_n += 1
                outs = [("pair", pair_n), ("pair", pair_n)]
                why = ("threefry under a fixed key: counter-block "
                       "bijection — outputs injective JOINTLY "
                       f"(pair #{pair_n})")
            else:
                outs = [_DEP] * len(eqn.outvars)
                why = ("threefry with a seed-dependent key operand: "
                       "not injective in the key")
        elif name == "concatenate":
            if _INJ in sts and all(s in (_CONST, _INJ) for s in sts):
                outs = [_INJ]
                why = ("concatenation containing an injective "
                       "coordinate: injective as a vector")
            else:
                outs = [_DEP]
                why = "concatenation without an injective coordinate"
        elif name in ("reshape", "broadcast_in_dim", "squeeze", "pad",
                      "copy"):
            outs, why = [sts[0]], "entry-preserving restructure"
        else:
            outs = [_DEP] * len(eqn.outvars)
            why = (f"no injectivity transfer rule for `{name}` on a "
                   "seed-dependent value")

        for v, s in zip(eqn.outvars, outs):
            status[v] = s
        note(eqn, outs, why)

    def walk(eqns):
        for eqn in eqns:
            eval_one(eqn)

    walk(raw.eqns)
    out_sts = [read(v) for v in raw.outvars]
    pairs_seen: dict = {}
    for s in out_sts:
        if isinstance(s, tuple):
            pairs_seen[s[1]] = pairs_seen.get(s[1], 0) + 1
    ok = (_INJ in out_sts) or any(n >= 2 for n in pairs_seen.values())

    findings: list[Finding] = []
    if not ok:
        reason = demoted[0] if demoted else \
            "no injective path from the seed to the key"
        findings.append(Finding(
            "SL702", ob.name, 0, 0,
            "per-world RNG key derivation is NOT provably injective "
            f"in the world seed: {reason}. Two worlds could derive "
            "the same key and replay each other's threefry counter "
            "stream; use a mod-2^n-bijective fold chain "
            "(tpu/elastic.world_key)"))
    row = {
        "obligation": ob.name,
        "ok": ok,
        "seed_domain": list(domain),
        "chain": chain,
        "claim": ("distinct seeds -> distinct derived keys -> the "
                  "per-world cipher invocation sets {(key_b, "
                  "counter)} are pairwise disjoint"),
    }
    return findings, row


def check_rng_disjoint(obligations=None
                       ) -> tuple[list[Finding], list[dict]]:
    """SL702 over every registered derivation chain."""
    findings, rows = [], []
    for ob in (obligations if obligations is not None
               else rng_obligations()):
        f, row = prove_fold_chain(ob)
        findings.extend(f)
        rows.append(row)
    return findings, rows


# --------------------------------------------------------------------------
# SL703: the vmap-traceability census + the batch-entry registry
# --------------------------------------------------------------------------


@dataclass
class BatchEntry:
    """One entry of the batch surface: ``build_w(w)`` returns the
    zero-arg (fn, args) thunk of the entry ALREADY batched over ``w``
    worlds (registry entries wrap their audit builder via
    ``jaxpr_audit.vmap_build``; prebatched obligations like the
    ensemble step supply their own world-parametrized builder)."""

    key: str
    build_w: Callable[[int], Callable]


def batch_entries() -> list[BatchEntry]:
    """The batch surface: every registered jaxpr-audit entry plus the
    ensemble consumer itself (per-world keys/shifts batched, params
    shared) — so the proofs cover both 'any entry CAN be ensembled'
    and the step ``drive_ensemble`` actually dispatches."""
    out = [
        BatchEntry(f"{e.module}:{e.name}",
                   lambda w, _b=e.build: jaxpr_audit.vmap_build(_b, w))
        for e in jaxpr_audit.default_entries()
    ]
    out.append(BatchEntry("shadow_tpu.tpu.elastic:ensemble_step[lean]",
                          jaxpr_audit.ensemble_step_build))
    return out


def _traced_w(entry: BatchEntry, w: int):
    closed, _shape_, _args = jaxpr_audit.traced(
        f"{entry.key}@vmapW{w}", entry.build_w(w))
    return closed


def _full_census(closed) -> dict[str, int]:
    from .dataflow import _iter_all_eqns

    census: dict[str, int] = {}
    for eqn in _iter_all_eqns(closed):
        census[eqn.primitive.name] = \
            census.get(eqn.primitive.name, 0) + 1
    return census


def check_vmap_census(entries=None, refusals=None
                      ) -> tuple[list[Finding], list[dict], list[dict]]:
    """SL703: every entry vmaps at both world counts with a stable
    census, or carries a written refusal (``refusals`` defaults to
    the checked-in ``VMAP_REFUSALS``; fixtures inject their own).
    Returns (findings, entry_rows, refusal_rows)."""
    entries = batch_entries() if entries is None else entries
    refused = VMAP_REFUSALS if refusals is None else refusals
    findings, rows, refusal_rows = [], [], []
    keys = {e.key for e in entries}

    for key, rationale in sorted(refused.items()):
        if key not in keys:
            findings.append(Finding(
                "SL703", key, 0, 0,
                "stale vmap refusal: no audited entry by this key — "
                "delete the refusal or fix the entry name"))
            continue
        if not rationale.strip():
            findings.append(Finding(
                "SL703", key, 0, 0,
                "vmap refusal without a written rationale: refusals "
                "are registered engineering decisions, not skips"))
        refusal_rows.append({"entry": key, "rationale": rationale})

    for entry in entries:
        if entry.key in refused:
            continue
        censuses = []
        failed = False
        for w in BATCH_WORLD_COUNTS:
            try:
                censuses.append(_full_census(_traced_w(entry, w)))
            except Exception as exc:  # noqa: BLE001 — the finding IS the report
                findings.append(Finding(
                    "SL703", entry.key, 0, 0,
                    f"entry does not vmap at W={w}: "
                    f"{type(exc).__name__}: {str(exc)[:160]} — fix "
                    "the kernel or register a VMAP_REFUSALS "
                    "rationale"))
                failed = True
                break
        if failed:
            continue
        stable = censuses[0] == censuses[1]
        if not stable:
            drift = sorted(
                k for k in set(censuses[0]) | set(censuses[1])
                if censuses[0].get(k) != censuses[1].get(k))
            findings.append(Finding(
                "SL703", entry.key, 0, 0,
                "vmapped jaxpr is not world-count-stable: primitive "
                f"census differs between W={BATCH_WORLD_COUNTS[0]} "
                f"and W={BATCH_WORLD_COUNTS[1]} on {drift} — the "
                "graph depends on the world count, so per-world "
                "behavior is not count-invariant"))
        rows.append({
            "entry": entry.key,
            "ok": stable,
            "world_counts": list(BATCH_WORLD_COUNTS),
            "ops": sum(censuses[0].values()),
        })
    return findings, rows, refusal_rows


def check_world_axis(entries=None, w: int = BATCH_WORLD_COUNTS[0]
                     ) -> tuple[list[Finding], list[dict]]:
    """SL701 over every non-refused entry's W-world batched jaxpr
    (reuses the trace cache the census pass already filled)."""
    entries = batch_entries() if entries is None else entries
    findings, rows = [], []
    for entry in entries:
        if entry.key in VMAP_REFUSALS:
            continue
        try:
            closed = _traced_w(entry, w)
        except Exception:  # noqa: BLE001  # shadowlint: disable=SL401 -- check_vmap_census reports this same trace failure as an SL703 finding; duplicating it here would double-count every broken entry
            continue
        f, row = world_axis_findings(closed, entry.key, w)
        findings.extend(f)
        rows.append(row)
    return findings, rows


# --------------------------------------------------------------------------
# the pass-5 driver
# --------------------------------------------------------------------------


def check_all_batch(selected=frozenset({"SL701", "SL702", "SL703"})
                    ) -> tuple[list[Finding], dict]:
    """Run the selected batch families over the registered surface.
    Returns (findings, batch_report) — the report is the
    ``--batch-report`` artifact and the json-v2 ``batch`` section."""
    findings: list[Finding] = []
    census_rows: list[dict] = []
    refusal_rows: list[dict] = []
    axis_rows: list[dict] = []
    rng_rows: list[dict] = []
    entries = batch_entries()

    if "SL703" in selected:
        f, census_rows, refusal_rows = check_vmap_census(entries)
        findings.extend(f)
    if "SL701" in selected:
        f, axis_rows = check_world_axis(entries)
        findings.extend(f)
    if "SL702" in selected:
        f, rng_rows = check_rng_disjoint()
        findings.extend(f)

    for f in findings:
        just = BATCH_ALLOWED.get((f.path, f.rule))
        if just:
            f.suppressed = True
            f.justification = just

    active = [f for f in findings if not f.suppressed]
    report = {
        "version": 1,
        "rules": sorted(selected & {"SL701", "SL702", "SL703"}),
        "world_counts": list(BATCH_WORLD_COUNTS),
        "caveat": (
            "SL701 proves dataflow isolation over the batched jaxpr; "
            "batched while-loop predicates (trip-count sharing with "
            "per-world select-freeze) are the vmap batching rule's "
            "contract, witnessed at runtime by the worlds-parity "
            "test. SL702's disjointness claim is on cipher "
            "invocation sets: distinct derived keys mean no two "
            "worlds ever issue the same (key, counter) threefry "
            "call."),
        "entries": axis_rows,
        "census": census_rows,
        "refusals": refusal_rows,
        "rng": rng_rows,
        "summary": {
            "entries": len(axis_rows),
            "proved": sum(1 for r in axis_rows if r["proved"]),
            "refused": len(refusal_rows),
            "rng_obligations": len(rng_rows),
            "active_findings": len(active),
            "suppressed_findings": len(findings) - len(active),
        },
    }
    return findings, report
