"""SL505: build-time equivalence proofs for the gated ``lax.cond``s.

The device plane leans on a handful of `lax.cond` *gates* — conds whose
two branches are claimed bitwise-equal on the domain where the gate
selects the fast branch, so the cond can only ever change SPEED, never
a bit:

- `plane.ingest_rows`' ``gate_idle`` (an entry-free merge is the
  identity on a front-packed row),
- the PR-11 ident-vs-sort gates (`plane._compact_ingress`,
  `plane._egress_order` FIFO: a stable sort of an already-ordered
  packed key with the column tiebreak IS the identity),
- the flow plane's idle gates (`flows.flow_recv` / `flows.flow_emit`:
  a window with no tagged deliveries / no valid emission lanes leaves
  every field untouched).

Until this pass those contracts were docstring sentences sampled by
runtime parity tests. Here each registered gate becomes a build-time
obligation, proved one of three ways (recorded per gate in the report):

1. **syntactic** — the two branch jaxprs are identical after
   canonicalization (dead-code elimination, constant folding,
   alpha-renaming). The degenerate-but-cheap case.
2. **structural** — predicate-assumption proof: the gate predicate is
   recognized as a sortedness check (``(k[:, :-1] <= k[:, 1:]).all()``
   over a cond operand), every stable 1-key sort of that operand in a
   branch is rewritten to the identity (stability + the in-key
   tiebreak make the permutation the identity on sorted input — the
   "sort-of-sorted" rewrite), and the remaining branch bodies are
   proved extensionally equal by a *selection witness*: both branches
   are evaluated on position-coded operands where every op must be
   either constant-derived (index arithmetic — concretely folded) or
   selection-transparent (gather / select_n / reshape / slice /
   concatenate / broadcast — ops that only COPY operand elements).
   Equal witness outputs under two independent code bases prove the
   branches compute the identical selection of their operands, for
   every input satisfying the predicate.
3. **exhaustive** — the fallback, clearly marked: the whole entry is
   evaluated concretely over a registered input lattice (tiny N/CE
   worlds with boundary values: empty/full rows, 0/1/I32_MAX
   sentinels, duplicate keys, foreign-tagged traffic) and on every
   lattice point where the predicate selects the fast branch, both
   branches must produce bitwise-equal outputs. The lattice must hit
   the gated domain at least ``min_gated`` times, or the proof fails
   as vacuous.

A failed proof names the FIRST diverging output leaf (and the lattice
point that exposed it) — see ``tests/lint_fixtures/fixture_condeq_gate.py``
for the seeded violation.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from .rules import Finding

try:
    from jax.extend import core as _core
except ImportError:  # older jax spells it jax.core
    from jax import core as _core

__all__ = [
    "GateObligation",
    "GateProof",
    "check_all_gates",
    "check_gate",
    "gate_obligations",
]

I32_MAX = np.int32(2**31 - 1)


# --------------------------------------------------------------------------
# obligation + proof records
# --------------------------------------------------------------------------


@dataclass
class GateObligation:
    """One registered `lax.cond` gate.

    ``build`` returns (fn, args) like an AuditEntry; the traced jaxpr
    must contain exactly one top-level ``cond`` (the gate).
    ``gate_value`` is the predicate value under which the gate claims
    branch equivalence (True for the ident-vs-sort gates — ordered
    input takes the identity branch; False for the idle gates — an
    empty window takes the identity branch). ``lattice`` returns the
    exhaustive-fallback input points (arg tuples shaped like
    ``build``'s args); ``out_names`` labels the cond's output leaves
    for the diverging-leaf message."""

    name: str
    module: str
    build: Callable[[], tuple[Callable, tuple]]
    gate_value: bool
    lattice: Callable[[], list[tuple]] | None = None
    out_names: Callable[[], list[str]] | None = None
    #: fail the proof unless at least this many lattice points land in
    #: the gated domain (a lattice that never exercises the gate would
    #: prove nothing)
    min_gated: int = 4


@dataclass
class GateProof:
    """The per-gate verdict for the ``--condeq-report`` artifact."""

    name: str
    module: str
    mode: str  # "syntactic" | "structural" | "exhaustive" | "failed"
    ok: bool
    detail: str = ""
    lattice_points: int = 0
    gated_points: int = 0
    findings: list[Finding] = field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "gate": f"{self.module}:{self.name}",
            "mode": self.mode,
            "ok": self.ok,
            "detail": self.detail,
            "lattice_points": self.lattice_points,
            "gated_points": self.gated_points,
        }


# --------------------------------------------------------------------------
# jaxpr utilities: locate the gate, evaluate eagerly
# --------------------------------------------------------------------------


def _raw(jaxpr_like):
    return getattr(jaxpr_like, "jaxpr", jaxpr_like)


def _find_gate(closed):
    """(eqn_index, eqn) of the single top-level cond."""
    conds = [(i, e) for i, e in enumerate(_raw(closed).eqns)
             if e.primitive.name == "cond"]
    if len(conds) != 1:
        raise ValueError(
            f"expected exactly one top-level lax.cond in the gate "
            f"entry, found {len(conds)} — trace the section helper "
            "that owns the gate, not a composite kernel")
    return conds[0]


def _eval_eqns(raw, consts, in_vals, *, until=None):
    """Eager forward evaluation of a (raw) jaxpr via primitive.bind.

    Evaluates equations [0, until) and returns the environment reader;
    with until=None evaluates everything and returns the output values.
    """
    env: dict = {}

    def read(v):
        if isinstance(v, _core.Literal):
            return v.val
        return env[v]

    for var, val in zip(raw.constvars, consts):
        env[var] = val
    if len(raw.invars) != len(in_vals):
        raise ValueError(f"arity mismatch: {len(raw.invars)} invars, "
                         f"{len(in_vals)} values")
    for var, val in zip(raw.invars, in_vals):
        env[var] = val

    stop = len(raw.eqns) if until is None else until
    for eqn in raw.eqns[:stop]:
        vals = [read(v) for v in eqn.invars]
        outs = eqn.primitive.bind(*vals, **eqn.params)
        if not eqn.primitive.multiple_results:
            outs = [outs]
        for var, out in zip(eqn.outvars, outs):
            env[var] = out
    if until is None:
        return [read(v) for v in raw.outvars]
    return read


def _eval_branch(branch_closed, operand_vals):
    raw = _raw(branch_closed)
    consts = getattr(branch_closed, "consts", [])
    return _eval_eqns(raw, consts, list(operand_vals))


# --------------------------------------------------------------------------
# mode 1: syntactic canonical equality
# --------------------------------------------------------------------------


def _canon_param(value) -> str:
    if isinstance(value, (_core.Jaxpr, _core.ClosedJaxpr)):
        return f"jaxpr<{_canonical_form(value)}>"
    if isinstance(value, (tuple, list)):
        return "(" + ",".join(_canon_param(v) for v in value) + ")"
    if isinstance(value, np.ndarray):
        return (f"ndarray<{value.dtype}{value.shape}:"
                f"{hashlib.sha256(np.ascontiguousarray(value).tobytes()).hexdigest()[:16]}>")
    return repr(value)


def _canon_const(value) -> str:
    try:
        return _canon_param(np.asarray(value))
    except TypeError:  # extended dtypes (PRNG keys) refuse conversion
        return f"opaque<{type(value).__name__}>"


def _live_eqns(raw):
    """Dead-code elimination: equations whose outputs (transitively)
    feed the jaxpr outputs, in original order."""
    needed = {v for v in raw.outvars if not isinstance(v, _core.Literal)}
    keep = []
    for eqn in reversed(raw.eqns):
        if any(v in needed for v in eqn.outvars):
            keep.append(eqn)
            for v in eqn.invars:
                if not isinstance(v, _core.Literal):
                    needed.add(v)
    keep.reverse()
    return keep


def _canonical_form(jaxpr_like) -> str:
    """Alpha-renamed, dead-code-eliminated textual form. Constants fold
    implicitly: a literal renders by value, and consts render by their
    byte hash, so two branches differing only in var names or dead
    equations canonicalize identically."""
    raw = _raw(jaxpr_like)
    consts = list(getattr(jaxpr_like, "consts", []))
    names: dict = {}

    def ref(v):
        if isinstance(v, _core.Literal):
            return f"lit:{_canon_const(v.val)}"
        if v not in names:
            names[v] = f"v{len(names)}"
        return names[v]

    lines = []
    for var, const in zip(raw.constvars, consts):
        lines.append(f"const {ref(var)} = {_canon_const(const)}")
    for var in raw.invars:
        lines.append(f"in {ref(var)} : {var.aval.str_short()}")
    for eqn in _live_eqns(raw):
        params = ",".join(f"{k}={_canon_param(v)}"
                          for k, v in sorted(eqn.params.items()))
        ins = ",".join(ref(v) for v in eqn.invars)
        outs = ",".join(ref(v) for v in eqn.outvars)
        lines.append(f"{outs} = {eqn.primitive.name}[{params}]({ins})")
    lines.append("out " + ",".join(ref(v) for v in raw.outvars))
    return "\n".join(lines)


# --------------------------------------------------------------------------
# mode 2: predicate assumptions + sort elimination + selection witness
# --------------------------------------------------------------------------


def _full_slice(start, limit, strides, shape, axis):
    """True when the slice spans every axis fully except `axis`."""
    if strides is not None and any(s != 1 for s in strides):
        return False
    for d, (s, l, n) in enumerate(zip(start, limit, shape)):
        if d == axis:
            continue
        if s != 0 or l != n:
            return False
    return True


def _sorted_assumptions(raw, gate_eqn):
    """Operand indices the predicate asserts are sorted.

    Recognizes the in-tree gate pattern: the cond's index operand
    derives (through convert_element_type) from
    ``reduce_and(le(slice(x, ..axis window [0, C-1]),
    slice(x, ..axis window [1, C])))`` — pairwise-adjacent
    non-decreasing along `axis`, which is sortedness. Returns
    {operand_position: axis} for operands x that are passed to the
    branches."""
    producers = {}
    for eqn in raw.eqns:
        for v in eqn.outvars:
            producers[v] = eqn

    def producer(v):
        return None if isinstance(v, _core.Literal) else producers.get(v)

    idx_eqn = producer(gate_eqn.invars[0])
    while idx_eqn is not None and idx_eqn.primitive.name in (
            "convert_element_type", "copy"):
        idx_eqn = producer(idx_eqn.invars[0])
    if idx_eqn is None or idx_eqn.primitive.name != "reduce_and":
        return {}
    le_eqn = producer(idx_eqn.invars[0])
    if le_eqn is None or le_eqn.primitive.name != "le":
        return {}
    lo_eqn, hi_eqn = (producer(le_eqn.invars[0]),
                      producer(le_eqn.invars[1]))
    if not (lo_eqn and hi_eqn) or lo_eqn.primitive.name != "slice" \
            or hi_eqn.primitive.name != "slice":
        return {}
    if lo_eqn.invars[0] is not hi_eqn.invars[0]:
        return {}
    x = lo_eqn.invars[0]
    shape = tuple(x.aval.shape)
    lo_p, hi_p = lo_eqn.params, hi_eqn.params
    axis = None
    for d, n in enumerate(shape):
        if (lo_p["start_indices"][d] == 0
                and lo_p["limit_indices"][d] == n - 1
                and hi_p["start_indices"][d] == 1
                and hi_p["limit_indices"][d] == n):
            axis = d
            break
    if axis is None:
        return {}
    if not (_full_slice(lo_p["start_indices"], lo_p["limit_indices"],
                        lo_p.get("strides"), shape, axis)
            and _full_slice(hi_p["start_indices"],
                            hi_p["limit_indices"],
                            hi_p.get("strides"), shape, axis)):
        return {}
    # x must reach the branches as an operand (invars[1:] of the cond)
    out = {}
    for pos, v in enumerate(gate_eqn.invars[1:]):
        if v is x:
            out[pos] = axis
    return out


#: ops that only COPY operand elements (or insert constants) — safe to
#: apply to position-coded witnesses; indices/predicates must be
#: constant-derived
_SELECTION_PRIMS = frozenset({
    "gather", "select_n", "reshape", "broadcast_in_dim", "transpose",
    "slice", "squeeze", "concatenate", "rev", "expand_dims", "copy",
    "pad", "dynamic_slice",
})


#: call-like primitives the witness evaluator descends through
#: (take_along_axis and jnp.where trace as pjit wrappers)
_WITNESS_CALLS = ("pjit", "closed_call", "core_call", "custom_jvp_call",
                  "custom_vjp_call")


class _WitnessFail(Exception):
    pass


def _witness_run(jaxpr_like, in_vals, in_coded, sorted_vars):
    """One (sub-)jaxpr pass of the selection-witness evaluation:
    values are concrete numpy arrays, `coded` flags mark values derived
    from operand position codes. Returns (out_vals, out_coded)."""
    raw = _raw(jaxpr_like)
    consts = list(getattr(jaxpr_like, "consts", []))
    env: dict = {}
    coded: dict = {}

    def read(v):
        if isinstance(v, _core.Literal):
            return np.asarray(v.val)
        return env[v]

    def is_coded(v):
        return (not isinstance(v, _core.Literal)) and coded.get(v, False)

    for var, const in zip(raw.constvars, consts):
        env[var] = np.asarray(const)
        coded[var] = False
    for var, val, c in zip(raw.invars, in_vals, in_coded):
        env[var] = np.asarray(val)
        coded[var] = c

    for eqn in raw.eqns:
        name = eqn.primitive.name
        ins = [read(v) for v in eqn.invars]
        ins_coded = [is_coded(v) for v in eqn.invars]

        if name == "sort":
            key_var = eqn.invars[0]
            if (key_var in sorted_vars
                    and eqn.params.get("num_keys") == 1
                    and eqn.params.get("is_stable")
                    and eqn.params.get("dimension")
                    == sorted_vars[key_var]):
                # sort-of-sorted: stability + the in-key tiebreak make
                # the permutation the identity on sorted keys, so the
                # outputs are the operands verbatim
                outs = list(ins)
                out_coded = list(ins_coded)
            else:
                raise _WitnessFail(
                    "sort without a predicate sortedness assumption")
        elif name in _WITNESS_CALLS:
            from .dataflow import _first_sub_jaxpr

            sub = _first_sub_jaxpr(eqn.params)
            if sub is None or len(_raw(sub).invars) != len(ins):
                raise _WitnessFail(
                    f"call-like `{name}` the witness cannot map 1:1")
            outs, out_coded = _witness_run(sub, ins, ins_coded,
                                           sorted_vars)
            outs = outs[:len(eqn.outvars)]
            out_coded = out_coded[:len(eqn.outvars)]
        elif not any(ins_coded):
            # constant-derived (index arithmetic): fold concretely
            outs = eqn.primitive.bind(*[np.asarray(v) for v in ins],
                                      **eqn.params)
            if not eqn.primitive.multiple_results:
                outs = [outs]
            out_coded = [False] * len(outs)
        elif name in _SELECTION_PRIMS:
            # coded data may only ride the DATA slots: indices
            # (gather/dynamic_slice trailing args) and select_n's
            # predicate must be constant-derived
            if name in ("gather", "dynamic_slice") and any(
                    ins_coded[1:]):
                raise _WitnessFail(f"{name} with coded indices")
            if name == "select_n" and ins_coded[0]:
                raise _WitnessFail("select_n with a coded predicate")
            params = eqn.params
            if name == "gather" and params.get("fill_value") is not None:
                # bool operands were re-typed to int32 codes; keep the
                # fill binding-compatible (fill positions are index-
                # determined and compare by value across branches)
                params = dict(params)
                fv = params["fill_value"]
                params["fill_value"] = np.int32(
                    int(bool(fv)) if isinstance(fv, (bool, np.bool_))
                    else int(fv))
            outs = eqn.primitive.bind(
                *[np.asarray(v) for v in ins], **params)
            if not eqn.primitive.multiple_results:
                outs = [outs]
            out_coded = [True] * len(outs)
        else:
            raise _WitnessFail(
                f"non-selection primitive `{name}` touches coded data")
        for var, out, c in zip(eqn.outvars, outs, out_coded):
            env[var] = np.asarray(out)
            coded[var] = c

    return ([read(v) for v in raw.outvars],
            [is_coded(v) for v in raw.outvars])


def _witness_codes(gate_eqn, code_base):
    """Position-coded witness values for the cond operands.

    jax unions the two branches' closures WITHOUT dedup, so the same
    parent value can appear at several operand positions — those
    positions must carry IDENTICAL codes (the branches are compared as
    functions of the distinct parent values, not of the positional
    slots)."""
    vals: list = []
    by_parent: dict[int, np.ndarray] = {}
    next_code = code_base
    for v in gate_eqn.invars[1:]:
        if not isinstance(v, _core.Literal) and id(v) in by_parent:
            vals.append(by_parent[id(v)])
            continue
        aval = v.aval
        shape = tuple(aval.shape)
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        dt = str(aval.dtype)
        if dt not in ("bool", "int32", "uint32", "int8", "int16"):
            raise _WitnessFail(f"operand dtype {dt} not codeable")
        codes = np.arange(next_code, next_code + n,
                          dtype=np.int32).reshape(shape)
        next_code += n
        vals.append(codes)
        if not isinstance(v, _core.Literal):
            by_parent[id(v)] = codes
    return vals


def _witness_eval(branch_closed, operand_vals, sorted_ops):
    """Evaluate one branch on position-coded operands (see
    `_witness_codes`; bool/int32/uint32 operands are substituted with
    int32 codes of the same shape — selection ops are dtype-generic,
    so the selection map the codes reveal is the operand's too). Equal
    witness outputs across branches (under two independent code bases)
    prove both branches compute the identical selection of their
    operands, for every input satisfying the predicate assumption."""
    raw = _raw(branch_closed)
    sorted_vars = {raw.invars[pos]: axis
                   for pos, axis in sorted_ops.items()}
    return _witness_run(branch_closed, list(operand_vals),
                        [True] * len(operand_vals), sorted_vars)


def _structural_proof(gate_eqn, parent_raw):
    """Try the predicate-assumption structural proof. Returns
    (ok, detail) — ok=None means 'not applicable, fall back'."""
    branches = gate_eqn.params["branches"]
    sorted_ops = _sorted_assumptions(parent_raw, gate_eqn)
    results = []
    for base in (10_000_019, 20_000_033):  # two independent code bases
        try:
            operand_vals = _witness_codes(gate_eqn, base)
            pair = [_witness_eval(b, operand_vals, sorted_ops)
                    for b in branches]
        except _WitnessFail as exc:
            return None, str(exc)
        results.append(pair)
    for (outs_a, _), (outs_b, _) in results:
        for i, (a, b) in enumerate(zip(outs_a, outs_b)):
            if a.shape != b.shape or not np.array_equal(
                    np.asarray(a), np.asarray(b)):
                return False, (f"selection witness diverges at output "
                               f"{i}")
    assumed = (f"assuming operand(s) {sorted(sorted_ops)} sorted "
               f"(predicate pattern)" if sorted_ops else "no assumption")
    return True, f"selection-witness equality; {assumed}"


# --------------------------------------------------------------------------
# mode 3: exhaustive lattice evaluation
# --------------------------------------------------------------------------


def _flatten_args(args):
    from jax import tree_util

    return tree_util.tree_leaves(args)


def _exhaustive_proof(obl: GateObligation, closed, gate_idx, gate_eqn,
                      names: list[str]):
    """Evaluate the entry over the lattice; on every gated point both
    branches must agree bitwise. Returns (ok, gated, total, detail)."""
    raw = _raw(closed)
    consts = list(closed.consts)
    branches = gate_eqn.params["branches"]
    fast = 1 if obl.gate_value else 0

    points = obl.lattice() if obl.lattice is not None else []
    if not points:
        return False, 0, 0, "no lattice registered and structural proof"\
            " not applicable"
    gated = 0
    for p_idx, args in enumerate(points):
        flat = _flatten_args(args)
        read = _eval_eqns(raw, consts, flat, until=gate_idx)
        op_vals = [read(v) for v in gate_eqn.invars]
        sel = int(np.asarray(op_vals[0]))
        if sel != fast:
            continue
        gated += 1
        outs_fast = _eval_branch(branches[fast], op_vals[1:])
        outs_ref = _eval_branch(branches[1 - fast], op_vals[1:])
        for i, (a, b) in enumerate(zip(outs_fast, outs_ref)):
            a, b = np.asarray(a), np.asarray(b)
            if not np.array_equal(a, b):
                leaf = names[i] if i < len(names) else f"out[{i}]"
                bad = np.argwhere(a != b)
                first = tuple(int(x) for x in bad[0]) if bad.size else ()
                return False, gated, len(points), (
                    f"branches diverge at output leaf `{leaf}`"
                    f"{list(first)} on lattice point {p_idx}: "
                    f"fast={a[first] if first else a!r} "
                    f"ref={b[first] if first else b!r}")
    if gated < obl.min_gated:
        return False, gated, len(points), (
            f"lattice exercises the gated domain only {gated}x "
            f"(need >= {obl.min_gated}): the proof would be vacuous")
    return True, gated, len(points), "bitwise-equal on every gated point"


# --------------------------------------------------------------------------
# the checker
# --------------------------------------------------------------------------


def check_gate(obl: GateObligation, *, trace=None) -> GateProof:
    """Prove one gate obligation; `trace` short-circuits the build with
    an already-traced closed jaxpr (the shared proof-pass cache)."""
    if trace is None:
        from .jaxpr_audit import traced

        trace = traced(f"{obl.module}:{obl.name}", obl.build)[0]
    raw = _raw(trace)
    gate_idx, gate_eqn = _find_gate(trace)
    branches = gate_eqn.params["branches"]
    where = f"{obl.module}:{obl.name}"
    names = obl.out_names() if obl.out_names is not None else []

    # mode 1: canonical syntactic equality
    if _canonical_form(branches[0]) == _canonical_form(branches[1]):
        return GateProof(obl.name, obl.module, "syntactic", True,
                         "branches canonicalize identically")

    # mode 2: predicate-assumption structural proof
    ok, detail = _structural_proof(gate_eqn, raw)
    if ok is True:
        return GateProof(obl.name, obl.module, "structural", True,
                         detail)
    if ok is False:
        proof = GateProof(obl.name, obl.module, "failed", False, detail)
        proof.findings.append(Finding(
            "SL505", where, 0, 0,
            f"branch-equivalence proof failed (structural): {detail} — "
            "the gate is not bitwise-invisible "
            "(docs/determinism.md 'Branch gates are theorems')"))
        return proof

    # mode 3: exhaustive fallback (clearly marked in the report)
    ok, gated, total, detail = _exhaustive_proof(
        obl, trace, gate_idx, gate_eqn, names)
    proof = GateProof(obl.name, obl.module,
                      "exhaustive" if ok else "failed", ok, detail,
                      lattice_points=total, gated_points=gated)
    if not ok:
        proof.findings.append(Finding(
            "SL505", where, 0, 0,
            f"branch-equivalence proof failed (exhaustive): {detail}"))
    return proof


def check_all_gates(obligations=None, *, traces=None
                    ) -> tuple[list[Finding], list[GateProof]]:
    findings: list[Finding] = []
    proofs: list[GateProof] = []
    for obl in (obligations if obligations is not None
                else gate_obligations()):
        trace = (traces or {}).get(f"{obl.module}:{obl.name}")
        proof = check_gate(obl, trace=trace)
        proofs.append(proof)
        findings.extend(proof.findings)
    return findings, proofs


# --------------------------------------------------------------------------
# the registered gate surface (the real tree)
# --------------------------------------------------------------------------


def _mini_state(rng, n=4, ce=8, ci=8, *, occupancies=None,
                prio_choices=(0, 1, 5, 5, 1_000, int(I32_MAX) - 1),
                deliver_sorted=False):
    """One front-packed NetPlaneState lattice point: per-row occupancy
    with boundary payload values (0/1/dups/near-sentinel), dead lanes
    at the make_state defaults."""
    import jax.numpy as jnp

    from ..tpu import plane

    state = plane.make_state(n, egress_cap=ce, ingress_cap=ci)
    occ = (occupancies if occupancies is not None
           else [int(rng.integers(0, ce + 1)) for _ in range(n)])
    eg_valid = np.zeros((n, ce), bool)
    eg_prio = np.full((n, ce), int(I32_MAX), np.int64)
    eg_dst = np.full((n, ce), -1, np.int64)
    eg_bytes = np.zeros((n, ce), np.int64)
    eg_seq = np.zeros((n, ce), np.int64)
    eg_sock = np.zeros((n, ce), np.int64)
    in_valid = np.zeros((n, ci), bool)
    in_deliver = np.full((n, ci), int(I32_MAX), np.int64)
    in_src = np.full((n, ci), -1, np.int64)
    in_seq = np.zeros((n, ci), np.int64)
    for row in range(n):
        k = min(occ[row % len(occ)], ce)
        eg_valid[row, :k] = True
        vals = np.sort(rng.choice(prio_choices, size=k))
        eg_prio[row, :k] = vals
        eg_dst[row, :k] = rng.integers(0, n, size=k)
        eg_bytes[row, :k] = rng.choice([0, 1, 64, 1500], size=k)
        eg_seq[row, :k] = rng.integers(0, 100, size=k)
        eg_sock[row, :k] = rng.integers(0, 4, size=k)
        ki = min(occ[row % len(occ)], ci)
        in_valid[row, :ki] = True
        dv = rng.choice([0, 1, 7, 7, 50_000, 9_999_999], size=ki)
        in_deliver[row, :ki] = np.sort(dv) if deliver_sorted else dv
        in_src[row, :ki] = rng.integers(0, n, size=ki)
        in_seq[row, :ki] = rng.integers(0, 100, size=ki)
    return state._replace(
        eg_valid=jnp.asarray(eg_valid),
        eg_prio=jnp.asarray(eg_prio, jnp.int32),
        eg_dst=jnp.asarray(eg_dst, jnp.int32),
        eg_bytes=jnp.asarray(eg_bytes, jnp.int32),
        eg_seq=jnp.asarray(eg_seq, jnp.int32),
        eg_sock=jnp.asarray(eg_sock, jnp.int32),
        in_valid=jnp.asarray(in_valid),
        in_deliver_rel=jnp.asarray(in_deliver, jnp.int32),
        in_src=jnp.asarray(in_src, jnp.int32),
        in_seq=jnp.asarray(in_seq, jnp.int32),
    )


def _state_leaf_names(n=4, ce=8, ci=8):
    from ..tpu import plane

    from .dataflow import leaf_paths

    return leaf_paths(plane.make_state(n, egress_cap=ce, ingress_cap=ci),
                      prefix="state")


def _ingest_rows_gate():
    """ingest_rows' gate_idle: an entry-free merge must be the identity
    on a front-packed row (the contract every producer-side gate and
    the flow plane's emit gate inherit)."""
    import jax.numpy as jnp

    from ..tpu import plane

    n, k = 4, 4
    z = lambda: jnp.zeros((n, k), jnp.int32)

    def build():
        state = _mini_state(np.random.default_rng(0))

        def fn(state, dst, nbytes, prio, seq, valid):
            return plane.ingest_rows(state, dst, nbytes, prio, seq,
                                     jnp.zeros((n, k), bool), valid)

        return fn, (state, z(), z(), z(), z(), jnp.zeros((n, k), bool))

    def lattice():
        rng = np.random.default_rng(7)
        pts = []
        occ_sets = ([0, 0, 0, 0], [1, 0, 8, 3], [8, 8, 8, 8],
                    [7, 1, 0, 8], [2, 2, 2, 2])
        for occ in occ_sets:
            for _ in range(3):
                st = _mini_state(rng, occupancies=occ)
                # gated domain: no new valid entries at all
                pts.append((st, z(), z(), z(), z(),
                            jnp.zeros((n, k), bool)))
        # reference-branch coverage (vacuous for the theorem, keeps the
        # lattice honest about both domains)
        st = _mini_state(rng, occupancies=[1, 2, 3, 4])
        valid = jnp.zeros((n, k), bool).at[0, 0].set(True)
        pts.append((st, z(), z(),
                    jnp.full((n, k), 3, jnp.int32), z(), valid))
        return pts

    return GateObligation(
        "ingest_rows[gate_idle]", "shadow_tpu.tpu.plane", build,
        gate_value=False, lattice=lattice,
        out_names=_state_leaf_names, min_gated=12)


def _compact_ingress_gate():
    """_compact_ingress's ordered gate: a stable sort of an
    already-sorted (validity | deliver) packed key is the identity."""
    import jax.numpy as jnp

    from ..tpu import plane

    def build():
        state = _mini_state(np.random.default_rng(1),
                            deliver_sorted=True)
        in_deliver = jnp.where(state.in_valid, state.in_deliver_rel,
                               plane.I32_MAX)

        def fn(state, in_deliver):
            return plane._compact_ingress(state, in_deliver,
                                          packed_sort=True)

        return fn, (state, in_deliver)

    def lattice():
        import jax.numpy as jnp

        rng = np.random.default_rng(11)
        pts = []
        for occ in ([0, 0, 0, 0], [8, 8, 8, 8], [1, 3, 0, 8],
                    [4, 4, 4, 4]):
            for _ in range(3):
                st = _mini_state(rng, occupancies=occ,
                                 deliver_sorted=True)
                dv = jnp.where(st.in_valid, st.in_deliver_rel,
                               plane.I32_MAX)
                pts.append((st, dv))
        # unsorted points exercise the reference branch
        st = _mini_state(rng, occupancies=[5, 5, 5, 5],
                         deliver_sorted=False)
        dv = jnp.where(st.in_valid, st.in_deliver_rel, plane.I32_MAX)
        pts.append((st, dv))
        return pts

    def out_names():
        return ["deliver_c", "src_c", "seq_c", "sock_c", "bytes_c",
                "valid_c"]

    return GateObligation(
        "_compact_ingress[ordered]", "shadow_tpu.tpu.plane", build,
        gate_value=True, lattice=lattice, out_names=out_names,
        min_gated=8)


def _egress_order_gate():
    """_egress_order's FIFO fast path: a stable sort of a
    non-decreasing (validity | priority) packed key is the identity."""
    import jax.numpy as jnp

    from ..tpu import plane

    def _args(state):
        tsend_rb = jnp.where(state.eg_valid, state.eg_tsend, 0)
        return (state, state.eg_prio, jnp.zeros_like(state.eg_sock),
                tsend_rb, state.eg_clamp)

    def build():
        state = _mini_state(np.random.default_rng(2))

        def fn(state, qkey1, qkey2, tsend_rb, clamp_rb):
            return plane._egress_order(state, qkey1, qkey2, tsend_rb,
                                       clamp_rb, rr_enabled=False,
                                       packed_sort=True)

        return fn, _args(state)

    def lattice():
        rng = np.random.default_rng(13)
        pts = []
        for occ in ([0, 0, 0, 0], [8, 8, 8, 8], [1, 3, 0, 8],
                    [2, 6, 4, 4]):
            for _ in range(3):
                pts.append(_args(_mini_state(rng, occupancies=occ)))
        # an out-of-order row for the reference branch
        st = _mini_state(rng, occupancies=[4, 4, 4, 4])
        st = st._replace(eg_prio=st.eg_prio[:, ::-1])
        pts.append(_args(st))
        return pts

    def out_names():
        return ["eg_prio", "eg_sock", "eg_dst", "eg_bytes", "eg_seq",
                "eg_ctrl", "eg_tsend", "eg_clamp", "eg_valid"]

    return GateObligation(
        "_egress_order[fifo-ordered]", "shadow_tpu.tpu.plane", build,
        gate_value=True, lattice=lattice, out_names=out_names,
        min_gated=8)


def _flow_tables():
    from ..tpu import flows as flows_mod

    n = 4
    return flows_mod.make_flow_tables(
        np.arange(n, dtype=np.int32),
        (np.arange(n, dtype=np.int32) + 1) % n,
        np.full(n, 1400, np.int32)), n


def _flow_state_points(rng, n, count, *, emittable: bool):
    """FlowState lattice points honoring the shift invariant (rcv_bits
    bit 0 False) with boundary cwnd/RTO/clock values; `emittable`
    controls whether any flow has unsent stream or a pending ack."""
    import jax.numpy as jnp

    from ..tpu import flows as flows_mod

    pts = []
    for _ in range(count):
        fs = flows_mod.make_flow_state(n)
        una = rng.integers(0, 50, size=n)
        sent = una + rng.integers(0, 8, size=n)
        bits = rng.integers(0, 2, size=(n, flows_mod.RECV_WND)) == 1
        bits[:, 0] = False  # the post-advance shift invariant
        fs = fs._replace(
            snd_una=jnp.asarray(una, jnp.int32),
            snd_nxt=jnp.asarray(sent, jnp.int32),
            snd_max=jnp.asarray(sent + rng.integers(0, 3, size=n),
                                jnp.int32),
            stream_len=jnp.asarray(
                sent + (rng.integers(1, 5, size=n) if emittable
                        else 0), jnp.int32),
            rcv_nxt=jnp.asarray(rng.integers(0, 40, size=n), jnp.int32),
            rcv_bits=jnp.asarray(bits),
            ack_pending=jnp.asarray(
                rng.integers(0, 2, size=n) == 1 if emittable
                else np.zeros(n, bool)),
            cwnd=jnp.asarray(rng.choice([1, 2, 64, 1 << 20], size=n),
                             jnp.int32),
            srtt_ms=jnp.asarray(rng.choice([0, 1, 3000], size=n),
                                jnp.int32),
            rto_ms=jnp.asarray(rng.choice([200, 60_000], size=n),
                               jnp.int32),
            rto_armed=jnp.asarray(rng.integers(0, 2, size=n) == 1),
            rto_deadline_ms=jnp.asarray(rng.integers(0, 100, size=n),
                                        jnp.int32),
            clock_ms=jnp.asarray(rng.integers(0, 50, size=n),
                                 jnp.int32),
        )
        pts.append(fs)
    return pts


def _delivered_dict(rng, n, ci, kind: str):
    """A delivered dict for the flow_recv lattice. kind:
    'empty' (no deliveries), 'untagged' (mask set, reserved socks),
    'foreign' (flow-tagged but endpoint-mismatched — must still read
    as idle), 'tagged' (real flow traffic, reference branch)."""
    import jax.numpy as jnp

    mask = np.zeros((n, ci), bool)
    sock = np.zeros((n, ci), np.int64)
    seq = np.zeros((n, ci), np.int64)
    src = np.zeros((n, ci), np.int64)
    if kind != "empty":
        k = 3
        for row in range(n):
            mask[row, :k] = True
            seq[row, :k] = rng.integers(0, 64, size=k)
            if kind == "untagged":
                sock[row, :k] = rng.integers(0, 2, size=k)  # reserved
                src[row, :k] = rng.integers(0, n, size=k)
            elif kind == "foreign":
                sock[row, :k] = (rng.integers(0, n, size=k) + 1) * 2
                src[row, :k] = row  # never the flow's src for dst=row
            else:  # tagged: flow f = row-1 delivers data to dst row
                f = (row - 1) % n
                sock[row, :k] = (f + 1) * 2
                src[row, :k] = f
    return {
        "mask": jnp.asarray(mask),
        "src": jnp.asarray(src, jnp.int32),
        "seq": jnp.asarray(seq, jnp.int32),
        "sock": jnp.asarray(sock, jnp.int32),
        "bytes": jnp.asarray(np.full((n, ci), 1400), jnp.int32),
        "deliver_rel": jnp.asarray(
            rng.integers(0, 1_000_000, size=(n, ci)), jnp.int32),
    }


def _flow_recv_gate():
    """flow_recv's idle gate: a window with no flow-tagged deliveries
    (including untagged and endpoint-mismatched tagged traffic) leaves
    every flow field untouched."""
    import jax.numpy as jnp

    from ..tpu import flows as flows_mod

    ft, n = _flow_tables()
    ci = 8

    def build():
        rng = np.random.default_rng(3)
        fs = _flow_state_points(rng, n, 1, emittable=False)[0]

        def fn(fs, delivered, window_ns):
            return flows_mod.flow_recv(ft, fs, delivered, window_ns)

        return fn, (fs, _delivered_dict(rng, n, ci, "empty"),
                    jnp.int32(2_000_000))

    def lattice():
        rng = np.random.default_rng(17)
        pts = []
        for kind in ("empty", "untagged", "foreign"):
            for fs in _flow_state_points(rng, n, 4, emittable=False):
                pts.append((fs, _delivered_dict(rng, n, ci, kind),
                            jnp.int32(int(rng.choice(
                                [1_000_000, 10_000_000])))))
        for fs in _flow_state_points(rng, n, 2, emittable=False):
            pts.append((fs, _delivered_dict(rng, n, ci, "tagged"),
                        jnp.int32(1_000_000)))
        return pts

    def out_names():
        from .dataflow import leaf_paths

        from ..tpu import flows as flows_mod

        return leaf_paths(flows_mod.make_flow_state(n), prefix="fs") \
            + ["credits"]

    return GateObligation(
        "flow_recv[idle]", "shadow_tpu.tpu.flows", build,
        gate_value=False, lattice=lattice, out_names=out_names,
        min_gated=10)


def _flow_emit_gate():
    """flow_emit's idle gate: an append with zero valid emission lanes
    is the bitwise identity on the egress rings — including full rows,
    where the overflow counter must not move."""
    from ..tpu import flows as flows_mod

    ft, n = _flow_tables()

    def build():
        rng = np.random.default_rng(4)
        fs = _flow_state_points(rng, n, 1, emittable=False)[0]
        state = _mini_state(rng)

        def fn(fs, state):
            return flows_mod.flow_emit(ft, fs, state)

        return fn, (fs, state)

    def lattice():
        import jax.numpy as jnp

        rng = np.random.default_rng(19)
        pts = []
        for occ in ([0, 0, 0, 0], [8, 8, 8, 8], [1, 3, 0, 8]):
            for fs in _flow_state_points(rng, n, 4, emittable=False):
                pts.append((fs, _mini_state(rng, occupancies=occ)))
                # an armed RTO that fires rewinds snd_nxt and re-emits
                # (reference branch); the disarmed twin is guaranteed
                # idle, so the gated domain keeps its coverage floor
                pts.append((fs._replace(
                    rto_armed=jnp.zeros((n,), bool)),
                    _mini_state(rng, occupancies=occ)))
        for fs in _flow_state_points(rng, n, 2, emittable=True):
            pts.append((fs, _mini_state(rng, occupancies=[2, 2, 2, 2])))
        return pts

    def out_names():
        return _state_leaf_names()

    return GateObligation(
        "flow_emit[idle]", "shadow_tpu.tpu.flows", build,
        gate_value=False, lattice=lattice, out_names=out_names,
        # a lattice point whose RTO fires rewinds snd_nxt and emits
        # (reference branch); the remainder stay idle — require a
        # healthy gated majority without pinning the exact split
        min_gated=6)


def gate_obligations() -> list[GateObligation]:
    """The SL505 proof surface: every gated lax.cond the device plane
    relies on (docs/determinism.md 'Branch gates are theorems')."""
    return [
        _ingest_rows_gate(),
        _compact_ingress_gate(),
        _egress_order_gate(),
        _flow_recv_gate(),
        _flow_emit_gate(),
    ]
