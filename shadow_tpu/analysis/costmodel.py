"""shadowcost: compiled-HLO cost fences for the window plane (SL6xx).

Where shadowprove (SL501-SL506) proves the device plane *correct* at
build time, this pass proves it *cheap* at build time: every perf gate
before it was a runtime measurement that only holds on a matched
container (the PR-7/PR-11 cross-container false-regression saga). The
compiled artifact is the container-independent substrate — same jax/XLA
version, same platform key, same HLO — so its costs can be checked in
and diffed like any other ledger. Four legs over the registered cost
entries (``default_cost_entries``), all sharing the per-process
lower+compile memo (``jaxpr_audit.compiled``, keyed (trace_key,
platform)) on top of the PR-14 jaxpr trace cache:

- **SL601 compiled-cost budgets** — ``jit(...).lower().compile()``
  each entry, pull XLA ``cost_analysis()`` (flops, bytes accessed,
  transcendentals), and diff against the checked-in, platform-keyed
  ``analysis/cost_budgets.json`` under per-metric tolerance bands. A
  CI perf fence that needs no warm benchmark and never lies across
  containers: budgets for a platform only gate ON that platform.
  Regen is explicit (``tools/shadowlint.py --write-cost-budgets``), so
  every cost delta is visible in the diff.

- **SL601 watermark extrapolation** — compile ``window_step`` and
  ``chain_windows`` at TWO host-axis shapes and compare XLA
  ``memory_analysis()`` peak temp bytes: an entry whose temp watermark
  grows super-linearly in N fails the build. This is the regression
  fence for the ROADMAP-2 million-host ``shard_map`` work — a hidden
  [N, N] (or worse) temp at N=4 is a terabyte at N=1M.

- **SL602 fusion-boundary census** — parse the optimized HLO and
  census every producer->consumer pair that MATERIALIZES an
  [N, CE]-or-larger intermediate between fusions (post-fusion, every
  non-fused value is a real buffer: a write + a read the fusion work
  would elide). The per-entry count is budgeted next to the SL601
  metrics; the full ranked worklist — shape, bytes, both ends, the
  source ``op_name`` — is the artifact ROADMAP-4's rank->place->egress
  fusion work consumes (``--cost-report``).

- **SL603 host-sync fence** — the SL405 telemetry-read rule
  generalized tree-wide: in the driver-loop modules (``bench.py``,
  ``tools/chaos_smoke.py``, ``workloads/runner.py``, ``tpu/elastic.py``)
  any ``jax.device_get`` / ``.item()`` / ``float()`` / ``np.asarray``
  / ``block_until_ready`` on a device value INSIDE a ``for``/``while``
  body is a per-iteration blocking sync — the exact pipeline stall the
  chained driver exists to amortize — and fails the build. Chain-end /
  teardown reads outside loops are the sanctioned drain pattern
  (harvester ticks and flight-recorder drains run from ``on_chain``
  callbacks, which are not lexically inside loops); values already
  pulled through one ``jax.device_get`` are host-side and exempt.
  Justified exceptions live in the ``HOST_SYNC_ALLOWED`` registry
  (or a standard suppression comment).

Docs: docs/performance.md "Static cost fences";
docs/determinism.md rules table (SL601/SL602/SL603).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Callable

from .rules import Finding, parse_suppressions

__all__ = [
    "CostEntry",
    "DRIVER_MODULES",
    "HOST_SYNC_ALLOWED",
    "build_cost_report",
    "check_cost_budgets",
    "check_host_sync",
    "check_host_sync_source",
    "check_watermarks",
    "cost_budget_path",
    "default_cost_entries",
    "entry_costs",
    "format_cost_delta",
    "fusion_boundaries",
    "proof_gate_budget_s",
    "run_cost_pass",
    "write_cost_budgets",
]


# --------------------------------------------------------------------------
# the cost-entry registry
# --------------------------------------------------------------------------


@dataclass
class CostEntry:
    """One budgeted compiled entry.

    ``key`` doubles as the shared trace/compile cache key AND the
    budget-ledger key; ``build`` is the zero-arg (fn, args) thunk
    (reused from the jaxpr-audit registry wherever possible — one
    builder per cache key, the PR-14 collision rule). ``n``/``ce``
    give the traced host-axis size and egress ring width, so the
    SL602 "[N, CE]-or-larger" materialization threshold scales with
    the entry's own shape. ``scale`` names the watermark twin: a
    second build at ``scale_n`` hosts whose peak temp bytes must stay
    within a linear extrapolation of the base shape's.
    """

    key: str
    n: int
    ce: int
    build: Callable[[], tuple]
    scale_n: int | None = None
    scale_build: Callable[[], tuple] | None = None

    @property
    def scale_key(self) -> str | None:
        return f"{self.key}@n{self.scale_n}" if self.scale_n else None


def default_cost_entries() -> list[CostEntry]:
    """The budgeted surface: the window-step compile modes the drivers
    actually dispatch (hot path, lean, flows, the fused pallas
    pipeline), the ingest kernel, the device-resident chain, and the
    standalone flow kernel — every builder REUSED from the jaxpr-audit
    registry so the cost ledger and the op ledger can never diverge on
    what an entry is. Two deliberate exclusions, both priced by the
    seven-family proof-gate time budget (one CI step, one shared
    cache): the two-dispatch ``window_step[pallas]`` variant
    (``pallas_fused`` subsumes its kernels on the gating path) and
    ``window_step[flows]`` (its compiled cost is structurally
    ``window_step[lean]`` + the standalone ``flow_step`` kernel, both
    budgeted here; the flow sections' fusion structure is censused on
    ``flow_step`` where it is not diluted by the window body).
    window_step and chain_windows carry the two-shape watermark pairs
    the ROADMAP-2 shard_map fence extrapolates from."""
    from .jaxpr_audit import (_chain_entry, _compute_entry,
                              _flows_entry, _ingest_rows_entry,
                              _plane_entry, ensemble_step_build)

    mod = "shadow_tpu.tpu.plane"
    return [
        CostEntry(f"{mod}:window_step[rr,aqm,loss]", 4, 8,
                  _plane_entry(True, True, False)),
        CostEntry(f"{mod}:window_step[lean]", 4, 8,
                  _plane_entry(False, False, True),
                  scale_n=8,
                  scale_build=_plane_entry(False, False, True, n=8)),
        CostEntry(f"{mod}:window_step[pallas_fused]", 4, 8,
                  _plane_entry(False, False, True,
                               kernel="pallas_fused")),
        CostEntry(f"{mod}:ingest_rows[planes]", 4, 8,
                  _ingest_rows_entry()),
        CostEntry(f"{mod}:chain_windows", 4, 8,
                  _chain_entry(),
                  scale_n=8, scale_build=_chain_entry(n=8)),
        CostEntry("shadow_tpu.tpu.flows:flow_step", 4, 8,
                  _flows_entry("step")),
        # the compute plane (ISSUE-20): the compute-threaded window
        # step IS a dispatched driver mode (family `serve`), so it is
        # budgeted whole — unlike window_step[flows] it adds only the
        # O(N*CI) FIFO section, and a regression there would hide
        # inside the lean budget's slack if priced by decomposition
        CostEntry(f"{mod}:window_step[compute]", 4, 8,
                  _compute_entry("window")),
        # the SL601 ensemble fence (ISSUE-16): the vmapped ensemble
        # step at two WORLD counts — `n` here is the scaled dimension
        # (worlds, not hosts), so the W=2 -> W=4 watermark pair fences
        # super-linear ensemble memory exactly like the host-axis
        # n=4 -> n=8 pairs above. The key matches batchdim's @vmapW2
        # trace-cache variant, so the proof pass and the cost pass
        # share one trace of the batched step.
        CostEntry("shadow_tpu.tpu.elastic:ensemble_step[lean]@vmapW2",
                  2, 8, ensemble_step_build(2),
                  scale_n=4, scale_build=ensemble_step_build(4)),
    ]


def _compiled(key: str, build):
    from .jaxpr_audit import compiled

    return compiled(key, build)


def _platform() -> str:
    import jax

    return jax.devices()[0].platform


# --------------------------------------------------------------------------
# optimized-HLO parsing (the SL602 substrate)
# --------------------------------------------------------------------------

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

#: one array-shape atom: ``f32[64,64]{1,0}`` / ``s32[]`` / ``pred[4,8]``
_SHAPE_RE = re.compile(r"([a-z]+[0-9]*)\[([0-9,]*)\](?:\{[^}]*\})?")

#: ``  [ROOT ]%name = <shape(s)> opcode(...`` — shapes may be a
#: parenthesized tuple, so the opcode is matched as the last word
#: before the first call paren
_INSTR_RE = re.compile(
    r"^\s+(ROOT\s+)?%([\w.\-]+)\s+=\s+(.*?)\s+([\w\-]+)\(")

_COMP_HEAD_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s+\(")


def _match_comp_header(line: str):
    """(is_entry, name) when `line` opens a computation, else None.
    Parameter lists NEST parens (while/cond region params are tuples:
    ``%region_1.655 (arg_tuple.656: (u32[4,8], ...)) -> ... {``), so
    the list is balanced procedurally before requiring the ``->``
    return arrow — a plain regex here silently dropped every loop
    body from the census."""
    if not line.rstrip().endswith("{"):
        return None
    m = _COMP_HEAD_RE.match(line)
    if m is None:
        return None
    i, depth = m.end() - 1, 0
    while i < len(line):
        if line[i] == "(":
            depth += 1
        elif line[i] == ")":
            depth -= 1
            if depth == 0:
                break
        i += 1
    if "->" not in line[i:]:
        return None
    return bool(m.group(1)), m.group(2)


@dataclass
class _Instr:
    name: str
    opcode: str
    #: (dtype, element_count, shape_text) per array in the result
    results: list[tuple[str, int, str]]
    operands: list[str]
    op_name: str
    is_root: bool


def _parse_shapes(text: str) -> list[tuple[str, int, str]]:
    out = []
    for m in _SHAPE_RE.finditer(text):
        dtype, dims = m.group(1), m.group(2)
        if dtype not in _DTYPE_BYTES:
            continue  # token/opaque types carry no buffer of interest
        count = 1
        for d in dims.split(","):
            if d:
                count *= int(d)
        out.append((dtype, count, f"{dtype}[{dims}]"))
    return out


def _parse_instr(line: str) -> _Instr | None:
    m = _INSTR_RE.match(line)
    if m is None:
        return None
    is_root, name, shapes_text, opcode = (
        bool(m.group(1)), m.group(2), m.group(3), m.group(4))
    # operands live between the opcode's '(' and its matching ')'
    start = m.end()
    depth, i = 1, start
    while i < len(line) and depth:
        if line[i] == "(":
            depth += 1
        elif line[i] == ")":
            depth -= 1
        i += 1
    operands = re.findall(r"%([\w.\-]+)", line[start:i - 1])
    op_name = ""
    nm = re.search(r'op_name="([^"]*)"', line)
    if nm:
        op_name = nm.group(1)
    return _Instr(name, opcode, _parse_shapes(shapes_text), operands,
                  op_name, is_root)


def _parse_hlo(text: str) -> dict[str, tuple[bool, list[_Instr]]]:
    """computation name -> (is_entry, instructions), across the whole
    optimized module."""
    comps: dict[str, tuple[bool, list[_Instr]]] = {}
    current: list[_Instr] | None = None
    for line in text.splitlines():
        if current is None:
            head = _match_comp_header(line)
            if head is not None:
                current = []
                comps[head[1]] = (head[0], current)
            continue
        if line.startswith("}"):
            current = None
            continue
        instr = _parse_instr(line)
        if instr is not None:
            current.append(instr)
    return comps


def count_fusions(text: str) -> int:
    """Fusion instructions across every computation of the module."""
    return _count_fusions(_parse_hlo(text))


def _count_fusions(comps: dict) -> int:
    return sum(1 for _name, (_e, instrs) in comps.items()
               for ins in instrs if ins.opcode == "fusion")


#: opcodes whose results are not *materialized intermediates* the
#: fusion work could elide: inputs, pure aliasing/bookkeeping, and the
#: control-flow wrappers (their bodies are censused separately — a
#: while's carry is the loop contract, not a fusion boundary)
_NOT_A_BOUNDARY = frozenset({
    "parameter", "constant", "iota", "get-tuple-element", "tuple",
    "bitcast", "copy", "after-all", "while", "conditional", "call",
})


#: consumers that merely repackage a value (no read of the bytes):
#: looked THROUGH when resolving who actually consumes a buffer — a
#: value whose resolved consumer set is empty only feeds the
#: computation's outputs, which no fusion can elide
_TRANSPARENT_CONSUMERS = frozenset({
    "tuple", "get-tuple-element", "bitcast", "copy",
})


def fusion_boundaries(text: str, min_elems: int) -> list[dict]:
    """Every producer->consumer pair in the optimized module that
    materializes an array of >= `min_elems` elements between fusions,
    ranked largest-first. Fused-computation bodies are skipped
    (nothing inside a fusion materializes); every other computation —
    entry, while/cond bodies — is censused, since chain_windows' hot
    path lives in its while body. Consumers are resolved through
    tuple/GTE repackaging, and a value that only reaches the ROOT
    (an output, not an intermediate) is not a boundary."""
    return _boundaries_from(_parse_hlo(text), min_elems)


def _boundaries_from(comps: dict, min_elems: int) -> list[dict]:
    out = []
    for comp_name, (is_entry, instrs) in comps.items():
        if "fused_computation" in comp_name:
            continue
        direct: dict[str, list[_Instr]] = {}
        for ins in instrs:
            for op in ins.operands:
                direct.setdefault(op, []).append(ins)

        def real_consumers(name: str, seen: set[str]) -> set[str]:
            found: set[str] = set()
            for ins in direct.get(name, ()):
                if ins.opcode in _TRANSPARENT_CONSUMERS:
                    # root repackaging = the value exits the
                    # computation; a non-root repack forwards to its
                    # own consumers
                    if not ins.is_root and ins.name not in seen:
                        seen.add(ins.name)
                        found |= real_consumers(ins.name, seen)
                else:
                    # a computing root still READS the buffer
                    found.add(f"{ins.opcode}:{ins.name}")
            return found

        for ins in instrs:
            if ins.is_root or ins.opcode in _NOT_A_BOUNDARY:
                continue
            big = [(d, c, s) for d, c, s in ins.results
                   if c >= min_elems]
            if not big:
                continue
            used_by = real_consumers(ins.name, set())
            if not used_by:
                continue
            nbytes = sum(_DTYPE_BYTES[d] * c for d, c, _s in big)
            out.append({
                "computation": "entry" if is_entry else comp_name,
                "producer": f"{ins.opcode}:{ins.name}",
                "consumers": sorted(used_by),
                "shapes": [s for _d, _c, s in big],
                "bytes": nbytes,
                "op_name": ins.op_name,
            })
    out.sort(key=lambda b: (-b["bytes"], b["computation"],
                            b["producer"]))
    return out


# --------------------------------------------------------------------------
# per-entry compiled costs
# --------------------------------------------------------------------------

#: per-process memo of the parsed costs, keyed (entry key, platform)
_COSTS_CACHE: dict[tuple[str, str], dict] = {}


def entry_costs(entry: CostEntry) -> dict:
    """The budgetable metrics + the boundary worklist for one entry,
    off the shared compile memo: XLA cost_analysis scalars, the module
    fusion count, the >=[N, CE] boundary census, and the peak temp
    bytes (memory_analysis)."""
    cache_key = (entry.key, _platform())
    hit = _COSTS_CACHE.get(cache_key)
    if hit is not None:
        return hit
    comp = _compiled(entry.key, entry.build)
    ca = comp.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    comps = _parse_hlo(comp.as_text())  # ONE parse feeds both censuses
    boundaries = _boundaries_from(comps, entry.n * entry.ce)
    mem = comp.memory_analysis()
    hit = {
        "metrics": {
            "flops": int(ca.get("flops", 0)),
            "bytes_accessed": int(ca.get("bytes accessed", 0)),
            "transcendentals": int(ca.get("transcendentals", 0)),
            "fusions": _count_fusions(comps),
            "big_boundaries": len(boundaries),
        },
        "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0) or 0),
        "boundaries": boundaries,
        "threshold_elems": entry.n * entry.ce,
    }
    _COSTS_CACHE[cache_key] = hit
    return hit


# --------------------------------------------------------------------------
# SL601/SL602: the platform-keyed cost ledger
# --------------------------------------------------------------------------

_COST_BUDGET_FILE = "cost_budgets.json"

#: which rule owns each budgeted metric: arithmetic/traffic costs are
#: SL601, fusion-structure counts are SL602
_METRIC_RULE = {
    "flops": "SL601",
    "bytes_accessed": "SL601",
    "transcendentals": "SL601",
    "fusions": "SL602",
    "big_boundaries": "SL602",
}

#: default tolerance bands, mirrored into the checked-in ledger so
#: they are reviewable next to the numbers they guard. A metric passes
#: when it is within the relative band OR the absolute one (small
#: counts need the abs floor; big counts need the rel band).
_DEFAULT_TOLERANCE = {
    "flops": {"rel": 0.25, "abs": 64},
    "bytes_accessed": {"rel": 0.25, "abs": 4096},
    "transcendentals": {"rel": 0.25, "abs": 8},
    "fusions": {"abs": 2},
    "big_boundaries": {"abs": 0},
}


def cost_budget_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        _COST_BUDGET_FILE)


def _canonical_dump(doc: dict, path: str) -> None:
    """ONE spelling for ledger bytes (op + cost budgets): sorted keys,
    indent 2, trailing newline — so a double regen is byte-identical
    and a regen diff is minimal (pinned by tests/test_costmodel.py)."""
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")


def write_cost_budgets(path: str | None = None, entries=None) -> dict:
    """Regenerate THIS platform's section of the cost ledger,
    preserving every other platform's budgets (an accelerator
    container's numbers survive a CPU-container regen and vice versa).
    With an explicit `entries` subset only those keys update."""
    path = path or cost_budget_path()
    doc = {
        "_comment": (
            "SL601/SL602 compiled-cost ledger: XLA cost_analysis "
            "scalars + fusion/boundary census per registered cost "
            "entry (analysis/costmodel.default_cost_entries), keyed "
            "by platform — budgets only gate on the platform they "
            "were measured on, so this fence never lies across "
            "containers. CI diffs the live compile against this file "
            "under the tolerance bands below; regenerate via `python "
            "tools/shadowlint.py --write-cost-budgets` and justify "
            "the delta in the PR."),
        "version": 1,
        "tolerance": _DEFAULT_TOLERANCE,
        "platforms": {},
    }
    if os.path.exists(path):
        with open(path, encoding="utf-8") as fh:
            prior = json.load(fh)
        doc["platforms"] = prior.get("platforms", {})
        doc["tolerance"] = prior.get("tolerance", _DEFAULT_TOLERANCE)
    all_entries = entries if entries is not None \
        else default_cost_entries()
    platform = _platform()
    section = {} if entries is None \
        else dict(doc["platforms"].get(platform, {}))
    for entry in all_entries:
        section[entry.key] = dict(
            sorted(entry_costs(entry)["metrics"].items()))
    doc["platforms"][platform] = section
    _canonical_dump(doc, path)
    return doc


def _within(want: int, have: int, tol: dict) -> bool:
    if have == want:  # exact match passes under ANY band shape
        return True
    delta = abs(have - want)
    if "rel" in tol and want and delta <= tol["rel"] * abs(want):
        return True
    return "abs" in tol and delta <= tol["abs"]


def check_cost_budgets(path: str | None = None, entries=None
                       ) -> tuple[list[Finding], list[dict]]:
    """Diff the live compiled costs against the checked-in ledger for
    THIS platform. Returns (findings, deltas); deltas carry the
    budget-vs-actual table the CLI renders on failure."""
    path = path or cost_budget_path()
    entries = entries if entries is not None else default_cost_entries()

    def infra(where: str, message: str) -> list[Finding]:
        # ledger-infrastructure failures (missing file / platform /
        # entry) break BOTH budget families: emit one finding per
        # rule, so a `--only SL602` run can never go green on a
        # ledger it could not check (main() filters by selected rule)
        return [Finding(rule, where, 0, 0, message)
                for rule in ("SL601", "SL602")]

    if not os.path.exists(path):
        return infra(
            path,
            "cost ledger missing: run `python tools/shadowlint.py "
            "--write-cost-budgets` and check the file in"), []
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    platform = _platform()
    budgets = doc.get("platforms", {}).get(platform)
    if budgets is None:
        return infra(
            path,
            f"no cost budgets for platform `{platform}`: regenerate "
            "the ledger on this container (--write-cost-budgets) so "
            "the fence gates here too"), []
    tolerance = doc.get("tolerance", _DEFAULT_TOLERANCE)

    findings: list[Finding] = []
    deltas: list[dict] = []
    live = {e.key: e for e in entries}
    for key in sorted(set(budgets) | set(live)):
        want = budgets.get(key)
        entry = live.get(key)
        if want is None:
            findings.extend(infra(
                key,
                "cost entry has no budget on this platform: "
                "regenerate the ledger (--write-cost-budgets) so the "
                "new entry's compiled cost is pinned"))
            continue
        if entry is None:
            findings.extend(infra(
                key,
                "budgeted cost entry no longer registered: regenerate "
                "the ledger (--write-cost-budgets) to drop it "
                "explicitly"))
            continue
        costs = entry_costs(entry)
        have = costs["metrics"]
        diff = {}
        for metric in sorted(set(want) | set(have)):
            w, h = int(want.get(metric, 0)), int(have.get(metric, 0))
            tol = tolerance.get(metric, {})
            if not _within(w, h, tol):
                diff[metric] = {"budget": w, "actual": h}
        if not diff:
            continue
        deltas.append({"entry": key, "platform": platform,
                       "delta": diff})
        for rule in ("SL601", "SL602"):
            ruled = [m for m in diff if _METRIC_RULE.get(m, "SL601")
                     == rule]
            if not ruled:
                continue
            worst = max(ruled, key=lambda m: abs(diff[m]["actual"]
                                                 - diff[m]["budget"]))
            extra = ""
            if rule == "SL602" and costs["boundaries"]:
                top = costs["boundaries"][0]
                extra = (f"; largest boundary `{top['producer']} -> "
                         f"{', '.join(top['consumers'])}` materializes "
                         f"{'+'.join(top['shapes'])} "
                         f"({top['bytes']} B) at "
                         f"`{top['op_name'] or top['computation']}`")
            findings.append(Finding(
                rule, key, 0, 0,
                f"compiled {worst} deviates from the checked-in "
                f"budget ({diff[worst]['budget']} budgeted, "
                f"{diff[worst]['actual']} actual, platform "
                f"`{platform}`"
                + (f"; +{len(ruled) - 1} more metric(s)"
                   if len(ruled) > 1 else "")
                + ")" + extra
                + " — a compiled-cost regression, or a ledger update "
                "missing from this diff (--write-cost-budgets)"))
    return findings, deltas


def format_cost_delta(deltas: list[dict]) -> str:
    """Readable budget-vs-actual table for the CI log (same shape as
    the SL502 table)."""
    lines = ["entry                                    metric"
             "               budget  actual   delta"]
    for d in deltas:
        for metric, v in sorted(d["delta"].items()):
            lines.append(
                f"{d['entry'][:40]:<40} {metric:<18} "
                f"{v['budget']:>8}  {v['actual']:>6}  "
                f"{v['actual'] - v['budget']:>+6}")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# SL601: two-shape watermark extrapolation
# --------------------------------------------------------------------------

#: a temp watermark may grow up to `slack` times faster than the
#: host-axis shape before it reads as super-linear; the absolute floor
#: absorbs shape-independent scratch (compiler bookkeeping, small
#: per-column pads) that would otherwise dominate tiny trace shapes
WATERMARK_SLACK = 1.5
WATERMARK_FLOOR_BYTES = 4096


def proof_gate_budget_s(n_cpus: int | None = None) -> int:
    """THE wall-time budget for the gating shadowlint proof step,
    scaled to the runner: CI wraps the gate in
    ``timeout $(python -c 'from shadow_tpu.analysis.costmodel import
    proof_gate_budget_s; print(proof_gate_budget_s())')``.

    The fixed 30s budget PR 15 inherited failed by ~1.3s on 1-CPU
    containers, so the pin is now a measured cost model instead of a
    constant: on a 1-CPU runner the seven SL5xx/SL6xx families cost
    ~31s and the SL7xx batch pass adds ~45-55s of vmap re-tracing
    (two world counts over the 28 non-refused entries; measured
    2026-08 on the CI container class). Tracing parallelizes poorly
    but XLA compilation and the interpreter walks do gain from extra
    cores, hence the 1/n term; the constant floor absorbs the
    serial trace path. Budget = 60 + 120/n seconds, i.e. 180s on the
    1-CPU runner (~2x the measured 86s total) and 90s at 4 cores —
    tight enough that a hung trace or an accidental second compile
    sweep still fails fast, loose enough that scheduler jitter on
    small runners cannot flake the gate."""
    if n_cpus is None:
        n_cpus = os.cpu_count() or 1
    return 60 + 120 // max(1, n_cpus)


def check_watermarks(entries=None) -> tuple[list[Finding], list[dict]]:
    """Compile each watermark pair at both shapes and fail any entry
    whose peak temp bytes grow faster than linearly in N (with slack):
    ``temp(n2) <= temp(n1) * (n2/n1) * slack + floor``. The ROADMAP-2
    shard_map fence: at a million hosts, a super-linear temp is the
    difference between a shard that fits and one that cannot exist."""
    findings: list[Finding] = []
    rows: list[dict] = []
    for entry in (entries if entries is not None
                  else default_cost_entries()):
        if entry.scale_build is None:
            continue
        temp1 = entry_costs(entry)["temp_bytes"]
        comp2 = _compiled(entry.scale_key, entry.scale_build)
        mem2 = comp2.memory_analysis()
        temp2 = int(getattr(mem2, "temp_size_in_bytes", 0) or 0)
        factor = entry.scale_n / entry.n
        bound = int(temp1 * factor * WATERMARK_SLACK
                    + WATERMARK_FLOOR_BYTES)
        ok = temp2 <= bound
        rows.append({
            "entry": entry.key, "n1": entry.n, "n2": entry.scale_n,
            "temp1_bytes": temp1, "temp2_bytes": temp2,
            "linear_bound_bytes": bound, "ok": ok,
        })
        if not ok:
            growth = temp2 / max(temp1, 1)
            findings.append(Finding(
                "SL601", entry.key, 0, 0,
                f"peak temp watermark grows super-linearly in N: "
                f"{temp1} B at N={entry.n} -> {temp2} B at "
                f"N={entry.scale_n} ({growth:.1f}x for a {factor:.0f}x "
                f"shape; linear bound {bound} B) — a hidden "
                "quadratic-in-hosts buffer, the exact thing the "
                "ROADMAP-2 million-host shard_map cut cannot absorb"))
    return findings, rows


# --------------------------------------------------------------------------
# SL603: the tree-wide host-sync fence
# --------------------------------------------------------------------------

#: the driver-loop modules the fence covers — the files that own a
#: window-driving loop (everything else either is the sanctioned
#: harvest boundary, shadow_tpu/telemetry/, or never holds device
#: values in a loop). The shadowscope tracer and its report CLI are
#: swept too: the run ledger is emitted AT the chain-boundary sync and
#: must stay incapable of smuggling a per-span device read in later
#: (docs/observability.md "Run ledger").
DRIVER_MODULES = (
    "bench.py",
    "tools/chaos_smoke.py",
    "tools/trace_report.py",
    "shadow_tpu/workloads/runner.py",
    "shadow_tpu/tpu/elastic.py",
    "shadow_tpu/telemetry/tracer.py",
)

#: (repo-relative path, enclosing function) -> justification. The
#: registry analogue of the jaxpr-audit allow-lists: every sanctioned
#: in-loop sync documents WHY it must block there.
HOST_SYNC_ALLOWED: dict[tuple[str, str], str] = {
    ("shadow_tpu/tpu/elastic.py", "run_elastic_window"): (
        "the elastic capacity policy's decision point: one per-ring "
        "overflow readback per CHAIN attempt is the driver contract "
        "(docs/robustness.md 'Elastic capacity') — chain_len amortizes "
        "the sync, and the growth decision cannot be made without "
        "materializing the overflow counters"),
}

#: call leaves that ARE a blocking device sync wherever they run
_SYNC_CALL_PATHS = {"jax.device_get", "jax.block_until_ready"}
_SYNC_ATTR_LEAVES = {"item", "block_until_ready"}
#: host-materialization callables that sync when fed a device value.
#: DELIBERATELY not ``int``/``bool``: in this tree those coerce host
#: values (regex groups, numpy post-processing scalars, python ints)
#: almost exclusively — adding them costs ~6 false positives per
#: driver module sweep for a spelling (bare ``int(device_scalar)``)
#: no in-tree code uses; every real device read routes through
#: device_get / np.asarray / .item() / float(), which ARE netted.
#: A lexical fence buys zero-noise gating at the price of that hole.
_MATERIALIZERS = {"float"}
_NP_MATERIALIZERS = {"numpy.asarray", "numpy.array", "numpy.atleast_1d"}


class _HostNames:
    """Flow-insensitive per-scope set of names known to hold HOST
    values (pulled through jax.device_get, or plain numpy
    constructions): float()/np.asarray()/.item() on those is host
    arithmetic, not a device sync."""

    def __init__(self):
        self._scopes: list[set[str]] = [set()]

    def push(self):
        self._scopes.append(set())

    def pop(self):
        self._scopes.pop()

    def mark(self, name: str):
        self._scopes[-1].add(name)

    def unmark(self, name: str):
        for s in self._scopes:
            s.discard(name)

    def is_host(self, name: str) -> bool:
        return any(name in s for s in self._scopes)


def _resolve(imports: dict[str, str], node: ast.expr) -> str | None:
    """Dotted path through the import table (the astlint discipline,
    inlined: the cost pass must not import jax to lint sources)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    root = imports.get(node.id)
    if root is None:
        if parts:
            return None
        root = node.id
    parts.append(root)
    return ".".join(reversed(parts))


def _contains_device_get(node: ast.AST, imports: dict[str, str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            if _resolve(imports, sub.func) == "jax.device_get":
                return True
            if isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "device_get":
                return True
    return False


def _operand_is_host(node: ast.expr, imports: dict[str, str],
                     hosts: _HostNames) -> bool:
    """True when the expression provably reads host memory: it is
    routed through jax.device_get itself, or every Name it touches is
    a known host value (and it touches at least one)."""
    if _contains_device_get(node, imports):
        return True
    names = [s for s in ast.walk(node) if isinstance(s, ast.Name)]
    return bool(names) and all(hosts.is_host(n.id) for n in names)


class _SyncFence(ast.NodeVisitor):
    def __init__(self, relpath: str):
        self.relpath = relpath
        self.imports: dict[str, str] = {}
        self.hosts = _HostNames()
        self.loop_depth = 0
        self.fn_stack: list[str] = []
        self.findings: list[Finding] = []

    # -- bookkeeping -----------------------------------------------------

    def visit_Import(self, node):
        for alias in node.names:
            self.imports[alias.asname or alias.name.split(".")[0]] = (
                alias.name if alias.asname
                else alias.name.split(".")[0])

    def visit_ImportFrom(self, node):
        if node.level or not node.module:
            return
        for alias in node.names:
            self.imports[alias.asname or alias.name] = (
                f"{node.module}.{alias.name}")

    def _visit_fn(self, node):
        self.fn_stack.append(node.name)
        self.hosts.push()
        # a function body is a fresh sync context: the loop that
        # matters is the one INSIDE the function, not a loop that
        # happens to define it (a def in a loop runs later, not
        # per-iteration)
        outer, self.loop_depth = self.loop_depth, 0
        self.generic_visit(node)
        self.loop_depth = outer
        self.hosts.pop()
        self.fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Assign(self, node):
        # host-producing values: a device_get anywhere in the value
        # (the pull itself), or a numpy materializer call. NOT
        # block_until_ready — it returns the DEVICE array, only
        # flushed (a later read still syncs)
        is_host = isinstance(node.value, ast.Call) and (
            _resolve(self.imports, node.value.func)
            in _NP_MATERIALIZERS
            or _contains_device_get(node.value, self.imports))
        for target in node.targets:
            if isinstance(target, ast.Name):
                (self.hosts.mark if is_host
                 else self.hosts.unmark)(target.id)
        self.generic_visit(node)

    def _mark_host_targets(self, target, iter_expr):
        """Loop/comprehension targets drawn from a host iterable (a
        device_get'd pull, or an expression over already-host names)
        are host values inside the body."""
        if not _operand_is_host(iter_expr, self.imports, self.hosts):
            return
        for sub in ast.walk(target):
            if isinstance(sub, ast.Name):
                self.hosts.mark(sub.id)

    def _visit_loop(self, node):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            # the iterable evaluates ONCE — only the body repeats
            self.visit(node.iter)
            self._mark_host_targets(node.target, node.iter)
            self.loop_depth += 1
            for stmt in list(node.body) + list(node.orelse):
                self.visit(stmt)
            self.loop_depth -= 1
        else:  # while: the test re-evaluates per iteration
            self.loop_depth += 1
            self.visit(node.test)
            for stmt in list(node.body) + list(node.orelse):
                self.visit(stmt)
            self.loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    def _visit_comp(self, node):
        # a comprehension IS a loop: its element expression and the
        # later generators re-evaluate per item (only the first
        # generator's iterable runs once) — without this, any flagged
        # `for` could be rewritten as a listcomp to dodge the fence
        gens = node.generators
        self.visit(gens[0].iter)
        self._mark_host_targets(gens[0].target, gens[0].iter)
        self.loop_depth += 1
        for i, gen in enumerate(gens):
            if i > 0:
                self.visit(gen.iter)
                self._mark_host_targets(gen.target, gen.iter)
            for cond in gen.ifs:
                self.visit(cond)
        if isinstance(node, ast.DictComp):
            self.visit(node.key)
            self.visit(node.value)
        else:
            self.visit(node.elt)
        self.loop_depth -= 1

    visit_ListComp = _visit_comp
    visit_SetComp = _visit_comp
    visit_DictComp = _visit_comp
    visit_GeneratorExp = _visit_comp

    # -- the fence -------------------------------------------------------

    def _emit(self, node, what: str):
        fn = self.fn_stack[-1] if self.fn_stack else "<module>"
        finding = Finding(
            "SL603", self.relpath, node.lineno, node.col_offset,
            f"per-iteration host sync `{what}` inside a driver loop "
            f"(in `{fn}`): every pass blocks the dispatch pipeline on "
            "a D2H readback — drain at chain ends (`on_chain`) or "
            "through the asynchronous harvester/flight-recorder "
            "instead (docs/performance.md 'Static cost fences')")
        allow = HOST_SYNC_ALLOWED.get((self.relpath, fn))
        if allow:
            finding.suppressed = True
            finding.justification = allow
        self.findings.append(finding)

    def visit_Call(self, node):
        if self.loop_depth:
            resolved = _resolve(self.imports, node.func)
            if resolved in _SYNC_CALL_PATHS:
                self._emit(node, resolved)
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _SYNC_ATTR_LEAVES \
                    and not _operand_is_host(node.func.value,
                                             self.imports, self.hosts):
                self._emit(node, f"...{node.func.attr}()")
            elif resolved in (_MATERIALIZERS | _NP_MATERIALIZERS) \
                    and node.args \
                    and not _operand_is_host(node.args[0],
                                             self.imports, self.hosts):
                self._emit(node, f"{resolved}(...)")
        self.generic_visit(node)


def check_host_sync_source(source: str, relpath: str) -> list[Finding]:
    """SL603 over one file's text; standard suppression comments and
    the HOST_SYNC_ALLOWED registry both mark findings suppressed."""
    tree = ast.parse(source, filename=relpath)
    fence = _SyncFence(relpath)
    fence.visit(tree)
    sup = parse_suppressions(source)
    for f in fence.findings:
        just = sup.lookup(f.rule, f.line)
        if just is not None:
            f.suppressed = True
            f.justification = just
    return sorted(fence.findings, key=lambda f: (f.path, f.line, f.col))


def check_host_sync(repo_root: str | None = None) -> list[Finding]:
    """The tree-wide fence: every DRIVER_MODULES file, findings
    suppressed only by the registry or a justified comment."""
    root = repo_root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    findings: list[Finding] = []
    for rel in DRIVER_MODULES:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            findings.append(Finding(
                "SL603", rel, 0, 0,
                "driver module missing: the host-sync fence cannot "
                "check it (update costmodel.DRIVER_MODULES)"))
            continue
        with open(path, encoding="utf-8") as fh:
            findings.extend(check_host_sync_source(fh.read(), rel))
    return findings


# --------------------------------------------------------------------------
# the pass driver + report artifact
# --------------------------------------------------------------------------

#: how many boundary rows ride in each PER-ENTRY report section (a
#: readable head next to the metrics); the cross-entry
#: ``fusion_worklist`` is COMPLETE — a consumer working it top-down
#: must never believe a truncated list was everything (the no-silent-
#: caps rule), so each section also carries its ``boundaries_total``
_WORKLIST_PER_ENTRY = 12


def build_cost_report(entries=None, *, budget_findings=None,
                      deltas=None, watermarks=None,
                      sync_findings=None) -> dict:
    """The ``--cost-report`` artifact: per-entry compiled costs, the
    ranked fusion-boundary worklist (the ROADMAP-4 handoff), the
    watermark extrapolation rows, and the host-sync scan. Pre-computed
    pieces are passed in by run_cost_pass so a gating run builds the
    artifact for free; a report-only run computes them here."""
    entries = entries if entries is not None else default_cost_entries()
    if watermarks is None:
        _wf, watermarks = check_watermarks(entries)
    if sync_findings is None:
        sync_findings = check_host_sync()
    if deltas is None and budget_findings is None:
        budget_findings, deltas = check_cost_budgets(entries=entries)

    sections = []
    worklist = []
    for entry in entries:
        costs = entry_costs(entry)
        sections.append({
            "entry": entry.key,
            "traced_shape": {"n": entry.n, "ce": entry.ce},
            "metrics": costs["metrics"],
            "temp_bytes": costs["temp_bytes"],
            "boundary_threshold_elems": costs["threshold_elems"],
            "boundaries_total": len(costs["boundaries"]),
            "boundaries": costs["boundaries"][:_WORKLIST_PER_ENTRY],
        })
        for b in costs["boundaries"]:  # the FULL ranked worklist
            worklist.append(dict(b, entry=entry.key))
    worklist.sort(key=lambda b: (-b["bytes"], b["entry"],
                                 b["producer"]))
    return {
        "version": 1,
        "rules": ["SL601", "SL602", "SL603"],
        "platform": _platform(),
        "entries": sections,
        "fusion_worklist": worklist,
        "watermarks": watermarks,
        "budget_deltas": deltas or [],
        "host_sync": {
            "modules": list(DRIVER_MODULES),
            "active": [f.to_json() for f in sync_findings
                       if not f.suppressed],
            "allowed": [f.to_json() for f in sync_findings
                        if f.suppressed],
        },
        "summary": {
            "entries": len(sections),
            "budget_deltas": len(deltas or []),
            "worklist": len(worklist),
            "watermark_failures": sum(1 for w in watermarks
                                      if not w["ok"]),
            "host_sync_active": sum(1 for f in sync_findings
                                    if not f.suppressed),
        },
    }


def run_cost_pass(selected=frozenset({"SL601", "SL602", "SL603"}),
                  entries=None
                  ) -> tuple[list[Finding], list[dict], dict | None]:
    """SL6xx gate: returns (findings, budget deltas, report). The
    report is built whenever any compiled family ran (so the CI step's
    ``--cost-report`` artifact is free); a pure-SL603 selection skips
    every compile and returns report=None."""
    findings: list[Finding] = []
    deltas: list[dict] = []
    report = None
    compiled_rules = {"SL601", "SL602"} & set(selected)
    watermarks = sync_findings = None
    budget_findings = None
    if compiled_rules:
        entries = entries if entries is not None \
            else default_cost_entries()
        budget_findings, deltas = check_cost_budgets(entries=entries)
        findings.extend(budget_findings)
        wm_findings, watermarks = check_watermarks(entries)
        findings.extend(wm_findings)
    if "SL603" in selected:
        sync_findings = check_host_sync()
        findings.extend(sync_findings)
    if compiled_rules:
        # sync_findings=None (SL603 deselected) lets the report run its
        # own cheap AST scan — the artifact's host_sync section must
        # reflect the tree, not the selection
        report = build_cost_report(
            entries, budget_findings=budget_findings, deltas=deltas,
            watermarks=watermarks, sync_findings=sync_findings)
    return findings, deltas, report
