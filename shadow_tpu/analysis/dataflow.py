"""The jaxpr dataflow engine behind the SL5xx proof rules.

Three analyses over the same traced graphs the SL2xx audit already
walks, all *static* — they prove properties for every input in seconds,
where the runtime parity matrices sample a handful of corners in
minutes:

- ``propagate_taint`` — forward taint propagation over a closed jaxpr:
  mark a subset of the input leaves tainted and compute which output
  leaves any tainted value can reach. Descends into every control-flow
  sub-jaxpr (``pjit``/``closed_call``/``custom_*`` inline 1:1;
  ``scan``/``while`` run a carry fixpoint; ``cond`` joins over
  branches) and models IMPLICIT flows: a tainted ``while``/``cond``
  predicate taints every output of the construct, because a
  data-dependent trip count or branch choice changes results even when
  no tainted value is copied directly. Unknown primitives carrying a
  sub-jaxpr are handled conservatively (any tainted input taints all
  outputs), so the analysis can over-approximate but never miss a
  flow — a "clean" verdict is a theorem (SL501).

- ``op_census`` — a static count of the expensive primitives
  (sorts, gathers, scatter variants, control flow, pallas calls, host
  transfers) across a jaxpr and every sub-jaxpr. Diffed against the
  checked-in ``op_budgets.json`` by SL502: a reintroduced variadic
  sort or per-column scatter changes the census and fails CI without
  running a bench.

- ``shard_census`` — classifies each expensive primitive as
  host-axis-local (operates within a row of the ``[N, ...]`` SoA
  layout) or cross-host (indexes, scatters, sorts, or reduces ACROSS
  axis 0). The per-section report (SL504) is the work-list for the
  ROADMAP-2 ``shard_map`` cut: cross-host ops need a collective or a
  ragged exchange; host-local ops shard for free.

The taint labels are human-readable provenance strings (the input leaf
that sourced the taint), so an SL501 failure names both ends of the
illegal flow: ``metrics.pkts_out -> new_state.rng_counter``.
"""

from __future__ import annotations

from dataclasses import dataclass, field

try:
    from jax.extend import core as _core
except ImportError:  # older jax spells it jax.core
    from jax import core as _core

__all__ = [
    "OpClass",
    "leaf_paths",
    "op_census",
    "propagate_taint",
    "shard_census",
]


# --------------------------------------------------------------------------
# taint propagation
# --------------------------------------------------------------------------

#: primitives whose params carry sub-jaxprs that inline 1:1 with the
#: equation's invars (call-like: no reordering, no carry)
_CALL_LIKE = ("pjit", "closed_call", "core_call", "remat", "checkpoint",
              "custom_jvp_call", "custom_vjp_call",
              "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr")

_SUB_JAXPR_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr")


def _first_sub_jaxpr(params):
    for key in _SUB_JAXPR_KEYS:
        sub = params.get(key)
        if sub is not None:
            return sub
    return None


def _any_sub_jaxpr(params):
    """True when ANY param transitively holds a jaxpr (the conservative
    fallback trigger for primitives we don't model)."""
    def holds(value):
        if isinstance(value, (_core.Jaxpr, _core.ClosedJaxpr)):
            return True
        if isinstance(value, (tuple, list)):
            return any(holds(v) for v in value)
        return False

    return any(holds(v) for v in params.values())


def _join(*labels):
    """First non-None label wins (provenance is best-effort; taint
    presence is exact)."""
    for lab in labels:
        if lab is not None:
            return lab
    return None


def _eval_jaxpr(jaxpr_like, in_labels):
    """Forward pass over one (possibly closed) jaxpr; returns the
    output-leaf labels. Constvars are clean by definition (they are
    trace-time data, not plane inputs)."""
    raw = getattr(jaxpr_like, "jaxpr", jaxpr_like)
    env: dict = {}

    def read(v):
        if isinstance(v, _core.Literal):
            return None
        return env.get(v)

    def write(v, lab):
        if lab is not None:
            env[v] = lab

    if len(raw.invars) != len(in_labels):
        raise ValueError(
            f"jaxpr arity mismatch: {len(raw.invars)} invars, "
            f"{len(in_labels)} labels")
    for var, lab in zip(raw.invars, in_labels):
        write(var, lab)

    for eqn in raw.eqns:
        ins = [read(v) for v in eqn.invars]
        outs = _eval_eqn(eqn, ins)
        for var, lab in zip(eqn.outvars, outs):
            write(var, lab)

    return [read(v) for v in raw.outvars]


def _fixpoint(step, carry):
    """Iterate a monotone carry-label transformer to its fixpoint.
    Taint only ever turns on, so len(carry)+1 rounds always suffice."""
    for _ in range(len(carry) + 1):
        new = step(carry)
        merged = [_join(a, b) for a, b in zip(carry, new)]
        if merged == carry:
            return carry
        carry = merged
    return carry


def _passthrough_outputs(branches, n_ops) -> set[int]:
    """Output positions every branch returns verbatim from the SAME
    operand (outvar IS an invar, identical operand index across
    branches): those outputs are branch-invariant, so a tainted
    predicate cannot affect them."""
    common: dict[int, int] | None = None
    for branch in branches:
        raw = getattr(branch, "jaxpr", branch)
        pos = {id(v): j for j, v in enumerate(raw.invars)}
        this = {}
        for i, out in enumerate(raw.outvars):
            j = pos.get(id(out))
            if j is not None:
                this[i] = j
        if common is None:
            common = this
        else:
            common = {i: j for i, j in common.items()
                      if this.get(i) == j}
    return set(common or ())


def _is_carry_identity(body_raw, n_body_consts: int, i: int) -> bool:
    """True when while-body carry slot `i` is returned verbatim
    (outvars[i] IS invars[n_body_consts + i])."""
    if i >= len(body_raw.outvars):
        return False
    out = body_raw.outvars[i]
    j = n_body_consts + i
    return j < len(body_raw.invars) and body_raw.invars[j] is out


def _eval_eqn(eqn, ins):
    name = eqn.primitive.name
    params = eqn.params
    n_out = len(eqn.outvars)

    def conservative():
        lab = _join(*ins)
        return [lab] * n_out

    if name in _CALL_LIKE:
        sub = _first_sub_jaxpr(params)
        raw = getattr(sub, "jaxpr", sub) if sub is not None else None
        if raw is not None and len(raw.invars) == len(ins):
            outs = _eval_jaxpr(sub, ins)
            if len(outs) >= n_out:
                return outs[:n_out]
        return conservative()

    if name == "cond":
        pred, ops = ins[0], ins[1:]
        outs = [None] * n_out
        for branch in params["branches"]:
            raw = getattr(branch, "jaxpr", branch)
            if len(raw.invars) != len(ops):
                return conservative()
            b_outs = _eval_jaxpr(branch, ops)
            outs = [_join(a, b) for a, b in zip(outs, b_outs)]
        if pred is not None:
            # implicit flow: a tainted predicate selects WHICH branch
            # ran, so every output is tainted even if no branch copies
            # a tainted value — EXCEPT outputs every branch passes
            # through verbatim from the same operand (branch-invariant:
            # the identity-gated merges like ingest_rows' gate_idle
            # return untouched leaves as the same Var in both branches,
            # so the choice of branch cannot change them)
            invariant = _passthrough_outputs(params["branches"], len(ops))
            outs = [o if i in invariant else _join(o, pred)
                    for i, o in enumerate(outs)]
        return outs

    if name == "while":
        cn = params["cond_nconsts"]
        bn = params["body_nconsts"]
        cond_c, body_c = ins[:cn], ins[cn:cn + bn]
        carry0 = ins[cn + bn:]

        body_raw = getattr(params["body_jaxpr"], "jaxpr",
                           params["body_jaxpr"])

        def step(carry):
            pred = _eval_jaxpr(
                params["cond_jaxpr"], list(cond_c) + list(carry))[0]
            new = _eval_jaxpr(
                params["body_jaxpr"], list(body_c) + list(carry))
            if pred is not None:
                # implicit flow: a tainted trip count taints the whole
                # carry (different iteration counts -> different
                # values) — except slots the body passes through
                # verbatim (carry[i] -> carry[i]): their value is the
                # same after 0 or N iterations
                new = [c if _is_carry_identity(body_raw, bn, i)
                       else _join(c, pred)
                       for i, c in enumerate(new)]
            return new

        return _fixpoint(step, list(carry0))

    if name == "scan":
        nc = params["num_consts"]
        ncar = params["num_carry"]
        consts, carry0, xs = ins[:nc], ins[nc:nc + ncar], ins[nc + ncar:]
        body = params["jaxpr"]

        def step(carry):
            outs = _eval_jaxpr(body, list(consts) + list(carry) + list(xs))
            return outs[:ncar]

        carry = _fixpoint(step, list(carry0))
        ys = _eval_jaxpr(
            body, list(consts) + list(carry) + list(xs))[ncar:]
        return list(carry) + list(ys)

    if name == "pallas_call" or _any_sub_jaxpr(params):
        # opaque kernel / unmodeled higher-order primitive: assume every
        # output can see every input (sound over-approximation)
        return conservative()

    # plain primitive: pure dataflow, every output sees every input
    return conservative()


def propagate_taint(closed_jaxpr, in_labels):
    """Labels (provenance strings or None) for each output leaf of
    `closed_jaxpr`, given one label per input leaf (None = clean)."""
    return _eval_jaxpr(closed_jaxpr, list(in_labels))


# --------------------------------------------------------------------------
# leaf naming
# --------------------------------------------------------------------------


def leaf_paths(pytree, prefix: str = "") -> list[str]:
    """Dotted/keyed path of every leaf, in jax flatten order —
    ``state.eg_dst``, ``delivered['mask']``, ``[2]`` — via the
    registered keypath machinery, so NamedTuples render as attribute
    accesses and custom nodes fall back to their registered keys."""
    from jax import tree_util

    flat = tree_util.tree_flatten_with_path(pytree)[0]
    out = []
    for path, _leaf in flat:
        text = tree_util.keystr(path)
        out.append(prefix + text if prefix else text.lstrip("."))
    return out


# --------------------------------------------------------------------------
# op census (SL502) and shard classification (SL504)
# --------------------------------------------------------------------------

#: the primitives the op budget tracks: everything whose count moving is
#: a perf event worth an explicit diff (the sort diet, the scatter diet,
#: control-flow structure, kernel dispatches, host hops)
_CENSUS_EXACT = frozenset({
    "sort", "gather", "while", "cond", "scan", "pallas_call",
    "device_put", "infeed", "outfeed",
})
_CENSUS_PREFIXES = ("scatter",)  # scatter, scatter-add, scatter-mul, ...
_CENSUS_MARKERS = ("callback",)  # pure_callback, io_callback, ...


def _census_key(name: str) -> str | None:
    if name in _CENSUS_EXACT:
        return name
    for pre in _CENSUS_PREFIXES:
        if name.startswith(pre):
            return name
    for marker in _CENSUS_MARKERS:
        if marker in name:
            return name
    return None


def _iter_all_eqns(jaxpr_like):
    raw = getattr(jaxpr_like, "jaxpr", jaxpr_like)
    for eqn in raw.eqns:
        yield eqn
        for value in eqn.params.values():
            values = value if isinstance(value, (tuple, list)) else (value,)
            for sub in values:
                if isinstance(sub, (_core.Jaxpr, _core.ClosedJaxpr)):
                    yield from _iter_all_eqns(sub)


def op_census(closed_jaxpr) -> dict[str, int]:
    """Static count of budget-tracked primitives across the jaxpr and
    every nested sub-jaxpr. A scan body's ops count once (the census is
    structural, not a dynamic op count)."""
    census: dict[str, int] = {}
    for eqn in _iter_all_eqns(closed_jaxpr):
        key = _census_key(eqn.primitive.name)
        if key is not None:
            census[key] = census.get(key, 0) + 1
    return census


@dataclass
class OpClass:
    """One expensive primitive occurrence, classified for shardability."""

    primitive: str
    cls: str  # "host_local" | "cross_host" | "opaque"
    reason: str
    shapes: list[str] = field(default_factory=list)

    def to_json(self) -> dict:
        return {"primitive": self.primitive, "class": self.cls,
                "reason": self.reason, "shapes": self.shapes}


#: reductions whose `axes` param decides host-locality
_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_or", "reduce_and",
    "reduce_prod", "reduce_xor", "argmax", "argmin",
})

#: cumulative ops with an `axis` param
_CUM_PRIMS = frozenset({
    "cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp",
})


def _classify_eqn(eqn, operand_static: bool) -> OpClass | None:
    """Host-axis locality of one equation, None when it is not a
    shard-relevant primitive. Axis 0 is the host axis by the SoA layout
    contract (`tpu/plane.py` NetPlaneState). `operand_static` marks the
    first operand as a trace-time constant (a lookup table): indexing a
    constant table is a replicated read under shard_map, not a
    cross-shard hop, regardless of how the index was computed."""
    name = eqn.primitive.name
    params = eqn.params
    shapes = [str(tuple(getattr(v.aval, "shape", ())))
              for v in eqn.invars if hasattr(v, "aval")]

    def cls(kind, reason):
        return OpClass(name, kind, reason, shapes)

    if name == "sort":
        dim = params.get("dimension", -1)
        ndim = max((len(getattr(v.aval, "shape", ()))
                    for v in eqn.invars if hasattr(v, "aval")),
                   default=0)
        if ndim <= 1 or dim == 0:
            return cls("cross_host",
                       "sort over a flattened/host axis: becomes a "
                       "cross-shard merge under shard_map")
        return cls("host_local", f"row sort along dim {dim}")
    if name == "gather":
        dn = params.get("dimension_numbers")
        if dn is not None and 0 in tuple(dn.start_index_map):
            if operand_static:
                return cls("host_local",
                           "replicated-table lookup (constant operand)")
            return cls("cross_host",
                       "gather indexed by a computed host id: "
                       "cross-shard read")
        return cls("host_local", "row-local gather (host axis batched)")
    if name.startswith("scatter"):
        dn = params.get("dimension_numbers")
        if dn is not None and 0 in tuple(dn.scatter_dims_to_operand_dims):
            return cls("cross_host",
                       "scatter keyed by a computed host id: the "
                       "routing exchange — a ragged all-to-all under "
                       "shard_map")
        return cls("host_local", "row-local scatter (host axis batched)")
    if name in _REDUCE_PRIMS:
        axes = tuple(params.get("axes", ()))
        ndim = max((len(getattr(v.aval, "shape", ()))
                    for v in eqn.invars if hasattr(v, "aval")),
                   default=0)
        if ndim >= 1 and 0 in axes:
            return cls("cross_host",
                       "reduction over the host axis: a collective "
                       "(psum/pmin) under shard_map")
        return None  # row-local reductions are free; don't report
    if name in _CUM_PRIMS:
        if params.get("axis") == 0:
            return cls("cross_host", "cumulative op along the host axis")
        return None
    if name == "pallas_call":
        return cls("opaque",
                   "hand-written kernel: shardability decided by its "
                   "grid/tile mapping, not inferable from the jaxpr")
    return None


def _classify_walk(jaxpr_like, in_static, sink):
    """Recursive classification pass threading per-var STATICNESS (is
    this value a pure function of trace-time constants?) so table
    lookups are told apart from cross-host reads. Returns the output
    vars' staticness."""
    raw = getattr(jaxpr_like, "jaxpr", jaxpr_like)
    env: dict = {}

    def stat(v):
        return isinstance(v, _core.Literal) or env.get(v, False)

    for var, s in zip(raw.invars, in_static):
        env[var] = s
    for var in raw.constvars:
        env[var] = True

    for eqn in raw.eqns:
        ins = [stat(v) for v in eqn.invars]
        name = eqn.primitive.name
        params = eqn.params
        outs = [all(ins)] * len(eqn.outvars)

        def opaque(reason):
            # never silently drop a body the walk cannot model: the
            # report's one job is "nothing cross-host hides here", so
            # an unwalked sub-jaxpr must surface as an opaque entry
            # (op_census still counts its eqns; only the host-locality
            # classification is unavailable)
            sink(OpClass(name, "opaque", reason,
                         [str(tuple(getattr(v.aval, "shape", ())))
                          for v in eqn.invars if hasattr(v, "aval")]))

        if name in _CALL_LIKE:
            sub = _first_sub_jaxpr(params)
            sub_raw = getattr(sub, "jaxpr", sub) if sub is not None \
                else None
            if sub_raw is not None and len(sub_raw.invars) == len(ins):
                sub_outs = _classify_walk(sub, ins, sink)
                outs = (sub_outs + [False] * len(eqn.outvars)
                        )[:len(eqn.outvars)]
            else:
                opaque("call-like primitive whose body the classifier "
                       "could not map 1:1")
                outs = [False] * len(eqn.outvars)
        elif name == "cond":
            for branch in params["branches"]:
                b_raw = getattr(branch, "jaxpr", branch)
                if len(b_raw.invars) == len(ins) - 1:
                    _classify_walk(branch, ins[1:], sink)
                else:
                    opaque("cond branch whose operands the classifier "
                           "could not map")
            outs = [False] * len(eqn.outvars)
        elif name == "while":
            cn, bn = params["cond_nconsts"], params["body_nconsts"]
            carry_f = [False] * (len(ins) - cn - bn)
            _classify_walk(params["cond_jaxpr"], ins[:cn] + carry_f,
                           sink)
            _classify_walk(params["body_jaxpr"],
                           ins[cn:cn + bn] + carry_f, sink)
            outs = [False] * len(eqn.outvars)
        elif name == "scan":
            nc = params["num_consts"]
            rest = [False] * (len(ins) - nc)
            _classify_walk(params["jaxpr"], ins[:nc] + rest, sink)
            outs = [False] * len(eqn.outvars)
        else:
            oc = _classify_eqn(eqn, bool(ins) and ins[0])
            if oc is not None:
                sink(oc)
            elif name != "pallas_call" and _any_sub_jaxpr(params):
                # an unmodeled higher-order primitive (custom_vmap,
                # custom_root, ...) carrying a body the walk did not
                # enter
                opaque("unmodeled higher-order primitive: its body "
                       "was not classified")
                outs = [False] * len(eqn.outvars)
        for var, s in zip(eqn.outvars, outs):
            env[var] = s

    return [stat(v) for v in raw.outvars]


def shard_census(closed_jaxpr) -> dict:
    """SL504 classification of one entry's jaxpr: every shard-relevant
    primitive bucketed host_local / cross_host / opaque, with cross-host
    occurrences enumerated individually (they are the shard_map
    work-list) and host-local ones aggregated by primitive."""
    host_local: dict[str, int] = {}
    cross: list[OpClass] = []
    opaque: list[OpClass] = []

    def sink(oc: OpClass):
        if oc.cls == "host_local":
            host_local[oc.primitive] = host_local.get(oc.primitive, 0) + 1
        elif oc.cls == "opaque":
            opaque.append(oc)
        else:
            cross.append(oc)

    raw = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    _classify_walk(closed_jaxpr, [False] * len(raw.invars), sink)
    return {
        "host_local": dict(sorted(host_local.items())),
        "cross_host": [oc.to_json() for oc in cross],
        "opaque": [oc.to_json() for oc in opaque],
    }
