"""shadowlint pass 2: jaxpr audit of the jitted ``tpu/`` entry points.

Abstract-evals each registered kernel entry (small representative
shapes — the graph structure, primitives, and dtypes are shape-
independent) and walks the closed jaxpr plus every nested sub-jaxpr
(pjit / while_loop / scan / cond / custom_* bodies) flagging:

- SL201: 64-bit dtypes anywhere in the graph (x64 leak).
- SL202: redundant ``convert_element_type`` chains — a convert whose
  input is itself a single-use convert output and whose composite is a
  dtype round-trip (a -> b -> a). This is the jaxpr signature of
  weak-type churn, the classic silent-recompile trigger.
- SL203: host-callback primitives in the graph.
- SL204: callbacks or device transfers inside a while_loop/scan body —
  one host hop per iteration.
- SL205: constants over a size threshold baked into the graph instead
  of passed as arguments.

The registry (`default_entries`) covers all five kernel modules:
``plane`` (window_step in both qdisc/AQM compile modes + chain_windows
in every presence-switch variant — plain/metrics/guards/workload — +
ingest_rows with all four observability planes threaded), ``tcp``
(event + pull + replay), ``transport`` (the DeviceTransport kernel
set), ``floweng`` (the fused window driver), and ``codel`` (trace
replay + integrated router). Entries carry per-rule allow-lists
with justifications — the pass-2 analogue of the source-comment
suppression syntax, since jaxpr findings have no line to anchor to.
The same registry feeds the SL502 op-budget census and the SL504
shardability report (``analysis/proofs.py``); the SL501 invisibility
proofs trace their own variant surface (`proofs.invisibility_specs`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from .rules import Finding

__all__ = [
    "AuditEntry",
    "audit_all",
    "audit_entry",
    "audit_jaxpr",
    "compiled",
    "default_entries",
    "ensemble_step_build",
    "traced",
    "traced_vmap",
    "vmap_build",
]

# constants above this many bytes should be kernel *arguments*
CONST_BYTES_LIMIT = 256 * 1024

_64BIT = ("float64", "int64", "uint64", "complex128")

# primitive names that cross the device<->host boundary
_CALLBACK_MARKERS = ("callback", "outside_call", "infeed", "outfeed",
                     "debug_print")
_TRANSFER_PRIMS = {"device_put", "convert_device_array", "copy_to_host"}


@dataclass
class AuditEntry:
    """One jitted entry point to audit.

    ``build`` returns a zero-argument trace thunk: a (fn, args) pair
    with every static argument already closed over, so the auditor just
    calls ``jax.make_jaxpr(fn)(*args)``.
    """

    name: str
    module: str
    build: Callable[[], tuple[Callable, tuple]]
    allow: dict[str, str] = field(default_factory=dict)


def _subjaxprs(value):
    """Yield (jaxpr, is_loop_body) for any jaxpr nested in an eqn param."""
    try:
        from jax.extend import core
    except ImportError:  # older jax spells it jax.core
        from jax import core
    jaxpr_types = (core.Jaxpr, core.ClosedJaxpr)
    if isinstance(value, jaxpr_types):
        yield value
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _subjaxprs(v)


def _iter_eqns(jaxpr, in_loop: bool):
    """DFS over (eqn, in_loop) across every nested jaxpr."""
    raw = getattr(jaxpr, "jaxpr", jaxpr)  # peel ClosedJaxpr
    for eqn in raw.eqns:
        yield eqn, in_loop
        is_loop = eqn.primitive.name in ("while", "scan")
        for key, value in eqn.params.items():
            for sub in _subjaxprs(value):
                yield from _iter_eqns(sub, in_loop or is_loop)


def _consts_of(jaxpr):
    """(name, array) for every literal const across nested jaxprs."""
    raw = getattr(jaxpr, "jaxpr", jaxpr)
    for const in getattr(jaxpr, "consts", []):
        yield raw, const
    for eqn in raw.eqns:
        for value in eqn.params.values():
            for sub in _subjaxprs(value):
                yield from _consts_of(sub)


def audit_jaxpr(closed_jaxpr, where: str,
                const_bytes_limit: int = CONST_BYTES_LIMIT
                ) -> list[Finding]:
    """Walk one closed jaxpr (and every sub-jaxpr) for SL201-SL205."""
    findings: list[Finding] = []
    seen_64: set[str] = set()
    n_callbacks = 0
    n_loop_hops = 0

    # producer map for convert-chain detection is per-jaxpr; collect
    # convert eqns grouped by their owning jaxpr object id
    converts_by_jaxpr: dict[int, list] = {}

    def visit(jaxpr):
        raw = getattr(jaxpr, "jaxpr", jaxpr)
        converts = converts_by_jaxpr.setdefault(id(raw), [])
        for eqn in raw.eqns:
            if eqn.primitive.name == "convert_element_type":
                converts.append(eqn)
            for value in eqn.params.values():
                for sub in _subjaxprs(value):
                    visit(sub)

    visit(closed_jaxpr)

    for eqn, in_loop in _iter_eqns(closed_jaxpr, False):
        name = eqn.primitive.name
        for var in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(var, "aval", None)
            dtype = str(getattr(aval, "dtype", ""))
            if dtype in _64BIT and dtype not in seen_64:
                seen_64.add(dtype)
                findings.append(Finding(
                    "SL201", where, 0, 0,
                    f"{dtype} value in the device graph (primitive "
                    f"`{name}`); the plane contract is 32-bit"))
        if any(marker in name for marker in _CALLBACK_MARKERS):
            n_callbacks += 1
            if n_callbacks == 1:
                findings.append(Finding(
                    "SL203", where, 0, 0,
                    f"host callback primitive `{name}` in a jitted "
                    "kernel"))
        if in_loop and (name in _TRANSFER_PRIMS
                        or any(m in name for m in _CALLBACK_MARKERS)):
            n_loop_hops += 1
            if n_loop_hops == 1:
                findings.append(Finding(
                    "SL204", where, 0, 0,
                    f"host transfer/callback `{name}` inside a "
                    "while_loop/scan body: one sync per iteration"))

    # SL202: convert chains within one jaxpr. Map each convert's outvar
    # to its eqn; a convert consuming another convert's single-use
    # output where the composite is dtype-identity is redundant churn.
    for converts in converts_by_jaxpr.values():
        by_outvar = {id(eqn.outvars[0]): eqn for eqn in converts}
        for eqn in converts:
            src = eqn.invars[0]
            feeder = by_outvar.get(id(src))
            if feeder is None:
                continue
            d0 = str(feeder.invars[0].aval.dtype)
            d2 = str(eqn.outvars[0].aval.dtype)
            if d0 == d2:
                d1 = str(feeder.outvars[0].aval.dtype)
                findings.append(Finding(
                    "SL202", where, 0, 0,
                    f"convert_element_type round-trip {d0} -> {d1} -> "
                    f"{d2}: weak-type churn; pin the dtype at the "
                    "source"))

    for raw, const in _consts_of(closed_jaxpr):
        try:
            arr = np.asarray(const)
        except TypeError:
            # extended dtypes (PRNG keys) refuse conversion; size via the
            # aval instead
            arr = np.zeros(getattr(const, "shape", ()), np.uint32)
        if arr.nbytes > const_bytes_limit:
            findings.append(Finding(
                "SL205", where, 0, 0,
                f"{arr.nbytes} B constant ({arr.dtype}{list(arr.shape)}) "
                f"baked into the graph (limit {const_bytes_limit} B); "
                "pass it as a kernel argument"))

    return findings


#: the one shared per-process jaxpr cache, keyed "module:name". Every
#: traced analysis pass — the SL2xx audit, the SL501 invisibility
#: proofs, the SL502 census, the SL504 shard report, and the SL505/
#: SL506 provers — re-traces the same audited entries; hoisting one
#: memo here means a full shadowlint run (or the gating CI proof step)
#: traces each entry ONCE. Entry names are stable per process; callers
#: passing ad-hoc entries must give distinct names. Values are
#: (closed_jaxpr, out_shape, args, fn) — the build thunk's fn rides
#: along so `compiled` below can lower the SAME entry without
#: re-running the builder.
_TRACE_CACHE: dict[str, tuple] = {}

#: the compiled-artifact memo on top of the trace cache, keyed
#: (trace_key, platform): the SL6xx cost fences (analysis/costmodel.py)
#: pull XLA cost_analysis(), memory_analysis(), and the optimized HLO
#: text off each audited entry — one lower+compile per entry per
#: platform, shared across SL601 (cost budgets), SL602 (fusion
#: boundaries), and the watermark extrapolation.
_COMPILE_CACHE: dict[tuple[str, str], object] = {}


def traced(key: str, build):
    """(closed_jaxpr, out_shape, args) for one audited entry,
    memoized across every analysis pass."""
    hit = _TRACE_CACHE.get(key)
    if hit is None:
        import jax

        fn, args = build()
        closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(*args)
        hit = (closed, out_shape, args, fn)
        _TRACE_CACHE[key] = hit
    return hit[:3]


def vmap_build(build, w: int):
    """Wrap an audit-entry build thunk into its W-world vmapped
    variant: every argument leaf is tree-stacked along a new leading
    world axis and the entry fn is wrapped in ``jax.vmap``. Closed-over
    data (params tables, the RNG root) stays SHARED across worlds — it
    lands in the batched jaxpr as constvars, which is exactly the
    world-free/world-batched split the SL701 axis-provenance walk
    (analysis/batchdim.py) starts from."""
    def vbuild():
        import jax
        import jax.numpy as jnp

        fn, args = build()
        vargs = jax.tree.map(lambda x: jnp.stack([x] * w), args)
        return jax.vmap(fn), vargs

    return vbuild


def traced_vmap(key: str, build, w: int):
    """The ``@vmapW{w}`` trace-cache variant of one audited entry:
    (closed_jaxpr, out_shape, args) of the entry vmapped over ``w``
    stacked worlds, memoized in the SAME per-process cache as the solo
    trace — so the SL701/SL703 batch pass and the SL601 ensemble
    watermark twins each trace a given (entry, world-count) once."""
    return traced(f"{key}@vmapW{w}", vmap_build(build, w))


def ensemble_step_build(w: int, n: int = 4):
    """The ensemble consumer at W worlds — the step
    ``tpu/elastic.drive_ensemble`` vmaps: one loss-enabled
    ``window_step`` per world with PER-WORLD fold_in keys, shifts, and
    windows batched along the leading world axis while the params
    tables stay shared. This is the entry whose SL701 proof covers the
    batched-RNG path (per-world threefry keys), and the one the SL601
    W=2/W=4 watermark twins fence for super-linear ensemble memory."""
    def build():
        import jax
        import jax.numpy as jnp

        from ..tpu import elastic, plane

        params = plane.make_params(
            latency_ns=np.full((n, n), 1_000_000, np.int64),
            loss=np.full((n, n), 0.01, np.float64),
            up_bw_bps=np.full(n, 1_000_000_000, np.int64),
        )
        state = plane.make_state(n, egress_cap=8, ingress_cap=8,
                                 params=params)
        root = jax.random.key(0)
        keys = elastic.world_keys(root, jnp.arange(w, dtype=jnp.int32))
        states = jax.tree.map(lambda x: jnp.stack([x] * w), state)

        def step(state, key, shift, window):
            return plane.window_step(state, params, key, shift, window,
                                     rr_enabled=False)

        return jax.vmap(step), (states, keys,
                                jnp.zeros((w,), jnp.int32),
                                jnp.full((w,), 10_000_000, jnp.int32))

    return build


def compiled(key: str, build):
    """The compiled XLA executable for one audited entry, memoized
    per (trace_key, platform). Populates/shares the jaxpr trace memo,
    then lowers through ``jit(fn).lower(*args).compile()`` exactly
    once — so a full SL6xx pass (cost budgets + fusion census +
    watermark extrapolation) compiles each registered entry once, not
    once per rule family."""
    import jax

    platform = jax.devices()[0].platform
    cache_key = (key, platform)
    hit = _COMPILE_CACHE.get(cache_key)
    if hit is None:
        traced(key, build)  # one builder call, shared with every pass
        _closed, _shape, args, fn = _TRACE_CACHE[key]
        hit = jax.jit(fn).lower(*args).compile()
        _COMPILE_CACHE[cache_key] = hit
    return hit


def audit_entry(entry: AuditEntry) -> list[Finding]:
    closed, _shape, _args = traced(f"{entry.module}:{entry.name}",
                                   entry.build)
    findings = audit_jaxpr(closed, f"{entry.module}:{entry.name}")
    for f in findings:
        just = entry.allow.get(f.rule)
        if just:
            f.suppressed = True
            f.justification = just
    return findings


# ---------------------------------------------------------------------------
# entry registry: all five tpu kernel modules at small shapes
# ---------------------------------------------------------------------------

class _StubHost:
    def __init__(self, host_id: int, node_id: int):
        self.host_id = host_id
        self.node_id = node_id


class _StubRouting:
    """Minimal RoutingInfo twin for DeviceTransport's constructor."""

    def __init__(self, n_nodes: int):
        self.latency_ns = np.full((n_nodes, n_nodes), 1_000_000, np.int64)
        np.fill_diagonal(self.latency_ns, 5_000)

    def node_index(self, node_id: int) -> int:
        return int(node_id)


def _plane_entry(rr_enabled: bool, router_aqm: bool, no_loss: bool,
                 packed_sort: bool = True, kernel: str = "xla",
                 telemetry: bool = False, faults: bool = False,
                 guards: bool = False, trace: bool = False,
                 n: int = 4):
    def build():
        import jax
        import jax.numpy as jnp

        from ..faults.plane import neutral_faults
        from ..guards.plane import make_guards
        from ..telemetry import make_flightrec, make_histograms, \
            make_metrics
        from ..tpu import plane

        m = 3
        params = plane.make_params(
            latency_ns=np.full((m, m), 1_000_000, np.int64),
            loss=np.full((m, m), 0.0 if no_loss else 0.01, np.float64),
            up_bw_bps=np.full(n, 1_000_000_000, np.int64),
            qdisc_rr=np.array([True, False] * (n // 2)),
            down_bw_bps=np.full(n, 1_000_000_000, np.int64),
            host_node=np.arange(n, dtype=np.int32) % m,
        )
        state = plane.make_state(n, egress_cap=8, ingress_cap=8,
                                 params=params)
        root = jax.random.key(0)

        if faults:
            def fn(state, fault_arrays, shift, window):
                return plane.window_step(
                    state, params, root, shift, window,
                    rr_enabled=rr_enabled, router_aqm=router_aqm,
                    no_loss=no_loss, packed_sort=packed_sort,
                    kernel=kernel, faults=fault_arrays)

            return fn, (state, neutral_faults(n, m), jnp.int32(0),
                        jnp.int32(10_000_000))

        if telemetry:
            def fn(state, metrics, shift, window):
                return plane.window_step(
                    state, params, root, shift, window,
                    rr_enabled=rr_enabled, router_aqm=router_aqm,
                    no_loss=no_loss, packed_sort=packed_sort,
                    kernel=kernel, metrics=metrics)

            return fn, (state, make_metrics(n), jnp.int32(0),
                        jnp.int32(10_000_000))

        if trace:
            def fn(state, hist, flightrec, shift, window):
                return plane.window_step(
                    state, params, root, shift, window,
                    rr_enabled=rr_enabled, router_aqm=router_aqm,
                    no_loss=no_loss, packed_sort=packed_sort,
                    kernel=kernel, hist=hist, flightrec=flightrec)

            return fn, (state, make_histograms(n),
                        make_flightrec(0, sample_every=4, ring=64),
                        jnp.int32(0), jnp.int32(10_000_000))

        if guards:
            def fn(state, guard_state, shift, window):
                return plane.window_step(
                    state, params, root, shift, window,
                    rr_enabled=rr_enabled, router_aqm=router_aqm,
                    no_loss=no_loss, packed_sort=packed_sort,
                    kernel=kernel, guards=guard_state)

            return fn, (state, make_guards(n), jnp.int32(0),
                        jnp.int32(10_000_000))

        def fn(state, shift, window):
            return plane.window_step(
                state, params, root, shift, window,
                rr_enabled=rr_enabled, router_aqm=router_aqm,
                no_loss=no_loss, packed_sort=packed_sort, kernel=kernel)

        return fn, (state, jnp.int32(0), jnp.int32(10_000_000))

    return build


def _routing_entry(stage: str):
    """The routing-stage split (plane section 5): `routing_rank` audits
    the bucketed-order computation (row seq-rank + diet flat sort +
    histogram placement), `routing_place` the fused per-column
    gather-scatters — the same split the per-section profiler times."""
    def build():
        import jax.numpy as jnp

        from ..tpu import plane

        n, ce, ci = 4, 8, 8
        rng = np.random.default_rng(0)
        sent = jnp.asarray(rng.integers(0, 2, (n, ce)) == 0)
        eg_dst = jnp.asarray(rng.integers(0, n, (n, ce)), jnp.int32)
        eg_seq = jnp.asarray(rng.integers(0, 100, (n, ce)), jnp.int32)
        eg_bytes = jnp.full((n, ce), 1400, jnp.int32)
        eg_sock = jnp.zeros((n, ce), jnp.int32)
        deliver = jnp.asarray(
            rng.integers(0, 10**6, (n, ce)), jnp.int32)
        n_valid = jnp.zeros((n,), jnp.int32)
        if stage == "rank":
            def fn(sent, eg_dst, eg_seq, deliver, n_valid):
                return plane._routing_rank(
                    sent, eg_dst, eg_seq, deliver, n_valid, ci)

            return fn, (sent, eg_dst, eg_seq, deliver, n_valid)
        row_perm, o_pos, offsets, take_n, _ovf = plane._routing_rank(
            sent, eg_dst, eg_seq, deliver, n_valid, ci)
        z = lambda: jnp.zeros((n, ci), jnp.int32)
        return plane._routing_place, (
            row_perm, o_pos, offsets, take_n, n_valid, eg_seq, eg_bytes,
            eg_sock, deliver, z(), z(), z(), z(), z(),
            jnp.zeros((n, ci), bool))

    return build


def _chain_entry(variant: str = "plain", n: int = 4):
    """`chain_windows` in each presence-switch compile mode: the chain
    is THE device-resident driver loop, so every pytree that can ride
    its while_loop carry (metrics / guards / the workload generator)
    gets its own audited trace — a host sync smuggled into any carry
    variant fails SL204 here, not in production."""
    def build():
        import jax
        import jax.numpy as jnp

        from ..guards.plane import make_guards
        from ..telemetry import make_metrics
        from ..tpu import plane
        params = plane.make_params(
            latency_ns=np.full((n, n), 1_000_000, np.int64),
            loss=np.zeros((n, n)),
            up_bw_bps=np.full(n, 1_000_000_000, np.int64),
        )
        state = plane.make_state(n, egress_cap=8, ingress_cap=8,
                                 params=params)
        root = jax.random.key(0)

        def chain(state, shift0, horizon, **kw):
            return plane.chain_windows(
                state, params, root, shift0, jnp.int32(1_000_000),
                jnp.int32(1_000_000), horizon, horizon,
                rr_enabled=False, no_loss=True, **kw)

        args = (state, jnp.int32(0), jnp.int32(50_000_000))
        if variant == "metrics":
            def fn(state, metrics, shift0, horizon):
                return chain(state, shift0, horizon, metrics=metrics)

            return fn, (args[0], make_metrics(n), *args[1:])
        if variant == "guards":
            def fn(state, guards, shift0, horizon):
                return chain(state, shift0, horizon, guards=guards)

            return fn, (args[0], make_guards(n), *args[1:])
        if variant == "flows":
            from ..tpu import flows as flows_mod

            ft = flows_mod.make_flow_tables(
                np.arange(n, dtype=np.int32),
                (np.arange(n, dtype=np.int32) + 1) % n,
                np.full(n, 1400, np.int32))

            def fn(state, fs, shift0, horizon):
                return chain(state, shift0, horizon, flows=(ft, fs))

            return fn, (args[0], flows_mod.make_flow_state(n),
                        *args[1:])
        if variant == "compute":
            from ..tpu import compute as compute_mod

            ct = compute_mod.make_compute_tables(
                np.full((n, 1), 25_000, np.int32), queue_cap=16)

            def fn(state, cs, shift0, horizon):
                return chain(state, shift0, horizon, compute=(ct, cs))

            return fn, (args[0], compute_mod.make_compute_state(ct),
                        *args[1:])
        if variant == "workload":
            from ..workloads import compile_program, parse_scenario
            from ..workloads import device as wdevice

            prog = compile_program(parse_scenario({
                "name": "audit-onoff", "hosts": n, "egress_cap": 8,
                "ingress_cap": 8, "windows": 4,
                "patterns": [{"kind": "onoff", "burst": 1, "rounds": 2,
                              "gap_ns": 200_000,
                              "off_mean_ns": 2_000_000}],
            }))
            wl = wdevice.to_device(prog)

            def fn(state, ws, shift0, horizon):
                return chain(state, shift0, horizon, workload=(wl, ws))

            return fn, (args[0], wdevice.make_workload_state(prog),
                        *args[1:])

        def fn(state, shift0, horizon):
            return chain(state, shift0, horizon)

        return fn, args

    return build


def _ingest_rows_entry():
    """`ingest_rows` with all four observability planes threaded — the
    third kernel of the SL501 proof surface gets the SL2xx audit too
    (widest compile mode: every plane section in the graph)."""
    def build():
        import jax.numpy as jnp

        from ..guards.plane import make_guards
        from ..telemetry import make_flightrec, make_histograms, \
            make_metrics
        from ..tpu import plane

        n, k = 4, 4
        params = plane.make_params(
            latency_ns=np.full((n, n), 1_000_000, np.int64),
            loss=np.zeros((n, n)),
            up_bw_bps=np.full(n, 1_000_000_000, np.int64),
        )
        state = plane.make_state(n, egress_cap=8, ingress_cap=8,
                                 params=params)
        z = lambda: jnp.zeros((n, k), jnp.int32)

        def fn(state, metrics, guards, hist, flightrec, dst, nbytes,
               prio, seq, valid):
            return plane.ingest_rows(
                state, dst, nbytes, prio, seq,
                jnp.zeros((n, k), bool), valid,
                metrics=metrics, guards=guards, hist=hist,
                flightrec=flightrec)

        return fn, (state, make_metrics(n), make_guards(n),
                    make_histograms(n),
                    make_flightrec(0, sample_every=4, ring=64),
                    z(), z(), z(), z(), jnp.zeros((n, k), bool))

    return build


def _flows_entry(kind: str):
    """The flow plane (docs/robustness.md "Flow plane"): the
    flows-threaded window_step variant plus the standalone flow_step
    composition — both SL2xx-audited and, for the window_step variant,
    the SL501 append-only proof subject (`analysis/proofs.py`)."""
    def build():
        import jax
        import jax.numpy as jnp

        from ..tpu import flows as flows_mod
        from ..tpu import plane

        n = 4
        params = plane.make_params(
            latency_ns=np.full((n, n), 1_000_000, np.int64),
            loss=np.full((n, n), 0.01, np.float64),
            up_bw_bps=np.full(n, 1_000_000_000, np.int64),
        )
        state = plane.make_state(n, egress_cap=8, ingress_cap=8,
                                 params=params)
        root = jax.random.key(0)
        ft = flows_mod.make_flow_tables(
            np.arange(n, dtype=np.int32),
            (np.arange(n, dtype=np.int32) + 1) % n,
            np.full(n, 1400, np.int32))
        fs = flows_mod.make_flow_state(n)
        if kind == "window":
            def fn(state, fs, shift, window):
                return plane.window_step(
                    state, params, root, shift, window,
                    rr_enabled=False, flows=(ft, fs))

            return fn, (state, fs, jnp.int32(0),
                        jnp.int32(10_000_000))
        ci = state.in_src.shape[1]
        delivered = {
            "mask": jnp.zeros((n, ci), bool),
            "src": jnp.zeros((n, ci), jnp.int32),
            "seq": jnp.zeros((n, ci), jnp.int32),
            "sock": jnp.zeros((n, ci), jnp.int32),
            "bytes": jnp.zeros((n, ci), jnp.int32),
            "deliver_rel": jnp.zeros((n, ci), jnp.int32),
        }

        def fn(ft_arrays, fs, state, delivered):
            return flows_mod.flow_step(ft_arrays, fs, state, delivered,
                                       jnp.int32(10_000_000))

        return fn, (ft, fs, state, delivered)

    return build


def _compute_entry(kind: str):
    """The device compute plane (docs/workloads.md "Serving load & the
    compute plane"): the compute-threaded window_step variant plus the
    standalone compute_step FIFO kernel — both SL2xx-audited and, for
    the window_step variant, the SL501 FULL-invisibility proof subject
    (`analysis/proofs.py`): compute taint may reach only the appended
    ComputeState output, never state / delivered / next_event."""
    def build():
        import jax
        import jax.numpy as jnp

        from ..tpu import compute as compute_mod
        from ..tpu import plane

        n = 4
        params = plane.make_params(
            latency_ns=np.full((n, n), 1_000_000, np.int64),
            loss=np.zeros((n, n)),
            up_bw_bps=np.full(n, 1_000_000_000, np.int64),
        )
        state = plane.make_state(n, egress_cap=8, ingress_cap=8,
                                 params=params)
        root = jax.random.key(0)
        ct = compute_mod.make_compute_tables(
            np.full((n, 1), 25_000, np.int32), queue_cap=16)
        cs = compute_mod.make_compute_state(ct)
        if kind == "window":
            def fn(state, cs, shift, window):
                return plane.window_step(
                    state, params, root, shift, window,
                    rr_enabled=False, compute=(ct, cs))

            return fn, (state, cs, jnp.int32(0),
                        jnp.int32(10_000_000))
        ci = state.in_src.shape[1]
        delivered = {
            "mask": jnp.zeros((n, ci), bool),
            "src": jnp.zeros((n, ci), jnp.int32),
            "seq": jnp.zeros((n, ci), jnp.int32),
            "sock": jnp.zeros((n, ci), jnp.int32),
            "bytes": jnp.zeros((n, ci), jnp.int32),
            "deliver_rel": jnp.zeros((n, ci), jnp.int32),
        }

        def fn(ct_arrays, cs, delivered):
            return compute_mod.compute_step(
                ct_arrays, cs, delivered, jnp.int32(0),
                jnp.int32(10_000_000))

        return fn, (ct, cs, delivered)

    return build


def _tcp_entry(kind: str):
    def build():
        import jax.numpy as jnp

        from ..tpu import tcp as dtcp

        c = 4
        plane = dtcp.make_tcp_plane(c, reass_slots=8)
        if kind == "event":
            fn = dtcp.tcp_event_step
            args = (plane, jnp.zeros((c,), jnp.int32),
                    jnp.zeros((c, dtcp.N_FIELDS), jnp.int32),
                    jnp.zeros((c,), jnp.int32))
        elif kind == "pull":
            fn = dtcp.tcp_pull_step
            args = (plane, jnp.zeros((c,), jnp.int32))
        else:  # replay
            t = 3
            fn = dtcp.tcp_replay
            args = (plane, jnp.zeros((c, t), jnp.int32),
                    jnp.zeros((c, t, dtcp.N_FIELDS), jnp.int32),
                    jnp.zeros((c, t), jnp.int32))
        return fn, args

    return build


def _transport_entry(kernel: str):
    def build():
        import jax.numpy as jnp

        from ..tpu.transport import DeviceTransport

        n = 4
        dt = DeviceTransport(
            [_StubHost(i + 1, i % 3) for i in range(n)],
            _StubRouting(3), None, egress_cap=8, ingress_cap=8,
            mode="sync", compact_cap=16)
        # audit the GUARDED + HISTOGRAMMED variants: guard checks and
        # histogram adds are part of the kernel surface whenever the
        # planes are enabled, and the disabled traces are strict
        # subsets (g=None / h=None compile them out)
        dt.enable_guards()
        dt.enable_histograms()
        st, g, h = dt.state, dt._guard, dt._hist
        if kernel == "ingest":
            b = 8
            z = lambda: jnp.zeros((b,), jnp.int32)
            args = (st, g, h, z(), z(), z(), z(), z(), z(),
                    jnp.zeros((b,), bool))
            return dt._k_ingest, args
        if kernel == "step":
            return dt._k_step, (st, g, h, jnp.int32(0),
                                jnp.int32(1_000_000))
        if kernel == "chain":
            i32 = jnp.int32
            return dt._k_chain, (st, g, h, i32(0), i32(1_000_000),
                                 i32(1_000_000), i32(50_000_000),
                                 i32(50_000_000))
        # batch_verify: K windows of B ingest rows
        k, b = 4, 8
        zk = lambda: jnp.zeros((k,), jnp.int32)
        row = {key: jnp.zeros((k, b), jnp.int32)
               for key in ("src", "dst", "seq", "tag", "send", "clamp")}
        row["valid"] = jnp.zeros((k, b), bool)
        args = (st, g, h, zk(), zk(), row, jnp.zeros((k,), jnp.uint32),
                jnp.zeros((k,), jnp.uint32), zk(), jnp.int32(0))
        return dt._k_batch_verify, args

    return build


def _floweng_entry():
    def build():
        import functools

        from ..tpu import floweng

        world = floweng.make_flow_world(
            latency_us=np.full(4, 1000, np.int64),
            size_bytes=np.full(4, 65536, np.int64),
            queue_slots=16, loss=0.01)
        fn = functools.partial(
            floweng.run_windows, n_windows=2, window_us=1000,
            max_events_per_window=8, ack_every=2, sched_batch=2,
            pull_cap=2, gso_segs=4)
        return fn, (world,)

    return build


def _codel_entry(kernel: str):
    def build():
        import jax.numpy as jnp

        from ..tpu import codel

        n, k = 4, 8
        arrival = jnp.full((n, k), codel.I32_MAX, jnp.int32)
        size = jnp.zeros((n, k), jnp.int32)
        if kernel == "codel_drain":
            pops = jnp.full((n, k), codel.I32_MAX, jnp.int32)
            st = codel.make_codel_state(n)
            return codel.codel_drain, (arrival, size, pops, st)
        st = codel.make_router_state(n)
        rate = jnp.full((n,), 125_000, jnp.int32)
        cap = rate + 1500

        def fn(arrival, size, rate, cap, st):
            return codel.router_drain(
                arrival, size, jnp.int32(10_000_000), rate, cap, st)

        return fn, (arrival, size, rate, cap, st)

    return build


def default_entries() -> list[AuditEntry]:
    """The audited kernel surface: every jitted entry point of the five
    tpu/ modules at small representative shapes."""
    entries = [
        AuditEntry("window_step[rr,aqm,loss]", "shadow_tpu.tpu.plane",
                   _plane_entry(True, True, False)),
        AuditEntry("window_step[lean]", "shadow_tpu.tpu.plane",
                   _plane_entry(False, False, True)),
        AuditEntry("window_step[legacy-sort]", "shadow_tpu.tpu.plane",
                   _plane_entry(True, True, False, packed_sort=False)),
        AuditEntry("window_step[pallas]", "shadow_tpu.tpu.plane",
                   _plane_entry(False, False, True, kernel="pallas")),
        AuditEntry("window_step[pallas_fused]", "shadow_tpu.tpu.plane",
                   _plane_entry(False, False, True,
                                kernel="pallas_fused")),
        AuditEntry("window_step[telemetry]", "shadow_tpu.tpu.plane",
                   _plane_entry(True, True, False, telemetry=True)),
        AuditEntry("window_step[faults]", "shadow_tpu.tpu.plane",
                   _plane_entry(True, True, False, faults=True)),
        AuditEntry("window_step[guards]", "shadow_tpu.tpu.plane",
                   _plane_entry(True, True, False, guards=True)),
        AuditEntry("window_step[trace]", "shadow_tpu.tpu.plane",
                   _plane_entry(True, True, False, trace=True)),
        AuditEntry("routing_rank", "shadow_tpu.tpu.plane",
                   _routing_entry("rank")),
        AuditEntry("routing_place", "shadow_tpu.tpu.plane",
                   _routing_entry("place")),
        AuditEntry("chain_windows", "shadow_tpu.tpu.plane",
                   _chain_entry()),
        AuditEntry("chain_windows[metrics]", "shadow_tpu.tpu.plane",
                   _chain_entry("metrics")),
        AuditEntry("chain_windows[guards]", "shadow_tpu.tpu.plane",
                   _chain_entry("guards")),
        AuditEntry("chain_windows[workload]", "shadow_tpu.tpu.plane",
                   _chain_entry("workload")),
        AuditEntry("chain_windows[flows]", "shadow_tpu.tpu.plane",
                   _chain_entry("flows")),
        AuditEntry("ingest_rows[planes]", "shadow_tpu.tpu.plane",
                   _ingest_rows_entry()),
        AuditEntry("window_step[flows]", "shadow_tpu.tpu.plane",
                   _flows_entry("window")),
        AuditEntry("flow_step", "shadow_tpu.tpu.flows",
                   _flows_entry("step")),
        AuditEntry("chain_windows[compute]", "shadow_tpu.tpu.plane",
                   _chain_entry("compute")),
        AuditEntry("window_step[compute]", "shadow_tpu.tpu.plane",
                   _compute_entry("window")),
        AuditEntry("compute_step", "shadow_tpu.tpu.compute",
                   _compute_entry("step")),
        AuditEntry("tcp_event_step", "shadow_tpu.tpu.tcp",
                   _tcp_entry("event")),
        AuditEntry("tcp_pull_step", "shadow_tpu.tpu.tcp",
                   _tcp_entry("pull")),
        AuditEntry("tcp_replay", "shadow_tpu.tpu.tcp",
                   _tcp_entry("replay")),
        AuditEntry("ingest", "shadow_tpu.tpu.transport",
                   _transport_entry("ingest")),
        AuditEntry("step_compact", "shadow_tpu.tpu.transport",
                   _transport_entry("step")),
        AuditEntry("chain", "shadow_tpu.tpu.transport",
                   _transport_entry("chain")),
        AuditEntry("batch_verify", "shadow_tpu.tpu.transport",
                   _transport_entry("verify")),
        AuditEntry("run_windows", "shadow_tpu.tpu.floweng",
                   _floweng_entry()),
        AuditEntry("codel_drain", "shadow_tpu.tpu.codel",
                   _codel_entry("codel_drain")),
        AuditEntry("router_drain", "shadow_tpu.tpu.codel",
                   _codel_entry("router_drain")),
    ]
    return entries


def audit_all(entries: list[AuditEntry] | None = None) -> list[Finding]:
    out: list[Finding] = []
    for entry in entries if entries is not None else default_entries():
        out.extend(audit_entry(entry))
    return out
