"""shadowlint pass 3: dataflow proofs over the audited kernel surface.

Three rule families on top of ``analysis/dataflow.py`` (the SL505
branch-equivalence prover and the SL506 range analysis live in their
own modules, ``analysis/condeq.py`` / ``analysis/ranges.py``, sharing
the same per-process trace cache — ``jaxpr_audit.traced``):

- **SL501 presence-invisibility** — for every observability-plane
  variant of ``window_step`` / ``chain_windows`` / ``ingest_rows``
  (metrics, guards, hist, flightrec, and their compositions) the plane
  input leaves are tainted and the analysis must prove no tainted value
  reaches any sim-state output leaf: NetPlaneState columns, the RNG
  counter, the clock offsets, the delivered stream. This turns the
  runtime "bitwise-invisible" parity matrices (tests/test_telemetry.py,
  test_guards.py, test_flightrec.py — a handful of rr×aqm×no_loss
  corners, minutes per run) into a theorem over ALL inputs, checked in
  seconds on every build. The workload plane gets the relaxed
  *append-only* theorem instead: its generator may only ever touch the
  egress columns and the overflow counter — the wire, RNG, clocks,
  ingress rings, and delivered stream are provably out of its reach.

- **SL502 op-budget ledger** — the static census of expensive
  primitives per audited entry, diffed against the checked-in
  ``op_budgets.json``. A reintroduced variadic sort or per-column
  scatter changes the census and fails CI without a bench; budget
  updates must be explicit in the diff
  (``tools/shadowlint.py --write-op-budgets``).

- **SL504 shardability report + row-local fence** — every entry's
  shard-relevant primitives classified host-axis-local vs cross-host,
  the scoping work-list for the ROADMAP-2 ``shard_map`` cut; the
  tcp/codel row-local stages (``ROW_LOCAL_PINNED``) GATE on keeping
  an empty cross-host set.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from .dataflow import leaf_paths, op_census, propagate_taint, shard_census
from .jaxpr_audit import (_chain_entry, _compute_entry, _flows_entry,
                          _ingest_rows_entry, _plane_entry)
from .rules import Finding

__all__ = [
    "InvisibilitySpec",
    "ROW_LOCAL_PINNED",
    "budget_path",
    "build_shard_report",
    "check_invisibility",
    "check_op_budgets",
    "check_row_local_fence",
    "compute_censuses",
    "format_budget_delta",
    "invisibility_specs",
    "write_op_budgets",
]

#: NetPlaneState fields the workload generator is ALLOWED to write —
#: the egress ring it appends to and the ring-full drop counter that
#: append maintains. Everything else in sim state must stay
#: taint-free under the append-only theorem.
WORKLOAD_APPEND_OK = frozenset({
    "eg_dst", "eg_bytes", "eg_prio", "eg_seq", "eg_ctrl", "eg_tsend",
    "eg_clamp", "eg_sock", "eg_valid", "n_overflow_dropped",
})

#: the flow plane (tpu/flows.py) carries the SAME append-only
#: confinement: its retransmissions and delayed acks enter through
#: `plane.ingest`, so the egress columns + the overflow counter are
#: the only sim-state leaves its taint may reach — the wire, RNG,
#: clocks, ingress rings, and the delivered stream are provably out
#: of its reach (the flows-off world theorem: with flows=None nothing
#: exists to taint, and with flows threaded the plane writes ONLY the
#: append surface).
FLOWS_APPEND_OK = WORKLOAD_APPEND_OK


@dataclass
class InvisibilitySpec:
    """One SL501 proof obligation.

    ``build`` returns (fn, args) exactly like an ``AuditEntry``;
    ``tainted_args`` maps positional arg index -> taint-label prefix
    (the plane name); ``protected`` decides, per output leaf, whether
    taint reaching it is a violation — given the top-level output tuple
    index and the leaf's key path. ``trace_key`` overrides the shared
    trace-cache key (default ``module:name``) when the spec shares a
    builder with an audit entry under a different display name.
    """

    name: str
    module: str
    build: Callable[[], tuple[Callable, tuple]]
    tainted_args: dict[int, str] = field(default_factory=dict)
    protected: Callable[[int, str], bool] = lambda idx, path: True
    trace_key: str | None = None

    @property
    def cache_key(self) -> str:
        return self.trace_key or f"{self.module}:{self.name}"


def _protect_lead(n: int):
    """Protect the first `n` top-level outputs (the sim-state lead of a
    window_step/chain_windows return); the plane outputs after them are
    legitimately tainted."""
    return lambda idx, path: idx < n


def _out_index(path: str) -> int:
    """Top-level tuple index of an output leaf path like
    ``[0].eg_dst`` (single-output entries render with no prefix -> 0)."""
    if path.startswith("["):
        return int(path[1:path.index("]")])
    return 0


# --------------------------------------------------------------------------
# spec builders (small representative shapes, like jaxpr_audit)
# --------------------------------------------------------------------------


def _mini_world(n: int = 4, m: int = 3):
    import jax

    from ..tpu import plane

    params = plane.make_params(
        latency_ns=np.full((m, m), 1_000_000, np.int64),
        loss=np.full((m, m), 0.01, np.float64),
        up_bw_bps=np.full(n, 1_000_000_000, np.int64),
        qdisc_rr=np.array([True, False] * (n // 2)),
        down_bw_bps=np.full(n, 1_000_000_000, np.int64),
        host_node=np.arange(n, dtype=np.int32) % m,
    )
    state = plane.make_state(n, egress_cap=8, ingress_cap=8,
                             params=params)
    return plane, params, state, jax.random.key(0), n


def _window_planes_entry(*, metrics: bool = False, guards: bool = False,
                         hist: bool = False, flightrec: bool = False):
    """window_step with any subset of the four observability planes
    threaded (rr+aqm+loss: the widest compile mode)."""
    def build():
        import jax.numpy as jnp

        from ..guards.plane import make_guards
        from ..telemetry import make_flightrec, make_histograms, \
            make_metrics

        plane, params, state, root, n = _mini_world()
        planes = {}
        if metrics:
            planes["metrics"] = make_metrics(n)
        if guards:
            planes["guards"] = make_guards(n)
        if hist:
            planes["hist"] = make_histograms(n)
        if flightrec:
            planes["flightrec"] = make_flightrec(
                0, sample_every=4, ring=64)
        keys = list(planes)

        def fn(state, *rest):
            plane_args = dict(zip(keys, rest[:len(keys)]))
            shift, window = rest[len(keys):]
            return plane.window_step(
                state, params, root, shift, window,
                rr_enabled=True, router_aqm=True, no_loss=False,
                **plane_args)

        args = (state, *[planes[k] for k in keys],
                jnp.int32(0), jnp.int32(10_000_000))
        return fn, args

    return build


def _chain_planes_entry(*, metrics: bool = False, guards: bool = False,
                        workload: bool = False):
    """chain_windows with metrics/guards (and optionally the workload
    generator) riding the while-loop carry."""
    def build():
        import jax
        import jax.numpy as jnp

        from ..guards.plane import make_guards
        from ..telemetry import make_metrics
        from ..tpu import plane

        root = jax.random.key(0)
        n = 4
        params = plane.make_params(
            latency_ns=np.full((n, n), 1_000_000, np.int64),
            loss=np.zeros((n, n)),
            up_bw_bps=np.full(n, 1_000_000_000, np.int64),
        )
        state = plane.make_state(n, egress_cap=8, ingress_cap=8,
                                 params=params)
        kw_builders = {}
        if metrics:
            kw_builders["metrics"] = make_metrics(n)
        if guards:
            kw_builders["guards"] = make_guards(n)
        keys = list(kw_builders)

        wl = ws0 = None
        if workload:
            from ..workloads import compile_program, parse_scenario
            from ..workloads import device as wdevice

            prog = compile_program(parse_scenario({
                "name": "proof-onoff", "hosts": n, "egress_cap": 8,
                "ingress_cap": 8, "windows": 4,
                "patterns": [{"kind": "onoff", "burst": 1, "rounds": 2,
                              "gap_ns": 200_000,
                              "off_mean_ns": 2_000_000}],
            }))
            wl = wdevice.to_device(prog)
            ws0 = wdevice.make_workload_state(prog)

        def fn(state, *rest):
            plane_args = dict(zip(keys, rest[:len(keys)]))
            cursor = len(keys)
            if workload:
                ws = rest[cursor]
                cursor += 1
                plane_args["workload"] = (wl, ws)
            shift0, horizon = rest[cursor:]
            return plane.chain_windows(
                state, params, root, shift0, jnp.int32(1_000_000),
                jnp.int32(1_000_000), horizon, horizon,
                rr_enabled=False, no_loss=True, **plane_args)

        args = [state, *[kw_builders[k] for k in keys]]
        if workload:
            args.append(ws0)
        args += [jnp.int32(0), jnp.int32(50_000_000)]
        return fn, tuple(args)

    return build


# the ingest_rows builder is shared with the SL2xx audit registry
# (jaxpr_audit._ingest_rows_entry): same trace, two rule families —
# keeping one copy means the audit and the proof can never silently
# diverge on what "the ingest_rows kernel" is


def _workload_step_entry():
    """workload_step in isolation: the append-only theorem's subject."""
    def build():
        import jax.numpy as jnp

        from ..workloads import compile_program, parse_scenario
        from ..workloads import device as wdevice

        plane, _params, state, _root, n = _mini_world()
        prog = compile_program(parse_scenario({
            "name": "proof-onoff", "hosts": n, "egress_cap": 8,
            "ingress_cap": 8, "windows": 4,
            "patterns": [{"kind": "onoff", "burst": 1, "rounds": 2,
                          "gap_ns": 200_000,
                          "off_mean_ns": 2_000_000}],
        }))
        wl = wdevice.to_device(prog)
        ws0 = wdevice.make_workload_state(prog)
        ci = state.in_src.shape[1]
        delivered = {
            "mask": jnp.zeros((n, ci), bool),
            "src": jnp.zeros((n, ci), jnp.int32),
            "seq": jnp.zeros((n, ci), jnp.int32),
            "sock": jnp.zeros((n, ci), jnp.int32),
            "bytes": jnp.zeros((n, ci), jnp.int32),
            "deliver_rel": jnp.zeros((n, ci), jnp.int32),
        }

        def fn(ws, wl_arrays, state, delivered):
            return wdevice.workload_step(
                wl_arrays, ws, state, delivered, jnp.int32(0),
                jnp.int32(1_000_000))

        return fn, (ws0, wl, state, delivered)

    return build


def _workload_protected(idx: int, path: str) -> bool:
    """Append-only: protect every state leaf EXCEPT the egress columns
    and the overflow counter. workload_step returns (state', ws')."""
    if idx != 0:
        return False
    leaf = path.split(".")[-1].split("[")[0]
    return leaf not in WORKLOAD_APPEND_OK


def _flows_window_protected(idx: int, path: str) -> bool:
    """The flows-threaded window_step append-only theorem: state leaves
    outside the append surface, the delivered dict, and next_event are
    protected (delivered and next_event are computed BEFORE the flow
    section — docs/robustness.md 'Flow plane'); the FlowState output
    (idx 3) is legitimately tainted."""
    if idx == 0:
        leaf = path.split(".")[-1].split("[")[0]
        return leaf not in FLOWS_APPEND_OK
    return idx in (1, 2)


def _flows_step_protected(idx: int, path: str) -> bool:
    """flow_step standalone returns (state', fs', credits): the same
    append-only confinement on state'; fs'/credits are the plane's
    own outputs."""
    if idx != 0:
        return False
    leaf = path.split(".")[-1].split("[")[0]
    return leaf not in FLOWS_APPEND_OK


def invisibility_specs() -> list[InvisibilitySpec]:
    """The SL501 proof surface: every observability-plane variant of the
    three ingest/step/chain kernels, the composed all-planes traces, and
    the workload generator's append-only obligation."""
    mod = "shadow_tpu.tpu.plane"
    wmod = "shadow_tpu.workloads.device"
    return [
        # the single-plane window/chain specs REUSE the SL2xx audit
        # builders outright (not merely equivalent copies): the shared
        # trace cache keys by entry name, so a same-named spec with a
        # different builder would silently win or lose the trace
        # depending on pass order — one builder per key removes the
        # ambiguity by construction
        InvisibilitySpec(
            "window_step[metrics]", mod,
            _plane_entry(True, True, False, telemetry=True),
            tainted_args={1: "metrics"}, protected=_protect_lead(3),
            trace_key="shadow_tpu.tpu.plane:window_step[telemetry]"),
        InvisibilitySpec(
            "window_step[guards]", mod,
            _plane_entry(True, True, False, guards=True),
            tainted_args={1: "guards"}, protected=_protect_lead(3)),
        InvisibilitySpec(
            "window_step[hist]", mod,
            _window_planes_entry(hist=True),
            tainted_args={1: "hist"}, protected=_protect_lead(3)),
        InvisibilitySpec(
            "window_step[flightrec]", mod,
            _window_planes_entry(flightrec=True),
            tainted_args={1: "flightrec"}, protected=_protect_lead(3)),
        InvisibilitySpec(
            "window_step[metrics+guards+hist+flightrec]", mod,
            _window_planes_entry(metrics=True, guards=True, hist=True,
                                 flightrec=True),
            tainted_args={1: "metrics", 2: "guards", 3: "hist",
                          4: "flightrec"},
            protected=_protect_lead(3)),
        InvisibilitySpec(
            "chain_windows[metrics]", mod,
            _chain_entry("metrics"),
            tainted_args={1: "metrics"}, protected=_protect_lead(5)),
        InvisibilitySpec(
            "chain_windows[guards]", mod,
            _chain_entry("guards"),
            tainted_args={1: "guards"}, protected=_protect_lead(5)),
        # the composed workload chain: metrics+guards thread through the
        # generator's own ingest_rows too — prove they stay invisible to
        # sim state AND to the workload state riding the same carry
        # (outputs: state, delivered, off, next_rel, n, m, g, ws)
        InvisibilitySpec(
            "chain_windows[workload+metrics+guards]", mod,
            _chain_planes_entry(metrics=True, guards=True,
                                workload=True),
            tainted_args={1: "metrics", 2: "guards"},
            protected=lambda idx, path: idx < 5 or idx == 7),
        InvisibilitySpec(
            "ingest_rows[metrics+guards+hist+flightrec]", mod,
            _ingest_rows_entry(),
            tainted_args={1: "metrics", 2: "guards", 3: "hist",
                          4: "flightrec"},
            protected=_protect_lead(1),
            trace_key="shadow_tpu.tpu.plane:ingest_rows[planes]"),
        InvisibilitySpec(
            "workload_step[append-only]", wmod,
            _workload_step_entry(),
            tainted_args={0: "ws", 1: "wl"},
            protected=_workload_protected),
        # the flow plane's obligations (docs/robustness.md "Flow
        # plane"): taint the per-flow state at the kernel boundary and
        # prove it can reach ONLY the egress append surface — the
        # machine theorem behind "flows=None worlds are untouched and
        # flows-on cannot perturb the wire"
        InvisibilitySpec(
            "window_step[flows]", "shadow_tpu.tpu.plane",
            _flows_entry("window"),
            tainted_args={1: "flows"},
            protected=_flows_window_protected),
        InvisibilitySpec(
            "flow_step[append-only]", "shadow_tpu.tpu.flows",
            _flows_entry("step"),
            tainted_args={0: "ft", 1: "fs"},
            protected=_flows_step_protected,
            trace_key="shadow_tpu.tpu.flows:flow_step"),
        # the compute plane's obligation (docs/workloads.md "Serving
        # load & the compute plane"): FULL invisibility, not append-
        # only — compute consumes the delivered dict read-only and owes
        # nothing back to the wire (credit gating composes in the
        # runner, outside this kernel), so taint on the ComputeState
        # input may reach ONLY the appended ComputeState output (idx 3)
        InvisibilitySpec(
            "window_step[compute]", "shadow_tpu.tpu.plane",
            _compute_entry("window"),
            tainted_args={1: "compute"},
            protected=_protect_lead(3)),
    ]


# --------------------------------------------------------------------------
# SL501 checker
# --------------------------------------------------------------------------


def _flat_len(tree) -> int:
    from jax import tree_util

    return len(tree_util.tree_flatten(tree)[0])


def check_invisibility(spec: InvisibilitySpec) -> list[Finding]:
    """Run one proof obligation; empty list = the theorem holds."""
    from .jaxpr_audit import traced

    closed, out_shape, args = traced(spec.cache_key, spec.build)

    in_labels: list[str | None] = []
    for i, arg in enumerate(args):
        prefix = spec.tainted_args.get(i)
        if prefix is None:
            in_labels.extend([None] * _flat_len(arg))
        else:
            in_labels.extend(leaf_paths(arg, prefix=prefix))
    if len(in_labels) != len(closed.in_avals):
        raise AssertionError(
            f"{spec.name}: flattened {len(in_labels)} arg leaves but "
            f"the jaxpr has {len(closed.in_avals)} inputs")

    out_labels = propagate_taint(closed, in_labels)
    out_paths = leaf_paths(out_shape)
    if len(out_paths) != len(out_labels):
        raise AssertionError(
            f"{spec.name}: {len(out_paths)} output paths vs "
            f"{len(out_labels)} output labels")

    where = f"{spec.module}:{spec.name}"
    findings = []
    for path, label in zip(out_paths, out_labels):
        if label is None:
            continue
        if spec.protected(_out_index(path), path):
            findings.append(Finding(
                "SL501", where, 0, 0,
                f"plane input `{label}` reaches sim-state output leaf "
                f"`{path}`: the presence switch is not "
                "bitwise-invisible (docs/determinism.md 'Proofs vs "
                "parity tests')"))
    return findings


def check_all_invisibility(
        specs: list[InvisibilitySpec] | None = None) -> list[Finding]:
    out: list[Finding] = []
    for spec in specs if specs is not None else invisibility_specs():
        out.extend(check_invisibility(spec))
    return out


# --------------------------------------------------------------------------
# SL502 op-budget ledger
# --------------------------------------------------------------------------

_BUDGET_FILE = "op_budgets.json"


def _traced(entry):
    """The shared per-process jaxpr memo (`jaxpr_audit.traced`): the
    SL2xx audit, SL501 proofs, SL502 census, SL504 report, and the
    SL505/SL506 provers all walk the same traced graphs — one full
    shadowlint run traces each audited entry once, not once per pass
    (the gating CI proof step's time budget rests on this)."""
    from .jaxpr_audit import traced

    return traced(f"{entry.module}:{entry.name}", entry.build)[0]


def budget_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        _BUDGET_FILE)


def compute_censuses(entries=None) -> dict[str, dict[str, int]]:
    """Static op census per audited entry (jaxpr_audit registry)."""
    from .jaxpr_audit import default_entries

    out: dict[str, dict[str, int]] = {}
    for entry in entries if entries is not None else default_entries():
        out[f"{entry.module}:{entry.name}"] = dict(
            sorted(op_census(_traced(entry)).items()))
    return out


def write_op_budgets(path: str | None = None, entries=None) -> dict:
    budgets = compute_censuses(entries)
    doc = {
        "_comment": (
            "SL502 op-budget ledger: the static count of expensive "
            "primitives per audited kernel entry. CI diffs the live "
            "census against this file — a changed count fails the "
            "build unless this file changes WITH it (regenerate via "
            "`python tools/shadowlint.py --write-op-budgets` and "
            "justify the delta in the PR)."),
        "version": 1,
        "budgets": budgets,
    }
    with open(path or budget_path(), "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return doc


def check_op_budgets(path: str | None = None, entries=None
                     ) -> tuple[list[Finding], list[dict]]:
    """Diff the live census against the checked-in ledger. Returns
    (findings, deltas) — deltas carry the per-primitive table the CLI
    renders on failure."""
    path = path or budget_path()
    if not os.path.exists(path):
        return [Finding(
            "SL502", path, 0, 0,
            "op-budget ledger missing: run `python tools/shadowlint.py "
            "--write-op-budgets` and check the file in")], []
    with open(path, encoding="utf-8") as fh:
        budgets = json.load(fh)["budgets"]
    actual = compute_censuses(entries)

    findings: list[Finding] = []
    deltas: list[dict] = []
    for name in sorted(set(budgets) | set(actual)):
        want = budgets.get(name)
        have = actual.get(name)
        if want is None:
            findings.append(Finding(
                "SL502", name, 0, 0,
                "audited entry has no op budget: regenerate the ledger "
                "(--write-op-budgets) so the new kernel's op count is "
                "pinned"))
            continue
        if have is None:
            findings.append(Finding(
                "SL502", name, 0, 0,
                "budgeted entry no longer audited: regenerate the "
                "ledger (--write-op-budgets) to drop it explicitly"))
            continue
        if want == have:
            continue
        diff = {}
        for prim in sorted(set(want) | set(have)):
            w, h = want.get(prim, 0), have.get(prim, 0)
            if w != h:
                diff[prim] = {"budget": w, "actual": h}
        deltas.append({"entry": name, "delta": diff})
        worst = max(diff, key=lambda p: abs(diff[p]["actual"]
                                            - diff[p]["budget"]))
        findings.append(Finding(
            "SL502", name, 0, 0,
            f"op census deviates from the checked-in budget ({worst}: "
            f"{diff[worst]['budget']} budgeted, "
            f"{diff[worst]['actual']} actual"
            + (f"; +{len(diff) - 1} more primitive(s)"
               if len(diff) > 1 else "")
            + ") — an expensive-primitive regression, or a ledger "
            "update missing from this diff (--write-op-budgets)"))
    return findings, deltas


def format_budget_delta(deltas: list[dict]) -> str:
    """Readable budget-vs-actual table for the CI log."""
    lines = ["entry                                    primitive"
             "            budget  actual   delta"]
    for d in deltas:
        for prim, v in sorted(d["delta"].items()):
            lines.append(
                f"{d['entry'][:40]:<40} {prim:<20} "
                f"{v['budget']:>6}  {v['actual']:>6}  "
                f"{v['actual'] - v['budget']:>+6}")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# SL504 shardability report
# --------------------------------------------------------------------------


def build_shard_report(entries=None) -> dict:
    """Per-entry shardability classification — the scoping work-list
    for the ROADMAP-2 shard_map refactor. Informational EXCEPT for the
    `ROW_LOCAL_PINNED` fence below."""
    from .jaxpr_audit import default_entries

    sections = {}
    for entry in entries if entries is not None else default_entries():
        sections[f"{entry.module}:{entry.name}"] = shard_census(
            _traced(entry))
    n_cross = sum(len(s["cross_host"]) for s in sections.values())
    return {
        "version": 1,
        "rule": "SL504",
        "summary": {
            "sections": len(sections),
            "cross_host_ops": n_cross,
            "opaque_kernels": sum(len(s["opaque"])
                                  for s in sections.values()),
        },
        "row_local_pinned": sorted(ROW_LOCAL_PINNED),
        "sections": sections,
    }


#: entries whose cross-host set is pinned EMPTY — the row-local stages
#: the ROADMAP-2 shard_map refactor relies on shard-for-free. A
#: cross-host primitive appearing in one of these is a sharding
#: regression fence, not a report line: SL504 GATES on it.
ROW_LOCAL_PINNED = frozenset({
    "shadow_tpu.tpu.tcp:tcp_event_step",
    "shadow_tpu.tpu.tcp:tcp_pull_step",
    "shadow_tpu.tpu.codel:codel_drain",
    "shadow_tpu.tpu.codel:router_drain",
})


def check_row_local_fence(report: dict | None = None) -> list[Finding]:
    """SL504's gating half: every `ROW_LOCAL_PINNED` entry must report
    an empty cross-host set (the regression fence for the ROADMAP-2
    shard_map cut — these stages shard for free today and must stay
    that way). Without a pre-built report, only the pinned entries are
    traced/classified (the fast gating path; `--shard-report` still
    emits the full registry)."""
    if report is None:
        from .jaxpr_audit import default_entries

        pinned = [e for e in default_entries()
                  if f"{e.module}:{e.name}" in ROW_LOCAL_PINNED]
        report = build_shard_report(pinned)
    findings: list[Finding] = []
    for key in sorted(ROW_LOCAL_PINNED):
        section = report["sections"].get(key)
        if section is None:
            findings.append(Finding(
                "SL504", key, 0, 0,
                "row-local-pinned entry missing from the audit "
                "registry: the shard fence cannot check it"))
            continue
        for oc in section["cross_host"]:
            findings.append(Finding(
                "SL504", key, 0, 0,
                f"cross-host `{oc['primitive']}` in a row-local-pinned "
                f"stage ({oc['reason']}; shapes {oc['shapes']}): a "
                "sharding regression — this stage must stay "
                "host-axis-local for the ROADMAP-2 shard_map cut"))
    return findings
