"""SL506: integer range / bit-budget abstract interpretation.

Every "no overflow because both < I32_MAX//2" comment in
`tpu/plane.py` / `tpu/flows.py` is a hand-reasoned interval argument.
This pass mechanizes it: a forward interval analysis over the SAME
traced jaxprs the SL2xx/SL501/SL502 passes audit, seeded from a
checked-in **input-domain registry** (`range_specs()` — window_ns <=
I32_MAX//4, wire bytes <= the 2^24 budget, deliver offsets within the
int32-ns wire budget, ...), with:

- exact transfer functions for the integer arithmetic the plane uses
  (add/sub/mul/neg/abs/div/rem/min/max/clamp/select/cumsum/
  reduce_sum/scatter-add/shifts), value-preserving joins for the
  selection ops (sort/gather/slice/concatenate/...), and descent into
  every control-flow sub-jaxpr;
- ``while``-loop carry fixpoints with **predicate refinement**: the
  loop condition's conjunctive comparisons narrow the carry intervals
  inside the body (and one step backward through add/sub producers),
  which is exactly how the `chain_windows` hand-proof works — `off`
  and `next_ev` both stay `< I32_MAX//2` BECAUSE the loop only
  continues while `next_ev < hs - off` — so that comment becomes a
  machine-checked theorem instead of prose;
- **declared-modular** leaves (`rng_counter`, metrics/histogram
  counters, the flow plane's segment indices and ms clock, RR
  virtual-finish counters): int32 counters that wrap BY CONTRACT (the
  harvester delta-unwraps them); arithmetic fed by a modular value is
  wrap-exempt and stays modular;
- an explicit per-entry ``allow`` list (substring match, justification
  mandatory) for wraps that are real but harmless by the masking
  discipline — every consumer masks the affected lanes by validity —
  mirroring the SL2xx audit allow-lists.

The build FAILS (SL506 finding) on any non-exempt signed-integer op
whose computed interval admits wraparound, naming the op, its nesting
path, and the computed interval. Everything else lands in the
``--range-report`` artifact: per-entry output-leaf interval tables,
the assumption inventory (domains, modular leaves, allows), and the
primitives the analysis did not model (conservative full-range).

Caveat recorded in the report: intervals are computed on the audit
registry's representative shapes — prefix-sum and reduction factors
scale with ring capacity, so the shape-dependent budgets (e.g.
egress_cap * max_bytes < 2^31 for the token-gate cumsum) are enforced
separately at config/compile time (workloads/spec.py, plane.make_params).
"""

from __future__ import annotations

import fnmatch
from dataclasses import dataclass, field

import numpy as np

from .rules import Finding

try:
    from jax.extend import core as _core
except ImportError:  # older jax spells it jax.core
    from jax import core as _core

__all__ = [
    "IVal",
    "RangeSpec",
    "analyze_entry",
    "check_all_ranges",
    "range_specs",
]

I32_MAX = 2**31 - 1
I32_MIN = -(2**31)

#: fixpoint budget before widening a while/scan carry slot to its
#: dtype range (taint-free analogue of dataflow._fixpoint; intervals
#: can climb forever, so widening is load-bearing here)
_WIDEN_AT = 6
_MAX_ITERS = 10


# --------------------------------------------------------------------------
# the interval value
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class IVal:
    """[lo, hi] over mathematical integers, plus the wrap-exemption
    flag (modular counters wrap by contract)."""

    lo: int
    hi: int
    modular: bool = False

    def join(self, other: "IVal") -> "IVal":
        return IVal(min(self.lo, other.lo), max(self.hi, other.hi),
                    self.modular or other.modular)


_SIGNED_RANGES = {
    "int8": (-(2**7), 2**7 - 1),
    "int16": (-(2**15), 2**15 - 1),
    "int32": (I32_MIN, I32_MAX),
    "int64": (-(2**63), 2**63 - 1),
}
_UNSIGNED_RANGES = {
    "uint8": (0, 2**8 - 1),
    "uint16": (0, 2**16 - 1),
    "uint32": (0, 2**32 - 1),
    "uint64": (0, 2**64 - 1),
}


def _dtype_str(aval) -> str:
    return str(getattr(aval, "dtype", ""))


def _int_range(dt: str):
    if dt == "bool":
        return (0, 1)
    return _SIGNED_RANGES.get(dt) or _UNSIGNED_RANGES.get(dt)


def _default_ival(aval) -> IVal | None:
    """Conservative value for an unseeded/unmodeled output: the dtype
    range for integers/bools, untracked (None) for floats/keys."""
    rng = _int_range(_dtype_str(aval))
    return IVal(*rng) if rng is not None else None


def _const_ival(value) -> IVal | None:
    try:
        arr = np.asarray(value)
    except TypeError:  # extended dtypes (PRNG keys) refuse conversion
        return None
    if arr.dtype == np.bool_:
        return IVal(0, 1)
    if not np.issubdtype(arr.dtype, np.integer):
        return None
    if arr.size == 0:
        return IVal(0, 0)
    return IVal(int(arr.min()), int(arr.max()))


def _div_trunc(a: int, b: int) -> int:
    """C-style truncating division (lax.div semantics)."""
    q = abs(a) // abs(b)
    return q if (a >= 0) == (b >= 0) else -q


# --------------------------------------------------------------------------
# the per-entry spec (the checked-in domain registry)
# --------------------------------------------------------------------------


@dataclass
class RangeSpec:
    """One analyzed entry: the audit-registry key it traces, a name
    prefix per positional argument, and the input-domain registry.

    ``domains`` maps fnmatch patterns over input leaf paths to
    (lo, hi, justification); ``modular`` marks wrap-exempt counter
    leaves; ``allow`` suppresses named residual findings (substring
    match against the finding message) with a mandatory justification.
    Unlisted integer leaves default to their FULL dtype range — the
    conservative choice that forces every assumption to be written
    down here."""

    key: str  # "module:name" in the jaxpr-audit registry
    arg_names: list[str]
    domains: dict[str, tuple[int, int, str]] = field(default_factory=dict)
    modular: dict[str, str] = field(default_factory=dict)
    allow: dict[str, str] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.key.split(":", 1)[1]

    @property
    def module(self) -> str:
        return self.key.split(":", 1)[0]


# --------------------------------------------------------------------------
# transfer functions
# --------------------------------------------------------------------------

#: value-preserving ops: outputs are copies/permutations of the first
#: (data) operand's elements
_PASS_FIRST = frozenset({
    "reshape", "broadcast_in_dim", "transpose", "slice", "squeeze",
    "rev", "expand_dims", "copy", "dynamic_slice", "reduce_max",
    "reduce_min", "cummax", "cummin", "stop_gradient", "device_put",
    "reduce_precision", "real", "copy_p",
})

#: ops whose outputs join ALL integer operands' values
_JOIN_ALL = frozenset({
    "concatenate", "pad", "dynamic_update_slice", "scatter",
    "scatter-max", "scatter-min", "clamp_deprecated",
})

#: silently-opaque primitives: known to produce untracked/full-range
#: outputs by design (no "unmodeled" note)
_KNOWN_OPAQUE = frozenset({
    "threefry2x32", "random_bits", "random_seed", "random_wrap",
    "random_fold_in",
})

_CALL_LIKE = ("pjit", "closed_call", "core_call", "remat", "checkpoint",
              "custom_jvp_call", "custom_vjp_call",
              "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr")

_SUB_JAXPR_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr")


def _first_sub_jaxpr(params):
    for key in _SUB_JAXPR_KEYS:
        sub = params.get(key)
        if sub is not None:
            return sub
    return None


def _mul_bounds(a: IVal, b: IVal):
    cands = [a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi]
    return min(cands), max(cands)


def _axis_factor(eqn, axes) -> int:
    shape = tuple(getattr(eqn.invars[0].aval, "shape", ()))
    f = 1
    for a in axes:
        f *= int(shape[a]) if a < len(shape) else 1
    return max(f, 1)


class _Analysis:
    """One entry's walk: env of IVals, findings, report notes."""

    def __init__(self, spec: RangeSpec):
        self.spec = spec
        self.findings: list[Finding] = []
        self.unmodeled: dict[str, int] = {}
        self.opaque: list[str] = []
        self.gather_fills = 0
        self._seen: set[str] = set()
        self.quiet = 0  # >0 while iterating a fixpoint

    # -- finding emission --------------------------------------------------

    def emit(self, eqn, path: str, lo: int, hi: int, ins):
        if self.quiet:
            return
        name = eqn.primitive.name
        opnds = ", ".join(
            f"[{v.lo}, {v.hi}]" if v is not None else "?" for v in ins)
        dt = _dtype_str(eqn.outvars[0].aval)
        src = _source_line(eqn)
        msg = (f"int32 `{name}` admits wraparound at {path}"
               f"{f' ({src})' if src else ''}: computed "
               f"interval [{lo}, {hi}] exceeds {dt} (operands {opnds})"
               " — widen the guard, clamp the domain, or declare the "
               "feeding counter modular (analysis/ranges.py registry)")
        if msg in self._seen:
            return
        self._seen.add(msg)
        where = f"{self.spec.module}:{self.spec.name}"
        f = Finding("SL506", where, 0, 0, msg)
        for pat, why in self.spec.allow.items():
            if pat in msg:
                f.suppressed = True
                f.justification = why
                break
        self.findings.append(f)

    def note_unmodeled(self, name: str):
        if not self.quiet:
            self.unmodeled[name] = self.unmodeled.get(name, 0) + 1

    # -- one equation ------------------------------------------------------

    def eval_eqn(self, eqn, ins: list[IVal | None], path: str
                 ) -> list[IVal | None]:
        name = eqn.primitive.name
        params = eqn.params
        out_avals = [v.aval for v in eqn.outvars]
        n_out = len(out_avals)

        def default_outs():
            return [_default_ival(a) for a in out_avals]

        def mod_any():
            return any(v is not None and v.modular for v in ins)

        def checked(lo: int, hi: int, *, aval=None) -> IVal:
            """Signed-int arithmetic result: finding on wrap unless a
            modular operand exempts it."""
            aval = aval if aval is not None else out_avals[0]
            dt = _dtype_str(aval)
            rng = _SIGNED_RANGES.get(dt)
            if rng is None:  # unsigned/float result: untracked wrap-ok
                full = _int_range(dt)
                return IVal(*full, modular=mod_any()) if full else None
            if mod_any():
                return IVal(*rng, modular=True)
            if lo < rng[0] or hi > rng[1]:
                self.emit(eqn, path, lo, hi, ins)
                return IVal(*rng)
            return IVal(lo, hi)

        def v(i) -> IVal:
            val = ins[i]
            return val if val is not None else (
                _default_ival(eqn.invars[i].aval) or IVal(I32_MIN,
                                                          I32_MAX))

        # control flow -----------------------------------------------------
        if name in _CALL_LIKE:
            tag0 = params.get("name")
            if tag0 == "searchsorted":
                # modeled library call: insertion indices lie in
                # [0, len(sorted)] — descending into its binary-search
                # scan (uint32 midpoint tricks) would only add noise
                length = int(tuple(getattr(
                    eqn.invars[0].aval, "shape", (0,)))[-1] or 0)
                return [IVal(0, length)] * n_out
            if tag0 == "floor_divide" and len(ins) == 2:
                # modeled library call: jnp's floor-divide wraps lax.div
                # in a sign-correction select whose untaken q-1 arm
                # would otherwise join into the interval
                a, b = v(0), v(1)
                if (b.lo >= 1 or b.hi <= -1) and _int_range(
                        _dtype_str(out_avals[0])):
                    cands = [x // y for x in (a.lo, a.hi)
                             for y in (b.lo, b.hi)]
                    return [checked(min(cands), max(cands))]
            if tag0 == "clip" and len(ins) == 3 and _int_range(
                    _dtype_str(out_avals[0])):
                # modeled library call: jnp.clip traces as
                # pjit(max-then-min), and like the clamp primitive it
                # pins its output into the bound operands' range for
                # ANY input — including a wrapped modular counter —
                # so the clipped value re-enters ordinary checked
                # arithmetic (this is what makes the flow plane's
                # `clip(deadline - clock, 0, budget)` wake/RTO paths
                # genuinely proven instead of modular-exempt)
                x, lo_op, hi_op = v(0), v(1), v(2)
                return [IVal(min(max(x.lo, lo_op.lo), hi_op.lo),
                             min(max(x.hi, lo_op.hi), hi_op.hi),
                             modular=lo_op.modular or hi_op.modular)]
            if tag0 in ("remainder", "mod") and len(ins) == 2:
                # floor-mod: the result's sign follows the divisor
                b = v(1)
                if b.lo >= 1 and _int_range(_dtype_str(out_avals[0])):
                    return [IVal(0, b.hi - 1, modular=mod_any())]
                if b.hi <= -1 and _int_range(_dtype_str(out_avals[0])):
                    return [IVal(b.lo + 1, 0, modular=mod_any())]
            sub = _first_sub_jaxpr(params)
            if sub is not None and len(_raw(sub).invars) == len(ins):
                tag = params.get("name") or name
                outs = self.run(sub, ins, path + f"/{tag}")
                return (outs + default_outs())[:n_out]
            self.note_unmodeled(name)
            return default_outs()
        if name == "cond":
            outs = None
            for i, branch in enumerate(params["branches"]):
                b_outs = self.run(branch, ins[1:],
                                  path + f"/cond.b{i}")
                outs = b_outs if outs is None else [
                    (a.join(b) if a is not None and b is not None
                     else None)
                    for a, b in zip(outs, b_outs)]
            return outs if outs is not None else default_outs()
        if name == "while":
            return self._while(eqn, ins, path)
        if name == "scan":
            return self._scan(eqn, ins, path)
        if name == "pallas_call":
            if not self.quiet:
                self.opaque.append(path + "/pallas_call")
            return default_outs()

        # arithmetic (checked) --------------------------------------------
        if name == "add":
            a, b = v(0), v(1)
            return [checked(a.lo + b.lo, a.hi + b.hi)]
        if name == "sub":
            a, b = v(0), v(1)
            return [checked(a.lo - b.hi, a.hi - b.lo)]
        if name == "mul":
            return [checked(*_mul_bounds(v(0), v(1)))]
        if name == "neg":
            a = v(0)
            return [checked(-a.hi, -a.lo)]
        if name == "abs":
            a = v(0)
            return [checked(max(0, a.lo, -a.hi)
                            if a.lo > 0 or a.hi < 0 else 0,
                            max(abs(a.lo), abs(a.hi)))]
        if name == "integer_pow":
            a, y = v(0), int(params.get("y", 1))
            cands = [a.lo**y, a.hi**y] + ([0] if a.lo < 0 < a.hi else [])
            return [checked(min(cands), max(cands))]
        if name == "div":
            a, b = v(0), v(1)
            if b.lo >= 1 or b.hi <= -1:
                cands = [_div_trunc(x, y) for x in (a.lo, a.hi)
                         for y in (b.lo, b.hi)]
                # only the INT_MIN / -1 corner can wrap
                return [checked(min(cands), max(cands))]
            return default_outs()
        if name == "rem":
            a, b = v(0), v(1)
            if b.lo >= 1 or b.hi <= -1:
                m = max(abs(b.lo), abs(b.hi)) - 1
                return [IVal(-m if a.lo < 0 else 0,
                             m if a.hi > 0 else 0, modular=mod_any())]
            return default_outs()
        if name == "shift_left":
            a, k = v(0), v(1)
            kh = min(max(k.hi, 0), 63)
            cands = [a.lo << kh, a.hi << kh, a.lo, a.hi]
            return [checked(min(cands), max(cands))]
        if name in ("shift_right_arithmetic", "shift_right_logical"):
            a = v(0)
            if a.lo >= 0:
                return [IVal(0, a.hi, modular=mod_any())]
            if name == "shift_right_arithmetic":
                return [IVal(min(a.lo, 0), max(a.hi, 0),
                             modular=mod_any())]
            return default_outs()
        if name == "cumsum":
            a = v(0)
            f = _axis_factor(eqn, (params.get("axis", 0),))
            return [checked(min(a.lo, f * a.lo), max(a.hi, f * a.hi))]
        if name == "cumprod":
            a = v(0)
            if 0 <= a.lo and a.hi <= 1:
                # the rcv_bits leading-run trick: products of 0/1 stay
                # 0/1 for any prefix length
                return [IVal(0, 1, modular=a.modular)]
            self.note_unmodeled(name)
            return default_outs()
        if name == "reduce_sum":
            a = v(0)
            f = _axis_factor(eqn, tuple(params.get("axes", ())))
            return [checked(f * a.lo, f * a.hi)]
        if name == "reduce_prod":
            self.note_unmodeled(name)
            return default_outs()
        if name.startswith("scatter-add") or name == "scatter_add":
            a, upd = v(0), v(2) if len(ins) > 2 else v(-1)
            n_upd = int(np.prod(
                tuple(getattr(eqn.invars[-1].aval, "shape", ())) or (1,),
                dtype=np.int64))
            return [checked(a.lo + n_upd * min(0, upd.lo),
                            a.hi + n_upd * max(0, upd.hi))]
        if name.startswith("scatter-mul"):
            self.note_unmodeled(name)
            return default_outs()

        # exact non-wrapping integer ops ----------------------------------
        if name in ("max", "min"):
            a, b = v(0), v(1)
            pick = max if name == "max" else min
            return [IVal(pick(a.lo, b.lo), pick(a.hi, b.hi),
                         modular=mod_any())]
        if name == "clamp":
            # clamp = min(max(x, lo), hi), monotone in EACH argument:
            # the result bounds use each operand's matching bound
            lo_op, x, hi_op = v(0), v(1), v(2)
            lo = min(max(x.lo, lo_op.lo), hi_op.lo)
            hi = min(max(x.hi, lo_op.hi), hi_op.hi)
            # a clamp PINS its output into [lo_op.lo, hi_op.hi] for ANY
            # input value — including a wrapped modular counter — so the
            # clamped VALUE is no longer wrap-exempt: downstream
            # arithmetic on it is ordinary bounded arithmetic and must
            # be checked (this is what makes the flow plane's
            # `clip(deadline - clock, 0, budget)` launder its modular
            # clock into a genuinely proven wake computation)
            return [IVal(lo, hi,
                         modular=lo_op.modular or hi_op.modular)]
        if name == "select_n":
            cases = [x for x in ins[1:] if x is not None]
            if len(cases) != len(ins) - 1:
                return default_outs()
            out = cases[0]
            for c in cases[1:]:
                out = out.join(c)
            return [out]
        if name == "sort":
            # per-operand permutation: output k carries operand k's
            # values
            return [ins[i] if ins[i] is not None
                    else _default_ival(out_avals[i])
                    for i in range(n_out)]
        if name == "gather":
            # OOB fills are assumed unreachable (recorded in the
            # report): the plane's gather indices are ranks/clipped
            # ids bounded by construction, and joining every
            # take_along_axis fill sentinel (-2^31) would reduce the
            # whole analysis to noise
            if params.get("fill_value") is not None and not self.quiet:
                self.gather_fills += 1
            a = v(0)
            return [IVal(a.lo, a.hi, modular=a.modular)]
        if name in _JOIN_ALL:
            vals = [x for i, x in enumerate(ins)
                    if x is not None
                    and _int_range(_dtype_str(eqn.invars[i].aval))]
            if not vals:
                return default_outs()
            out = vals[0]
            for x in vals[1:]:
                out = out.join(x)
            return [out]
        if name in _PASS_FIRST:
            if ins and ins[0] is not None:
                return [ins[0]] * n_out
            return default_outs()
        if name == "iota":
            dim = params.get("dimension", 0)
            shape = tuple(params.get("shape", ()))
            hi = int(shape[dim]) - 1 if shape else 0
            return [IVal(0, max(hi, 0))]
        if name in ("argmax", "argmin"):
            shape = tuple(getattr(eqn.invars[0].aval, "shape", ()))
            return [IVal(0, max((max(shape) - 1) if shape else 0, 0))]
        if name in ("reduce_or", "reduce_and", "eq", "ne", "lt", "le",
                    "gt", "ge", "is_finite", "not", "xor_bool",
                    "le_to", "lt_to"):
            if _dtype_str(out_avals[0]) == "bool":
                return [IVal(0, 1)]
            return default_outs()
        if name in ("and", "or", "xor"):
            if _dtype_str(out_avals[0]) == "bool":
                return [IVal(0, 1)]
            a, b = v(0), v(1)
            if a.lo >= 0 and b.lo >= 0:
                if name == "and":
                    return [IVal(0, min(a.hi, b.hi),
                                 modular=mod_any())]
                hi = (1 << max(a.hi, b.hi).bit_length()) - 1
                return [IVal(0, hi, modular=mod_any())]
            return default_outs()
        if name == "sign":
            return [IVal(-1, 1)]
        if name in ("population_count", "clz"):
            return [IVal(0, 64)]
        if name == "convert_element_type":
            src_dt = _dtype_str(eqn.invars[0].aval)
            dst_dt = _dtype_str(out_avals[0])
            dst = _int_range(dst_dt)
            if dst is None:
                return [None]
            a = ins[0]
            if a is None or _int_range(src_dt) is None:
                return [IVal(*dst)]
            if dst[0] <= a.lo and a.hi <= dst[1]:
                return [IVal(a.lo, a.hi, modular=a.modular)]
            # narrowing reinterpretation: wraps by design (the packed
            # uint32 sort-key discipline) — full range, never a finding
            return [IVal(*dst, modular=a.modular)]
        if name in _KNOWN_OPAQUE:
            return default_outs()

        self.note_unmodeled(name)
        return default_outs()

    # -- jaxpr walk --------------------------------------------------------

    def run(self, jaxpr_like, in_vals, path: str):
        raw = _raw(jaxpr_like)
        consts = list(getattr(jaxpr_like, "consts", []))
        env: dict = {}

        def read(var):
            if isinstance(var, _core.Literal):
                return _const_ival(var.val)
            return env.get(var)

        for var, const in zip(raw.constvars, consts):
            env[var] = _const_ival(const)
        for var, val in zip(raw.invars, in_vals):
            env[var] = val
        for eqn in raw.eqns:
            ins = [read(v) for v in eqn.invars]
            outs = self.eval_eqn(eqn, ins, path)
            for var, out in zip(eqn.outvars, outs):
                env[var] = out
        return [read(v) for v in raw.outvars]

    # -- while / scan ------------------------------------------------------

    def _refine_by_cond(self, cond_jaxpr, cond_ins):
        """Evaluate the loop condition and narrow the carried intervals
        by its conjunctive comparisons (the predicate-refinement that
        turns `while next_ev < hs - off` into interval facts). Returns
        the refined copies of `cond_ins`."""
        raw = _raw(cond_jaxpr)
        consts = list(getattr(cond_jaxpr, "consts", []))
        env: dict = {}
        producers: dict = {}

        def read(var):
            if isinstance(var, _core.Literal):
                return _const_ival(var.val)
            return env.get(var)

        for var, const in zip(raw.constvars, consts):
            env[var] = _const_ival(const)
        for var, val in zip(raw.invars, cond_ins):
            env[var] = val
        self.quiet += 1
        try:
            for eqn in raw.eqns:
                ins = [read(v) for v in eqn.invars]
                outs = self.eval_eqn(eqn, ins, "cond")
                for var, out in zip(eqn.outvars, outs):
                    env[var] = out
                    producers[var] = eqn
        finally:
            self.quiet -= 1

        def narrow(var, lo=None, hi=None, depth=0):
            if isinstance(var, _core.Literal) or depth > 3:
                return
            cur = env.get(var)
            if cur is None:
                return
            new_lo = max(cur.lo, lo) if lo is not None else cur.lo
            new_hi = min(cur.hi, hi) if hi is not None else cur.hi
            if new_lo > new_hi or (new_lo == cur.lo
                                   and new_hi == cur.hi):
                return
            env[var] = IVal(new_lo, new_hi, cur.modular)
            eqn = producers.get(var)
            if eqn is None:
                return
            name = eqn.primitive.name
            if name == "convert_element_type":
                narrow(eqn.invars[0], lo, hi, depth + 1)
            elif name == "add" and len(eqn.invars) == 2:
                p, q = eqn.invars
                pv, qv = read(p), read(q)
                if pv is None or qv is None:
                    return
                if hi is not None:
                    narrow(p, hi=hi - qv.lo, depth=depth + 1)
                    narrow(q, hi=hi - pv.lo, depth=depth + 1)
                if lo is not None:
                    narrow(p, lo=lo - qv.hi, depth=depth + 1)
                    narrow(q, lo=lo - pv.hi, depth=depth + 1)
            elif name == "sub" and len(eqn.invars) == 2:
                p, q = eqn.invars
                pv, qv = read(p), read(q)
                if pv is None or qv is None:
                    return
                if hi is not None:
                    narrow(p, hi=hi + qv.hi, depth=depth + 1)
                    narrow(q, lo=pv.lo - hi, depth=depth + 1)
                if lo is not None:
                    narrow(p, lo=lo + qv.lo, depth=depth + 1)
                    narrow(q, hi=pv.hi - lo, depth=depth + 1)
            elif name == "min":
                if lo is not None:
                    for op in eqn.invars:
                        narrow(op, lo=lo, depth=depth + 1)
            elif name == "max":
                if hi is not None:
                    for op in eqn.invars:
                        narrow(op, hi=hi, depth=depth + 1)

        # conjuncts: walk back from the output through and/reduce_and
        stack = [raw.outvars[0]]
        seen: set = set()
        while stack:
            var = stack.pop()
            if isinstance(var, _core.Literal) or id(var) in seen:
                continue
            seen.add(id(var))
            eqn = producers.get(var)
            if eqn is None:
                continue
            name = eqn.primitive.name
            if name in ("and", "reduce_and", "convert_element_type"):
                stack.extend(eqn.invars)
            elif name in ("lt", "le", "gt", "ge"):
                x, y = eqn.invars
                if name in ("gt", "ge"):  # x > y == y < x
                    x, y = y, x
                xv, yv = read(x), read(y)
                off = 1 if name in ("lt", "gt") else 0
                if yv is not None:
                    narrow(x, hi=yv.hi - off)
                if xv is not None:
                    narrow(y, lo=xv.lo + off)
        return [read(v) for v in raw.invars]

    def _while(self, eqn, ins, path: str):
        params = eqn.params
        cn, bn = params["cond_nconsts"], params["body_nconsts"]
        cond_c, body_c = ins[:cn], ins[cn:cn + bn]
        carry = list(ins[cn + bn:])
        body = params["body_jaxpr"]
        cond = params["cond_jaxpr"]
        # a value the predicate constrains may enter the BODY as a
        # body-const under a different position: map refined cond
        # consts back to the body consts sharing the same parent var
        cond_vars = eqn.invars[:cn]
        body_vars = eqn.invars[cn:cn + bn]

        def body_ins(refined_all, carry_ref):
            by_parent = {id(v): r for v, r in
                         zip(cond_vars, refined_all[:cn])}
            consts = [by_parent.get(id(v), orig)
                      for v, orig in zip(body_vars, body_c)]
            return consts + carry_ref

        self.quiet += 1
        try:
            for it in range(_MAX_ITERS):
                refined_all = self._refine_by_cond(
                    cond, list(cond_c) + carry)
                outs = self.run(body,
                                body_ins(refined_all,
                                         refined_all[cn:]),
                                path + "/while")
                new = []
                changed = False
                for c, o, var in zip(carry, outs,
                                     _raw(body).outvars):
                    if c is None or o is None:
                        new.append(None)
                        continue
                    j = c.join(o)
                    if j != c:
                        changed = True
                        if it >= _WIDEN_AT:
                            rng = _int_range(_dtype_str(var.aval)) \
                                or (I32_MIN, I32_MAX)
                            j = IVal(*rng, modular=j.modular)
                    new.append(j)
                carry = new
                if not changed:
                    break
        finally:
            self.quiet -= 1
        # final reporting passes with the converged carry: the body
        # refined by the predicate (its arithmetic runs only while it
        # holds) AND the condition jaxpr itself, unrefined — the
        # predicate's own arithmetic executes on every entry
        refined_all = self._refine_by_cond(cond, list(cond_c) + carry)
        self.run(body, body_ins(refined_all, refined_all[cn:]),
                 path + "/while")
        self.run(cond, list(cond_c) + carry, path + "/while.cond")
        return carry

    def _scan(self, eqn, ins, path: str):
        params = eqn.params
        nc, ncar = params["num_consts"], params["num_carry"]
        consts, carry = ins[:nc], list(ins[nc:nc + ncar])
        xs = ins[nc + ncar:]
        body = params["jaxpr"]

        length = params.get("length")
        if length is not None and 0 < length <= 64:
            # exact unroll: scans are bounded (searchsorted bit steps,
            # the codel micro-step trace) — iterating the body
            # `length` times keeps loop-local counters precise where a
            # widened fixpoint would flood the report
            ys = None
            for _ in range(int(length)):
                outs = self.run(body, list(consts) + carry + list(xs),
                                path + "/scan")
                carry = outs[:ncar]
                tail = outs[ncar:]
                ys = tail if ys is None else [
                    (a.join(b) if a is not None and b is not None
                     else None) for a, b in zip(ys, tail)]
            return carry + (ys if ys is not None else [])

        self.quiet += 1
        try:
            for it in range(_MAX_ITERS):
                outs = self.run(body, list(consts) + carry + list(xs),
                                path + "/scan")[:ncar]
                new = []
                changed = False
                for c, o, var in zip(carry, outs,
                                     _raw(body).outvars[:ncar]):
                    if c is None or o is None:
                        new.append(None)
                        continue
                    j = c.join(o)
                    if j != c:
                        changed = True
                        if it >= _WIDEN_AT:
                            rng = _int_range(_dtype_str(var.aval)) \
                                or (I32_MIN, I32_MAX)
                            j = IVal(*rng, modular=j.modular)
                    new.append(j)
                carry = new
                if not changed:
                    break
        finally:
            self.quiet -= 1
        outs = self.run(body, list(consts) + carry + list(xs),
                        path + "/scan")
        return carry + outs[ncar:]


def _raw(jaxpr_like):
    return getattr(jaxpr_like, "jaxpr", jaxpr_like)


def _source_line(eqn) -> str:
    """Best-effort shadow_tpu/ file:line of the offending op (jax
    records a user traceback per equation; fall back silently when the
    private helper moves)."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return ""
        fname = frame.file_name.replace("\\", "/")
        if "shadow_tpu/" in fname:
            fname = "shadow_tpu/" + fname.split("shadow_tpu/", 1)[1]
        func = getattr(frame, "function_name", "")
        # the function name is the STABLE anchor for allow patterns
        # (line numbers drift with unrelated edits)
        return f"{fname}:{frame.start_line}" + (f" in {func}()"
                                                if func else "")
    except Exception:
        return ""


# --------------------------------------------------------------------------
# entry analysis
# --------------------------------------------------------------------------


def _seed_inputs(spec: RangeSpec, args) -> tuple[list, list[str]]:
    """Per-leaf IVals for the entry arguments, from the domain
    registry. Returns (ivals, notes) — notes record which pattern
    seeded which leaf for the report."""
    from jax import tree_util

    from .dataflow import leaf_paths

    if len(spec.arg_names) != len(args):
        raise ValueError(
            f"{spec.key}: arg_names has {len(spec.arg_names)} entries "
            f"but the audit builder produced {len(args)} args")
    ivals: list = []
    notes: list[str] = []
    for name, arg in zip(spec.arg_names, args):
        paths = leaf_paths(arg, prefix=name)
        leaves = tree_util.tree_leaves(arg)
        for path, leaf in zip(paths, leaves):
            aval_dt = str(np.asarray(leaf).dtype) \
                if not hasattr(leaf, "dtype") else str(leaf.dtype)
            if _int_range(aval_dt) is None:
                ivals.append(None)
                continue
            # exact match first: dict-key paths like
            # `delivered['sock']` contain fnmatch character classes.
            # modular wins over a domain pattern covering the same
            # leaf (a bounded modular counter is still wrap-exempt)
            matched = None
            for pat, _why in spec.modular.items():
                if path == pat or fnmatch.fnmatch(path, pat):
                    matched = IVal(*_int_range(aval_dt), modular=True)
                    notes.append(f"{path}: modular ({pat})")
                    break
            if matched is None:
                for pat, (lo, hi, _why) in spec.domains.items():
                    if path == pat or fnmatch.fnmatch(path, pat):
                        matched = IVal(lo, hi)
                        notes.append(
                            f"{path}: [{lo}, {hi}] (domain {pat})")
                        break
            if matched is None:
                matched = IVal(*_int_range(aval_dt))
                notes.append(f"{path}: full {aval_dt} (unseeded)")
            ivals.append(matched)
    return ivals, notes


def analyze_entry(spec: RangeSpec, *, trace=None, args=None,
                  out_shape=None) -> tuple[list[Finding], dict]:
    """Run one entry's interval analysis. Returns (findings, report
    section). `trace`/`args` short-circuit the build (the shared
    proof-pass trace cache)."""
    if trace is None or args is None:
        from .jaxpr_audit import default_entries, traced

        entry = next(e for e in default_entries()
                     if f"{e.module}:{e.name}" == spec.key)
        trace, out_shape, args = traced(spec.key, entry.build)

    in_vals, notes = _seed_inputs(spec, args)
    raw = _raw(trace)
    if len(in_vals) != len(raw.invars):
        raise AssertionError(
            f"{spec.key}: {len(in_vals)} seeded leaves vs "
            f"{len(raw.invars)} jaxpr inputs")
    ana = _Analysis(spec)
    outs = ana.run(trace, in_vals, spec.name)

    out_paths = None
    if out_shape is not None:
        from .dataflow import leaf_paths

        out_paths = leaf_paths(out_shape)
    table = {}
    for i, val in enumerate(outs):
        key = out_paths[i] if out_paths and i < len(out_paths) \
            else f"out[{i}]"
        table[key] = (None if val is None else
                      [val.lo, val.hi] + (["modular"] if val.modular
                                          else []))
    report = {
        "entry": spec.key,
        "outputs": table,
        "seeds": notes,
        "assumptions": {pat: why for pat, (_l, _h, why)
                        in spec.domains.items()},
        "modular": dict(spec.modular),
        "allow": dict(spec.allow),
        "unmodeled": dict(sorted(ana.unmodeled.items())),
        "gather_fills_assumed_unreachable": ana.gather_fills,
        "opaque": ana.opaque,
        "findings": [f.message for f in ana.findings
                     if not f.suppressed],
        "suppressed": [f.message for f in ana.findings if f.suppressed],
    }
    return ana.findings, report


def check_all_ranges(specs=None) -> tuple[list[Finding], dict]:
    findings: list[Finding] = []
    sections = []
    for spec in (specs if specs is not None else range_specs()):
        f, report = analyze_entry(spec)
        findings.extend(f)
        sections.append(report)
    report = {
        "version": 1,
        "rule": "SL506",
        "caveat": ("intervals computed on the audit registry's "
                   "representative shapes; shape-scaled budgets "
                   "(capacity x max-bytes prefix sums) are enforced "
                   "at config/compile time"),
        "entries": sections,
        "summary": {
            "entries": len(sections),
            "active_findings": sum(1 for f in findings
                                   if not f.suppressed),
            "suppressed_findings": sum(1 for f in findings
                                       if f.suppressed),
        },
    }
    return findings, report


# --------------------------------------------------------------------------
# the checked-in domain registry
# --------------------------------------------------------------------------

_B = I32_MAX  # shorthand

#: the wire-size budget: spec.py caps message/pattern byte knobs so
#: capacity-scaled prefix sums (token gate cumsum, byte counters) stay
#: inside int32 at every supported ring size
BYTES_BUDGET = 1 << 24

_WHY_TSEND = ("rebased send times: a queued packet's tsend drops by "
              "one window per round; the half-budget floor holds while "
              "a packet waits < ~1 s virtual for bandwidth (token rate "
              ">= 1 B/ms; recorded as an open assumption — the rebase "
              "is not inductively closed by intervals alone)")
_WHY_DELIVER = ("deliver offsets: max(tsend + latency, clamp) with "
                "tsend <= window <= I32_MAX//4 and latency <= "
                "I32_MAX//2 (make_params budget); I32_MAX is the idle "
                "sentinel; the lower edge is one window of rebase")
_WHY_WINDOW = ("window_ns <= I32_MAX//4: enforced at scenario parse "
               "(workloads/spec.py) and the config runahead budget")
_WHY_SHIFT = ("shift_ns < I32_MAX//2: the chain driver opens windows "
              "at next_event, which its own loop bounds below the "
              "horizon clamp (the chain_windows while-theorem)")
_WHY_COUNTER = ("modular device counter: wraps by contract, the "
                "harvester delta-unwraps (docs/observability.md)")
_WHY_FLOWSEQ = ("flow segment indices / ms clock are declared modular "
                "(ISSUE scope): cumulative stream offsets wrap like "
                "every device counter; comparisons are range-relative")
_WHY_RR = ("RR virtual-finish counters are floor-rebased each window "
           "(within ~CE of zero) but join the I32_MAX idle sentinel "
           "through masked lanes; rank arithmetic on them is "
           "order-relative, the packed key masks invalid lanes")

#: NetPlaneState domains shared by every window_step-family entry
_STATE_DOMAINS = {
    "state.eg_dst": (-1, 1 << 20, "host ids; spec caps hosts <= 2^20"),
    "state.in_src": (-1, 1 << 20, "host ids; spec caps hosts <= 2^20"),
    "state.eg_bytes": (0, BYTES_BUDGET,
                       "wire bytes <= 2^24 (spec byte budget)"),
    "state.in_bytes": (0, BYTES_BUDGET,
                       "wire bytes <= 2^24 (spec byte budget)"),
    "state.eg_prio": (0, _B, "priorities: monotone counters from 0, "
                             "I32_MAX idle sentinel"),
    "state.eg_tsend": (-(_B // 2), _B // 4, _WHY_TSEND),
    "state.eg_clamp": (-(2**30), _B // 2,
                       "NO_CLAMP sentinel (-2^30) or a window-relative "
                       "barrier within the shift budget"),
    "state.in_deliver_rel": (-(_B // 4), _B, _WHY_DELIVER),
    "state.in_sock": (0, _B, "payload tags are non-negative"),
    "state.eg_sock": (0, _B, "payload tags are non-negative"),
    "state.tb_balance": (0, 2**30, "token balance <= cap <= 2^30 "
                                   "(make_params rate clamp)"),
    "state.tb_rem_ns": (0, 999_999, "sub-millisecond remainder"),
    # the destination-side router scalars (codel.RouterDownState)
    "state.router.mode": (0, 1, "store/drop enum"),
    "state.router.interval_end": (
        -(_B // 2), _B // 2,
        "CoDel timers sit within one control interval of the window "
        "horizon; rebased down every window"),
    "state.router.drop_next": (
        -(_B // 2), _B // 2,
        "CoDel timers sit within one control interval of the window "
        "horizon; rebased down every window"),
    "state.router.resume": (
        -(_B // 2), _B - 1_000_000,
        "relay resume time; wait_until saturates at I32_MAX - "
        "interval_ms and the conformance re-check recomputes "
        "(codel.py)"),
    "state.router.dn_balance": (0, 2**30,
                                "down-bucket balance <= cap <= 2^30 "
                                "(make_params rate clamp)"),
    "state.router.dn_last_refill": (
        -1_000_000, _B // 4,
        "re-anchored into (-1ms, 0] at every rebase "
        "(codel.rebase_router_state), advanced at most to the window "
        "horizon by in-window refills"),
    "state.router.cached_src": (-1, 1 << 20, "host ids"),
    "state.router.cached_sock": (0, _B, "payload tags"),
    "state.router.cached_bytes": (0, BYTES_BUDGET,
                                  "wire bytes budget"),
}
_STATE_MODULAR = {
    "state.n_*": _WHY_COUNTER,
    "state.rng_counter": _WHY_COUNTER,
    "state.rr_sent": _WHY_RR,
    "state.eg_seq": "per-source packet ids grow without bound: "
                    "modular like every counter",
    "state.in_seq": "per-source packet ids grow without bound: "
                    "modular like every counter",
    "state.router.cur_count": "CoDel drop counts: monotone counters, "
                              "modular like every device counter",
    "state.router.prev_count": "CoDel drop counts: monotone counters, "
                               "modular like every device counter",
    "state.router.dropped": _WHY_COUNTER,
    "state.router.cached_seq": "per-source packet ids: modular",
}

_DELIVERED_DOMAINS = {
    "delivered['bytes']": (0, BYTES_BUDGET, "wire bytes budget"),
    "delivered['deliver_rel']": (-(_B // 4), _B, _WHY_DELIVER),
    "delivered['src']": (-1, 1 << 20, "host ids"),
    "delivered['sock']": (0, _B, "payload tags"),
}
_DELIVERED_MODULAR = {"delivered['seq']": _WHY_FLOWSEQ}

#: the flow plane's wrap-exempt leaves are ENUMERATED, not fs.*: only
#: the segment-index stream offsets, the ms clock, and the cumulative
#: counters wrap by contract — cwnd/Reno/RTT-estimator/timer
#: arithmetic below gets real checked domains (a blanket fs.* made
#: the flow half of the proof vacuous)
_FS_MODULAR = {
    "fs.snd_una": _WHY_FLOWSEQ, "fs.snd_nxt": _WHY_FLOWSEQ,
    "fs.snd_max": _WHY_FLOWSEQ, "fs.stream_len": _WHY_FLOWSEQ,
    "fs.rcv_nxt": _WHY_FLOWSEQ, "fs.rtt_seq": _WHY_FLOWSEQ,
    "fs.clock_ms": _WHY_FLOWSEQ,
    "fs.retransmit_count": _WHY_COUNTER,
    "fs.retransmitted_bytes": _WHY_COUNTER,
    "fs.rto_fired": _WHY_COUNTER,
    "fs.rto_gen": _WHY_COUNTER,
    "fs.backoff_count": "monotone backoff tally: consumed only by "
                        "==0 / >0 compares (Karn gating); the RTO "
                        "value itself saturates at the _set_rto clamp",
}
_FS_DOMAINS = {
    "fs.cwnd": (0, 1 << 24,
                "congestion window in segments: additive growth "
                "clamped by ssthresh/recv_wnd; 2^24 segments is far "
                "past any modeled bandwidth-delay product"),
    "fs.ssthresh": (0, 1 << 30,
                    "slow-start threshold: halved cwnd or the "
                    "SSTHRESH_INF sentinel (tcp/cong.py)"),
    "fs.dup_acks": (0, 1 << 16, "dup-ack run length"),
    "fs.avoid_acked": (0, 1 << 24, "congestion-avoidance ack tally, "
                                   "reset each cwnd advance"),
    "fs.srtt_ms": (0, 1 << 22,
                   "RFC 6298 estimator in ms: samples are window-"
                   "quantized RTTs bounded by the RTO_MAX clamp"),
    "fs.rttvar_ms": (0, 1 << 22, "estimator variance, same budget"),
    "fs.rto_ms": (0, 1 << 22,
                  "_set_rto clips into [RTO_MIN_MS, RTO_MAX_MS] "
                  "(tpu/tcp.py); 2^22 ms leaves backoff headroom"),
    "fs.rto_deadline_ms": (-(_B // 2), _B,
                           "absolute virtual ms against the modular "
                           "clock; consumed only via clamped "
                           "differences (next_deadline_rel_ns)"),
    "fs.rtt_sent_ms": (-(_B // 2), _B,
                       "probe timestamp against the modular clock"),
    "fs.clock_rem_ns": (0, 999_999, "sub-millisecond remainder"),
}
_PLANES_MODULAR = {
    "metrics.*": _WHY_COUNTER,
    "guards.*": "guard tallies/bitmasks: saturating accumulators, "
                "modular by the same harvest contract",
    "hist.*": _WHY_COUNTER,
    "flightrec.*": "trace-ring cursor/buckets: modular, overwrites "
                   "counted at drain",
}

_SCALARS = {
    "shift_ns": (0, _B // 2, _WHY_SHIFT),
    "window_ns": (0, _B // 4, _WHY_WINDOW),
    "horizon_rel": (0, _B // 2,
                    "pre-clamped to <= I32_MAX//2 by the caller "
                    "(chain_windows docstring contract)"),
}


#: the Reno congestion-avoidance tick (tcp._avoid_tick:
#: `while acked >= cwnd: acked -= cwnd; cwnd += 1`) bounds cwnd
#: RELATIONALLY — it grows one segment per cwnd-worth of acks, so it
#: stays within one segment of the ack tally's 2^24 budget — which an
#: interval fixpoint cannot represent; the loop is the flow plane's
#: one justified residual
_AVOID_TICK_ALLOW = {
    "/while (shadow_tpu/tpu/tcp.py": (
        "Reno avoid-tick: the loop guard (acked >= cwnd) keeps cwnd "
        "within one segment of the ack tally (<= the fs.cwnd 2^24 "
        "budget); the bound is relational, beyond the interval "
        "fixpoint (tcp._avoid_tick)"),
}

#: codel's resume-time machinery is deliberately wrap-TOLERANT: a
#: saturating wait_until detects its own overflow (`r < now`) and the
#: conformance re-check recomputes a too-early firing; the refill span
#: against a saturated anchor is clamped by `max(. , 0)` + the cap min,
#: so the wrapped intermediate never commits (codel.py docstrings)
_CODEL_SATURATION_ALLOW = {
    "in wait_until()": (
        "deliberate saturation: wait_until detects its own int32 "
        "overflow (r < now) and clamps to I32_MAX - interval_ms; the "
        "resume conformance re-check recomputes early firings"),
    "in refill()": (
        "span against a saturated resume anchor: max(., 0) clamps the "
        "span and the cap min bounds the refill, so a wrapped "
        "intermediate never commits (codel.py refill/wait_until)"),
}


def _window_spec(key: str, extra_args=(), extra_domains=None,
                 extra_modular=None, allow=None) -> RangeSpec:
    domains = dict(_STATE_DOMAINS)
    domains.update({"shift_ns": _SCALARS["shift_ns"],
                    "window_ns": _SCALARS["window_ns"]})
    domains.update(extra_domains or {})
    modular = dict(_STATE_MODULAR)
    modular.update(extra_modular or {})
    return RangeSpec(
        key=key,
        arg_names=["state", *extra_args, "shift_ns", "window_ns"],
        domains=domains, modular=modular, allow=dict(allow or {}))


def range_specs() -> list[RangeSpec]:
    """The SL506 surface: the plane.py / flows.py kernel family whose
    overflow comments this pass turns into theorems (the audit
    registry's representative traces, via the shared cache)."""
    return [
        _window_spec("shadow_tpu.tpu.plane:window_step[lean]"),
        _window_spec("shadow_tpu.tpu.plane:window_step[rr,aqm,loss]",
                     allow=_CODEL_SATURATION_ALLOW),
        _window_spec("shadow_tpu.tpu.plane:window_step[flows]",
                     extra_args=["fs"], extra_domains=_FS_DOMAINS,
                     extra_modular=_FS_MODULAR,
                     allow=_AVOID_TICK_ALLOW),
        RangeSpec(
            key="shadow_tpu.tpu.plane:ingest_rows[planes]",
            arg_names=["state", "metrics", "guards", "hist",
                       "flightrec", "dst", "nbytes", "prio", "seq",
                       "valid"],
            domains={
                **_STATE_DOMAINS,
                "dst": (-1, 1 << 20, "host ids"),
                "nbytes": (0, BYTES_BUDGET, "wire bytes budget"),
                "prio": (0, _B, "priorities"),
            },
            modular={**_STATE_MODULAR, **_PLANES_MODULAR,
                     "seq": _WHY_FLOWSEQ},
            allow={
                "/take_along_axis (shadow_tpu/tpu/plane.py": (
                    "packed-rank permutation indices occupy the key's "
                    "low bits (< W by _assert_bit_budget's trace-time "
                    "guard); the masked AND is invisible to intervals "
                    "and take_along_axis's negative-index arm never "
                    "executes for non-negative ranks"),
            }),
        RangeSpec(
            key="shadow_tpu.tpu.flows:flow_step",
            arg_names=["ft", "fs", "state", "delivered"],
            domains={
                **_STATE_DOMAINS, **_DELIVERED_DOMAINS, **_FS_DOMAINS,
                "ft.src": (-1, 1 << 20, "host ids"),
                "ft.dst": (-1, 1 << 20, "host ids"),
                "ft.pkt_bytes": (0, BYTES_BUDGET, "wire bytes budget"),
            },
            modular={**_STATE_MODULAR, **_DELIVERED_MODULAR,
                     **_FS_MODULAR},
            allow=dict(_AVOID_TICK_ALLOW)),
        RangeSpec(
            key="shadow_tpu.tpu.plane:chain_windows",
            arg_names=["state", "shift0", "horizon_rel"],
            domains={
                **_STATE_DOMAINS,
                "shift0": _SCALARS["shift_ns"],
                "horizon_rel": _SCALARS["horizon_rel"],
            },
            modular=dict(_STATE_MODULAR)),
        # the flows-threaded chain: the RTO-wake re-arm (plane.py
        # `wake = window_ns + min(rto_rel, I32_MAX//2)`) rides the
        # while carry — the arithmetic the plane.py:616 comment used
        # to hand-argue
        RangeSpec(
            key="shadow_tpu.tpu.plane:chain_windows[flows]",
            arg_names=["state", "fs", "shift0", "horizon_rel"],
            domains={
                **_STATE_DOMAINS, **_FS_DOMAINS,
                "shift0": _SCALARS["shift_ns"],
                "horizon_rel": _SCALARS["horizon_rel"],
            },
            modular={**_STATE_MODULAR, **_FS_MODULAR},
            allow=dict(_AVOID_TICK_ALLOW)),
    ]
