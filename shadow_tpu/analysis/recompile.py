"""Recompile-counter harness: assert jit-cache-hit behavior.

A silent recompile costs 1-20 s of wall time (10-20 s on a tunneled
TPU) and destroys the sim-sec/wall-sec metric in BASELINE.json without
failing anything — the classic causes being weak-typed Python scalars
reaching a jitted signature, shape drift, and accidental static
arguments. This harness wraps a callable in ``jax.jit``, counts
compile-cache misses per call via the executable cache size, and sweeps
the representative shape ladder so the contract "N distinct static
shapes => exactly N compiles, every later call a cache hit" is asserted
mechanically.

The shape ladder mirrors ``tools/bench_ladder.py`` structurally: rung-2
(single-node switch mesh) and rung-3 (multi-node GML fleet) host/queue
shapes, scaled down so the sweep traces in seconds on CPU. Shapes are
what drive compilation; the host *count* only scales array extents.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, NamedTuple

import numpy as np

__all__ = [
    "CompileCounter",
    "LadderShape",
    "ladder_shapes",
    "sweep_window_step",
]


class LadderShape(NamedTuple):
    """One rung-shaped device configuration (scaled down)."""

    name: str
    n_hosts: int
    n_nodes: int
    egress_cap: int
    ingress_cap: int


def ladder_shapes() -> list[LadderShape]:
    """The bench-ladder shape sweep (`tools/bench_ladder.py` rungs 2/3,
    scaled): a single-node switch mesh and two GML fleet sizes."""
    return [
        LadderShape("rung2_switch_mesh", 8, 1, 8, 16),
        LadderShape("rung3_gml_small", 16, 4, 8, 16),
        LadderShape("rung3_gml_wide", 64, 8, 16, 32),
    ]


@dataclass
class CompileCounter:
    """Wrap `fn` in jax.jit and count cache misses per call.

    ``misses`` increments whenever a call grew the jit executable
    cache — i.e. the call compiled instead of hitting. ``expect(n)``
    marks the next n misses as expected (first-call compiles per static
    shape); ``unexpected_misses`` is what must stay zero.
    """

    fn: Callable
    static_argnames: tuple = ()
    calls: int = 0
    misses: int = 0
    expected: int = 0
    log: list = field(default_factory=list)

    def __post_init__(self):
        import jax

        self._jit = jax.jit(self.fn, static_argnames=self.static_argnames)

    def expect(self, n: int = 1) -> None:
        self.expected += n

    @property
    def unexpected_misses(self) -> int:
        return max(0, self.misses - self.expected)

    def __call__(self, *args, **kwargs):
        before = self._jit._cache_size()
        out = self._jit(*args, **kwargs)
        after = self._jit._cache_size()
        self.calls += 1
        if after > before:
            self.misses += after - before
            self.log.append((self.calls, after - before))
        return out


def _build_shape(shape: LadderShape, rng: np.random.Generator):
    import jax

    from ..tpu import plane

    m = shape.n_nodes
    params = plane.make_params(
        latency_ns=rng.integers(
            100_000, 5_000_000, (m, m)).astype(np.int64),
        loss=rng.uniform(0.0, 0.01, (m, m)),
        up_bw_bps=np.full(shape.n_hosts, 1_000_000_000, np.int64),
        host_node=(np.arange(shape.n_hosts) % m).astype(np.int32),
    )
    state = plane.make_state(
        shape.n_hosts, egress_cap=shape.egress_cap,
        ingress_cap=shape.ingress_cap, params=params)
    return params, state, jax.random.key(7)


def sweep_window_step(shapes: list[LadderShape] | None = None,
                      rounds: int = 4, repeats: int = 2) -> dict:
    """Drive ``plane.window_step`` across the shape ladder and report
    cache behavior.

    Per shape: one expected compile, then `rounds` windows with varying
    (shift, window) scalars — which MUST all hit — then `repeats - 1`
    re-sweeps of the whole ladder, which must add zero compiles. Window
    scalars go in as np.int32 so a weak-typed Python int can never
    sneak a new signature in; that conversion discipline is exactly
    what the harness exists to enforce on callers.

    Returns ``{"shapes": [...], "total_compiles", "expected_compiles",
    "unexpected_misses"}`` — the acceptance gate is
    ``unexpected_misses == 0``.
    """
    from ..tpu import plane

    shapes = shapes if shapes is not None else ladder_shapes()
    rng = np.random.default_rng(13)

    counter = CompileCounter(
        plane.window_step,
        static_argnames=("rr_enabled", "router_aqm", "no_loss"))

    built = [(s, *_build_shape(s, rng)) for s in shapes]
    per_shape = []
    for sweep in range(repeats):
        for shape, params, state, key in built:
            if sweep == 0:
                counter.expect(1)  # first sight of this static shape
            before = counter.misses
            st = state
            for r in range(rounds):
                shift = np.int32(0 if r == 0 else 1_000_000 * r)
                window = np.int32(1_000_000 * (r + 1))
                st, _delivered, _next = counter(
                    st, params, key, shift, window,
                    rr_enabled=False, router_aqm=False, no_loss=False)
            if sweep == 0:
                per_shape.append({
                    "shape": shape.name,
                    "n_hosts": shape.n_hosts,
                    "compiles": counter.misses - before,
                })
    return {
        "shapes": per_shape,
        "rounds_per_shape": rounds,
        "repeats": repeats,
        "total_compiles": counter.misses,
        "expected_compiles": counter.expected,
        "unexpected_misses": counter.unexpected_misses,
    }
