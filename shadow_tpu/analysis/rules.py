"""The shadowlint rule registry, findings, and suppression syntax.

Every rule protects one determinism or jit-cache invariant of the
simulation (PAPER.md: "same seed -> same results, on any machine, at any
parallelism"). Pass 1 (``astlint``) rules are SL1xx and run over source
text; pass 2 (``jaxpr_audit``) rules are SL2xx and run over the jaxprs of
the jitted ``tpu/`` entry points.

Suppression syntax (pass 1)::

    time.monotonic()  # shadowlint: disable=SL101 -- wall-clock stats only

A ``# shadowlint: disable=SLxxx[,SLyyy] -- <justification>`` comment
suppresses those rules on its own line and on the line directly below it
(so it can trail the offending statement or sit on the preceding line).
The justification after ``--`` is REQUIRED: a disable comment without one
still fails the lint, so every suppression documents why the hazard is
not real. Pass-2 entries carry per-rule allow-lists in the audit registry
(`jaxpr_audit.default_entries`) instead, since jaxpr findings have no
source line to anchor a comment to.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

__all__ = [
    "Finding",
    "RULES",
    "RuleInfo",
    "Suppressions",
    "parse_suppressions",
]


@dataclass(frozen=True)
class RuleInfo:
    id: str
    name: str
    summary: str
    invariant: str  # the determinism invariant the rule protects


RULES: dict[str, RuleInfo] = {
    r.id: r
    for r in [
        RuleInfo(
            "SL101", "wall-clock-read",
            "wall-clock read (time.time/monotonic/perf_counter, "
            "datetime.now) in simulation code",
            "simulated time comes only from the event clock; real time "
            "feeding any simulation decision breaks replay",
        ),
        RuleInfo(
            "SL102", "global-randomness",
            "unseeded/global randomness (random.*, legacy np.random.*) "
            "outside core/rng.py",
            "all draws come from the seeded Xoshiro256++ streams in "
            "core/rng.py (or counter-based threefry on device), so "
            "results are a pure function of the config seed",
        ),
        RuleInfo(
            "SL103", "unordered-iteration",
            "iteration over a set/frozenset where order can feed event "
            "scheduling",
            "event order must be scheduling-independent; set iteration "
            "order depends on insertion history and hash seeding",
        ),
        RuleInfo(
            "SL104", "mutable-default-arg",
            "mutable default argument (list/dict/set) on a function",
            "a shared mutable default carries state across calls and "
            "hosts, making results depend on call history",
        ),
        RuleInfo(
            "SL105", "traced-branch",
            "Python-level branching on a traced value inside a tpu/ "
            "kernel module",
            "host branches on device values force a blocking sync and "
            "bake one branch into the compiled graph (silent recompiles "
            "or wrong results under jit)",
        ),
        RuleInfo(
            "SL301", "sync-in-kernel",
            "jax.device_get / block_until_ready inside a tpu/ kernel "
            "body (a function that is jitted or a lax control-flow body)",
            "telemetry harvest and every other host readback stay "
            "OUTSIDE jitted code (docs/observability.md): a sync inside "
            "a kernel body blocks the device pipeline on every window "
            "and turns into a host callback under jit",
        ),
        RuleInfo(
            "SL402", "assert-in-kernel",
            "Python `assert` inside a tpu/ kernel body (a jit-decorated"
            "/jit-wrapped function or a lax control-flow body)",
            "an assert in traced code runs ONCE at trace time against "
            "abstract values (and vanishes entirely under -O): it can "
            "never check runtime data, so it reads as an invariant "
            "check that silently is not one. Runtime invariants go "
            "through the guard plane (shadow_tpu/guards/, "
            "docs/robustness.md); trace-time shape/static checks use "
            "an explicit raise",
        ),
        RuleInfo(
            "SL401", "swallowed-error",
            "broad exception swallow (`except Exception: pass` or a "
            "bare `except:` without re-raise)",
            "the fault plane's whole premise is that failures surface "
            "as structured, attributable events (docs/robustness.md); "
            "a silently swallowed broad exception turns a real fault "
            "into an unexplained hang or wrong result",
        ),
        RuleInfo(
            "SL403", "variadic-sort",
            "lax.sort (or the `_row_sort` wrapper) carrying more than 3 "
            "payload operands through the comparator network in tpu/",
            "the sort diet (docs/performance.md): payload columns ride a "
            "packed-key permutation or a bucketed counting placement, "
            "never the O(n log n) comparator network — the variadic "
            "anti-pattern was the window step's dominant cost until PR 2 "
            "removed it; the compiled-in packed_sort=False parity "
            "reference paths carry justified suppressions",
        ),
        RuleInfo(
            "SL405", "sync-telemetry-read",
            "host-side float(...)/.item() read of a device telemetry "
            "array (metrics/histogram/flight-recorder leaves) outside "
            "harvest-boundary code",
            "every observability read goes through the asynchronous "
            "TelemetryHarvester/FlightRecorder drain "
            "(docs/observability.md no-host-sync rule): a float()/"
            ".item() on a device counter is a blocking D2H sync that "
            "stalls the dispatch pipeline wherever it runs — "
            "shadow_tpu/telemetry/ (the harvest boundary itself) is "
            "the one sanctioned reader",
        ),
        RuleInfo(
            "SL201", "x64-leak",
            "64-bit dtype (float64/int64) appearing in a device jaxpr",
            "the device plane is int32/float32 by contract "
            "(tpu/plane.py dtype discipline); x64 leaks change numerics "
            "between hosts and recompile per weak-type",
        ),
        RuleInfo(
            "SL202", "convert-churn",
            "redundant convert_element_type chain in a device jaxpr",
            "dtype round-trips signal weak-type churn at jit boundaries "
            "— the classic silent-recompile trigger",
        ),
        RuleInfo(
            "SL203", "host-callback",
            "host callback primitive inside a jitted kernel",
            "callbacks leave the device mid-kernel: nondeterministic "
            "interleaving and a host sync on the hot path",
        ),
        RuleInfo(
            "SL204", "transfer-in-loop",
            "host transfer/callback inside a while_loop/scan body",
            "a per-iteration device<->host hop turns an O(1)-dispatch "
            "window chain into O(iterations) syncs",
        ),
        RuleInfo(
            "SL205", "baked-constant",
            "large constant baked into a jitted graph",
            "big captured constants bloat every compiled executable and "
            "re-upload on each compile; pass them as arguments instead",
        ),
    ]
}


@dataclass
class Finding:
    """One rule violation (or suppressed violation) with its location.

    ``line`` is 1-based for pass-1 findings and 0 for jaxpr findings,
    whose location is the audit entry name in ``path``.
    """

    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    justification: str = ""

    def to_json(self) -> dict:
        info = RULES[self.rule]
        return {
            "rule": self.rule,
            "name": info.name,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "suppressed": self.suppressed,
            "justification": self.justification,
        }

    def __str__(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}" if self.line else self.path
        tag = " [suppressed]" if self.suppressed else ""
        return f"{loc}: {self.rule} {self.message}{tag}"


_SUPPRESS_RE = re.compile(
    r"#\s*shadowlint:\s*disable=([A-Z0-9,\s]+?)"
    r"(?:\s*--\s*(.*?))?\s*$"
)


@dataclass
class Suppressions:
    """Per-file map of line -> {rule -> justification}.

    A disable comment on line L covers findings on L and L+1; an empty
    justification means the comment is malformed (missing ``-- reason``)
    and does NOT suppress.
    """

    by_line: dict[int, dict[str, str]] = field(default_factory=dict)
    malformed: list[tuple[int, str]] = field(default_factory=list)

    def lookup(self, rule: str, line: int) -> str | None:
        """Justification text if (rule, line) is suppressed, else None."""
        for cand in (line, line - 1):
            just = self.by_line.get(cand, {}).get(rule)
            if just:
                return just
        return None


def parse_suppressions(source: str) -> Suppressions:
    sup = Suppressions()
    for lineno, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = [r.strip() for r in m.group(1).split(",") if r.strip()]
        just = (m.group(2) or "").strip()
        if not just:
            sup.malformed.append((lineno, text.strip()))
            continue
        slot = sup.by_line.setdefault(lineno, {})
        for rule in rules:
            slot[rule] = just
    return sup
