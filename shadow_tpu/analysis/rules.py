"""The shadowlint rule registry, findings, and suppression syntax.

Every rule protects one determinism or jit-cache invariant of the
simulation (PAPER.md: "same seed -> same results, on any machine, at any
parallelism"). Pass 1 (``astlint``) rules are SL1xx and run over source
text; pass 2 (``jaxpr_audit``) rules are SL2xx and run over the jaxprs of
the jitted ``tpu/`` entry points.

Suppression syntax (pass 1)::

    time.monotonic()  # shadowlint: disable=SL101 -- wall-clock stats only

A ``# shadowlint: disable=SLxxx[,SLyyy] -- <justification>`` comment
suppresses those rules on its own line and on the line directly below it
(so it can trail the offending statement or sit on the preceding line).
The justification after ``--`` is REQUIRED: a disable comment without one
still fails the lint, so every suppression documents why the hazard is
not real. Pass-2 entries carry per-rule allow-lists in the audit registry
(`jaxpr_audit.default_entries`) instead, since jaxpr findings have no
source line to anchor a comment to.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

__all__ = [
    "Finding",
    "RULES",
    "RuleInfo",
    "Suppressions",
    "parse_suppressions",
]


@dataclass(frozen=True)
class RuleInfo:
    id: str
    name: str
    summary: str
    invariant: str  # the determinism invariant the rule protects
    #: where the rule runs — a human-readable scope line for
    #: ``shadowlint --list-rules`` (path prefixes for AST rules, the
    #: audited registry for jaxpr/proof rules)
    scope: str = ""
    #: the seeded violation under tests/lint_fixtures/ proving the rule
    #: can fail (every rule MUST have one; pinned by
    #: tests/test_shadowlint.py::test_every_rule_has_a_fixture)
    fixture: str = ""


RULES: dict[str, RuleInfo] = {
    r.id: r
    for r in [
        RuleInfo(
            "SL101", "wall-clock-read",
            "wall-clock read (time.time/monotonic/perf_counter, "
            "datetime.now) in simulation code",
            "simulated time comes only from the event clock; real time "
            "feeding any simulation decision breaks replay",
            scope="shadow_tpu/ (tools/ benchmarks measure wall time on purpose)",
            fixture="fixture_wallclock.py",
        ),
        RuleInfo(
            "SL102", "global-randomness",
            "unseeded/global randomness (random.*, legacy np.random.*) "
            "outside core/rng.py",
            "all draws come from the seeded Xoshiro256++ streams in "
            "core/rng.py (or counter-based threefry on device), so "
            "results are a pure function of the config seed",
            scope="everywhere except core/rng.py",
            fixture="fixture_randomness.py",
        ),
        RuleInfo(
            "SL103", "unordered-iteration",
            "iteration over a set/frozenset where order can feed event "
            "scheduling",
            "event order must be scheduling-independent; set iteration "
            "order depends on insertion history and hash seeding",
            scope="core/, net/, host/, kernel/, process/, tcp/, apps/",
            fixture="fixture_unordered.py",
        ),
        RuleInfo(
            "SL104", "mutable-default-arg",
            "mutable default argument (list/dict/set) on a function",
            "a shared mutable default carries state across calls and "
            "hosts, making results depend on call history",
            scope="everywhere",
            fixture="fixture_mutable_default.py",
        ),
        RuleInfo(
            "SL105", "traced-branch",
            "Python-level branching on a traced value inside a tpu/ "
            "kernel module",
            "host branches on device values force a blocking sync and "
            "bake one branch into the compiled graph (silent recompiles "
            "or wrong results under jit)",
            scope="shadow_tpu/tpu/",
            fixture="fixture_traced_branch.py",
        ),
        RuleInfo(
            "SL301", "sync-in-kernel",
            "jax.device_get / block_until_ready inside a tpu/ kernel "
            "body (a function that is jitted or a lax control-flow body)",
            "telemetry harvest and every other host readback stay "
            "OUTSIDE jitted code (docs/observability.md): a sync inside "
            "a kernel body blocks the device pipeline on every window "
            "and turns into a host callback under jit",
            scope="shadow_tpu/tpu/",
            fixture="fixture_kernel_sync.py",
        ),
        RuleInfo(
            "SL402", "assert-in-kernel",
            "Python `assert` inside a tpu/ kernel body (a jit-decorated"
            "/jit-wrapped function or a lax control-flow body)",
            "an assert in traced code runs ONCE at trace time against "
            "abstract values (and vanishes entirely under -O): it can "
            "never check runtime data, so it reads as an invariant "
            "check that silently is not one. Runtime invariants go "
            "through the guard plane (shadow_tpu/guards/, "
            "docs/robustness.md); trace-time shape/static checks use "
            "an explicit raise",
            scope="shadow_tpu/tpu/",
            fixture="fixture_kernel_assert.py",
        ),
        RuleInfo(
            "SL401", "swallowed-error",
            "broad exception swallow (`except Exception: pass` or a "
            "bare `except:` without re-raise)",
            "the fault plane's whole premise is that failures surface "
            "as structured, attributable events (docs/robustness.md); "
            "a silently swallowed broad exception turns a real fault "
            "into an unexplained hang or wrong result",
            scope="shadow_tpu/",
            fixture="fixture_swallowed.py",
        ),
        RuleInfo(
            "SL403", "variadic-sort",
            "lax.sort (or the `_row_sort` wrapper) carrying more than 3 "
            "payload operands through the comparator network in tpu/",
            "the sort diet (docs/performance.md): payload columns ride a "
            "packed-key permutation or a bucketed counting placement, "
            "never the O(n log n) comparator network — the variadic "
            "anti-pattern was the window step's dominant cost until PR 2 "
            "removed it; the compiled-in packed_sort=False parity "
            "reference paths carry justified suppressions",
            scope="shadow_tpu/tpu/",
            fixture="fixture_variadic_sort.py",
        ),
        RuleInfo(
            "SL405", "sync-telemetry-read",
            "host-side float(...)/.item() read of a device telemetry "
            "array (metrics/histogram/flight-recorder leaves) outside "
            "harvest-boundary code",
            "every observability read goes through the asynchronous "
            "TelemetryHarvester/FlightRecorder drain "
            "(docs/observability.md no-host-sync rule): a float()/"
            ".item() on a device counter is a blocking D2H sync that "
            "stalls the dispatch pipeline wherever it runs — "
            "shadow_tpu/telemetry/ (the harvest boundary itself) is "
            "the one sanctioned reader",
            scope="shadow_tpu/ except shadow_tpu/telemetry/ (the harvest boundary)",
            fixture="fixture_telemetry_read.py",
        ),
        RuleInfo(
            "SL201", "x64-leak",
            "64-bit dtype (float64/int64) appearing in a device jaxpr",
            "the device plane is int32/float32 by contract "
            "(tpu/plane.py dtype discipline); x64 leaks change numerics "
            "between hosts and recompile per weak-type",
            scope="jaxpr audit registry (analysis/jaxpr_audit.py)",
            fixture="fixture_x64_leak.py",
        ),
        RuleInfo(
            "SL202", "convert-churn",
            "redundant convert_element_type chain in a device jaxpr",
            "dtype round-trips signal weak-type churn at jit boundaries "
            "— the classic silent-recompile trigger",
            scope="jaxpr audit registry (analysis/jaxpr_audit.py)",
            fixture="fixture_convert_churn.py",
        ),
        RuleInfo(
            "SL203", "host-callback",
            "host callback primitive inside a jitted kernel",
            "callbacks leave the device mid-kernel: nondeterministic "
            "interleaving and a host sync on the hot path",
            scope="jaxpr audit registry (analysis/jaxpr_audit.py)",
            fixture="fixture_host_callback.py",
        ),
        RuleInfo(
            "SL204", "transfer-in-loop",
            "host transfer/callback inside a while_loop/scan body",
            "a per-iteration device<->host hop turns an O(1)-dispatch "
            "window chain into O(iterations) syncs",
            scope="jaxpr audit registry (analysis/jaxpr_audit.py)",
            fixture="fixture_loop_transfer.py",
        ),
        RuleInfo(
            "SL205", "baked-constant",
            "large constant baked into a jitted graph",
            "big captured constants bloat every compiled executable and "
            "re-upload on each compile; pass them as arguments instead",
            scope="jaxpr audit registry (analysis/jaxpr_audit.py)",
            fixture="fixture_baked_constant.py",
        ),
        RuleInfo(
            "SL501", "presence-invisibility",
            "an observability-plane input leaf (metrics/guards/hist/"
            "flightrec; workload under the append-only relaxation) "
            "reaches a sim-state output leaf in the traced jaxpr",
            "the presence switches are bitwise-invisible BY THEOREM: "
            "the taint analysis (analysis/dataflow.py) proves, for "
            "every plane variant of window_step/chain_windows/"
            "ingest_rows and for all inputs, that no plane value can "
            "flow into NetPlaneState columns, the RNG counter, the "
            "clock offsets, or the delivered stream — where the "
            "runtime parity matrices only sample rr×aqm×no_loss "
            "corners (docs/determinism.md 'Proofs vs parity tests')",
            scope="proof registry (analysis/proofs.invisibility_specs)",
            fixture="fixture_taint_leak.py",
        ),
        RuleInfo(
            "SL502", "op-budget",
            "the static census of expensive primitives (sorts, "
            "gathers, scatter variants, control flow, pallas calls, "
            "host transfers) deviates from the checked-in "
            "analysis/op_budgets.json ledger",
            "the sort/scatter diet stays dieted without re-benching "
            "every PR: a reintroduced variadic sort or per-column "
            "scatter changes the census and fails CI in seconds; "
            "legitimate changes regenerate the ledger "
            "(tools/shadowlint.py --write-op-budgets) so every op-cost "
            "delta is explicit in the diff (docs/performance.md)",
            scope="jaxpr audit registry (analysis/jaxpr_audit.py)",
            fixture="fixture_op_budget.py",
        ),
        RuleInfo(
            "SL503", "donation-safety",
            "a buffer-donation hazard: a donated array referenced "
            "after dispatch, or a raw jax.jit(donate_argnums=...) "
            "bypassing the donating_jit wrapper",
            "the donation contract (docs/performance.md): a donated "
            "pytree is CONSUMED by the call — XLA may alias its "
            "buffers in place, so a later host read sees garbage (or "
            "deleted-buffer errors) only on donating backends, i.e. "
            "only in production. All donation goes through "
            "tpu.donating_jit (whose CPU-backend no-op keeps tests "
            "meaningful) with consistent donate_argnums across the "
            "unified drivers; host code rebinds the returned state and "
            "never touches the donated argument again",
            scope="shadow_tpu/, tools/, bench.py",
            fixture="fixture_donation.py",
        ),
        RuleInfo(
            "SL504", "shardability-fence",
            "expensive primitives classified host-axis-local vs "
            "cross-host per audited section; GATES when a cross-host "
            "op appears in a row-local-pinned stage (tcp/codel)",
            "the ROADMAP-2 shard_map cut needs a scoped work-list "
            "before any million-host work starts: cross-host ops "
            "(gathers/scatters keyed by computed host ids, full-axis "
            "sorts, host-axis reductions) need a collective or a "
            "ragged exchange; host-local ops shard for free. The "
            "report (tools/shadowlint.py --shard-report) stays "
            "informational for most entries, but the tcp/codel "
            "row-local stages are pinned EMPTY "
            "(proofs.ROW_LOCAL_PINNED): a cross-host primitive "
            "sneaking into one fails the build — the regression fence "
            "for the shard_map refactor",
            scope="jaxpr audit registry (analysis/jaxpr_audit.py)",
            fixture="fixture_shard_classify.py",
        ),
        RuleInfo(
            "SL505", "branch-equivalence",
            "a registered lax.cond gate (gate_idle / ident-vs-sort / "
            "flow idle gates) whose branches are NOT provably "
            "bitwise-equal on the gated domain",
            "the device plane's cond gates may only ever change "
            "SPEED, never a bit: the idle gates must be the identity "
            "on entry-free windows and the ident-vs-sort gates must "
            "equal the sort on ordered input — the contract memoized "
            "replay and deeper sort-diet gating stand on. The prover "
            "(analysis/condeq.py) shows branch equality structurally "
            "(canonicalization + the sort-of-sorted rewrite + a "
            "selection witness) or by exhaustive evaluation over a "
            "registered boundary-value lattice, with the mode "
            "recorded per gate (docs/determinism.md 'Branch gates "
            "are theorems')",
            scope="gate registry (analysis/condeq.gate_obligations)",
            fixture="fixture_condeq_gate.py",
        ),
        RuleInfo(
            "SL601", "compiled-cost-budget",
            "a registered entry's compiled-HLO cost (XLA "
            "cost_analysis flops / bytes accessed / transcendentals) "
            "deviates from the platform-keyed "
            "analysis/cost_budgets.json beyond its tolerance band, or "
            "its peak temp watermark grows super-linearly across the "
            "two traced shapes",
            "the perf fences hold at BUILD time on the compiled "
            "artifact, which is container-independent for a given "
            "platform key — where every runtime gate only holds on a "
            "matched container (the PR-7/PR-11 cross-container "
            "false-regression lesson). analysis/costmodel.py lowers "
            "each cached jaxpr through jit().lower().compile(), diffs "
            "the cost scalars against the checked-in ledger, and "
            "extrapolates the temp watermark across two host-axis "
            "shapes (the ROADMAP-2 million-host memory fence); "
            "legitimate changes regenerate the ledger "
            "(--write-cost-budgets) so every cost delta is explicit "
            "in the diff (docs/performance.md 'Static cost fences')",
            scope="cost registry (analysis/costmodel.default_cost_entries)",
            fixture="fixture_fusion_break.py",
        ),
        RuleInfo(
            "SL602", "fusion-boundary",
            "a registered entry's optimized HLO materializes more "
            "[N,CE]-or-larger intermediates between fusions than the "
            "checked-in budget (or its fusion count drifts): a "
            "producer->consumer pair writing + re-reading a "
            "ring-sized buffer the fusion work should elide",
            "the compiled-floor attack (ROADMAP-4) is fusion work, "
            "and its progress must be monotone: every materialized "
            ">=[N,CE] boundary is a write+read of HBM/cache the "
            "rank->place->egress pipeline exists to remove, so the "
            "census is budgeted per entry and the full ranked "
            "worklist (shape, bytes, both ends, source op_name) is "
            "the artifact that fusion work consumes "
            "(--cost-report; docs/performance.md 'Static cost "
            "fences')",
            scope="cost registry (analysis/costmodel.default_cost_entries)",
            fixture="fixture_fusion_break.py",
        ),
        RuleInfo(
            "SL603", "host-sync-fence",
            "a per-iteration host sync (jax.device_get / .item() / "
            "float() / np.asarray / block_until_ready on a device "
            "value) inside a for/while body of a driver-loop module "
            "(bench.py, tools/chaos_smoke.py, workloads/runner.py, "
            "tpu/elastic.py)",
            "the chained driver's whole value is host syncs ONLY at "
            "chain ends (docs/performance.md 'The driver loop'): a "
            "blocking D2H read inside a driver loop re-serializes "
            "the dispatch pipeline per iteration — the SL405 "
            "telemetry rule generalized to every device value in the "
            "four modules that own a window loop. Chain-end/teardown "
            "reads outside loops and values already pulled through "
            "jax.device_get are the sanctioned pattern; deliberate "
            "in-loop syncs (the elastic overflow readback) carry "
            "justified allows in costmodel.HOST_SYNC_ALLOWED",
            scope="driver-loop modules (costmodel.DRIVER_MODULES)",
            fixture="fixture_host_sync.py",
        ),
        RuleInfo(
            "SL506", "integer-range",
            "a non-exempt signed-int32 op whose interval (seeded from "
            "the checked-in input-domain registry) admits wraparound",
            "the plane's int32-ns dtype discipline holds by interval "
            "arithmetic, not by luck: analysis/ranges.py propagates "
            "[lo, hi] through every audited plane/flows jaxpr — "
            "while-loop carries refined by the loop predicate, "
            "declared-modular counters wrap-exempt — and fails the "
            "build on any op that can overflow, naming the op, its "
            "source line, and the computed interval. Every 'no "
            "overflow because ...' comment is now either this "
            "theorem or a caught bug (docs/determinism.md)",
            scope="range registry (analysis/ranges.range_specs)",
            fixture="fixture_int_overflow.py",
        ),
        RuleInfo(
            "SL701", "world-isolation",
            "a primitive in a vmapped entry's batched jaxpr that "
            "reduces, gathers, scatters, sorts, concatenates, or "
            "otherwise combines values ACROSS the leading world axis",
            "the ensemble contract — world b of a W-world vmapped "
            "run equals world b's solo run — holds because NO "
            "dataflow path mixes two worlds: analysis/batchdim.py "
            "re-traces every audited entry under jax.vmap and walks "
            "axis provenance through every primitive (broadcast "
            "moves the world dim by broadcast_dimensions, gather/"
            "scatter must carry it in their declared batching dims, "
            "reductions must not name it). A finding names the op, "
            "its source line, and the offending axis; zero findings "
            "is the world-isolation theorem the worlds-parity test "
            "witnesses at runtime (docs/determinism.md 'Worlds are "
            "theorems')",
            scope="batch registry (analysis/batchdim.batch_entries)",
            fixture="fixture_cross_world.py",
        ),
        RuleInfo(
            "SL702", "rng-stream-disjointness",
            "a per-world RNG key derivation chain that is not "
            "provably injective in the world seed",
            "per-world randomness never collides because the "
            "derivation seed -> key is injective: "
            "analysis/batchdim.py walks the fold chain's jaxpr "
            "symbolically (mod-2^n bijections pass outright, "
            "non-bijective affine steps need a wrap-free interval "
            "argument over the declared seed domain — the SL506 "
            "machinery on fold-in arithmetic) and a threefry "
            "invocation under a fixed root key is a counter-block "
            "bijection. Distinct seeds therefore yield distinct "
            "derived keys, so no two worlds ever issue the same "
            "(key, counter) cipher call — counter streams are "
            "pairwise disjoint for all b != c",
            scope="RNG obligation registry "
                  "(analysis/batchdim.rng_obligations)",
            fixture="fixture_rng_overlap.py",
        ),
        RuleInfo(
            "SL703", "vmap-traceability-census",
            "an audited entry that fails to vmap at the two audit "
            "world counts, whose batched primitive census drifts "
            "with the world count, or a vmap refusal that is stale "
            "or rationale-free",
            "every entry on the audit surface is ensemble-ready BY "
            "CONSTRUCTION or refuses in writing: "
            "analysis/batchdim.py traces each entry under vmap at "
            "W=2 and W=3 and requires an identical primitive census "
            "(same graph, wider arrays — the world-count "
            "shape-polymorphism witness). Pallas kernels refuse via "
            "batchdim.VMAP_REFUSALS with a written rationale, "
            "exactly like the faults/guards refusals — registered, "
            "not silent; a refusal naming a de-registered entry is "
            "itself a finding",
            scope="batch registry (analysis/batchdim.batch_entries "
                  "+ batchdim.VMAP_REFUSALS)",
            fixture="fixture_vmap_refusal.py",
        ),
    ]
}


@dataclass
class Finding:
    """One rule violation (or suppressed violation) with its location.

    ``line`` is 1-based for pass-1 findings and 0 for jaxpr findings,
    whose location is the audit entry name in ``path``.
    """

    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    justification: str = ""

    def to_json(self) -> dict:
        info = RULES[self.rule]
        return {
            "rule": self.rule,
            "name": info.name,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "suppressed": self.suppressed,
            "justification": self.justification,
        }

    def __str__(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}" if self.line else self.path
        tag = " [suppressed]" if self.suppressed else ""
        return f"{loc}: {self.rule} {self.message}{tag}"


_SUPPRESS_RE = re.compile(
    r"#\s*shadowlint:\s*disable=([A-Z0-9,\s]+?)"
    r"(?:\s*--\s*(.*?))?\s*$"
)


@dataclass
class Suppressions:
    """Per-file map of line -> {rule -> justification}.

    A disable comment on line L covers findings on L and L+1; an empty
    justification means the comment is malformed (missing ``-- reason``)
    and does NOT suppress.
    """

    by_line: dict[int, dict[str, str]] = field(default_factory=dict)
    malformed: list[tuple[int, str]] = field(default_factory=list)

    def lookup(self, rule: str, line: int) -> str | None:
        """Justification text if (rule, line) is suppressed, else None."""
        for cand in (line, line - 1):
            just = self.by_line.get(cand, {}).get(rule)
            if just:
                return just
        return None


def parse_suppressions(source: str) -> Suppressions:
    sup = Suppressions()
    for lineno, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = [r.strip() for r in m.group(1).split(",") if r.strip()]
        just = (m.group(2) or "").strip()
        if not just:
            sup.malformed.append((lineno, text.strip()))
            continue
        slot = sup.by_line.setdefault(lineno, {})
        for rule in rules:
            slot[rule] = just
    return sup
