"""Built-in applications for the process plane.

Parity: the reference runs real binaries (`examples/apps/{curl,nginx,
iperf-2,http-server,...}`, tgen); until the native interposition plane
lands, these Python coroutine apps cover the same simulation roles:

- http-server / http-client: the BASELINE rung-1 basic-file-transfer pair
  (`examples/docs/basic-file-transfer/shadow.yaml` — python http.server
  serving a file + curl fetching it).
- udp-echo-server / udp-client: datagram smoke traffic.
- tgen-server / tgen-client: fixed-size stream transfers like the tgen
  throughput tests (`src/test/tgen/README.md`).

Config `processes[].path` selects an app by name; `args` pass through as
strings (argv-style).
"""

from __future__ import annotations

from typing import Callable

from ..kernel import errors

MS = 1_000_000


def http_server(api, port="80", size="10485760"):
    """Serve `size` bytes to every GET, forever (like `python3 -m
    http.server` with one file)."""
    port, size = int(port), int(size)
    payload = bytes(i & 0xFF for i in range(1024)) * (size // 1024 + 1)
    payload = payload[:size]
    lst = api.tcp_socket()
    lst.bind(("0.0.0.0", port))
    lst.listen()
    header = (
        b"HTTP/1.0 200 OK\r\nContent-Length: " + str(size).encode() + b"\r\n\r\n"
    )
    while True:
        conn = yield from api.accept(lst)
        # serve sequentially (http.server is single-threaded too)
        req = b""
        while b"\r\n\r\n" not in req:
            chunk = yield from api.recv(conn)
            if not chunk:
                break
            req += chunk
        if b"\r\n\r\n" in req:
            yield from api.send_all(conn, header + payload)
        api.close(conn)


def http_client(api, server="server", port="80", path="/file"):
    """GET a file and check the declared Content-Length arrived (curl)."""
    s = api.tcp_socket()
    yield from api.connect(s, (server, int(port)))
    yield from api.send_all(
        s, b"GET " + path.encode() + b" HTTP/1.0\r\nHost: x\r\n\r\n"
    )
    data = b""
    while b"\r\n\r\n" not in data:
        chunk = yield from api.recv(s)
        if not chunk:
            raise errors.SyscallError(errors.ECONNRESET, "short response")
        data += chunk
    head, _, body = data.partition(b"\r\n\r\n")
    length = None
    for line in head.split(b"\r\n"):
        if line.lower().startswith(b"content-length:"):
            length = int(line.split(b":")[1])
    assert length is not None, "no Content-Length"
    while len(body) < length:
        chunk = yield from api.recv(s)
        if not chunk:
            break
        body += chunk
    api.close(s)
    if len(body) != length:
        raise errors.SyscallError(errors.ECONNRESET, "truncated body")
    return 0


def udp_echo_server(api, port="5353"):
    s = api.udp_socket()
    s.bind(("0.0.0.0", int(port)))
    while True:
        data, src = yield from api.recvfrom(s)
        yield from api.sendto(s, data, src)


def udp_client(api, server="server", port="5353", count="10", interval_ms="100"):
    s = api.udp_socket()
    got = 0
    for i in range(int(count)):
        yield from api.sendto(s, b"ping-%d" % i, (server, int(port)))
        data, _src = yield from api.recvfrom(s)
        got += 1
        yield from api.sleep(int(interval_ms) * MS)
    assert got == int(count)
    return 0


def tgen_server(api, port="8888"):
    """Fixed-size transfer server: reads an 8-byte size request, streams
    that many bytes (tgen's fixed-size transfer model)."""
    lst = api.tcp_socket()
    lst.bind(("0.0.0.0", int(port)))
    lst.listen()
    chunk = bytes(range(256)) * 256  # 64 KiB pattern
    while True:
        conn = yield from api.accept(lst)
        req = yield from api.recv_exact(conn, 8)
        if len(req) == 8:
            want = int.from_bytes(req, "big")
            sent = 0
            while sent < want:
                n = yield from api.send(conn, chunk[: min(65536, want - sent)])
                sent += n
        api.close(conn)


def tgen_client(api, server="server", port="8888", size="1048576", count="1"):
    for _ in range(int(count)):
        s = api.tcp_socket()
        yield from api.connect(s, (server, int(port)))
        want = int(size)
        yield from api.send_all(s, want.to_bytes(8, "big"))
        body = yield from api.recv_exact(s, want)
        api.close(s)
        if len(body) != want:
            raise errors.SyscallError(errors.ECONNRESET, "short transfer")
    return 0


APP_REGISTRY: dict[str, Callable] = {
    "http-server": http_server,
    "http-client": http_client,
    "udp-echo-server": udp_echo_server,
    "udp-client": udp_client,
    "tgen-server": tgen_server,
    "tgen-client": tgen_client,
}


def resolve(path: str) -> Callable:
    """Map a config `path` to an app. Accepts bare names and ignores
    directory prefixes so configs can say `/bin/http-server`."""
    name = path.rsplit("/", 1)[-1]
    try:
        return APP_REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown app {path!r}; available: {sorted(APP_REGISTRY)}"
        ) from None
