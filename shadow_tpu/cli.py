"""Command-line entry point: `python -m shadow_tpu <config.yaml>`.

Parity: reference `src/main/shadow.rs` `run_shadow` — load + merge config
(CLI over file), init logging, create the data directory (refusing to
clobber an existing one), write `processed-config.yaml` for
reproducibility (`manager.rs:182-193`), run the simulation, write
`sim-stats.json` (`manager.rs:523-546`), and exit nonzero when any process
missed its expected final state (`controller.rs:69-73`).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import shutil
import sys

from .core import shadowlog, units
from .core.config import ConfigError, ConfigOptions, load_config_file
from .core.manager import Manager

# Documented exit codes (docs/robustness.md; asserted in tests/test_cli.py).
# 1 keeps its historical meaning — the SIMULATION failed (a process missed
# its expected final state, a mirrored transport diverged, a data dir was
# refused) — while configuration, watchdog, crash, guard, and capacity
# failures get their own codes so wrappers can tell "fix the config" from
# "file a bug" from "inspect the emergency checkpoint" from "the simulation
# failed its own runtime invariants" from "provision bigger rings (or go
# elastic)".
EXIT_OK = 0
EXIT_SIM_FAILURE = 1
EXIT_CONFIG = 2
EXIT_WATCHDOG = 3
EXIT_CRASH = 4
EXIT_GUARD = 5
EXIT_CAPACITY = 6


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="shadow_tpu",
        description="TPU-native discrete-event network simulator",
    )
    p.add_argument("config", help="simulation YAML config")
    p.add_argument("--seed", type=int, help="override general.seed")
    p.add_argument("--stop-time", help="override general.stop_time (e.g. 10s)")
    p.add_argument("--parallelism", type=int, help="worker parallelism")
    p.add_argument(
        "--scheduler",
        choices=["serial", "thread-per-core", "thread-per-host"],
        help="override experimental.scheduler",
    )
    p.add_argument(
        "--log-level",
        choices=["error", "warning", "info", "debug", "trace"],
        help="override general.log_level",
    )
    p.add_argument(
        "-d", "--data-directory", help="override general.data_directory"
    )
    p.add_argument(
        "-e",
        "--force",
        action="store_true",
        help="remove a pre-existing data directory instead of refusing",
    )
    p.add_argument(
        "--show-config", action="store_true",
        help="print the processed config and exit",
    )
    p.add_argument(
        "--telemetry", action="store_true",
        help="enable the telemetry harvester (overrides telemetry.enabled; "
             "heartbeat JSONL + Perfetto trace land in the data directory)",
    )
    p.add_argument(
        "--guards", action="store_true",
        help="enable the guard plane (overrides guards.enabled; runtime "
             "invariants + cross-plane reconciliation + progress "
             "detection under the configured per-class policies — see "
             "docs/robustness.md)",
    )
    p.add_argument(
        "--resume", metavar="CKPT",
        help="resume from a checkpoint directory (flow-engine runs: "
             "completed buckets are skipped and the continued run is "
             "bitwise-identical to an uninterrupted one; see "
             "docs/robustness.md)",
    )
    return p


def _apply_overrides(config: ConfigOptions, args) -> None:
    if args.seed is not None:
        config.general.seed = args.seed
    if args.stop_time is not None:
        config.general.stop_time = units.parse_duration_ns(args.stop_time)
    if args.parallelism is not None:
        config.general.parallelism = args.parallelism
    if args.scheduler is not None:
        config.experimental.scheduler = args.scheduler
    if args.data_directory is not None:
        config.general.data_directory = args.data_directory
    if args.telemetry:
        config.telemetry.enabled = True
    if args.guards:
        config.guards.enabled = True


def _config_as_dict(config: ConfigOptions) -> dict:
    import dataclasses
    import enum as _enum

    def conv(x):
        if dataclasses.is_dataclass(x) and not isinstance(x, type):
            return {k: conv(v) for k, v in dataclasses.asdict(x).items()}
        if isinstance(x, _enum.Enum):
            return x.value if not isinstance(x.value, int) else x.name.lower()
        if isinstance(x, dict):
            return {k: conv(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return [conv(v) for v in x]
        return x

    return {
        "general": conv(config.general),
        "network": conv(config.network),
        "experimental": conv(config.experimental),
        "telemetry": conv(config.telemetry),
        "faults": conv(config.faults),
        "guards": conv(config.guards),
        "capacity": conv(config.capacity),
        "strict": config.strict,
        "hosts": {name: conv(h) for name, h in config.hosts.items()},
    }


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        config = load_config_file(args.config)
    except Exception as e:
        print(f"shadow_tpu: config error: {e}", file=sys.stderr)
        return EXIT_CONFIG
    _apply_overrides(config, args)

    if args.show_config:
        json.dump(_config_as_dict(config), sys.stdout, indent=2)
        print()
        return 0

    level_name = args.log_level or config.general.log_level.name
    level = {"TRACE": logging.DEBUG}.get(
        str(level_name).upper(), getattr(logging, str(level_name).upper(), logging.INFO)
    )
    shadowlog.init_logging(level)
    log = logging.getLogger("shadow_tpu.cli")

    data_dir = config.general.data_directory
    if os.path.exists(data_dir):
        if args.resume:
            # resuming continues the SAME run: the data directory (which
            # usually holds the checkpoint being resumed) is reused in
            # place, never wiped — wiping would destroy the checkpoint
            pass
        elif not args.force:
            print(
                f"shadow_tpu: data directory {data_dir!r} exists "
                "(pass -e/--force to replace it)",
                file=sys.stderr,
            )
            return EXIT_SIM_FAILURE
        else:
            shutil.rmtree(data_dir)
    os.makedirs(data_dir, exist_ok=True)

    import yaml

    with open(os.path.join(data_dir, "processed-config.yaml"), "w") as fh:
        yaml.safe_dump(_config_as_dict(config), fh, sort_keys=False)

    from .core.capacity import CapacityError
    from .faults.checkpoint import CheckpointError
    from .faults.watchdog import WatchdogError
    from .guards.report import GuardError

    try:
        mgr = Manager(config, data_dir=data_dir)
        mgr.resume_from = args.resume
        log.info("simulation starting: %d hosts", len(mgr.hosts))
        stats = mgr.run()
    except ConfigError as e:
        print(f"shadow_tpu: config error: {e}", file=sys.stderr)
        return EXIT_CONFIG
    except CheckpointError as e:
        print(f"shadow_tpu: checkpoint error: {e}", file=sys.stderr)
        return EXIT_CONFIG
    except WatchdogError as e:
        # structured hang: blame is in the message, forensics in the
        # emergency checkpoint the Manager dropped before raising
        log.error("watchdog abort: %s", e)
        print(f"shadow_tpu: watchdog abort: {e}", file=sys.stderr)
        return EXIT_WATCHDOG
    except CapacityError as e:
        # a ring-full overflow under the strict capacity policy: the
        # run refused to silently diverge from the reference's
        # unbounded-queue semantics (docs/robustness.md "Elastic
        # capacity"); blame is in the message
        log.error("capacity abort: %s", e)
        print(f"shadow_tpu: capacity abort: {e}", file=sys.stderr)
        print(
            "shadow_tpu: raise the ring capacities or run "
            "capacity.mode: elastic (rings grow on demand, "
            "bitwise-identical to pre-provisioned)",
            file=sys.stderr,
        )
        return EXIT_CAPACITY
    except GuardError as e:
        # the simulation failed its OWN runtime invariants: the
        # violation report (guards-report.json) is in the data dir, and
        # an abort+checkpoint policy also left the emergency checkpoint
        # + finalized telemetry as a postmortem bundle
        log.error("guard abort: %s", e)
        print(f"shadow_tpu: guard abort: {e}", file=sys.stderr)
        print(
            f"shadow_tpu: violation report: "
            f"{os.path.join(data_dir, 'guards-report.json')}",
            file=sys.stderr,
        )
        return EXIT_GUARD
    except Exception:
        import traceback

        traceback.print_exc()
        print(
            "shadow_tpu: simulation crashed (see traceback above); an "
            "emergency checkpoint was dropped in the data directory's "
            "checkpoints/ if one could be written",
            file=sys.stderr,
        )
        return EXIT_CRASH
    log.info(
        "simulation finished: %d rounds, %d packets, %.2fs wall",
        stats.rounds, stats.packets_sent, stats.wall_seconds,
    )

    if mgr.harvester is not None:
        log.info(
            "telemetry: %d heartbeat lines over %d harvests -> %s",
            mgr.harvester.emitted, mgr.harvester.harvests,
            mgr.harvester.sink_path or "(log only)",
        )

    if mgr.guard_violations:
        # warn-policy violations: the run completed, but it failed its
        # own invariants — say so loudly and point at the report
        log.warning(
            "guards: %d violation(s) recorded under warn policy — see %s",
            len(mgr.guard_violations),
            os.path.join(data_dir, "guards-report.json"),
        )

    payload = stats.as_dict()
    payload["hosts"] = mgr.host_stats()
    with open(os.path.join(data_dir, "sim-stats.json"), "w") as fh:
        json.dump(payload, fh, indent=2)

    if stats.process_failures:
        for name, why in stats.process_failures:
            log.error("process failure: %s: %s", name, why)
        return EXIT_SIM_FAILURE
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
