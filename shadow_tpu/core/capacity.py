"""The capacity policy plane: ring sizing as an explicit, auditable policy.

Shadow's CPU queues are unbounded — a reference run never loses packets
to *simulator* capacity — but the TPU rebuild's SoA rings are fixed-size
(`tpu/plane.make_state(egress_cap, ingress_cap)`, the transport's
per-destination in-flight slots, the flow engine's segment rings).
Ring-full overflow used to be counted and silently dropped
(`n_overflow_dropped` / `drop_ring_full`), which is a fidelity hazard:
an under-provisioned run diverges from the reference semantics with no
recourse except guessing bigger caps. This module makes capacity a
first-class policy (docs/robustness.md "Elastic capacity"):

- ``fixed``   — today's behavior: overflow is counted, dropped, and
  surfaced in metrics/logs (plus a structured once-per-run capacity
  event, so the drop is never only a log line).
- ``strict``  — any ring-full overflow raises :class:`CapacityError`
  with per-host blame (CLI exit code 6): the run refuses to diverge.
- ``elastic`` — the headline: drivers snapshot state before each
  window, and on overflow the offending ring dimension DOUBLES (to the
  next power of two, bounded by ``max_doublings``) and the window
  re-executes from the snapshot, so the final stream is bitwise
  identical to a run pre-provisioned at the final capacity
  (docs/determinism.md "Growth is bitwise-invisible"). The device-side
  repack kernel lives in `tpu/elastic.grow_state`; this module is the
  jax-free policy/accounting half so the CLI, config, and flow engine
  can import it without pulling the device stack.

Every policy decision lands in a :class:`CapacityTrajectory` — the one
capacity record a run produces, shared by the window-step drivers
(bench.py, tools/chaos_smoke.py), `DeviceTransport`, and the flow
engine's queue-slot re-runs — and surfaced in sim-stats.json,
telemetry heartbeats, and trace instants.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

log = logging.getLogger("shadow_tpu.capacity")

#: valid `capacity.mode` values (core/config.py validates against this)
CAPACITY_MODES = ("fixed", "strict", "elastic")


class CapacityError(RuntimeError):
    """Ring-full overflow under the `strict` capacity policy (CLI exit
    code 6, docs/robustness.md): the simulation would have silently
    diverged from the reference's unbounded-queue semantics. Carries
    per-ring blame so the operator knows which dimension (and which
    hosts) to provision."""

    def __init__(self, message: str, *, ring: str = "",
                 blame: list | None = None):
        self.ring = ring
        self.blame = list(blame or [])
        if self.blame:
            shown = ", ".join(str(b) for b in self.blame[:8])
            more = (f" (+{len(self.blame) - 8} more)"
                    if len(self.blame) > 8 else "")
            message = f"{message} [blame: {shown}{more}]"
        super().__init__(message)


def next_pow2(n: int) -> int:
    """The smallest power of two >= n (n >= 1)."""
    return 1 << max(int(n) - 1, 0).bit_length()


@dataclass
class CapacityTrajectory:
    """The run's one capacity record: every growth / exhaustion / drop
    event across every capacity-bounded ring (device plane, transport,
    flow engine), in virtual-time order. Events are plain dicts so they
    ride sim-stats.json, heartbeat lines, and checkpoint meta
    unchanged."""

    mode: str = "fixed"
    events: list = field(default_factory=list)

    def record_growth(self, *, time_ns: int, ring: str, from_cap: int,
                      to_cap: int, overflow: int, plane: str) -> dict:
        ev = {
            "kind": "capacity-growth", "time_ns": int(time_ns),
            "ring": ring, "from": int(from_cap), "to": int(to_cap),
            "overflow": int(overflow), "plane": plane,
        }
        self.events.append(ev)
        log.warning(
            "capacity: %s ring %s grows %d -> %d at t=%d ns (%d "
            "packet(s) would have been ring-full drops; none were)",
            plane, ring, from_cap, to_cap, time_ns, overflow)
        return ev

    def record_drop(self, *, time_ns: int, ring: str, cap: int,
                    overflow: int, plane: str,
                    exhausted: bool = False) -> dict:
        """A ring-full drop that WILL happen (fixed mode, or elastic
        growth exhausted): structured once-per-event accounting, never
        just a log line."""
        ev = {
            "kind": ("capacity-exhausted" if exhausted
                     else "capacity-drop"),
            "time_ns": int(time_ns), "ring": ring, "cap": int(cap),
            "overflow": int(overflow), "plane": plane,
        }
        self.events.append(ev)
        log.error(
            "capacity: %s ring %s dropped %d packet(s) at cap %d "
            "(t=%d ns%s)", plane, ring, overflow, cap, time_ns,
            "; growth budget exhausted" if exhausted else
            "; capacity.mode=elastic would re-execute instead of drop")
        return ev

    def growth_events(self) -> list:
        return [e for e in self.events
                if e["kind"] == "capacity-growth"]

    def as_dict(self) -> dict:
        return {"mode": self.mode, "events": list(self.events)}


@dataclass
class RingPolicy:
    """Growth bookkeeping for the window-step drivers' two ring
    dimensions (egress CE / ingress CI). Doubling counts are per
    dimension and bounded by ``max_doublings``; growth targets are
    always powers of two so the Pallas kernels stay eligible
    (`tpu/pallas_egress.py`) and recompiles stay log2-bounded."""

    mode: str = "fixed"
    max_doublings: int = 3
    egress_cap: int = 16
    ingress_cap: int = 32
    plane: str = "plane"
    trajectory: CapacityTrajectory = None  # type: ignore[assignment]
    _eg_doublings: int = 0
    _in_doublings: int = 0
    _noted: frozenset = frozenset()  # rings with a drop/exhaustion noted

    def __post_init__(self):
        if self.mode not in CAPACITY_MODES:
            raise ValueError(
                f"capacity.mode: expected one of "
                f"{'|'.join(CAPACITY_MODES)}, got {self.mode!r}")
        if self.trajectory is None:
            self.trajectory = CapacityTrajectory(self.mode)

    def plan_growth(self, *, eg_overflow: int, in_overflow: int,
                    time_ns: int):
        """Decide the post-overflow ring sizes. Returns (new_ce, new_ci)
        when at least one dimension can grow (events recorded), or None
        when the growth budget is exhausted for every overflowing
        dimension (exhaustion recorded — the caller commits the
        overflowing attempt and the drops become real)."""
        new_ce, new_ci = self.egress_cap, self.ingress_cap
        if eg_overflow > 0 and self._eg_doublings < self.max_doublings:
            new_ce = next_pow2(self.egress_cap + 1)
            self._eg_doublings += 1
            self.trajectory.record_growth(
                time_ns=time_ns, ring="egress", from_cap=self.egress_cap,
                to_cap=new_ce, overflow=eg_overflow, plane=self.plane)
        if in_overflow > 0 and self._in_doublings < self.max_doublings:
            new_ci = next_pow2(self.ingress_cap + 1)
            self._in_doublings += 1
            self.trajectory.record_growth(
                time_ns=time_ns, ring="ingress",
                from_cap=self.ingress_cap, to_cap=new_ci,
                overflow=in_overflow, plane=self.plane)
        if (new_ce, new_ci) == (self.egress_cap, self.ingress_cap):
            if eg_overflow > 0:
                self.note_drop(ring="egress", overflow=eg_overflow,
                               time_ns=time_ns, exhausted=True)
            if in_overflow > 0:
                self.note_drop(ring="ingress", overflow=in_overflow,
                               time_ns=time_ns, exhausted=True)
            return None
        self.egress_cap, self.ingress_cap = new_ce, new_ci
        return new_ce, new_ci

    def to_meta(self) -> dict:
        """JSON-serializable policy snapshot for checkpoints: current
        caps, per-dimension growth budget consumed, the once-per-run
        drop-dedup set, and the trajectory so far. `restore_meta` is
        the inverse — together they own the bookkeeping, so drivers
        never reach into policy internals."""
        return {
            "mode": self.mode,
            "egress_cap": self.egress_cap,
            "ingress_cap": self.ingress_cap,
            "eg_doublings": self._eg_doublings,
            "in_doublings": self._in_doublings,
            "noted": sorted(self._noted),
            "events": list(self.trajectory.events),
        }

    def restore_meta(self, meta: dict) -> None:
        """Continue a checkpointed run with the same grown caps, the
        same remaining growth budget, the same drop dedup, and the
        same trajectory history."""
        self.egress_cap = int(meta["egress_cap"])
        self.ingress_cap = int(meta["ingress_cap"])
        self._eg_doublings = int(meta["eg_doublings"])
        self._in_doublings = int(meta["in_doublings"])
        self._noted = frozenset(meta.get("noted", ()))
        self.trajectory.events.extend(meta.get("events", ()))

    def note_drop(self, *, ring: str, overflow: int, time_ns: int,
                  exhausted: bool = False) -> None:
        """Structured ONCE-PER-RUN accounting of a ring that dropped
        (fixed mode, or elastic with the growth budget exhausted);
        per-window drop totals already live in the metrics plane, so
        the trajectory records the first occurrence, not a spam of
        repeats."""
        if ring in self._noted:
            return
        self._noted = self._noted | {ring}
        cap = self.egress_cap if ring == "egress" else self.ingress_cap
        self.trajectory.record_drop(
            time_ns=time_ns, ring=ring, cap=cap, overflow=overflow,
            plane=self.plane, exhausted=exhausted)
