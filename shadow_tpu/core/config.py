"""Simulation configuration: typed options, YAML loading, merge semantics.

Parity with the reference's three-layer config system
(`src/main/core/configuration.rs`):
- a YAML file provides `general`, `network`, `experimental`, `host_defaults`,
  and `hosts` sections (`configuration.rs:93`);
- CLI/programmatic overrides win field-by-field over the file, which wins
  over defaults (`configuration.rs:112-196`);
- `x-`-prefixed top-level extension keys are ignored so configs can hold YAML
  anchors (`shadow.rs:366-385`); standard YAML merge keys (`<<`) are resolved
  by the YAML loader;
- durations/sizes/rates accept typed units ("10s", "1 Gbit");
- the fully-resolved config can be re-serialized for reproducibility
  (`manager.rs:182-193`).
"""

from __future__ import annotations

import copy
import dataclasses
import enum
from dataclasses import dataclass, field
from typing import Any, Optional

import yaml

from . import simtime, units


class ConfigError(ValueError):
    pass


class LogLevel(enum.IntEnum):
    ERROR = 0
    WARNING = 1
    INFO = 2
    DEBUG = 3
    TRACE = 4

    @staticmethod
    def parse(text: str) -> "LogLevel":
        try:
            return LogLevel[text.upper()]
        except KeyError:
            raise ConfigError(f"unknown log level: {text!r}") from None


class QDiscMode(enum.Enum):
    """NIC queuing discipline (`configuration.rs:961`)."""

    FIFO = "fifo"
    ROUND_ROBIN = "round-robin"


class FinalState(enum.Enum):
    """Expected process end state (`configuration.rs:614`)."""

    RUNNING = "running"
    EXITED = "exited"
    SIGNALED = "signaled"


@dataclass
class ExpectedFinalState:
    kind: FinalState = FinalState.EXITED
    value: int = 0  # exit code or signal number

    @staticmethod
    def parse(raw: Any) -> "ExpectedFinalState":
        if raw is None:
            return ExpectedFinalState()
        if isinstance(raw, str):
            if raw == "running":
                return ExpectedFinalState(FinalState.RUNNING, 0)
            raise ConfigError(f"bad expected_final_state: {raw!r}")
        if isinstance(raw, dict) and len(raw) == 1:
            ((k, v),) = raw.items()
            if k == "exited":
                return ExpectedFinalState(FinalState.EXITED, int(v))
            if k == "signaled":
                return ExpectedFinalState(FinalState.SIGNALED, int(v))
        raise ConfigError(f"bad expected_final_state: {raw!r}")


@dataclass
class GeneralOptions:
    """`configuration.rs:197` GeneralOptions."""

    stop_time: int = 0  # ns; required
    seed: int = 1
    parallelism: int = 0  # 0 = auto (min(cores, hosts), manager.rs:248-298)
    bootstrap_end_time: int = 0  # ns; rate limits/loss bypassed before this
    log_level: LogLevel = LogLevel.INFO
    heartbeat_interval: Optional[int] = simtime.SECOND  # ns
    data_directory: str = "shadow.data"
    template_directory: Optional[str] = None
    progress: bool = False
    model_unblocked_syscall_latency: bool = False


@dataclass
class GraphSource:
    """`network.graph` — built-in or GML by file/inline."""

    type: str = "gml"  # "gml" | "1_gbit_switch"
    file_path: Optional[str] = None
    inline: Optional[str] = None


@dataclass
class NetworkOptions:
    """`configuration.rs:282` NetworkOptions."""

    graph: GraphSource = field(default_factory=GraphSource)
    use_shortest_path: bool = True


@dataclass
class ExperimentalOptions:
    """Subset of `configuration.rs:314-538` that is meaningful here; unknown
    keys are rejected loudly rather than silently dropped."""

    runahead: int = simtime.MILLISECOND  # lower bound on window size
    use_dynamic_runahead: bool = False
    interface_qdisc: QDiscMode = QDiscMode.FIFO
    socket_send_buffer: int = 131072
    socket_send_autotune: bool = True
    socket_recv_buffer: int = 174760
    socket_recv_autotune: bool = True
    use_cpu_pinning: bool = True
    # CPU oversubscription model (`sim_config.rs:173-174,248-249`): events
    # are deferred once unapplied native-execution delay exceeds the
    # threshold. None disables the model (the reference default); enabling
    # it trades determinism for realism since charges are wall-time based.
    cpu_threshold: Optional[int] = None
    cpu_precision: Optional[int] = 200  # ns, `sim_config.rs:249`
    use_worker_spinning: bool = True
    use_memory_manager: bool = False
    use_new_tcp: bool = False
    max_unapplied_cpu_latency: int = simtime.MICROSECOND
    unblocked_syscall_latency: int = simtime.MICROSECOND
    unblocked_vdso_latency: int = 10 * simtime.NANOSECOND
    host_heartbeat_interval: Optional[int] = simtime.SECOND
    strace_logging_mode: str = "off"  # off | standard | deterministic
    # perf timers (reference cargo feature `perf_timers`, `host.rs:142-143,
    # 722-730` + `handler/mod.rs:84-89`): wall-clock instrumentation of
    # host execution and per-syscall handler time; off by default since the
    # measured values are inherently nondeterministic
    use_perf_timers: bool = False
    # shadow libcrypto's RAND entry points with the deterministic
    # simulated-getrandom stream (`src/lib/preload-openssl/rng.c`)
    use_preload_openssl_rng: bool = True
    scheduler: str = "thread-per-core"  # thread-per-core | thread-per-host | serial
    use_tpu_net_plane: bool = True  # offload router/relay/latency/loss to TPU
    tpu_devices: Optional[int] = None  # None = all visible devices
    # route live inter-host transport through the device plane (one device
    # round trip per scheduling round); event order matches CPU transport
    use_tpu_transport: bool = False
    # sync: the device is authoritative — the round loop blocks on its
    #   released deliveries each window (right when the accelerator is
    #   locally attached: D2H is microseconds).
    # mirrored: the CPU pushes deliveries at capture (bitwise-identical to
    #   CPU transport by construction) while the device runs the same
    #   ingest/step sequence asynchronously and every window's released
    #   set is verified against the CPU ledger a few rounds later — zero
    #   blocking pulls, for links where a D2H pull costs milliseconds
    #   (e.g. a tunneled/disaggregated TPU; measured ~100 ms per fresh
    #   pull on the round-4 dev machine).
    # auto: probe the D2H round-trip at transport init and pick.
    tpu_transport_mode: str = "auto"  # auto | sync | mirrored
    # execute a tgen-shaped workload ENTIRELY on the device flow engine
    # (tpu/floweng.py): both TCP endpoints, wire, timers, and app model
    # advance inside lax.scan windows; completions reconcile into sim
    # stats. Errors out (FlowPlanError) if the config contains anything
    # but tgen-server/tgen-client processes — an explicit promise, not
    # a heuristic. See core/flowplan.py for the fidelity contract.
    use_flow_engine: bool = False
    # per-host filesystem view for managed native processes: absolute
    # non-system paths redirect under the host's data dir (read-through
    # to the real path for base-layer files), so two hosts writing
    # /tmp/shared.log never collide (reference file.c/fileat.c role,
    # re-designed as namespace redirection; see BASELINE.md)
    host_path_isolation: bool = True
    tpu_egress_cap: int = 256  # per-host device egress slots
    tpu_ingress_cap: int = 256  # per-host device in-flight slots
    tpu_compact_cap: int = 4096  # per-window compacted-delivery slots
    # device-plane fused-kernel selector: "xla" = the packed-key sort
    # diet + bucketed routing path (default); "pallas" = the fused
    # Pallas kernels for egress (tpu/pallas_egress.py) and routing
    # (tpu/pallas_route.py; FIFO qdisc only, bitwise-identical,
    # interpret mode off-TPU). Governs the general plane's window_step
    # drivers (bench.py via BENCH_PLANE_KERNEL, tools/profile_plane.py
    # --kernel); the use_tpu_transport path has its own kernels and
    # does not consult this — Manager-driven runs therefore warn
    # loudly (ConfigError under `strict: true`) when it is set. See
    # docs/performance.md.
    plane_kernel: str = "xla"


@dataclass
class FlightRecorderOptions:
    """`telemetry.flight_recorder` — the sampled per-packet hop
    recorder (docs/observability.md "Distributions and the flight
    recorder"). `sample_every` = K tags ~1/K packets with a seeded
    deterministic mask (pure function of (seed, src, seq)); `ring` is
    the device-side trace-ring capacity (overflow is counted and
    reported loudly, and the ring participates in elastic capacity
    growth). Consumed by the device-plane window drivers (bench.py,
    tools/chaos_smoke.py, tools/run_scenarios.py); Manager-driven runs
    warn that hop tracing is not executed there (ConfigError under
    top-level `strict: true`)."""

    enabled: bool = False
    sample_every: int = 64
    ring: int = 4096


@dataclass
class TelemetryOptions:
    """The `telemetry:` config block (no reference counterpart — this
    rebuild's device plane needs its own observability; see
    docs/observability.md).

    `interval` is VIRTUAL time between harvests. `sink` is the heartbeat
    JSONL path (default: <data_dir>/telemetry.jsonl when a data dir
    exists; "off" = log-summary-only). `trace` is the Perfetto
    trace.json output path (default: <data_dir>/trace.json when
    enabled; "off" disables). `per_host` emits one heartbeat line per
    host per harvest in addition to the run summary line — turn off for
    very large fleets. `histograms` threads the log2-bucketed
    latency/queue-depth distributions (`telemetry/histo.py`) through
    the device kernels; `flight_recorder` configures the sampled
    per-packet hop recorder. Not supported on the flow-engine path
    (`experimental.use_flow_engine`), which never runs the round loop —
    enabling both logs a warning."""

    enabled: bool = False
    interval: int = simtime.SECOND  # virtual ns between harvests
    sink: Optional[str] = None
    trace: Optional[str] = None
    per_host: bool = True
    histograms: bool = False
    flight_recorder: FlightRecorderOptions = field(
        default_factory=FlightRecorderOptions)


@dataclass
class CapacityOptions:
    """The `capacity:` config block (docs/robustness.md "Elastic
    capacity") — the ring-sizing policy for every capacity-bounded
    ring: the device plane's egress/ingress rings, the transport's
    per-destination in-flight slots, and the flow engine's segment
    rings (`core/capacity.py`).

    - `fixed`   — overflow is counted and dropped (today's behavior),
      with a structured once-per-run capacity event so the drop is
      never only a log line.
    - `strict`  — any ring-full overflow raises `CapacityError` with
      per-host blame (CLI exit code 6): the run refuses to silently
      diverge from the reference's unbounded-queue semantics.
    - `elastic` — rings DOUBLE (next power of two, bounded by
      `max_doublings` per ring dimension) and the window re-executes
      from the pre-window snapshot, so the final stream is bitwise
      identical to a run pre-provisioned at the final capacity
      (docs/determinism.md "Growth is bitwise-invisible").

    Top-level `strict: true` additionally promotes `fixed`-mode ring
    drops to the strict failure (a strict caller never silently loses
    packets to simulator capacity)."""

    mode: str = "fixed"  # fixed | strict | elastic
    max_doublings: int = 3


@dataclass
class WorkloadOptions:
    """The `workload:` config block (no reference counterpart — the
    workload plane, docs/workloads.md): a declarative traffic
    scenario riding the device plane.

    `scenario` names a standalone scenario YAML (the DSL in
    `shadow_tpu/workloads/spec.py`); "off" is the explicit-disable
    sentinel (YAML 1.1 parses a bare ``off`` as boolean False — the
    same footgun `telemetry.sink` and `strace_logging_mode` already
    harden against — and a bare ``on`` maps to None, i.e. "enabled,
    path supplied elsewhere"). The whole block also accepts the bare
    spellings: ``workload: off`` / ``workload: on``. `seed` overrides
    the scenario's own seed (and `general.seed`) for the compiled
    traffic program.

    Manager-driven runs do not execute workload scenarios — the corpus
    runner consumes this block instead (`tools/run_scenarios.py
    --config sim.yaml` resolves `scenario` relative to the config file
    and applies the `seed` override): declaring the block on a Manager
    run warns loudly, ConfigError under top-level `strict: true`."""

    enabled: bool = False
    scenario: Optional[str] = None
    seed: Optional[int] = None


@dataclass
class FlowsOptions:
    """The `flows:` config block (no reference counterpart — the
    device flow plane, docs/robustness.md "Flow plane"): RTO
    retransmit + congestion backpressure for scenario traffic.

    `emit_cap` bounds the data segments one flow emits per window
    (cwnd beyond it carries to the next window); `recv_wnd` sizes the
    receiver's out-of-order bitmap in segments (and therefore the
    sender's effective window clamp). Like the workload plane and the
    flight recorder, the flow plane rides the device-plane WINDOW
    DRIVERS only (`tools/run_scenarios.py` executes scenarios whose
    spec declares ``transport: flows``); declaring the block on a
    Manager-driven run warns loudly, ConfigError under top-level
    `strict: true`. The whole block accepts the bare YAML 1.1
    spellings ``flows: off`` / ``flows: on``."""

    enabled: bool = False
    emit_cap: int = 8
    recv_wnd: int = 64


@dataclass
class MemoOptions:
    """The `memo:` config block (no reference counterpart — the
    steady-state memo plane, `tpu/memo.py`, docs/performance.md
    "Steady-state memoization"): chain-level delta replay for
    periodic/quiescent traffic, with replay pinned bitwise-equal to
    re-execution by the golden corpus parity gate.

    `max_bytes` bounds the LRU replay cache; `min_repeat` is how many
    times a span key must recur before its delta is recorded (1 =
    record on first sight); `chain_len` is the memo span length in
    windows when no telemetry cadence dictates one (shorter spans find
    more recurrences in a short drained tail, longer spans amortize
    the per-boundary host snapshot). Like the flow plane, memoization
    rides the device-plane WINDOW DRIVERS only (`tools/run_scenarios.py
    --memo`); the block accepts the bare YAML 1.1 spellings
    ``memo: off`` / ``memo: on``."""

    enabled: bool = False
    max_bytes: int = 64 << 20
    min_repeat: int = 1
    chain_len: int = 4


#: valid per-class guard policies (guards/report.py shares this set)
GUARD_POLICIES = ("off", "warn", "abort", "abort+checkpoint")


@dataclass
class GuardsOptions:
    """The `guards:` config block (docs/robustness.md "Guard plane") —
    runtime self-verification of the simulation against itself.

    Three guard classes, each with its own policy:

    - `device`    — on-device conservation/structure invariants threaded
      through the device kernels (`tpu/plane.window_step(..., guards=)`
      and the `DeviceTransport` kernels);
    - `reconcile` — cross-plane reconciliation of device counters
      against independent CPU ledgers and SimStats fleet totals, at
      telemetry harvest boundaries and teardown;
    - `progress`  — the round-loop zero-progress livelock detector
      (`progress_rounds` consecutive stalled rounds trip it).

    Policies: `off` | `warn` (log each violation, keep running) |
    `abort` (raise GuardError, CLI exit 5) | `abort+checkpoint` (abort
    plus the emergency checkpoint + finalized telemetry — a full
    postmortem bundle). `enabled: false` (the default) turns the whole
    plane off regardless of per-class policies, so `guards: {enabled:
    true}` activates the warn-everything default in one line."""

    enabled: bool = False
    device: str = "warn"
    reconcile: str = "warn"
    progress: str = "warn"
    progress_rounds: int = 64

    def active(self, cls: str) -> bool:
        return self.enabled and getattr(self, cls) != "off"


@dataclass
class FaultCheckpointOptions:
    """`faults.checkpoint` — periodic sim-state checkpoints
    (docs/robustness.md). `interval` is VIRTUAL time between
    checkpoints (None = only the emergency checkpoint on a crash).
    `directory` defaults to <data_dir>/checkpoints. `keep` bounds how
    many periodic checkpoints are retained (oldest pruned)."""

    interval: Optional[int] = None  # virtual ns; None = off
    directory: Optional[str] = None
    keep: int = 2


@dataclass
class FaultsOptions:
    """The `faults:` config block (no reference counterpart — failure
    as a first-class, seeded simulation input; docs/robustness.md).

    `events` is a list of raw event mappings and `random` a mapping of
    seeded generators — both compiled and validated by
    `faults/schedule.compile_schedule` (at Manager build time, so a bad
    event is a ConfigError before anything runs). `watchdog` is the
    WALL-clock round timeout (a hung managed process becomes a
    structured WatchdogError instead of a wedged simulator; wall time
    here can only change failure detection, never results). `seed`
    overrides `general.seed` for the fault-schedule RNG stream.
    `kernel_fallback` lets a failing Pallas plane kernel degrade to the
    XLA path (logged loudly) instead of killing the run;
    `device_retries`/`retry_backoff`/`retry_cap`/`retry_jitter` govern
    the transient-device-error retry loop around transport dispatches:
    exponential backoff from `retry_backoff`, capped at `retry_cap`,
    with seeded jitter shaving up to `retry_jitter` (a [0,1] fraction)
    off each delay — the whole sleep schedule is a pure function of
    the config (faults/healing.backoff_schedule), so retry timing is
    replicable in postmortems."""

    seed: Optional[int] = None
    events: list = field(default_factory=list)
    random: Optional[dict] = None
    respawn_on_reboot: bool = True
    watchdog: Optional[int] = None  # WALL ns
    kernel_fallback: bool = True
    device_retries: int = 3
    retry_backoff: int = 50 * simtime.MILLISECOND  # WALL ns
    retry_cap: int = 2 * simtime.SECOND  # WALL ns, backoff ceiling
    retry_jitter: float = 0.5  # [0,1] fraction shaved per delay
    checkpoint: FaultCheckpointOptions = field(
        default_factory=FaultCheckpointOptions)

    def any_injection(self) -> bool:
        return bool(self.events or self.random)


@dataclass
class HostDefaultOptions:
    """`configuration.rs:551` — per-host options with global defaults.

    All fields default to None ("unset") so an explicit per-host value — even
    one equal to the global default, like `pcap_enabled: false` overriding a
    global `true` — is distinguishable from "not specified".
    """

    log_level: Optional[LogLevel] = None
    pcap_enabled: Optional[bool] = None
    pcap_capture_size: Optional[int] = None

    def merged_with(self, override: "HostDefaultOptions") -> "HostDefaultOptions":
        out = copy.copy(self)
        for f in dataclasses.fields(override):
            v = getattr(override, f.name)
            if v is not None:
                setattr(out, f.name, v)
        return out

    def resolved(self) -> "HostDefaultOptions":
        """Concrete values with hard defaults filled in for unset fields."""
        out = copy.copy(self)
        if out.pcap_enabled is None:
            out.pcap_enabled = False
        if out.pcap_capture_size is None:
            out.pcap_capture_size = 65535
        return out


@dataclass
class ProcessOptions:
    """`configuration.rs:644` ProcessOptions."""

    path: str = ""
    args: list[str] = field(default_factory=list)
    environment: dict[str, str] = field(default_factory=dict)
    start_time: int = 0  # ns
    shutdown_time: Optional[int] = None  # ns
    shutdown_signal: int = 15  # SIGTERM
    expected_final_state: ExpectedFinalState = field(default_factory=ExpectedFinalState)


@dataclass
class HostOptions:
    """`configuration.rs:675` HostOptions."""

    network_node_id: int = 0
    processes: list[ProcessOptions] = field(default_factory=list)
    ip_addr: Optional[str] = None
    bandwidth_down: Optional[int] = None  # bits/sec; overrides graph node
    bandwidth_up: Optional[int] = None
    host_options: HostDefaultOptions = field(default_factory=HostDefaultOptions)


@dataclass
class ConfigOptions:
    general: GeneralOptions = field(default_factory=GeneralOptions)
    network: NetworkOptions = field(default_factory=NetworkOptions)
    experimental: ExperimentalOptions = field(default_factory=ExperimentalOptions)
    telemetry: TelemetryOptions = field(default_factory=TelemetryOptions)
    faults: FaultsOptions = field(default_factory=FaultsOptions)
    guards: GuardsOptions = field(default_factory=GuardsOptions)
    capacity: CapacityOptions = field(default_factory=CapacityOptions)
    workload: WorkloadOptions = field(default_factory=WorkloadOptions)
    flows: FlowsOptions = field(default_factory=FlowsOptions)
    memo: MemoOptions = field(default_factory=MemoOptions)
    host_defaults: HostDefaultOptions = field(default_factory=HostDefaultOptions)
    hosts: dict[str, HostOptions] = field(default_factory=dict)
    # strict mode: unsupported feature combinations that normally
    # log-and-ignore (flow-engine runs configured with fault injection,
    # the watchdog, telemetry, or guards) become ConfigErrors (exit 2)
    # instead — for CI and wrappers that must not silently lose a
    # requested feature
    strict: bool = False


# ---------------------------------------------------------------------------
# Parsing
# ---------------------------------------------------------------------------

_DUR_FIELDS = {
    "stop_time",
    "bootstrap_end_time",
    "heartbeat_interval",
    "start_time",
    "shutdown_time",
    "runahead",
    "max_unapplied_cpu_latency",
    "unblocked_syscall_latency",
    "unblocked_vdso_latency",
    "host_heartbeat_interval",
    "interval",  # telemetry.interval / faults.checkpoint.interval
    "watchdog",  # faults.watchdog (WALL-clock round timeout)
    "retry_backoff",  # faults.retry_backoff (WALL-clock)
    "retry_cap",  # faults.retry_cap (WALL-clock backoff ceiling)
}
_RATE_FIELDS = {"bandwidth_down", "bandwidth_up"}
_BYTE_FIELDS = {"socket_send_buffer", "socket_recv_buffer", "pcap_capture_size"}


def _coerce(name: str, value: Any, default: Any) -> Any:
    if value is None:
        return None
    if name in _DUR_FIELDS:
        return units.parse_duration_ns(value)
    if name in _RATE_FIELDS:
        return units.parse_bits_per_sec(value)
    if name in _BYTE_FIELDS:
        return units.parse_bytes(value)
    if name in ("sink", "trace", "scenario"):
        # telemetry.sink / telemetry.trace / workload.scenario: YAML
        # 1.1 parses bare `off` as False and bare `on` as True (same
        # trap as strace_logging_mode below). off -> the "off"
        # sentinel; on -> None, i.e. "enabled at the default path".
        if value is False:
            return "off"
        if value is True:
            return None
        if not isinstance(value, str):
            raise ConfigError(
                f"{name}: expected a path, on, or off, got {value!r}")
        return value
    if name in ("device", "reconcile", "progress") \
            and isinstance(default, str) and value is False:
        # guard policy fields: YAML 1.1 parses a bare `off` as boolean
        # False (same trap as strace_logging_mode / telemetry.sink).
        # The default-type check keeps the boolean general.progress
        # flag out of this mapping.
        return "off"
    if name == "log_level":
        return LogLevel.parse(value)
    if name == "interface_qdisc":
        return QDiscMode(value)
    if name == "expected_final_state":
        return ExpectedFinalState.parse(value)
    if name == "args":
        return value.split() if isinstance(value, str) else [str(a) for a in value]
    if name == "environment":
        return {str(k): str(v) for k, v in (value or {}).items()}
    # Scalar fields: validate against the type of the field's default so a
    # wrong-typed YAML value fails here, not deep inside the simulation.
    if isinstance(default, bool):
        if not isinstance(value, bool):
            raise ConfigError(f"{name}: expected a boolean, got {value!r}")
    elif isinstance(default, int):
        if isinstance(value, bool) or not isinstance(value, int):
            raise ConfigError(f"{name}: expected an integer, got {value!r}")
    elif isinstance(default, str):
        if isinstance(value, bool) and name == "strace_logging_mode":
            # YAML 1.1 parses a bare `off` as boolean False; the reference
            # accepts `strace_logging_mode: off` literally, so map it back
            if value is False:
                return "off"
            raise ConfigError(
                f"{name}: expected off|standard|deterministic, got true")
        if not isinstance(value, str):
            raise ConfigError(f"{name}: expected a string, got {value!r}")
    return value


def _fill_dataclass(cls, raw: dict, where: str):
    if raw is None:
        raw = {}
    if not isinstance(raw, dict):
        raise ConfigError(f"{where}: expected a mapping, got {type(raw).__name__}")
    known = {f.name: f for f in dataclasses.fields(cls)}
    obj = cls()
    for key, value in raw.items():
        key = str(key)
        if key.startswith("x-"):
            continue
        if key not in known:
            raise ConfigError(f"{where}: unknown option {key!r}")
        f = known[key]
        if f.name == "graph":
            setattr(obj, key, _parse_graph(value))
        elif f.name == "processes":
            setattr(
                obj,
                key,
                [_fill_dataclass(ProcessOptions, p, f"{where}.processes[{i}]")
                 for i, p in enumerate(value or [])],
            )
        elif f.name == "host_options":
            setattr(obj, key, _fill_dataclass(HostDefaultOptions, value, f"{where}.host_options"))
        elif f.name == "checkpoint" and cls is FaultsOptions:
            setattr(obj, key, _fill_dataclass(
                FaultCheckpointOptions, value, f"{where}.checkpoint"))
        elif f.name == "flight_recorder" and cls is TelemetryOptions:
            # YAML 1.1 sub-block hardening: a bare `flight_recorder:
            # off/on` parses as a boolean — coerce to the disabled/
            # enabled default block like the `workload:` block does
            if value is False:
                setattr(obj, key, FlightRecorderOptions(enabled=False))
            elif value is True:
                setattr(obj, key, FlightRecorderOptions(enabled=True))
            else:
                setattr(obj, key, _fill_dataclass(
                    FlightRecorderOptions, value,
                    f"{where}.flight_recorder"))
        elif f.name in ("events", "random") and cls is FaultsOptions:
            # raw event/generator mappings; validated by
            # faults/schedule.compile_schedule at Manager build time
            if f.name == "events" and value is not None \
                    and not isinstance(value, list):
                raise ConfigError(f"{where}.events: expected a list")
            if f.name == "random" and value is not None \
                    and not isinstance(value, dict):
                raise ConfigError(f"{where}.random: expected a mapping")
            setattr(obj, key, value if value is not None
                    else getattr(obj, key))
        else:
            setattr(obj, key, _coerce(key, value, getattr(obj, f.name)))
    return obj


def _parse_graph(raw: dict) -> GraphSource:
    if not isinstance(raw, dict) or "type" not in raw:
        raise ConfigError("network.graph: requires a 'type'")
    g = GraphSource(type=raw["type"])
    if g.type == "gml":
        g.file_path = raw.get("file", {}).get("path") if isinstance(raw.get("file"), dict) else raw.get("file")
        g.inline = raw.get("inline")
        if (g.file_path is None) == (g.inline is None):
            raise ConfigError("network.graph: gml needs exactly one of 'file' or 'inline'")
    elif g.type != "1_gbit_switch":
        raise ConfigError(f"network.graph: unknown type {g.type!r}")
    return g


def parse_config_dict(raw: dict) -> ConfigOptions:
    if not isinstance(raw, dict):
        raise ConfigError("config root must be a mapping")
    cfg = ConfigOptions()
    for key, value in raw.items():
        key = str(key)
        if key.startswith("x-"):
            continue  # extension keys hold YAML anchors (shadow.rs:366-385)
        if key == "general":
            cfg.general = _fill_dataclass(GeneralOptions, value, "general")
        elif key == "network":
            cfg.network = _fill_dataclass(NetworkOptions, value, "network")
        elif key == "experimental":
            cfg.experimental = _fill_dataclass(ExperimentalOptions, value, "experimental")
        elif key == "telemetry":
            cfg.telemetry = _fill_dataclass(TelemetryOptions, value, "telemetry")
        elif key == "faults":
            cfg.faults = _fill_dataclass(FaultsOptions, value, "faults")
        elif key == "guards":
            cfg.guards = _fill_dataclass(GuardsOptions, value, "guards")
        elif key == "capacity":
            cfg.capacity = _fill_dataclass(CapacityOptions, value,
                                           "capacity")
        elif key == "workload":
            # YAML 1.1 block-level hardening: a bare `workload: off` /
            # `workload: on` parses as a boolean — coerce to the
            # disabled/enabled default block instead of dying on
            # "expected a mapping" (docs/workloads.md)
            if value is False:
                cfg.workload = WorkloadOptions(enabled=False)
            elif value is True:
                cfg.workload = WorkloadOptions(enabled=True)
            else:
                cfg.workload = _fill_dataclass(WorkloadOptions, value,
                                               "workload")
        elif key == "flows":
            # same YAML 1.1 bare off/on hardening as the workload and
            # flight_recorder blocks (docs/robustness.md "Flow plane")
            if value is False:
                cfg.flows = FlowsOptions(enabled=False)
            elif value is True:
                cfg.flows = FlowsOptions(enabled=True)
            else:
                cfg.flows = _fill_dataclass(FlowsOptions, value,
                                            "flows")
        elif key == "memo":
            # same YAML 1.1 bare off/on hardening as the flows block
            if value is False:
                cfg.memo = MemoOptions(enabled=False)
            elif value is True:
                cfg.memo = MemoOptions(enabled=True)
            else:
                cfg.memo = _fill_dataclass(MemoOptions, value, "memo")
        elif key == "strict":
            if not isinstance(value, bool):
                raise ConfigError(
                    f"strict: expected a boolean, got {value!r}")
            cfg.strict = value
        elif key in ("host_defaults", "host_option_defaults"):
            cfg.host_defaults = _fill_dataclass(HostDefaultOptions, value, key)
        elif key == "hosts":
            for name, hraw in (value or {}).items():
                _validate_hostname(name)
                cfg.hosts[str(name)] = _fill_dataclass(HostOptions, hraw, f"hosts.{name}")
        else:
            raise ConfigError(f"unknown top-level config section {key!r}")
    if cfg.general.stop_time <= 0:
        raise ConfigError("general.stop_time is required and must be positive")
    # the device plane's window budget (SL506 input-domain registry,
    # analysis/ranges.py: window_ns <= I32_MAX//4): runahead is the
    # window-length floor, and a window beyond a quarter of the int32-ns
    # range breaks the rebase/deliver arithmetic the range proof
    # guarantees — fail at parse, not as silent wraparound mid-run
    if cfg.experimental.runahead < 1:
        raise ConfigError("experimental.runahead must be a positive "
                          "duration")
    if cfg.experimental.runahead > (2**31 - 1) // 4:
        raise ConfigError(
            f"experimental.runahead ({cfg.experimental.runahead} ns) "
            f"exceeds the device window budget of I32_MAX//4 ns "
            "(~0.53 s): the int32-ns window arithmetic the SL506 range "
            "proof covers (docs/determinism.md) requires windows "
            "within a quarter of the int32 range")
    if not cfg.hosts:
        raise ConfigError("at least one host is required")
    if cfg.experimental.plane_kernel not in ("xla", "pallas",
                                             "pallas_fused"):
        raise ConfigError(
            f"experimental.plane_kernel: expected 'xla', 'pallas', or "
            f"'pallas_fused', got {cfg.experimental.plane_kernel!r}")
    for cap_name in ("tpu_egress_cap", "tpu_ingress_cap",
                     "tpu_compact_cap"):
        if getattr(cfg.experimental, cap_name) < 1:
            raise ConfigError(f"experimental.{cap_name} must be >= 1")
    if cfg.experimental.plane_kernel != "xla":
        kname = cfg.experimental.plane_kernel
        ce = cfg.experimental.tpu_egress_cap
        if ce & (ce - 1):
            # the fused Pallas egress kernels' bitonic row sorts need a
            # power-of-two egress ring (tpu/pallas_egress.py /
            # tpu/pallas_pipeline.py); failing HERE beats the opaque
            # trace-time death it used to be. Elastic growth always
            # targets powers of two, so an elastic run never grows its
            # way out of pallas eligibility.
            raise ConfigError(
                f"experimental.plane_kernel: {kname!r} requires a "
                f"power-of-two egress capacity (the fused kernel's "
                f"bitonic row sort), got tpu_egress_cap={ce}; pick a "
                f"power of two or use plane_kernel: xla")
        ci = cfg.experimental.tpu_ingress_cap
        if kname == "pallas_fused" and ci & (ci - 1):
            # the fused pipeline additionally compacts the ingress ring
            # in-kernel (tpu/pallas_pipeline.py kernel B)
            raise ConfigError(
                f"experimental.plane_kernel: 'pallas_fused' requires a "
                f"power-of-two ingress capacity (the fused compaction "
                f"bitonic), got tpu_ingress_cap={ci}; pick a power of "
                f"two or use plane_kernel: xla|pallas")
    from .capacity import CAPACITY_MODES

    if cfg.capacity.mode not in CAPACITY_MODES:
        raise ConfigError(
            f"capacity.mode: expected one of "
            f"{'|'.join(CAPACITY_MODES)}, got {cfg.capacity.mode!r}")
    if cfg.capacity.max_doublings < 0:
        raise ConfigError("capacity.max_doublings must be >= 0")
    # unconditional (not just when enabled): the CLI --telemetry flag can
    # flip `enabled` on AFTER parsing, and a bad interval must fail here
    # as a ConfigError, not mid-run inside the harvester
    if cfg.telemetry.interval is None or cfg.telemetry.interval <= 0:
        raise ConfigError("telemetry.interval must be a positive duration")
    if cfg.telemetry.flight_recorder.sample_every < 1:
        raise ConfigError(
            "telemetry.flight_recorder.sample_every must be >= 1")
    if cfg.telemetry.flight_recorder.ring < 1:
        raise ConfigError("telemetry.flight_recorder.ring must be >= 1")
    # flows knobs validate unconditionally like the flight recorder's:
    # the corpus runner consults them whether or not a Manager run
    # would, and a bad bound must die at parse, never at trace time
    if cfg.flows.emit_cap < 1:
        raise ConfigError("flows.emit_cap must be >= 1")
    if cfg.flows.recv_wnd < 1:
        raise ConfigError("flows.recv_wnd must be >= 1")
    if cfg.flows.emit_cap > cfg.flows.recv_wnd:
        raise ConfigError(
            f"flows.emit_cap ({cfg.flows.emit_cap}) must not exceed "
            f"flows.recv_wnd ({cfg.flows.recv_wnd}): a window's "
            "emission burst has to fit the receiver's reorder bitmap "
            "or the tail would be discarded on arrival by design")
    # memo knobs validate unconditionally for the same reason (the
    # CLI --memo flag flips `enabled` after parsing)
    if cfg.memo.max_bytes < 1:
        raise ConfigError("memo.max_bytes must be >= 1")
    if cfg.memo.min_repeat < 1:
        raise ConfigError("memo.min_repeat must be >= 1")
    if cfg.memo.chain_len < 1:
        raise ConfigError("memo.chain_len must be >= 1")
    if cfg.faults.checkpoint.interval is not None \
            and cfg.faults.checkpoint.interval <= 0:
        raise ConfigError(
            "faults.checkpoint.interval must be a positive duration")
    if cfg.faults.checkpoint.keep < 1:
        raise ConfigError("faults.checkpoint.keep must be >= 1")
    if cfg.faults.watchdog is not None and cfg.faults.watchdog <= 0:
        raise ConfigError("faults.watchdog must be a positive duration")
    if cfg.faults.device_retries < 0:
        raise ConfigError("faults.device_retries must be >= 0")
    if cfg.faults.retry_backoff < 0:
        raise ConfigError("faults.retry_backoff must be >= 0")
    if cfg.faults.retry_cap < cfg.faults.retry_backoff:
        raise ConfigError("faults.retry_cap must be >= faults."
                          "retry_backoff (it is the backoff ceiling)")
    if not 0.0 <= cfg.faults.retry_jitter <= 1.0:
        raise ConfigError("faults.retry_jitter must be in [0, 1]")
    if cfg.workload.seed is not None and cfg.workload.seed < 0:
        raise ConfigError("workload.seed must be >= 0")
    for cls in ("device", "reconcile", "progress"):
        policy = getattr(cfg.guards, cls)
        if policy not in GUARD_POLICIES:
            raise ConfigError(
                f"guards.{cls}: expected one of "
                f"{'|'.join(GUARD_POLICIES)}, got {policy!r}")
    if cfg.guards.progress_rounds <= 0:
        raise ConfigError("guards.progress_rounds must be positive")
    return cfg


def _validate_hostname(name: str) -> None:
    if not name or not all(c.isalnum() or c in ".-_" for c in str(name)):
        raise ConfigError(f"invalid hostname {name!r}")


def load_config_file(path: str, overrides: Optional[dict] = None) -> ConfigOptions:
    with open(path) as fh:
        raw = yaml.safe_load(fh)
    return parse_config(raw, overrides)


def load_config_str(text: str, overrides: Optional[dict] = None) -> ConfigOptions:
    return parse_config(yaml.safe_load(text), overrides)


def parse_config(raw: dict, overrides: Optional[dict] = None) -> ConfigOptions:
    """Parse a raw config mapping, applying CLI-style overrides field-by-field
    (overrides win over file values, which win over defaults).

    Overrides use the same YAML-level value forms as the file: durations are
    unit strings ("10s") or bare numbers meaning SECONDS (reference parity:
    `stop_time: 10` in the reference's own configs means 10 s) — never raw
    nanosecond ints.
    """
    if raw is None:
        raw = {}  # empty YAML document; required-field errors fire below
    if overrides:
        raw = _deep_merge(copy.deepcopy(raw), overrides)
    return parse_config_dict(raw)


def _deep_merge(base: dict, over: dict) -> dict:
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(base.get(k), dict):
            _deep_merge(base[k], v)
        else:
            base[k] = v
    return base


def to_processed_dict(cfg: ConfigOptions) -> dict:
    """Fully-resolved config as plain data, suitable for re-serialization to
    `processed-config.yaml` (`manager.rs:182-193`)."""

    def conv(obj):
        if dataclasses.is_dataclass(obj):
            return {f.name: conv(getattr(obj, f.name)) for f in dataclasses.fields(obj)}
        if isinstance(obj, enum.Enum):
            return obj.name.lower() if isinstance(obj, LogLevel) else obj.value
        if isinstance(obj, dict):
            return {k: conv(v) for k, v in obj.items()}
        if isinstance(obj, list):
            return [conv(v) for v in obj]
        return obj

    return conv(cfg)
