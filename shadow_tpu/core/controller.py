"""The simulation time authority: round windows and runahead.

Parity: reference `src/main/core/controller.rs` (window =
`[min_next_event, min_next_event + runahead)` clipped to the end time,
`controller.rs:80-113`) and `src/main/core/runahead.rs` (static runahead =
min possible graph latency; dynamic = min latency actually used so far; both
floored by the config lower bound).
"""

from __future__ import annotations

import threading
from typing import Optional


class Runahead:
    def __init__(
        self,
        is_dynamic: bool,
        min_possible_latency_ns: int,
        min_runahead_config_ns: Optional[int],
    ):
        assert min_possible_latency_ns > 0
        self._is_dynamic = is_dynamic
        self._min_possible = min_possible_latency_ns
        self._min_config = min_runahead_config_ns or 0
        self._min_used: Optional[int] = None
        self._lock = threading.Lock()

    def get(self) -> int:
        used = self._min_used if self._min_used is not None else self._min_possible
        return max(used, self._min_config)

    def update_lowest_used_latency(self, latency_ns: int) -> None:
        assert latency_ns > 0
        if not self._is_dynamic:
            return
        if self._min_used is not None and latency_ns >= self._min_used:
            return
        with self._lock:
            if self._min_used is None or latency_ns < self._min_used:
                self._min_used = latency_ns


class Controller:
    """Owns the simulation end time; computes each next scheduling window."""

    def __init__(self, stop_time_ns: int, runahead: Runahead):
        self.stop_time = stop_time_ns
        self.runahead = runahead

    def first_window(self) -> Optional[tuple[int, int]]:
        return self.next_window(0)

    def next_window(self, min_next_event_time: Optional[int]) -> Optional[tuple[int, int]]:
        """Window starting at the global min next-event time
        (`controller.rs:87-113`); None when the simulation is over."""
        if min_next_event_time is None or min_next_event_time >= self.stop_time:
            return None
        start = min_next_event_time
        end = min(start + self.runahead.get(), self.stop_time)
        return (start, end)
