"""Events, their deterministic total order, and the per-host event queue.

The ordering rules here are the heart of the determinism contract; they are
kept identical in spirit to the reference:

- Events sort by time first (`src/main/core/work/event.rs:102-110`).
- At equal times, ALL packet events sort before ALL local events — a packet
  arriving at time T must beat a timer that fires at T, regardless of which
  was enqueued first.
- Packet events tie-break by (src_host_id, src_host_event_id)
  (`event.rs:131-155`): the sending host's identity and its per-host
  monotone counter, both scheduling-independent.
- Local events tie-break by the receiving host's per-host event_id counter
  (`event.rs:163-184`).

The queue asserts monotonic pops (`event_queue.rs:36-39`): popping an event
earlier than one already popped is a simulation bug, never silently allowed.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass
from typing import Any, Callable, Optional

# EventData discriminants; packet < local so packets win time ties.
_KIND_PACKET = 0
_KIND_LOCAL = 1


@dataclass(frozen=True)
class PacketEventKey:
    src_host_id: int
    src_event_id: int


class TaskRef:
    """A closure executed on a host at a scheduled time.

    Parity: reference `src/main/core/work/task.rs`; `name` shows up in traces.
    """

    __slots__ = ("fn", "name")

    def __init__(self, fn: Callable[..., None], name: str = "task"):
        self.fn = fn
        self.name = name

    def execute(self, host) -> None:
        self.fn(host)

    def __repr__(self) -> str:
        return f"TaskRef({self.name})"


@dataclass(eq=False)
class Event:
    """A scheduled occurrence on one host.

    `key` is the scheduling-independent total-order tie-break:
      packet: (0, src_host_id, src_event_id)
      local:  (1, dst_event_id, 0)
    """

    time: int
    kind: int
    key: tuple[int, int]
    payload: Any  # Packet for kind=PACKET, TaskRef for kind=LOCAL

    def sort_key(self) -> tuple[int, int, int, int]:
        return (self.time, self.kind, self.key[0], self.key[1])

    def __lt__(self, other: "Event"):
        # Reached only when two events share an identical sort key inside the
        # heap — a violated uniqueness invariant (duplicate (src_host,
        # event_id) or event-id counter bug), never a legal state.
        raise AssertionError(
            f"duplicate event sort key {self.sort_key()}: {self!r} vs {other!r}"
        )

    @staticmethod
    def new_packet(time: int, packet, src_host_id: int, src_event_id: int) -> "Event":
        return Event(time, _KIND_PACKET, (src_host_id, src_event_id), packet)

    @staticmethod
    def new_local(time: int, task: TaskRef, event_id: int) -> "Event":
        return Event(time, _KIND_LOCAL, (event_id, 0), task)

    @property
    def is_packet(self) -> bool:
        return self.kind == _KIND_PACKET


class EventQueue:
    """Per-host min-heap of events with a monotonic-pop assertion.

    Parity: reference `src/main/core/work/event_queue.rs:10-48`
    (BinaryHeap<Reverse<PanickingOrd<Event>>> + assert on pop order).
    """

    __slots__ = ("_heap", "_last_popped")

    def __init__(self):
        self._heap: list[tuple[tuple[int, int, int, int], Event]] = []
        self._last_popped: Optional[tuple[int, int, int, int]] = None

    def push(self, event: Event) -> None:
        heapq.heappush(self._heap, (event.sort_key(), event))

    def next_time(self) -> Optional[int]:
        return self._heap[0][1].time if self._heap else None

    def peek_key(self) -> Optional[tuple[int, int, int, int]]:
        return self._heap[0][0] if self._heap else None

    def pop(self) -> Optional[Event]:
        if not self._heap:
            return None
        key, event = heapq.heappop(self._heap)
        # Keys are unique by contract, so equality is as much a bug as going
        # backwards (it means a duplicate (src_host, event_id) slipped past the
        # push-time guard, e.g. the same Event object pushed twice).
        if self._last_popped is not None and key <= self._last_popped:
            raise AssertionError(
                f"non-monotonic or duplicate event pop: {key} after {self._last_popped}"
            )
        self._last_popped = key
        return event

    def __len__(self) -> int:
        return len(self._heap)

    def __bool__(self) -> bool:
        return bool(self._heap)

    def purge(self) -> list["Event"]:
        """Drop every pending event (the fault plane's host-crash
        semantics: a crash loses the queue). The monotonic-pop floor is
        KEPT — post-reboot events must still sort after everything the
        host already executed."""
        out = [event for _key, event in self._heap]
        self._heap.clear()
        return out
