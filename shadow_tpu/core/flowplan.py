"""Compile a YAML tgen workload into a device flow plan.

The reference drives its throughput benchmarks with tgen processes
talking through the full packet plane (`src/test/tgen/README.md:1-20`).
This rebuild's flow engine (`tpu/floweng.py`) executes that workload
class — fixed-size TCP transfers between host pairs — entirely on the
TPU. This module is the bridge from config to device: it inspects a
parsed `ConfigOptions`, verifies the workload is flow-engine-shaped
(every process a built-in `tgen-server` / `tgen-client`, single
transfer per client), resolves each client's server, path latency, and
composed path loss through the routing tables, and emits the arrays
`make_flow_world` consumes.

Opt in with `experimental.use_flow_engine: true`; `Manager.run`
delegates to `run_flow_simulation` below, which reconciles per-flow
completions back into `SimStats` (failures for incomplete transfers,
segment counts as the event/packet tallies). A config that is not
flow-engine-shaped raises `FlowPlanError` naming the offending process
— the flag is an explicit promise, not a heuristic.

Fidelity contract (documented in BASELINE.md): fixed shortest-path
latency per flow, segment-granular Bernoulli loss composed along the
path (both directions), no shared-NIC queueing — at ladder shapes the
NIC serialization time of a full transfer is ~two orders of magnitude
under one path RTT, so completion times are RTT/loss-dominated.
"""

from __future__ import annotations

import logging
import time as _walltime
from dataclasses import dataclass

import numpy as np

from . import simtime
from .capacity import CapacityError, CapacityTrajectory

log = logging.getLogger("shadow.flowplan")


class FlowPlanError(ValueError):
    """The config is not a flow-engine-shaped workload."""


@dataclass
class FlowPlan:
    client: list  # [F] client host name
    server: list  # [F] server host name
    size: np.ndarray  # [F] bytes the server streams to the client
    start_us: np.ndarray  # [F] client connect time
    latency_us: np.ndarray  # [F] client->server path latency
    latency_back_us: np.ndarray  # [F] server->client (may differ on
    # directed graphs)
    loss: np.ndarray  # [F] client->server path loss probability
    loss_back: np.ndarray  # [F] server->client
    window_us: int
    stop_us: int
    seed: int


def compile_flow_plan(config, routing, node_index_of_host=None) -> FlowPlan:
    """Extract the flow plan from a parsed config. `routing` is the
    Manager's `RoutingInfo`; `node_index_of_host` maps a host name to
    its network node id (defaults to the config's network_node_id)."""
    if node_index_of_host is None:
        node_index_of_host = {
            name: h.network_node_id for name, h in config.hosts.items()
        }
    servers: dict[str, tuple[str, int]] = {}  # name -> (port, node)
    clients = []
    for name, host in config.hosts.items():
        for popt in host.processes:
            if popt.path == "tgen-server":
                port = popt.args[0] if popt.args else "8888"
                servers[name] = (port, node_index_of_host[name])
            elif popt.path == "tgen-client":
                args = list(popt.args) + ["server", "8888", "1048576", "1"][
                    len(popt.args):]
                server, port, size, count = args[:4]
                if int(count) != 1:
                    raise FlowPlanError(
                        f"host {name}: tgen-client count={count}; the "
                        f"flow engine runs single transfers per client "
                        f"(count=1)")
                clients.append((name, node_index_of_host[name], server,
                                port, int(size), popt.start_time))
            else:
                raise FlowPlanError(
                    f"host {name}: process '{popt.path}' is not a tgen "
                    f"app; experimental.use_flow_engine only accepts "
                    f"tgen-server/tgen-client workloads")
    if not clients:
        raise FlowPlanError("no tgen-client processes in the config")

    F = len(clients)
    size = np.zeros(F, np.int64)
    start_us = np.zeros(F, np.int64)
    latency_us = np.zeros(F, np.int64)
    latency_back_us = np.zeros(F, np.int64)
    loss = np.zeros(F, np.float64)
    loss_back = np.zeros(F, np.float64)
    names_c, names_s = [], []
    for f, (cname, cnode, server, port, sz, t0) in enumerate(clients):
        if server not in servers:
            raise FlowPlanError(
                f"host {cname}: tgen-client targets '{server}' but no "
                f"host runs a tgen-server")
        sport, snode = servers[server]
        if sport != port:
            raise FlowPlanError(
                f"host {cname}: port {port} != server port {sport}")
        fwd = routing.path(cnode, snode)  # client -> server
        back = routing.path(snode, cnode)  # server -> client (directed
        # graphs may be asymmetric; each lane carries its own direction)
        if fwd.latency_ns < simtime.MICROSECOND \
                or back.latency_ns < simtime.MICROSECOND:
            raise FlowPlanError(
                f"host {cname}: path to '{server}' has sub-microsecond "
                f"latency ({min(fwd.latency_ns, back.latency_ns)} ns); "
                f"the flow engine's PDES window cannot go below 1 us")
        size[f] = sz
        start_us[f] = t0 // simtime.MICROSECOND
        if start_us[f] >= 2**31:
            raise FlowPlanError(
                f"host {cname}: start_time {start_us[f]} us exceeds the "
                f"flow engine's int32 microsecond domain (~35.8 simulated "
                f"minutes); it would silently wrap on device")
        latency_us[f] = fwd.latency_ns // simtime.MICROSECOND
        latency_back_us[f] = back.latency_ns // simtime.MICROSECOND
        loss[f] = fwd.packet_loss
        loss_back[f] = back.packet_loss
        names_c.append(cname)
        names_s.append(server)

    stop_us = config.general.stop_time // simtime.MICROSECOND
    if stop_us >= 2**31:
        raise FlowPlanError(
            f"general.stop_time {stop_us} us exceeds the flow engine's "
            f"int32 microsecond domain (~35.8 simulated minutes); it "
            f"would silently wrap on device")
    # PDES lookahead: windows no wider than the narrowest flow's one-way
    # latency (pairs are independent — only a pair's own latency bounds
    # its window), clamped to keep per-window bursts inside the rings
    window_us = int(min(latency_us.min(), int(latency_back_us.min()),
                        25_000))
    return FlowPlan(
        client=names_c, server=names_s, size=size, start_us=start_us,
        latency_us=latency_us, latency_back_us=latency_back_us,
        loss=loss, loss_back=loss_back, window_us=window_us,
        stop_us=int(stop_us), seed=config.general.seed,
    )


# window-width ladder for latency buckets: flows whose one-way latency
# admits a wider window run in a separate world with that window — pairs
# never interact, so partitioning by latency is exact PDES decomposition
# (not an approximation), and it keeps fast-flow worlds from forcing
# narrow windows on slow flows. A flow may always run NARROWER windows
# than its latency admits, so the ladder is coarse (fewer, larger
# buckets amortize per-dispatch and probe overhead better than exact
# windows amortize step count). Padding each bucket to a power of two
# maximizes XLA compile-cache hits across configs.
_WINDOW_LADDER = (1_000, 2_000, 5_000, 20_000)


def _bucket_window(lat_us: int) -> int:
    w = min(lat_us, _WINDOW_LADDER[-1])
    best = 0
    for step in _WINDOW_LADDER:
        if step <= w:
            best = step
    return best if best else int(w)  # sub-ladder latency: exact window


def _plan_fingerprint(plan: FlowPlan) -> str:
    """Digest of everything that determines a flow run's results: a
    resume against a DIFFERENT config/seed must refuse, not silently
    merge incompatible bucket results."""
    import hashlib

    h = hashlib.sha256()
    h.update(repr((plan.client, plan.server, plan.window_us, plan.stop_us,
                   plan.seed)).encode())
    for arr in (plan.size, plan.start_us, plan.latency_us,
                plan.latency_back_us, plan.loss, plan.loss_back):
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def run_flow_simulation(config, routing, stats, *, checkpoint_dir=None,
                        resume_from=None):
    """Execute the config's tgen workload on the device flow engine and
    fill `stats` (a `SimStats`) the way the round loop would: segments
    as events/packets, wire drops as packet drops, incomplete transfers
    as process failures against the clients' expected exit 0.

    Checkpoint/resume (docs/robustness.md): latency buckets are
    independent worlds, so bucket completion is an EXACT resume unit.
    With `checkpoint_dir` set, a ``flow-progress`` checkpoint lands
    after every finished bucket; `resume_from` restores it (fingerprint
    -verified against this config+seed) and recomputes only the
    remaining buckets — the merged results are bitwise-identical to an
    uninterrupted run because per-bucket results are deterministic and
    disjoint."""
    from ..tpu import enable_compilation_cache, floweng

    enable_compilation_cache()
    # shadowlint: disable=SL101 -- wall-clock perf stat only; never feeds sim state
    wall0 = _walltime.monotonic()
    plan = compile_flow_plan(config, routing)
    F = len(plan.size)
    buckets: dict[int, list[int]] = {}
    for f in range(F):
        lookahead = min(int(plan.latency_us[f]),
                        int(plan.latency_back_us[f]))
        buckets.setdefault(_bucket_window(lookahead), []).append(f)

    complete_us = np.full(F, np.iinfo(np.int32).max, np.int64)
    bytes_read = np.zeros(F, np.int64)
    segments = wire_drops = queue_drops = retransmits = 0
    rounds = 0
    total_retries = 0
    ring_dirty = False  # a bucket's FINAL run still had ring drops
    # ring-capacity policy (core/capacity.py): the flow engine's
    # per-destination segment rings are this path's capacity dimension.
    # Engine ring drops were ALWAYS re-run with doubled queue_slots
    # (they are an engine artifact, not modeled wire loss), so fixed
    # and elastic behave identically here — the policy contributes the
    # unified trajectory record, the strict failure, and the
    # max_doublings bound.
    cap_opts = getattr(config, "capacity", None)
    max_doublings = cap_opts.max_doublings if cap_opts else 3
    cap_mode = cap_opts.mode if cap_opts else "fixed"
    trajectory = CapacityTrajectory(cap_mode)
    fingerprint = _plan_fingerprint(plan)
    done_buckets: set[int] = set()
    if resume_from:
        from ..faults.checkpoint import CheckpointError, load_checkpoint

        meta, arrays = load_checkpoint(resume_from)
        if meta.get("kind") != "flow":
            raise CheckpointError(
                f"{resume_from}: kind {meta.get('kind')!r} is not a "
                f"flow-engine checkpoint")
        if meta.get("plan_fingerprint") != fingerprint:
            raise CheckpointError(
                f"{resume_from}: checkpoint was written by a different "
                f"config/seed (plan fingerprint mismatch); refusing to "
                f"merge incompatible bucket results")
        complete_us = arrays["complete_us"].astype(np.int64)
        bytes_read = arrays["bytes_read"].astype(np.int64)
        c = meta["counters"]
        segments, wire_drops = c["segments"], c["wire_drops"]
        queue_drops, retransmits = c["queue_drops"], c["retransmits"]
        rounds, total_retries = c["rounds"], c["retries"]
        ring_dirty = bool(c["ring_dirty"])
        trajectory.events.extend(meta.get("capacity_events", []))
        done_buckets = set(meta["done_buckets"])
        log.info("flow engine: resumed from %s (%d/%d bucket(s) done)",
                 resume_from, len(done_buckets), len(buckets))

    def _bucket_checkpoint():
        if not checkpoint_dir:
            return
        import os

        from ..faults.checkpoint import write_checkpoint

        write_checkpoint(
            os.path.join(checkpoint_dir, "flow-progress"),
            meta={
                "kind": "flow",
                "plan_fingerprint": fingerprint,
                "done_buckets": sorted(done_buckets),
                "capacity_events": list(trajectory.events),
                "counters": {
                    "segments": int(segments),
                    "wire_drops": int(wire_drops),
                    "queue_drops": int(queue_drops),
                    "retransmits": int(retransmits),
                    "rounds": int(rounds),
                    "retries": int(total_retries),
                    "ring_dirty": bool(ring_dirty),
                },
            },
            arrays={"complete_us": complete_us, "bytes_read": bytes_read},
        )

    for window_us, idx in sorted(buckets.items(), reverse=True):
        if window_us in done_buckets:
            log.info("flow engine: bucket window %d us already complete "
                     "in the resumed checkpoint; skipping", window_us)
            continue
        Fb = len(idx)
        pad = max(8, 1 << (Fb - 1).bit_length()) - Fb
        sel = np.asarray(idx)
        lat = np.concatenate([plan.latency_us[sel],
                              np.full(pad, window_us, np.int64)])
        lat_b = np.concatenate([plan.latency_back_us[sel],
                                np.full(pad, window_us, np.int64)])
        size = np.concatenate([plan.size[sel], np.zeros(pad, np.int64)])
        start = np.concatenate([plan.start_us[sel],
                                np.full(pad, np.iinfo(np.int32).max,
                                        np.int64)])
        loss = np.concatenate([plan.loss[sel], np.zeros(pad)])
        loss_b = np.concatenate([plan.loss_back[sel], np.zeros(pad)])
        log.info("flow engine: bucket window %d us, %d flows (+%d pad)",
                 window_us, Fb, pad)
        chunk = max(1, 1_000_000 // window_us)  # ~1 sim-s per dispatch
        # ring-capacity drops are an ENGINE artifact (per-destination
        # segment rings overflowing), not modeled wire loss — the TCP
        # machines recover via retransmit, so results stay valid but
        # completion times are distorted. Same discipline as step-cap
        # saturation: re-run the bucket from scratch with doubled rings.
        queue_slots = 256
        for ring_attempt in range(max_doublings + 1):
            world = floweng.make_flow_world(
                lat, size, start_us=start, loss=loss, seed=plan.seed,
                server_writes=True, queue_slots=queue_slots,
                latency_back_us=lat_b, loss_back=loss_b)
            world, sim_s, retries = floweng.run_to_completion(
                world, window_us, max_sim_s=plan.stop_us / 1e6,
                chunk_windows=chunk, probe_every=3)
            world = floweng.finalize_to(world, plan.stop_us)
            res = floweng.flow_results(world)
            if res["queue_drops"] == 0:
                break
            if cap_mode == "strict":
                # strict refuses the self-healing re-run too: the
                # caller claimed the provisioning was right
                raise CapacityError(
                    f"flow engine: {int(res['queue_drops'])} "
                    f"ring-capacity drop(s) in the {window_us} us "
                    f"bucket under capacity.mode=strict "
                    f"(queue_slots={queue_slots}); raise the rings or "
                    f"run capacity.mode=elastic", ring="flow-queue")
            if ring_attempt == max_doublings:
                ring_dirty = True
                ev = trajectory.record_drop(
                    time_ns=config.general.stop_time, ring="flow-queue",
                    cap=queue_slots, overflow=int(res["queue_drops"]),
                    plane="floweng", exhausted=True)
                ev["bucket_window_us"] = window_us
                log.warning(
                    "flow engine: ring drops persist after %d doublings "
                    "(queue_slots=%d); reconciled packets_dropped now "
                    "includes %d engine ring drops alongside wire drops",
                    max_doublings, queue_slots, res["queue_drops"])
                break
            # the ad-hoc doubled-queue_slots re-run, now ONE policy with
            # the device planes: a bucket re-run from scratch with
            # doubled rings IS the elastic snapshot/re-execute (the
            # snapshot is the bucket's deterministic start), so fixed
            # and elastic both take it; only the trajectory record and
            # bounds come from the policy
            ev = trajectory.record_growth(
                time_ns=config.general.stop_time, ring="flow-queue",
                from_cap=queue_slots, to_cap=queue_slots * 2,
                overflow=int(res["queue_drops"]), plane="floweng")
            ev["bucket_window_us"] = window_us
            queue_slots *= 2
            log.warning(
                "flow engine: %d ring-capacity drop(s) in the %d us "
                "bucket (engine ring overflow, distinct from modeled "
                "wire drops) — re-running with queue_slots=%d",
                res["queue_drops"], window_us, queue_slots)
            total_retries += 1
        complete_us[sel] = res["complete_us"][:Fb]
        bytes_read[sel] = res["bytes_read"][:Fb]
        segments += res["segments"]
        wire_drops += res["wire_drops"]
        queue_drops += res["queue_drops"]
        retransmits += res["retransmits"]
        rounds += int(round(sim_s * 1e6 / window_us))
        total_retries += retries
        done_buckets.add(window_us)
        _bucket_checkpoint()

    ok = bytes_read >= plan.size
    for f in np.nonzero(~ok)[0]:
        stats.process_failures.append((
            f"{plan.client[f]}/tgen-client",
            f"expected exited(0), got running (transfer "
            f"{int(bytes_read[f])}/{int(plan.size[f])}"
            f" bytes from {plan.server[f]})",
        ))
    if total_retries:
        log.warning("flow engine re-ran %d time(s) after window "
                    "saturation%s", total_retries,
                    " (ring drops persisted in a final run)" if ring_dirty
                    else " (final runs clean)")
    if ring_dirty and getattr(config, "strict", False):
        # top-level strict: a final run that still lost packets to
        # engine ring capacity is a refused silent divergence, not a
        # warning (same promotion as the transport's ingress drops)
        raise CapacityError(
            "flow engine: ring-capacity drops persisted after the "
            "growth budget (capacity.max_doublings="
            f"{max_doublings}) under strict: true; raise the rings or "
            "the budget", ring="flow-queue")
    stats.capacity_events = list(trajectory.events)
    stats.rounds = rounds
    stats.events_executed = segments
    stats.packets_sent = segments
    stats.packets_dropped = wire_drops + queue_drops
    stats.sim_time_ns = config.general.stop_time
    stats.wall_seconds = _walltime.monotonic() - wall0  # shadowlint: disable=SL101 -- perf stat
    stats.flow_complete_us = complete_us
    stats.flow_retransmits = retransmits
    return stats
