"""The simulation driver: builds hosts from config, runs the round loop.

Parity: reference `src/main/core/manager.rs` — builds hosts (`build_host`,
`manager.rs:551`), shuffles them for thread assignment (`manager.rs:272`),
picks parallelism = min(cores, hosts) (`manager.rs:248-298`), runs the
boot → scheduling-loop → shutdown phases (`manager.rs:357-489`), and merges
worker stats. The Controller supplies each next window from the global min
next-event time (`controller.rs:80-113`).
"""

from __future__ import annotations

import heapq
import json
import logging
import os
import sys
import time as _walltime
from dataclasses import dataclass, field
from typing import Optional

from ..host.cpu import Cpu
from ..host.host import Host
from ..net import graph as netgraph
from ..net.dns import Dns
from . import resource_usage, simtime
from .config import ConfigOptions, FinalState
from .controller import Controller, Runahead
from .rng import Xoshiro256pp, host_seed_for
from .scheduler import make_scheduler
from . import worker as worker_mod
from .worker import WorkerShared

log = logging.getLogger("shadow_tpu.manager")


@dataclass
class SimStats:
    """Merged end-of-run statistics (`sim_stats.rs`, `manager.rs:523-546`)."""

    rounds: int = 0
    events_executed: int = 0
    packets_sent: int = 0
    packets_dropped: int = 0
    # injected fault-plane drops (crashed endpoints, corruption bursts,
    # purged queues) — never folded into packets_dropped, so the final
    # stats keep the same taxonomy as the tracker/telemetry counters
    # (docs/robustness.md)
    packets_dropped_fault: int = 0
    sim_time_ns: int = 0
    wall_seconds: float = 0.0
    process_failures: list = field(default_factory=list)
    # capacity-trajectory events (core/capacity.py): every ring
    # growth/drop/exhaustion the run recorded, across the transport's
    # in-flight slots and the flow engine's segment rings — the
    # "metrics minus capacity trajectory" remainder is what the elastic
    # parity contract compares (docs/robustness.md "Elastic capacity")
    capacity_events: list = field(default_factory=list)

    def as_dict(self) -> dict:
        return {
            "rounds": self.rounds,
            "packets_sent": self.packets_sent,
            "packets_dropped": self.packets_dropped,
            "packets_dropped_fault": self.packets_dropped_fault,
            "sim_time_ns": self.sim_time_ns,
            "wall_seconds": self.wall_seconds,
            "process_failures": list(self.process_failures),
            "capacity_events": list(self.capacity_events),
        }


def _tracker_dispatch(packet, status):
    """Route a packet status to the executing host's trackers."""
    host = worker_mod.current_host()
    if host is None:
        return
    for tracker in getattr(host, "trackers", ()):
        tracker.on_packet_status(packet, status)


def _raw_cpu_frequency_khz() -> int:
    """The machine's raw CPU frequency (`manager.rs:826-830`), with a
    /proc/cpuinfo fallback and a 1 GHz default when neither is readable."""
    try:
        with open("/sys/devices/system/cpu/cpu0/cpufreq/cpuinfo_max_freq") as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        pass
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    return int(float(line.split(":", 1)[1]) * 1000)
    except (OSError, ValueError, IndexError):
        pass
    return 1_000_000  # 1 GHz


class Manager:
    def __init__(self, config: ConfigOptions, data_dir: Optional[str] = None):
        self.config = config
        self.data_dir = data_dir  # set by the CLI; enables pcap/stats files
        self._pcap_writers = []
        self.global_rng = Xoshiro256pp(config.general.seed)
        self.dns = Dns()
        self.hosts: list[Host] = []
        self.hosts_by_name: dict[str, Host] = {}

        # --- network graph + routing ---------------------------------------
        gsrc = config.network.graph
        if gsrc.type == "1_gbit_switch":
            text = netgraph.ONE_GBIT_SWITCH_GRAPH
        elif gsrc.inline is not None:
            text = gsrc.inline
        else:
            text = netgraph.load_graph_text(gsrc.file_path)
        self.graph = netgraph.NetworkGraph.parse(text)

        used_nodes = [h.network_node_id for h in config.hosts.values()]
        self.routing = netgraph.build_routing(
            self.graph, used_nodes, config.network.use_shortest_path
        )

        # --- flow-engine delegation ------------------------------------------
        # a tgen workload bound for the device flow engine needs only the
        # graph + routing built above: skip hosts, trackers, scheduler
        # worker threads, and transport entirely (they would be built,
        # pinned, and never used)
        self.stats = SimStats()
        self.trackers = {}
        # unified telemetry (docs/observability.md): instantiated at
        # run() start so a Manager built-but-never-run opens no sink.
        # Assigned before the flow-engine early return so every Manager
        # has the attribute (the CLI reads it after run()).
        self.harvester = None
        # fault-plane / checkpoint state shared by BOTH run paths (the
        # flow engine checkpoints per bucket; the round loop per
        # interval + on the crash path) — initialized before the
        # flow-engine early return so every Manager has the attributes
        self.fault_schedule = None
        self._watchdog = None
        self._last_window_start = 0
        self.resume_from = None  # set by the CLI's --resume
        # guard plane (docs/robustness.md): the ledger collects every
        # violation and dispatches the per-class policy; the reconciler
        # and progress detector attach below when their classes are
        # active. Initialized before the flow-engine early return so
        # every Manager has the attributes.
        self._guard_ledger = None
        self._guard_recon = None
        self._progress = None
        self._progress_packets = 0
        if config.guards.enabled:
            from ..guards.report import GuardLedger

            self._guard_ledger = GuardLedger(policies={
                "device": config.guards.device,
                "reconcile": config.guards.reconcile,
                "progress": config.guards.progress,
            })
        self._ckpt_dir = config.faults.checkpoint.directory or (
            os.path.join(self.data_dir, "checkpoints")
            if self.data_dir else None)
        self._next_ckpt_ns = None
        if config.faults.checkpoint.interval:
            if self._ckpt_dir is None:
                log.warning(
                    "faults.checkpoint.interval is set but there is no "
                    "data directory and no faults.checkpoint.directory; "
                    "periodic checkpoints are disabled for this run")
            else:
                self._next_ckpt_ns = config.faults.checkpoint.interval
        if config.experimental.plane_kernel != "xla":
            # the config validates `plane_kernel: pallas` but no
            # Manager-driven run consults it: the use_tpu_transport
            # device path has its own fused kernels, and the CPU object
            # plane / flow engine run no window_step at all — only the
            # general plane's drivers (bench.py via BENCH_PLANE_KERNEL,
            # tools/profile_plane.py --kernel, direct window_step
            # callers) honor the flag. A silent no-op here looked like a
            # broken feature (docs/performance.md caveat), so warn
            # loudly / refuse under `strict: true`.
            self._unsupported_combo(
                f"experimental.plane_kernel: "
                f"{config.experimental.plane_kernel!r} is not consulted "
                "by Manager-driven runs (use_tpu_transport has its own "
                "fused kernels; the CPU plane runs no window_step) — "
                "this run proceeds on its default kernels; the flag "
                "governs bench.py and tools/profile_plane.py only")
        if config.telemetry.flight_recorder.enabled:
            # the sampled hop recorder rides the device-plane WINDOW
            # drivers (bench.py, tools/chaos_smoke.py,
            # tools/run_scenarios.py), which own the fixed window
            # cadence its virtual timestamps decode against;
            # Manager-driven rounds have no such driver loop — a
            # silently-ignored opt-in would look like a broken feature
            # (docs/observability.md "Distributions and the flight
            # recorder")
            self._unsupported_combo(
                "telemetry.flight_recorder is not consulted by "
                "Manager-driven runs: sampled per-packet hop tracing "
                "rides the device-plane window drivers (bench.py, "
                "tools/chaos_smoke.py, tools/run_scenarios.py) — this "
                "run proceeds without hop tracing; telemetry.histograms "
                "remains available on the use_tpu_transport path")
        if config.flows.enabled:
            # the flow plane (RTO retransmit / congestion
            # backpressure) threads the device-plane window drivers —
            # tools/run_scenarios.py executes it for scenarios with
            # `transport: flows`; Manager-driven runs use the CPU
            # socket machinery (or use_tpu_transport), neither of
            # which consults this block — a silently-ignored opt-in
            # would look like a broken feature (docs/robustness.md
            # "Flow plane")
            self._unsupported_combo(
                "flows.enabled is not consulted by Manager-driven "
                "runs: the device flow plane rides the window drivers "
                "(tools/run_scenarios.py, scenarios with `transport: "
                "flows`) — this run proceeds on its normal socket "
                "transport")
        if config.workload.enabled or config.workload.scenario not in (
                None, "off"):
            # the workload plane's generators ride the device-plane
            # window drivers (tools/run_scenarios.py is the driver);
            # Manager-driven runs execute managed processes, not
            # scenario programs — a silently-ignored `workload:` block
            # would look like a broken feature (docs/workloads.md)
            self._unsupported_combo(
                "workload.enabled is not consulted by Manager-driven "
                "runs: scenario traffic programs run through the "
                "device-plane drivers (tools/run_scenarios.py) — this "
                "run proceeds without the declared workload")
        if config.experimental.use_flow_engine:
            # unsupported feature combinations: log-and-ignore by
            # default; `strict: true` promotes each to a ConfigError
            # (exit 2) so CI and wrappers never silently lose a
            # requested feature
            if config.faults.any_injection() or config.faults.watchdog:
                # the flow engine has no hosts, processes, or round loop
                # to inject against; a silently-ignored schedule would
                # look like a broken feature
                self._unsupported_combo(
                    "faults injection/watchdog are not supported with "
                    "experimental.use_flow_engine; only checkpoint/resume "
                    "applies to flow-engine runs")
            if config.telemetry.enabled:
                # the flow engine never runs the round loop the
                # harvester hooks; a silently-ignored opt-in would look
                # like a broken feature
                self._unsupported_combo(
                    "telemetry.enabled is not supported with "
                    "experimental.use_flow_engine; no heartbeats or "
                    "trace will be emitted for this run")
            if config.guards.enabled:
                # guards hook the round loop, the transport kernels,
                # and the harvest boundary — none of which exist here
                self._unsupported_combo(
                    "guards.enabled is not supported with "
                    "experimental.use_flow_engine; no invariants will "
                    "be checked for this run")
            return

        # --- IP assignment + host seeds (config-declared order) -------------
        ips = netgraph.IpAssignment()
        host_plans = []
        for name, opts in config.hosts.items():
            if opts.ip_addr is not None:
                ips.assign_manual(opts.ip_addr, opts.network_node_id)
                ip = opts.ip_addr
            else:
                ip = ips.assign_auto(opts.network_node_id)
            seed = host_seed_for(self.global_rng, name)
            host_plans.append((name, opts, ip, seed))

        # --- runahead from the routing table --------------------------------
        min_latency = self.routing.get_smallest_latency_ns()
        self.runahead = Runahead(
            config.experimental.use_dynamic_runahead,
            min_latency,
            config.experimental.runahead,
        )
        self.controller = Controller(config.general.stop_time, self.runahead)

        # --- hosts -----------------------------------------------------------
        ip_to_host: dict[str, Host] = {}
        ip_to_node: dict[str, int] = {}
        raw_freq_khz = _raw_cpu_frequency_khz()
        for host_id, (name, opts, ip, seed) in enumerate(host_plans, start=1):
            node = self.graph.node_by_id(opts.network_node_id)
            bw_down = opts.bandwidth_down or node.bandwidth_down
            bw_up = opts.bandwidth_up or node.bandwidth_up
            if bw_down is None or bw_up is None:
                raise netgraph.GraphError(
                    f"host {name!r}: no bandwidth on host or graph node "
                    f"{opts.network_node_id}"
                )
            host_opts = config.host_defaults.merged_with(opts.host_options).resolved()
            pcap_factory = self._make_pcap_factory(name, host_opts)
            # sim freq == native freq, like `manager.rs:565` passing the
            # machine's raw frequency as the host frequency; threshold None
            # keeps the model (and its wall-time nondeterminism) off
            cpu = Cpu(
                raw_freq_khz, raw_freq_khz,
                config.experimental.cpu_threshold,
                config.experimental.cpu_precision,
            )
            host = Host(
                cpu=cpu,
                host_id=host_id,
                name=name,
                ip=ip,
                node_id=opts.network_node_id,
                seed=seed,
                bandwidth_down_bps=bw_down,
                bandwidth_up_bps=bw_up,
                qdisc=config.experimental.interface_qdisc,
                experimental=config.experimental,
                pcap_factory=pcap_factory,
                model_unblocked_syscall_latency=(
                    config.general.model_unblocked_syscall_latency
                ),
            )
            if config.experimental.host_path_isolation:
                # per-host filesystem view (file-family syscalls): the
                # redirect root lives beside the host's output dir; a
                # shared per-run temp root stands in when no data dir
                # was given
                host.vfs_enabled = True
                # ABSOLUTE: the rewritten path is resolved by the GUEST
                # against ITS cwd, and the simulator's own makedirs
                # against ours — only an absolute root means the same dir
                host.vfs_host_dir = os.path.abspath(os.path.join(
                    self._vfs_data_root(), "hosts", name))
                host.vfs_root = os.path.join(host.vfs_host_dir, "root")
            self.hosts.append(host)
            self.hosts_by_name[name] = host
            ip_to_host[ip] = host
            ip_to_node[ip] = opts.network_node_id
            self.dns.register(name, ip)
            self._wire_processes(host, name, opts)

        # the simulation's /etc/hosts view, consumed by the addrinfo
        # preload so managed binaries resolve simulated hostnames
        # (`shim_api_addrinfo.c` + the reference's mounted hosts file)
        import tempfile

        if self.data_dir:
            os.makedirs(self.data_dir, exist_ok=True)
            # absolute: the path is handed to managed processes whose cwd
            # is their per-host data dir, not the simulator's
            hosts_path = os.path.abspath(
                os.path.join(self.data_dir, "etc-hosts"))
        else:
            fd, hosts_path = tempfile.mkstemp(prefix="shadow-hosts-")
            os.close(fd)
            self._hosts_file_temp = True  # unlinked at run() teardown
        with open(hosts_path, "w") as fh:
            fh.write(self.dns.hosts_file())
        self.hosts_file_path = hosts_path
        for host in self.hosts:
            host.hosts_file_path = hosts_path

        self.shared = WorkerShared(
            dns=self.dns,
            routing=self.routing,
            ip_to_host=ip_to_host,
            ip_to_node_id=ip_to_node,
            runahead=self.runahead,
            sim_end_time=config.general.stop_time,
            bootstrap_end_time=config.general.bootstrap_end_time,
        )
        self.transport = None
        if config.experimental.use_tpu_transport:
            from ..tpu.transport import DeviceTransport

            self.transport = DeviceTransport(
                self.hosts, self.routing, ip_to_node,
                egress_cap=config.experimental.tpu_egress_cap,
                ingress_cap=config.experimental.tpu_ingress_cap,
                mode=config.experimental.tpu_transport_mode,
                compact_cap=config.experimental.tpu_compact_cap,
                capacity_mode=config.capacity.mode,
                max_doublings=config.capacity.max_doublings,
                # top-level strict promotes fixed-mode ring drops to the
                # strict capacity failure: a strict caller never
                # silently loses packets to simulator capacity
                capacity_strict=(
                    config.capacity.mode == "strict"
                    or (config.strict
                        and config.capacity.mode == "fixed")),
            )
            self.shared.device_transport = self.transport
            # self-healing: transient device errors retry with backoff
            # before the crash path (faults/healing.py)
            self.transport.retry_attempts = config.faults.device_retries
            self.transport.retry_backoff_s = config.faults.retry_backoff / 1e9
            self.transport.retry_cap_s = config.faults.retry_cap / 1e9
            self.transport.retry_jitter = config.faults.retry_jitter
            # the retry sleep schedule is seeded like the fault plane
            # (faults.seed falls back to general.seed) so identical
            # configs retry on identical wall cadences
            self.transport.retry_seed = (
                config.faults.seed if config.faults.seed is not None
                else config.general.seed)
            # guard plane: thread the device invariant accumulator
            # through every transport dispatch, and pair the device
            # counters with the CPU ledger for reconciliation (mid-run
            # pairs are only meaningful in sync mode — the mirrored
            # device re-executes windows in lagged batches, so there
            # reconciliation runs on the settled teardown snapshot)
            if config.guards.active("device"):
                self.transport.enable_guards()
            if config.guards.active("reconcile"):
                from ..guards.reconcile import TransportReconciler

                self._guard_recon = TransportReconciler(
                    self.transport, [h.name for h in self.hosts],
                    mid_run=self.transport.mode == "sync")

        # --- fault plane (faults/schedule.py; docs/robustness.md) -----------
        # compiled HERE so a bad `faults:` block dies as a ConfigError
        # before anything runs; the schedule is shared with every worker
        # through the send-packet overlay
        if config.faults.any_injection():
            from ..faults.schedule import compile_schedule

            node_map = {
                opts.network_node_id: self.routing.node_index(
                    opts.network_node_id)
                for opts in config.hosts.values()
            }
            self.fault_schedule = compile_schedule(
                config.faults,
                host_names=[h.name for h in self.hosts],
                n_nodes=len(self.routing.latency_ns),
                seed=config.general.seed,
                stop_time_ns=config.general.stop_time,
                node_index=lambda nid: node_map[nid],
            )
            self.fault_schedule.set_node_map(node_map)
            self.shared.fault_plane = self.fault_schedule
            log.info("fault plane: %d scheduled event(s), fingerprint %s",
                     len(self.fault_schedule.events),
                     self.fault_schedule.fingerprint()[:12])

        # guard plane: the round-loop zero-progress detector (the
        # virtual-time complement of the wall-clock watchdog)
        if config.guards.active("progress"):
            from ..guards.progress import ProgressDetector

            self._progress = ProgressDetector(
                config.guards.progress_rounds)

        # parallelism = min(cores, hosts) unless configured
        par = config.general.parallelism
        if par <= 0:
            par = min(os.cpu_count() or 1, len(self.hosts))

        # random thread-assignment order (`manager.rs:272`); per-round host
        # iteration uses this fixed shuffled order
        self._host_order = list(self.hosts)
        self.global_rng.shuffle(self._host_order)

        # Active-host heap: only hosts with an event before the round end
        # are iterated each round. Hosts announce new events through the
        # dirty sink (one append per host per round, under their queue
        # lock); the Manager re-keys them at round barriers. At 1k+ hosts
        # the old iterate-everyone round loop spent more wall time polling
        # idle hosts than executing events.
        self._host_heap: list[tuple[int, int]] = []  # (next_t, host_id)
        self._dirty_hosts: list = []
        self._cross_hosts: list = []
        self._host_by_id = {h.host_id: h for h in self.hosts}
        for host in self.hosts:
            host._dirty_sink = self._dirty_hosts
            host._cross_sink = self._cross_hosts

        self.scheduler = make_scheduler(
            config.experimental.scheduler, self.shared, par,
            hosts=self._host_order,
            pin_cpus=config.experimental.use_cpu_pinning,
        )

        self.stats = SimStats()

        # manager heartbeat + resource watchdogs + status printer state
        # (`manager.rs:380-388,439-453`, `controller.rs:116-168`)
        self._heartbeat_interval = config.general.heartbeat_interval
        self._last_heartbeat = 0
        self._check_fd_usage = True
        self._check_mem_usage = True
        self._last_resource_check = 0.0
        self._progress_enabled = config.general.progress
        self._last_progress = 0.0
        self._wall_start = 0.0

        # Per-host trackers dispatch off the packet status-trace stream —
        # only when something consumes them (heartbeats or stats output),
        # so library runs with heartbeats disabled pay nothing per packet.
        from ..host.tracker import Tracker
        from ..net import packet as packet_mod

        hb = config.experimental.host_heartbeat_interval
        if hb or self.data_dir:
            self.trackers = {
                h.name: Tracker(h, hb) for h in self.hosts
            }
            # per-instance wrapper so run()'s cleanup can tell OUR hook from
            # one installed by a different Manager in the same process.
            # Early-out on statuses no tracker reacts to BEFORE the
            # current-host lookup: this hook fires on every status
            # transition of every packet (~10 per packet), and only ~3
            # of them move a counter
            # the hook fires on every status transition (~10 per
            # packet); early-out here on the ~3 statuses trackers react
            # to. The filter lives in OUR closure, not the packet
            # module, so a replacement full-stream tracer is unaffected
            wanted = frozenset(
                packet_mod.PacketStatus(s) for s in Tracker.WANTED)
            self._status_hook = lambda packet, status: (
                _tracker_dispatch(packet, status)
                if status in wanted else None)
            packet_mod.status_trace_hook = self._status_hook
        else:
            self.trackers = {}
            self._status_hook = None

    def _unsupported_combo(self, message: str) -> None:
        """Unsupported feature-combination handling (flow-engine combos,
        the plane_kernel no-op): warn by default, ConfigError under
        top-level `strict: true` (exit 2) — the feature the config asked
        for will NOT run, and strict callers want that to be fatal, not
        a log line."""
        if self.config.strict:
            from .config import ConfigError

            raise ConfigError(f"strict mode: {message}")
        log.warning(message)

    # -- telemetry ------------------------------------------------------

    def _telemetry_sink_path(self) -> Optional[str]:
        t = self.config.telemetry
        if t.sink == "off":
            return None  # log-summary-only mode
        if t.sink:
            return t.sink
        return (os.path.join(self.data_dir, "telemetry.jsonl")
                if self.data_dir else None)

    def _telemetry_trace_path(self) -> Optional[str]:
        t = self.config.telemetry
        if t.trace == "off":
            return None
        if t.trace:
            return t.trace
        return (os.path.join(self.data_dir, "trace.json")
                if self.data_dir else None)

    def _start_telemetry(self) -> None:
        if not self.config.telemetry.enabled:
            return
        from ..telemetry import TelemetryHarvester

        if self.config.telemetry.histograms:
            if self.transport is not None:
                # per-destination delivery-latency / in-flight-depth
                # log2 histograms ride the transport kernels as a
                # static presence switch and drain through the same
                # async harvest (docs/observability.md "Distributions
                # and the flight recorder")
                self.transport.enable_histograms()
            else:
                self._unsupported_combo(
                    "telemetry.histograms needs the device transport "
                    "(experimental.use_tpu_transport): the CPU object "
                    "plane has no device counter arrays to bucket — "
                    "this run emits no histograms")

        on_drain = None
        if self._guard_recon is not None:
            # cross-plane reconciliation rides the harvester's drain:
            # the device snapshot for a tick materializes one interval
            # later, and is compared against the CPU ledger copied at
            # the SAME tick (guards/reconcile.py) — zero added syncs
            def on_drain(time_ns, device_totals, cpu):
                self._guard_ledger.apply(
                    "reconcile",
                    self._guard_recon.on_drain(time_ns, device_totals,
                                               cpu))

        self.harvester = TelemetryHarvester(
            interval_ns=self.config.telemetry.interval,
            sink=self._telemetry_sink_path(),
            host_names=[h.name for h in self.hosts],
            per_host=self.config.telemetry.per_host,
            # heartbeats are retained in memory only for the trace
            # export; with the trace off they'd be dead weight on a
            # long run (per-host records every interval)
            retain=bool(self._telemetry_trace_path()),
            on_drain=on_drain,
        )

    def _telemetry_tick(self, now_ns: int) -> None:
        """One harvest: device transport counters (fresh undonated
        copies; the D2H pull is asynchronous and materializes a full
        interval later) merged with the CPU tracker counters under the
        host-id namespace."""
        device = (self.transport.telemetry_arrays()
                  if self.transport is not None else None)
        if device is not None:
            # [N, B] histogram leaves merge into the same device dict;
            # the harvester splits them off by rank (empty when
            # telemetry.histograms is off)
            device = {**device, **self.transport.histogram_arrays()}
        cpu = {
            t.host.host_id: t.counters.as_dict()
            for t in self.trackers.values()
        } or None
        drain = getattr(self.transport, "drain_capacity_events", None)
        if drain is not None:
            # capacity resize events ride the heartbeat stream (and the
            # trace as instants) — a run that grew its rings says so in
            # its own telemetry, not only in a log line
            for ev in drain():
                self.harvester.note_event(ev)
        self.harvester.tick(now_ns, device=device, cpu=cpu)
        if self._guard_recon is not None:
            # pair the device snapshot just started with a same-instant
            # CPU ledger copy; compared when the harvester drains it
            self._guard_recon.note_tick(now_ns)

    def _finish_telemetry(self) -> None:
        if self.harvester is None:
            return
        self._telemetry_tick(self.config.general.stop_time)
        self.harvester.finalize()
        trace_path = self._telemetry_trace_path()
        if trace_path:
            from ..telemetry import export

            info = export.write_perfetto_trace(
                self.harvester.heartbeats, trace_path)
            log.info("telemetry trace: %s (%d events, %d hosts)",
                     trace_path, info["events"], info["hosts_plotted"])

    # ------------------------------------------------------------------

    def _make_pcap_factory(self, host_name: str, host_opts):
        """Per-host, per-interface pcap capture when enabled and a data dir
        exists (`host.rs:279-282` PcapConfig; lo.pcap + eth0.pcap like the
        reference)."""
        if not self.data_dir or not host_opts.pcap_enabled:
            return None
        import os

        from ..utils.pcap import PcapWriter

        host_dir = os.path.join(self.data_dir, "hosts", host_name)
        os.makedirs(host_dir, exist_ok=True)
        snaplen = host_opts.pcap_capture_size
        if snaplen is None:
            snaplen = 65535

        def factory(iface_name: str):
            writer = PcapWriter(
                open(os.path.join(host_dir, f"{iface_name}.pcap"), "wb"), snaplen
            )
            self._pcap_writers.append(writer)

            def hook(packet, inbound, _writer=writer):
                host = worker_mod.current_host()
                _writer.record(packet, host.now() if host else 0)

            return hook

        return factory

    def _vfs_data_root(self) -> str:
        if self.data_dir:
            return self.data_dir
        root = getattr(self, "_tmp_data_root", None)
        if root is None:
            import tempfile

            root = self._tmp_data_root = tempfile.mkdtemp(
                prefix="shadow-tpu-vfs-")
        return root

    def _wire_processes(self, host: Host, host_name: str, opts) -> None:
        """Schedule spawn (and optional shutdown-signal) tasks for each
        configured process (`manager.rs:551` build_host + `host.rs:406-454`
        add_application)."""
        from .. import apps as app_registry
        from ..process.process import SimProcess
        from .event import TaskRef

        self._spawned = getattr(self, "_spawned", [])
        # per-host spawn registry: the fault plane's reboot-respawn and
        # the watchdog's blame collector both need (cell, spawn) by host
        self._respawn_by_host = getattr(self, "_respawn_by_host", {})
        for i, popt in enumerate(opts.processes):
            # app-registry coroutines first; real executables run as managed
            # native processes under the interposition shim
            app = None
            try:
                app = app_registry.resolve(popt.path)
            except ValueError:
                import shutil as _shutil

                if not (os.path.isfile(popt.path) and os.access(popt.path, os.X_OK)) \
                        and _shutil.which(popt.path) is None:
                    raise
            proc_name = f"{host_name}.{popt.path.rsplit('/', 1)[-1]}.{i}"
            cell: dict = {}

            def spawn(h, app=app, popt=popt, proc_name=proc_name, cell=cell,
                      host_name=host_name):
                if app is not None:
                    proc = SimProcess(h, proc_name, app, tuple(popt.args))
                else:
                    from ..process.managed import ManagedSimProcess

                    out_dir = (
                        os.path.join(self.data_dir, "hosts", host_name)
                        if self.data_dir else None
                    )
                    proc = ManagedSimProcess(
                        h, proc_name, [popt.path, *popt.args],
                        output_dir=out_dir,
                        strace_mode=self.config.experimental
                        .strace_logging_mode,
                    )
                cell["proc"] = proc
                proc.spawn()
                if cell.get("pending_kill") is not None and proc.is_alive:
                    # shutdown_time <= start_time: deliver the signal at
                    # the spawn instant rather than dropping it
                    proc.stop(cell["pending_kill"])

            host.add_application(popt.start_time, spawn)
            if popt.shutdown_time is not None:

                def shutdown(h, popt=popt, cell=cell):
                    proc = cell.get("proc")
                    if proc is not None:
                        proc.stop(popt.shutdown_signal)
                    else:
                        # spawn hasn't run yet (same-timestamp ordering or
                        # shutdown before start): record the pending kill
                        cell["pending_kill"] = popt.shutdown_signal

                host.schedule_task_at(
                    TaskRef(shutdown, "process-shutdown"), popt.shutdown_time
                )
            self._spawned.append((proc_name, popt, cell))
            self._respawn_by_host.setdefault(host_name, []).append(
                (proc_name, popt, cell, spawn))

    def _check_final_states(self) -> list:
        """Compare each process against expected_final_state
        (`worker.rs:589-604` plugin-error accounting)."""
        from ..process.process import ProcessState

        failures = []
        for proc_name, popt, cell in getattr(self, "_spawned", []):
            proc = cell.get("proc")
            exp = popt.expected_final_state
            if proc is None:
                failures.append((proc_name, "never spawned"))
                continue
            if not self._final_state_ok(proc, exp):
                failures.append(
                    (
                        proc_name,
                        f"expected {exp.kind.value}({exp.value}), got "
                        f"{proc.state.value}(exit={proc.exit_status} "
                        f"sig={proc.kill_signal})",
                    )
                )
        return failures

    def _rekey_hosts(self, hosts) -> None:
        """Recompute next-event times and re-enter the heap. Called only
        at round barriers (hosts quiescent). Stale heap entries are
        dropped lazily at pop time by comparing against
        host._cached_next."""
        heap = self._host_heap
        for host in hosts:
            host._dirty = False
            t = host.next_event_time()
            if t != host._cached_next:
                host._cached_next = t
                if t is not None:
                    heapq.heappush(heap, (t, host.host_id))

    def _rekey_dirty(self) -> None:
        """Round-barrier pass: every host that gained an event since the
        last barrier re-enters the heap (the sink list object is shared
        with the hosts, so it is drained in place)."""
        if self._dirty_hosts:
            dirty = self._dirty_hosts[:]
            self._dirty_hosts.clear()
            self._rekey_hosts(dirty)

    def _min_host_event(self):
        """Earliest pending event time across all hosts (None = all idle);
        lazily discards stale heap entries."""
        self._rekey_dirty()
        heap = self._host_heap
        by_id = self._host_by_id
        while heap:
            t, hid = heap[0]
            if by_id[hid]._cached_next == t:
                return t
            heapq.heappop(heap)
        return None

    def _pop_active(self, end_ns: int) -> list:
        """Hosts with an event before `end_ns`, in deterministic
        (next_t, host_id) order; they leave the heap (re-keyed after the
        round runs)."""
        self._rekey_dirty()
        heap = self._host_heap
        by_id = self._host_by_id
        active = []
        while heap and heap[0][0] < end_ns:
            t, hid = heapq.heappop(heap)
            host = by_id[hid]
            if host._cached_next == t:
                host._cached_next = None
                active.append(host)
        return active

    # -- heartbeat / watchdogs / progress (`manager.rs:675-793`) --------

    def _log_heartbeat(self, now_ns: int) -> None:
        """The tornettools-contract rusage line + a meminfo JSON line.
        Format is contractually stable (`manager.rs:692-717`)."""
        ru = resource_usage.rusage_self()
        log.info(
            "Process resource usage at simtime %d reported by getrusage(): "
            "ru_maxrss=%.03f GiB, ru_utime=%.03f minutes, "
            "ru_stime=%.03f minutes, ru_nvcsw=%d, ru_nivcsw=%d",
            now_ns,
            ru.ru_maxrss / 1048576.0,  # KiB -> GiB
            ru.ru_utime / 60.0,
            ru.ru_stime / 60.0,
            ru.ru_nvcsw,
            ru.ru_nivcsw,
        )
        try:
            mem = resource_usage.meminfo()
        except OSError as e:
            log.warning("unable to read /proc/meminfo: %s", e)
            return
        log.info(
            "System memory usage in bytes at simtime %d ns reported by "
            "/proc/meminfo: %s",
            now_ns,
            json.dumps(mem),
        )

    def _check_resource_usage(self) -> None:
        """Warn-once watchdogs: fd usage >90%%, free memory <500 MiB
        (`manager.rs:719-751`)."""
        if self._check_fd_usage:
            try:
                usage, limit = resource_usage.fd_usage()
                if usage > limit * 90 // 100:
                    log.warning(
                        "Using more than 90%% (%d/%d) of available file "
                        "descriptors", usage, limit)
                    self._check_fd_usage = False
            except OSError as e:
                log.warning("Unable to check fd usage: %s", e)
                self._check_fd_usage = False
        if self._check_mem_usage:
            try:
                remaining = resource_usage.memory_remaining()
                if remaining < 500 * 1024 * 1024:
                    log.warning("Only %d MiB of memory available",
                                remaining // 1024 // 1024)
                    self._check_mem_usage = False
            except OSError as e:
                log.warning("Unable to check memory usage: %s", e)
                self._check_mem_usage = False

    @staticmethod
    def _final_state_ok(proc, exp) -> bool:
        """The expected_final_state predicate, shared by the end-of-run
        check and the live progress counter (`worker.rs:589-604`)."""
        from ..process.process import ProcessState

        if exp.kind == FinalState.RUNNING:
            return proc.state == ProcessState.RUNNING
        if exp.kind == FinalState.EXITED:
            return (proc.state == ProcessState.EXITED
                    and proc.exit_status == exp.value)
        return (proc.state == ProcessState.KILLED
                and proc.kill_signal == exp.value)

    def _live_failures(self) -> int:
        """Processes already finished in a state that contradicts their
        expected_final_state (the status bar's failed counter)."""
        from ..process.process import ProcessState

        n = 0
        for _name, popt, cell in getattr(self, "_spawned", []):
            proc = cell.get("proc")
            if proc is None or proc.state == ProcessState.RUNNING:
                continue  # still running = not failed yet
            if not self._final_state_ok(proc, popt.expected_final_state):
                n += 1
        return n

    def _print_progress(self, now_ns: int) -> None:
        """`controller.rs:123-142` status line, at most once per wall
        second, to stderr (the non-TTY "printer" flavor)."""
        stop = max(1, self.config.general.stop_time)
        frac = min(100, round(100 * now_ns / stop))
        # shadowlint: disable=SL101 -- progress line realtime display; never feeds sim state
        wall = _walltime.monotonic() - self._wall_start
        print(
            f"{frac}% — simulated: {simtime.fmt(now_ns)}/"
            f"{simtime.fmt(stop)}, realtime: {wall:.1f}s, "
            f"processes failed: {self._live_failures()}",
            file=sys.stderr, flush=True,
        )

    # -- fault plane + self-healing (docs/robustness.md) ----------------

    def _fault_horizon(self, min_next):
        """Fold the next fault instant into the window computation so a
        round boundary lands EXACTLY on each scheduled fault — the
        SIGKILL/respawn happens at the configured virtual instant, not
        at whatever boundary drifts past it."""
        if self.fault_schedule is None:
            return min_next
        nxt = self.fault_schedule.peek_next_ns()
        if nxt is None or nxt >= self.controller.stop_time:
            return min_next
        return nxt if min_next is None else min(min_next, nxt)

    def _clamp_window_to_fault(self, start: int, end: int) -> int:
        """A fault instant STRICTLY INSIDE a window would otherwise fire
        a full runahead late (the start-side fold above only helps when
        the fault is the earliest event): shrink the round end to the
        fault instant so the next boundary lands on it. Shorter windows
        are always legal under conservative PDES."""
        if self.fault_schedule is None:
            return end
        nxt = self.fault_schedule.peek_next_ns()
        if nxt is not None and start < nxt < end:
            return nxt
        return end

    def _apply_faults(self, now_ns: int) -> None:
        """Fire every fault event due at this round boundary, mirroring
        the schedule's mask state onto the CPU objects (the device masks
        are read off the same schedule by device-plane drivers)."""
        if self.fault_schedule is None:
            return
        from .event import TaskRef

        link_changed = False
        for ev in self.fault_schedule.advance(now_ns):
            log.warning("fault plane: firing %s", ev.describe())
            if ev.kind in ("link_degrade", "link_restore"):
                link_changed = True  # table rebuilt ONCE after the loop
                continue
            host = self.hosts_by_name[ev.host]
            if ev.kind == "host_crash":
                purged = host.fault_crash()
                killed = 0
                for _pn, _popt, cell, _spawn in \
                        self._respawn_by_host.get(ev.host, ()):
                    proc = cell.get("proc")
                    if proc is not None and proc.is_alive:
                        proc.stop(9)  # SIGKILL at the virtual instant
                        killed += 1
                log.warning(
                    "fault plane: host %s crashed at %d (%d event(s) "
                    "purged, %d process(es) SIGKILLed)",
                    ev.host, now_ns, purged, killed)
            elif ev.kind == "host_reboot":
                host.fault_reboot()
                respawned = 0
                if self.config.faults.respawn_on_reboot:
                    for pn, popt, cell, spawn in \
                            self._respawn_by_host.get(ev.host, ()):
                        t = max(now_ns, popt.start_time)
                        host.schedule_task_at(
                            TaskRef(spawn, "process-respawn"), t)
                        respawned += 1
                # crashed hosts lost their heartbeat tasks with the
                # queue; restart the cadence at the reboot instant
                for tracker in self.trackers.values():
                    if tracker.host is host:
                        tracker.start()
                log.warning(
                    "fault plane: host %s rebooted at %d (%d process "
                    "respawn(s) scheduled)", ev.host, now_ns, respawned)
            elif ev.kind in ("iface_down", "iface_up"):
                host.fault_set_iface(ev.kind == "iface_up")
            elif ev.kind in ("host_degrade", "host_restore"):
                div = ev.bandwidth_div if ev.kind == "host_degrade" else 1
                host.relay_inet_out.set_fault_divisor(div)
            # corrupt_burst/_corrupt_end live entirely in the schedule
            # masks the send filter reads
        if link_changed and self.transport is not None:
            # keep on-device deliver arithmetic bit-identical to the CPU
            # overlay. One rebuild per boundary, not per event — the
            # schedule's lat_mult already reflects every event fired
            # above, and apply_fault_latency flushes the mirrored batch
            # and recompiles all four kernels (expensive)
            self.transport.apply_fault_latency(self.fault_schedule.lat_mult)

    def _collect_watchdog_blame(self, round_start_ns: int):
        """Runs ON THE WATCHDOG THREAD while workers may still be
        blocked: read-only over the process table + pidwatcher, builds
        the per-host blame the WatchdogError carries."""
        from ..faults.watchdog import HostBlame
        from ..process.pidwatcher import get_watcher

        watched = set(get_watcher().watched_pids())
        blame = []
        for host_name in sorted(self._respawn_by_host):
            procs, pids, wpids = [], [], []
            for proc_name, _popt, cell, _spawn in \
                    self._respawn_by_host[host_name]:
                proc = cell.get("proc")
                if proc is None or not getattr(proc, "is_alive", False):
                    continue
                procs.append(proc_name)
                native = getattr(proc, "proc", None)
                pid = getattr(native, "pid", None)
                if pid:
                    pids.append(pid)
                    if pid in watched:
                        wpids.append(pid)
            if procs:
                blame.append(HostBlame(host_name, procs, pids, wpids))
        return blame

    # -- guard plane (docs/robustness.md "Guard plane") ------------------

    def _collect_host_waits(self):
        """Who is waiting on what: every host holding alive processes,
        with its next queued event (None = blocked purely on input).
        Read-only over the process table, like the watchdog blame."""
        from ..guards.progress import HostWait
        from ..process.process import ProcessState

        waits = []
        for host_name in sorted(getattr(self, "_respawn_by_host", {})):
            procs = []
            for proc_name, _popt, cell, _spawn in \
                    self._respawn_by_host[host_name]:
                proc = cell.get("proc")
                if proc is None:
                    continue
                alive = getattr(proc, "is_alive", None)
                if alive is None:
                    alive = proc.state == ProcessState.RUNNING
                if alive:
                    procs.append(proc_name)
            if procs:
                host = self.hosts_by_name[host_name]
                waits.append(HostWait(host_name, procs,
                                      host.next_event_time()))
        return waits

    def _observe_progress(self, window_start: int, active,
                          events_before: int) -> None:
        """One round's progress sample: host events executed + packets
        moved. Everything observed is virtual-time/counter state — a
        run that never stalls is bitwise-unaffected."""
        events_after = sum(h.n_events_executed for h in active)
        packets_now = int(self.routing.packet_counters.sum())
        diagnosis = self._progress.observe(
            window_start,
            events_delta=events_after - events_before,
            packets_delta=packets_now - self._progress_packets,
        )
        self._progress_packets = packets_now
        if diagnosis is not None:
            diagnosis.waiting = self._collect_host_waits()
            diagnosis.device_in_flight = (
                self.transport.in_flight if self.transport else 0)
            self._guard_ledger.apply("progress",
                                     [diagnosis.to_violation()])

    def _final_guard_checks(self) -> None:
        """Teardown self-verification on settled counters: the device
        guard accumulator (transport kernels) and the full cross-plane
        reconciliation including SimStats fleet totals. Blocking pulls
        are fine here — the run is over."""
        if self._guard_ledger is None:
            return
        from ..guards.report import GuardViolation

        if self.transport is not None:
            report = self.transport.guard_report()
            if report is not None and not report["clean"]:
                self._guard_ledger.apply("device", [GuardViolation(
                    cls="device", check=",".join(report["classes"]),
                    time_ns=self.config.general.stop_time,
                    expected="clean device guard accumulator",
                    actual=report["classes"],
                    detail=f"first violation at guarded dispatch "
                           f"{report['first_window']} of "
                           f"{report['windows']}")])
        if self._guard_recon is not None:
            self._guard_ledger.apply(
                "reconcile",
                self._guard_recon.final(
                    self.config.general.stop_time,
                    packets_sent=self.stats.packets_sent))

    def _write_guard_report(self) -> None:
        if self._guard_ledger is None or not self.data_dir:
            return
        from ..guards.report import write_report

        extra = {"clean": not self._guard_ledger.violations}
        if self.transport is not None:
            try:
                extra["device_guard"] = self.transport.guard_report()
            except Exception as e:  # teardown path: report, don't mask
                log.warning("guards: device report unavailable: %s", e)
        if self._progress is not None:
            extra["progress_trips"] = self._progress.trips
        write_report(self.data_dir, self._guard_ledger, extra=extra)

    def _run_round_guarded(self, start: int, active, end: int):
        """scheduler.run_round under the round watchdog: a wedged
        managed process becomes a WatchdogError with host blame instead
        of a simulator that hangs forever."""
        if self._watchdog is None:
            return self.scheduler.run_round(active, end)
        with self._watchdog.guard(start):
            sched_min = self.scheduler.run_round(active, end)
        if self._watchdog.strike is not None:
            raise self._watchdog.strike
        return sched_min

    def _checkpoint_due(self, window_start: int) -> None:
        interval = self.config.faults.checkpoint.interval
        if (self._next_ckpt_ns is None or self._ckpt_dir is None
                or window_start < self._next_ckpt_ns):
            return
        from ..faults.checkpoint import write_manager_checkpoint

        write_manager_checkpoint(
            self, self._ckpt_dir, window_start, reason="periodic",
            keep=self.config.faults.checkpoint.keep)
        while self._next_ckpt_ns <= window_start:
            self._next_ckpt_ns += interval

    def _emergency_checkpoint(self) -> None:
        """Crash/watchdog path: preserve the forensic state of exactly
        the run that needs explaining. Never raises."""
        if self._ckpt_dir is None:
            return
        from ..faults.checkpoint import write_manager_checkpoint

        write_manager_checkpoint(
            self, self._ckpt_dir, self._last_window_start,
            reason="emergency")

    def _round_upkeep(self, window_start: int) -> None:
        """Per-round heartbeat/watchdog/progress pass (`manager.rs:439-453`)."""
        if (self._heartbeat_interval
                and window_start >= self._last_heartbeat
                + self._heartbeat_interval):
            self._last_heartbeat = window_start
            self._log_heartbeat(window_start)
        # shadowlint: disable=SL101 -- heartbeat/watchdog pacing; never feeds sim state
        wall = _walltime.monotonic()
        if wall - self._last_resource_check >= 30.0:
            self._last_resource_check = wall
            self._check_resource_usage()
        if self._progress_enabled and wall - self._last_progress >= 1.0:
            self._last_progress = wall
            self._print_progress(window_start)
        if self.harvester is not None and self.harvester.due(window_start):
            self._telemetry_tick(window_start)
        self._checkpoint_due(window_start)

    def run(self) -> SimStats:
        if self.config.experimental.use_flow_engine:
            # tgen-shaped workload on the device flow engine: the round
            # loop never runs; flowplan reconciles completions into the
            # same SimStats surface (failures, packets, sim time).
            # Checkpoints are bucket-granular (flowplan.py): --resume
            # skips completed buckets, results bitwise-identical.
            from . import flowplan

            return flowplan.run_flow_simulation(
                self.config, self.routing, self.stats,
                checkpoint_dir=self._ckpt_dir
                if self.config.faults.checkpoint.interval is not None
                or self.resume_from else None,
                resume_from=self.resume_from)
        wall_start = _walltime.monotonic()  # shadowlint: disable=SL101 -- perf stat
        self._wall_start = wall_start
        self._last_resource_check = wall_start
        if self.resume_from:
            # round-loop runs cannot restore mid-run state (host event
            # queues hold live closures, managed processes hold kernel
            # state — docs/robustness.md); only the flow engine and the
            # device-plane drivers resume. Fail loudly, don't pretend.
            from .config import ConfigError

            raise ConfigError(
                "--resume is supported for flow-engine runs "
                "(experimental.use_flow_engine) and device-plane "
                "checkpoints (tools/chaos_smoke.py); round-loop Manager "
                "checkpoints are diagnostic snapshots — see "
                "docs/robustness.md")
        if self.config.faults.watchdog:
            from ..faults.watchdog import RoundWatchdog

            self._watchdog = RoundWatchdog(
                self.config.faults.watchdog / 1e9,
                self._collect_watchdog_blame)
        try:
            # round 0: boot all hosts (schedules application-start tasks)
            for host in self._host_order:
                host.boot()
            for tracker in self.trackers.values():
                tracker.start()
            self._start_telemetry()

            # the scheduling loop (`manager.rs:392-478`)
            min_next = self._min_host_event()
            window = self.controller.next_window(
                self._fault_horizon(min_next))
            while window is not None:
                start, end = window
                self._last_window_start = start
                self._apply_faults(start)
                end = self._clamp_window_to_fault(start, end)
                self._round_upkeep(start)
                if self.transport is not None:
                    # release device-held packets due in this window into
                    # host event queues before anyone executes; the device
                    # chains straight through delivery-free windows up to
                    # the earliest CPU-side event (host queues are
                    # quiescent here, so that horizon is exact). The
                    # fault horizon clamps the chain too: the device must
                    # not run past an instant whose crash/link event the
                    # CPU hasn't applied yet.
                    host_min = self._min_host_event()
                    self.transport.release(
                        start, end, horizon_ns=self._fault_horizon(host_min),
                        runahead_ns=self.runahead.get(),
                        stop_ns=self.controller.stop_time,
                    )
                # only hosts with an event in this window run; everyone
                # else keeps their heap entry untouched
                active = self._pop_active(end)
                events_before = (
                    sum(h.n_events_executed for h in active)
                    if self._progress is not None else 0)
                # sched_min matters in sync device mode: a packet captured
                # this round lives on NEITHER a host queue nor the device
                # yet (ingest happens at finish_round below) — only the
                # sending worker's next_event_time knows its deliver time
                # (`manager.rs:430-436`)
                sched_min = self._run_round_guarded(start, active, end)
                if self.transport is not None:
                    # barrier: ship this round's captured egress to device
                    self.transport.finish_round(start, end)
                # round boundary: absorb watcher-thread posts (managed
                # process deaths) into the now-quiescent host queues.
                # pop() one at a time: a copy-then-clear would race the
                # watcher thread's append between the two ops and lose
                # the host's drain forever (its sink guard only re-arms
                # once _cross_pending empties)
                while self._cross_hosts:
                    self._cross_hosts.pop().drain_cross_thread_tasks()
                # ran hosts left the heap at _pop_active; dirty hosts
                # (event pushes during the round) re-key alongside them
                self._rekey_hosts(active)
                self.stats.rounds += 1
                if self._progress is not None:
                    self._observe_progress(start, active, events_before)
                min_next = self._min_host_event()
                for t in (sched_min,
                          None if self.transport is None
                          else self.transport.next_pending_abs):
                    if t is not None:
                        min_next = t if min_next is None else min(min_next, t)
                window = self.controller.next_window(
                    self._fault_horizon(min_next))

            if self.transport is not None:
                # mirrored mode: drain the lagged device-verification
                # pipeline before declaring the run done
                self.transport.finalize()
                if self.transport.divergence_count:
                    # a diverged mirror is a FAILED run (nonzero CLI
                    # exit), not a log line — the device re-execution is
                    # a correctness gate (VERDICT r4 #6)
                    self.stats.process_failures.append((
                        "device-transport",
                        f"mirrored device transport diverged from the "
                        f"CPU ledger in "
                        f"{self.transport.divergence_count} window(s) "
                        f"of {self.transport.verified_windows} verified",
                    ))

            # absorb any managed-process death the watcher reported too
            # late for a round-boundary drain
            for host in self.hosts:
                for proc in host.processes:
                    reap = getattr(proc, "reap_if_native_dead", None)
                    if reap is not None:
                        reap()

            # final telemetry harvest (after transport finalize so the
            # device counters are settled) + trace export
            self._finish_telemetry()

            # expected-final-state check happens before teardown kills
            # everyone (extend: a transport-divergence failure may
            # already be recorded above)
            self.stats.process_failures.extend(self._check_final_states())

            # teardown (`manager.rs:480-489`)
            for host in self._host_order:
                host.shutdown()
            self.scheduler.join()

            self.stats.sim_time_ns = self.config.general.stop_time
            self.stats.events_executed = sum(
                h.n_events_executed for h in self._host_order)
            self.stats.packets_sent = int(self.routing.packet_counters.sum())
            self.stats.packets_dropped = self.shared.packet_drop_count
            self.stats.packets_dropped_fault = (
                self.shared.fault_drop_count
                + sum(h.fault_packets_dropped for h in self.hosts))
            # the full capacity trajectory (growths + drops, incl.
            # anything finalize() just accounted) lands in the final
            # stats — sim-stats.json carries it verbatim. getattr:
            # tests stand in phantom transports without the policy.
            cap = getattr(self.transport, "capacity", None)
            if cap is not None:
                self.stats.capacity_events = list(cap.events)
            # shadowlint: disable=SL101 -- wall-clock perf stat only
            self.stats.wall_seconds = _walltime.monotonic() - wall_start
            for writer in self._pcap_writers:
                writer.close()

            # guard plane teardown pass: device guard accumulator +
            # full cross-plane reconciliation against the settled
            # SimStats totals. Runs LAST so an abort policy reports on
            # a finished, fully-accounted run (the raise still takes
            # the crash path below: emergency checkpoint + telemetry
            # finalize = the postmortem bundle).
            self._final_guard_checks()
            return self.stats
        except BaseException as e:
            # crash / watchdog path: drop the emergency checkpoint FIRST
            # — it documents exactly the run that is about to die — then
            # let the error propagate through the telemetry-preserving
            # finally below. A plain `abort` guard policy opts out of
            # the checkpoint; `abort+checkpoint` keeps it.
            from ..guards.report import GuardError

            if not isinstance(e, GuardError) or e.want_checkpoint:
                self._emergency_checkpoint()
            raise
        finally:
            # crash path: preserve whatever telemetry is buffered — the
            # run that died is exactly the one the heartbeats should
            # explain. Idempotent after the normal _finish_telemetry.
            if self.harvester is not None:
                try:
                    self.harvester.finalize()
                except Exception as e:  # never mask the primary error
                    log.warning("telemetry flush failed at teardown: %s", e)
            # every guarded run leaves guards-report.json behind — the
            # violation report for aborts, a clean: true record
            # otherwise. write_report never raises.
            self._write_guard_report()
            # a data-dir-less run's per-host filesystem trees live in a
            # private temp root: the caller never asked for persistence
            tmp_root = getattr(self, "_tmp_data_root", None)
            if tmp_root is not None:
                import shutil

                shutil.rmtree(tmp_root, ignore_errors=True)
                self._tmp_data_root = None
            # drop the process-wide status hook so later Manager instances
            # in the same process don't pay per-packet dispatch to a stale
            # tracker set (only if it is still ours — a newer Manager may
            # have installed its own)
            from ..net import packet as packet_mod

            if (
                self._status_hook is not None
                and packet_mod.status_trace_hook is self._status_hook
            ):
                packet_mod.status_trace_hook = None

    @property
    def guard_violations(self) -> list:
        """Every violation the guard plane recorded this run (empty
        when guards are off or the run was clean)."""
        return (list(self._guard_ledger.violations)
                if self._guard_ledger is not None else [])

    def host_stats(self) -> dict:
        """Per-host tracker counters for sim-stats.json, plus perf-timer
        readings when experimental.use_perf_timers is on."""
        out = {name: t.counters.as_dict() for name, t in self.trackers.items()}
        if self.config.experimental.use_perf_timers:
            for host in self.hosts:
                # every handler ever created on the host registers itself
                # (incl. fork children already reaped) — see
                # SyscallHandler.__init__'s perf_handlers registry
                # closed handlers folded their durations into the host
                # aggregate; live ones still hold their own dicts
                agg: dict[int, int] = dict(
                    getattr(host, "perf_syscall_ns", {}))
                for handler in getattr(host, "perf_handlers", []):
                    for nr, ns in handler.syscall_ns.items():
                        agg[nr] = agg.get(nr, 0) + ns
                entry = out.setdefault(host.name, {})
                entry["perf"] = {
                    "execution_ns": host.execution_ns,
                    "syscall_ns": {str(nr): ns
                                   for nr, ns in sorted(agg.items())},
                }
        return out


def run_simulation(config: ConfigOptions) -> SimStats:
    return Manager(config).run()
