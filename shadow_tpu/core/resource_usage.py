"""Process/system resource probes for manager heartbeats and watchdogs.

Parity: reference `src/main/core/resource_usage.rs` (meminfo parsing) and
`manager.rs:675-793` (getrusage heartbeat, fd/memory watchdogs).
"""

from __future__ import annotations

import os
import resource


def rusage_self():
    """getrusage(RUSAGE_SELF) — maxrss in KiB, times in seconds."""
    return resource.getrusage(resource.RUSAGE_SELF)


def meminfo(path: str = "/proc/meminfo") -> dict[str, int]:
    """Parse /proc/meminfo into {field: bytes} (`resource_usage.rs`).

    Values are reported by the kernel in KiB despite the 'kB' suffix.
    """
    out: dict[str, int] = {}
    with open(path) as fh:
        for line in fh:
            if ":" not in line:
                continue
            key, rest = line.split(":", 1)
            parts = rest.split()
            if not parts:
                continue
            try:
                value = int(parts[0])
            except ValueError:
                continue
            if len(parts) > 1 and parts[1] == "kB":
                value *= 1024
            out[key.strip()] = value
    return out


def fd_usage() -> tuple[int, int]:
    """(open fds, soft limit) — `manager.rs:756-775`."""
    count = len(os.listdir("/proc/self/fd"))
    soft, _hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    return count, soft


def memory_remaining() -> int:
    """Available system memory in bytes (`manager.rs:777-793`)."""
    info = meminfo()
    return info.get("MemAvailable", 0)
