"""Deterministic RNG streams.

The simulation's determinism contract: one global stream seeded from the
config seed; each host derives its own independent stream so results do not
depend on scheduling order or thread count.

Parity: reference `src/main/core/sim_config.rs:49-50` (global Xoshiro256++
seeded from the config seed) and `sim_config.rs:217-244` (per-host seed =
global random value XOR a stable hostname hash). We keep the same structure —
xoshiro256++ core, splitmix64 seeding, hostname-hash mixing — so host streams
are independent of host construction order beyond the config-declared order.

The TPU plane does NOT use these streams: it uses counter-based keys
(jax.random threefry keyed by (host_seed, counter)) so that vectorization and
sharding cannot reorder draws. `host_seed_for` here is the bridge — the same
per-host 64-bit seed feeds both planes.
"""

from __future__ import annotations

import hashlib

_MASK = (1 << 64) - 1


def splitmix64(state: int) -> tuple[int, int]:
    """One splitmix64 step: returns (new_state, output)."""
    state = (state + 0x9E3779B97F4A7C15) & _MASK
    z = state
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK
    return state, z ^ (z >> 31)


def _rotl(x: int, k: int) -> int:
    return ((x << k) | (x >> (64 - k))) & _MASK


def hostname_hash(name: str) -> int:
    """Stable 64-bit hash of a hostname (blake2b-8; not Python's salted hash)."""
    return int.from_bytes(hashlib.blake2b(name.encode(), digest_size=8).digest(), "little")


class Xoshiro256pp:
    """xoshiro256++ PRNG; deterministic across platforms and Python versions."""

    __slots__ = ("s",)

    def __init__(self, seed: int):
        state = seed & _MASK
        s = []
        for _ in range(4):
            state, out = splitmix64(state)
            s.append(out)
        self.s = s

    def next_u64(self) -> int:
        s = self.s
        result = (_rotl((s[0] + s[3]) & _MASK, 23) + s[0]) & _MASK
        t = (s[1] << 17) & _MASK
        s[2] ^= s[0]
        s[3] ^= s[1]
        s[1] ^= s[2]
        s[0] ^= s[3]
        s[2] ^= t
        s[3] = _rotl(s[3], 45)
        return result

    # -- convenience draws used by the simulation ---------------------------

    def random(self) -> float:
        """Uniform float in [0, 1) with 53 bits of precision."""
        return (self.next_u64() >> 11) * (2.0**-53)

    def randrange(self, lo: int, hi: int) -> int:
        """Uniform integer in [lo, hi) via rejection-free Lemire-style reduction."""
        span = hi - lo
        if span <= 0:
            raise ValueError("empty range")
        return lo + (self.next_u64() * span >> 64)

    def bernoulli(self, p: float) -> bool:
        return self.random() < p

    def shuffle(self, xs: list) -> None:
        for i in range(len(xs) - 1, 0, -1):
            j = self.randrange(0, i + 1)
            xs[i], xs[j] = xs[j], xs[i]


def host_seed_for(global_rng: Xoshiro256pp, hostname: str) -> int:
    """Per-host seed: a draw from the global stream XOR the hostname hash.

    Drawing in config-declared host order makes the seed independent of
    scheduling; XORing the name hash decorrelates hosts that would otherwise
    share a draw position across config edits.
    """
    return global_rng.next_u64() ^ hostname_hash(hostname)
