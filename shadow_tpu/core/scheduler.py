"""Host-parallel schedulers.

Parity: reference `src/lib/scheduler/` — hosts are the unit of parallelism;
within one round every host runs independently and a barrier separates
rounds. `ThreadPerCoreScheduler` mirrors the default thread-per-core design
with work stealing (`thread_per_core.rs:193-212`): worker threads drain a
shared host list via an atomic cursor (equivalent to stealing from a global
pool; determinism holds because per-round host execution is independent and
all cross-host effects carry scheduling-independent ordering keys).
`SerialScheduler` mirrors thread-per-host degenerate single-thread use and is
the default for the Python plane (the heavy batched work belongs to the TPU
plane; the C++ syscall plane has its own pool).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from .worker import Worker, WorkerShared


class SerialScheduler:
    parallelism = 1

    def __init__(self, shared: WorkerShared):
        self.worker = Worker(shared, 0)

    def run_round(self, hosts, round_end: int) -> Optional[int]:
        """Execute all hosts up to `round_end`; return the min next-event
        time across hosts and in-flight packet deliveries."""
        w = self.worker
        w.start_round(round_end)
        min_next: Optional[int] = None
        for host in hosts:
            w.set_active_host(host)
            host.execute(round_end)
            t = host.next_event_time()
            if t is not None and (min_next is None or t < min_next):
                min_next = t
            w.set_active_host(None)
        if w.next_event_time is not None and (
            min_next is None or w.next_event_time < min_next
        ):
            min_next = w.next_event_time
        return min_next

    def join(self) -> None:
        pass


class ThreadPerCoreScheduler:
    """N worker threads pull hosts from a shared cursor each round."""

    def __init__(self, shared: WorkerShared, parallelism: int):
        self.parallelism = max(1, parallelism)
        self._workers = [Worker(shared, i) for i in range(self.parallelism)]

    def run_round(self, hosts, round_end: int) -> Optional[int]:
        hosts = list(hosts)
        cursor = [0]
        cursor_lock = threading.Lock()
        results: list[Optional[int]] = [None] * self.parallelism

        def run(worker: Worker, slot: int):
            worker.start_round(round_end)
            min_next: Optional[int] = None
            while True:
                with cursor_lock:
                    i = cursor[0]
                    cursor[0] += 1
                if i >= len(hosts):
                    break
                host = hosts[i]
                worker.set_active_host(host)
                host.execute(round_end)
                t = host.next_event_time()
                if t is not None and (min_next is None or t < min_next):
                    min_next = t
                worker.set_active_host(None)
            if worker.next_event_time is not None and (
                min_next is None or worker.next_event_time < min_next
            ):
                min_next = worker.next_event_time
            results[slot] = min_next

        threads = [
            threading.Thread(target=run, args=(w, i), daemon=True)
            for i, w in enumerate(self._workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()  # the round barrier

        live = [r for r in results if r is not None]
        return min(live) if live else None

    def join(self) -> None:
        pass


def make_scheduler(kind: str, shared: WorkerShared, parallelism: int):
    if kind == "serial" or parallelism <= 1:
        return SerialScheduler(shared)
    if kind in ("thread-per-core", "thread-per-host"):
        return ThreadPerCoreScheduler(shared, parallelism)
    raise ValueError(f"unknown scheduler {kind!r}")
