"""Host-parallel schedulers.

Parity: reference `src/lib/scheduler/` — hosts are the unit of parallelism;
within one round every host runs independently and a barrier separates
rounds. Three schedulers, as in the reference (`configuration.rs:533`):

- `ThreadPerCoreScheduler` (default): N persistent worker threads, hosts
  dealt round-robin into per-thread queues each round, **work stealing**
  when a thread drains its own queue (it cycles over the other threads'
  queues starting from its own index, `thread_per_core.rs:193-212`).
  Threads are created once and parked between rounds (the reference's
  UnboundedThreadPool), and pinned to CPUs when the platform allows
  (`affinity.c`; `use_cpu_pinning` defaults on).
- `ThreadPerHostScheduler`: one persistent OS thread per host, host pinned
  to its thread for the simulation's lifetime (`thread_per_host.rs`).
- `SerialScheduler`: single-thread degenerate case.

Determinism holds for all three because per-round host execution is
independent and all cross-host effects carry scheduling-independent
ordering keys — `tools/compare_runs.py --matrix` proves it per config.
The Python planes are GIL-bound; the scalable data path is the TPU plane
(`shadow_tpu.tpu`), and these schedulers exist for semantic parity and for
overlapping managed-process I/O waits, which do release the GIL.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, List, Optional, Sequence

from .worker import Worker, WorkerShared
from .worker import set_current_cpu as worker_mod_set_cpu


def _pin_to_cpu(index: int) -> Optional[int]:
    """Best-effort CPU pinning (`affinity_getGoodWorkerAffinity`): worker i
    gets core i mod n_cores. Returns the chosen cpu (None = unsupported)
    and records it thread-locally so managed native threads can be
    migrated to follow their worker (`managed_thread.rs:533-544`)."""
    try:
        cpus = sorted(os.sched_getaffinity(0))
        cpu = cpus[index % len(cpus)]
        os.sched_setaffinity(0, {cpu})
    except (AttributeError, OSError):
        return None
    worker_mod_set_cpu(cpu)
    return cpu


class SerialScheduler:
    parallelism = 1

    def __init__(self, shared: WorkerShared):
        self.worker = Worker(shared, 0)

    def run_round(self, hosts, round_end: int) -> Optional[int]:
        """Execute all hosts up to `round_end`; return the min next-event
        time across hosts and in-flight packet deliveries."""
        w = self.worker
        w.start_round(round_end)
        min_next: Optional[int] = None
        for host in hosts:
            w.set_active_host(host)
            host.execute(round_end)
            t = host.next_event_time()
            if t is not None and (min_next is None or t < min_next):
                min_next = t
            w.set_active_host(None)
        if w.next_event_time is not None and (
            min_next is None or w.next_event_time < min_next
        ):
            min_next = w.next_event_time
        return min_next

    def join(self) -> None:
        pass


class _RoundPool:
    """Persistent worker threads executing one callback per round.

    The reference keeps one pool for the whole simulation and parks workers
    between rounds (`pools/unbounded.rs`); respawning threads per round (the
    round-1 design) cost a spawn/join per thread per window.
    """

    def __init__(self, n: int, pin_cpus: bool):
        self._n = n
        self._round_fn: Optional[Callable[[int], None]] = None
        self._gen = 0
        self._done = 0
        self._errors: List[BaseException] = []
        self._lock = threading.Lock()
        self._start_cv = threading.Condition(self._lock)
        self._done_cv = threading.Condition(self._lock)
        self._stop = False
        self._threads = [
            threading.Thread(
                target=self._loop, args=(i, pin_cpus), daemon=True,
                name=f"shadow-worker-{i}",
            )
            for i in range(n)
        ]
        for t in self._threads:
            t.start()

    def _loop(self, index: int, pin_cpus: bool) -> None:
        if pin_cpus:
            _pin_to_cpu(index)
        seen = 0
        while True:
            with self._start_cv:
                while self._gen == seen and not self._stop:
                    self._start_cv.wait()
                if self._stop:
                    return
                seen = self._gen
                fn = self._round_fn
            # the thread must survive a failing round: swallow the error
            # into the barrier result so the pool stays whole and run()
            # re-raises on the driving thread
            err: Optional[BaseException] = None
            try:
                fn(index)
            except BaseException as e:  # noqa: BLE001 — transported, not dropped
                err = e
            with self._done_cv:
                if err is not None:
                    self._errors.append(err)
                self._done += 1
                if self._done == self._n:
                    self._done_cv.notify_all()

    def run(self, fn: Callable[[int], None]) -> None:
        """Run `fn(worker_index)` on every thread; blocks until all done
        (the round barrier). Re-raises the first worker exception here."""
        with self._start_cv:
            self._round_fn = fn
            self._done = 0
            self._errors = []
            self._gen += 1
            self._start_cv.notify_all()
        with self._done_cv:
            while self._done < self._n:
                self._done_cv.wait()
            errors = self._errors
        if errors:
            raise errors[0]

    def shutdown(self) -> None:
        with self._start_cv:
            self._stop = True
            self._start_cv.notify_all()
        for t in self._threads:
            t.join(timeout=5)


class ThreadPerCoreScheduler:
    """Persistent pinned workers + per-thread host queues + work stealing."""

    def __init__(self, shared: WorkerShared, parallelism: int,
                 pin_cpus: bool = True):
        self.parallelism = max(1, parallelism)
        self._workers = [Worker(shared, i) for i in range(self.parallelism)]
        self._pool = _RoundPool(self.parallelism, pin_cpus)
        self._results: List[Optional[int]] = [None] * self.parallelism
        # per-thread double-buffered host queues (`thread_per_core.rs:87-94`);
        # rebuilt per round from the host list, guarded by the round barrier
        self._queues: List[List] = [[] for _ in range(self.parallelism)]
        self._cursors: List[int] = [0] * self.parallelism
        self._qlocks = [threading.Lock() for _ in range(self.parallelism)]
        self._round_end = 0

    def _worker_round(self, index: int) -> None:
        worker = self._workers[index]
        worker.start_round(self._round_end)
        min_next: Optional[int] = None
        n = self.parallelism
        # drain own queue, then steal others' cycling from own index
        # (`thread_per_core.rs:193-212`)
        for qi in range(n):
            q = (index + qi) % n
            queue = self._queues[q]
            lock = self._qlocks[q]
            while True:
                with lock:
                    i = self._cursors[q]
                    if i >= len(queue):
                        break
                    self._cursors[q] = i + 1
                host = queue[i]
                worker.set_active_host(host)
                host.execute(self._round_end)
                t = host.next_event_time()
                if t is not None and (min_next is None or t < min_next):
                    min_next = t
                worker.set_active_host(None)
        if worker.next_event_time is not None and (
            min_next is None or worker.next_event_time < min_next
        ):
            min_next = worker.next_event_time
        self._results[index] = min_next

    def run_round(self, hosts, round_end: int) -> Optional[int]:
        n = self.parallelism
        for q in self._queues:
            q.clear()
        # round-robin deal mirrors the reference's host assignment
        # (`thread_per_core.rs:70-85`); hosts were already shuffled once by
        # the manager for load balance (`manager.rs:272`)
        for i, host in enumerate(hosts):
            self._queues[i % n].append(host)
        self._cursors = [0] * n
        self._results = [None] * n
        self._round_end = round_end
        self._pool.run(self._worker_round)
        live = [r for r in self._results if r is not None]
        return min(live) if live else None

    def join(self) -> None:
        self._pool.shutdown()


class ThreadPerHostScheduler:
    """One persistent thread per host; the host never migrates
    (`thread_per_host.rs` — host pinned in TLS for its lifetime). The
    number of hosts *running* at once is bounded by `parallelism` via a
    semaphore — the reference analogue is the logical-processor layer that
    multiplexes per-host threads onto worker CPUs (`pools/bounded.rs`)."""

    def __init__(self, shared: WorkerShared, hosts: Sequence,
                 parallelism: int, pin_cpus: bool = True):
        self.parallelism = max(1, parallelism)
        self._hosts = list(hosts)
        self._known = set(map(id, self._hosts))  # hosts pinned for life
        n = len(self._hosts)
        self._workers = [Worker(shared, i) for i in range(n)]
        self._pool = _RoundPool(n, pin_cpus)
        self._run_slots = threading.Semaphore(self.parallelism)
        self._results: List[Optional[int]] = [None] * n
        self._round_end = 0

    def _worker_round(self, index: int) -> None:
        worker = self._workers[index]
        host = self._hosts[index]
        if id(host) not in self._active:
            # not in this round's active set: the host has no event
            # before the round end, nothing to do (its thread still
            # exists — hosts are pinned for the simulation's lifetime)
            self._results[index] = None
            return
        min_next: Optional[int] = None
        with self._run_slots:
            worker.start_round(self._round_end)
            worker.set_active_host(host)
            host.execute(self._round_end)
            t = host.next_event_time()
            if t is not None:
                min_next = t
            worker.set_active_host(None)
        if worker.next_event_time is not None and (
            min_next is None or worker.next_event_time < min_next
        ):
            min_next = worker.next_event_time
        self._results[index] = min_next

    def run_round(self, hosts, round_end: int) -> Optional[int]:
        known = self._known
        if any(id(h) not in known for h in hosts):
            raise ValueError(
                "thread-per-host hosts are pinned at construction; "
                "run_round was given an unknown host"
            )
        self._active = set(map(id, hosts))
        self._results = [None] * len(self._hosts)
        self._round_end = round_end
        self._pool.run(self._worker_round)
        live = [r for r in self._results if r is not None]
        return min(live) if live else None

    def join(self) -> None:
        self._pool.shutdown()


def make_scheduler(kind: str, shared: WorkerShared, parallelism: int,
                   hosts: Optional[Sequence] = None, pin_cpus: bool = True):
    if kind == "thread-per-host":
        if not hosts:
            raise ValueError(
                "thread-per-host scheduler requires a non-empty host list")
        return ThreadPerHostScheduler(shared, hosts, parallelism, pin_cpus)
    if kind == "serial" or parallelism <= 1:
        return SerialScheduler(shared)
    if kind == "thread-per-core":
        return ThreadPerCoreScheduler(shared, parallelism, pin_cpus)
    raise ValueError(f"unknown scheduler {kind!r}")
