"""Simulation-aware logging.

Parity: reference `src/main/core/logger/shadow_logger.rs` — every record is
tagged with the *emulated* time and the executing host, so logs from
parallel runs are comparable and the determinism harness can diff them.
The reference buffers asynchronously for throughput; Python's logging is
synchronous, so the deterministic content contract is the part preserved
(timestamps of the real clock are excluded from the deterministic format).
"""

from __future__ import annotations

import logging

from . import simtime
from .worker import current_host


class SimContextFilter(logging.Filter):
    """Injects sim_time / host_name fields into every record."""

    def filter(self, record: logging.LogRecord) -> bool:
        host = current_host()
        record.host_name = host.name if host is not None else "-"
        record.sim_time = host.now() if host is not None else 0
        record.sim_time_str = simtime.fmt(record.sim_time)
        return True


DETERMINISTIC_FORMAT = (
    "%(sim_time_str)s [%(levelname)s] [%(host_name)s] %(name)s: %(message)s"
)
WALL_FORMAT = (
    "%(asctime)s %(sim_time_str)s [%(levelname)s] [%(host_name)s] "
    "%(name)s: %(message)s"
)


def init_logging(level: int = logging.INFO, deterministic: bool = True,
                 stream=None) -> logging.Handler:
    """Install a handler on the shadow_tpu logger tree; returns it so the
    CLI can flush/remove. Deterministic mode omits wall-clock timestamps
    (the diffable format the determinism harness compares)."""
    logger = logging.getLogger("shadow_tpu")
    handler = logging.StreamHandler(stream)
    handler.setFormatter(
        logging.Formatter(DETERMINISTIC_FORMAT if deterministic else WALL_FORMAT)
    )
    handler.addFilter(SimContextFilter())
    logger.addHandler(handler)
    logger.setLevel(level)
    return handler
