"""Simulation-aware logging.

Parity: reference `src/main/core/logger/shadow_logger.rs` — every record is
tagged with the *emulated* time and the executing host, so logs from
parallel runs are comparable and the determinism harness can diff them.

Like the reference (`shadow_logger.rs:17-60`, async buffered flush with
a flusher thread), records are handed to a background flusher by
default: the worker thread pays only a queue put (sim context is
captured producer-side, where the thread-local host is visible), and
the blocking stderr write happens on the flusher. The deterministic
content contract is unchanged — the async path formats exactly the
records the sync path would, and `close()` drains the queue before the
CLI exits. Per-thread record order is preserved; cross-thread
interleaving was never deterministic in either mode (the reference's
isn't either — the determinism harness strips/sorts accordingly).
"""

from __future__ import annotations

import logging
import logging.handlers
import queue as _queue

from . import simtime
from .worker import current_host


class SimContextFilter(logging.Filter):
    """Injects sim_time / host_name fields into every record."""

    def filter(self, record: logging.LogRecord) -> bool:
        host = current_host()
        record.host_name = host.name if host is not None else "-"
        record.sim_time = host.now() if host is not None else 0
        record.sim_time_str = simtime.fmt(record.sim_time)
        return True


DETERMINISTIC_FORMAT = (
    "%(sim_time_str)s [%(levelname)s] [%(host_name)s] %(name)s: %(message)s"
)
WALL_FORMAT = (
    "%(asctime)s %(sim_time_str)s [%(levelname)s] [%(host_name)s] "
    "%(name)s: %(message)s"
)


class AsyncShadowHandler(logging.handlers.QueueHandler):
    """Buffered background flush (`shadow_logger.rs:17-60`): the
    producer thread captures the sim context (filters run producer-side
    — the thread-local active host is only visible there) and enqueues;
    a daemon listener thread runs the real stream handler. `close()`
    stops the listener, which drains every queued record first."""

    def __init__(self, target: logging.Handler):
        super().__init__(_queue.SimpleQueue())
        self.addFilter(SimContextFilter())
        self._listener = logging.handlers.QueueListener(self.queue, target)
        self._target = target
        self._listener.start()

    def close(self) -> None:
        if self._listener is not None:
            self._listener.stop()  # joins the thread after a full drain
            self._listener = None
            self._target.close()
        super().close()

    def flush(self) -> None:
        # stop/start cycles the listener through a full queue drain
        if self._listener is not None:
            self._listener.stop()
            self._target.flush()
            self._listener.start()


def init_logging(level: int = logging.INFO, deterministic: bool = True,
                 stream=None, buffered: bool = True) -> logging.Handler:
    """Install a handler on the shadow_tpu logger tree; returns it so the
    CLI can flush/remove. Deterministic mode omits wall-clock timestamps
    (the diffable format the determinism harness compares). `buffered`
    (default) flushes from a background thread like the reference's
    ShadowLogger; pass False for strictly synchronous emission (e.g.
    debugging a crash where the tail of the log matters)."""
    logger = logging.getLogger("shadow_tpu")
    target = logging.StreamHandler(stream)
    target.setFormatter(
        logging.Formatter(DETERMINISTIC_FORMAT if deterministic else WALL_FORMAT)
    )
    if buffered:
        handler: logging.Handler = AsyncShadowHandler(target)
    else:
        handler = target
        handler.addFilter(SimContextFilter())
    logger.addHandler(handler)
    logger.setLevel(level)
    return handler
