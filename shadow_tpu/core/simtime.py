"""Simulation and emulated time.

Times are plain Python ints (nanoseconds) for hot-loop speed; this module
provides the constants, conversions, and the emulated-time epoch.

Parity: reference `src/lib/shadow-shim-helper-rs/src/simulation_time.rs:22`
(SimulationTime = u64 nanoseconds since simulation start) and
`emulated_time.rs:18-45` (EmulatedTime epoch = 2000-01-01 00:00:00 UTC, so
simulated applications observe plausible wall-clock dates).
"""

from __future__ import annotations

import datetime

# One unit of each duration, in nanoseconds.
NANOSECOND = 1
MICROSECOND = 1_000
MILLISECOND = 1_000_000
SECOND = 1_000_000_000
MINUTE = 60 * SECOND
HOUR = 60 * MINUTE

# EmulatedTime epoch: what sim-time zero looks like to managed applications.
# 2000-01-01T00:00:00Z expressed as ns since the UNIX epoch.
EMUTIME_SIMULATION_START_UNIX_NS = int(
    datetime.datetime(2000, 1, 1, tzinfo=datetime.timezone.utc).timestamp()
) * SECOND


# clockids whose reads are raw simulation time (zero at sim start) rather
# than emulated-epoch time: MONOTONIC(1), MONOTONIC_RAW(4),
# MONOTONIC_COARSE(6), BOOTTIME(7). Must match clockid_is_monotonic() in
# interpose/shim.cc (the in-shim fast path answers the same clocks).
MONOTONIC_CLOCK_IDS = frozenset((1, 4, 6, 7))


def emulated_from_sim(sim_ns: int) -> int:
    """Map simulation time -> emulated UNIX time (ns) seen by applications."""
    return EMUTIME_SIMULATION_START_UNIX_NS + sim_ns


def sim_from_emulated(emu_unix_ns: int) -> int:
    """Inverse of :func:`emulated_from_sim`."""
    return emu_unix_ns - EMUTIME_SIMULATION_START_UNIX_NS


def from_seconds(s: float) -> int:
    return round(s * SECOND)


def to_seconds(ns: int) -> float:
    return ns / SECOND


def fmt(ns: int) -> str:
    """Human-readable duration, used by the logger (e.g. '00:00:03.000000042')."""
    s, rem = divmod(ns, SECOND)
    h, s = divmod(s, 3600)
    m, s = divmod(s, 60)
    return f"{h:02d}:{m:02d}:{s:02d}.{rem:09d}"
