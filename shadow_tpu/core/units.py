"""Typed config units: durations, byte sizes, and bit rates.

Accepts the same spellings the reference accepts in YAML configs ("1 Gbit",
"10 ms", "16 MiB", "2 seconds"). Parity: reference `src/main/utility/units.rs`
(typed unit parsing with SI and binary prefixes).
"""

from __future__ import annotations

import re

from . import simtime

_NUM = r"(?P<num>[0-9]+(?:\.[0-9]+)?)"
_RE = re.compile(_NUM + r"\s*(?P<unit>[A-Za-zμ]*)$")

_TIME_UNITS = {
    "": simtime.SECOND,  # bare numbers in time positions mean seconds
    "ns": simtime.NANOSECOND,
    "nanosecond": simtime.NANOSECOND,
    "nanoseconds": simtime.NANOSECOND,
    "us": simtime.MICROSECOND,
    "μs": simtime.MICROSECOND,
    "microsecond": simtime.MICROSECOND,
    "microseconds": simtime.MICROSECOND,
    "ms": simtime.MILLISECOND,
    "millisecond": simtime.MILLISECOND,
    "milliseconds": simtime.MILLISECOND,
    "s": simtime.SECOND,
    "sec": simtime.SECOND,
    "secs": simtime.SECOND,
    "second": simtime.SECOND,
    "seconds": simtime.SECOND,
    "m": simtime.MINUTE,
    "min": simtime.MINUTE,
    "mins": simtime.MINUTE,
    "minute": simtime.MINUTE,
    "minutes": simtime.MINUTE,
    "h": simtime.HOUR,
    "hr": simtime.HOUR,
    "hrs": simtime.HOUR,
    "hour": simtime.HOUR,
    "hours": simtime.HOUR,
}

_SI = {
    "": 1,
    "K": 10**3,
    "kilo": 10**3,
    "M": 10**6,
    "mega": 10**6,
    "G": 10**9,
    "giga": 10**9,
    "T": 10**12,
    "tera": 10**12,
}
_BIN = {
    "Ki": 2**10,
    "kibi": 2**10,
    "Mi": 2**20,
    "mebi": 2**20,
    "Gi": 2**30,
    "gibi": 2**30,
    "Ti": 2**40,
    "tebi": 2**40,
}


def _build_scaled(suffixes: tuple[str, ...]) -> dict[str, int]:
    out: dict[str, int] = {}
    for suffix in suffixes:
        for prefix, mult in list(_SI.items()) + list(_BIN.items()):
            out[prefix + suffix] = mult
            out[(prefix + suffix).lower()] = mult
    return out


_BYTE_UNITS = _build_scaled(("B", "byte", "bytes"))
_BYTE_UNITS[""] = 1
_BIT_UNITS = _build_scaled(("bit", "bits", "b"))
_BIT_UNITS[""] = 1  # bare numbers mean bits/sec, like bare bytes/durations


class UnitParseError(ValueError):
    pass


def _split(text: str | int | float) -> tuple[float, str]:
    if isinstance(text, (int, float)):
        return float(text), ""
    m = _RE.match(text.strip())
    if not m:
        raise UnitParseError(f"cannot parse unit value: {text!r}")
    return float(m.group("num")), m.group("unit")


def parse_duration_ns(text: str | int | float) -> int:
    """Parse a duration ('10 ms', '2s', 30) into integer nanoseconds."""
    num, unit = _split(text)
    try:
        scale = _TIME_UNITS[unit]
    except KeyError:
        raise UnitParseError(f"unknown time unit {unit!r} in {text!r}") from None
    return round(num * scale)


def parse_bytes(text: str | int | float) -> int:
    num, unit = _split(text)
    try:
        scale = _BYTE_UNITS[unit]
    except KeyError:
        raise UnitParseError(f"unknown byte unit {unit!r} in {text!r}") from None
    return round(num * scale)


def parse_bits_per_sec(text: str | int | float) -> int:
    """Parse a bandwidth ('1 Gbit', '10 Mbit', '100 Mbps') into bits/second."""
    num, unit = _split(text)
    # "Mbps"-style spellings: strip the per-second suffix, but only when a
    # unit remains — a bare "ps" (e.g. a picosecond duration misplaced in a
    # rate field) must stay an error, not parse as dimensionless bits/sec.
    if unit.endswith("ps") and len(unit) > 2:
        unit = unit[:-2]
    try:
        scale = _BIT_UNITS[unit]
    except KeyError:
        raise UnitParseError(f"unknown rate unit {unit!r} in {text!r}") from None
    return round(num * scale)
