"""Per-thread simulation context and the cross-host packet send path.

Parity: reference `src/main/core/worker.rs` — `Worker` holds the active host,
the clock (current time + round end), and a per-thread min next-event-time;
`WorkerShared` holds global read-mostly state (routing tables, DNS, host
registry, runahead, end times). `Worker.send_packet` (`worker.rs:326-410`) is
the ONLY cross-host communication point: it resolves the destination host,
applies Bernoulli path loss (never for zero-payload control packets,
`worker.rs:364-367`; never while bootstrapping), samples path latency, clamps
the delivery time to at least the round end (what makes round-batched
execution legal), and pushes a packet event into the destination host's
queue.

TPU note: in the TPU network plane this entire function becomes a batched
kernel: dense [N,N] latency/loss lookups + counter-based Bernoulli + a
bucketed all-to-all by destination shard (see `shadow_tpu/tpu/`).
"""

from __future__ import annotations

import threading
from typing import Optional

from ..net.packet import Packet, PacketStatus

# Thread-local "which host is executing on this scheduler thread" — the
# dispatch point for per-host instrumentation (tracker counters, strace),
# mirroring the reference's thread-local Worker (`worker.rs:57`).
_active = threading.local()


def current_host():
    return getattr(_active, "host", None)


def current_cpu():
    """The CPU this scheduler thread is pinned to (None = unpinned)."""
    return getattr(_active, "cpu", None)


def set_current_cpu(cpu) -> None:
    _active.cpu = cpu


class WorkerShared:
    """Global state shared by all workers; read-mostly after setup."""

    def __init__(
        self,
        *,
        dns,
        routing,
        ip_to_host,  # dict: ip str -> Host
        ip_to_node_id,  # dict: ip str -> graph node id
        runahead,
        sim_end_time: int,
        bootstrap_end_time: int = 0,
    ):
        self.dns = dns
        self.routing = routing
        self.ip_to_host = ip_to_host
        self.ip_to_node_id = ip_to_node_id
        self.runahead = runahead
        self.sim_end_time = sim_end_time
        self.bootstrap_end_time = bootstrap_end_time
        self.packet_drop_count = 0
        # injected fault-plane drops, kept APART from packet_drop_count
        # so SimStats never conflates a scheduled outage with wire loss
        # (docs/robustness.md drop taxonomy)
        self.fault_drop_count = 0
        # set by the Manager when experimental.use_tpu_transport is on:
        # cross-host delivery runs through the device plane
        self.device_transport = None
        # set by the Manager when a `faults:` schedule is configured: the
        # compiled FaultSchedule whose overlay send_packet consults
        # (crashed endpoints, link latency multipliers, corruption
        # bursts). None = the fault branch below never runs.
        self.fault_plane = None
        # guards the (non-atomic) numpy counter updates and the drop count
        self._count_lock = threading.Lock()

    def latency_and_reliability(self, src_ip: str, dst_ip: str) -> tuple[int, float]:
        src_node = self.ip_to_node_id[src_ip]
        dst_node = self.ip_to_node_id[dst_ip]
        props = self.routing.path(src_node, dst_node)
        return props.latency_ns, 1.0 - props.packet_loss

    def count_packet(self, src_ip: str, dst_ip: str) -> None:
        with self._count_lock:
            self.routing.increment_packet_count(
                self.ip_to_node_id[src_ip], self.ip_to_node_id[dst_ip]
            )

    def count_drop(self) -> None:
        with self._count_lock:
            self.packet_drop_count += 1

    def count_fault_drop(self) -> None:
        with self._count_lock:
            self.fault_drop_count += 1


class Worker:
    """Per-thread context. One exists per scheduler thread (or one total under
    the serial scheduler)."""

    def __init__(self, shared: WorkerShared, worker_id: int = 0):
        self.shared = shared
        self.worker_id = worker_id
        self.active_host = None
        self.current_time: int = 0
        self.round_end_time: int = 0
        # Min delivery time of packets sent this round — the destination may
        # already have executed and reported its next-event time, so the
        # sender's worker accounts for the new event (`manager.rs:430-436`).
        self.next_event_time: Optional[int] = None
        self.syscall_counts: dict[str, int] = {}

    # -- round lifecycle ----------------------------------------------------

    def start_round(self, round_end_time: int) -> None:
        self.round_end_time = round_end_time
        self.next_event_time = None

    def set_active_host(self, host) -> None:
        self.active_host = host
        _active.host = host
        if host is not None:
            host._worker = self

    def update_next_event_time(self, t: int) -> None:
        if self.next_event_time is None or t < self.next_event_time:
            self.next_event_time = t

    def is_bootstrapping(self) -> bool:
        return self.current_time < self.shared.bootstrap_end_time

    # -- the cross-host send path (`worker.rs:326-410`) ---------------------

    def send_packet(self, src_host, packet: Packet) -> None:
        now = self.current_time
        if now >= self.shared.sim_end_time:
            return  # simulation is over, don't bother

        dst_ip = packet.dst[0]
        dst_host = self.shared.ip_to_host.get(dst_ip)
        if dst_host is None:
            # Unroutable destination: model as a silent drop.
            packet.add_status(PacketStatus.INET_DROPPED)
            self.shared.count_drop()
            return

        latency, reliability = self.shared.latency_and_reliability(
            packet.src[0], dst_ip
        )

        # Fault plane (faults/schedule.py): crashed endpoints drop the
        # packet (FAULT_DROPPED, never the loss counter), degraded links
        # multiply latency, and an active corruption burst may draw an
        # extra Bernoulli from the SOURCE host's stream. The filter runs
        # BEFORE the loss draw so a corruption-free schedule consumes
        # exactly the same RNG stream as a faultless run.
        fp = self.shared.fault_plane
        if fp is not None:
            drop, latency = fp.filter_send(
                src_host, dst_host, packet,
                self.shared.ip_to_node_id[packet.src[0]],
                self.shared.ip_to_node_id[dst_ip], latency)
            if drop:
                packet.add_status(PacketStatus.FAULT_DROPPED)
                self.shared.count_fault_drop()
                return

        # Bernoulli path loss from the *source host's* RNG stream — part of
        # the determinism contract. Control packets (payload 0) are never
        # dropped so congestion control can always see loss signals.
        chance = src_host.rng.random()
        if (
            not self.is_bootstrapping()
            and chance >= reliability
            and packet.payload_size() > 0
        ):
            packet.add_status(PacketStatus.INET_DROPPED)
            self.shared.count_drop()
            return

        self.shared.runahead.update_lowest_used_latency(latency)
        self.shared.count_packet(packet.src[0], dst_ip)
        packet.add_status(PacketStatus.INET_SENT)

        # Delay the packet until at least the next round: the destination may
        # have already executed this round.
        deliver_time = max(now + latency, self.round_end_time)
        self.update_next_event_time(deliver_time)

        src_event_id = src_host.next_packet_event_id()
        transport = self.shared.device_transport
        if transport is not None:
            # device mode: the plane computes the deliver time and routes
            # the packet; everything above (RNG draw, counters, statuses,
            # event-id allocation) already happened identically, so event
            # keys — and therefore event order — match the CPU path
            transport.capture(src_host, dst_host, packet, now, src_event_id,
                              self.round_end_time, deliver_time)
            if not transport.mirrored:
                return
            # mirrored mode: the CPU push below is authoritative (bitwise
            # CPU-transport behavior); the device runs the same window
            # asynchronously and is verified against it a few rounds later
        dst_host.push_packet_event(
            packet, deliver_time, src_host.host_id, src_event_id
        )
