"""The fault plane: deterministic fault injection, checkpoint/restore,
and self-healing execution (docs/robustness.md).

- `schedule`   — the `faults:` config block compiled into a seeded,
  virtual-time event schedule with CPU mask state + device arrays.
- `plane`      — the `FaultArrays` SoA masks `tpu/plane.window_step`
  threads as a static presence switch (faults=None compiles out).
- `checkpoint` — atomic, checksummed checkpoints: bitwise device-plane
  restore, flow-engine bucket resume, Manager diagnostic snapshots.
- `watchdog`   — the round watchdog: hung managed processes become a
  structured `WatchdogError` with per-host blame.
- `healing`    — transient-device-error retry and the Pallas->XLA
  kernel fallback.
"""

from .checkpoint import (CheckpointError, load_checkpoint,  # noqa: F401
                         load_plane_checkpoint, prune_checkpoints,
                         save_plane_checkpoint, write_checkpoint)
from .healing import (KernelFallback, is_transient_device_error,  # noqa: F401
                      retry_transient)
from .plane import FaultArrays, neutral_faults  # noqa: F401
from .schedule import (FaultEvent, FaultSchedule,  # noqa: F401
                       compile_schedule)
from .watchdog import HostBlame, RoundWatchdog, WatchdogError  # noqa: F401

__all__ = [
    "CheckpointError", "FaultArrays", "FaultEvent", "FaultSchedule",
    "HostBlame", "KernelFallback", "RoundWatchdog", "WatchdogError",
    "compile_schedule", "is_transient_device_error", "load_checkpoint",
    "load_plane_checkpoint", "neutral_faults", "prune_checkpoints",
    "retry_transient", "save_plane_checkpoint", "write_checkpoint",
]
