"""The fault plane: deterministic fault injection, checkpoint/restore,
and self-healing execution (docs/robustness.md).

- `schedule`   — the `faults:` config block compiled into a seeded,
  virtual-time event schedule with CPU mask state + device arrays.
- `plane`      — the `FaultArrays` SoA masks `tpu/plane.window_step`
  threads as a static presence switch (faults=None compiles out).
- `checkpoint` — atomic, checksummed checkpoints: bitwise device-plane
  restore, flow-engine bucket resume, Manager diagnostic snapshots,
  and the shared single-file npz format (`write_npz_checkpoint`).
- `runstate`   — the full-run checkpointer: the ENTIRE chained-driver
  carry (every plane + schedule position + memo cache) in one atomic
  file, resumable to a byte-identical final artifact.
- `watchdog`   — the round watchdog: hung managed processes become a
  structured `WatchdogError` with per-host blame.
- `healing`    — transient-device-error retry (deterministic seeded
  backoff) and the Pallas->XLA kernel fallback.
"""

from .checkpoint import (CheckpointError, load_checkpoint,  # noqa: F401
                         load_npz_checkpoint, load_plane_checkpoint,
                         prune_checkpoints, save_plane_checkpoint,
                         write_checkpoint, write_npz_checkpoint)
from .healing import (KernelFallback, backoff_schedule,  # noqa: F401
                      is_transient_device_error, retry_transient)
from .plane import FaultArrays, neutral_faults  # noqa: F401
from .runstate import (RUNSTATE_SCHEMA, RunCheckpointer,  # noqa: F401
                       flatten_carry, latest_checkpoint, load_runstate,
                       restore_carry, resume_carry)
from .schedule import (FaultEvent, FaultSchedule,  # noqa: F401
                       compile_schedule)
from .watchdog import HostBlame, RoundWatchdog, WatchdogError  # noqa: F401

__all__ = [
    "CheckpointError", "FaultArrays", "FaultEvent", "FaultSchedule",
    "HostBlame", "KernelFallback", "RUNSTATE_SCHEMA", "RoundWatchdog",
    "RunCheckpointer", "WatchdogError", "backoff_schedule",
    "compile_schedule", "flatten_carry", "is_transient_device_error",
    "latest_checkpoint", "load_checkpoint", "load_npz_checkpoint",
    "load_plane_checkpoint", "load_runstate", "neutral_faults",
    "prune_checkpoints", "restore_carry", "resume_carry",
    "retry_transient", "save_plane_checkpoint", "write_checkpoint",
    "write_npz_checkpoint",
]
