"""Crash-survivable checkpoints: atomic write-rename + manifest checksums.

Format: a checkpoint is a DIRECTORY ``<name>/`` containing

- ``arrays.npz``    — every numpy/device array leaf, flattened to
  ``<group>.<field>`` keys (device pytrees go through `jax.device_get`
  first; restore re-uploads),
- ``meta.json``     — JSON-serializable metadata (virtual clock, round,
  RNG streams, counters, config digest, ``kind``),
- ``MANIFEST.json`` — sha256 of both payload files plus the format
  version. `load_checkpoint` re-hashes and refuses a mismatch.

Atomicity: everything is written into ``<name>.tmp-<pid>/`` and
`os.replace`d into place, so a checkpoint either exists completely or
not at all — a run killed mid-write leaves only a ``.tmp-*`` turd that
the next `prune_checkpoints` sweep removes. (os.replace of a directory
over an existing one fails on POSIX, so the previous checkpoint of the
same name is rotated away first; the rotation window leaves the older
sibling checkpoints intact, which is why periodic checkpoints are
timestamped names, not one mutating directory.)

Three checkpoint kinds share the format (``meta["kind"]``):

- ``plane``   — a device-plane world (NetPlaneState [+ FaultArrays,
  PlaneMetrics] + rng key + virtual clock): full bitwise restore, used
  by `tools/chaos_smoke.py` and the tests' kill/resume parity matrix.
- ``flow``    — flow-engine bucket progress (core/flowplan.py): the CLI
  ``--resume`` path; completed buckets are never recomputed and the
  merged results are bitwise-identical to an uninterrupted run.
- ``manager`` — a round-loop diagnostic snapshot (RNG streams, clocks,
  tracker counters, stats, telemetry totals, transport counters):
  written periodically and as the EMERGENCY checkpoint on the crash
  path. Not resumable (host event queues hold live closures and
  managed native processes hold kernel state no serializer can see —
  docs/robustness.md spells out the boundary), but it preserves the
  forensic state of exactly the runs that need explaining.

A second, SINGLE-FILE format lives alongside the directory format:
`write_npz_checkpoint` / `load_npz_checkpoint` pack every array plus an
embedded JSON meta record (with a per-ARRAY sha256 map and a schema
stamp) into one ``.npz``, written tmp + fsync + rename so the file
either exists whole or not at all. `faults/runstate.py` (full-run
checkpoints) and `tpu/memo.py` (`ChainMemo.save/load`) both ride this
format. The checksums are corruption detection — truncation, bit rot,
schema drift — not a cryptographic tamper seal.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
from typing import Any, Optional

import numpy as np

log = logging.getLogger("shadow_tpu.faults")

FORMAT_VERSION = 1
MANIFEST = "MANIFEST.json"
_ARRAYS = "arrays.npz"
_META = "meta.json"


class CheckpointError(RuntimeError):
    """Unreadable, corrupt, or mismatched checkpoint."""


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for block in iter(lambda: fh.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed entry survives power loss
    (POSIX only promises the rename is durable once the parent is)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # e.g. platforms refusing O_RDONLY on directories
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_checkpoint(path: str, *, meta: dict,
                     arrays: Optional[dict[str, np.ndarray]] = None) -> dict:
    """Write one checkpoint directory atomically; returns the manifest.

    `meta` must be JSON-serializable; `arrays` values must be numpy
    arrays (callers `jax.device_get` device pytrees first). `path` is
    the final directory name."""
    path = os.path.abspath(path)
    parent = os.path.dirname(path)
    os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        np.savez(os.path.join(tmp, _ARRAYS), **(arrays or {}))
        with open(os.path.join(tmp, _META), "w") as fh:
            json.dump(meta, fh, sort_keys=True, indent=1)
        manifest = {
            "format": FORMAT_VERSION,
            "kind": meta.get("kind", "unknown"),
            "sha256": {
                _ARRAYS: _sha256(os.path.join(tmp, _ARRAYS)),
                _META: _sha256(os.path.join(tmp, _META)),
            },
        }
        with open(os.path.join(tmp, MANIFEST), "w") as fh:
            json.dump(manifest, fh, sort_keys=True, indent=1)
        # fsync the payload so the rename can't land before the bytes
        for name in (_ARRAYS, _META, MANIFEST):
            fd = os.open(os.path.join(tmp, name), os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        if os.path.exists(path):
            # rotate the old same-name checkpoint out of the way so the
            # replace is atomic; it is gone only after the new one lands
            old = f"{path}.old-{os.getpid()}"
            os.replace(path, old)
            os.replace(tmp, path)
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.replace(tmp, path)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    _fsync_dir(parent)
    return manifest


def load_checkpoint(path: str) -> tuple[dict, dict[str, np.ndarray]]:
    """Verify the manifest checksums and return (meta, arrays)."""
    path = os.path.abspath(path)
    mpath = os.path.join(path, MANIFEST)
    if not os.path.isfile(mpath):
        raise CheckpointError(f"{path}: not a checkpoint (no {MANIFEST})")
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
    except (OSError, ValueError) as e:
        raise CheckpointError(f"{path}: unreadable manifest: {e}") from e
    if manifest.get("format") != FORMAT_VERSION:
        raise CheckpointError(
            f"{path}: checkpoint format {manifest.get('format')!r} != "
            f"supported {FORMAT_VERSION}")
    shas = manifest.get("sha256")
    # a manifest that lists no checksum for a payload file verifies
    # nothing about it — a truncated arrays.npz would be half-accepted.
    # Both payload files MUST be covered (the old hole: iterate-what's-
    # listed silently skipped anything missing from the map).
    if not isinstance(shas, dict) or not {_ARRAYS, _META} <= set(shas):
        absent = sorted({_ARRAYS, _META} - set(shas or ()))
        raise CheckpointError(
            f"{path}: manifest lists no checksum for {absent} — refusing "
            f"a checkpoint whose payload cannot be verified")
    for name, want in shas.items():
        fpath = os.path.join(path, name)
        if not os.path.isfile(fpath):
            raise CheckpointError(f"{path}: missing payload file {name}")
        got = _sha256(fpath)
        if got != want:
            raise CheckpointError(
                f"{path}: checksum mismatch on {name} (manifest {want[:12]}"
                f"..., file {got[:12]}...) — the checkpoint is corrupt")
    try:
        with open(os.path.join(path, _META)) as fh:
            meta = json.load(fh)
        with np.load(os.path.join(path, _ARRAYS)) as z:
            arrays = {k: z[k] for k in z.files}
    except CheckpointError:
        raise
    except Exception as e:  # truncated zip, bad JSON, OSError, ...
        raise CheckpointError(
            f"{path}: unreadable payload (truncated or corrupt): {e}") from e
    return meta, arrays


def prune_checkpoints(directory: str, keep: int, prefix: str = "ckpt-") -> None:
    """Keep the newest `keep` periodic checkpoints (by name — names
    embed the zero-padded round number, so lexicographic == temporal)
    and sweep dead ``.tmp-*`` / ``.old-*`` partials."""
    if not os.path.isdir(directory):
        return
    entries = sorted(
        e for e in os.listdir(directory)
        if e.startswith(prefix) and ".tmp-" not in e and ".old-" not in e)
    for e in entries[:-keep] if keep > 0 else entries:
        shutil.rmtree(os.path.join(directory, e), ignore_errors=True)
    for e in os.listdir(directory):
        if ".tmp-" in e or ".old-" in e:
            shutil.rmtree(os.path.join(directory, e), ignore_errors=True)


# ---------------------------------------------------------------------------
# single-file atomic checkpoints: .npz with an embedded, self-verifying
# meta record (the runstate / ChainMemo persistence format)
# ---------------------------------------------------------------------------

NPZ_META_KEY = "__meta__"


def _array_sha256(arr: np.ndarray) -> str:
    """Content hash of one array: dtype + shape + bytes, so a bit flip,
    a silent dtype cast, or a reshape all read as corruption."""
    arr = np.ascontiguousarray(arr)
    h = hashlib.sha256()
    h.update(str(arr.dtype).encode())
    h.update(repr(tuple(arr.shape)).encode())
    h.update(arr.tobytes())
    return h.hexdigest()


def write_npz_checkpoint(path: str, *, schema: str, meta: dict,
                         arrays: dict[str, np.ndarray]) -> dict:
    """Atomically write one self-verifying ``.npz`` checkpoint file.

    The JSON-serializable `meta` is embedded in the archive itself (as
    a uint8 blob under `NPZ_META_KEY`) together with a `schema` stamp,
    the format version, and a per-array sha256 map covering EVERY
    array — so there is exactly one file to rename, and a load can
    refuse truncation/corruption naming the offending field. Write
    order is tmp file -> fsync -> os.replace -> parent-dir fsync; a
    kill at any instant leaves either the old file or the new one,
    never a prefix. Returns the full embedded meta."""
    path = os.path.abspath(path)
    parent = os.path.dirname(path)
    os.makedirs(parent, exist_ok=True)
    clean: dict[str, np.ndarray] = {}
    for name, arr in arrays.items():
        if name == NPZ_META_KEY:
            raise CheckpointError(
                f"array name {name!r} collides with the embedded meta key")
        clean[name] = np.asarray(arr)
    full_meta = dict(meta)
    full_meta["format"] = FORMAT_VERSION
    full_meta["schema"] = schema
    full_meta["sha256"] = {n: _array_sha256(a)
                           for n, a in sorted(clean.items())}
    blob = np.frombuffer(
        json.dumps(full_meta, sort_keys=True).encode(), dtype=np.uint8)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:
            np.savez(fh, **{NPZ_META_KEY: blob}, **clean)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(parent)
    return full_meta


def load_npz_checkpoint(path: str, *,
                        schema: str) -> tuple[dict, dict[str, np.ndarray]]:
    """Load + verify a `write_npz_checkpoint` file; (meta, arrays).

    Refuses — always as `CheckpointError`, always naming what's wrong —
    an unreadable/truncated archive, a missing or undecodable meta
    record, a format/schema mismatch, an array listed in the checksum
    map but absent from the archive, an array present but NOT covered
    by the map, and any per-array checksum mismatch."""
    path = os.path.abspath(path)
    if not os.path.isfile(path):
        raise CheckpointError(f"{path}: no such checkpoint file")
    try:
        with np.load(path) as z:
            payload = {k: z[k] for k in z.files}
    except Exception as e:  # BadZipFile / EOF / OSError / pickle refusal
        raise CheckpointError(
            f"{path}: unreadable checkpoint (truncated or corrupt): "
            f"{e}") from e
    if NPZ_META_KEY not in payload:
        raise CheckpointError(
            f"{path}: missing embedded meta record {NPZ_META_KEY!r} — not "
            f"a runstate-format checkpoint")
    try:
        meta = json.loads(bytes(payload.pop(NPZ_META_KEY)).decode())
    except ValueError as e:
        raise CheckpointError(
            f"{path}: undecodable embedded meta record: {e}") from e
    if meta.get("format") != FORMAT_VERSION:
        raise CheckpointError(
            f"{path}: checkpoint format {meta.get('format')!r} != "
            f"supported {FORMAT_VERSION}")
    if meta.get("schema") != schema:
        raise CheckpointError(
            f"{path}: schema {meta.get('schema')!r} != expected {schema!r} "
            f"— written by an incompatible shadow_tpu version?")
    want = meta.get("sha256")
    if not isinstance(want, dict):
        raise CheckpointError(
            f"{path}: meta carries no per-array sha256 map — refusing a "
            f"checkpoint whose arrays cannot be verified")
    missing = sorted(set(want) - set(payload))
    if missing:
        raise CheckpointError(
            f"{path}: missing array {missing[0]!r} (listed in the checksum "
            f"map but absent from the archive — truncated checkpoint?)")
    extra = sorted(set(payload) - set(want))
    if extra:
        raise CheckpointError(
            f"{path}: array {extra[0]!r} is not covered by the checksum "
            f"map — refusing an unverifiable field")
    for name in sorted(want):
        got = _array_sha256(payload[name])
        if got != want[name]:
            raise CheckpointError(
                f"{path}: checksum mismatch on array {name!r} (meta "
                f"{want[name][:12]}..., file {got[:12]}...) — the "
                f"checkpoint is corrupt")
    return meta, payload


# ---------------------------------------------------------------------------
# device-plane checkpoints (kind="plane"): full bitwise restore
# ---------------------------------------------------------------------------


def _flatten_named(prefix: str, pytree) -> dict[str, np.ndarray]:
    """NamedTuple-of-arrays -> {prefix.field: np.ndarray} (nested
    NamedTuples recurse with dotted names)."""
    out: dict[str, np.ndarray] = {}
    for name in pytree._fields:
        leaf = getattr(pytree, name)
        if hasattr(leaf, "_fields"):
            out.update(_flatten_named(f"{prefix}.{name}", leaf))
        else:
            out[f"{prefix}.{name}"] = np.asarray(leaf)
    return out


def _unflatten_named(prefix: str, template, arrays: dict[str, np.ndarray]):
    """Inverse of `_flatten_named`: rebuild `template`'s type with the
    stored leaves re-uploaded as jnp arrays (dtype preserved)."""
    import jax.numpy as jnp

    kw = {}
    for name in template._fields:
        leaf = getattr(template, name)
        if hasattr(leaf, "_fields"):
            kw[name] = _unflatten_named(f"{prefix}.{name}", leaf, arrays)
        else:
            key = f"{prefix}.{name}"
            if key not in arrays:
                raise CheckpointError(
                    f"checkpoint is missing array leaf {key!r} — written "
                    f"by an incompatible shadow_tpu version?")
            kw[name] = jnp.asarray(arrays[key])
    return type(template)(**kw)


def save_plane_checkpoint(path: str, *, state, clock_ns: int,
                          rng_key_data: np.ndarray,
                          faults=None, metrics=None,
                          extra_arrays: Optional[dict] = None,
                          meta: Optional[dict] = None) -> dict:
    """Checkpoint a device-plane world (`tpu/plane.NetPlaneState` and
    friends) with full bitwise restore. `rng_key_data` is
    `jax.random.key_data(root_key)` (raw uint32 words, reconstructed
    with `jax.random.wrap_key_data`). `extra_arrays` carries any
    driver-private carry (e.g. the PHOLD respawn sequence counters);
    restore returns them under `extra`."""
    import jax

    arrays = _flatten_named("state", jax.device_get(state))
    arrays["rng.key_data"] = np.asarray(rng_key_data)
    if faults is not None:
        arrays.update(_flatten_named("faults", jax.device_get(faults)))
    if metrics is not None:
        arrays.update(_flatten_named("metrics", jax.device_get(metrics)))
    for name, arr in (extra_arrays or {}).items():
        arrays[f"extra.{name}"] = np.asarray(jax.device_get(arr))
    full_meta = {
        "kind": "plane",
        "clock_ns": int(clock_ns),
        "has_faults": faults is not None,
        "has_metrics": metrics is not None,
    }
    if hasattr(state, "eg_dst") and hasattr(state, "in_src"):
        # ring dimensions ride the meta so a resumed elastic run knows
        # the capacity the world had grown to (the arrays carry the
        # shapes anyway; this makes them inspectable without loading)
        full_meta["ring_dims"] = {
            "egress_cap": int(np.asarray(arrays["state.eg_dst"]).shape[1]),
            "ingress_cap": int(np.asarray(arrays["state.in_src"]).shape[1]),
        }
    full_meta.update(meta or {})
    return write_checkpoint(path, meta=full_meta, arrays=arrays)


def load_plane_checkpoint(path: str, *, state_template,
                          faults_template=None, metrics_template=None,
                          grow_to=None):
    """Restore a `plane` checkpoint. Returns a dict with `state`,
    `clock_ns`, `rng_key` (a rebuilt jax PRNG key), and — when stored
    and a template is given — `faults` / `metrics`.

    The restored state keeps the ring shapes it was SAVED with (the
    template only provides pytree structure), so a checkpoint written
    mid-growth restores the grown world bitwise. `grow_to=(egress_cap,
    ingress_cap)` additionally repacks the restored state into larger
    rings via `tpu/elastic.grow_state` — digest-verified state
    equivalence across the resize is pinned by tests/test_elastic.py —
    so a CE=32 checkpoint restores cleanly into a CE=64 world
    (shrinking is refused there, never silent)."""
    import jax

    meta, arrays = load_checkpoint(path)
    if meta.get("kind") != "plane":
        raise CheckpointError(
            f"{path}: kind {meta.get('kind')!r} is not a device-plane "
            f"checkpoint")
    out: dict[str, Any] = {
        "meta": meta,
        "clock_ns": int(meta["clock_ns"]),
        "state": _unflatten_named("state", state_template, arrays),
        "rng_key": jax.random.wrap_key_data(
            jax.numpy.asarray(arrays["rng.key_data"])),
    }
    if grow_to is not None:
        from ..tpu import elastic

        out["state"] = elastic.grow_state(out["state"], *grow_to)
    if meta.get("has_faults") and faults_template is not None:
        out["faults"] = _unflatten_named("faults", faults_template, arrays)
    if meta.get("has_metrics") and metrics_template is not None:
        out["metrics"] = _unflatten_named("metrics", metrics_template,
                                          arrays)
    out["extra"] = {k[len("extra."):]: v for k, v in arrays.items()
                    if k.startswith("extra.")}
    return out


# ---------------------------------------------------------------------------
# manager snapshots (kind="manager"): periodic + emergency diagnostics
# ---------------------------------------------------------------------------


def manager_snapshot(manager, now_ns: int, *, reason: str) -> dict:
    """The serializable core of a round-loop Manager: RNG streams,
    clocks, tracker counters, stats, telemetry totals, and the device
    transport's counter arrays. See the module docstring for why this
    kind is diagnostic, not resumable."""
    meta: dict[str, Any] = {
        "kind": "manager",
        "resumable": False,
        "reason": reason,
        "clock_ns": int(now_ns),
        "rounds": int(manager.stats.rounds),
        "seed": int(manager.config.general.seed),
        "stop_time_ns": int(manager.config.general.stop_time),
        "global_rng_state": [int(s) for s in manager.global_rng.s],
        "hosts": {
            h.name: {
                "now_ns": int(h.now()),
                "rng_state": [int(s) for s in h.rng.s],
                "events_executed": int(h.n_events_executed),
                "fault_down": bool(getattr(h, "fault_down", False)),
                "fault_packets_dropped": int(
                    getattr(h, "fault_packets_dropped", 0)),
            }
            for h in manager.hosts
        },
        "trackers": {name: t.counters.as_dict()
                     for name, t in manager.trackers.items()},
        "stats": manager.stats.as_dict(),
    }
    if manager.harvester is not None:
        meta["telemetry"] = {
            "harvests": manager.harvester.harvests,
            "emitted": manager.harvester.emitted,
        }
    ledger = getattr(manager, "_guard_ledger", None)
    if ledger is not None:
        # the violation ledger rides every snapshot: an emergency
        # checkpoint dropped by an abort guard policy carries the
        # findings that killed the run (docs/robustness.md)
        meta["guards"] = ledger.as_dict()
    arrays: dict[str, np.ndarray] = {}
    transport = getattr(manager, "transport", None)
    if transport is not None:
        import jax

        # the capacity trajectory (ring growths/drops so far) rides
        # every snapshot — an emergency checkpoint of an
        # under-provisioned run says so itself (getattr: tests stand
        # in phantom transports without the policy)
        cap_summary = getattr(transport, "capacity_summary", None)
        if cap_summary is not None:
            meta["capacity"] = cap_summary()
        for name, arr in transport.telemetry_arrays().items():
            arrays[f"transport.{name}"] = np.asarray(jax.device_get(arr))
    return {"meta": meta, "arrays": arrays}


def write_manager_checkpoint(manager, directory: str, now_ns: int, *,
                             reason: str, keep: int = 2) -> Optional[str]:
    """Periodic/emergency Manager snapshot; never raises (a failing
    emergency checkpoint must not mask the crash it documents)."""
    try:
        snap = manager_snapshot(manager, now_ns, reason=reason)
        name = ("emergency" if reason == "emergency"
                else f"ckpt-{manager.stats.rounds:012d}")
        path = os.path.join(directory, name)
        write_checkpoint(path, meta=snap["meta"], arrays=snap["arrays"])
        if reason != "emergency":
            prune_checkpoints(directory, keep)
        log.info("checkpoint: wrote %s snapshot at simtime %d -> %s",
                 reason, now_ns, path)
        return path
    except Exception:
        log.error("checkpoint: failed to write %s snapshot", reason,
                  exc_info=True)
        return None
