"""Crash-survivable checkpoints: atomic write-rename + manifest checksums.

Format: a checkpoint is a DIRECTORY ``<name>/`` containing

- ``arrays.npz``    — every numpy/device array leaf, flattened to
  ``<group>.<field>`` keys (device pytrees go through `jax.device_get`
  first; restore re-uploads),
- ``meta.json``     — JSON-serializable metadata (virtual clock, round,
  RNG streams, counters, config digest, ``kind``),
- ``MANIFEST.json`` — sha256 of both payload files plus the format
  version. `load_checkpoint` re-hashes and refuses a mismatch.

Atomicity: everything is written into ``<name>.tmp-<pid>/`` and
`os.replace`d into place, so a checkpoint either exists completely or
not at all — a run killed mid-write leaves only a ``.tmp-*`` turd that
the next `prune_checkpoints` sweep removes. (os.replace of a directory
over an existing one fails on POSIX, so the previous checkpoint of the
same name is rotated away first; the rotation window leaves the older
sibling checkpoints intact, which is why periodic checkpoints are
timestamped names, not one mutating directory.)

Three checkpoint kinds share the format (``meta["kind"]``):

- ``plane``   — a device-plane world (NetPlaneState [+ FaultArrays,
  PlaneMetrics] + rng key + virtual clock): full bitwise restore, used
  by `tools/chaos_smoke.py` and the tests' kill/resume parity matrix.
- ``flow``    — flow-engine bucket progress (core/flowplan.py): the CLI
  ``--resume`` path; completed buckets are never recomputed and the
  merged results are bitwise-identical to an uninterrupted run.
- ``manager`` — a round-loop diagnostic snapshot (RNG streams, clocks,
  tracker counters, stats, telemetry totals, transport counters):
  written periodically and as the EMERGENCY checkpoint on the crash
  path. Not resumable (host event queues hold live closures and
  managed native processes hold kernel state no serializer can see —
  docs/robustness.md spells out the boundary), but it preserves the
  forensic state of exactly the runs that need explaining.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
from typing import Any, Optional

import numpy as np

log = logging.getLogger("shadow_tpu.faults")

FORMAT_VERSION = 1
MANIFEST = "MANIFEST.json"
_ARRAYS = "arrays.npz"
_META = "meta.json"


class CheckpointError(RuntimeError):
    """Unreadable, corrupt, or mismatched checkpoint."""


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for block in iter(lambda: fh.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def write_checkpoint(path: str, *, meta: dict,
                     arrays: Optional[dict[str, np.ndarray]] = None) -> dict:
    """Write one checkpoint directory atomically; returns the manifest.

    `meta` must be JSON-serializable; `arrays` values must be numpy
    arrays (callers `jax.device_get` device pytrees first). `path` is
    the final directory name."""
    path = os.path.abspath(path)
    parent = os.path.dirname(path)
    os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        np.savez(os.path.join(tmp, _ARRAYS), **(arrays or {}))
        with open(os.path.join(tmp, _META), "w") as fh:
            json.dump(meta, fh, sort_keys=True, indent=1)
        manifest = {
            "format": FORMAT_VERSION,
            "kind": meta.get("kind", "unknown"),
            "sha256": {
                _ARRAYS: _sha256(os.path.join(tmp, _ARRAYS)),
                _META: _sha256(os.path.join(tmp, _META)),
            },
        }
        with open(os.path.join(tmp, MANIFEST), "w") as fh:
            json.dump(manifest, fh, sort_keys=True, indent=1)
        # fsync the payload so the rename can't land before the bytes
        for name in (_ARRAYS, _META, MANIFEST):
            fd = os.open(os.path.join(tmp, name), os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        if os.path.exists(path):
            # rotate the old same-name checkpoint out of the way so the
            # replace is atomic; it is gone only after the new one lands
            old = f"{path}.old-{os.getpid()}"
            os.replace(path, old)
            os.replace(tmp, path)
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.replace(tmp, path)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return manifest


def load_checkpoint(path: str) -> tuple[dict, dict[str, np.ndarray]]:
    """Verify the manifest checksums and return (meta, arrays)."""
    path = os.path.abspath(path)
    mpath = os.path.join(path, MANIFEST)
    if not os.path.isfile(mpath):
        raise CheckpointError(f"{path}: not a checkpoint (no {MANIFEST})")
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
    except (OSError, ValueError) as e:
        raise CheckpointError(f"{path}: unreadable manifest: {e}") from e
    if manifest.get("format") != FORMAT_VERSION:
        raise CheckpointError(
            f"{path}: checkpoint format {manifest.get('format')!r} != "
            f"supported {FORMAT_VERSION}")
    for name, want in manifest.get("sha256", {}).items():
        fpath = os.path.join(path, name)
        if not os.path.isfile(fpath):
            raise CheckpointError(f"{path}: missing payload file {name}")
        got = _sha256(fpath)
        if got != want:
            raise CheckpointError(
                f"{path}: checksum mismatch on {name} (manifest {want[:12]}"
                f"..., file {got[:12]}...) — the checkpoint is corrupt")
    with open(os.path.join(path, _META)) as fh:
        meta = json.load(fh)
    with np.load(os.path.join(path, _ARRAYS)) as z:
        arrays = {k: z[k] for k in z.files}
    return meta, arrays


def prune_checkpoints(directory: str, keep: int, prefix: str = "ckpt-") -> None:
    """Keep the newest `keep` periodic checkpoints (by name — names
    embed the zero-padded round number, so lexicographic == temporal)
    and sweep dead ``.tmp-*`` / ``.old-*`` partials."""
    if not os.path.isdir(directory):
        return
    entries = sorted(
        e for e in os.listdir(directory)
        if e.startswith(prefix) and ".tmp-" not in e and ".old-" not in e)
    for e in entries[:-keep] if keep > 0 else entries:
        shutil.rmtree(os.path.join(directory, e), ignore_errors=True)
    for e in os.listdir(directory):
        if ".tmp-" in e or ".old-" in e:
            shutil.rmtree(os.path.join(directory, e), ignore_errors=True)


# ---------------------------------------------------------------------------
# device-plane checkpoints (kind="plane"): full bitwise restore
# ---------------------------------------------------------------------------


def _flatten_named(prefix: str, pytree) -> dict[str, np.ndarray]:
    """NamedTuple-of-arrays -> {prefix.field: np.ndarray} (nested
    NamedTuples recurse with dotted names)."""
    out: dict[str, np.ndarray] = {}
    for name in pytree._fields:
        leaf = getattr(pytree, name)
        if hasattr(leaf, "_fields"):
            out.update(_flatten_named(f"{prefix}.{name}", leaf))
        else:
            out[f"{prefix}.{name}"] = np.asarray(leaf)
    return out


def _unflatten_named(prefix: str, template, arrays: dict[str, np.ndarray]):
    """Inverse of `_flatten_named`: rebuild `template`'s type with the
    stored leaves re-uploaded as jnp arrays (dtype preserved)."""
    import jax.numpy as jnp

    kw = {}
    for name in template._fields:
        leaf = getattr(template, name)
        if hasattr(leaf, "_fields"):
            kw[name] = _unflatten_named(f"{prefix}.{name}", leaf, arrays)
        else:
            key = f"{prefix}.{name}"
            if key not in arrays:
                raise CheckpointError(
                    f"checkpoint is missing array leaf {key!r} — written "
                    f"by an incompatible shadow_tpu version?")
            kw[name] = jnp.asarray(arrays[key])
    return type(template)(**kw)


def save_plane_checkpoint(path: str, *, state, clock_ns: int,
                          rng_key_data: np.ndarray,
                          faults=None, metrics=None,
                          extra_arrays: Optional[dict] = None,
                          meta: Optional[dict] = None) -> dict:
    """Checkpoint a device-plane world (`tpu/plane.NetPlaneState` and
    friends) with full bitwise restore. `rng_key_data` is
    `jax.random.key_data(root_key)` (raw uint32 words, reconstructed
    with `jax.random.wrap_key_data`). `extra_arrays` carries any
    driver-private carry (e.g. the PHOLD respawn sequence counters);
    restore returns them under `extra`."""
    import jax

    arrays = _flatten_named("state", jax.device_get(state))
    arrays["rng.key_data"] = np.asarray(rng_key_data)
    if faults is not None:
        arrays.update(_flatten_named("faults", jax.device_get(faults)))
    if metrics is not None:
        arrays.update(_flatten_named("metrics", jax.device_get(metrics)))
    for name, arr in (extra_arrays or {}).items():
        arrays[f"extra.{name}"] = np.asarray(jax.device_get(arr))
    full_meta = {
        "kind": "plane",
        "clock_ns": int(clock_ns),
        "has_faults": faults is not None,
        "has_metrics": metrics is not None,
    }
    if hasattr(state, "eg_dst") and hasattr(state, "in_src"):
        # ring dimensions ride the meta so a resumed elastic run knows
        # the capacity the world had grown to (the arrays carry the
        # shapes anyway; this makes them inspectable without loading)
        full_meta["ring_dims"] = {
            "egress_cap": int(np.asarray(arrays["state.eg_dst"]).shape[1]),
            "ingress_cap": int(np.asarray(arrays["state.in_src"]).shape[1]),
        }
    full_meta.update(meta or {})
    return write_checkpoint(path, meta=full_meta, arrays=arrays)


def load_plane_checkpoint(path: str, *, state_template,
                          faults_template=None, metrics_template=None,
                          grow_to=None):
    """Restore a `plane` checkpoint. Returns a dict with `state`,
    `clock_ns`, `rng_key` (a rebuilt jax PRNG key), and — when stored
    and a template is given — `faults` / `metrics`.

    The restored state keeps the ring shapes it was SAVED with (the
    template only provides pytree structure), so a checkpoint written
    mid-growth restores the grown world bitwise. `grow_to=(egress_cap,
    ingress_cap)` additionally repacks the restored state into larger
    rings via `tpu/elastic.grow_state` — digest-verified state
    equivalence across the resize is pinned by tests/test_elastic.py —
    so a CE=32 checkpoint restores cleanly into a CE=64 world
    (shrinking is refused there, never silent)."""
    import jax

    meta, arrays = load_checkpoint(path)
    if meta.get("kind") != "plane":
        raise CheckpointError(
            f"{path}: kind {meta.get('kind')!r} is not a device-plane "
            f"checkpoint")
    out: dict[str, Any] = {
        "meta": meta,
        "clock_ns": int(meta["clock_ns"]),
        "state": _unflatten_named("state", state_template, arrays),
        "rng_key": jax.random.wrap_key_data(
            jax.numpy.asarray(arrays["rng.key_data"])),
    }
    if grow_to is not None:
        from ..tpu import elastic

        out["state"] = elastic.grow_state(out["state"], *grow_to)
    if meta.get("has_faults") and faults_template is not None:
        out["faults"] = _unflatten_named("faults", faults_template, arrays)
    if meta.get("has_metrics") and metrics_template is not None:
        out["metrics"] = _unflatten_named("metrics", metrics_template,
                                          arrays)
    out["extra"] = {k[len("extra."):]: v for k, v in arrays.items()
                    if k.startswith("extra.")}
    return out


# ---------------------------------------------------------------------------
# manager snapshots (kind="manager"): periodic + emergency diagnostics
# ---------------------------------------------------------------------------


def manager_snapshot(manager, now_ns: int, *, reason: str) -> dict:
    """The serializable core of a round-loop Manager: RNG streams,
    clocks, tracker counters, stats, telemetry totals, and the device
    transport's counter arrays. See the module docstring for why this
    kind is diagnostic, not resumable."""
    meta: dict[str, Any] = {
        "kind": "manager",
        "resumable": False,
        "reason": reason,
        "clock_ns": int(now_ns),
        "rounds": int(manager.stats.rounds),
        "seed": int(manager.config.general.seed),
        "stop_time_ns": int(manager.config.general.stop_time),
        "global_rng_state": [int(s) for s in manager.global_rng.s],
        "hosts": {
            h.name: {
                "now_ns": int(h.now()),
                "rng_state": [int(s) for s in h.rng.s],
                "events_executed": int(h.n_events_executed),
                "fault_down": bool(getattr(h, "fault_down", False)),
                "fault_packets_dropped": int(
                    getattr(h, "fault_packets_dropped", 0)),
            }
            for h in manager.hosts
        },
        "trackers": {name: t.counters.as_dict()
                     for name, t in manager.trackers.items()},
        "stats": manager.stats.as_dict(),
    }
    if manager.harvester is not None:
        meta["telemetry"] = {
            "harvests": manager.harvester.harvests,
            "emitted": manager.harvester.emitted,
        }
    ledger = getattr(manager, "_guard_ledger", None)
    if ledger is not None:
        # the violation ledger rides every snapshot: an emergency
        # checkpoint dropped by an abort guard policy carries the
        # findings that killed the run (docs/robustness.md)
        meta["guards"] = ledger.as_dict()
    arrays: dict[str, np.ndarray] = {}
    transport = getattr(manager, "transport", None)
    if transport is not None:
        import jax

        # the capacity trajectory (ring growths/drops so far) rides
        # every snapshot — an emergency checkpoint of an
        # under-provisioned run says so itself (getattr: tests stand
        # in phantom transports without the policy)
        cap_summary = getattr(transport, "capacity_summary", None)
        if cap_summary is not None:
            meta["capacity"] = cap_summary()
        for name, arr in transport.telemetry_arrays().items():
            arrays[f"transport.{name}"] = np.asarray(jax.device_get(arr))
    return {"meta": meta, "arrays": arrays}


def write_manager_checkpoint(manager, directory: str, now_ns: int, *,
                             reason: str, keep: int = 2) -> Optional[str]:
    """Periodic/emergency Manager snapshot; never raises (a failing
    emergency checkpoint must not mask the crash it documents)."""
    try:
        snap = manager_snapshot(manager, now_ns, reason=reason)
        name = ("emergency" if reason == "emergency"
                else f"ckpt-{manager.stats.rounds:012d}")
        path = os.path.join(directory, name)
        write_checkpoint(path, meta=snap["meta"], arrays=snap["arrays"])
        if reason != "emergency":
            prune_checkpoints(directory, keep)
        log.info("checkpoint: wrote %s snapshot at simtime %d -> %s",
                 reason, now_ns, path)
        return path
    except Exception:
        log.error("checkpoint: failed to write %s snapshot", reason,
                  exc_info=True)
        return None
