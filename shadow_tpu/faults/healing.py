"""Self-healing execution: transient-error retry and kernel fallback.

Two recovery mechanisms shared by the Manager's device dispatches and
the bench/profiler drivers (docs/robustness.md):

- `retry_transient` — retry-with-backoff around a device dispatch.
  Only errors that LOOK transient (resource exhaustion, transport
  hiccups on a tunneled accelerator link) are retried; anything else —
  and exhaustion of the retry budget — re-raises so a real bug still
  fails the run. The backoff sleeps WALL time, which can only change
  performance, never results — but the *schedule* of sleeps is itself
  deterministic: `backoff_schedule` derives the exact delay sequence
  (exponential from the base, capped, seeded jitter) as a pure
  function of (attempts, base, cap, jitter, seed, what), so two runs
  of the same config retry on the same wall cadence and a postmortem
  can reproduce the timing it is reading about.
- `KernelFallback` — the Pallas->XLA degradation path. A Pallas plane
  kernel that fails to lower/compile/execute on this backend demotes
  the run to the bitwise-identical XLA path, ONCE, loudly; the run
  completes instead of dying, and the fallback is recorded so CI and
  operators see it.
"""

from __future__ import annotations

import hashlib
import logging
import time as _walltime
from typing import Callable, Optional, Tuple

log = logging.getLogger("shadow_tpu.faults")

#: substrings that mark a device error as plausibly transient
#: (XlaRuntimeError messages carry the grpc/absl status name)
_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED", "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED",
    "connection reset", "Broken pipe", "temporarily unavailable",
)


def is_transient_device_error(exc: BaseException) -> bool:
    """Heuristic classifier for retryable device/runtime errors. Python
    errors (TypeError, ValueError, tracer leaks) are NEVER transient."""
    if isinstance(exc, (TypeError, ValueError, KeyError, AssertionError)):
        return False
    text = f"{type(exc).__name__}: {exc}"
    return any(marker in text for marker in _TRANSIENT_MARKERS)


def backoff_schedule(attempts: int, *, base_s: float = 0.05,
                     cap_s: float = 2.0, jitter: float = 0.5,
                     seed: int = 0,
                     what: str = "device dispatch") -> Tuple[float, ...]:
    """The deterministic retry-delay sequence: delay k starts at
    `min(cap_s, base_s * 2**k)` and seeded jitter shaves up to a
    `jitter` fraction off it (de-synchronizing a fleet of workers all
    retrying the same stalled link, without ever sleeping LONGER than
    the unjittered exponential). Pure function of its arguments — the
    k-th jitter draw is sha256(seed, what, k) mapped to [0, 1), no
    PRNG object and no global stream, so two runs of the same config
    sleep the same schedule and the seed-pinned tests assert the
    exact floats."""
    if attempts < 0:
        raise ValueError(f"attempts must be >= 0, got {attempts}")
    if not 0.0 <= jitter <= 1.0:
        raise ValueError(f"jitter must be in [0, 1], got {jitter}")
    out = []
    for k in range(attempts):
        digest = hashlib.sha256(f"{seed}|{what}|{k}".encode()).digest()
        u = int.from_bytes(digest[:8], "big") / 2.0 ** 64
        delay = min(cap_s, base_s * (2.0 ** k))
        out.append(delay * (1.0 - jitter * u))
    return tuple(out)


def retry_transient(fn: Callable, *args, attempts: int = 3,
                    backoff_s: float = 0.05, cap_s: float = 2.0,
                    jitter: float = 0.5, seed: int = 0,
                    classify=is_transient_device_error,
                    what: str = "device dispatch", **kwargs):
    """Call `fn`; on a transient error retry up to `attempts` more
    times, sleeping the `backoff_schedule` delay sequence (exponential
    from `backoff_s`, capped at `cap_s`, seeded jitter). Non-transient
    errors and budget exhaustion re-raise the ORIGINAL error."""
    delays = backoff_schedule(attempts, base_s=backoff_s, cap_s=cap_s,
                              jitter=jitter, seed=seed, what=what)
    for attempt in range(attempts + 1):
        try:
            return fn(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001 — classified + re-raised
            if attempt >= attempts or not classify(e):
                raise
            delay = delays[attempt]
            log.warning(
                "transient error in %s (attempt %d/%d, retrying in "
                "%.2fs): %s", what, attempt + 1, attempts, delay, e)
            _walltime.sleep(delay)


class KernelFallback:
    """Sticky Pallas->XLA demotion for the plane-kernel drivers.

    `build(kernel)` must return a ready-to-call driver for that kernel
    name; the builder is invoked lazily so the XLA twin is only
    compiled if the fallback actually fires. After a fallback,
    `self.kernel` is "xla" and `self.fell_back` records the demotion
    (surfaced in bench JSON / chaos-smoke output)."""

    def __init__(self, kernel: str, build: Callable[[str], Callable],
                 enabled: bool = True):
        if kernel not in ("xla", "pallas", "pallas_fused"):
            raise ValueError(f"unknown plane kernel {kernel!r}")
        self.kernel = kernel
        self.fell_back = False
        self.failure: Optional[str] = None
        self._build = build
        self._enabled = enabled
        self._driver: Optional[Callable] = None

    def __call__(self, *args, **kwargs):
        if self._driver is None:
            self._driver = self._build(self.kernel)
        try:
            return self._driver(*args, **kwargs)
        except Exception as e:
            if self.kernel == "xla" or not self._enabled:
                raise
            # LOUD: a silent demotion would let a broken Pallas kernel
            # masquerade as a healthy run at XLA speed
            log.error(
                "pallas plane kernel failed (%s: %s) — falling back to "
                "the bitwise-identical XLA path; the run continues but "
                "the fused kernel is NOT being exercised",
                type(e).__name__, e)
            self.failure = f"{type(e).__name__}: {e}"
            self.kernel = "xla"
            self.fell_back = True
            self._driver = self._build("xla")
            try:
                return self._driver(*args, **kwargs)
            except Exception as e2:
                # trace/compile-time failures (the common pallas case)
                # leave the arguments intact and the retry succeeds; an
                # EXECUTION-time failure after a donating dispatch may
                # have consumed the donated input buffers, in which case
                # the re-run dies on deleted buffers — surface the
                # ORIGINAL kernel failure with that context instead of
                # the confusing secondary error
                raise RuntimeError(
                    f"pallas plane kernel failed ({self.failure}) and "
                    f"the XLA fallback could not re-run with the same "
                    f"arguments (donated inputs are consumed at "
                    f"dispatch): {type(e2).__name__}: {e2}") from e
