"""Device-side fault masks for the TPU network plane (`FaultArrays`).

The fault plane makes failure a *simulated input*: host crashes, NIC
link flaps, per-link degradation, and burst packet corruption are
compiled from the seeded `faults:` schedule (`faults/schedule.py`) into
SoA mask arrays that ride into `tpu/plane.window_step` as ordinary
kernel arguments, under the same discipline as the telemetry switch
(`telemetry/metrics.py`):

1. **Static presence switch.** `window_step(..., faults=None)` compiles
   every fault branch out — the jaxpr is identical to the pre-fault
   plane and the results are bitwise-identical (pinned by the parity
   matrix in tests/test_faults.py).
2. **Neutral masks are identity.** `neutral_faults(...)` (everyone
   alive, multiplier 1, corruption 0) produces bitwise-identical
   simulation state to `faults=None` for any in-budget world — the
   masks gate with `where`/`&` on values the step already materialized.
3. **Dtype discipline.** bool / int32 / float32 like everything else on
   device (tpu/plane.py header); latency multipliers are integers and
   the degraded latency is clamped to the int32 window budget before
   the multiply so the arithmetic can never wrap.
4. **Independent corruption stream.** Burst corruption draws use the
   same counter-based threefry as path loss but with the host index
   offset by N, so the loss stream is untouched: a schedule with
   corruption never perturbs which packets the base world loss-drops.

Fault *semantics* on device (documented in docs/robustness.md):

- a host with `host_alive=False` or `link_up=False` neither transmits
  (its queued egress drops, counted per source host) nor accepts new
  routing (packets sent toward it drop at routing time, counted per
  destination host). Packets already in its ingress ring still deliver
  — the crash withdraws the route, it does not reach into the wire.
- `lat_mult[src_node, dst_node]` multiplies path latency (int >= 1).
- `bw_div[host]` divides the egress token-bucket refill rate (>= 1).
- `corrupt_p[host]` adds an independent Bernoulli corruption drop on
  that host's egress (control packets exempt, like path loss).

This module is dependency-light (jax/numpy only): `tpu/plane.py`
imports it, never the other way around.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class FaultArrays(NamedTuple):
    """The compiled fault masks for one scheduling window. Leaves are
    plain kernel arguments (not static), so advancing the schedule
    between rounds never recompiles."""

    host_alive: jax.Array  # [N] bool — host not crashed
    link_up: jax.Array  # [N] bool — NIC administratively up
    lat_mult: jax.Array  # [M, M] int32 >= 1 — per-link latency multiplier
    bw_div: jax.Array  # [N] int32 >= 1 — egress bandwidth divisor
    corrupt_p: jax.Array  # [N] float32 — burst corruption probability


def neutral_faults(n_hosts: int, n_nodes: int | None = None) -> FaultArrays:
    """An all-healthy mask set: bitwise-identity against faults=None."""
    m = n_nodes if n_nodes is not None else n_hosts
    return FaultArrays(
        host_alive=jnp.ones((n_hosts,), bool),
        link_up=jnp.ones((n_hosts,), bool),
        lat_mult=jnp.ones((m, m), jnp.int32),
        bw_div=jnp.ones((n_hosts,), jnp.int32),
        corrupt_p=jnp.zeros((n_hosts,), jnp.float32),
    )


def faults_from_numpy(host_alive: np.ndarray, link_up: np.ndarray,
                      lat_mult: np.ndarray, bw_div: np.ndarray,
                      corrupt_p: np.ndarray) -> FaultArrays:
    """Upload a schedule's current numpy mask state (the
    `FaultSchedule.device_arrays` bridge).

    Each array is COPIED before the upload: on the CPU backend
    `jnp.asarray` may zero-copy alias the numpy buffer, and the
    schedule mutates its mask arrays in place on the next `advance()` —
    an aliased buffer would let a later event leak into a window whose
    dispatch hadn't drained yet (observed as cross-process
    nondeterminism; pinned by tests/test_faults.py determinism runs)."""
    def up(arr, dtype):
        return jnp.asarray(np.array(arr, dtype=dtype, copy=True))

    return FaultArrays(
        host_alive=up(host_alive, bool),
        link_up=up(link_up, bool),
        lat_mult=up(lat_mult, np.int32),
        bw_div=up(bw_div, np.int32),
        corrupt_p=up(corrupt_p, np.float32),
    )
