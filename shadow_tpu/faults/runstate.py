"""Full-run checkpoint/resume for the chained drivers (`RunCheckpointer`).

The crash-survivability tentpole: at a chain boundary — the ONE place
the host already regains control (SL603's sanctioned sync cadence) —
the ENTIRE driver carry is spilled to a single atomic self-verifying
``.npz`` (`faults/checkpoint.write_npz_checkpoint`: tmp + fsync +
rename, per-array sha256, versioned schema). "Entire" means entire:

- the net-plane state AND every extras plane riding the carry
  (workload state, metrics, guards, histograms, flight recorder,
  FlowState) — flattened by structural path, with disabled presence
  planes recorded as explicit ``none_paths`` so a resume under
  different switches is REFUSED, never silently wrong;
- the RNG root key words (`jax.random.key_data`) when the caller
  threads one, and the virtual-clock offset (``round`` ×
  ``window_ns``);
- the elastic growth history (`RingPolicy.to_meta` — the capacity
  trajectory rides the meta, and the grown array shapes ride the
  arrays themselves: `restore_carry` takes structure from the
  template but SHAPES from the file);
- the fault-schedule position (its monotone event-cursor time);
- the spilled `ChainMemo` cache (`ChainMemo.spill` under a ``memo.``
  prefix — the cache survives the crash with the run, retiring the
  old ``--memo`` × checkpoint incompatibility).

The contract is the same theorem every plane obeys (docs/
determinism.md): a run SIGKILLed at any chain boundary and resumed
from the latest checkpoint produces a final artifact byte-identical
to the uninterrupted run — including under faults, flows, memo, and
elastic growth. The two load-bearing facts are (a) `chain_spans`'
ABSOLUTE cut alignment (a resume partitions the remaining rounds
exactly as the uninterrupted run did) and (b) chain length being
bitwise-invisible to the state stream, so the extra cut a checkpoint
boundary introduces changes nothing.

Corruption is refused, never half-accepted: truncation, bit flips,
schema drift, a missing carry leaf, or a presence-switch mismatch
each raise a structured `CheckpointError` naming the offending field
(pinned by tests/test_runstate.py).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import numpy as np

from .checkpoint import (CheckpointError, load_npz_checkpoint,
                         write_npz_checkpoint)

__all__ = [
    "RUNSTATE_SCHEMA", "RunCheckpointer", "flatten_carry",
    "latest_checkpoint", "load_runstate", "restore_carry",
    "resume_carry",
]

#: schema stamp for full-run checkpoints (`load_npz_checkpoint`
#: refuses a mismatch before any field is trusted)
RUNSTATE_SCHEMA = "runstate-v1"

_SUFFIX = ".runstate.npz"


def _is_namedtuple(node) -> bool:
    return isinstance(node, tuple) and hasattr(node, "_fields")


def _is_prng_key(node) -> bool:
    """Typed PRNG-key leaf? (They refuse `np.asarray`; their raw words
    spill via `jax.random.key_data` and re-wrap on restore.)"""
    dt = getattr(node, "dtype", None)
    if dt is None:
        return False
    import jax

    return jax.dtypes.issubdtype(dt, jax.dtypes.prng_key)


def flatten_carry(carry, prefix: str = "carry"):
    """Flatten a driver carry into path-named host arrays.

    Returns ``(arrays, none_paths)``: every array leaf under its
    structural path (``carry.0.eg_dst``, ``carry.1.3.hist_qdepth``,
    ...) and the sorted paths of every ``None`` subtree (disabled
    presence planes) — recorded explicitly so `restore_carry` can
    refuse a presence-switch drift by name instead of mis-pairing
    leaves."""
    arrays: dict[str, np.ndarray] = {}
    nones: list[str] = []

    def rec(node, path: str):
        if node is None:
            nones.append(path)
            return
        if _is_namedtuple(node):
            for fname, val in zip(node._fields, node):
                rec(val, f"{path}.{fname}")
            return
        if isinstance(node, (tuple, list)):
            for i, val in enumerate(node):
                rec(val, f"{path}.{i}")
            return
        if isinstance(node, dict):
            for k in sorted(node):
                rec(node[k], f"{path}.{k}")
            return
        if _is_prng_key(node):
            import jax

            arrays[path] = np.asarray(jax.random.key_data(node))
            return
        arrays[path] = np.asarray(node)

    rec(carry, prefix)
    return arrays, sorted(nones)


def restore_carry(template, arrays, *, none_paths=(),
                  prefix: str = "carry", source: str = "<checkpoint>"):
    """Inverse of `flatten_carry`: rebuild the template's STRUCTURE
    with the checkpoint's leaves re-uploaded as device arrays.

    The template contributes only pytree structure and leaf types —
    shapes come from the file, so a checkpoint written mid-elastic-
    growth restores the grown world bitwise into a template built at
    seed capacity. Refusals (all `CheckpointError`, all naming the
    path): a leaf the template expects but the file lacks; a plane
    this run disabled that the checkpoint recorded live; a plane this
    run enabled that the checkpoint recorded as ``None``."""
    import jax.numpy as jnp

    none_set = set(none_paths)

    def rec(node, path: str):
        if node is None:
            if path in none_set:
                return None
            below = [k for k in arrays
                     if k == path or k.startswith(path + ".")]
            if below:
                raise CheckpointError(
                    f"{source}: presence mismatch at {path!r} — this run "
                    f"has the plane disabled (None) but the checkpoint "
                    f"recorded {below[0]!r}; resume with the same "
                    f"switches as the checkpointing run")
            return None
        if _is_namedtuple(node):
            return type(node)(*(rec(v, f"{path}.{f}")
                                for f, v in zip(node._fields, node)))
        if isinstance(node, tuple):
            return tuple(rec(v, f"{path}.{i}")
                         for i, v in enumerate(node))
        if isinstance(node, list):
            return [rec(v, f"{path}.{i}") for i, v in enumerate(node)]
        if isinstance(node, dict):
            return {k: rec(node[k], f"{path}.{k}") for k in sorted(node)}
        if path in none_set:
            raise CheckpointError(
                f"{source}: presence mismatch at {path!r} — the "
                f"checkpoint recorded this plane disabled (None) but "
                f"this run has it enabled; resume with the same "
                f"switches as the checkpointing run")
        if path not in arrays:
            raise CheckpointError(
                f"{source}: checkpoint is missing carry leaf {path!r} — "
                f"written by an incompatible configuration?")
        if _is_prng_key(node):
            # typed keys spilled as raw key words; the template leaf
            # supplies the impl to wrap them back under
            import jax

            return jax.random.wrap_key_data(
                jnp.asarray(arrays[path]),
                impl=jax.random.key_impl(node))
        return jnp.asarray(arrays[path])

    return rec(template, prefix)


class RunCheckpointer:
    """Periodic full-run checkpoints at chain boundaries.

    Construct one per run and hand it to
    ``drive_chained_windows(checkpointer=)`` or
    ``drive_ensemble(checkpointer=)`` (the ensemble's per-world
    batched carries land in ONE file — the leading world axis is just
    another array dimension). The driver merges `cut_rounds` into its
    boundary set (so checkpoint instants are chain cuts even when
    ``every`` is not a multiple of ``chain_len`` — bitwise-invisible
    by the chain-length theorem) and calls `save` at every due
    boundary.

    ``schedule`` / ``policy`` / ``memo`` are the host-side companions
    whose state must survive with the carry: the fault schedule's
    position, the `RingPolicy` growth trajectory, and the `ChainMemo`
    cache. ``extra_meta`` rides every checkpoint verbatim (scenario
    fingerprints, knob digests — whatever the resume path wants to
    cross-check)."""

    def __init__(self, directory: str, *, every: int,
                 label: str = "run", keep: int = 2,
                 window_ns: int = 0, rng_key_data=None,
                 schedule=None, policy=None, memo=None,
                 extra_meta: Optional[dict] = None,
                 kill_after: Optional[int] = None):
        if every < 1:
            raise ValueError(f"checkpoint every must be >= 1, got {every}")
        if keep < 1:
            raise ValueError(f"checkpoint keep must be >= 1, got {keep}")
        self.directory = os.path.abspath(directory)
        self.every = int(every)
        self.label = str(label)
        self.keep = int(keep)
        self.window_ns = int(window_ns)
        self.rng_key_data = rng_key_data
        self.schedule = schedule
        self.policy = policy
        self.memo = memo
        self.extra_meta = dict(extra_meta or {})
        # CI/test crash point: die with SIGKILL's exit code the
        # instant the checkpoint for this round is durable — the
        # kill/resume parity gate's deterministic "preemption"
        self.kill_after = kill_after
        self.saved = 0
        self.last_path: Optional[str] = None

    # -- driver protocol --------------------------------------------------

    def cut_rounds(self, n_rounds: int) -> tuple:
        """The checkpoint instants as explicit chain boundaries."""
        return tuple(range(self.every, n_rounds, self.every))

    def due(self, r1: int, n_rounds: int) -> bool:
        """Checkpoint after the span ending at ``r1``? (The final
        boundary is skipped — the run is already finishing.)"""
        return r1 % self.every == 0 and r1 < n_rounds

    def path_for(self, r1: int) -> str:
        return os.path.join(self.directory,
                            f"{self.label}-r{r1:08d}{_SUFFIX}")

    def save(self, r1: int, carry, *, host: bool = False,
             tracer=None) -> dict:
        """Write the checkpoint for the boundary at round ``r1``.

        ``carry`` is the driver's ``(state, extras)`` — device arrays
        by default, or an already-host memo mirror with ``host=True``
        (the fast-forward path checkpoints with NO device round-trip
        at all). One `jax.device_get` per checkpoint otherwise — the
        same sanctioned boundary sync as the memo snapshot and the
        telemetry harvest."""
        if not host:
            import jax

            carry = jax.device_get(carry)
        arrays, none_paths = flatten_carry(carry)
        meta: dict[str, Any] = {
            "kind": "runstate",
            "label": self.label,
            "round": int(r1),
            "window_ns": self.window_ns,
            "time_ns": int(r1) * self.window_ns,
            "none_paths": none_paths,
        }
        meta.update(self.extra_meta)
        if self.rng_key_data is not None:
            arrays["rng.key_data"] = np.asarray(self.rng_key_data)
        if self.schedule is not None:
            # the schedule's position is its monotone advance time:
            # the cursor is a pure function of it, so resume replays
            # one advance() to land on the identical cursor
            meta["schedule"] = {
                "now_ns": int(r1) * self.window_ns,
                "fingerprint": self.schedule.fingerprint(),
            }
        if self.policy is not None:
            meta["capacity"] = self.policy.to_meta()
        if self.memo is not None:
            m_meta, m_arrays = self.memo.spill(prefix="memo.")
            meta["memo"] = m_meta
            arrays.update(m_arrays)
        path = self.path_for(r1)
        write_npz_checkpoint(path, schema=RUNSTATE_SCHEMA, meta=meta,
                             arrays=arrays)
        self.saved += 1
        self.last_path = path
        self._prune()
        ckpt_id = os.path.basename(path)[:-len(_SUFFIX)]
        if tracer is not None:
            tracer.annotate("checkpoint", id=ckpt_id, r=int(r1),
                            path=path)
        if self.kill_after is not None and int(r1) == int(self.kill_after):
            if tracer is not None:
                tracer.annotate("kill", r=int(r1), id=ckpt_id)
            os._exit(137)  # the SIGKILL exit code chaos_smoke uses
        return {"path": path, "id": ckpt_id, "round": int(r1)}

    def _prune(self) -> None:
        files = sorted(
            e for e in os.listdir(self.directory)
            if e.startswith(f"{self.label}-r") and e.endswith(_SUFFIX))
        for e in files[:-self.keep]:
            try:
                os.unlink(os.path.join(self.directory, e))
            except OSError:
                pass
        for e in os.listdir(self.directory):
            if ".tmp-" in e:
                try:
                    os.unlink(os.path.join(self.directory, e))
                except OSError:
                    pass


# ---------------------------------------------------------------------------
# resume side
# ---------------------------------------------------------------------------


def latest_checkpoint(directory: str, label: str = "run") -> Optional[str]:
    """Newest runstate checkpoint for ``label`` (names embed the
    zero-padded round, so lexicographic == temporal); None when the
    directory holds none."""
    if not os.path.isdir(directory):
        return None
    files = sorted(
        e for e in os.listdir(directory)
        if e.startswith(f"{label}-r") and e.endswith(_SUFFIX))
    return os.path.join(directory, files[-1]) if files else None


def load_runstate(path: str) -> tuple[dict, dict[str, np.ndarray]]:
    """Load + verify one runstate checkpoint; ``(meta, arrays)``.
    Every refusal (truncation, tamper, schema drift, uncovered field)
    is a `CheckpointError` naming what is wrong — see
    `faults/checkpoint.load_npz_checkpoint`."""
    meta, arrays = load_npz_checkpoint(path, schema=RUNSTATE_SCHEMA)
    if meta.get("kind") != "runstate":
        raise CheckpointError(
            f"{path}: kind {meta.get('kind')!r} is not a full-run "
            f"checkpoint")
    return meta, arrays


def resume_carry(path: str, template_carry, *, schedule=None,
                 policy=None, memo=None) -> dict:
    """One-call resume: load, verify, rebuild the carry, and restore
    the host-side companions.

    Returns ``{"round", "carry", "meta", "rng_key_data",
    "memo_loaded"}``. ``template_carry`` is the freshly built
    ``(state, extras)`` of a cold run of the SAME configuration —
    structure from it, bytes and shapes from the file. When given,
    ``schedule`` is advanced to the recorded position, ``policy``
    re-absorbs the growth trajectory, and ``memo`` re-admits the
    spilled cache (salt-checked; `ChainMemo.absorb` refuses a
    mismatched world)."""
    meta, arrays = load_runstate(path)
    carry = restore_carry(template_carry, arrays,
                          none_paths=meta.get("none_paths", ()),
                          source=path)
    out: dict[str, Any] = {
        "round": int(meta["round"]),
        "carry": carry,
        "meta": meta,
        "rng_key_data": arrays.get("rng.key_data"),
        "memo_loaded": 0,
    }
    if schedule is not None and "schedule" in meta:
        want = meta["schedule"].get("fingerprint")
        if want is not None and want != schedule.fingerprint():
            raise CheckpointError(
                f"{path}: fault-schedule fingerprint mismatch (checkpoint "
                f"{str(want)[:12]}..., this run "
                f"{schedule.fingerprint()[:12]}...) — resume with the "
                f"schedule the checkpointing run used")
        schedule.advance(int(meta["schedule"]["now_ns"]))
    if policy is not None and "capacity" in meta:
        policy.restore_meta(meta["capacity"])
    if memo is not None and "memo" in meta:
        # restore=True: this is a RESUME, not a cross-run cache
        # import — per-entry hits, persisted flags, and every counter
        # come back verbatim, so the resumed run's memo report is
        # byte-identical to the uninterrupted twin's
        out["memo_loaded"] = memo.absorb(meta["memo"], arrays,
                                         prefix="memo.", source=path,
                                         restore=True)
    return out
