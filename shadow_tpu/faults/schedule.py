"""Compile the `faults:` config block into a deterministic schedule.

Failure is a *simulated input* here, with the same contract as every
other input (PAPER.md: same seed -> same results): the `faults:` block
— explicit events plus optional seeded random generators — compiles
into one sorted, virtual-time event list. The schedule is a pure
function of (config, seed); two compiles are identical, and replaying a
run with the same seed replays the same crashes at the same virtual
instants. The compiled schedule drives BOTH planes:

- the CPU plane: the Manager fires due events at round boundaries
  (feeding each event time into the window computation, so a boundary
  lands exactly on the fault instant) — SIGKILLing managed processes,
  purging crashed hosts' queues, flipping NIC link state, and updating
  the link/corruption overlay `Worker.send_packet` consults;
- the device plane: `device_arrays()` exports the current mask state as
  a `faults/plane.FaultArrays` pytree for `window_step(..., faults=)`.

Event kinds (times are virtual; `duration`/`until` auto-generate the
paired recovery event):

- ``host_crash`` / ``host_reboot`` — ``{at, kind, host}``: SIGKILL +
  queue purge at the crash instant; reboot restores connectivity and
  (by default) respawns the host's configured processes.
- ``iface_down`` / ``iface_up`` — ``{at, kind, host}``: administrative
  NIC link flap; inbound packets drop at the interface, outbound never
  leave.
- ``link_degrade`` / ``link_restore`` — ``{at, kind, src_node,
  dst_node, latency_mult[, symmetric=true][, duration|until]}``:
  per-link latency multiplier (integer >= 1).
- ``host_degrade`` / ``host_restore`` — ``{at, kind, host,
  bandwidth_div[, duration|until]}``: divide the host's egress
  bandwidth.
- ``corrupt_burst`` — ``{at, kind, host, p, duration}``: burst packet
  corruption; the host's outbound data packets drop with probability
  ``p`` for ``duration`` (control packets exempt, like path loss).
  Corrupted packets land in the ``fault`` drop bucket, never in the
  loss-sample counter.

Seeded random generators (``random:``) expand into the same kinds:

- ``host_crashes: {count, window: [start, end], downtime}``
- ``iface_flaps: {count, window: [start, end], downtime}``

draws come from a dedicated Xoshiro256++ stream seeded from
``general.seed`` (or ``faults.seed``, which overrides it, letting a
fault scenario vary independently of the workload seed) mixed with a
fault-plane domain separator — the fault draws never perturb the
simulation's own RNG streams.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from ..core import units
from ..core.config import ConfigError
from ..core.rng import Xoshiro256pp

#: domain separator for the fault-schedule RNG stream (never shared with
#: the global/host streams, which hash hostnames instead)
_FAULT_SEED_SALT = 0xFA17_0000_0000_0001

HOST_KINDS = frozenset({
    "host_crash", "host_reboot", "iface_down", "iface_up",
    "host_degrade", "host_restore", "corrupt_burst",
})
LINK_KINDS = frozenset({"link_degrade", "link_restore"})
ALL_KINDS = HOST_KINDS | LINK_KINDS

#: kind -> the auto-generated recovery kind for `duration`/`until`
_RECOVERY = {
    "host_crash": "host_reboot",
    "iface_down": "iface_up",
    "link_degrade": "link_restore",
    "host_degrade": "host_restore",
    "corrupt_burst": "_corrupt_end",
}


@dataclass(frozen=True)
class FaultEvent:
    """One compiled fault instant. `seq` is the stable tiebreak for
    same-instant events (config order, then generator order)."""

    time_ns: int
    kind: str
    host: Optional[str] = None
    src_node: Optional[int] = None
    dst_node: Optional[int] = None
    latency_mult: int = 1
    bandwidth_div: int = 1
    corrupt_p: float = 0.0
    symmetric: bool = True
    seq: int = 0

    def describe(self) -> str:
        tgt = (self.host if self.host is not None
               else f"link {self.src_node}->{self.dst_node}")
        return f"t={self.time_ns}ns {self.kind} {tgt}"


def _dur(raw: Any, where: str) -> int:
    try:
        return units.parse_duration_ns(raw)
    except (ValueError, TypeError) as e:
        raise ConfigError(f"{where}: {e}") from None


def _parse_event(raw: dict, i: int, host_names: set[str]) -> list[FaultEvent]:
    where = f"faults.events[{i}]"
    if not isinstance(raw, dict):
        raise ConfigError(f"{where}: expected a mapping, got {raw!r}")
    raw = dict(raw)
    kind = raw.pop("kind", None)
    if kind not in ALL_KINDS:
        raise ConfigError(
            f"{where}: unknown kind {kind!r} (expected one of "
            f"{', '.join(sorted(ALL_KINDS))})")
    at = raw.pop("at", None)
    if at is None:
        raise ConfigError(f"{where}: missing required field 'at'")
    t = _dur(at, f"{where}.at")
    duration = raw.pop("duration", None)
    until = raw.pop("until", None)
    if duration is not None and until is not None:
        raise ConfigError(f"{where}: give 'duration' or 'until', not both")
    end = None
    if duration is not None:
        end = t + _dur(duration, f"{where}.duration")
    elif until is not None:
        end = _dur(until, f"{where}.until")
        if end <= t:
            raise ConfigError(f"{where}: until must be after at")

    kw: dict = {"time_ns": t, "kind": kind}
    if kind in HOST_KINDS:
        host = raw.pop("host", None)
        if host not in host_names:
            raise ConfigError(
                f"{where}: host {host!r} is not a configured host")
        kw["host"] = str(host)
    else:
        for f in ("src_node", "dst_node"):
            v = raw.pop(f, None)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                raise ConfigError(
                    f"{where}: {f} must be a non-negative node index")
            kw[f] = v
        kw["symmetric"] = bool(raw.pop("symmetric", True))
    if kind == "link_degrade":
        m = raw.pop("latency_mult", None)
        if not isinstance(m, int) or isinstance(m, bool) or m < 1:
            raise ConfigError(
                f"{where}: latency_mult must be an integer >= 1")
        kw["latency_mult"] = m
    if kind == "host_degrade":
        d = raw.pop("bandwidth_div", None)
        if not isinstance(d, int) or isinstance(d, bool) or d < 1:
            raise ConfigError(
                f"{where}: bandwidth_div must be an integer >= 1")
        kw["bandwidth_div"] = d
    if kind == "corrupt_burst":
        p = raw.pop("p", None)
        if not isinstance(p, (int, float)) or isinstance(p, bool) \
                or not (0.0 <= float(p) <= 1.0):
            raise ConfigError(f"{where}: p must be a probability in [0, 1]")
        kw["corrupt_p"] = float(p)
        if end is None:
            raise ConfigError(
                f"{where}: corrupt_burst requires duration (or until)")
    if raw:
        raise ConfigError(
            f"{where}: unknown field(s) {sorted(raw)} for kind {kind!r}")

    out = [FaultEvent(**kw)]
    if end is not None:
        rk = _RECOVERY.get(kind)
        if rk is None:
            raise ConfigError(
                f"{where}: duration/until is not meaningful for {kind!r}")
        rkw = dict(kw)
        rkw.update(time_ns=end, kind=rk, latency_mult=1, bandwidth_div=1,
                   corrupt_p=0.0)
        out.append(FaultEvent(**rkw))
    return out


def _expand_random(spec: dict, host_names: list[str],
                   rng: Xoshiro256pp) -> list[FaultEvent]:
    """Seeded generators -> concrete events. Draw order is fixed
    (generator key order is pinned below, not dict order) so the
    expansion is a pure function of the seed."""
    out: list[FaultEvent] = []
    known = {"host_crashes": ("host_crash", "host_reboot"),
             "iface_flaps": ("iface_down", "iface_up")}
    unknown = set(spec) - set(known)
    if unknown:
        raise ConfigError(
            f"faults.random: unknown generator(s) {sorted(unknown)} "
            f"(expected {sorted(known)})")
    for gen_name in ("host_crashes", "iface_flaps"):  # FIXED draw order
        g = spec.get(gen_name)
        if g is None:
            continue
        if not isinstance(g, dict):
            raise ConfigError(f"faults.random.{gen_name}: expected a mapping")
        g = dict(g)
        count = g.pop("count", None)
        window = g.pop("window", None)
        downtime = g.pop("downtime", None)
        if g:
            raise ConfigError(
                f"faults.random.{gen_name}: unknown field(s) {sorted(g)}")
        if not isinstance(count, int) or isinstance(count, bool) or count < 1:
            raise ConfigError(
                f"faults.random.{gen_name}.count must be an integer >= 1")
        if (not isinstance(window, (list, tuple)) or len(window) != 2):
            raise ConfigError(
                f"faults.random.{gen_name}.window must be [start, end]")
        w0 = _dur(window[0], f"faults.random.{gen_name}.window[0]")
        w1 = _dur(window[1], f"faults.random.{gen_name}.window[1]")
        if w1 <= w0:
            raise ConfigError(
                f"faults.random.{gen_name}.window end must be after start")
        if downtime is None:
            raise ConfigError(
                f"faults.random.{gen_name}: missing required 'downtime'")
        down_ns = _dur(downtime, f"faults.random.{gen_name}.downtime")
        down_kind, up_kind = known[gen_name]
        for _ in range(count):
            host = host_names[rng.randrange(0, len(host_names))]
            t = w0 + rng.randrange(0, w1 - w0)
            out.append(FaultEvent(time_ns=t, kind=down_kind, host=host))
            out.append(FaultEvent(time_ns=t + down_ns, kind=up_kind,
                                  host=host))
    return out


class FaultSchedule:
    """The compiled, sorted schedule plus the live mask state it folds
    into as `advance()` consumes events.

    Mask state (numpy; the `faults/plane.FaultArrays` mirror):
    `host_alive [N]`, `link_up [N]`, `bw_div [N]`, `corrupt_p [N]`,
    `lat_mult [M, M]`. Host index = position in `host_names`
    (config-declared order, the Manager's host_id - 1). Link events
    address *node indices* in [0, M): callers whose graph node IDs are
    not dense indices pass `node_index` to map them at compile time.
    """

    def __init__(self, events: list[FaultEvent], host_names: list[str],
                 n_nodes: int):
        self.events = sorted(events, key=lambda e: (e.time_ns, e.seq))
        self.host_names = list(host_names)
        self.host_index = {n: i for i, n in enumerate(self.host_names)}
        n, m = len(self.host_names), max(int(n_nodes), 1)
        self.n_hosts, self.n_nodes = n, m
        self.host_alive = np.ones(n, bool)
        self.link_up = np.ones(n, bool)
        self.bw_div = np.ones(n, np.int32)
        self.corrupt_p = np.zeros(n, np.float32)
        self.lat_mult = np.ones((m, m), np.int32)
        self._cursor = 0
        self.fired: list[FaultEvent] = []
        # raw graph-node-id -> dense node index; the CPU send filter
        # receives raw ids (Worker's ip_to_node_id) while the mask
        # matrix lives in dense index space
        self._node_map: Optional[dict] = None

    def set_node_map(self, node_map: dict) -> None:
        self._node_map = dict(node_map)

    # -- compile-time views ----------------------------------------------

    @property
    def remaining(self) -> int:
        return len(self.events) - self._cursor

    def peek_next_ns(self) -> Optional[int]:
        if self._cursor >= len(self.events):
            return None
        return self.events[self._cursor].time_ns

    def fingerprint(self) -> str:
        """Stable digest of the compiled event list (determinism tests:
        same seed -> same schedule, byte for byte)."""
        import hashlib

        h = hashlib.sha256()
        for e in self.events:
            h.update(repr((e.time_ns, e.kind, e.host, e.src_node,
                           e.dst_node, e.latency_mult, e.bandwidth_div,
                           e.corrupt_p, e.symmetric)).encode())
        return h.hexdigest()

    def span_fingerprint(self, t0_ns: int, t1_ns: int) -> str:
        """Digest of everything the fault plane contributes to the
        span (t0, t1]: the CURRENT mask state (the masks the span's
        first window runs under — callers must have `advance`d the
        schedule to t0 first) plus every still-pending event firing
        inside the span, with times RELATIVE to t0 so a periodic fault
        pattern fingerprints equal across its repeats.

        This is the memo plane's span salt (`drive_chained_windows`
        ``memo_span_salt``): a chain span is only replayable onto
        another span whose fault masks AND in-span event sequence are
        identical — the chaos_smoke opt-out discipline ("fault-injected
        spans are never memoized unless the schedule span fingerprint
        matches")."""
        import hashlib

        h = hashlib.sha256()
        for arr in (self.host_alive, self.link_up, self.bw_div,
                    self.corrupt_p, self.lat_mult):
            h.update(str(arr.dtype).encode())
            h.update(np.ascontiguousarray(arr).tobytes())
        for e in self.events[self._cursor:]:
            if e.time_ns <= t0_ns:
                continue
            if e.time_ns > t1_ns:
                break
            h.update(repr((e.time_ns - t0_ns, e.kind, e.host,
                           e.src_node, e.dst_node, e.latency_mult,
                           e.bandwidth_div, e.corrupt_p,
                           e.symmetric)).encode())
        return h.hexdigest()

    # -- runtime ----------------------------------------------------------

    def advance(self, now_ns: int) -> list[FaultEvent]:
        """Consume every event with time <= now_ns, fold it into the
        mask state, and return the fired list (caller mirrors them onto
        the CPU objects)."""
        fired: list[FaultEvent] = []
        while self._cursor < len(self.events) \
                and self.events[self._cursor].time_ns <= now_ns:
            ev = self.events[self._cursor]
            self._cursor += 1
            self._apply(ev)
            fired.append(ev)
        self.fired.extend(fired)
        return fired

    def _apply(self, ev: FaultEvent) -> None:
        if ev.kind in LINK_KINDS:
            s, d = ev.src_node, ev.dst_node
            if not (0 <= s < self.n_nodes and 0 <= d < self.n_nodes):
                raise ConfigError(
                    f"fault event {ev.describe()}: node index out of "
                    f"range for a {self.n_nodes}-node graph")
            mult = ev.latency_mult if ev.kind == "link_degrade" else 1
            self.lat_mult[s, d] = mult
            if ev.symmetric:
                self.lat_mult[d, s] = mult
            return
        i = self.host_index[ev.host]
        if ev.kind == "host_crash":
            self.host_alive[i] = False
        elif ev.kind == "host_reboot":
            self.host_alive[i] = True
        elif ev.kind == "iface_down":
            self.link_up[i] = False
        elif ev.kind == "iface_up":
            self.link_up[i] = True
        elif ev.kind == "host_degrade":
            self.bw_div[i] = ev.bandwidth_div
        elif ev.kind == "host_restore":
            self.bw_div[i] = 1
        elif ev.kind == "corrupt_burst":
            self.corrupt_p[i] = ev.corrupt_p
        elif ev.kind == "_corrupt_end":
            self.corrupt_p[i] = 0.0

    def device_arrays(self):
        """The current mask state as a `FaultArrays` pytree for
        `window_step(..., faults=)` (lazy jax import: CPU-plane-only
        callers never pull jax in here)."""
        from .plane import faults_from_numpy

        return faults_from_numpy(self.host_alive, self.link_up,
                                 self.lat_mult, self.bw_div,
                                 self.corrupt_p)

    # -- the CPU-plane send filter (`Worker.send_packet`) ----------------

    def filter_send(self, src_host, dst_host, packet, src_node: int,
                    dst_node: int, latency: int) -> tuple[bool, int]:
        """Apply the fault overlay to one cross-host send. Returns
        (drop, latency'). The corruption draw comes from the SOURCE
        host's RNG stream (scheduling-independent, like path loss) and
        only happens while a burst is active for that host — so a
        schedule without corruption never perturbs the stream."""
        if getattr(src_host, "fault_down", False) \
                or getattr(dst_host, "fault_down", False):
            return True, latency
        if self._node_map is not None:
            src_node = self._node_map.get(src_node, -1)
            dst_node = self._node_map.get(dst_node, -1)
        if (0 <= src_node < self.n_nodes and 0 <= dst_node < self.n_nodes):
            mult = int(self.lat_mult[src_node, dst_node])
            if mult > 1:
                latency = latency * mult
        i = self.host_index.get(src_host.name)
        if i is not None and self.corrupt_p[i] > 0.0 \
                and packet.payload_size() > 0 \
                and src_host.rng.random() < float(self.corrupt_p[i]):
            return True, latency
        return False, latency


def compile_schedule(faults_opts, *, host_names: list[str], n_nodes: int,
                     seed: int, stop_time_ns: int,
                     node_index=None) -> FaultSchedule:
    """`faults:` config block -> sorted `FaultSchedule`.

    `node_index` maps the config's graph node IDs to dense [0, M)
    indices for the device mask (identity when None). Events past
    `stop_time_ns` are kept (they simply never fire) but logged-free;
    events at t <= 0 are a config error — the schedule describes
    failures *during* the run."""
    host_set = set(host_names)
    events: list[FaultEvent] = []
    for i, raw in enumerate(faults_opts.events or []):
        events.extend(_parse_event(raw, i, host_set))
    if faults_opts.random:
        fseed = seed if faults_opts.seed is None else faults_opts.seed
        rng = Xoshiro256pp((fseed ^ _FAULT_SEED_SALT) & ((1 << 64) - 1))
        events.extend(_expand_random(faults_opts.random, list(host_names),
                                     rng))
    for ev in events:
        if ev.time_ns <= 0:
            raise ConfigError(
                f"faults: event {ev.describe()} must have at > 0")
    if node_index is not None:
        events = [
            (e if e.src_node is None else _reindex(e, node_index))
            for e in events
        ]
    # stable seq assignment AFTER expansion: config order, then
    # generator order — the same-instant tiebreak is reproducible
    events = [FaultEvent(**{**e.__dict__, "seq": i})
              for i, e in enumerate(events)]
    return FaultSchedule(events, list(host_names), n_nodes)


def _reindex(ev: FaultEvent, node_index) -> FaultEvent:
    try:
        s, d = node_index(ev.src_node), node_index(ev.dst_node)
    except (KeyError, ValueError):
        raise ConfigError(
            f"faults: event {ev.describe()} names a graph node that is "
            f"not used by any host") from None
    return FaultEvent(**{**ev.__dict__, "src_node": s, "dst_node": d})
