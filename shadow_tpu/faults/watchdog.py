"""The round watchdog: a hung round becomes a structured error.

A managed native process that wedges (an infinite loop that never traps
a syscall, a binary stuck before the shim handshake) leaves a scheduler
worker blocked in `recv_from_shim` forever — the one liveness hole the
`ChildPidWatcher` (which only detects *death*) cannot cover. The
watchdog closes it:

- the Manager arms the watchdog around every `scheduler.run_round`;
- if the round does not finish within the WALL-clock timeout
  (`faults.watchdog`), the watchdog thread collects per-host blame —
  which hosts were in the round, which managed processes are still
  alive, and which of their pids the pidwatcher is still watching —
  then SIGKILLs the blamed native pids. The kill makes the pidwatcher
  fire, which closes the IPC writers, which wakes the blocked
  `recv_from_shim` calls: the round completes instead of hanging;
- back on the driving thread, the Manager sees the strike and raises
  `WatchdogError` carrying the blame — a structured failure (CLI exit
  code 3, docs/robustness.md) with an emergency checkpoint behind it,
  not a simulator that sits silent forever.

Wall-clock here detects *failure*, never feeds simulation state: a run
that does not trip the watchdog is bitwise-unaffected by it.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

log = logging.getLogger("shadow_tpu.faults")


@dataclass
class HostBlame:
    """Why the watchdog blames one host for a hung round."""

    host: str
    processes: list[str] = field(default_factory=list)  # proc names
    native_pids: list[int] = field(default_factory=list)
    watched_pids: list[int] = field(default_factory=list)  # per pidwatcher

    def describe(self) -> str:
        pids = ", ".join(
            f"{p}{'*' if p in self.watched_pids else ''}"
            for p in self.native_pids) or "none"
        return (f"host {self.host}: processes [{', '.join(self.processes)}]"
                f" native pids [{pids}] (* = still watched by the "
                f"pidwatcher, i.e. alive when the watchdog fired)")


class WatchdogError(RuntimeError):
    """A round exceeded the watchdog timeout. `.blame` names the hosts
    and managed processes that were still executing."""

    def __init__(self, round_start_ns: int, timeout_s: float,
                 blame: list[HostBlame], killed: list[int]):
        self.round_start_ns = round_start_ns
        self.timeout_s = timeout_s
        self.blame = blame
        self.killed = killed
        lines = "; ".join(b.describe() for b in blame) or "no live blame"
        super().__init__(
            f"round at simtime {round_start_ns} exceeded the {timeout_s:g}s "
            f"watchdog ({len(killed)} wedged native process(es) killed): "
            f"{lines}")


class RoundWatchdog:
    """One daemon timer armed per round.

    `collect_blame(round_start_ns)` is the Manager's callback: it runs
    ON THE WATCHDOG THREAD while workers are still blocked, so it must
    only read process-table state and send signals — never touch host
    event queues."""

    def __init__(self, timeout_s: float,
                 collect_blame: Callable[[int], list[HostBlame]]):
        if timeout_s <= 0:
            raise ValueError("watchdog timeout must be positive")
        self.timeout_s = float(timeout_s)
        self._collect_blame = collect_blame
        self._timer: Optional[threading.Timer] = None
        self._lock = threading.Lock()
        self.strike: Optional[WatchdogError] = None  # set by the timer

    def arm(self, round_start_ns: int) -> None:
        with self._lock:
            self._round_start = round_start_ns
            self._timer = threading.Timer(
                self.timeout_s, self._fire, args=(round_start_ns,))
            self._timer.daemon = True
            self._timer.start()

    def disarm(self) -> None:
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None

    def _fire(self, round_start_ns: int) -> None:
        # Timer.cancel() is a no-op once the callback has started, so a
        # round completing right AT the timeout could race the strike:
        # re-check armed state under the lock — if disarm() already ran
        # (the round finished), healthy processes must NOT be killed
        with self._lock:
            if self._timer is None or self._round_start != round_start_ns:
                return
            self._timer = None
        log.error(
            "watchdog: round at simtime %d still running after %gs — "
            "collecting blame and killing wedged managed processes",
            round_start_ns, self.timeout_s)
        try:
            blame = self._collect_blame(round_start_ns)
        except Exception:
            log.error("watchdog: blame collection failed", exc_info=True)
            blame = []
        killed = kill_blamed(blame)
        self.strike = WatchdogError(round_start_ns, self.timeout_s, blame,
                                    killed)

    class _Guard:
        def __init__(self, wd: "RoundWatchdog", round_start_ns: int):
            self._wd = wd
            self._start = round_start_ns

        def __enter__(self):
            self._wd.arm(self._start)
            return self._wd

        def __exit__(self, *exc):
            self._wd.disarm()
            return False

    def guard(self, round_start_ns: int) -> "RoundWatchdog._Guard":
        return RoundWatchdog._Guard(self, round_start_ns)


def kill_blamed(blame: list[HostBlame]) -> list[int]:
    """SIGKILL every blamed native pid. SIGKILL (not TERM): the process
    is wedged — the whole point is that it no longer services anything,
    and only an unmaskable kill guarantees the pidfd fires and the
    blocked IPC reads wake."""
    killed: list[int] = []
    for b in blame:
        for pid in b.native_pids:
            try:
                os.kill(pid, signal.SIGKILL)
                killed.append(pid)
            except (ProcessLookupError, PermissionError):
                continue  # already gone (raced its own exit) or not ours
    if killed:
        log.error("watchdog: SIGKILLed wedged native pids %s", killed)
    return killed
