"""The guard plane: runtime invariants, cross-plane reconciliation,
and virtual-time progress detection (docs/robustness.md).

The simulation checks itself against itself at runtime, in three legs:

- `plane`     — `GuardState`, the on-device conservation/structure
  checks threaded through `tpu/plane.window_step` / `ingest_rows` and
  the `DeviceTransport` kernels as a static presence switch
  (guards=None compiles out, bitwise-identical).
- `reconcile` — per-host-id reconciliation of device counters against
  independently-maintained CPU ledgers and `SimStats` fleet totals at
  telemetry harvest boundaries and teardown.
- `progress`  — the round-loop zero-progress livelock detector
  (virtual-time complement of the fault plane's wall-clock watchdog).
- `report`    — `GuardViolation` / `GuardError` / `GuardLedger`: one
  structured shape for every finding, policy dispatch (warn / abort /
  abort+checkpoint, CLI exit 5), and the guards-report.json artifact.
"""

from .plane import (GUARD_BIT_NAMES, GUARD_CLOCK,  # noqa: F401
                    GUARD_EGRESS_FLOW, GUARD_INGEST_FLOW,
                    GUARD_INGRESS_FLOW, GUARD_KEY_BUDGET,
                    GUARD_RING_STRUCT, GUARD_RNG_MONOTONE, GuardState,
                    decode_bits, make_guards, summarize)
from .progress import (HostWait, ProgressDetector,  # noqa: F401
                       StallDiagnosis)
from .reconcile import (TRANSPORT_PAIRS, TransportReconciler,  # noqa: F401
                        reconcile_fleet, reconcile_per_host)
from .report import (POLICIES, GuardError, GuardLedger,  # noqa: F401
                     GuardViolation, write_report)

__all__ = [
    "GUARD_BIT_NAMES",
    "GUARD_CLOCK",
    "GUARD_EGRESS_FLOW",
    "GUARD_INGEST_FLOW",
    "GUARD_INGRESS_FLOW",
    "GUARD_KEY_BUDGET",
    "GUARD_RING_STRUCT",
    "GUARD_RNG_MONOTONE",
    "GuardError",
    "GuardLedger",
    "GuardState",
    "GuardViolation",
    "HostWait",
    "POLICIES",
    "ProgressDetector",
    "StallDiagnosis",
    "TRANSPORT_PAIRS",
    "TransportReconciler",
    "decode_bits",
    "make_guards",
    "reconcile_fleet",
    "reconcile_per_host",
    "summarize",
    "write_report",
]
