"""Device-side runtime invariants for the TPU plane (`GuardState`).

The guard plane makes the simulation *self-verifying*: conservation
laws and structural invariants of the SoA world are checked ON DEVICE,
every window, with pure `jnp` compares over values the kernels already
materialized — under the same discipline as the telemetry and fault
switches (`telemetry/metrics.py`, `faults/plane.py`):

1. **Static presence switch.** `window_step(..., guards=None)` compiles
   every check out — the jaxpr is identical to the unguarded plane and
   the results are bitwise-identical. Threading a `GuardState` never
   touches simulation state either: guards only READ; the parity matrix
   in tests/test_guards.py pins guards-on == guards-off bitwise.
2. **No raising inside jit.** A violated invariant cannot raise from a
   traced kernel (the check IS traced). Violations accumulate as
   per-host int32 bitmasks plus the window index of the FIRST
   violation; drivers pull the tiny pytree at a sync point they already
   own (teardown, a harvest boundary, the chaos driver's end) and
   decode it with `summarize`/`decode_bits`.
3. **Dtype discipline.** int32 like everything on device; bitmask
   compares and segment adds only — the profiler section
   `window_step_guards` and the chaos-smoke CI gate hold the presence
   switch to the same overhead budget as telemetry and faults.

The checked invariants (docs/robustness.md "Guard plane"):

- **egress conservation** (`GUARD_EGRESS_FLOW`): per host, packets
  occupying the egress ring at window entry == packets that left this
  window (token-gate sendable + fault purge) + packets still queued at
  exit. A qdisc sort or compaction that loses or duplicates a slot
  trips this.
- **ingress conservation** (`GUARD_INGRESS_FLOW`): per host, ring
  occupancy at entry + routed arrivals == overflow drops + AQM drops +
  deliveries + relay-cached transitions + occupancy at exit. A scatter
  that drops valid packets silently trips this.
- **ring structure** (`GUARD_RING_STRUCT`): validity is front-packed
  and invalid slots carry their I32_MAX sentinels — the invariant every
  min-reduction and append in the plane relies on.
- **packed-key bit budget** (`GUARD_KEY_BUDGET`): live sort keys
  (priority, seq) stay non-negative, the domain the uint32 packed-key
  sort diet is order-isomorphic over (tpu/plane.py `_pack_valid_key`).
- **RNG monotonicity** (`GUARD_RNG_MONOTONE`): the per-host counter
  stream advances by [0, CE] draws per window — the determinism
  contract's bookkeeping.
- **virtual clock** (`GUARD_CLOCK`, scalar): window rebases are
  monotone (shift >= 0) and windows non-negative.
- **ingest conservation** (`GUARD_INGEST_FLOW`): `ingest`/`ingest_rows`
  appends exactly (incoming - overflow) entries per row.

Elastic ring growth (`tpu/elastic.grow_state`, docs/robustness.md
"Elastic capacity") is invariant-preserving by construction: the
accumulators are [N]/scalar-shaped (never ring-shaped), growth pads
rings with front-pack-respecting defaults (invalid lanes, I32_MAX
sentinels), and every conservation identity here is a masked sum — so
guards thread unchanged through a resize, and a guards-on elastic run
must stay as clean as its pre-provisioned twin
(tests/test_elastic.py pins it). The elastic drivers restore the guard
accumulator alongside the state snapshot when they discard an
overflowing window attempt, so re-execution never double-counts a
window.

This module is dependency-light (jax/numpy only): `tpu/plane.py`
imports it, never the other way around.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

I32_MAX = np.int32(2**31 - 1)

# violation bits; per-host unless marked scalar
GUARD_EGRESS_FLOW = 1 << 0
GUARD_INGRESS_FLOW = 1 << 1
GUARD_RING_STRUCT = 1 << 2
GUARD_KEY_BUDGET = 1 << 3
GUARD_RNG_MONOTONE = 1 << 4
GUARD_CLOCK = 1 << 5  # scalar (flags leaf)
GUARD_INGEST_FLOW = 1 << 6

GUARD_BIT_NAMES = {
    GUARD_EGRESS_FLOW: "egress-conservation",
    GUARD_INGRESS_FLOW: "ingress-conservation",
    GUARD_RING_STRUCT: "ring-structure",
    GUARD_KEY_BUDGET: "packed-key-budget",
    GUARD_RNG_MONOTONE: "rng-monotone",
    GUARD_CLOCK: "virtual-clock",
    GUARD_INGEST_FLOW: "ingest-conservation",
}

#: checks evaluated per guarded window (the `checks` accounting leaf)
_CHECKS_PER_WINDOW = 6


class GuardState(NamedTuple):
    """Accumulating violation state; plain kernel arguments (never
    static), so threading guards never recompiles between rounds."""

    violations: jax.Array  # [N] int32 bitmask of GUARD_* bits
    first_window: jax.Array  # [N] int32 window idx of first hit (I32_MAX)
    flags: jax.Array  # scalar int32 bitmask (window-global checks)
    windows: jax.Array  # scalar int32 — guarded windows so far
    checks: jax.Array  # scalar int32 — individual checks evaluated


def make_guards(n_hosts: int) -> GuardState:
    """A clean guard accumulator for `n_hosts` hosts."""
    return GuardState(
        violations=jnp.zeros((n_hosts,), jnp.int32),
        first_window=jnp.full((n_hosts,), I32_MAX, jnp.int32),
        flags=jnp.zeros((), jnp.int32),
        windows=jnp.zeros((), jnp.int32),
        checks=jnp.zeros((), jnp.int32),
    )


def _record(guards: GuardState, bad_bits: jax.Array,
            scalar_bits, n_checks: int) -> GuardState:
    """Fold one window's per-host violation bits (and scalar bits) into
    the accumulator; first_window pins the CURRENT window index for
    hosts whose first bit lands now."""
    hit_now = (guards.violations == 0) & (bad_bits != 0)
    return GuardState(
        violations=guards.violations | bad_bits,
        first_window=jnp.where(hit_now, guards.windows,
                               guards.first_window),
        flags=guards.flags | scalar_bits,
        windows=guards.windows + 1,
        checks=guards.checks + jnp.int32(n_checks),
    )


def _front_packed(valid: jax.Array) -> jax.Array:
    """Per row: True when an invalid slot precedes a valid one — the
    front-pack invariant is broken."""
    return (~valid[:, :-1] & valid[:, 1:]).any(axis=1)


def _struct_bits(state) -> jax.Array:
    """Per-host ring-structure violations: validity must be
    front-packed and invalid slots must carry their I32_MAX
    sentinels — the invariants every min-reduction and append in the
    plane relies on."""
    return (
        _front_packed(state.eg_valid)
        | _front_packed(state.in_valid)
        | (~state.in_valid
           & (state.in_deliver_rel != I32_MAX)).any(axis=1)
        | (~state.eg_valid
           & (state.eg_prio != I32_MAX)).any(axis=1)
    )


def _key_bits(state) -> jax.Array:
    """Per-host packed-key bit-budget violations: live sort keys must
    be non-negative (the uint32 fuse in plane._pack_valid_key is only
    order-isomorphic over that domain)."""
    return (state.eg_valid
            & ((state.eg_prio < 0) | (state.eg_seq < 0))).any(axis=1)


def check_window(guards: GuardState, *, state, eg_occ_in,
                 eg_left_this_window, in_occ_in, arrivals, overflowed,
                 delivered, qdisc_delta, cached_in, cached_out,
                 new_state, rng_delta, egress_cap: int, shift_ns,
                 window_ns) -> GuardState:
    """Section 9 of `window_step` (compiled in only when a GuardState is
    threaded): evaluate every window invariant over values the step
    already materialized. Pure reads — nothing here feeds back into
    simulation state.

    `state`/`new_state` are the window's entry/exit states — structure
    and key-budget invariants are checked on BOTH, so at-rest
    corruption between windows (a bad restore, a driver bug, bitflips)
    is caught at the next step even though the step's own sorts would
    re-normalize it. `eg_left_this_window` [N] = packets that left the
    egress ring (sendable + fault purge); `arrivals` [N] = routed
    packets per destination; `cached_in/out` [N] int32 = relay-cached
    occupancy before/after (zeros in direct mode); `rng_delta` [N] =
    RNG counter advance this window."""
    eg_occ_out = new_state.eg_valid.sum(axis=1, dtype=jnp.int32)
    in_occ_out = new_state.in_valid.sum(axis=1, dtype=jnp.int32)

    # conservation (all int32 modular; equality is exact while any
    # per-host flow stays < 2^31 per window, amply true by capacity)
    egress_bad = eg_occ_in - eg_left_this_window != eg_occ_out
    ingress_bad = (in_occ_in + arrivals - overflowed - delivered
                   - qdisc_delta + cached_in - cached_out) != in_occ_out

    struct_bad = _struct_bits(state) | _struct_bits(new_state)
    key_bad = _key_bits(state) | _key_bits(new_state)

    rng_bad = (rng_delta < 0) | (rng_delta > jnp.int32(egress_cap))

    bad = (
        jnp.where(egress_bad, GUARD_EGRESS_FLOW, 0)
        | jnp.where(ingress_bad, GUARD_INGRESS_FLOW, 0)
        | jnp.where(struct_bad, GUARD_RING_STRUCT, 0)
        | jnp.where(key_bad, GUARD_KEY_BUDGET, 0)
        | jnp.where(rng_bad, GUARD_RNG_MONOTONE, 0)
    ).astype(jnp.int32)

    clock_bad = (jnp.int32(shift_ns) < 0) | (jnp.int32(window_ns) < 0)
    scalar_bits = jnp.where(clock_bad, GUARD_CLOCK, 0).astype(jnp.int32)
    return _record(guards, bad, scalar_bits, _CHECKS_PER_WINDOW)


def check_ingest(guards: GuardState, *, occ_before, occ_after, incoming,
                 overflow) -> GuardState:
    """Append conservation for `ingest`/`ingest_rows`: each row must
    gain exactly (incoming - overflow) entries. Does not advance the
    window counter — ingest rides between windows, so a violation pins
    the index of the window about to run."""
    bad = jnp.where(
        occ_after - occ_before != incoming - overflow,
        GUARD_INGEST_FLOW, 0).astype(jnp.int32)
    hit_now = (guards.violations == 0) & (bad != 0)
    return guards._replace(
        violations=guards.violations | bad,
        first_window=jnp.where(hit_now, guards.windows,
                               guards.first_window),
        checks=guards.checks + 1,
    )


# -- host-side decode (outside jit; drivers pull the pytree first) ------


def decode_bits(bits: int) -> list[str]:
    """Names of the guard classes set in a violation bitmask."""
    return [name for bit, name in sorted(GUARD_BIT_NAMES.items())
            if bits & bit]


def summarize(guards) -> dict:
    """Host-side summary of a pulled GuardState: total violation count,
    per-class host counts, and the first offenders. `guards` may be a
    GuardState of device arrays or of numpy arrays."""
    violations = np.asarray(jax.device_get(guards.violations))
    first = np.asarray(jax.device_get(guards.first_window))
    flags = int(jax.device_get(guards.flags))
    bad_hosts = np.nonzero(violations)[0]
    by_class: dict[str, int] = {}
    for bit, name in sorted(GUARD_BIT_NAMES.items()):
        n = int(((violations & bit) != 0).sum()) + (
            1 if flags & bit else 0)
        if n:
            by_class[name] = n
    offenders = [
        {"host_index": int(h), "bits": decode_bits(int(violations[h])),
         "first_window": int(first[h])}
        for h in bad_hosts[:16]
    ]
    return {
        "violating_hosts": int(bad_hosts.size),
        "scalar_flags": decode_bits(flags),
        "by_class": by_class,
        "first_offenders": offenders,
        "windows_checked": int(jax.device_get(guards.windows)),
        "checks_evaluated": int(jax.device_get(guards.checks)),
        "clean": bad_hosts.size == 0 and flags == 0,
    }
