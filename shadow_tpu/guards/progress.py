"""Virtual-time progress detection: the round-loop livelock guard.

The fault plane's watchdog (faults/watchdog.py) covers WALL-clock
hangs — a wedged native process that stops the round loop dead. This
detector covers the complementary failure: the round loop keeps
SPINNING — virtual time advances round after round — while nothing is
actually simulated. The signature case is a device plane (or any
next-event source) that keeps advertising a pending event which never
materializes into an executed host event or a delivered packet, while
managed processes sit blocked on input that will never arrive: a
zero-progress livelock that would otherwise burn wall time to the stop
time and report silently wrong (empty) results.

A round counts as STALLED when all of:

- virtual time advanced (the window start moved forward);
- zero host events executed (nothing was drained from any queue);
- zero packets moved on either plane (no sends, no deliveries).

`max_rounds` consecutive stalled rounds trip the detector, producing a
`StallDiagnosis` naming who is waiting on what: every host with alive
processes (and what its next queued event is, if any), plus the
device-plane in-flight population. Everything observed is virtual-time
/ counter state — wall clock never enters, so a run that does not trip
the detector is bitwise-unaffected by it (docs/determinism.md).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .report import GuardViolation


@dataclass
class HostWait:
    """One host's contribution to a stall diagnosis."""

    host: str
    alive_processes: list[str] = field(default_factory=list)
    next_event_ns: Optional[int] = None

    def describe(self) -> str:
        nxt = (f"next event at {self.next_event_ns}"
               if self.next_event_ns is not None else "no queued events")
        procs = ", ".join(self.alive_processes) or "none"
        return f"host {self.host}: blocked processes [{procs}], {nxt}"


@dataclass
class StallDiagnosis:
    """Who is waiting on what after N zero-progress rounds."""

    stalled_rounds: int
    first_stalled_ns: int
    window_start_ns: int
    waiting: list[HostWait] = field(default_factory=list)
    device_in_flight: int = 0

    def describe(self) -> str:
        hosts = "; ".join(w.describe() for w in self.waiting) or \
            "no host holds blocked processes"
        return (
            f"{self.stalled_rounds} consecutive rounds advanced virtual "
            f"time ({self.first_stalled_ns} -> {self.window_start_ns} ns) "
            f"without executing an event or moving a packet; "
            f"device in-flight: {self.device_in_flight}; {hosts}")

    def to_violation(self) -> GuardViolation:
        return GuardViolation(
            cls="progress", check="zero-progress-livelock",
            time_ns=self.window_start_ns,
            host=self.waiting[0].host if self.waiting else None,
            expected="events or packets within "
                     f"{self.stalled_rounds} rounds",
            actual="none", detail=self.describe(),
        )


class ProgressDetector:
    """Feed one `observe()` per round; returns a StallDiagnosis when
    the stall budget is exhausted (then re-arms, so a `warn` policy
    reports each full stall period once instead of every round)."""

    def __init__(self, max_rounds: int):
        if max_rounds <= 0:
            raise ValueError("guards.progress_rounds must be positive")
        self.max_rounds = int(max_rounds)
        self._streak = 0
        self._first_stalled_ns: Optional[int] = None
        self._last_start: Optional[int] = None
        self.trips = 0

    def observe(self, window_start_ns: int, *, events_delta: int,
                packets_delta: int,
                waiting: Optional[list[HostWait]] = None,
                device_in_flight: int = 0) -> Optional[StallDiagnosis]:
        advanced = (self._last_start is not None
                    and window_start_ns > self._last_start)
        self._last_start = window_start_ns
        if not advanced or events_delta > 0 or packets_delta > 0:
            self._streak = 0
            self._first_stalled_ns = None
            return None
        if self._streak == 0:
            self._first_stalled_ns = window_start_ns
        self._streak += 1
        if self._streak < self.max_rounds:
            return None
        diagnosis = StallDiagnosis(
            stalled_rounds=self._streak,
            first_stalled_ns=int(self._first_stalled_ns or 0),
            window_start_ns=int(window_start_ns),
            waiting=list(waiting or []),
            device_in_flight=int(device_in_flight),
        )
        self.trips += 1
        self._streak = 0
        self._first_stalled_ns = None
        return diagnosis
