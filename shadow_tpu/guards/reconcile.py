"""Cross-plane reconciliation: device counters vs CPU ledgers vs stats.

Batched device execution hides single-counter corruption: a wrong
per-host total on either plane would surface (if at all) as silently
wrong stats. This module closes the loop by comparing, per host-id,
counters that are maintained INDEPENDENTLY on the two planes but are
equal by construction:

- the `DeviceTransport` kernels count ingested packets per SOURCE host
  (`n_out`), released packets per DESTINATION host (`n_released`), and
  ring-overflow drops (`n_overflow`) on device;
- the transport's CPU side mirrors the same events in plain numpy
  int64 ledgers at capture / release time (`cpu_ledger`), and the
  `SimStats` fleet totals count every routed packet a third way
  (`routing.packet_counters`).

Any disagreement is a real accounting bug — a lost scatter, a counter
that wrapped wrong, a D2H corruption — and becomes a structured
`GuardViolation` carrying the host blame and the offending counter
pair.

Timing discipline: device snapshots materialize asynchronously one
harvest interval late (telemetry/harvest.py), so comparisons pair each
device snapshot with the CPU ledger copied AT THE SAME TICK. In
mirrored transport mode the device re-executes windows in batches and
its counters lag by design — mid-run comparison would be pure noise —
so reconciliation runs only on the settled teardown snapshot there
(the Manager wires the mode in; docs/robustness.md).
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

import numpy as np

from .report import GuardViolation

#: (device counter name, CPU ledger name) identity pairs for the
#: device-transport reconciliation
TRANSPORT_PAIRS = (
    ("pkts_out", "captured"),
    ("pkts_in", "released"),
)


def reconcile_per_host(time_ns: int,
                       device: Mapping[str, np.ndarray],
                       cpu: Mapping[str, np.ndarray],
                       pairs: Sequence[tuple[str, str]],
                       host_names: Optional[Sequence[str]] = None,
                       max_violations: int = 32) -> list[GuardViolation]:
    """Compare per-host device totals against the CPU ledger for every
    (device_field, cpu_field) pair present on both sides. Returns one
    violation per (pair, host) mismatch, capped at `max_violations`
    (the cap is recorded as a final fleet-level violation so truncation
    is never silent)."""
    out: list[GuardViolation] = []
    truncated = 0
    for dev_name, cpu_name in pairs:
        if dev_name not in device or cpu_name not in cpu:
            continue
        dev = np.asarray(device[dev_name], np.int64)
        led = np.asarray(cpu[cpu_name], np.int64)
        n = min(dev.shape[0], led.shape[0])
        bad = np.nonzero(dev[:n] != led[:n])[0]
        for i in bad:
            if len(out) >= max_violations:
                truncated += 1
                continue
            name = (host_names[i] if host_names and i < len(host_names)
                    else f"host{i + 1}")
            out.append(GuardViolation(
                cls="reconcile",
                check=f"{dev_name}-vs-{cpu_name}",
                time_ns=time_ns, host=name,
                expected=int(led[i]), actual=int(dev[i]),
                detail="device counter disagrees with the CPU ledger "
                       "for this host-id",
            ))
    if truncated:
        out.append(GuardViolation(
            cls="reconcile", check="per-host-mismatch-overflow",
            time_ns=time_ns,
            detail=f"{truncated} further per-host mismatches truncated "
                   f"from this report (cap {max_violations})",
        ))
    return out


def reconcile_fleet(time_ns: int,
                    checks: Sequence[tuple[str, int, int, str]],
                    ) -> list[GuardViolation]:
    """Fleet-total identities: `checks` is (name, expected, actual,
    detail) tuples; every inequality becomes a violation."""
    return [
        GuardViolation(cls="reconcile", check=name, time_ns=time_ns,
                       expected=int(expected), actual=int(actual),
                       detail=detail)
        for name, expected, actual, detail in checks
        if int(expected) != int(actual)
    ]


class TransportReconciler:
    """The Manager-side reconciliation hook for `use_tpu_transport`
    runs. Snapshots the transport's CPU ledger at each telemetry tick
    (same instant as the device copy the harvester starts), then
    compares when the harvester's drain materializes that snapshot —
    zero added device syncs. `final` comparisons additionally check the
    fleet conservation identity and the SimStats totals."""

    def __init__(self, transport, host_names: Sequence[str],
                 *, mid_run: bool):
        self._transport = transport
        self._host_names = list(host_names)
        # mirrored mode lags by design: compare only the settled
        # teardown snapshot there
        self._mid_run = mid_run
        self._pending: dict[int, dict[str, np.ndarray]] = {}

    def note_tick(self, time_ns: int) -> None:
        """Called at harvest tick time, right after the harvester
        started the async device copy: pair it with a same-instant
        ledger snapshot."""
        if self._mid_run:
            self._pending[int(time_ns)] = self._transport.cpu_ledger()

    def on_drain(self, time_ns: int, device_totals: dict,
                 _cpu) -> list[GuardViolation]:
        """Harvester drain callback: the device snapshot for `time_ns`
        is now host-resident; reconcile it against the ledger snapshot
        taken at the same tick."""
        ledger = self._pending.pop(int(time_ns), None)
        if ledger is None:
            return []
        return reconcile_per_host(
            time_ns, device_totals, ledger, TRANSPORT_PAIRS,
            self._host_names)

    def final(self, time_ns: int, *, packets_sent: Optional[int] = None,
              ) -> list[GuardViolation]:
        """Teardown reconciliation on settled counters (a blocking pull
        is fine here — the run is over). Valid in BOTH transport modes:
        sync released everything it delivered, mirrored flushed every
        record batch in `finalize`."""
        import jax

        device = {
            name: np.asarray(jax.device_get(arr), np.int64)
            for name, arr in self._transport.telemetry_arrays().items()
        }
        ledger = self._transport.cpu_ledger()
        out = reconcile_per_host(time_ns, device, ledger,
                                 TRANSPORT_PAIRS, self._host_names)
        # fleet conservation: everything ingested is released, dropped
        # to overflow, or still in flight on device
        fleet = [(
            "transport-conservation",
            int(device["pkts_out"].sum()),
            int(device["pkts_in"].sum())
            + int(device["drop_ring_full"].sum())
            + int(self._transport.device_in_flight()),
            "sum(n_out) != sum(n_released) + sum(n_overflow) + in-flight",
        )]
        if packets_sent is not None:
            # every routed packet was captured exactly once
            # (worker.send_packet counts then captures)
            fleet.append((
                "packets_sent-vs-captured",
                int(packets_sent),
                int(ledger["captured"].sum()),
                "SimStats.packets_sent != transport captures",
            ))
        out.extend(reconcile_fleet(time_ns, fleet))
        return out
