"""Structured guard violations, the abort error, and the report file.

Every guard class (device conservation, cross-plane reconciliation,
progress detection) funnels its findings into the same shapes:

- `GuardViolation` — one discrepancy with per-host blame and the
  offending counter pair (machine-readable via `as_dict`, human via
  `describe`);
- `GuardError` — raised when the configured policy for the violating
  class is `abort` / `abort+checkpoint` (CLI exit code `EXIT_GUARD` =
  5, docs/robustness.md). `want_checkpoint` tells the Manager's crash
  path whether to drop the emergency checkpoint — `abort+checkpoint`
  ships a full postmortem bundle (emergency checkpoint + finalized
  telemetry), plain `abort` just dies with the report;
- `write_report` — the `guards-report.json` artifact the Manager drops
  in the data directory whenever a run recorded violations.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass, field
from typing import Any, Optional

log = logging.getLogger("shadow_tpu.guards")

#: policies a guard class may be configured with (core/config.py)
POLICIES = ("off", "warn", "abort", "abort+checkpoint")


@dataclass
class GuardViolation:
    """One self-check discrepancy with blame attached."""

    cls: str  # "device" | "reconcile" | "progress"
    check: str  # e.g. "ingress-conservation", "pkts_out-vs-captured"
    time_ns: int
    host: Optional[str] = None  # blamed host name (None = fleet-level)
    expected: Any = None
    actual: Any = None
    detail: str = ""

    def describe(self) -> str:
        where = f" host={self.host}" if self.host else ""
        pair = ""
        if self.expected is not None or self.actual is not None:
            pair = f" expected={self.expected} actual={self.actual}"
        tail = f" ({self.detail})" if self.detail else ""
        return (f"[{self.cls}] {self.check}{where} "
                f"time_ns={self.time_ns}{pair}{tail}")

    def as_dict(self) -> dict:
        return {
            "class": self.cls,
            "check": self.check,
            "time_ns": self.time_ns,
            "host": self.host,
            "expected": self.expected,
            "actual": self.actual,
            "detail": self.detail,
        }


class GuardError(RuntimeError):
    """A guard class with an abort policy recorded violations. Carries
    the violations and whether the crash path should also write the
    emergency checkpoint (`abort+checkpoint`)."""

    def __init__(self, cls: str, violations: list[GuardViolation],
                 want_checkpoint: bool):
        self.cls = cls
        self.violations = list(violations)
        self.want_checkpoint = want_checkpoint
        head = "; ".join(v.describe() for v in self.violations[:4])
        more = (f" (+{len(self.violations) - 4} more)"
                if len(self.violations) > 4 else "")
        super().__init__(
            f"guard plane abort [{cls} policy]: "
            f"{len(self.violations)} violation(s): {head}{more}")


@dataclass
class GuardLedger:
    """Run-scoped violation collector + policy dispatcher. The Manager
    owns one; every guard class reports through `apply`."""

    policies: dict[str, str] = field(default_factory=dict)
    violations: list[GuardViolation] = field(default_factory=list)

    def apply(self, cls: str, found: list[GuardViolation]) -> None:
        """Record `found` and enforce the class policy: warn logs each
        violation; abort raises GuardError (the caller's crash path owns
        checkpoint + telemetry finalization)."""
        if not found:
            return
        self.violations.extend(found)
        policy = self.policies.get(cls, "warn")
        for v in found:
            log.warning("guard violation: %s", v.describe())
        if policy in ("abort", "abort+checkpoint"):
            raise GuardError(cls, found, policy == "abort+checkpoint")

    def as_dict(self) -> dict:
        by_class: dict[str, int] = {}
        for v in self.violations:
            by_class[v.cls] = by_class.get(v.cls, 0) + 1
        return {
            "violations": [v.as_dict() for v in self.violations],
            "by_class": by_class,
            "total": len(self.violations),
        }


def write_report(directory: str, ledger: GuardLedger,
                 extra: Optional[dict] = None) -> Optional[str]:
    """Drop guards-report.json into `directory`; never raises (the
    report must not mask the error it documents)."""
    try:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, "guards-report.json")
        payload = ledger.as_dict()
        if extra:
            payload.update(extra)
        with open(path, "w") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
        return path
    except OSError:
        log.error("guards: failed to write report", exc_info=True)
        return None
