"""Simulated-CPU time accounting.

Parity: reference `src/main/host/cpu.rs:8-40` — native execution time spent
by managed code is charged to a simulated CPU at a configured frequency; when
accumulated unapplied delay exceeds a threshold, event execution is pushed
into the future (rounded up to a precision), modelling an oversubscribed CPU.
"""

from __future__ import annotations

from typing import Optional


class Cpu:
    __slots__ = ("_sim_freq_khz", "_native_freq_khz", "_threshold", "_precision", "_now", "_time_cursor")

    def __init__(
        self,
        sim_frequency_khz: int,
        native_frequency_khz: int,
        threshold_ns: Optional[int],
        precision_ns: Optional[int],
    ):
        self._sim_freq_khz = sim_frequency_khz
        self._native_freq_khz = native_frequency_khz
        self._threshold = threshold_ns
        self._precision = precision_ns
        self._now = 0
        # The simulated-CPU "busy until" cursor; delay = cursor - now.
        self._time_cursor = 0

    def update_time(self, now: int) -> None:
        self._now = now
        if self._time_cursor < now:
            self._time_cursor = now

    def add_delay(self, native_ns: int) -> None:
        """Charge native execution time, scaled by the frequency ratio."""
        scaled = native_ns * self._native_freq_khz // max(1, self._sim_freq_khz)
        self._time_cursor += scaled

    def delay(self) -> int:
        """Outstanding delay to apply, 0 if below threshold. Rounded up to the
        configured precision so events don't splinter into ns-grade wakeups."""
        if self._threshold is None:
            return 0
        raw = self._time_cursor - self._now
        if raw <= self._threshold:
            return 0
        if self._precision:
            raw = -(-raw // self._precision) * self._precision
        return raw
