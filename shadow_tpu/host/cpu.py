"""Simulated-CPU time accounting.

Parity: reference `src/main/host/cpu.rs:8-95` — native execution time spent
by managed code is charged to a simulated CPU at a configured frequency
ratio; when the accumulated unapplied delay exceeds a threshold, event
execution is pushed into the future, modelling an oversubscribed CPU.
Charged delays are rounded to the configured precision (nearest, up at the
midpoint — `cpu.rs:62-76`); the reported delay is the raw backlog.
"""

from __future__ import annotations

from typing import Optional


class Cpu:
    __slots__ = ("_sim_freq_khz", "_native_freq_khz", "threshold",
                 "_precision", "_now", "_time_cursor")

    def __init__(
        self,
        sim_frequency_khz: int,
        native_frequency_khz: int,
        threshold_ns: Optional[int],
        precision_ns: Optional[int],
    ):
        if precision_ns is not None and precision_ns < 0:
            raise ValueError("cpu_precision must be >= 0 (0 = no rounding)")
        self._sim_freq_khz = sim_frequency_khz
        self._native_freq_khz = native_frequency_khz
        self.threshold = threshold_ns  # None = model disabled (`cpu.rs:83`)
        self._precision = precision_ns
        self._now = 0
        # The simulated-CPU "busy until" cursor; delay = cursor - now.
        self._time_cursor = 0

    def update_time(self, now: int) -> None:
        self._now = now
        if self._time_cursor < now:
            self._time_cursor = now

    def add_delay(self, native_ns: int) -> None:
        """Charge native execution time, scaled by the frequency ratio and
        rounded to the precision (nearest, ties up — `cpu.rs:62-76`)."""
        scaled = native_ns * self._native_freq_khz // max(1, self._sim_freq_khz)
        if self._precision:
            rem = scaled % self._precision
            scaled -= rem
            if rem * 2 >= self._precision:
                scaled += self._precision
        self._time_cursor += scaled

    def delay(self) -> int:
        """Outstanding delay to apply; 0 when disabled or below threshold
        (`cpu.rs:81-95`)."""
        if self.threshold is None:
            return 0
        raw = self._time_cursor - self._now
        if raw <= self.threshold:
            return 0
        return raw
