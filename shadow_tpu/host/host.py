"""The simulated machine.

Parity: reference `src/main/host/host.rs` — a Host owns its event queue, RNG
(seeded from config, `host.rs:233`), a router plus three relays (inet-out
rate-limited by up-bandwidth, inet-in by down-bandwidth, loopback unlimited,
`host.rs:295-311`), a network namespace, monotone counters that feed the
deterministic event/packet ordering (`host.rs:159-168,679-720`), an optional
CPU model, and its applications. `Host::execute` (`host.rs:810-865`) is the
inner hot loop: pop events below the round end; packet events enter the
router's CoDel queue and wake the inet-in relay; local events run their task.
"""

from __future__ import annotations

import threading
from time import perf_counter_ns as _perf_ns
from typing import Callable, Optional

from ..core.config import QDiscMode
from ..core.event import Event, EventQueue, TaskRef
from ..core.rng import Xoshiro256pp
from ..net.namespace import NetworkNamespace
from ..net.packet import Packet, PacketStatus
from ..net.relay import Relay
from ..net.router import Router
from .cpu import Cpu


class Host:
    def __init__(
        self,
        *,
        host_id: int,
        name: str,
        ip: str,
        node_id: int,
        seed: int,
        bandwidth_down_bps: int,
        bandwidth_up_bps: int,
        qdisc: QDiscMode = QDiscMode.FIFO,
        cpu: Optional[Cpu] = None,
        pcap_factory=None,
        experimental=None,
        model_unblocked_syscall_latency: bool = False,
    ):
        self.host_id = host_id
        self.name = name
        self.ip = ip
        self.node_id = node_id
        self.rng = Xoshiro256pp(seed)
        self.cpu = cpu
        # ExperimentalOptions (socket buffer sizes/autotuning, TCP selection);
        # sockets read their defaults from here.
        self.config_experimental = experimental
        # `general.model_unblocked_syscall_latency` (`configuration.rs`):
        # gates the in-shim latency accumulator for managed processes
        self.model_unblocked_syscall_latency = model_unblocked_syscall_latency

        self.event_queue = EventQueue()
        self._queue_lock = threading.Lock()  # cross-thread packet pushes
        self._cross_lock = threading.Lock()  # cross-thread task posts
        self._cross_pending: list[TaskRef] = []
        # Active-host tracking (the Manager's round heap): any event push
        # appends this host once per round to the dirty sink so the
        # Manager re-keys it at the barrier; hosts with no events before
        # the round end are never iterated at all (at 1k+ hosts the
        # idle-poll loop used to dominate the round cost).
        self._dirty = False
        self._dirty_sink: Optional[list] = None
        self._cross_sink: Optional[list] = None
        self._cached_next: Optional[int] = None  # Manager heap key

        # Deterministic ordering counters (`host.rs:159-168`).
        self._local_event_id = 0
        self._packet_event_id = 0
        self._packet_priority = 0
        self.n_events_executed = 0  # summed into SimStats at teardown
        # perf timers (`host.rs:142-143,722-730`): wall ns spent in
        # execute(), accumulated only when the experimental knob is on
        self._perf_enabled = bool(experimental is not None and getattr(
            experimental, "use_perf_timers", False))
        self.execution_ns = 0
        # virtual PID allocation base (process.FIRST_PID; not imported to
        # keep host free of process-plane dependencies)
        self._next_pid = 1000

        # Clock: maintained by execute(); relays and sockets read it.
        self._now = 0
        # The worker currently executing this host (set by the scheduler).
        self._worker = None
        # Fault plane (faults/schedule.py): True between a host_crash
        # and its host_reboot. A down host executes nothing, accepts no
        # packet events, and its crash purged the queue.
        self.fault_down = False
        self.fault_packets_dropped = 0

        self.netns = NetworkNamespace(ip, qdisc, pcap_factory)
        # The router's address is the unspecified address (`host.rs:298`):
        # get_packet_device maps any non-local address to it, and relays'
        # "local delivery" checks (src address == packet dst) never match it.
        self.router = Router("0.0.0.0", self._send_packet_out, self.now)
        # bits/sec -> bytes/sec for the relay rate limiters
        self.relay_inet_out = Relay(self, ip, bandwidth_up_bps // 8)
        self.relay_inet_in = Relay(self, "0.0.0.0", bandwidth_down_bps // 8)
        self.relay_loopback = Relay(self, "127.0.0.1", None)
        self._in_notify_socket_has_packets = False

        # Applications: (start_time, callable(host)) pairs added before boot.
        self._applications: list[tuple[int, Callable]] = []
        self.processes: list = []  # populated by the process plane

    # -- relay/host environment protocol ------------------------------------

    def now(self) -> int:
        return self._now

    def is_bootstrapping(self) -> bool:
        return self._worker.is_bootstrapping() if self._worker else False

    def get_packet_device(self, ip: str):
        """The host's routing table (`host.rs:965-973`): local interfaces for
        local addresses, the router for everything else."""
        iface = self.netns.interface_for(ip)
        return iface if iface is not None else self.router

    def schedule_relay_task(self, callback: Callable[[], None], delay_ns: int) -> None:
        self.schedule_task_with_delay(TaskRef(lambda host: callback(), "relay"), delay_ns)

    def _send_packet_out(self, packet: Packet) -> None:
        """Router egress → the simulated internet via the worker."""
        self._worker.send_packet(self, packet)

    # -- counters -----------------------------------------------------------

    def next_packet_event_id(self) -> int:
        self._packet_event_id += 1
        return self._packet_event_id

    def next_pid(self) -> int:
        pid = self._next_pid
        self._next_pid += 1
        return pid

    def dns_lookup(self, name: str):
        """Simulated DNS (the worker holds the global registry)."""
        if self._worker is None:
            return None
        return self._worker.shared.dns.name_to_ip(name)

    def get_next_packet_priority(self) -> int:
        self._packet_priority += 1
        return self._packet_priority

    # -- scheduling ---------------------------------------------------------

    def _mark_dirty(self) -> None:
        """Caller holds _queue_lock (or the host is quiescent)."""
        if not self._dirty and self._dirty_sink is not None:
            self._dirty = True
            self._dirty_sink.append(self)

    def schedule_task_at(self, task: TaskRef, time_ns: int) -> None:
        assert time_ns >= self._now, "cannot schedule into the past"
        self._local_event_id += 1
        with self._queue_lock:
            self.event_queue.push(Event.new_local(time_ns, task, self._local_event_id))
            self._mark_dirty()

    def schedule_task_with_delay(self, task: TaskRef, delay_ns: int) -> None:
        self.schedule_task_at(task, self._now + delay_ns)

    def push_packet_event(
        self, packet: Packet, time_ns: int, src_host_id: int, src_event_id: int
    ) -> None:
        """Called from ANY worker thread (`worker.rs:629-639`)."""
        if self.fault_down:
            # crashed destination: the packet event is lost, bucketed as
            # a fault drop (never wire loss). Guards the device-transport
            # release path too — the send-side filter in Worker.send_packet
            # can't see a crash that happened after capture. The counter
            # update takes the queue lock: this runs on ANY worker thread.
            packet.add_status(PacketStatus.FAULT_DROPPED)
            with self._queue_lock:
                self.fault_packets_dropped += 1
            return
        with self._queue_lock:
            self.event_queue.push(
                Event.new_packet(time_ns, packet, src_host_id, src_event_id)
            )
            self._mark_dirty()

    def post_cross_thread_task(self, task: TaskRef) -> None:
        """Queue a task from a non-worker thread (the ChildPidWatcher
        reporting a managed-process death). Posted tasks cannot go straight
        into the event queue — the poster can't observe a coherent host
        clock, and a stale-timestamped event would break the monotonic-pop
        invariant — so the Manager drains them at the next round boundary
        (`drain_cross_thread_tasks`), when the host is quiescent."""
        with self._cross_lock:
            if not self._cross_pending and self._cross_sink is not None:
                self._cross_sink.append(self)
            self._cross_pending.append(task)

    def drain_cross_thread_tasks(self) -> Optional[int]:
        """Round-boundary drain (called by the Manager between rounds, no
        worker running this host): schedules every posted task at the host
        clock and returns that time, or None if nothing was pending."""
        with self._cross_lock:
            pending, self._cross_pending = self._cross_pending, []
        if not pending:
            return None
        for task in pending:
            self.schedule_task_at(task, self._now)
        return self._now

    def next_event_time(self) -> Optional[int]:
        with self._queue_lock:
            return self.event_queue.next_time()

    # -- fault plane (faults/schedule.py; docs/robustness.md) ---------------

    def fault_crash(self) -> int:
        """Host crash at the current round boundary: the event queue and
        inbound router are purged (a crash loses everything), the NIC
        goes down, and no new packet events are accepted until
        `fault_reboot`. Process SIGKILLs are the Manager's job (it owns
        the process table). Returns the number of purged events."""
        self.fault_down = True
        with self._queue_lock:
            purged = self.event_queue.purge()
        for event in purged:
            if event.is_packet:
                event.payload.add_status(PacketStatus.FAULT_DROPPED)
                self.fault_packets_dropped += 1
        purged_router = self.router.purge_for_fault()
        self.fault_packets_dropped += purged_router
        self._cached_next = None  # Manager heap entries go stale lazily
        # the simulated kernel's networking state dies with the host:
        # port associations clear so respawned processes re-bind cleanly
        self.netns.purge_for_fault()
        for iface in (self.netns.internet, self.netns.localhost):
            iface.set_link_up(False)
        return len(purged) + purged_router

    def fault_reboot(self) -> None:
        """Restore connectivity after a crash. Respawning the host's
        configured processes is the Manager's job."""
        self.fault_down = False
        for iface in (self.netns.internet, self.netns.localhost):
            iface.set_link_up(True)

    def fault_set_iface(self, up: bool) -> None:
        """Administrative NIC flap (iface_down/iface_up): the internet
        interface only — loopback stays up, like pulling a cable."""
        self.netns.internet.set_link_up(up)
        if up:
            # kick the relays: backlog queued behind the downed link
            # resumes forwarding at the restore instant
            self.relay_inet_out.notify()
            self.relay_inet_in.notify()

    # -- applications -------------------------------------------------------

    def add_application(self, start_time_ns: int, app: Callable) -> None:
        """Register a callable(host) to run at `start_time_ns` (the process
        plane schedules spawns through this, `host.rs:406-454`)."""
        self._applications.append((start_time_ns, app))

    def boot(self) -> None:
        for start_time, app in self._applications:
            self.schedule_task_at(TaskRef(app, "process-start"), start_time)

    def shutdown(self) -> None:
        for proc in self.processes:
            stop = getattr(proc, "stop", None)
            if stop is not None:
                stop()

    # -- the inner hot loop (`host.rs:810-865`) ------------------------------

    def execute(self, until_ns: int) -> None:
        if self._perf_enabled:
            t0 = _perf_ns()  # shadowlint: disable=SL101 -- opt-in host-exec profiling stat
            try:
                self._execute(until_ns)
            finally:
                # shadowlint: disable=SL101 -- opt-in host-exec profiling stat
                self.execution_ns += _perf_ns() - t0
        else:
            self._execute(until_ns)

    def _execute(self, until_ns: int) -> None:
        while True:
            with self._queue_lock:
                nxt = self.event_queue.next_time()
                if nxt is None or nxt >= until_ns:
                    return
                event = self.event_queue.pop()

            self._now = event.time
            if self._worker is not None:
                self._worker.current_time = event.time

            # CPU oversubscription can push the event into the future
            # (`host.rs:821-849`).
            if self.cpu is not None and self.cpu.threshold is not None:
                self.cpu.update_time(event.time)
                delay = self.cpu.delay()
                if delay > 0:
                    new_time = event.time + delay
                    if event.is_packet:
                        with self._queue_lock:
                            self.event_queue.push(
                                Event.new_packet(
                                    new_time, event.payload, event.key[0], event.key[1]
                                )
                            )
                    else:
                        self._local_event_id += 1
                        with self._queue_lock:
                            self.event_queue.push(
                                Event.new_local(
                                    new_time, event.payload, self._local_event_id
                                )
                            )
                    continue

            # counted here, after the deferral check, so a CPU-deferred
            # event is not tallied twice
            self.n_events_executed += 1
            if event.is_packet:
                self.router.route_incoming_packet(event.payload)
                self.notify_router_has_packets()
            else:
                event.payload.execute(self)

    # -- notifications ------------------------------------------------------

    def notify_router_has_packets(self) -> None:
        self.relay_inet_in.notify()

    def notify_socket_has_packets(self, ip: str, socket) -> None:
        """A socket has data to send on the interface with address `ip`
        (`host.rs:988-1002`). Not reentrant (recursion guard mirrors
        `host.rs:989-991`)."""
        if self._in_notify_socket_has_packets:
            raise AssertionError("recursive notify_socket_has_packets")
        self._in_notify_socket_has_packets = True
        try:
            iface = self.netns.interface_for(ip)
            if iface is None:
                return
            iface.add_data_source(socket)
            if iface is self.netns.localhost:
                self.relay_loopback.notify()
            else:
                self.relay_inet_out.notify()
        finally:
            self._in_notify_socket_has_packets = False
