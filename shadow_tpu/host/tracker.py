"""Per-host traffic/heartbeat tracking.

Parity: reference `src/main/host/tracker.c` — per-host counters (packets
and bytes, in/out, by protocol) logged as heartbeat lines at
`host_heartbeat_interval`, feeding log-parsing tools. Counters hook the
packet status-trace stream, the same instrumentation point the reference's
`PacketCounter`/`ByteCounter` use.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field

from ..core.event import TaskRef
from ..net.packet import Packet, PacketStatus, Protocol

log = logging.getLogger("shadow_tpu.tracker")


@dataclass
class Counters:
    packets_in: int = 0
    packets_out: int = 0
    bytes_in: int = 0
    bytes_out: int = 0
    packets_dropped: int = 0
    # injected fault-plane drops (FAULT_DROPPED), kept APART from
    # packets_dropped so an injected outage is never misread as wire
    # loss (docs/robustness.md drop taxonomy)
    packets_dropped_fault: int = 0
    retransmitted: int = 0
    by_protocol: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        # by_protocol sorted by key: its insertion order is first-packet
        # order, which varies across seeds — sorted serialization keeps
        # heartbeat log diffs between seeds stable
        return {
            "packets_in": self.packets_in,
            "packets_out": self.packets_out,
            "bytes_in": self.bytes_in,
            "bytes_out": self.bytes_out,
            "packets_dropped": self.packets_dropped,
            "packets_dropped_fault": self.packets_dropped_fault,
            "retransmitted": self.retransmitted,
            "by_protocol": dict(sorted(self.by_protocol.items())),
        }


class Tracker:
    """Attach to a host; records its interface traffic and logs heartbeats."""

    def __init__(self, host, heartbeat_interval_ns: int | None):
        self.host = host
        self.counters = Counters()
        self._interval = heartbeat_interval_ns
        host.trackers = getattr(host, "trackers", [])
        host.trackers.append(self)

    def start(self) -> None:
        if self._interval:
            self.host.schedule_task_with_delay(
                TaskRef(self._heartbeat, "tracker-heartbeat"), self._interval
            )

    # statuses this tracker reacts to, as plain ints — everything else
    # early-outs before touching the packet (this handler runs per
    # status transition on the hottest path in the simulator; it was
    # computing total_size() and a Protocol(...).name round-trip on all
    # ~10 transitions of every packet before looking at the status)
    _SENT = int(PacketStatus.SND_INTERFACE_SENT)
    _RCVD = int(PacketStatus.RCV_INTERFACE_RECEIVED)
    _RETX = int(PacketStatus.SND_TCP_RETRANSMITTED)
    _FAULT = int(PacketStatus.FAULT_DROPPED)
    _DROPS = frozenset((
        int(PacketStatus.INET_DROPPED), int(PacketStatus.ROUTER_DROPPED),
        int(PacketStatus.RCV_SOCKET_DROPPED),
        int(PacketStatus.RCV_INTERFACE_DROPPED),
    ))
    WANTED = frozenset({_SENT, _RCVD, _RETX, _FAULT} | _DROPS)

    def on_packet_status(self, packet: Packet, status: PacketStatus) -> None:
        s = int(status)
        c = self.counters
        if s == self._SENT:
            c.packets_out += 1
            c.bytes_out += packet.total_size()
            proto = Protocol(packet.protocol).name
            c.by_protocol[proto] = c.by_protocol.get(proto, 0) + 1
        elif s == self._RCVD:
            c.packets_in += 1
            c.bytes_in += packet.total_size()
        elif s in self._DROPS:
            c.packets_dropped += 1
        elif s == self._FAULT:
            c.packets_dropped_fault += 1
        elif s == self._RETX:
            c.retransmitted += 1

    def _heartbeat(self, host) -> None:
        # JSON payload so parse_shadow.py can consume the line directly.
        # sort_keys + the sorted by_protocol above make the line a pure
        # function of the counter VALUES; the self-rescheduling task
        # fires for idle hosts too (zero-counter lines on a fixed
        # cadence), so heartbeat streams from different seeds diff
        # line-for-line
        log.info(
            "heartbeat host=%s time_ns=%d %s",
            self.host.name, self.host.now(),
            json.dumps(self.counters.as_dict(), sort_keys=True),
        )
        if self._interval:
            self.host.schedule_task_with_delay(
                TaskRef(self._heartbeat, "tracker-heartbeat"), self._interval
            )
