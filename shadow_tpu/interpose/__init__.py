"""Python bindings for the native runtime plane (ctypes).

Parity: this package is the C++ re-implementation of the reference's
cross-process substrate — `src/lib/shmem` (serializable shared-memory
blocks), `src/lib/vasi-sync/src/scchannel.rs` (futex rendezvous channels),
and `src/lib/shadow-shim-helper-rs/src/ipc.rs` + `shim_event.rs` (the
per-thread IPC block and event protocol). The seccomp/LD_PRELOAD shim that
rides on it is the next layer up.

Build: `make -C shadow_tpu/interpose` (pure g++, no external deps). The
bindings load lazily and raise a clear error when the library is missing,
so the Python planes work without the native build.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libshadow_ipc.so")

SHMEM_HANDLE_MAX = 128
SCCHANNEL_MSG_MAX = 1088  # keep in lockstep with scchannel.h


class ShMemBlock(ctypes.Structure):
    _fields_ = [
        ("addr", ctypes.c_void_p),
        ("size", ctypes.c_size_t),
        ("name", ctypes.c_char * 64),
        ("owner", ctypes.c_int),
    ]


class ShimSyscallArgs(ctypes.Structure):
    _fields_ = [("number", ctypes.c_int64), ("args", ctypes.c_uint64 * 6)]


class ShimSyscallComplete(ctypes.Structure):
    _fields_ = [
        ("retval", ctypes.c_int64),
        ("restartable", ctypes.c_uint32),
        ("_pad", ctypes.c_uint32),
    ]


class ShimStartReq(ctypes.Structure):
    _fields_ = [
        ("host_shmem_handle", ctypes.c_char * SHMEM_HANDLE_MAX),
        ("process_shmem_handle", ctypes.c_char * SHMEM_HANDLE_MAX),
        ("thread_shmem_handle", ctypes.c_char * SHMEM_HANDLE_MAX),
    ]


class ShimAddThreadReq(ctypes.Structure):
    _fields_ = [
        ("ipc_handle", ctypes.c_char * SHMEM_HANDLE_MAX),
        ("flags", ctypes.c_uint64),
        ("child_stack", ctypes.c_uint64),
        ("ptid", ctypes.c_uint64),
        ("ctid", ctypes.c_uint64),
        ("newtls", ctypes.c_uint64),
    ]


class ShimAddThreadRes(ctypes.Structure):
    _fields_ = [("child_native_tid", ctypes.c_int64)]


SHIM_REWRITE_PATH_MAX = 400


class ShimSyscallRewrite(ctypes.Structure):
    _fields_ = [
        ("args", ctypes.c_uint64 * 6),
        ("path_arg", ctypes.c_int32 * 2),
        ("path", (ctypes.c_char * SHIM_REWRITE_PATH_MAX) * 2),
    ]


class _ShimEventUnion(ctypes.Union):
    _fields_ = [
        ("syscall", ShimSyscallArgs),
        ("rewrite", ShimSyscallRewrite),
        ("complete", ShimSyscallComplete),
        ("start_req", ShimStartReq),
        ("add_thread_req", ShimAddThreadReq),
        ("add_thread_res", ShimAddThreadRes),
    ]


class ShimEvent(ctypes.Structure):
    _fields_ = [
        ("kind", ctypes.c_uint32),
        ("_pad", ctypes.c_uint32),
        ("sim_time_ns", ctypes.c_uint64),
        ("u", _ShimEventUnion),
    ]


# ShimEventKind values (ipc.h)
EVENT_NONE = 0
EVENT_START_REQ = 1
EVENT_SYSCALL_COMPLETE = 2
EVENT_SYSCALL_DO_NATIVE = 3
EVENT_ADD_THREAD_REQ = 4
EVENT_START_RES = 5
EVENT_SYSCALL = 6
EVENT_ADD_THREAD_RES = 7
EVENT_PROCESS_DEATH = 8
EVENT_SYSCALL_DO_NATIVE_REWRITE = 9

_lib: Optional[ctypes.CDLL] = None


SHIM_LIB_PATH = os.path.join(_DIR, "libshadow_shim.so")
PRELOAD_LIBC_LIB_PATH = os.path.join(_DIR, "libshadow_preload_libc.so")
PRELOAD_OPENSSL_LIB_PATH = os.path.join(_DIR, "libshadow_preload_openssl.so")


_built_this_process = False


def build(force: bool = False) -> str:
    """Build the native libraries with make; returns the IPC lib path.

    Runs make once per process even when the .so files exist — make's
    dependency check is what detects STALE libraries after a source edit
    (an exists()-only check shipped checkouts with outdated preloads)."""
    global _built_this_process
    if force or not _built_this_process:
        subprocess.run(
            ["make", "-C", _DIR], check=True, capture_output=True, text=True
        )
        _built_this_process = True
    return _LIB_PATH


def load() -> ctypes.CDLL:
    """Load (building if needed) and configure the library."""
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        try:
            build()
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"native IPC library build failed (run `make -C {_DIR}`):\n"
                f"{e.stderr}"
            ) from e
        except Exception as e:
            raise RuntimeError(
                f"native IPC library not built and build failed: {e}; "
                f"run `make -C {_DIR}`"
            ) from e
    lib = ctypes.CDLL(_LIB_PATH)
    lib.shmem_alloc.argtypes = [ctypes.c_size_t, ctypes.POINTER(ShMemBlock)]
    lib.shmem_alloc.restype = ctypes.c_int
    lib.shmem_serialize.argtypes = [ctypes.POINTER(ShMemBlock), ctypes.c_char_p]
    lib.shmem_serialize.restype = ctypes.c_int
    lib.shmem_deserialize.argtypes = [ctypes.c_char_p, ctypes.POINTER(ShMemBlock)]
    lib.shmem_deserialize.restype = ctypes.c_int
    lib.shmem_free.argtypes = [ctypes.POINTER(ShMemBlock)]
    lib.shmem_free.restype = ctypes.c_int
    lib.shmem_cleanup.restype = ctypes.c_int
    for name in ("ipc_to_shim_send", "ipc_to_shadow_send"):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_void_p, ctypes.POINTER(ShimEvent)]
        fn.restype = ctypes.c_int
    for name in ("ipc_to_shim_recv", "ipc_to_shadow_recv"):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_void_p, ctypes.POINTER(ShimEvent)]
        fn.restype = ctypes.c_long
    lib.ipc_to_shadow_recv_timed.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ShimEvent), ctypes.c_int64]
    lib.ipc_to_shadow_recv_timed.restype = ctypes.c_long
    lib.ipc_init.argtypes = [ctypes.c_void_p]
    lib.ipc_close.argtypes = [ctypes.c_void_p]
    lib.ipc_sizeof.restype = ctypes.c_uint64
    lib.shim_event_sizeof.restype = ctypes.c_uint64
    _lib = lib
    return lib


class SharedBlock:
    """RAII wrapper over ShMemBlock."""

    def __init__(self, size: Optional[int] = None, handle: Optional[str] = None):
        self._lib = load()
        self.block = ShMemBlock()
        if handle is not None:
            rc = self._lib.shmem_deserialize(handle.encode(), ctypes.byref(self.block))
        else:
            rc = self._lib.shmem_alloc(size, ctypes.byref(self.block))
        if rc != 0:
            raise OSError(f"shmem {'map' if handle else 'alloc'} failed")

    @property
    def addr(self) -> int:
        return self.block.addr

    @property
    def size(self) -> int:
        return self.block.size

    def serialize(self) -> str:
        buf = ctypes.create_string_buffer(SHMEM_HANDLE_MAX)
        if self._lib.shmem_serialize(ctypes.byref(self.block), buf) != 0:
            raise OSError("shmem_serialize failed")
        return buf.value.decode()

    def free(self) -> None:
        if self.block.addr:
            self._lib.shmem_free(ctypes.byref(self.block))


class ProcessShmemStruct(ctypes.Structure):
    """Mirror of ProcessShmem in shim_shmem.h."""

    _fields_ = [
        ("sim_time_ns", ctypes.c_uint64),
        ("max_runahead_ns", ctypes.c_uint64),
        ("epoch_offset_ns", ctypes.c_uint64),
        ("syscall_latency_ns", ctypes.c_uint64),
        ("enabled", ctypes.c_uint32),
        ("_pad", ctypes.c_uint32),
    ]


class ProcessClock:
    """Simulator-side view of one managed process's shared clock block
    (the in-shim time fast path, `shim_sys.c:25-80`). Single-writer
    alternation with the shim: only touch it while the shim is blocked in
    recv (i.e. from the worker thread that owns the process)."""

    def __init__(self):
        load()  # ensures the library (and shmem symbols) exist
        self.block = SharedBlock(size=ctypes.sizeof(ProcessShmemStruct))
        self._view = ProcessShmemStruct.from_address(self.block.addr)
        self._view.enabled = 0

    def configure(self, epoch_offset_ns: int, syscall_latency_ns: int) -> None:
        self._view.epoch_offset_ns = epoch_offset_ns
        self._view.syscall_latency_ns = syscall_latency_ns

    def publish(self, sim_time_ns: int, max_runahead_ns: int) -> None:
        """Called before handing control to the shim: the clock only moves
        forward (the shim may have advanced it past the host clock)."""
        if sim_time_ns > self._view.sim_time_ns:
            self._view.sim_time_ns = sim_time_ns
        self._view.max_runahead_ns = max_runahead_ns
        self._view.enabled = 1

    @property
    def sim_time_ns(self) -> int:
        return int(self._view.sim_time_ns)

    def serialize(self) -> str:
        return self.block.serialize()

    def free(self) -> None:
        self._view = None
        self.block.free()


class IpcChannel:
    """The per-thread IPCData block, shadow side or shim side."""

    def __init__(self, block: SharedBlock, init: bool = False):
        self._lib = load()
        self.block = block
        if block.size < self._lib.ipc_sizeof():
            raise ValueError("shmem block too small for IPCData")
        if init:
            self._lib.ipc_init(block.addr)

    @classmethod
    def create(cls) -> "IpcChannel":
        lib = load()
        return cls(SharedBlock(size=int(lib.ipc_sizeof())), init=True)

    @classmethod
    def attach(cls, handle: str) -> "IpcChannel":
        return cls(SharedBlock(handle=handle), init=False)

    def send_to_shim(self, ev: ShimEvent) -> None:
        if self._lib.ipc_to_shim_send(self.block.addr, ctypes.byref(ev)) != 0:
            raise OSError("ipc send failed")

    def recv_from_shadow(self) -> Optional[ShimEvent]:
        ev = ShimEvent()
        n = self._lib.ipc_to_shim_recv(self.block.addr, ctypes.byref(ev))
        return ev if n >= 0 else None

    def send_to_shadow(self, ev: ShimEvent) -> None:
        if self._lib.ipc_to_shadow_send(self.block.addr, ctypes.byref(ev)) != 0:
            raise OSError("ipc send failed")

    def recv_from_shim(self) -> Optional[ShimEvent]:
        ev = ShimEvent()
        n = self._lib.ipc_to_shadow_recv(self.block.addr, ctypes.byref(ev))
        return ev if n >= 0 else None

    def recv_from_shim_timed(self, timeout_ns: int) -> Optional[ShimEvent]:
        """Bounded recv: the event, None when the writer closed, or
        TimeoutError after timeout_ns of wall time with nothing sent."""
        ev = ShimEvent()
        n = self._lib.ipc_to_shadow_recv_timed(self.block.addr,
                                               ctypes.byref(ev), timeout_ns)
        if n == -2:
            raise TimeoutError
        return ev if n >= 0 else None

    def close(self) -> None:
        self._lib.ipc_close(self.block.addr)
