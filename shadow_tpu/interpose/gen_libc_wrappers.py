#!/usr/bin/env python3
"""Generate preload_libc.gen.c: libc symbol overrides that call the shim's
syscall entry directly instead of paying a seccomp SIGSYS round trip.

Parity: reference `src/lib/preload-libc/gen_syscall_wrappers_c.py` — the
LD_PRELOADed wrappers shadow the libc functions applications actually
call, forward to `shadow_tpu_api_syscall` (exported by libshadow_shim.so,
also in the preload chain), translate the raw kernel retval into the
libc errno convention, and fall back to a raw syscall when the shim is
not interposing. glibc-internal (hidden-symbol) calls still take the
seccomp path — same limitation as the reference.

Run by the Makefile; the output is a build artifact, not a source file.
"""

# (c_return_type, libc_name, syscall_nr, [c_params])
# syscall args are passed positionally; missing trailing args become 0.
WRAPPERS = [
    # time (the hot path — answered in-shim with zero kernel entries)
    ("int", "clock_gettime", 228, ["int clk", "void *ts"]),
    ("int", "gettimeofday", 96, ["void *tv", "void *tz"]),
    ("long", "time", 201, ["long *t"]),
    ("int", "nanosleep", 35, ["const void *req", "void *rem"]),
    # unistd
    ("long", "read", 0, ["int fd", "void *buf", "unsigned long n"]),
    ("long", "write", 1, ["int fd", "const void *buf", "unsigned long n"]),
    ("int", "close", 3, ["int fd"]),
    ("int", "dup", 32, ["int fd"]),
    ("int", "dup2", 33, ["int oldfd", "int newfd"]),
    ("int", "getpid", 39, []),
    ("long", "readv", 19, ["int fd", "const void *iov", "int cnt"]),
    ("long", "writev", 20, ["int fd", "const void *iov", "int cnt"]),
    # sockets
    ("int", "socket", 41, ["int dom", "int type", "int proto"]),
    ("int", "connect", 42, ["int fd", "const void *addr", "unsigned al"]),
    ("int", "accept", 43, ["int fd", "void *addr", "void *al"]),
    ("int", "accept4", 288, ["int fd", "void *addr", "void *al", "int fl"]),
    ("long", "sendto", 44,
     ["int fd", "const void *buf", "unsigned long n", "int flags",
      "const void *addr", "unsigned al"]),
    ("long", "recvfrom", 45,
     ["int fd", "void *buf", "unsigned long n", "int flags", "void *addr",
      "void *al"]),
    ("long", "sendmsg", 46, ["int fd", "const void *msg", "int flags"]),
    ("long", "recvmsg", 47, ["int fd", "void *msg", "int flags"]),
    ("int", "shutdown", 48, ["int fd", "int how"]),
    ("int", "bind", 49, ["int fd", "const void *addr", "unsigned al"]),
    ("int", "listen", 50, ["int fd", "int backlog"]),
    ("int", "getsockname", 51, ["int fd", "void *addr", "void *al"]),
    ("int", "getpeername", 52, ["int fd", "void *addr", "void *al"]),
    ("int", "setsockopt", 54,
     ["int fd", "int lvl", "int opt", "const void *val", "unsigned vl"]),
    ("int", "getsockopt", 55,
     ["int fd", "int lvl", "int opt", "void *val", "void *vl"]),
    # readiness
    ("int", "poll", 7, ["void *fds", "unsigned long nfds", "int timeout"]),
    ("int", "select", 23,
     ["int nfds", "void *r", "void *w", "void *e", "void *tv"]),
    ("int", "epoll_create", 213, ["int size"]),
    ("int", "epoll_create1", 291, ["int flags"]),
    ("int", "epoll_ctl", 233, ["int ep", "int op", "int fd", "void *ev"]),
    ("int", "epoll_wait", 232,
     ["int ep", "void *evs", "int maxev", "int timeout"]),
    # misc
    ("long", "getrandom", 318, ["void *buf", "unsigned long n", "unsigned fl"]),
    # file family (per-host cwd makes relative paths host-local; these
    # skip the SIGSYS trap on the way to the native kernel)
    ("int", "unlink", 87, ["const char *p"]),
    ("int", "unlinkat", 263, ["int dfd", "const char *p", "int flags"]),
    ("int", "rename", 82, ["const char *a", "const char *b"]),
    ("int", "renameat", 264,
     ["int da", "const char *a", "int db", "const char *b"]),
    ("int", "mkdir", 83, ["const char *p", "unsigned mode"]),
    ("int", "mkdirat", 258, ["int dfd", "const char *p", "unsigned mode"]),
    ("int", "rmdir", 84, ["const char *p"]),
    ("int", "chdir", 80, ["const char *p"]),
    ("int", "fchdir", 81, ["int fd"]),
    ("int", "link", 86, ["const char *a", "const char *b"]),
    ("int", "symlink", 88, ["const char *a", "const char *b"]),
    ("long", "readlink", 89, ["const char *p", "char *buf",
                              "unsigned long n"]),
    ("int", "chmod", 90, ["const char *p", "unsigned mode"]),
    ("int", "fchmod", 91, ["int fd", "unsigned mode"]),
    ("int", "chown", 92, ["const char *p", "unsigned u", "unsigned g"]),
    ("int", "fchown", 93, ["int fd", "unsigned u", "unsigned g"]),
    ("int", "lchown", 94, ["const char *p", "unsigned u", "unsigned g"]),
    ("int", "access", 21, ["const char *p", "int mode"]),
    ("int", "faccessat", 269, ["int dfd", "const char *p", "int mode"]),
    ("int", "truncate", 76, ["const char *p", "long n"]),
    ("int", "ftruncate", 77, ["int fd", "long n"]),
    ("int", "fsync", 74, ["int fd"]),
    ("int", "fdatasync", 75, ["int fd"]),
    ("int", "flock", 73, ["int fd", "int op"]),
    ("long", "lseek", 8, ["int fd", "long off", "int whence"]),
    ("long", "pread", 17, ["int fd", "void *buf", "unsigned long n",
                           "long off"]),
    ("long", "pwrite", 18, ["int fd", "const void *buf", "unsigned long n",
                            "long off"]),
    ("long", "preadv", 295, ["int fd", "const void *iov", "int cnt",
                             "long off"]),
    ("long", "pwritev", 296, ["int fd", "const void *iov", "int cnt",
                              "long off"]),
    ("long", "copy_file_range", 326,
     ["int fin", "void *offin", "int fout", "void *offout",
      "unsigned long n", "unsigned fl"]),
    ("long", "sendfile", 40, ["int out", "int in", "void *off",
                              "unsigned long n"]),
    ("long", "getdents64", 217, ["int fd", "void *dirp", "unsigned long n"]),
    ("int", "dup3", 292, ["int oldfd", "int newfd", "int flags"]),
    ("int", "pipe", 22, ["int *fds"]),
    ("int", "pipe2", 293, ["int *fds", "int flags"]),
    ("int", "statfs", 137, ["const char *p", "void *buf"]),
    ("int", "fstatfs", 138, ["int fd", "void *buf"]),
    ("unsigned", "umask", 95, ["unsigned mask"]),
    # descriptors / events
    ("int", "eventfd", 290, ["unsigned init", "int flags"]),
    ("int", "timerfd_create", 283, ["int clk", "int flags"]),
    ("int", "timerfd_settime", 286,
     ["int fd", "int flags", "const void *new", "void *old"]),
    ("int", "timerfd_gettime", 287, ["int fd", "void *cur"]),
    ("int", "inotify_init", 253, []),
    ("int", "inotify_init1", 294, ["int flags"]),
    ("int", "inotify_add_watch", 254,
     ["int fd", "const char *p", "unsigned mask"]),
    ("int", "inotify_rm_watch", 255, ["int fd", "int wd"]),
    # memory
    ("void *", "mmap", 9,
     ["void *addr", "unsigned long n", "int prot", "int flags", "int fd",
      "long off"]),
    ("int", "munmap", 11, ["void *addr", "unsigned long n"]),
    ("int", "mprotect", 10, ["void *addr", "unsigned long n", "int prot"]),
    ("int", "madvise", 28, ["void *addr", "unsigned long n", "int adv"]),
    ("int", "msync", 26, ["void *addr", "unsigned long n", "int flags"]),
    ("int", "mlock", 149, ["const void *addr", "unsigned long n"]),
    ("int", "munlock", 150, ["const void *addr", "unsigned long n"]),
    ("int", "mlockall", 151, ["int flags"]),
    ("int", "munlockall", 152, []),
    # identity / process info (virtualized by the simulated kernel)
    ("unsigned", "getuid", 102, []),
    ("unsigned", "geteuid", 107, []),
    ("unsigned", "getgid", 104, []),
    ("unsigned", "getegid", 108, []),
    ("int", "setuid", 105, ["unsigned u"]),
    ("int", "setgid", 106, ["unsigned g"]),
    ("int", "getgroups", 115, ["int n", "unsigned *list"]),
    ("int", "getresuid", 118, ["unsigned *r", "unsigned *e", "unsigned *s"]),
    ("int", "getresgid", 120, ["unsigned *r", "unsigned *e", "unsigned *s"]),
    ("int", "getppid", 110, []),
    ("int", "getpgid", 121, ["int pid"]),
    ("int", "getpgrp", 111, []),
    ("int", "setpgid", 109, ["int pid", "int pgid"]),
    ("int", "getsid", 124, ["int pid"]),
    ("int", "setsid", 112, []),
    ("int", "gettid", 186, []),
    ("int", "getrlimit", 97, ["int res", "void *rl"]),
    ("int", "setrlimit", 160, ["int res", "const void *rl"]),
    ("int", "prlimit64", 302,
     ["int pid", "int res", "const void *new", "void *old"]),
    ("int", "getrusage", 98, ["int who", "void *ru"]),
    ("int", "sysinfo", 99, ["void *info"]),
    ("int", "uname", 63, ["void *buf"]),
    ("int", "sethostname", 170, ["const char *n", "unsigned long len"]),
    # scheduling
    ("int", "sched_yield", 24, []),
    ("int", "sched_getscheduler", 145, ["int pid"]),
    ("int", "sched_getparam", 143, ["int pid", "void *param"]),
    # time
    ("int", "clock_getres", 229, ["int clk", "void *res"]),
    ("unsigned", "alarm", 37, ["unsigned sec"]),
    ("int", "getitimer", 36, ["int which", "void *cur"]),
    ("int", "setitimer", 38, ["int which", "const void *new", "void *old"]),
    ("long", "times", 100, ["void *buf"]),
    ("int", "pause", 34, []),
    # signals / processes (thin-syscall symbols only: no fork/pthread —
    # glibc bookkeeping — and no sigaction — kernel/libc struct skew)
    ("int", "kill", 62, ["int pid", "int sig"]),
    ("int", "waitid", 247,
     ["int idtype", "unsigned id", "void *info", "int opts"]),
    ("long", "wait4", 61,
     ["int pid", "int *status", "int opts", "void *ru"]),
    # sockets (batch calls)
    ("int", "socketpair", 53,
     ["int dom", "int type", "int proto", "int *sv"]),
    ("int", "sendmmsg", 307,
     ["int fd", "void *msgs", "unsigned n", "int flags"]),
    ("int", "recvmmsg", 299,
     ["int fd", "void *msgs", "unsigned n", "int flags", "void *timeout"]),
]

# libc-only names forwarded to a different syscall with fixed extra args
ALIASES = [
    # recv/send are recvfrom/sendto with a null address
    ("long", "recv", 45, ["int fd", "void *buf", "unsigned long n",
                          "int flags"], ["fd", "buf", "n", "flags", "0", "0"]),
    ("long", "send", 44, ["int fd", "const void *buf", "unsigned long n",
                          "int flags"], ["fd", "buf", "n", "flags", "0", "0"]),
    # LFS names: on x86_64 the plain syscalls already are 64-bit
    ("long", "lseek64", 8, ["int fd", "long off", "int whence"], None),
    ("long", "pread64", 17, ["int fd", "void *buf", "unsigned long n",
                             "long off"], None),
    ("long", "pwrite64", 18, ["int fd", "const void *buf",
                              "unsigned long n", "long off"], None),
    ("long", "preadv64", 295, ["int fd", "const void *iov", "int cnt",
                               "long off"], None),
    ("long", "pwritev64", 296, ["int fd", "const void *iov", "int cnt",
                                "long off"], None),
    ("int", "truncate64", 76, ["const char *p", "long n"], None),
    ("int", "ftruncate64", 77, ["int fd", "long n"], None),
    ("int", "statfs64", 137, ["const char *p", "void *buf"], None),
    ("int", "fstatfs64", 138, ["int fd", "void *buf"], None),
    ("void *", "mmap64", 9,
     ["void *addr", "unsigned long n", "int prot", "int flags", "int fd",
      "long off"], None),
    # wait family over wait4
    ("long", "wait", 61, ["int *status"],
     ["-1", "status", "0", "0"]),
    ("long", "waitpid", 61, ["int pid", "int *status", "int opts"],
     ["pid", "status", "opts", "0"]),
    # sigmask-taking variants: the kernel wants the sigset size (_NSIG/8)
    ("int", "ppoll", 271,
     ["void *fds", "unsigned long nfds", "const void *tmo",
      "const void *sigmask"], ["fds", "nfds", "tmo", "sigmask", "8"]),
    ("int", "epoll_pwait", 281,
     ["int ep", "void *evs", "int maxev", "int timeout",
      "const void *sigmask"],
     ["ep", "evs", "maxev", "timeout", "sigmask", "8"]),
    # creat(2) == open(O_CREAT|O_WRONLY|O_TRUNC)
    ("int", "creat", 2, ["const char *p", "unsigned mode"],
     ["p", "0x241", "mode"]),
    ("int", "creat64", 2, ["const char *p", "unsigned mode"],
     ["p", "0x241", "mode"]),
    # stat family over newfstatat(AT_FDCWD=-100 / AT_SYMLINK_NOFOLLOW)
    ("int", "stat", 262, ["const char *p", "void *buf"],
     ["-100", "p", "buf", "0"]),
    ("int", "stat64", 262, ["const char *p", "void *buf"],
     ["-100", "p", "buf", "0"]),
    ("int", "lstat", 262, ["const char *p", "void *buf"],
     ["-100", "p", "buf", "0x100"]),
    ("int", "lstat64", 262, ["const char *p", "void *buf"],
     ["-100", "p", "buf", "0x100"]),
    ("int", "fstat", 5, ["int fd", "void *buf"], None),
    ("int", "fstat64", 5, ["int fd", "void *buf"], None),
    ("int", "fstatat", 262,
     ["int dfd", "const char *p", "void *buf", "int flags"], None),
    ("int", "fstatat64", 262,
     ["int dfd", "const char *p", "void *buf", "int flags"], None),
]

# hand-written bodies: variadic signatures and non-errno return contracts
CUSTOM = r"""
#include <stdarg.h>

int open(const char *p, int flags, ...) {
    va_list ap; va_start(ap, flags);
    long mode = (flags & 0100) ? va_arg(ap, long) : 0; /* O_CREAT */
    va_end(ap);
    return (int)xlate(shadow_tpu_api_syscall(2, (long)p, flags, mode,
                                             0, 0, 0));
}
int open64(const char *p, int flags, ...) {
    va_list ap; va_start(ap, flags);
    long mode = (flags & 0100) ? va_arg(ap, long) : 0;
    va_end(ap);
    return (int)xlate(shadow_tpu_api_syscall(2, (long)p, flags, mode,
                                             0, 0, 0));
}
int openat(int dfd, const char *p, int flags, ...) {
    va_list ap; va_start(ap, flags);
    long mode = (flags & 0100) ? va_arg(ap, long) : 0;
    va_end(ap);
    return (int)xlate(shadow_tpu_api_syscall(257, dfd, (long)p, flags,
                                             mode, 0, 0));
}
int openat64(int dfd, const char *p, int flags, ...) {
    va_list ap; va_start(ap, flags);
    long mode = (flags & 0100) ? va_arg(ap, long) : 0;
    va_end(ap);
    return (int)xlate(shadow_tpu_api_syscall(257, dfd, (long)p, flags,
                                             mode, 0, 0));
}
int fcntl(int fd, int cmd, ...) {
    va_list ap; va_start(ap, cmd);
    long arg = va_arg(ap, long);
    va_end(ap);
    return (int)xlate(shadow_tpu_api_syscall(72, fd, cmd, arg, 0, 0, 0));
}
int fcntl64(int fd, int cmd, ...) {
    va_list ap; va_start(ap, cmd);
    long arg = va_arg(ap, long);
    va_end(ap);
    return (int)xlate(shadow_tpu_api_syscall(72, fd, cmd, arg, 0, 0, 0));
}
int ioctl(int fd, unsigned long req, ...) {
    va_list ap; va_start(ap, req);
    long arg = va_arg(ap, long);
    va_end(ap);
    return (int)xlate(shadow_tpu_api_syscall(16, fd, (long)req, arg,
                                             0, 0, 0));
}
int usleep(unsigned usec) {
    struct { long s; long ns; } ts = { usec / 1000000u,
                                       (long)(usec % 1000000u) * 1000 };
    return (int)xlate(shadow_tpu_api_syscall(35, (long)&ts, 0, 0, 0, 0, 0));
}
/* clock_nanosleep returns the error POSITIVELY (no errno) */
int clock_nanosleep(int clk, int flags, const void *req, void *rem) {
    long r = shadow_tpu_api_syscall(230, clk, flags, (long)req, (long)rem,
                                    0, 0);
    return r < 0 ? (int)-r : 0;
}
"""

HEADER = """\
/* GENERATED by gen_libc_wrappers.py — do not edit.
 * libc overrides calling the shim syscall entry directly (no SIGSYS). */
extern long shadow_tpu_api_syscall(long nr, long a, long b, long c,
                                   long d, long e, long f);
extern int *__errno_location(void);

static long xlate(long r) {
    if (r < 0 && r > -4096) {
        *__errno_location() = (int)-r;
        return -1;
    }
    return r;
}
"""


def emit(ret, name, nr, params, fwd=None):
    args = [p.split()[-1].lstrip("*") for p in params]
    if fwd is None:
        fwd = args
    pieces = [f"(long){a}" for a in fwd] + ["0"] * (6 - len(fwd))
    call = ", ".join(pieces)
    sig = ", ".join(params) if params else "void"
    return (f"{ret} {name}({sig}) {{\n"
            f"    return ({ret})xlate(shadow_tpu_api_syscall({nr}, {call}));\n"
            f"}}\n")


def main():
    out = [HEADER]
    names = set()
    for ret, name, nr, params in WRAPPERS:
        assert name not in names, f"duplicate wrapper {name}"
        names.add(name)
        out.append(emit(ret, name, nr, params))
    for ret, name, nr, params, fwd in ALIASES:
        assert name not in names, f"duplicate wrapper {name}"
        names.add(name)
        out.append(emit(ret, name, nr, params, fwd))
    out.append(CUSTOM)
    print("\n".join(out))


if __name__ == "__main__":
    main()
