/* Constructor-only injector (reference src/lib/preload-injector/injector.c
 * role): the combined preload library links the shim as a DT_NEEDED
 * dependency, so the dynamic linker loads it without the shim's own
 * symbols ever entering the interposition scope, and LD_PRELOAD stays at
 * ONE entry. No poke is needed to force the load: the libc wrappers in
 * the same link carry an undefined reference to shadow_tpu_api_syscall,
 * which pins the dependency even under --as-needed; the shim does its
 * own initialization in its constructor. This file exists to carry the
 * design (and a home for any future pre-main injection work) — it
 * deliberately defines NO interposable symbols. */

__attribute__((constructor, used)) static void _injector_load(void) {
    /* intentionally empty: see header comment */
}
