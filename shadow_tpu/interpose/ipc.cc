#include "ipc.h"

#include <string.h>

void ipc_init(IPCData *ipc) {
    scchannel_init(&ipc->to_shim);
    scchannel_init(&ipc->to_shadow);
}

int ipc_to_shim_send(IPCData *ipc, const ShimEvent *ev) {
    return scchannel_send(&ipc->to_shim, ev, sizeof(*ev));
}

long ipc_to_shim_recv(IPCData *ipc, ShimEvent *ev) {
    return scchannel_recv(&ipc->to_shim, ev, sizeof(*ev));
}

int ipc_to_shadow_send(IPCData *ipc, const ShimEvent *ev) {
    return scchannel_send(&ipc->to_shadow, ev, sizeof(*ev));
}

long ipc_to_shadow_recv(IPCData *ipc, ShimEvent *ev) {
    return scchannel_recv(&ipc->to_shadow, ev, sizeof(*ev));
}

long ipc_to_shadow_recv_timed(IPCData *ipc, ShimEvent *ev,
                              int64_t timeout_ns) {
    return scchannel_recv_timed(&ipc->to_shadow, ev, sizeof(*ev), timeout_ns);
}

void ipc_close(IPCData *ipc) {
    scchannel_close_writer(&ipc->to_shim);
    scchannel_close_writer(&ipc->to_shadow);
}

uint64_t ipc_sizeof(void) { return sizeof(IPCData); }
uint64_t shim_event_sizeof(void) { return sizeof(ShimEvent); }
