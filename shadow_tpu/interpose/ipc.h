/* The shim IPC vocabulary: event structs + the per-thread IPCData block.
 *
 * Parity: reference src/lib/shadow-shim-helper-rs/src/shim_event.rs
 * (ShimEventToShim / ShimEventToShadow) and ipc.rs (IPCData = one
 * shadow->plugin channel + one plugin->shadow channel, cache-line aligned
 * because false sharing between the two directions measurably hurt —
 * reference ipc.rs:10-14 / PR #2791).
 *
 * Everything here crosses address spaces, so every type must be standard
 * layout and trivially copyable with no pointers — the C++ equivalent of
 * the reference's VirtualAddressSpaceIndependent derive (src/lib/vasi).
 */
#ifndef SHADOW_TPU_IPC_H
#define SHADOW_TPU_IPC_H

#include <stdint.h>

#include "scchannel.h"
#include "vasi.h"
#include "shmem.h"

#define SHMEM_HANDLE_MAX_IPC SHMEM_HANDLE_MAX

#ifdef __cplusplus
#include <type_traits>
#endif

#ifdef __cplusplus
extern "C" {
#endif

enum ShimEventKind {
    SHIM_EVENT_NONE = 0,
    /* shadow -> shim */
    SHIM_EVENT_START_REQ = 1,
    SHIM_EVENT_SYSCALL_COMPLETE = 2,
    SHIM_EVENT_SYSCALL_DO_NATIVE = 3,
    SHIM_EVENT_ADD_THREAD_REQ = 4,
    /* shim -> shadow */
    SHIM_EVENT_START_RES = 5,
    SHIM_EVENT_SYSCALL = 6,
    SHIM_EVENT_ADD_THREAD_RES = 7,
    SHIM_EVENT_PROCESS_DEATH = 8,
    /* shadow -> shim: execute natively with substituted pointer args
     * (the simulator's per-host filesystem view rewrites path
     * arguments; the shim stages the strings on its own stack) */
    SHIM_EVENT_SYSCALL_DO_NATIVE_REWRITE = 9,
};

typedef struct ShimSyscallArgs {
    int64_t number;
    uint64_t args[6];
} ShimSyscallArgs;

#define SHIM_REWRITE_PATH_MAX 400

typedef struct ShimSyscallRewrite {
    uint64_t args[6];        /* full arg vector to execute with */
    int32_t path_arg[2];     /* arg index each path replaces; -1 = unused */
    char path[2][SHIM_REWRITE_PATH_MAX]; /* NUL-terminated */
} ShimSyscallRewrite;

typedef struct ShimSyscallComplete {
    int64_t retval;
    uint32_t restartable;
    uint32_t _pad;
} ShimSyscallComplete;

typedef struct ShimStartReq {
    /* serialized shmem handles the shim must map at startup */
    char host_shmem_handle[SHMEM_HANDLE_MAX_IPC];
    char process_shmem_handle[SHMEM_HANDLE_MAX_IPC];
    char thread_shmem_handle[SHMEM_HANDLE_MAX_IPC];
} ShimStartReq;

typedef struct ShimAddThreadReq {
    char ipc_handle[SHMEM_HANDLE_MAX_IPC];
    uint64_t flags;       /* clone flags */
    uint64_t child_stack;
    uint64_t ptid;
    uint64_t ctid;
    uint64_t newtls;
} ShimAddThreadReq;

typedef struct ShimAddThreadRes {
    int64_t child_native_tid;
} ShimAddThreadRes;

typedef struct ShimEvent {
    uint32_t kind;  /* ShimEventKind */
    uint32_t _pad;
    uint64_t sim_time_ns;  /* shim-advanced clock rides along each event */
    union {
        ShimSyscallArgs syscall;
        ShimSyscallRewrite rewrite;
        ShimSyscallComplete complete;
        ShimStartReq start_req;
        ShimAddThreadReq add_thread_req;
        ShimAddThreadRes add_thread_res;
    } u;
} ShimEvent;

#ifdef __cplusplus
#define SHIM_CACHELINE alignas(64)
#else
#define SHIM_CACHELINE _Alignas(64)
#endif

/* One per managed thread, allocated in its own shmem block. */
typedef struct IPCData {
    SHIM_CACHELINE SelfContainedChannel to_shim;    /* shadow -> plugin */
    SHIM_CACHELINE SelfContainedChannel to_shadow;  /* plugin -> shadow */
} IPCData;

void ipc_init(IPCData *ipc);
int ipc_to_shim_send(IPCData *ipc, const ShimEvent *ev);
long ipc_to_shim_recv(IPCData *ipc, ShimEvent *ev);
int ipc_to_shadow_send(IPCData *ipc, const ShimEvent *ev);
long ipc_to_shadow_recv(IPCData *ipc, ShimEvent *ev);
long ipc_to_shadow_recv_timed(IPCData *ipc, ShimEvent *ev,
                              int64_t timeout_ns);
void ipc_close(IPCData *ipc);
uint64_t ipc_sizeof(void);
uint64_t shim_event_sizeof(void);

#ifdef __cplusplus
}

SHADOW_TPU_ASSERT_VASI(ShimEvent);
SHADOW_TPU_ASSERT_VASI(ShimSyscallArgs);
SHADOW_TPU_ASSERT_VASI(ShimSyscallRewrite);
SHADOW_TPU_ASSERT_VASI(ShimSyscallComplete);
SHADOW_TPU_ASSERT_VASI(ShimStartReq);
SHADOW_TPU_ASSERT_VASI(ShimAddThreadReq);
SHADOW_TPU_ASSERT_VASI(ShimAddThreadRes);
SHADOW_TPU_ASSERT_VASI(IPCData);
static_assert(sizeof(ShimEvent) <= SCCHANNEL_MSG_MAX,
              "ShimEvent must fit one channel message");
#endif
#endif
