/* vDSO patching: force the kernel's userspace time functions onto the
 * syscall path so the seccomp filter can trap them.
 *
 * Parity: reference src/lib/shim/patch_vdso.c — locate [vdso] via the
 * auxv, walk .dynsym/.dynstr, and overwrite the entry points of
 * clock_gettime / gettimeofday / time / getcpu. The reference injects
 * jump trampolines to replacement functions; here each function is
 * overwritten *in place* with `mov eax, NR; syscall; ret` (8 bytes,
 * argument registers already correct), which avoids the reference's
 * jump-offset range fallbacks entirely: the syscall executes at a vDSO
 * instruction pointer, outside shim_text, so the filter traps it and the
 * simulator serves virtual time.
 *
 * Must run BEFORE the seccomp filter is installed (mprotect + plain libc
 * calls are used freely here).
 */

#include <elf.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>
#include <sys/auxv.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace {

struct Target {
    const char *name;
    uint32_t nr;  /* x86_64 syscall number */
};

const Target kTargets[] = {
    {"clock_gettime", 228},   {"__vdso_clock_gettime", 228},
    {"gettimeofday", 96},     {"__vdso_gettimeofday", 96},
    {"time", 201},            {"__vdso_time", 201},
    {"getcpu", 309},          {"__vdso_getcpu", 309},
    {"clock_getres", 229},    {"__vdso_clock_getres", 229},
};

const Elf64_Shdr *find_section(const Elf64_Ehdr *ehdr, const char *want) {
    if (ehdr->e_shoff == 0 || ehdr->e_shstrndx == SHN_UNDEF) return nullptr;
    const Elf64_Shdr *sections =
        (const Elf64_Shdr *)((const char *)ehdr + ehdr->e_shoff);
    const char *names =
        (const char *)ehdr + sections[ehdr->e_shstrndx].sh_offset;
    for (int i = 0; i < ehdr->e_shnum; i++) {
        if (strcmp(names + sections[i].sh_name, want) == 0) return &sections[i];
    }
    return nullptr;
}

/* mov eax, imm32; syscall; ret */
void write_stub(uint8_t *at, uint32_t nr) {
    at[0] = 0xb8;
    memcpy(at + 1, &nr, 4);
    at[5] = 0x0f;
    at[6] = 0x05;
    at[7] = 0xc3;
}

/* Some kernels export the vDSO time functions as 5-byte `jmp rel32` stubs
 * into a shared internal implementation (symbol sizes too small for our
 * 8-byte stub). Follow such jumps to the real entry before patching. */
uint8_t *resolve_entry(uint8_t *addr, uintptr_t lo, uintptr_t hi) {
    for (int hops = 0; hops < 4; hops++) {
        if ((uintptr_t)addr < lo || (uintptr_t)addr + 5 > hi) return nullptr;
        if (addr[0] != 0xe9) return addr;
        int32_t rel;
        memcpy(&rel, addr + 1, 4);
        addr = addr + 5 + rel;
    }
    return nullptr;
}

/* [vdso] bounds from /proc/self/maps (reference _getVdsoBounds). */
int vdso_bounds(uintptr_t *start, uintptr_t *end) {
    FILE *maps = fopen("/proc/self/maps", "r");
    if (!maps) return -1;
    char line[512];
    int found = -1;
    while (fgets(line, sizeof(line), maps)) {
        if (!strstr(line, "[vdso]")) continue;
        unsigned long lo, hi;
        if (sscanf(line, "%lx-%lx", &lo, &hi) == 2) {
            *start = lo;
            *end = hi;
            found = 0;
        }
        break;
    }
    fclose(maps);
    return found;
}

}  // namespace

extern "C" int shadow_tpu_patch_vdso(void) {
    const Elf64_Ehdr *ehdr = (const Elf64_Ehdr *)getauxval(AT_SYSINFO_EHDR);
    if (!ehdr) return -1;
    if (memcmp(ehdr->e_ident, ELFMAG, SELFMAG) != 0) return -1;

    const Elf64_Shdr *dynsym = find_section(ehdr, ".dynsym");
    const Elf64_Shdr *dynstr = find_section(ehdr, ".dynstr");
    if (!dynsym || !dynstr || dynsym->sh_entsize == 0) return -1;
    const Elf64_Sym *syms =
        (const Elf64_Sym *)((const char *)ehdr + dynsym->sh_offset);
    const char *strs = (const char *)ehdr + dynstr->sh_offset;
    size_t nsyms = dynsym->sh_size / dynsym->sh_entsize;

    uintptr_t base = (uintptr_t)ehdr;
    uintptr_t map_lo = 0, map_hi = 0;
    if (vdso_bounds(&map_lo, &map_hi) != 0 || base < map_lo || base >= map_hi)
        return -1;
    size_t span = map_hi - base;
    if (mprotect((void *)base, span, PROT_READ | PROT_WRITE | PROT_EXEC) != 0)
        return -1;

    int patched = 0;
    uint8_t *done_addr[16];
    uint32_t done_nr[16];
    int n_done = 0;
    for (size_t i = 0; i < nsyms; i++) {
        const char *name = strs + syms[i].st_name;
        for (const Target &t : kTargets) {
            if (strcmp(name, t.name) != 0) continue;
            if (syms[i].st_value == 0) continue;
            uint8_t *entry = resolve_entry(
                (uint8_t *)(base + syms[i].st_value), base, base + span);
            if (!entry || (uintptr_t)entry + 8 > base + span) continue;
            bool conflict = false, dup = false;
            for (int d = 0; d < n_done; d++) {
                if (done_addr[d] != entry) continue;
                if (done_nr[d] == t.nr) dup = true;
                else conflict = true;  /* two syscalls share an impl: skip */
            }
            if (dup || conflict) continue;
            write_stub(entry, t.nr);
            if (n_done < 16) {
                done_addr[n_done] = entry;
                done_nr[n_done] = t.nr;
                n_done++;
            }
            patched++;
        }
    }
    mprotect((void *)base, span, PROT_READ | PROT_EXEC);
    return patched;
}
