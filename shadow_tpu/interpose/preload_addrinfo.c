/* Simulated-DNS name resolution for managed processes.
 *
 * Parity: reference `src/lib/preload-libc/shim_api_addrinfo.c` —
 * getaddrinfo/freeaddrinfo resolved against the SIMULATION's hosts view
 * instead of the real resolver, so `curl http://server:8000/` works with
 * the simulated names. The Manager writes the hosts table (one
 * "IP name..." line per host) to a file named by SHADOW_TPU_HOSTS_FILE.
 *
 * Design: the resolver never falls through to glibc — the managed world
 * is fully simulated, names outside it don't exist (EAI_NONAME), exactly
 * the reference's posture. Numeric nodes, NULL/AI_PASSIVE, and numeric
 * services are handled inline. freeaddrinfo only ever sees our layout
 * (one malloc block per result: addrinfo + sockaddr_in back-to-back).
 */

#define _GNU_SOURCE 1
#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <errno.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <strings.h>
#include <sys/socket.h>

struct blk {
    struct addrinfo ai;
    struct sockaddr_in sa;
    char canon[64];
};

static int parse_port(const char *service, int *port_out) {
    if (!service || !*service) {
        *port_out = 0;
        return 0;
    }
    char *end = NULL;
    long p = strtol(service, &end, 10);
    if (end && *end == '\0' && p >= 0 && p <= 65535) {
        *port_out = (int)p;
        return 0;
    }
    /* common symbolic services, no NSS machinery in a preload */
    if (!strcasecmp(service, "http")) { *port_out = 80; return 0; }
    if (!strcasecmp(service, "https")) { *port_out = 443; return 0; }
    if (!strcasecmp(service, "domain")) { *port_out = 53; return 0; }
    return EAI_SERVICE;
}

static int lookup_hosts(const char *node, struct in_addr *out) {
    const char *path = getenv("SHADOW_TPU_HOSTS_FILE");
    if (!path)
        return -1;
    FILE *fh = fopen(path, "re");
    if (!fh)
        return -1;
    char line[512];
    int found = -1;
    while (found < 0 && fgets(line, sizeof line, fh)) {
        char *save = NULL;
        char *ip = strtok_r(line, " \t\r\n", &save);
        if (!ip || ip[0] == '#')
            continue;
        char *name;
        while ((name = strtok_r(NULL, " \t\r\n", &save)) != NULL) {
            if (!strcasecmp(name, node)) {
                if (inet_aton(ip, out))
                    found = 0;
                break;
            }
        }
    }
    fclose(fh);
    return found;
}

static struct addrinfo *make_result(struct in_addr addr, int port,
                                    int socktype, int protocol,
                                    const char *canon) {
    struct blk *b = (struct blk *)calloc(1, sizeof(struct blk));
    if (!b)
        return NULL;
    b->sa.sin_family = AF_INET;
    b->sa.sin_port = htons((unsigned short)port);
    b->sa.sin_addr = addr;
    b->ai.ai_family = AF_INET;
    b->ai.ai_socktype = socktype ? socktype : SOCK_STREAM;
    b->ai.ai_protocol = protocol;
    b->ai.ai_addrlen = sizeof(struct sockaddr_in);
    b->ai.ai_addr = (struct sockaddr *)&b->sa;
    if (canon) {
        strncpy(b->canon, canon, sizeof(b->canon) - 1);
        b->ai.ai_canonname = b->canon;
    }
    return &b->ai;
}

int getaddrinfo(const char *node, const char *service,
                const struct addrinfo *hints, struct addrinfo **res) {
    int port = 0;
    int rc = parse_port(service, &port);
    if (rc)
        return rc;
    int socktype = hints ? hints->ai_socktype : 0;
    int protocol = hints ? hints->ai_protocol : 0;
    int family = hints ? hints->ai_family : AF_UNSPEC;
    if (family != AF_UNSPEC && family != AF_INET)
        return EAI_FAMILY; /* the simulated internet is v4 */

    struct in_addr addr;
    if (!node || !*node) {
        /* AI_PASSIVE: the wildcard; otherwise loopback (getaddrinfo(3)) */
        addr.s_addr = (hints && (hints->ai_flags & AI_PASSIVE))
                          ? htonl(INADDR_ANY)
                          : htonl(INADDR_LOOPBACK);
    } else if (inet_aton(node, &addr)) {
        /* numeric: done */
    } else if (hints && (hints->ai_flags & AI_NUMERICHOST)) {
        return EAI_NONAME;
    } else if (!strcasecmp(node, "localhost")) {
        addr.s_addr = htonl(INADDR_LOOPBACK);
    } else if (lookup_hosts(node, &addr) != 0) {
        return EAI_NONAME; /* fully simulated: no real-resolver fallback */
    }
    struct addrinfo *ai = make_result(
        addr, port, socktype, protocol,
        (hints && (hints->ai_flags & AI_CANONNAME)) ? node : NULL);
    if (!ai)
        return EAI_MEMORY;
    *res = ai;
    return 0;
}

void freeaddrinfo(struct addrinfo *ai) {
    while (ai) {
        struct addrinfo *next = ai->ai_next;
        free(ai); /* struct blk starts at the addrinfo */
        ai = next;
    }
}

/* getnameinfo: reverse view over the same table (numeric fallback). */
int getnameinfo(const struct sockaddr *sa, socklen_t salen, char *host,
                socklen_t hostlen, char *serv, socklen_t servlen,
                int flags) {
    if (!sa || salen < (socklen_t)sizeof(struct sockaddr_in)
        || sa->sa_family != AF_INET)
        return EAI_FAMILY;
    const struct sockaddr_in *sin = (const struct sockaddr_in *)sa;
    if (serv && servlen)
        snprintf(serv, servlen, "%u", (unsigned)ntohs(sin->sin_port));
    if (host && hostlen) {
        char ip[INET_ADDRSTRLEN];
        inet_ntop(AF_INET, &sin->sin_addr, ip, sizeof ip);
        if (flags & NI_NUMERICHOST) {
            snprintf(host, hostlen, "%s", ip);
            return 0;
        }
        /* scan for a name owning this IP; fall back to numeric */
        const char *path = getenv("SHADOW_TPU_HOSTS_FILE");
        FILE *fh = path ? fopen(path, "re") : NULL;
        int named = 0;
        if (fh) {
            char line[512];
            while (!named && fgets(line, sizeof line, fh)) {
                char *save = NULL;
                char *lip = strtok_r(line, " \t\r\n", &save);
                if (!lip || lip[0] == '#' || strcmp(lip, ip))
                    continue;
                char *name = strtok_r(NULL, " \t\r\n", &save);
                if (name) {
                    snprintf(host, hostlen, "%s", name);
                    named = 1;
                }
            }
            fclose(fh);
        }
        if (!named) {
            if (flags & NI_NAMEREQD)
                return EAI_NONAME; /* name required, none known */
            snprintf(host, hostlen, "%s", ip);
        }
    }
    return 0;
}

/* ---- classic gethostby* family ------------------------------------- */
/* CPython's socketmodule and older apps use gethostbyname_r /
 * gethostbyaddr_r; without interposition those walk glibc NSS into real
 * DNS queries over the SIMULATED network (5s timeouts, wrong answers).
 * All four resolve against the same hosts table, instantly. */

static int fill_hostent(struct hostent *ret, char *buf, size_t buflen,
                        const char *name, struct in_addr addr) {
    /* layout in caller buffer: name string | addr bytes | ptr arrays;
     * budget BOTH alignment pads at their 7-byte worst case */
    size_t name_len = strlen(name) + 1;
    size_t need = name_len + 7 + sizeof(struct in_addr) + 7
                  + 3 * sizeof(char *);
    if (buflen < need)
        return ERANGE;
    char *p = buf;
    memcpy(p, name, name_len);
    ret->h_name = p;
    p += name_len;
    p = (char *)(((uintptr_t)p + 7) & ~(uintptr_t)7);
    memcpy(p, &addr, sizeof addr);
    char *addr_bytes = p;
    p += sizeof addr;
    p = (char *)(((uintptr_t)p + 7) & ~(uintptr_t)7);
    char **addr_list = (char **)p;
    addr_list[0] = addr_bytes;
    addr_list[1] = NULL;
    p += 2 * sizeof(char *);
    char **aliases = (char **)p;
    aliases[0] = NULL;
    ret->h_aliases = aliases;
    ret->h_addrtype = AF_INET;
    ret->h_length = sizeof(struct in_addr);
    ret->h_addr_list = addr_list;
    return 0;
}

int gethostbyname_r(const char *name, struct hostent *ret, char *buf,
                    size_t buflen, struct hostent **result,
                    int *h_errnop) {
    *result = NULL;
    struct in_addr addr;
    if (inet_aton(name, &addr)) {
        /* numeric */
    } else if (!strcasecmp(name, "localhost")) {
        addr.s_addr = htonl(INADDR_LOOPBACK);
    } else if (lookup_hosts(name, &addr) != 0) {
        if (h_errnop)
            *h_errnop = HOST_NOT_FOUND;
        return -1;
    }
    int rc = fill_hostent(ret, buf, buflen, name, addr);
    if (rc)
        return rc;
    *result = ret;
    return 0;
}

static int reverse_lookup(struct in_addr addr, char *name_out, size_t n) {
    char ip[INET_ADDRSTRLEN];
    inet_ntop(AF_INET, &addr, ip, sizeof ip);
    const char *path = getenv("SHADOW_TPU_HOSTS_FILE");
    FILE *fh = path ? fopen(path, "re") : NULL;
    if (!fh)
        return -1;
    char line[512];
    int found = -1;
    while (found < 0 && fgets(line, sizeof line, fh)) {
        char *save = NULL;
        char *lip = strtok_r(line, " \t\r\n", &save);
        if (!lip || lip[0] == '#' || strcmp(lip, ip))
            continue;
        char *nm = strtok_r(NULL, " \t\r\n", &save);
        if (nm) {
            snprintf(name_out, n, "%s", nm);
            found = 0;
        }
    }
    fclose(fh);
    return found;
}

int gethostbyaddr_r(const void *addr, socklen_t len, int type,
                    struct hostent *ret, char *buf, size_t buflen,
                    struct hostent **result, int *h_errnop) {
    *result = NULL;
    if (type != AF_INET || len != sizeof(struct in_addr)) {
        if (h_errnop)
            *h_errnop = HOST_NOT_FOUND;
        return -1;
    }
    struct in_addr a;
    memcpy(&a, addr, sizeof a);
    char name[256];
    if (reverse_lookup(a, name, sizeof name) != 0) {
        if (h_errnop)
            *h_errnop = HOST_NOT_FOUND;
        return -1; /* instant: no NSS walk, no simulated-net DNS query */
    }
    int rc = fill_hostent(ret, buf, buflen, name, a);
    if (rc)
        return rc;
    *result = ret;
    return 0;
}

static struct hostent static_he;
static char static_he_buf[1024];

struct hostent *gethostbyname(const char *name) {
    struct hostent *res = NULL;
    int herr = 0;
    if (gethostbyname_r(name, &static_he, static_he_buf,
                        sizeof static_he_buf, &res, &herr) != 0)
        return NULL;
    return res;
}

struct hostent *gethostbyaddr(const void *addr, socklen_t len, int type) {
    struct hostent *res = NULL;
    int herr = 0;
    if (gethostbyaddr_r(addr, len, type, &static_he, static_he_buf,
                        sizeof static_he_buf, &res, &herr) != 0)
        return NULL;
    return res;
}
