/* Deterministic OpenSSL RNG preload.
 *
 * Parity: reference `src/lib/preload-openssl/rng.c` — libcrypto seeds its
 * DRBGs from entropy sources the simulator cannot trap (RDRAND, jitter
 * entropy), so managed TLS apps would diverge run-to-run. This library
 * shadows the libcrypto RAND entry points and routes every byte request
 * through the getrandom(2) syscall, which the seccomp filter traps and the
 * simulated kernel answers from the host's seeded RNG stream
 * (syscall_handler getrandom emulation). Written against the public
 * OpenSSL RAND API surface (openssl/rand.h), independent implementation.
 *
 * Enabled by default for managed processes; gate with
 * experimental.use_preload_openssl_rng.
 */

#include <stddef.h>

#ifndef SYS_getrandom
#define SYS_getrandom 318
#endif

/* Raw syscall: must not depend on libc's wrapper (ordering within the
 * preload chain is not guaranteed). The seccomp filter traps this and the
 * simulator fills the buffer deterministically. */
static long raw_getrandom(void *buf, unsigned long n) {
    long ret;
    register long r10 __asm__("r10") = 0;
    __asm__ volatile("syscall"
                     : "=a"(ret)
                     : "a"((long)SYS_getrandom), "D"(buf), "S"(n), "d"(0L),
                       "r"(r10)
                     : "rcx", "r11", "memory");
    return ret;
}

static int fill_deterministic(unsigned char *buf, long n) {
    long off = 0;
    if (n < 0)
        return 0; /* libcrypto fails negative lengths; so do we */
    while (off < n) {
        long got = raw_getrandom(buf + off, (unsigned long)(n - off));
        if (got <= 0)
            return 0; /* OpenSSL failure convention */
        off += got;
    }
    return 1;
}

/* ---- the classic RAND API ------------------------------------------- */

int RAND_bytes(unsigned char *buf, int num) {
    return fill_deterministic(buf, num);
}

int RAND_priv_bytes(unsigned char *buf, int num) {
    return fill_deterministic(buf, num);
}

int RAND_pseudo_bytes(unsigned char *buf, int num) {
    return fill_deterministic(buf, num);
}

/* Seeding becomes a no-op: the simulated stream is already seeded. */
void RAND_seed(const void *buf, int num) { (void)buf; (void)num; }
void RAND_add(const void *buf, int num, double entropy) {
    (void)buf; (void)num; (void)entropy;
}
int RAND_poll(void) { return 1; }
int RAND_status(void) { return 1; }
void RAND_cleanup(void) {}

/* ---- DRBG entry points (OpenSSL 1.1.1) ------------------------------ */

int RAND_DRBG_bytes(void *drbg, unsigned char *out, size_t outlen) {
    (void)drbg;
    return fill_deterministic(out, (long)outlen);
}

int RAND_DRBG_generate(void *drbg, unsigned char *out, size_t outlen,
                       int prediction_resistance, const unsigned char *adin,
                       size_t adinlen) {
    (void)drbg; (void)prediction_resistance; (void)adin; (void)adinlen;
    return fill_deterministic(out, (long)outlen);
}

/* ---- method-table accessors ----------------------------------------- */

/* Apps (and libssl itself) may fetch the method table and call through
 * it, bypassing our global symbols — hand back a table of our own
 * functions. Layout matches openssl/rand.h RAND_METHOD. Callback return
 * types drifted across OpenSSL versions (void vs int); returning int is
 * ABI-safe on x86-64 since rax is caller-saved either way. */
typedef struct {
    int (*seed)(const void *buf, int num);
    int (*bytes)(unsigned char *buf, int num);
    void (*cleanup)(void);
    int (*add)(const void *buf, int num, double entropy);
    int (*pseudorand)(unsigned char *buf, int num);
    int (*status)(void);
} rand_method_t;

static int method_seed(const void *buf, int num) {
    (void)buf; (void)num;
    return 1;
}

static int method_add(const void *buf, int num, double entropy) {
    (void)buf; (void)num; (void)entropy;
    return 1;
}

static const rand_method_t deterministic_method = {
    method_seed,     RAND_bytes, RAND_cleanup,
    method_add,      RAND_pseudo_bytes, RAND_status,
};

const void *RAND_get_rand_method(void) { return &deterministic_method; }
const void *RAND_OpenSSL(void) { return &deterministic_method; }
const void *RAND_SSLeay(void) { return &deterministic_method; }

/* Refuse swaps back to an entropy-based method. */
int RAND_set_rand_method(const void *meth) {
    (void)meth;
    return 1;
}
