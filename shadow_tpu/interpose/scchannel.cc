#include "scchannel.h"

#include <errno.h>
#include <linux/futex.h>
#include <string.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

#define CLOSED_BIT 0x4u
#define STATE_MASK 0x3u

/* The futex syscall goes through the shared shim_text stub so the managed
 * process's seccomp filter (IP-range whitelist) never traps the channel's
 * own blocking machinery. */
#include "shim_syscall.h"

static long futex(uint32_t *uaddr, int op, uint32_t val) {
    return shim_text_syscall(SYS_futex, (long)(uintptr_t)uaddr, op, val, 0, 0,
                             0);
}

static uint32_t load_acq(const uint32_t *p) {
    return __atomic_load_n(p, __ATOMIC_ACQUIRE);
}

static int cas(uint32_t *p, uint32_t expect, uint32_t want) {
    return __atomic_compare_exchange_n(p, &expect, want, 0, __ATOMIC_ACQ_REL,
                                       __ATOMIC_ACQUIRE);
}

static void wait_while(uint32_t *word, uint32_t observed) {
    /* Sleep until *word changes from `observed` (futex handles the race). */
    futex(word, FUTEX_WAIT, observed);
}

static void wake_all(uint32_t *word) {
    futex(word, FUTEX_WAKE, INT32_MAX);
}

void scchannel_init(SelfContainedChannel *ch) {
    memset(ch, 0, sizeof(*ch));
    __atomic_store_n(&ch->state, SCCHANNEL_EMPTY, __ATOMIC_RELEASE);
}

/* Move the low state bits to `next` with a CAS loop so a concurrent
 * close_writer fetch_or can never be clobbered by a stale plain store. */
static void set_state(SelfContainedChannel *ch, uint32_t next) {
    for (;;) {
        uint32_t cur = load_acq(&ch->state);
        if (cas(&ch->state, cur, (cur & CLOSED_BIT) | next)) return;
    }
}

int scchannel_send(SelfContainedChannel *ch, const void *buf, uint32_t len) {
    if (len > SCCHANNEL_MSG_MAX) return -1;
    for (;;) {
        uint32_t cur = load_acq(&ch->state);
        if (cur & CLOSED_BIT) return -1; /* peer is gone: fail, don't hang */
        uint32_t st = cur & STATE_MASK;
        if (st == SCCHANNEL_EMPTY) {
            if (!cas(&ch->state, cur, (cur & CLOSED_BIT) | SCCHANNEL_WRITING))
                continue;
            break;
        }
        /* previous message unread: rendezvous discipline says wait */
        wait_while(&ch->state, cur);
    }
    memcpy(ch->msg, buf, len);
    ch->len = len;
    set_state(ch, SCCHANNEL_READY);
    wake_all(&ch->state);
    return 0;
}

long scchannel_recv(SelfContainedChannel *ch, void *buf, uint32_t cap) {
    for (;;) {
        uint32_t cur = load_acq(&ch->state);
        uint32_t st = cur & STATE_MASK;
        if (st == SCCHANNEL_READY) {
            if (!cas(&ch->state, cur, (cur & CLOSED_BIT) | SCCHANNEL_READING))
                continue;
            uint32_t n = ch->len;
            if (n > cap) n = cap;
            memcpy(buf, ch->msg, n);
            set_state(ch, SCCHANNEL_EMPTY);
            wake_all(&ch->state);
            return (long)n;
        }
        if (cur & CLOSED_BIT) return -1; /* closed and nothing pending */
        wait_while(&ch->state, cur);
    }
}

long scchannel_recv_timed(SelfContainedChannel *ch, void *buf, uint32_t cap,
                          int64_t timeout_ns) {
    struct timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    int64_t deadline =
        (int64_t)now.tv_sec * 1000000000 + now.tv_nsec + timeout_ns;
    for (;;) {
        uint32_t cur = load_acq(&ch->state);
        uint32_t st = cur & STATE_MASK;
        if (st == SCCHANNEL_READY) {
            if (!cas(&ch->state, cur, (cur & CLOSED_BIT) | SCCHANNEL_READING))
                continue;
            uint32_t n = ch->len;
            if (n > cap) n = cap;
            memcpy(buf, ch->msg, n);
            set_state(ch, SCCHANNEL_EMPTY);
            wake_all(&ch->state);
            return (long)n;
        }
        if (cur & CLOSED_BIT) return -1;
        clock_gettime(CLOCK_MONOTONIC, &now);
        int64_t rem =
            deadline - ((int64_t)now.tv_sec * 1000000000 + now.tv_nsec);
        if (rem <= 0) return -2;
        struct timespec ts = {(time_t)(rem / 1000000000),
                              (long)(rem % 1000000000)};
        shim_text_syscall(SYS_futex, (long)(uintptr_t)&ch->state, FUTEX_WAIT,
                          cur, (long)(uintptr_t)&ts, 0, 0);
    }
}

void scchannel_close_writer(SelfContainedChannel *ch) {
    __atomic_fetch_or(&ch->state, CLOSED_BIT, __ATOMIC_ACQ_REL);
    wake_all(&ch->state);
}

int scchannel_writer_closed(const SelfContainedChannel *ch) {
    return (load_acq(&ch->state) & CLOSED_BIT) != 0;
}
