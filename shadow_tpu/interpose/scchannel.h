/* SelfContainedChannel: a two-party rendezvous channel that lives entirely
 * inside one shared-memory region.
 *
 * Parity: reference src/lib/vasi-sync/src/scchannel.rs — states
 * Empty/Writing/Ready/Reading plus a writer-closed flag; readers block on a
 * futex until a message (or close) arrives; everything is
 * position-independent (offsets only, no pointers) so the same bytes work
 * at different mapped addresses in different processes.
 *
 * One channel carries one message at a time (strict rendezvous): that is
 * exactly the shim IPC pattern — shadow-to-plugin and plugin-to-shadow each
 * get their own channel inside IPCData (reference ipc.rs), and the two
 * sides strictly alternate.
 */
#ifndef SHADOW_TPU_SCCHANNEL_H
#define SHADOW_TPU_SCCHANNEL_H

#include <stddef.h>
#include <stdint.h>

#include "vasi.h"

#ifdef __cplusplus
extern "C" {
#endif

#define SCCHANNEL_MSG_MAX 1088  /* fits ShimEvent incl. the path-rewrite
                                   payload (two 400-byte paths) */

enum {
    SCCHANNEL_EMPTY = 0,
    SCCHANNEL_WRITING = 1,
    SCCHANNEL_READY = 2,
    SCCHANNEL_READING = 3,
};

typedef struct SelfContainedChannel {
    /* futex word: low 2 bits = state, bit 2 = writer closed */
    uint32_t state;
    uint32_t len;
    uint8_t msg[SCCHANNEL_MSG_MAX];
} SelfContainedChannel;

void scchannel_init(SelfContainedChannel *ch);

/* Blocking send; returns 0, or -1 if len > SCCHANNEL_MSG_MAX. Spins/futex
 * waits while a previous message is still unread. */
int scchannel_send(SelfContainedChannel *ch, const void *buf, uint32_t len);

/* Blocking receive; returns message length, or -1 when the writer closed
 * with no message pending (parity: WriterIsClosed). */
long scchannel_recv(SelfContainedChannel *ch, void *buf, uint32_t cap);

/* Like scchannel_recv but bounded by timeout_ns of wall time; returns -2
 * on timeout. Shadow-side only (uses clock_gettime, which a seccomp'd
 * shim must not call through libc). */
long scchannel_recv_timed(SelfContainedChannel *ch, void *buf, uint32_t cap,
                          int64_t timeout_ns);

/* Mark the writer side closed and wake any blocked reader (parity: the
 * ChildPidWatcher closing the channel when a managed process dies). */
void scchannel_close_writer(SelfContainedChannel *ch);

int scchannel_writer_closed(const SelfContainedChannel *ch);

#ifdef __cplusplus
}

SHADOW_TPU_ASSERT_VASI(SelfContainedChannel);
#endif

#endif /* SHADOW_TPU_SCCHANNEL_H */
