/* The interposition shim: LD_PRELOADed into managed processes.
 *
 * Parity: reference src/lib/shim — on load, attach the IPC shared-memory
 * block named by SHADOW_TPU_IPC_HANDLE, install a seccomp filter that
 * allows syscalls issued from the shim's own text range and traps every
 * other syscall to SIGSYS (shim_seccomp.c:144-260), then forward each
 * trapped syscall to the simulator over the futex channel and either
 * return the simulated result or re-execute natively from shim text
 * (shim_api_syscall.c / shim_sys.c). The reference also patches the vDSO
 * so clock_gettime etc. take the syscall path (patch_vdso.c) — same here,
 * by overwriting vDSO entry points with a jump to a trapping stub.
 *
 * Threads and fork (reference managed_thread.rs:349-428 + shim/src/clone.rs):
 * each managed thread owns its own IPC channel. A trapped clone() with
 * CLONE_VM follows the AddThread handshake — the simulator allocates a
 * child channel and replies ADD_THREAD_REQ; the shim runs the native clone
 * with a trampoline stack frame; the child attaches its channel (raw
 * syscalls only), announces itself, waits for the simulator's go-ahead,
 * then restores the app's trapped register state with rax=0 and jumps back
 * into application code. A fork-like clone (no CLONE_VM) needs no
 * trampoline: the child keeps its copied stack, swaps in the new channel,
 * and returns 0 through the normal signal path.
 */

#define _GNU_SOURCE 1
#include <errno.h>
#include <linux/audit.h>
#include <linux/filter.h>
#include <linux/seccomp.h>
#include <fcntl.h>
#include <signal.h>
#include <stddef.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/prctl.h>
#include <sys/syscall.h>
#include <ucontext.h>
#include <unistd.h>

#include "ipc.h"
#include "shim_shmem.h"
#include "shmem.h"

/* ------------------------------------------------------------------ */
/* Raw syscall from *shim text* — the only code the seccomp filter
 * whitelists by instruction pointer. Must not call into libc. */

#include "shim_syscall.h"
#define shim_raw_syscall shim_text_syscall

/* ------------------------------------------------------------------ */

extern "C" int shadow_tpu_patch_vdso(void);

static ShMemBlock g_ipc_block;
static IPCData *g_ipc = NULL;
static int g_interposing = 0;

/* Per-thread IPC channel (reference: one IPCData per managed thread,
 * ipc.rs:14-46). initial-exec TLS: fs-relative access, safe from signal
 * handlers, no lazy allocation. The main thread uses g_ipc.
 *
 * TLS only works for threads that own their TLS: a clone(CLONE_VM)
 * WITHOUT CLONE_SETTLS (Go's newosproc, other non-glibc runtimes that
 * set %fs after clone) shares the parent's %fs base at first, so a TLS
 * write from the child would clobber the PARENT's slot and cross their
 * channels. Those threads register in a tid-keyed table instead, and
 * cur_ipc() verifies TLS ownership by tid once any such thread exists. */
static __thread IPCData *t_ipc __attribute__((tls_model("initial-exec")));
static __thread long t_ipc_tid __attribute__((tls_model("initial-exec")));

#define TID_IPC_SLOTS 512
static struct { long tid; IPCData *ipc; } g_tid_ipc[TID_IPC_SLOTS];
static int g_shared_tls_threads; /* any live no-SETTLS thread */

static int tid_ipc_has_free_slot(void) {
    for (int i = 0; i < TID_IPC_SLOTS; i++) {
        if (__atomic_load_n(&g_tid_ipc[i].tid, __ATOMIC_ACQUIRE) == 0)
            return 1;
    }
    return 0;
}

static long raw_gettid(void) {
    return shim_raw_syscall(SYS_gettid, 0, 0, 0, 0, 0, 0);
}

static int tid_ipc_register(long tid, IPCData *ipc) {
    for (int i = 0; i < TID_IPC_SLOTS; i++) {
        long expect = 0;
        if (__atomic_compare_exchange_n(&g_tid_ipc[i].tid, &expect, tid, 0,
                                        __ATOMIC_ACQ_REL, __ATOMIC_ACQUIRE)) {
            __atomic_store_n(&g_tid_ipc[i].ipc, ipc, __ATOMIC_RELEASE);
            __atomic_store_n(&g_shared_tls_threads, 1, __ATOMIC_RELEASE);
            return 0;
        }
    }
    return -1;
}

static void tid_ipc_clear(long tid) {
    for (int i = 0; i < TID_IPC_SLOTS; i++) {
        if (__atomic_load_n(&g_tid_ipc[i].tid, __ATOMIC_ACQUIRE) == tid) {
            __atomic_store_n(&g_tid_ipc[i].ipc, (IPCData *)NULL,
                             __ATOMIC_RELEASE);
            __atomic_store_n(&g_tid_ipc[i].tid, 0, __ATOMIC_RELEASE);
            return;
        }
    }
}

static IPCData *tid_ipc_lookup(long tid) {
    for (int i = 0; i < TID_IPC_SLOTS; i++) {
        if (__atomic_load_n(&g_tid_ipc[i].tid, __ATOMIC_ACQUIRE) == tid)
            return __atomic_load_n(&g_tid_ipc[i].ipc, __ATOMIC_ACQUIRE);
    }
    return NULL;
}

static inline IPCData *cur_ipc(void) {
    if (!__atomic_load_n(&g_shared_tls_threads, __ATOMIC_ACQUIRE))
        return t_ipc ? t_ipc : g_ipc; /* fast path: TLS is trustworthy */
    long me = raw_gettid();
    if (t_ipc && t_ipc_tid == me) return t_ipc;
    IPCData *p = tid_ipc_lookup(me);
    if (p) return p;
    return t_ipc ? t_ipc : g_ipc;
}

/* per-process clock block (optional; fast path off when absent) */
static ShMemBlock g_proc_block;
static ProcessShmem *g_proc = NULL;

/* ------------------------------------------------------------------ */
/* In-shim time fast path (shim_sys.c:25-80): answer clock reads from
 * the shared clock, charging the modeled syscall latency, while the
 * advanced clock stays below the runahead bound. Returns 1 when the
 * syscall was fully handled locally. */

struct shim_timespec { int64_t tv_sec; int64_t tv_nsec; };
struct shim_timeval { int64_t tv_sec; int64_t tv_usec; };

static int clockid_is_monotonic(long clockid) {
    /* MONOTONIC(1), MONOTONIC_RAW(4), MONOTONIC_COARSE(6), BOOTTIME(7) */
    return clockid == 1 || clockid == 4 || clockid == 6 || clockid == 7;
}

static int shim_try_time_fastpath(long nr, const uint64_t args[6],
                                  long *out_ret) {
    if (!g_proc || !__atomic_load_n(&g_proc->enabled, __ATOMIC_ACQUIRE))
        return 0;
    if (nr != SYS_clock_gettime && nr != SYS_gettimeofday && nr != SYS_time)
        return 0;
    uint64_t now = g_proc->sim_time_ns + g_proc->syscall_latency_ns;
    if (now > g_proc->max_runahead_ns)
        return 0; /* runahead exhausted: yield to the simulator via IPC */
    g_proc->sim_time_ns = now;

    if (nr == SYS_clock_gettime) {
        long clockid = (long)args[0];
        struct shim_timespec *ts = (struct shim_timespec *)args[1];
        uint64_t ns = clockid_is_monotonic(clockid)
                          ? now
                          : g_proc->epoch_offset_ns + now;
        if (ts) {
            ts->tv_sec = (int64_t)(ns / 1000000000ull);
            ts->tv_nsec = (int64_t)(ns % 1000000000ull);
        }
        *out_ret = 0;
        return 1;
    }
    if (nr == SYS_gettimeofday) {
        struct shim_timeval *tv = (struct shim_timeval *)args[0];
        uint64_t ns = g_proc->epoch_offset_ns + now;
        if (tv) {
            tv->tv_sec = (int64_t)(ns / 1000000000ull);
            tv->tv_usec = (int64_t)((ns % 1000000000ull) / 1000);
        }
        *out_ret = 0;
        return 1;
    }
    /* SYS_time */
    uint64_t sec = (g_proc->epoch_offset_ns + now) / 1000000000ull;
    if (args[0]) *(int64_t *)args[0] = (int64_t)sec;
    *out_ret = (long)sec;
    return 1;
}

/* The seccomp IP whitelist covers the "shim_text" section, which holds
 * every syscall *instruction* the shim itself executes (shim_raw_syscall
 * here, raw_futex in scchannel.cc). The linker defines the bounds. */
extern char __start_shim_text[];
extern char __stop_shim_text[];

static void shim_log(const char *msg) {
    if (getenv("SHADOW_TPU_SHIM_DEBUG"))
        shim_raw_syscall(SYS_write, 2, (long)msg, (long)strlen(msg), 0, 0, 0);
}

/* Forward one syscall to the simulator; returns the value to hand back. */
static long shim_emulate_syscall(long nr, const uint64_t args[6]) {
    ShimEvent ev;
    memset(&ev, 0, sizeof(ev));
    ev.kind = SHIM_EVENT_SYSCALL;
    ev.u.syscall.number = nr;
    for (int i = 0; i < 6; i++) ev.u.syscall.args[i] = args[i];
    if (ipc_to_shadow_send(cur_ipc(), &ev) != 0) {
        /* simulator is gone: die quietly */
        shim_raw_syscall(SYS_exit_group, 1, 0, 0, 0, 0, 0);
    }
    ShimEvent reply;
    long n = ipc_to_shim_recv(cur_ipc(), &reply);
    if (n < 0) shim_raw_syscall(SYS_exit_group, 1, 0, 0, 0, 0, 0);
    if (reply.kind == SHIM_EVENT_SYSCALL_DO_NATIVE) {
        if (nr == SYS_exit && g_shared_tls_threads)
            tid_ipc_clear(raw_gettid()); /* free the no-SETTLS slot */
        return shim_raw_syscall(nr, (long)args[0], (long)args[1], (long)args[2],
                                (long)args[3], (long)args[4], (long)args[5]);
    }
    if (reply.kind == SHIM_EVENT_SYSCALL_DO_NATIVE_REWRITE) {
        /* per-host filesystem view: execute with substituted path args.
         * Strings must live in THIS address space; stage on the stack. */
        char p0[SHIM_REWRITE_PATH_MAX], p1[SHIM_REWRITE_PATH_MAX];
        uint64_t a[6];
        for (int i = 0; i < 6; i++) a[i] = reply.u.rewrite.args[i];
        int i0 = reply.u.rewrite.path_arg[0];
        int i1 = reply.u.rewrite.path_arg[1];
        if (i0 >= 0 && i0 < 6) {
            memcpy(p0, reply.u.rewrite.path[0], SHIM_REWRITE_PATH_MAX);
            p0[SHIM_REWRITE_PATH_MAX - 1] = 0;
            a[i0] = (uint64_t)p0;
        }
        if (i1 >= 0 && i1 < 6) {
            memcpy(p1, reply.u.rewrite.path[1], SHIM_REWRITE_PATH_MAX);
            p1[SHIM_REWRITE_PATH_MAX - 1] = 0;
            a[i1] = (uint64_t)p1;
        }
        return shim_raw_syscall(nr, (long)a[0], (long)a[1], (long)a[2],
                                (long)a[3], (long)a[4], (long)a[5]);
    }
    return reply.u.complete.retval;
}

/* ------------------------------------------------------------------ */
/* clone / fork support.
 *
 * shmem attach without libc: the clone child must map its IPC block
 * before it can announce itself, and it cannot touch interposed or
 * non-async-signal-safe libc on the way. Handles look like
 * "/shadow_tpu_shm_<pid>_<n>:<size>" (shmem.cc shmem_serialize). */

#ifndef CLONE_VM
#define CLONE_VM 0x100
#endif
#ifndef CLONE_VFORK
#define CLONE_VFORK 0x4000
#endif

static void *shim_raw_attach(const char *handle, uint64_t *size_out) {
    char path[160];
    const char *p = handle;
    const char *colon = NULL;
    for (const char *q = handle; *q; q++)
        if (*q == ':') colon = q;
    if (!colon) return NULL;
    uint64_t size = 0;
    for (const char *q = colon + 1; *q >= '0' && *q <= '9'; q++)
        size = size * 10 + (uint64_t)(*q - '0');
    if (size == 0) return NULL;
    size_t n = 0;
    const char prefix[] = "/dev/shm";
    for (; prefix[n]; n++) path[n] = prefix[n];
    for (; p < colon && n + 1 < sizeof(path); p++) path[n++] = *p;
    path[n] = '\0';
    long fd = shim_raw_syscall(SYS_openat, -100 /* AT_FDCWD */, (long)path,
                               O_RDWR, 0, 0, 0);
    if (fd < 0) return NULL;
    long addr = shim_raw_syscall(SYS_mmap, 0, (long)size,
                                 PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    shim_raw_syscall(SYS_close, fd, 0, 0, 0, 0, 0);
    if (addr < 0 && addr > -4096) return NULL;
    if (size_out) *size_out = size;
    return (void *)addr;
}

/* The trampoline frame the clone child starts on (carved just below the
 * app-provided child stack; the app's own frame data at [child_stack, ...)
 * — e.g. glibc clone.S's pushed fn/arg — is untouched). Offsets are
 * hard-coded in the restore asm below. */
struct CloneFrame {
    uint64_t rip;                       /* 0x00: app post-syscall rip */
    uint64_t rsp;                       /* 0x08: app child stack (arg 2) */
    uint64_t rbx, rbp, r12, r13, r14, r15; /* 0x10 - 0x38 */
    uint64_t rdi, rsi, rdx, rcx, r8, r9, r10, r11; /* 0x40 - 0x78 */
    char ipc_handle[SHMEM_HANDLE_MAX];  /* 0x80 */
    uint64_t settls; /* clone had CLONE_SETTLS: the child owns its TLS */
};

static_assert(offsetof(CloneFrame, ipc_handle) == 0x80, "frame layout");

extern "C" long shim_clone_raw(uint64_t flags, uint64_t child_sp,
                               uint64_t ptid, uint64_t ctid, uint64_t tls);

/* Restore the app's trapped register state in the child: rax = 0 (the
 * child's clone return), rsp = the stack glibc handed to clone, rip = the
 * instruction after the trapped syscall. The transient push lands below
 * the app stack pointer (free space) and ret pops it back. */
__asm__(
    ".text\n"
    ".local shim_clone_jump\n"
    "shim_clone_jump:\n"
    "  movq 0x08(%rdi), %rsp\n"
    "  movq 0x10(%rdi), %rbx\n"
    "  movq 0x18(%rdi), %rbp\n"
    "  movq 0x20(%rdi), %r12\n"
    "  movq 0x28(%rdi), %r13\n"
    "  movq 0x30(%rdi), %r14\n"
    "  movq 0x38(%rdi), %r15\n"
    "  movq 0x50(%rdi), %rdx\n"
    "  movq 0x58(%rdi), %rcx\n"
    "  movq 0x60(%rdi), %r8\n"
    "  movq 0x68(%rdi), %r9\n"
    "  movq 0x70(%rdi), %r10\n"
    "  movq 0x78(%rdi), %r11\n"
    "  pushq 0x00(%rdi)\n"
    "  movq 0x48(%rdi), %rsi\n"
    "  movq 0x40(%rdi), %rdi\n"
    "  xorl %eax, %eax\n"
    "  ret\n");
extern "C" void shim_clone_jump(CloneFrame *f) __attribute__((noreturn));

/* Child-side start: attach the per-thread channel, announce, wait for the
 * simulator's go-ahead, then become the application thread. Raw syscalls
 * only — nothing here may recurse into interposition. */
extern "C" __attribute__((visibility("hidden"), noreturn, used))
void shim_clone_child(CloneFrame *f) {
    IPCData *my = (IPCData *)shim_raw_attach(f->ipc_handle, NULL);
    if (!my) shim_raw_syscall(SYS_exit, 117, 0, 0, 0, 0, 0);
    long tid = shim_raw_syscall(SYS_gettid, 0, 0, 0, 0, 0, 0);
    if (f->settls) {
        /* fresh TLS (kernel installed it before we ran): safe to own */
        t_ipc = my;
        t_ipc_tid = tid;
    } else {
        /* %fs still points at the PARENT's TLS — writing t_ipc here
         * would hijack the parent's channel. Register by tid instead. */
        if (tid_ipc_register(tid, my) != 0)
            shim_raw_syscall(SYS_exit, 117, 0, 0, 0, 0, 0);
    }
    /* rdtsc trapping is a per-thread CPU flag; re-arm it here */
#ifndef PR_TSC_SIGSEGV
#define PR_TSC_SIGSEGV 2
#endif
    shim_raw_syscall(SYS_prctl, PR_SET_TSC, PR_TSC_SIGSEGV, 0, 0, 0, 0);
    ShimEvent ev;
    memset(&ev, 0, sizeof(ev));
    ev.kind = SHIM_EVENT_START_RES;
    ev.u.add_thread_res.child_native_tid = tid;
    if (ipc_to_shadow_send(my, &ev) != 0)
        shim_raw_syscall(SYS_exit, 117, 0, 0, 0, 0, 0);
    ShimEvent go;
    if (ipc_to_shim_recv(my, &go) < 0)
        shim_raw_syscall(SYS_exit, 117, 0, 0, 0, 0, 0);
    shim_clone_jump(f);
}

/* The native clone syscall, child path diverted onto the trampoline. Lives
 * in shim_text so the syscall instruction passes the seccomp IP filter. */
__asm__(
    ".pushsection shim_text,\"ax\",@progbits\n"
    ".globl shim_clone_raw\n"
    "shim_clone_raw:\n"
    "  movq %rcx, %r10\n"
    "  movl $56, %eax\n" /* SYS_clone */
    "  syscall\n"
    "  testq %rax, %rax\n"
    "  jnz 1f\n"
    "  movq %rsp, %rdi\n" /* child: rsp = CloneFrame */
    "  call shim_clone_child\n"
    "1: ret\n"
    ".popsection\n");

/* Thread-flavored clone (CLONE_VM): AddThread handshake + trampoline.
 * Returns the value for the app's rax. Needs the trapped register state
 * for the child's jump back into app code. */
static long shim_handle_clone_thread(const uint64_t args[6], greg_t *regs) {
#ifndef CLONE_SETTLS
#define CLONE_SETTLS 0x00080000
#endif
    /* The trampoline frame is carved below the child stack: a NULL stack
     * (run-on-parent's-stack clone) would wrap the pointer — refuse it
     * like the fork path refuses caller-provided stacks. */
    if (args[1] == 0) return -38; /* ENOSYS */
    /* A no-SETTLS child can only be routed via the tid table; reserve
     * capacity BEFORE the native clone, because afterwards the app has
     * already been told the thread exists and a silent 117-exit would
     * hang it. Threads of one managed process never run concurrently, so
     * this check cannot race another clone. */
    if (!(args[0] & CLONE_SETTLS) && !tid_ipc_has_free_slot())
        return -11; /* EAGAIN */
    ShimEvent ev;
    memset(&ev, 0, sizeof(ev));
    ev.kind = SHIM_EVENT_SYSCALL;
    ev.u.syscall.number = SYS_clone;
    for (int i = 0; i < 6; i++) ev.u.syscall.args[i] = args[i];
    if (ipc_to_shadow_send(cur_ipc(), &ev) != 0)
        shim_raw_syscall(SYS_exit_group, 1, 0, 0, 0, 0, 0);
    ShimEvent reply;
    if (ipc_to_shim_recv(cur_ipc(), &reply) < 0)
        shim_raw_syscall(SYS_exit_group, 1, 0, 0, 0, 0, 0);
    if (reply.kind == SHIM_EVENT_SYSCALL_COMPLETE)
        return reply.u.complete.retval; /* simulator refused (EAGAIN...) */
    if (reply.kind != SHIM_EVENT_ADD_THREAD_REQ) return -38; /* ENOSYS */

    uint64_t stack_top = args[1];
    CloneFrame *f = (CloneFrame *)((stack_top - sizeof(CloneFrame)) & ~63ULL);
    f->rip = (uint64_t)regs[REG_RIP];
    f->rsp = stack_top;
    f->rbx = (uint64_t)regs[REG_RBX];
    f->rbp = (uint64_t)regs[REG_RBP];
    f->r12 = (uint64_t)regs[REG_R12];
    f->r13 = (uint64_t)regs[REG_R13];
    f->r14 = (uint64_t)regs[REG_R14];
    f->r15 = (uint64_t)regs[REG_R15];
    f->rdi = (uint64_t)regs[REG_RDI];
    f->rsi = (uint64_t)regs[REG_RSI];
    f->rdx = (uint64_t)regs[REG_RDX];
    f->rcx = (uint64_t)regs[REG_RCX];
    f->r8 = (uint64_t)regs[REG_R8];
    f->r9 = (uint64_t)regs[REG_R9];
    f->r10 = (uint64_t)regs[REG_R10];
    f->r11 = (uint64_t)regs[REG_R11];
    memcpy(f->ipc_handle, reply.u.add_thread_req.ipc_handle,
           sizeof(f->ipc_handle));
    f->settls = (args[0] & CLONE_SETTLS) ? 1 : 0;

    /* CLONE_VFORK (posix_spawn/system) would block the parent in the
     * native clone until the child execs — but the child is parked
     * waiting for the simulator's go-ahead, which needs the parent's
     * ADD_THREAD_RES first: guaranteed deadlock. Strip it; the child has
     * its own stack (glibc allocates one for spawn helpers), so running
     * the parent concurrently is safe. Cost: exec-failure reporting from
     * posix_spawn helpers may be unreliable (known limitation). */
    long tid = shim_clone_raw(args[0] & ~(uint64_t)CLONE_VFORK, (uint64_t)f,
                              args[2], args[3], args[4]);

    ShimEvent res;
    memset(&res, 0, sizeof(res));
    res.kind = SHIM_EVENT_ADD_THREAD_RES;
    res.u.add_thread_res.child_native_tid = tid;
    if (ipc_to_shadow_send(cur_ipc(), &res) != 0)
        shim_raw_syscall(SYS_exit_group, 1, 0, 0, 0, 0, 0);
    ShimEvent fin;
    if (ipc_to_shim_recv(cur_ipc(), &fin) < 0)
        shim_raw_syscall(SYS_exit_group, 1, 0, 0, 0, 0, 0);
    return fin.u.complete.retval;
}

/* Fork-flavored clone (no CLONE_VM) and SYS_fork: the child keeps its
 * copied stack, so no trampoline — swap channels and return 0 upward
 * through the normal reply path. */
static long shim_handle_fork(long nr, const uint64_t args[6]) {
    /* a fork-like clone with a caller-provided stack would resume the
     * child mid-C-function on that stack (frame/ret addrs live on the old
     * one) — only the glibc fork shape (stack = 0) is supported */
    if (nr == SYS_clone && args[1] != 0) return -38; /* ENOSYS */
    ShimEvent ev;
    memset(&ev, 0, sizeof(ev));
    ev.kind = SHIM_EVENT_SYSCALL;
    ev.u.syscall.number = nr;
    for (int i = 0; i < 6; i++) ev.u.syscall.args[i] = args[i];
    if (ipc_to_shadow_send(cur_ipc(), &ev) != 0)
        shim_raw_syscall(SYS_exit_group, 1, 0, 0, 0, 0, 0);
    ShimEvent reply;
    if (ipc_to_shim_recv(cur_ipc(), &reply) < 0)
        shim_raw_syscall(SYS_exit_group, 1, 0, 0, 0, 0, 0);
    if (reply.kind == SHIM_EVENT_SYSCALL_COMPLETE)
        return reply.u.complete.retval;
    if (reply.kind != SHIM_EVENT_ADD_THREAD_REQ) return -38; /* ENOSYS */

    char handle[SHMEM_HANDLE_MAX];
    memcpy(handle, reply.u.add_thread_req.ipc_handle, sizeof(handle));

    long pid = shim_raw_syscall(nr, (long)args[0], (long)args[1],
                                (long)args[2], (long)args[3], (long)args[4],
                                (long)args[5]);
    if (pid == 0) {
        /* child: our copies of the parent's channels must never be used
         * again; the clock block is shared with the parent, so the fast
         * path is disabled here (the simulator answers time slowly but
         * correctly for forked children). */
        void *addr = shim_raw_attach(handle, NULL);
        if (!addr) shim_raw_syscall(SYS_exit_group, 117, 0, 0, 0, 0, 0);
        g_ipc = (IPCData *)addr;
        t_ipc = g_ipc;
        t_ipc_tid = shim_raw_syscall(SYS_gettid, 0, 0, 0, 0, 0, 0);
        /* only the forking thread survives fork: stale no-SETTLS slots
         * (and their parent-owned mappings) must not be consulted here */
        memset(g_tid_ipc, 0, sizeof(g_tid_ipc));
        __atomic_store_n(&g_shared_tls_threads, 0, __ATOMIC_RELEASE);
        g_proc = NULL;
        ShimEvent hello;
        memset(&hello, 0, sizeof(hello));
        hello.kind = SHIM_EVENT_START_RES;
        hello.u.add_thread_res.child_native_tid =
            shim_raw_syscall(SYS_getpid, 0, 0, 0, 0, 0, 0);
        if (ipc_to_shadow_send(g_ipc, &hello) != 0)
            shim_raw_syscall(SYS_exit_group, 117, 0, 0, 0, 0, 0);
        ShimEvent go;
        if (ipc_to_shim_recv(g_ipc, &go) < 0)
            shim_raw_syscall(SYS_exit_group, 117, 0, 0, 0, 0, 0);
        return 0;
    }

    ShimEvent res;
    memset(&res, 0, sizeof(res));
    res.kind = SHIM_EVENT_ADD_THREAD_RES;
    res.u.add_thread_res.child_native_tid = pid;
    if (ipc_to_shadow_send(cur_ipc(), &res) != 0)
        shim_raw_syscall(SYS_exit_group, 1, 0, 0, 0, 0, 0);
    ShimEvent fin;
    if (ipc_to_shim_recv(cur_ipc(), &fin) < 0)
        shim_raw_syscall(SYS_exit_group, 1, 0, 0, 0, 0, 0);
    return fin.u.complete.retval;
}

/* ------------------------------------------------------------------ */
/* rdtsc/rdtscp trap-and-emulate (reference src/lib/shim/shim_rdtsc.c +
 * src/lib/tsc): PR_SET_TSC(PR_TSC_SIGSEGV) makes every rdtsc fault; the
 * SIGSEGV handler decodes the instruction and returns the EMULATED
 * cycle count — a nominal 1 GHz TSC, so cycles == simulated ns — then
 * skips the instruction. Without this, real time leaks into any binary
 * using rdtsc (most modern language runtimes via their clock vDSO
 * fallbacks). */

static uint64_t shim_emulated_tsc_ns(void) {
    if (g_proc && __atomic_load_n(&g_proc->enabled, __ATOMIC_ACQUIRE)) {
        /* charge the modeled latency and honor the runahead bound like
         * the clock_gettime fast path — a TSC spin-wait must advance
         * time and eventually yield, or the simulation livelocks */
        uint64_t now = g_proc->sim_time_ns + g_proc->syscall_latency_ns;
        if (now <= g_proc->max_runahead_ns) {
            g_proc->sim_time_ns = now;
            return now;
        }
    }
    /* no shared clock yet, or runahead exhausted: ask the simulator
     * (full IPC round trip; it parks us until sim time catches up) */
    struct shim_timespec ts = {0, 0};
    uint64_t args[6] = {1 /* CLOCK_MONOTONIC */, (uint64_t)&ts, 0, 0, 0, 0};
    shim_emulate_syscall(SYS_clock_gettime, args);
    return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

static void shim_sigsegv_handler(int sig, siginfo_t *info, void *ucontext) {
    ucontext_t *ctx = (ucontext_t *)ucontext;
    greg_t *regs = ctx->uc_mcontext.gregs;
    const uint8_t *ip = (const uint8_t *)regs[REG_RIP];
    /* An exec fault (jump to unmapped memory) has si_addr == RIP; reading
     * the instruction bytes would re-fault and recurse. Only decode when
     * the faulting address is NOT the instruction pointer (a PR_SET_TSC
     * trap reports si_addr = NULL with RIP at the rdtsc). */
    int decodable = ip && (const uint8_t *)info->si_addr != ip;
    int is_rdtsc = decodable && ip[0] == 0x0f && ip[1] == 0x31;
    int is_rdtscp =
        decodable && ip[0] == 0x0f && ip[1] == 0x01 && ip[2] == 0xf9;
    if (!is_rdtsc && !is_rdtscp) {
        /* a real crash: fall back to default disposition and re-raise.
         * Raw syscalls only — libc getpid() is interposed by the preload
         * wrappers and would return the VIRTUAL pid (and re-enter IPC
         * from inside a crash handler). */
        signal(SIGSEGV, SIG_DFL);
        long tgid = shim_raw_syscall(SYS_getpid, 0, 0, 0, 0, 0, 0);
        long tid = shim_raw_syscall(SYS_gettid, 0, 0, 0, 0, 0, 0);
        shim_raw_syscall(SYS_tgkill, tgid, tid, SIGSEGV, 0, 0, 0);
        return;
    }
    uint64_t tsc = shim_emulated_tsc_ns(); /* 1 GHz: cycles == ns */
    regs[REG_RAX] = (greg_t)(tsc & 0xffffffffu);
    regs[REG_RDX] = (greg_t)(tsc >> 32);
    if (is_rdtscp) {
        regs[REG_RCX] = 0; /* IA32_TSC_AUX: cpu 0, node 0 */
        regs[REG_RIP] += 3;
    } else {
        regs[REG_RIP] += 2;
    }
    (void)sig;
    (void)info;
}

/* Direct entry for the preload-libc wrappers (reference
 * src/lib/preload-libc + shim_api_syscall.c): same dispatch as the
 * SIGSYS path but via a plain function call — no signal delivery, no
 * kernel round trip for locally-answered syscalls. */
extern "C" long shadow_tpu_api_syscall(long nr, long a, long b, long c,
                                       long d, long e, long f) {
    if (!g_interposing)
        return shim_raw_syscall(nr, a, b, c, d, e, f);
    uint64_t args[6] = {(uint64_t)a, (uint64_t)b, (uint64_t)c,
                        (uint64_t)d, (uint64_t)e, (uint64_t)f};
    long fast;
    if (shim_try_time_fastpath(nr, args, &fast)) return fast;
    if (nr == SYS_fork || nr == SYS_vfork
        || (nr == SYS_clone && !(args[0] & CLONE_VM)))
        /* vfork-as-fork is POSIX-legal; vfork-safe children only
         * exec/_exit, so copy semantics are indistinguishable here */
        return shim_handle_fork(nr == SYS_vfork ? SYS_fork : nr, args);
    if (nr == SYS_clone || nr == SYS_clone3)
        return -38; /* thread clone needs the trapped registers: ENOSYS */
    return shim_emulate_syscall(nr, args);
}

static void shim_sigsys_handler(int sig, siginfo_t *info, void *ucontext) {
    (void)sig;
    ucontext_t *ctx = (ucontext_t *)ucontext;
    greg_t *regs = ctx->uc_mcontext.gregs;
    long nr = info->si_syscall;
    uint64_t args[6] = {
        (uint64_t)regs[REG_RDI], (uint64_t)regs[REG_RSI],
        (uint64_t)regs[REG_RDX], (uint64_t)regs[REG_R10],
        (uint64_t)regs[REG_R8],  (uint64_t)regs[REG_R9],
    };
    long fast_ret;
    if (shim_try_time_fastpath(nr, args, &fast_ret)) {
        regs[REG_RAX] = fast_ret;
        return;
    }
    if (nr == SYS_clone3) {
        /* ENOSYS: glibc falls back to plain clone */
        regs[REG_RAX] = -38;
        return;
    }
    if (nr == SYS_clone && (args[0] & CLONE_VM)) {
        regs[REG_RAX] = shim_handle_clone_thread(args, regs);
        return;
    }
    if (nr == SYS_fork || nr == SYS_vfork || nr == SYS_clone) {
        /* vfork-as-fork (POSIX-legal: vfork-safe children only
         * exec/_exit before the parent observes anything) */
        regs[REG_RAX] = shim_handle_fork(
            nr == SYS_vfork ? SYS_fork : nr, args);
        return;
    }
    regs[REG_RAX] = shim_emulate_syscall(nr, args);
}

/* ------------------------------------------------------------------ */

static int install_seccomp_filter(void) {
    uintptr_t lo = (uintptr_t)__start_shim_text;
    uintptr_t hi = (uintptr_t)__stop_shim_text;
    if (hi <= lo) return -1;

    struct sock_filter filter[] = {
        /* A = arch; bail (allow) on non-x86_64 just in case */
        BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                 offsetof(struct seccomp_data, arch)),
        BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, AUDIT_ARCH_X86_64, 1, 0),
        BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_ALLOW),
        /* allow rt_sigreturn (signal trampoline lives outside shim text) */
        BPF_STMT(BPF_LD | BPF_W | BPF_ABS, offsetof(struct seccomp_data, nr)),
        BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, SYS_rt_sigreturn, 0, 1),
        BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_ALLOW),
        /* allow when instruction_pointer in [lo, hi) — the shim itself */
        BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                 offsetof(struct seccomp_data, instruction_pointer) + 4),
        BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, (uint32_t)(lo >> 32), 0, 4),
        BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                 offsetof(struct seccomp_data, instruction_pointer)),
        BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, (uint32_t)lo, 0, 2),
        BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, (uint32_t)hi, 1, 0),
        BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_ALLOW),
        /* everything else traps to SIGSYS */
        BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_TRAP),
    };
    struct sock_fprog prog = {
        .len = (unsigned short)(sizeof(filter) / sizeof(filter[0])),
        .filter = filter,
    };
    if (prctl(PR_SET_NO_NEW_PRIVS, 1, 0, 0, 0) != 0) return -1;
    if (prctl(PR_SET_SECCOMP, SECCOMP_MODE_FILTER, &prog) != 0) return -1;
    return 0;
}

/* ------------------------------------------------------------------ */

__attribute__((constructor)) static void shim_init(void) {
    const char *handle = getenv("SHADOW_TPU_IPC_HANDLE");
    if (!handle || !*handle) return; /* not under the simulator */

    if (shmem_deserialize(handle, &g_ipc_block) != 0) {
        fprintf(stderr, "shadow_tpu shim: cannot map IPC block %s\n", handle);
        _exit(112);
    }
    g_ipc = (IPCData *)g_ipc_block.addr;
    t_ipc = g_ipc;

    /* optional per-process clock block for the in-shim time fast path */
    const char *proc_handle = getenv("SHADOW_TPU_SHMEM_HANDLE");
    if (proc_handle && *proc_handle &&
        shmem_deserialize(proc_handle, &g_proc_block) == 0 &&
        g_proc_block.size >= sizeof(ProcessShmem)) {
        g_proc = (ProcessShmem *)g_proc_block.addr;
    }

    struct sigaction sa;
    memset(&sa, 0, sizeof(sa));
    sa.sa_sigaction = shim_sigsys_handler;
    sa.sa_flags = SA_SIGINFO | SA_NODEFER;
    if (sigaction(SIGSYS, &sa, NULL) != 0) _exit(113);

    /* trap rdtsc/rdtscp so cycle counters observe simulated time */
    struct sigaction segv;
    memset(&segv, 0, sizeof(segv));
    segv.sa_sigaction = shim_sigsegv_handler;
    segv.sa_flags = SA_SIGINFO | SA_NODEFER;
    if (sigaction(SIGSEGV, &segv, NULL) == 0) {
#ifndef PR_TSC_SIGSEGV
#define PR_TSC_SIGSEGV 2
#endif
        if (prctl(PR_SET_TSC, PR_TSC_SIGSEGV, 0, 0, 0) != 0)
            shim_log("shadow_tpu shim: PR_SET_TSC failed (rdtsc leaks real time)\n");
    }

    /* force vDSO time functions onto the (trappable) syscall path */
    if (shadow_tpu_patch_vdso() <= 0)
        shim_log("shadow_tpu shim: vdso patch failed (libc time may leak real time)\n");

    /* announce readiness (carries our pid for the simulator's records) */
    ShimEvent ev;
    memset(&ev, 0, sizeof(ev));
    ev.kind = SHIM_EVENT_START_RES;
    ev.u.add_thread_res.child_native_tid = (int64_t)getpid();
    ipc_to_shadow_send(g_ipc, &ev);

    if (install_seccomp_filter() != 0) _exit(114);
    g_interposing = 1;
    shim_log("shadow_tpu shim: interposition active\n");
}

__attribute__((destructor)) static void shim_fini(void) {
    if (g_ipc && g_interposing) {
        ShimEvent ev;
        memset(&ev, 0, sizeof(ev));
        ev.kind = SHIM_EVENT_PROCESS_DEATH;
        ipc_to_shadow_send(g_ipc, &ev);
    }
}
