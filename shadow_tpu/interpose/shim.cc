/* The interposition shim: LD_PRELOADed into managed processes.
 *
 * Parity: reference src/lib/shim — on load, attach the IPC shared-memory
 * block named by SHADOW_TPU_IPC_HANDLE, install a seccomp filter that
 * allows syscalls issued from the shim's own text range and traps every
 * other syscall to SIGSYS (shim_seccomp.c:144-260), then forward each
 * trapped syscall to the simulator over the futex channel and either
 * return the simulated result or re-execute natively from shim text
 * (shim_api_syscall.c / shim_sys.c). The reference also patches the vDSO
 * so clock_gettime etc. take the syscall path (patch_vdso.c) — same here,
 * by overwriting vDSO entry points with a jump to a trapping stub.
 *
 * Scope (round 1): single-threaded managed processes; clone/fork are
 * answered natively but child threads are not yet individually managed.
 */

#define _GNU_SOURCE 1
#include <errno.h>
#include <linux/audit.h>
#include <linux/filter.h>
#include <linux/seccomp.h>
#include <signal.h>
#include <stddef.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/prctl.h>
#include <sys/syscall.h>
#include <ucontext.h>
#include <unistd.h>

#include "ipc.h"
#include "shim_shmem.h"
#include "shmem.h"

/* ------------------------------------------------------------------ */
/* Raw syscall from *shim text* — the only code the seccomp filter
 * whitelists by instruction pointer. Must not call into libc. */

#include "shim_syscall.h"
#define shim_raw_syscall shim_text_syscall

/* ------------------------------------------------------------------ */

extern "C" int shadow_tpu_patch_vdso(void);

static ShMemBlock g_ipc_block;
static IPCData *g_ipc = NULL;
static int g_interposing = 0;

/* per-process clock block (optional; fast path off when absent) */
static ShMemBlock g_proc_block;
static ProcessShmem *g_proc = NULL;

/* ------------------------------------------------------------------ */
/* In-shim time fast path (shim_sys.c:25-80): answer clock reads from
 * the shared clock, charging the modeled syscall latency, while the
 * advanced clock stays below the runahead bound. Returns 1 when the
 * syscall was fully handled locally. */

struct shim_timespec { int64_t tv_sec; int64_t tv_nsec; };
struct shim_timeval { int64_t tv_sec; int64_t tv_usec; };

static int clockid_is_monotonic(long clockid) {
    /* MONOTONIC(1), MONOTONIC_RAW(4), MONOTONIC_COARSE(6), BOOTTIME(7) */
    return clockid == 1 || clockid == 4 || clockid == 6 || clockid == 7;
}

static int shim_try_time_fastpath(long nr, const uint64_t args[6],
                                  long *out_ret) {
    if (!g_proc || !__atomic_load_n(&g_proc->enabled, __ATOMIC_ACQUIRE))
        return 0;
    if (nr != SYS_clock_gettime && nr != SYS_gettimeofday && nr != SYS_time)
        return 0;
    uint64_t now = g_proc->sim_time_ns + g_proc->syscall_latency_ns;
    if (now > g_proc->max_runahead_ns)
        return 0; /* runahead exhausted: yield to the simulator via IPC */
    g_proc->sim_time_ns = now;

    if (nr == SYS_clock_gettime) {
        long clockid = (long)args[0];
        struct shim_timespec *ts = (struct shim_timespec *)args[1];
        uint64_t ns = clockid_is_monotonic(clockid)
                          ? now
                          : g_proc->epoch_offset_ns + now;
        if (ts) {
            ts->tv_sec = (int64_t)(ns / 1000000000ull);
            ts->tv_nsec = (int64_t)(ns % 1000000000ull);
        }
        *out_ret = 0;
        return 1;
    }
    if (nr == SYS_gettimeofday) {
        struct shim_timeval *tv = (struct shim_timeval *)args[0];
        uint64_t ns = g_proc->epoch_offset_ns + now;
        if (tv) {
            tv->tv_sec = (int64_t)(ns / 1000000000ull);
            tv->tv_usec = (int64_t)((ns % 1000000000ull) / 1000);
        }
        *out_ret = 0;
        return 1;
    }
    /* SYS_time */
    uint64_t sec = (g_proc->epoch_offset_ns + now) / 1000000000ull;
    if (args[0]) *(int64_t *)args[0] = (int64_t)sec;
    *out_ret = (long)sec;
    return 1;
}

/* The seccomp IP whitelist covers the "shim_text" section, which holds
 * every syscall *instruction* the shim itself executes (shim_raw_syscall
 * here, raw_futex in scchannel.cc). The linker defines the bounds. */
extern char __start_shim_text[];
extern char __stop_shim_text[];

static void shim_log(const char *msg) {
    if (getenv("SHADOW_TPU_SHIM_DEBUG"))
        shim_raw_syscall(SYS_write, 2, (long)msg, (long)strlen(msg), 0, 0, 0);
}

/* Forward one syscall to the simulator; returns the value to hand back. */
static long shim_emulate_syscall(long nr, const uint64_t args[6]) {
    ShimEvent ev;
    memset(&ev, 0, sizeof(ev));
    ev.kind = SHIM_EVENT_SYSCALL;
    ev.u.syscall.number = nr;
    for (int i = 0; i < 6; i++) ev.u.syscall.args[i] = args[i];
    if (ipc_to_shadow_send(g_ipc, &ev) != 0) {
        /* simulator is gone: die quietly */
        shim_raw_syscall(SYS_exit_group, 1, 0, 0, 0, 0, 0);
    }
    ShimEvent reply;
    long n = ipc_to_shim_recv(g_ipc, &reply);
    if (n < 0) shim_raw_syscall(SYS_exit_group, 1, 0, 0, 0, 0, 0);
    if (reply.kind == SHIM_EVENT_SYSCALL_DO_NATIVE) {
        return shim_raw_syscall(nr, (long)args[0], (long)args[1], (long)args[2],
                                (long)args[3], (long)args[4], (long)args[5]);
    }
    return reply.u.complete.retval;
}

/* ------------------------------------------------------------------ */
/* rdtsc/rdtscp trap-and-emulate (reference src/lib/shim/shim_rdtsc.c +
 * src/lib/tsc): PR_SET_TSC(PR_TSC_SIGSEGV) makes every rdtsc fault; the
 * SIGSEGV handler decodes the instruction and returns the EMULATED
 * cycle count — a nominal 1 GHz TSC, so cycles == simulated ns — then
 * skips the instruction. Without this, real time leaks into any binary
 * using rdtsc (most modern language runtimes via their clock vDSO
 * fallbacks). */

static uint64_t shim_emulated_tsc_ns(void) {
    if (g_proc && __atomic_load_n(&g_proc->enabled, __ATOMIC_ACQUIRE)) {
        /* charge the modeled latency and honor the runahead bound like
         * the clock_gettime fast path — a TSC spin-wait must advance
         * time and eventually yield, or the simulation livelocks */
        uint64_t now = g_proc->sim_time_ns + g_proc->syscall_latency_ns;
        if (now <= g_proc->max_runahead_ns) {
            g_proc->sim_time_ns = now;
            return now;
        }
    }
    /* no shared clock yet, or runahead exhausted: ask the simulator
     * (full IPC round trip; it parks us until sim time catches up) */
    struct shim_timespec ts = {0, 0};
    uint64_t args[6] = {1 /* CLOCK_MONOTONIC */, (uint64_t)&ts, 0, 0, 0, 0};
    shim_emulate_syscall(SYS_clock_gettime, args);
    return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

static void shim_sigsegv_handler(int sig, siginfo_t *info, void *ucontext) {
    ucontext_t *ctx = (ucontext_t *)ucontext;
    greg_t *regs = ctx->uc_mcontext.gregs;
    const uint8_t *ip = (const uint8_t *)regs[REG_RIP];
    /* An exec fault (jump to unmapped memory) has si_addr == RIP; reading
     * the instruction bytes would re-fault and recurse. Only decode when
     * the faulting address is NOT the instruction pointer (a PR_SET_TSC
     * trap reports si_addr = NULL with RIP at the rdtsc). */
    int decodable = ip && (const uint8_t *)info->si_addr != ip;
    int is_rdtsc = decodable && ip[0] == 0x0f && ip[1] == 0x31;
    int is_rdtscp =
        decodable && ip[0] == 0x0f && ip[1] == 0x01 && ip[2] == 0xf9;
    if (!is_rdtsc && !is_rdtscp) {
        /* a real crash: fall back to default disposition and re-raise.
         * Raw syscalls only — libc getpid() is interposed by the preload
         * wrappers and would return the VIRTUAL pid (and re-enter IPC
         * from inside a crash handler). */
        signal(SIGSEGV, SIG_DFL);
        long tgid = shim_raw_syscall(SYS_getpid, 0, 0, 0, 0, 0, 0);
        long tid = shim_raw_syscall(SYS_gettid, 0, 0, 0, 0, 0, 0);
        shim_raw_syscall(SYS_tgkill, tgid, tid, SIGSEGV, 0, 0, 0);
        return;
    }
    uint64_t tsc = shim_emulated_tsc_ns(); /* 1 GHz: cycles == ns */
    regs[REG_RAX] = (greg_t)(tsc & 0xffffffffu);
    regs[REG_RDX] = (greg_t)(tsc >> 32);
    if (is_rdtscp) {
        regs[REG_RCX] = 0; /* IA32_TSC_AUX: cpu 0, node 0 */
        regs[REG_RIP] += 3;
    } else {
        regs[REG_RIP] += 2;
    }
    (void)sig;
    (void)info;
}

/* Direct entry for the preload-libc wrappers (reference
 * src/lib/preload-libc + shim_api_syscall.c): same dispatch as the
 * SIGSYS path but via a plain function call — no signal delivery, no
 * kernel round trip for locally-answered syscalls. */
extern "C" long shadow_tpu_api_syscall(long nr, long a, long b, long c,
                                       long d, long e, long f) {
    if (!g_interposing)
        return shim_raw_syscall(nr, a, b, c, d, e, f);
    uint64_t args[6] = {(uint64_t)a, (uint64_t)b, (uint64_t)c,
                        (uint64_t)d, (uint64_t)e, (uint64_t)f};
    long fast;
    if (shim_try_time_fastpath(nr, args, &fast)) return fast;
    return shim_emulate_syscall(nr, args);
}

static void shim_sigsys_handler(int sig, siginfo_t *info, void *ucontext) {
    (void)sig;
    ucontext_t *ctx = (ucontext_t *)ucontext;
    greg_t *regs = ctx->uc_mcontext.gregs;
    long nr = info->si_syscall;
    uint64_t args[6] = {
        (uint64_t)regs[REG_RDI], (uint64_t)regs[REG_RSI],
        (uint64_t)regs[REG_RDX], (uint64_t)regs[REG_R10],
        (uint64_t)regs[REG_R8],  (uint64_t)regs[REG_R9],
    };
    long fast_ret;
    if (shim_try_time_fastpath(nr, args, &fast_ret)) {
        regs[REG_RAX] = fast_ret;
        return;
    }
    regs[REG_RAX] = shim_emulate_syscall(nr, args);
}

/* ------------------------------------------------------------------ */

static int install_seccomp_filter(void) {
    uintptr_t lo = (uintptr_t)__start_shim_text;
    uintptr_t hi = (uintptr_t)__stop_shim_text;
    if (hi <= lo) return -1;

    struct sock_filter filter[] = {
        /* A = arch; bail (allow) on non-x86_64 just in case */
        BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                 offsetof(struct seccomp_data, arch)),
        BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, AUDIT_ARCH_X86_64, 1, 0),
        BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_ALLOW),
        /* allow rt_sigreturn (signal trampoline lives outside shim text) */
        BPF_STMT(BPF_LD | BPF_W | BPF_ABS, offsetof(struct seccomp_data, nr)),
        BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, SYS_rt_sigreturn, 0, 1),
        BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_ALLOW),
        /* allow when instruction_pointer in [lo, hi) — the shim itself */
        BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                 offsetof(struct seccomp_data, instruction_pointer) + 4),
        BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, (uint32_t)(lo >> 32), 0, 4),
        BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                 offsetof(struct seccomp_data, instruction_pointer)),
        BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, (uint32_t)lo, 0, 2),
        BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, (uint32_t)hi, 1, 0),
        BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_ALLOW),
        /* everything else traps to SIGSYS */
        BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_TRAP),
    };
    struct sock_fprog prog = {
        .len = (unsigned short)(sizeof(filter) / sizeof(filter[0])),
        .filter = filter,
    };
    if (prctl(PR_SET_NO_NEW_PRIVS, 1, 0, 0, 0) != 0) return -1;
    if (prctl(PR_SET_SECCOMP, SECCOMP_MODE_FILTER, &prog) != 0) return -1;
    return 0;
}

/* ------------------------------------------------------------------ */

__attribute__((constructor)) static void shim_init(void) {
    const char *handle = getenv("SHADOW_TPU_IPC_HANDLE");
    if (!handle || !*handle) return; /* not under the simulator */

    if (shmem_deserialize(handle, &g_ipc_block) != 0) {
        fprintf(stderr, "shadow_tpu shim: cannot map IPC block %s\n", handle);
        _exit(112);
    }
    g_ipc = (IPCData *)g_ipc_block.addr;

    /* optional per-process clock block for the in-shim time fast path */
    const char *proc_handle = getenv("SHADOW_TPU_SHMEM_HANDLE");
    if (proc_handle && *proc_handle &&
        shmem_deserialize(proc_handle, &g_proc_block) == 0 &&
        g_proc_block.size >= sizeof(ProcessShmem)) {
        g_proc = (ProcessShmem *)g_proc_block.addr;
    }

    struct sigaction sa;
    memset(&sa, 0, sizeof(sa));
    sa.sa_sigaction = shim_sigsys_handler;
    sa.sa_flags = SA_SIGINFO | SA_NODEFER;
    if (sigaction(SIGSYS, &sa, NULL) != 0) _exit(113);

    /* trap rdtsc/rdtscp so cycle counters observe simulated time */
    struct sigaction segv;
    memset(&segv, 0, sizeof(segv));
    segv.sa_sigaction = shim_sigsegv_handler;
    segv.sa_flags = SA_SIGINFO | SA_NODEFER;
    if (sigaction(SIGSEGV, &segv, NULL) == 0) {
#ifndef PR_TSC_SIGSEGV
#define PR_TSC_SIGSEGV 2
#endif
        if (prctl(PR_SET_TSC, PR_TSC_SIGSEGV, 0, 0, 0) != 0)
            shim_log("shadow_tpu shim: PR_SET_TSC failed (rdtsc leaks real time)\n");
    }

    /* force vDSO time functions onto the (trappable) syscall path */
    if (shadow_tpu_patch_vdso() <= 0)
        shim_log("shadow_tpu shim: vdso patch failed (libc time may leak real time)\n");

    /* announce readiness (carries our pid for the simulator's records) */
    ShimEvent ev;
    memset(&ev, 0, sizeof(ev));
    ev.kind = SHIM_EVENT_START_RES;
    ev.u.add_thread_res.child_native_tid = (int64_t)getpid();
    ipc_to_shadow_send(g_ipc, &ev);

    if (install_seccomp_filter() != 0) _exit(114);
    g_interposing = 1;
    shim_log("shadow_tpu shim: interposition active\n");
}

__attribute__((destructor)) static void shim_fini(void) {
    if (g_ipc && g_interposing) {
        ShimEvent ev;
        memset(&ev, 0, sizeof(ev));
        ev.kind = SHIM_EVENT_PROCESS_DEATH;
        ipc_to_shadow_send(g_ipc, &ev);
    }
}
