/* Per-process shared state between the simulator and the shim.
 *
 * Parity: reference src/lib/shadow-shim-helper-rs/src/shim_shmem.rs
 * (ProcessShmem / HostShmem protected clock fields) + the in-shim hot
 * path it powers (src/lib/shim/shim_sys.c:25-80,200-226): time syscalls
 * are answered INSIDE the managed process from this block — zero IPC
 * round trips — with a per-syscall latency accumulated into the clock,
 * advancing locally while it stays under the round's runahead bound.
 * Crossing the bound falls back to the full IPC path, which hands
 * control to the simulator at the barrier (the reference's
 * SYS_shadow_yield has the same effect).
 *
 * Single-writer discipline: the simulator writes while the shim is
 * blocked in recv; the shim writes sim_time_ns while the simulator is
 * blocked in recv. Strict rendezvous alternation means no concurrent
 * writers; loads/stores are plain (the futex channel provides the
 * ordering).
 */
#ifndef SHADOW_TPU_SHIM_SHMEM_H
#define SHADOW_TPU_SHIM_SHMEM_H

#include <stdint.h>

#include "vasi.h"

#ifdef __cplusplus
extern "C" {
#endif

typedef struct ProcessShmem {
    /* simulation clock (ns); monotonic-clock zero == simulation start */
    uint64_t sim_time_ns;
    /* the shim may advance sim_time_ns locally up to this bound
     * (current round end); beyond it, syscalls take the IPC path */
    uint64_t max_runahead_ns;
    /* emulated-epoch offset: REALTIME = offset + sim_time
     * (reference EmulatedTime epoch 2000-01-01, emulated_time.rs:18-45) */
    uint64_t epoch_offset_ns;
    /* modeled cost charged per locally-answered syscall */
    uint64_t syscall_latency_ns;
    /* 1 = the fast path is enabled (simulator has initialized bounds) */
    uint32_t enabled;
    uint32_t _pad;
} ProcessShmem;

#ifdef __cplusplus
}
SHADOW_TPU_ASSERT_VASI(ProcessShmem);
#endif
#endif
