/* The one raw-syscall primitive shared by all shim-side code.
 *
 * Lives in the "shim_text" linker section: the seccomp filter whitelists
 * exactly [__start_shim_text, __stop_shim_text), so syscall instructions
 * here execute natively while everything else in the process traps to
 * SIGSYS (reference shim_seccomp.c's shim-IP allowance). `static` gives
 * each translation unit its own copy — both land in the section.
 *
 * Must not call libc (libc IPs would trap, recursing into the handler).
 */
#ifndef SHADOW_TPU_SHIM_SYSCALL_H
#define SHADOW_TPU_SHIM_SYSCALL_H

#define SHIM_TEXT __attribute__((section("shim_text"), noinline, unused))

SHIM_TEXT static long shim_text_syscall(long nr, long a1, long a2, long a3,
                                        long a4, long a5, long a6) {
    register long r10 __asm__("r10") = a4;
    register long r8 __asm__("r8") = a5;
    register long r9 __asm__("r9") = a6;
    long ret;
    __asm__ volatile("syscall"
                     : "=a"(ret)
                     : "a"(nr), "D"(a1), "S"(a2), "d"(a3), "r"(r10), "r"(r8),
                       "r"(r9)
                     : "rcx", "r11", "memory");
    return ret;
}

#endif
