#include "shmem.h"

#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <limits.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

static int g_counter = 0;

int shmem_alloc(size_t size, ShMemBlock *out) {
    if (!out || size == 0) return -1;
    memset(out, 0, sizeof(*out));
    snprintf(out->name, sizeof(out->name), "/shadow_tpu_shm_%d_%d",
             (int)getpid(), __atomic_fetch_add(&g_counter, 1, __ATOMIC_RELAXED));
    int fd = shm_open(out->name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) return -1;
    if (ftruncate(fd, (off_t)size) != 0) {
        close(fd);
        shm_unlink(out->name);
        return -1;
    }
    void *addr = mmap(NULL, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    close(fd);
    if (addr == MAP_FAILED) {
        shm_unlink(out->name);
        return -1;
    }
    out->addr = addr;
    out->size = size;
    out->owner = 1;
    return 0;
}

int shmem_serialize(const ShMemBlock *block, char *out) {
    if (!block || !out) return -1;
    snprintf(out, SHMEM_HANDLE_MAX, "%s:%zu", block->name, block->size);
    return 0;
}

int shmem_deserialize(const char *handle, ShMemBlock *out) {
    if (!handle || !out) return -1;
    memset(out, 0, sizeof(*out));
    const char *colon = strrchr(handle, ':');
    if (!colon) return -1;
    size_t name_len = (size_t)(colon - handle);
    if (name_len >= sizeof(out->name)) return -1;
    memcpy(out->name, handle, name_len);
    out->name[name_len] = '\0';
    out->size = strtoull(colon + 1, NULL, 10);
    if (out->size == 0) return -1;
    int fd = shm_open(out->name, O_RDWR, 0600);
    if (fd < 0) return -1;
    void *addr = mmap(NULL, out->size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    close(fd);
    if (addr == MAP_FAILED) return -1;
    out->addr = addr;
    out->owner = 0;
    return 0;
}

int shmem_free(ShMemBlock *block) {
    if (!block || !block->addr) return -1;
    munmap(block->addr, block->size);
    int rc = 0;
    if (block->owner) rc = shm_unlink(block->name);
    block->addr = NULL;
    return rc;
}

int shmem_cleanup(void) {
    DIR *d = opendir("/dev/shm");
    if (!d) return 0;
    int removed = 0;
    struct dirent *e;
    char self_prefix[64];
    snprintf(self_prefix, sizeof(self_prefix), "shadow_tpu_shm_%d_", (int)getpid());
    while ((e = readdir(d)) != NULL) {
        if (strncmp(e->d_name, "shadow_tpu_shm_", 15) != 0) continue;
        if (strncmp(e->d_name, self_prefix, strlen(self_prefix)) == 0) continue;
        /* Reclaim only when the owner is provably dead (ESRCH); EPERM
         * means alive-but-other-user — leave those alone. */
        int pid = atoi(e->d_name + 15);
        if (pid > 0 && !(kill(pid, 0) != 0 && errno == ESRCH)) continue;
        char path[NAME_MAX + 2];
        snprintf(path, sizeof(path), "/%s", e->d_name);
        if (shm_unlink(path) == 0) removed++;
    }
    closedir(d);
    return removed;
}
