/* Cross-process shared-memory blocks with serializable handles.
 *
 * Parity: reference src/lib/shmem (Rust) — an allocator whose blocks can be
 * serialized to a string handle, passed to another process (over IPC or
 * argv/env), and mapped there at a different address. All data structures
 * placed inside must therefore be position-independent (no raw pointers) —
 * the property the reference proves with the VirtualAddressSpaceIndependent
 * trait (src/lib/vasi) and we assert with standard-layout/trivially-copyable
 * static_asserts in ipc.h.
 *
 * Implementation: one POSIX shm object (shm_open) per block. The reference
 * sub-allocates pools; block-per-allocation is simpler and sufficient for
 * the per-thread IPC blocks the interposition plane needs (one IPCData per
 * managed thread, reference ipc.rs).
 */
#ifndef SHADOW_TPU_SHMEM_H
#define SHADOW_TPU_SHMEM_H

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Serialized handle: fits in a fixed buffer, printable, NUL-terminated. */
#define SHMEM_HANDLE_MAX 128

typedef struct ShMemBlock {
    void *addr;
    size_t size;
    char name[64];  /* shm object name, e.g. "/shadow_tpu_shm_<pid>_<n>" */
    int owner;      /* owner unlinks the shm object on free */
} ShMemBlock;

/* Allocate a zeroed shared block; returns 0 on success. */
int shmem_alloc(size_t size, ShMemBlock *out);

/* Write a printable handle for the block into out[SHMEM_HANDLE_MAX]. */
int shmem_serialize(const ShMemBlock *block, char *out);

/* Map a block from a serialized handle (in another process). */
int shmem_deserialize(const char *handle, ShMemBlock *out);

/* Unmap; the owning side also unlinks the shm object. */
int shmem_free(ShMemBlock *block);

/* Unlink any leftover shadow_tpu shm objects from dead runs
 * (parity: shadow.rs shm_cleanup). Returns number removed. */
int shmem_cleanup(void);

#ifdef __cplusplus
}
#endif
#endif
