/* Virtual-Address-Space-Independence marker (reference src/lib/vasi:
 * a derive macro asserting a type is safe to share across address
 * spaces). The C++ equivalent: standard layout (no vtables, predictable
 * member order), trivial copyability (memcpy-safe across processes),
 * and — enforced by review, not the compiler — no pointer members.
 * Apply SHADOW_TPU_ASSERT_VASI to EVERY type that crosses a process
 * boundary through shmem: the shim event vocabulary (ipc.h), the
 * channels (scchannel.h), and the clock/process blocks (shim_shmem.h)
 * all carry it. */
#ifndef SHADOW_TPU_VASI_H
#define SHADOW_TPU_VASI_H

#ifdef __cplusplus
#include <type_traits>

#define SHADOW_TPU_ASSERT_VASI(T)                                        \
    static_assert(std::is_standard_layout<T>::value &&                   \
                      std::is_trivially_copyable<T>::value,              \
                  #T " must be virtual-address-space independent "       \
                     "(standard layout + trivially copyable, "           \
                     "no pointers)")
#else
#define SHADOW_TPU_ASSERT_VASI(T)
#endif

#endif /* SHADOW_TPU_VASI_H */
