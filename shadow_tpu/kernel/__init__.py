"""The simulated kernel: file state/listener plane, descriptors, sockets,
timers, and (via the process plane) blocking-call conditions.

Parity: reference `src/main/host/descriptor/` + `src/main/host/syscall/` —
the layer between applications and the host's network/timer machinery.
"""
