"""The file-descriptor table: fd integers -> open files.

Parity: reference `src/main/host/descriptor/mod.rs` `DescriptorTable` —
lowest-available fd allocation, dup sharing the same underlying file,
close-on-last-reference, and explicit fd targets (dup2). Flags (CLOEXEC)
are per-descriptor, not per-file.
"""

from __future__ import annotations

from typing import Optional

from . import errors


class Descriptor:
    __slots__ = ("file", "cloexec")

    def __init__(self, file, cloexec: bool = False):
        self.file = file
        self.cloexec = cloexec


class DescriptorTable:
    def __init__(self):
        self._table: dict[int, Descriptor] = {}
        self._next_hint = 0

    def register(self, file, cloexec: bool = False) -> int:
        fd = self._lowest_free()
        self._table[fd] = Descriptor(file, cloexec)
        return fd

    def register_at(self, fd: int, file, cloexec: bool = False) -> int:
        """dup2-style: closes whatever occupied fd first."""
        if fd < 0:
            raise errors.SyscallError(errors.EBADF)
        if fd in self._table:
            self.close(fd)
        self._table[fd] = Descriptor(file, cloexec)
        return fd

    def get(self, fd: int):
        entry = self._table.get(fd)
        if entry is None:
            raise errors.SyscallError(errors.EBADF)
        return entry.file

    def dup(self, fd: int) -> int:
        entry = self._table.get(fd)
        if entry is None:
            raise errors.SyscallError(errors.EBADF)
        new_fd = self._lowest_free()
        self._table[new_fd] = Descriptor(entry.file, cloexec=False)
        return new_fd

    def close(self, fd: int) -> None:
        entry = self._table.pop(fd, None)
        if entry is None:
            raise errors.SyscallError(errors.EBADF)
        # close the file only when no other descriptor references it
        if not any(d.file is entry.file for d in self._table.values()):
            entry.file.close()

    def close_all(self) -> None:
        for fd in sorted(self._table):
            try:
                self.close(fd)
            except errors.SyscallError:
                pass

    def fds(self) -> list[int]:
        return sorted(self._table)

    def _lowest_free(self) -> int:
        fd = 0
        while fd in self._table:
            fd += 1
        return fd
