"""The file-descriptor table: fd integers -> open files.

Parity: reference `src/main/host/descriptor/mod.rs` `DescriptorTable` —
lowest-available fd allocation, dup sharing the same underlying file,
close-on-last-reference, and explicit fd targets (dup2). Flags (CLOEXEC)
are per-descriptor, not per-file.

Files are refcounted across ALL tables referencing them (`_open_refs` on
the file object, the moral equivalent of the reference's Arc<File>): fork
clones the parent's table into the child (`process.rs:591`
new_forked_process), after which both processes hold descriptors to the
same open files and the file closes only when the last one goes away.
"""

from __future__ import annotations

from . import errors


class Descriptor:
    __slots__ = ("file", "cloexec")

    def __init__(self, file, cloexec: bool = False):
        self.file = file
        self.cloexec = cloexec


def _ref(file) -> None:
    file._open_refs = getattr(file, "_open_refs", 0) + 1


def _unref(file) -> None:
    file._open_refs = getattr(file, "_open_refs", 1) - 1
    if file._open_refs <= 0:
        file.close()


# The canonical fd-geometry constants (syscall_handler and managed.py
# import these — one definition, no comment-tied copies):
# virtual fds live at VFD_BASE + slot; the limit the GUEST sees from
# getrlimit/prlimit64 is VISIBLE_FD_LIMIT (it must cover the virtual
# range — glibc validates fds against sysconf(_SC_OPEN_MAX)); the
# kernel-enforced cap on the NATIVE table at spawn is VFD_BASE, so
# native fds can never collide with virtual ones. Everything stays
# below FD_SETSIZE so select() on virtual fds is legal.
VFD_BASE = 700
VISIBLE_FD_LIMIT = 1024
assert VISIBLE_FD_LIMIT <= 1024  # FD_SETSIZE


class DescriptorTable:
    # allocation past the visible limit is EMFILE / EBADF, exactly what
    # a process at its fd limit sees
    CAPACITY = VISIBLE_FD_LIMIT - VFD_BASE

    def __init__(self):
        self._table: dict[int, Descriptor] = {}

    def register(self, file, cloexec: bool = False) -> int:
        fd = self._lowest_free()
        if fd >= self.CAPACITY:
            raise errors.SyscallError(errors.EMFILE)
        self._table[fd] = Descriptor(file, cloexec)
        _ref(file)
        return fd

    def register_at(self, fd: int, file, cloexec: bool = False) -> int:
        """dup2-style: closes whatever occupied fd first. A target past
        the visible fd limit is EBADF like Linux's dup2 past
        RLIMIT_NOFILE."""
        if fd < 0 or fd >= self.CAPACITY:
            raise errors.SyscallError(errors.EBADF)
        if fd in self._table:
            self.close(fd)
        self._table[fd] = Descriptor(file, cloexec)
        _ref(file)
        return fd

    def get(self, fd: int):
        entry = self._table.get(fd)
        if entry is None:
            raise errors.SyscallError(errors.EBADF)
        return entry.file

    def dup(self, fd: int) -> int:
        entry = self._table.get(fd)
        if entry is None:
            raise errors.SyscallError(errors.EBADF)
        new_fd = self._lowest_free()
        self._table[new_fd] = Descriptor(entry.file, cloexec=False)
        _ref(entry.file)
        return new_fd

    def close(self, fd: int) -> None:
        entry = self._table.pop(fd, None)
        if entry is None:
            raise errors.SyscallError(errors.EBADF)
        _unref(entry.file)

    def close_all(self) -> None:
        for fd in sorted(self._table):
            try:
                self.close(fd)
            except errors.SyscallError:
                pass

    def close_cloexec(self) -> None:
        """execve(2): drop every descriptor opened with CLOEXEC."""
        for fd in [f for f, e in self._table.items() if e.cloexec]:
            try:
                self.close(fd)
            except errors.SyscallError:
                pass

    def fork_into(self) -> "DescriptorTable":
        """fork(2) semantics: the child gets its own fd table whose entries
        reference the same open files (shared offsets/state)."""
        child = DescriptorTable()
        for fd, entry in self._table.items():
            child._table[fd] = Descriptor(entry.file, entry.cloexec)
            _ref(entry.file)
        return child

    def fds(self) -> list[int]:
        return sorted(self._table)

    def _lowest_free(self) -> int:
        fd = 0
        while fd in self._table:
            fd += 1
        return fd
