"""epoll: scalable readiness notification over simulated files.

Parity: reference `src/main/host/descriptor/epoll/` — an interest list of
(file, events, data) entries; level-triggered by default with EPOLLET
edge-triggering and EPOLLONESHOT; the epoll instance is itself a
StatefulFile whose READABLE bit reflects a non-empty ready set, so epolls
nest and blocked `epoll_wait`s park on an ordinary condition.
"""

from __future__ import annotations

import enum
from typing import Optional

from . import errors
from .status import CallbackQueue, FileSignal, FileState, ListenerFilter, StatefulFile


class EpollEvents(enum.IntFlag):
    IN = 0x001  # readable
    OUT = 0x004  # writable
    ERR = 0x008
    HUP = 0x010
    ET = 1 << 31  # edge-triggered
    ONESHOT = 1 << 30


def _file_state_to_events(state: FileState) -> EpollEvents:
    ev = EpollEvents(0)
    if state & FileState.READABLE:
        ev |= EpollEvents.IN
    if state & FileState.WRITABLE:
        ev |= EpollEvents.OUT
    if state & FileState.CLOSED:
        ev |= EpollEvents.HUP
    return ev


_MONITOR = FileState.READABLE | FileState.WRITABLE | FileState.CLOSED


class _Entry:
    __slots__ = ("file", "events", "data", "listener", "armed")

    def __init__(self, file, events: EpollEvents, data):
        self.file = file
        self.events = events
        self.data = data
        self.listener: Optional[int] = None
        self.armed = True  # ONESHOT disarms after a report


class Epoll(StatefulFile):
    def __init__(self):
        super().__init__(FileState.ACTIVE)
        self._entries: dict[int, _Entry] = {}  # keyed by id(file)

    # -- interest list (epoll_ctl) --------------------------------------

    def add(self, file, events: EpollEvents, data=None) -> None:
        key = id(file)
        if key in self._entries:
            raise errors.SyscallError(errors.EEXIST)
        entry = _Entry(file, events, data if data is not None else file)
        entry.listener = file.add_listener(
            _MONITOR, ListenerFilter.ALWAYS, self._make_callback(entry),
            signals=FileSignal.READ_BUFFER_GREW,
        )
        self._entries[key] = entry
        self._refresh()

    def modify(self, file, events: EpollEvents, data=None) -> None:
        entry = self._entries.get(id(file))
        if entry is None:
            raise errors.SyscallError(errors.ENOENT)
        entry.events = events
        if data is not None:
            entry.data = data
        entry.armed = True
        self._refresh()

    def remove(self, file) -> None:
        entry = self._entries.pop(id(file), None)
        if entry is None:
            raise errors.SyscallError(errors.ENOENT)
        if entry.listener is not None:
            entry.file.remove_listener(entry.listener)
        self._refresh()

    # -- wait (epoll_wait) ----------------------------------------------

    def ready(self, max_events: int = 64) -> list[tuple]:
        """Collect up to max_events (data, events) pairs; non-blocking.
        Level-triggered entries re-report while the condition holds;
        edge-triggered entries only after a fresh transition (tracked via
        the armed flag)."""
        out = []
        for entry in list(self._entries.values()):
            if len(out) >= max_events:
                break
            if not entry.armed:
                continue
            hits = self._entry_ready(entry)
            if hits:
                out.append((entry.data, hits))
                if entry.events & EpollEvents.ONESHOT:
                    entry.armed = False
                elif entry.events & EpollEvents.ET:
                    entry.armed = False  # re-armed by the next transition
        self._refresh()
        return out

    def wait(self, max_events: int = 64):
        """Generator for the Syscalls facade: blocks until something is
        ready (level-triggered semantics drive the epoll's own READABLE)."""
        while True:
            got = self.ready(max_events)
            if got:
                return got
            yield errors.Blocked(self, FileState.READABLE)

    def close(self) -> None:
        if self.is_closed():
            return
        for entry in self._entries.values():
            if entry.listener is not None:
                entry.file.remove_listener(entry.listener)
        self._entries.clear()
        self.update_state(
            FileState.ACTIVE | FileState.READABLE | FileState.CLOSED, FileState.CLOSED
        )

    # -- internals -------------------------------------------------------

    def _entry_ready(self, entry: _Entry) -> EpollEvents:
        now = _file_state_to_events(entry.file.state)
        interest = entry.events | EpollEvents.ERR | EpollEvents.HUP
        return now & interest

    def _make_callback(self, entry: _Entry):
        def on_change(state: FileState, changed: FileState, cq: CallbackQueue):
            if entry.events & EpollEvents.ET:
                # Linux ET fires again on every new event: a fresh off->on
                # transition OR new activity while the bit stays on (the
                # signal path delivers the latter with changed == NONE,
                # e.g. more bytes arriving on an already-readable pipe)
                if (changed & state & _MONITOR) or changed == FileState.NONE:
                    entry.armed = True
            self._refresh()

        return on_change

    def _refresh(self) -> None:
        if self.is_closed():
            return
        any_ready = any(
            e.armed and self._entry_ready(e) for e in self._entries.values()
        )
        self.update_state(
            FileState.READABLE,
            FileState.READABLE if any_ready else FileState.NONE,
        )
