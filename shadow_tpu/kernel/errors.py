"""Syscall-level error vocabulary for the simulated kernel.

Parity: reference `SyscallError` (`src/main/host/syscall/types.rs`) — a
syscall either fails with an errno, or *blocks* on a file reaching a state
(plus optional timeout), carrying whether SA_RESTART semantics apply.
Python's stdlib `errno` provides the numeric values.
"""

from __future__ import annotations

import errno as _errno
from typing import Optional

# Re-export the names handlers use, so call sites read like the reference.
EAGAIN = _errno.EAGAIN
EWOULDBLOCK = _errno.EWOULDBLOCK
EBADF = _errno.EBADF
EINVAL = _errno.EINVAL
EINTR = _errno.EINTR
ENOSYS = _errno.ENOSYS
EMSGSIZE = _errno.EMSGSIZE
EDESTADDRREQ = _errno.EDESTADDRREQ
EADDRINUSE = _errno.EADDRINUSE
EADDRNOTAVAIL = _errno.EADDRNOTAVAIL
ECONNREFUSED = _errno.ECONNREFUSED
ECONNRESET = _errno.ECONNRESET
EISCONN = _errno.EISCONN
ENOTCONN = _errno.ENOTCONN
EALREADY = _errno.EALREADY
EINPROGRESS = _errno.EINPROGRESS
EPIPE = _errno.EPIPE
ETIMEDOUT = _errno.ETIMEDOUT
EOPNOTSUPP = _errno.EOPNOTSUPP
ENOBUFS = _errno.ENOBUFS
EPROTONOSUPPORT = _errno.EPROTONOSUPPORT
EAFNOSUPPORT = _errno.EAFNOSUPPORT
ENFILE = _errno.ENFILE
EMFILE = _errno.EMFILE
EFAULT = _errno.EFAULT
ENOTDIR = _errno.ENOTDIR
ENAMETOOLONG = _errno.ENAMETOOLONG
ESPIPE = _errno.ESPIPE
ENODEV = _errno.ENODEV
EACCES = _errno.EACCES
ECHILD = _errno.ECHILD
ESRCH = _errno.ESRCH
EPERM = _errno.EPERM
ENOENT = _errno.ENOENT
EEXIST = _errno.EEXIST
ERANGE = _errno.ERANGE
ENOTSOCK = _errno.ENOTSOCK


class SyscallError(Exception):
    """A simulated syscall failed with `err` (a positive errno value)."""

    def __init__(self, err: int, msg: str = ""):
        self.errno = err
        super().__init__(msg or _errno.errorcode.get(err, str(err)))


class Blocked(Exception):
    """A simulated syscall must block.

    Carries the file + state bits to wait for (and optionally a timeout in
    emulated ns). The process plane converts this into a condition that
    parks the calling thread (`SysCallCondition`, reference
    `syscall_condition.c`). `restartable` is the SA_RESTART eligibility bit.
    """

    def __init__(
        self,
        file,
        state_mask,
        *,
        timeout_ns: Optional[int] = None,
        restartable: bool = True,
        forever: bool = False,
    ):
        self.file = file
        self.state_mask = state_mask
        self.timeout_ns = timeout_ns
        self.restartable = restartable
        # opt-in signal-only park (pause/sigsuspend): no file and no
        # timeout trigger; only signal delivery (or teardown) unparks
        self.forever = forever
        super().__init__(f"blocked on {state_mask!r}")
