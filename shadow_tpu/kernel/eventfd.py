"""eventfd: a 64-bit kernel counter usable as a wakeup channel.

Parity: reference `src/main/host/descriptor/eventfd.rs` — read returns the
counter (and zeroes it; or decrements by 1 in semaphore mode), write adds;
READABLE when counter > 0, WRITABLE while a write of 1 wouldn't overflow.
"""

from __future__ import annotations

from . import errors
from .status import FileState, StatefulFile

_MAX = (1 << 64) - 2


class EventFd(StatefulFile):
    def __init__(self, initval: int = 0, semaphore: bool = False):
        super().__init__(FileState.ACTIVE | FileState.WRITABLE)
        self.counter = initval
        self.semaphore = semaphore
        self.nonblocking = False
        # Smallest value a blocked writer is waiting to add (0 = none). The
        # poll-visible WRITABLE bit keeps Linux's "a write of 1 won't block"
        # meaning; blocked writers wait on EVENTFD_WRITE_SPACE, which turns
        # on once a read makes room for the smallest waiter. Tracking the
        # min means wakeups can be spurious (a larger waiter retries and
        # re-blocks) but never missed.
        self._pending_write = 0
        self._refresh()

    def read_value(self) -> int:
        if self.is_closed():
            raise errors.SyscallError(errors.EBADF)
        if self.counter == 0:
            if self.nonblocking:
                raise errors.SyscallError(errors.EWOULDBLOCK)
            raise errors.Blocked(self, FileState.READABLE)
        if self.semaphore:
            self.counter -= 1
            value = 1
        else:
            value, self.counter = self.counter, 0
        self._refresh()
        return value

    def write_value(self, value: int) -> None:
        if self.is_closed():
            raise errors.SyscallError(errors.EBADF)
        if value >= (1 << 64) - 1:
            raise errors.SyscallError(errors.EINVAL)
        if self.counter + value > _MAX:
            if self.nonblocking:
                raise errors.SyscallError(errors.EWOULDBLOCK)
            if self.state & FileState.EVENTFD_WRITE_SPACE:
                # The bit is on yet this write doesn't fit: whatever smaller
                # value it was advertising is stale (a cancelled writer) or
                # already consumed (every armed waiter fired when it turned
                # on). Re-seed from this writer so the bit turns OFF instead
                # of livelocking an immediate-wakeup retry loop.
                self._pending_write = value
            else:
                self._pending_write = (
                    value if self._pending_write == 0
                    else min(self._pending_write, value)
                )
            self._refresh()
            raise errors.Blocked(self, FileState.EVENTFD_WRITE_SPACE)
        self.counter += value
        self._pending_write = 0
        self._refresh()

    def close(self) -> None:
        if self.is_closed():
            return
        self.update_state(
            FileState.ACTIVE | FileState.READABLE | FileState.WRITABLE | FileState.CLOSED,
            FileState.CLOSED,
        )

    def _refresh(self) -> None:
        if self.is_closed():
            return
        values = FileState.NONE
        if self.counter > 0:
            values |= FileState.READABLE
        if self.counter + 1 <= _MAX:
            values |= FileState.WRITABLE
        if self.counter + max(1, self._pending_write) <= _MAX:
            values |= FileState.EVENTFD_WRITE_SPACE
        self.update_state(
            FileState.READABLE | FileState.WRITABLE | FileState.EVENTFD_WRITE_SPACE,
            values,
        )
