"""eventfd: a 64-bit kernel counter usable as a wakeup channel.

Parity: reference `src/main/host/descriptor/eventfd.rs` — read returns the
counter (and zeroes it; or decrements by 1 in semaphore mode), write adds;
READABLE when counter > 0, WRITABLE while a write of 1 wouldn't overflow.
"""

from __future__ import annotations

from . import errors
from .status import FileState, StatefulFile

_MAX = (1 << 64) - 2


class EventFd(StatefulFile):
    def __init__(self, initval: int = 0, semaphore: bool = False):
        super().__init__(FileState.ACTIVE | FileState.WRITABLE)
        self.counter = initval
        self.semaphore = semaphore
        self.nonblocking = False
        self._refresh()

    def read_value(self) -> int:
        if self.is_closed():
            raise errors.SyscallError(errors.EBADF)
        if self.counter == 0:
            if self.nonblocking:
                raise errors.SyscallError(errors.EWOULDBLOCK)
            raise errors.Blocked(self, FileState.READABLE)
        if self.semaphore:
            self.counter -= 1
            value = 1
        else:
            value, self.counter = self.counter, 0
        self._refresh()
        return value

    def write_value(self, value: int) -> None:
        if self.is_closed():
            raise errors.SyscallError(errors.EBADF)
        if value >= (1 << 64) - 1:
            raise errors.SyscallError(errors.EINVAL)
        if self.counter + value > _MAX:
            if self.nonblocking:
                raise errors.SyscallError(errors.EWOULDBLOCK)
            raise errors.Blocked(self, FileState.WRITABLE)
        self.counter += value
        self._refresh()

    def close(self) -> None:
        if self.is_closed():
            return
        self.update_state(
            FileState.ACTIVE | FileState.READABLE | FileState.WRITABLE | FileState.CLOSED,
            FileState.CLOSED,
        )

    def _refresh(self) -> None:
        if self.is_closed():
            return
        values = FileState.NONE
        if self.counter > 0:
            values |= FileState.READABLE
        if self.counter + 1 <= _MAX:
            values |= FileState.WRITABLE
        self.update_state(FileState.READABLE | FileState.WRITABLE, values)
