"""Emulated futexes: pthread mutexes/condvars/joins block in SIMULATED time.

Parity: reference `src/main/host/futex.c` (per-word wait queues, wake-N,
requeue) + `futex_table.rs` + the futex syscall handler
(`syscall/handler/futex.rs`). Without this, a managed pthread program's
blocking primitives would either spin natively (wall-clock leaks into the
sim) or native-block forever (the waker is sim-scheduled).

Design: one `FutexWaiter` token per blocked thread, queued FIFO per futex
word. A waiter parks on its token's FUTEX_WAKEUP state bit through the
ordinary `SysCallCondition` machinery, so timeouts compose exactly like
every other blocking syscall. `wake(n)` pops the first n tokens and flips
each token's bit individually — waking exactly n threads, in arrival
order, deterministically.

Word addresses are virtual addresses in the managed process; the table is
per-process (threads share it via the shared handler). Cross-process
shared-memory futexes are out of scope (the reference resolves those via
physical page addresses, `futex_table.rs`).
"""

from __future__ import annotations

from collections import deque

from .status import FileState, StatefulFile

# futex op numbers (linux/futex.h)
FUTEX_WAIT = 0
FUTEX_WAKE = 1
FUTEX_REQUEUE = 3
FUTEX_CMP_REQUEUE = 4
FUTEX_WAKE_OP = 5
FUTEX_WAIT_BITSET = 9
FUTEX_WAKE_BITSET = 10
FUTEX_PRIVATE_FLAG = 128
FUTEX_CLOCK_REALTIME = 256
FUTEX_CMD_MASK = ~(FUTEX_PRIVATE_FLAG | FUTEX_CLOCK_REALTIME)


MATCH_ANY = 0xFFFFFFFF


class FutexWaiter(StatefulFile):
    """One parked thread's wake token."""

    __slots__ = ("addr", "bitset")

    def __init__(self, addr: int, bitset: int = MATCH_ANY):
        super().__init__(FileState.ACTIVE)
        self.addr = addr
        self.bitset = bitset

    def close(self) -> None:  # descriptor-table protocol compat
        pass


class FutexTable:
    """word address -> FIFO of waiter tokens (`futex.c` FutexTable)."""

    def __init__(self):
        self._queues: dict[int, deque[FutexWaiter]] = {}

    def add_waiter(self, addr: int, bitset: int = MATCH_ANY) -> FutexWaiter:
        w = FutexWaiter(addr, bitset)
        self._queues.setdefault(addr, deque()).append(w)
        return w

    def remove_waiter(self, waiter: FutexWaiter) -> None:
        """Timeout/cancel cleanup: drop the token if still queued."""
        q = self._queues.get(waiter.addr)
        if q is None:
            return
        try:
            q.remove(waiter)
        except ValueError:
            pass  # already woken
        if not q:
            del self._queues[waiter.addr]

    def wake(self, addr: int, n: int, bitset: int = MATCH_ANY) -> int:
        """Wake up to n waiters whose bitset intersects `bitset`, in FIFO
        order; non-matching waiters keep their queue position (the
        kernel's FUTEX_WAKE_BITSET semantics)."""
        q = self._queues.get(addr)
        if not q:
            return 0
        woken = 0
        kept: deque[FutexWaiter] = deque()
        while q:
            w = q.popleft()
            if woken < n and (w.bitset & bitset):
                woken += 1
                # the state flip fires the parked thread's condition listener
                w.update_state(FileState.FUTEX_WAKEUP, FileState.FUTEX_WAKEUP)
            else:
                kept.append(w)
        if kept:
            self._queues[addr] = kept
        else:
            self._queues.pop(addr, None)
        return woken

    def requeue(self, addr: int, n_wake: int, addr2: int,
                n_requeue: int) -> tuple[int, int]:
        """Wake up to n_wake waiters of `addr`, then move up to n_requeue of
        the remainder to `addr2`'s queue. Returns (woken, requeued) — the
        syscall layer composes the op-specific return convention."""
        woken = self.wake(addr, n_wake)
        q = self._queues.get(addr)
        moved = 0
        while q and moved < n_requeue:
            w = q.popleft()
            w.addr = addr2
            self._queues.setdefault(addr2, deque()).append(w)
            moved += 1
        if q is not None and not q:
            self._queues.pop(addr, None)
        return woken, moved

    def waiter_count(self, addr: int) -> int:
        return len(self._queues.get(addr, ()))
