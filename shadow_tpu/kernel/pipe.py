"""Pipes: unidirectional byte stream between two file endpoints.

Parity: reference `src/main/host/descriptor/pipe.rs` — a shared ring buffer
(default capacity 64 KiB, Linux's pipe size) with distinct reader/writer
files; EOF when all writers close, EPIPE when all readers close.
"""

from __future__ import annotations

from collections import deque

from . import errors
from .status import FileSignal, FileState, StatefulFile

PIPE_CAPACITY = 65536


class _PipeShared:
    __slots__ = ("buf", "nbytes", "reader", "writer")

    def __init__(self):
        self.buf: deque[bytes] = deque()
        self.nbytes = 0
        self.reader: "PipeReader" = None
        self.writer: "PipeWriter" = None


def make_pipe() -> tuple["PipeReader", "PipeWriter"]:
    shared = _PipeShared()
    shared.reader = PipeReader(shared)
    shared.writer = PipeWriter(shared)
    return shared.reader, shared.writer


class PipeReader(StatefulFile):
    def __init__(self, shared: _PipeShared):
        super().__init__(FileState.ACTIVE)
        self._shared = shared
        self.nonblocking = False

    def recv(self, max_bytes: int = 65536) -> bytes:
        if self.is_closed():
            raise errors.SyscallError(errors.EBADF)
        sh = self._shared
        if sh.nbytes == 0:
            if sh.writer is None or sh.writer.is_closed():
                return b""  # EOF
            if self.nonblocking:
                raise errors.SyscallError(errors.EWOULDBLOCK)
            raise errors.Blocked(self, FileState.READABLE)
        out = []
        need = max_bytes
        while need > 0 and sh.buf:
            chunk = sh.buf[0]
            if len(chunk) <= need:
                out.append(sh.buf.popleft())
                need -= len(chunk)
            else:
                out.append(chunk[:need])
                sh.buf[0] = chunk[need:]
                need = 0
        got = b"".join(out)
        sh.nbytes -= len(got)
        self._refresh()
        if sh.writer is not None:
            sh.writer._refresh()
        return got

    def close(self) -> None:
        if self.is_closed():
            return
        self.update_state(
            FileState.ACTIVE | FileState.READABLE | FileState.CLOSED, FileState.CLOSED
        )
        if self._shared.writer is not None:
            self._shared.writer._refresh()

    def _refresh(self) -> None:
        if self.is_closed():
            return
        eof = self._shared.writer is None or self._shared.writer.is_closed()
        readable = self._shared.nbytes > 0 or eof
        self.update_state(
            FileState.READABLE, FileState.READABLE if readable else FileState.NONE
        )


class PipeWriter(StatefulFile):
    def __init__(self, shared: _PipeShared):
        super().__init__(FileState.ACTIVE | FileState.WRITABLE)
        self._shared = shared
        self.nonblocking = False

    def send(self, data: bytes) -> int:
        if self.is_closed():
            raise errors.SyscallError(errors.EBADF)
        sh = self._shared
        if sh.reader is None or sh.reader.is_closed():
            raise errors.SyscallError(errors.EPIPE)
        space = PIPE_CAPACITY - sh.nbytes
        if space == 0:
            if self.nonblocking:
                raise errors.SyscallError(errors.EWOULDBLOCK)
            raise errors.Blocked(self, FileState.WRITABLE)
        n = min(space, len(data))
        sh.buf.append(bytes(data[:n]))
        sh.nbytes += n
        self._refresh()
        sh.reader._refresh()
        sh.reader.emit_signal(FileSignal.READ_BUFFER_GREW)
        return n

    def close(self) -> None:
        if self.is_closed():
            return
        self.update_state(
            FileState.ACTIVE | FileState.WRITABLE | FileState.CLOSED, FileState.CLOSED
        )
        if self._shared.reader is not None:
            self._shared.reader._refresh()  # EOF becomes readable

    def _refresh(self) -> None:
        if self.is_closed():
            return
        writable = self._shared.nbytes < PIPE_CAPACITY
        self.update_state(
            FileState.WRITABLE, FileState.WRITABLE if writable else FileState.NONE
        )
