"""Simulated sockets (inet UDP/TCP; unix later)."""
