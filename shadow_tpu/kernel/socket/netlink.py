"""AF_NETLINK route sockets in the simulated kernel.

Parity: reference `src/main/host/descriptor/socket/netlink.rs` (1,285 LoC)
— NETLINK_ROUTE sockets answering RTM_GETLINK / RTM_GETADDR dump requests
with the host's simulated interfaces (lo + eth0), which is what
`getifaddrs(3)` and `ip addr`-style queries speak. Other request types get
an NLMSG_ERROR(-EOPNOTSUPP) reply, like the reference's catch-all.

Replies are queued as datagrams at request time (the kernel's netlink dumps
are synchronous from the requester's point of view): one NLM_F_MULTI
datagram carrying every entry, then one NLMSG_DONE datagram. Receive
supports MSG_PEEK / MSG_TRUNC because glibc's __netlink_recvmsg sizes its
buffer with a PEEK|TRUNC probe before the real read.
"""

from __future__ import annotations

import struct
from collections import deque
from typing import Optional

from .. import errors
from ..status import FileSignal, FileState, StatefulFile

AF_NETLINK = 16
NETLINK_ROUTE = 0

# nlmsghdr types
NLMSG_NOOP = 1
NLMSG_ERROR = 2
NLMSG_DONE = 3

# nlmsghdr flags
NLM_F_REQUEST = 0x01
NLM_F_MULTI = 0x02
NLM_F_ACK = 0x04
NLM_F_ROOT = 0x100
NLM_F_MATCH = 0x200
NLM_F_DUMP = NLM_F_ROOT | NLM_F_MATCH

# rtnetlink message types
RTM_NEWLINK = 16
RTM_GETLINK = 18
RTM_NEWADDR = 20
RTM_GETADDR = 22

AF_INET = 2
AF_UNSPEC = 0

# ifinfomsg
ARPHRD_ETHER = 1
ARPHRD_LOOPBACK = 772
IFF_UP = 0x1
IFF_BROADCAST = 0x2
IFF_LOOPBACK = 0x8
IFF_RUNNING = 0x40
IFF_MULTICAST = 0x1000
IFLA_ADDRESS = 1
IFLA_BROADCAST = 2
IFLA_IFNAME = 3
IFLA_MTU = 4

# ifaddrmsg
IFA_ADDRESS = 1
IFA_LOCAL = 2
IFA_LABEL = 3
IFA_BROADCAST = 4
RT_SCOPE_UNIVERSE = 0
RT_SCOPE_HOST = 254

RECV_QUEUE_MAX = 64
MTU_LO = 65536
MTU_ETH = 1500


def _align4(n: int) -> int:
    return (n + 3) & ~3


def _rtattr(rta_type: int, payload: bytes) -> bytes:
    ln = 4 + len(payload)
    return struct.pack("<HH", ln, rta_type) + payload + b"\x00" * (
        _align4(ln) - ln)


def _nlmsg(msg_type: int, flags: int, seq: int, pid: int,
           payload: bytes) -> bytes:
    ln = 16 + len(payload)
    return struct.pack("<IHHII", ln, msg_type, flags, seq, pid) + payload + \
        b"\x00" * (_align4(ln) - ln)


def _ip_bytes(ip: str) -> bytes:
    return bytes(int(p) for p in ip.split("."))


class _Iface:
    __slots__ = ("index", "name", "ip", "prefix", "arphrd", "flags",
                 "mtu", "scope")

    def __init__(self, index, name, ip, prefix, arphrd, flags, mtu, scope):
        self.index = index
        self.name = name
        self.ip = ip
        self.prefix = prefix
        self.arphrd = arphrd
        self.flags = flags
        self.mtu = mtu
        self.scope = scope


def host_interfaces(host) -> list[_Iface]:
    """The two simulated interfaces every host owns (`namespace.rs`)."""
    public_ip = host.netns.public_ip
    return [
        _Iface(1, "lo", "127.0.0.1", 8, ARPHRD_LOOPBACK,
               IFF_UP | IFF_LOOPBACK | IFF_RUNNING, MTU_LO, RT_SCOPE_HOST),
        _Iface(2, "eth0", public_ip, 24, ARPHRD_ETHER,
               IFF_UP | IFF_BROADCAST | IFF_RUNNING | IFF_MULTICAST,
               MTU_ETH, RT_SCOPE_UNIVERSE),
    ]


def _link_entry(iface: _Iface, seq: int, pid: int) -> bytes:
    # struct ifinfomsg: u8 family, u8 pad, u16 type, i32 index, u32 flags,
    # u32 change
    body = struct.pack("<BxHiII", AF_UNSPEC, iface.arphrd, iface.index,
                       iface.flags, 0)
    body += _rtattr(IFLA_IFNAME, iface.name.encode() + b"\x00")
    body += _rtattr(IFLA_MTU, struct.pack("<I", iface.mtu))
    mac = b"\x00" * 6 if iface.arphrd == ARPHRD_LOOPBACK else \
        b"\x02" + _ip_bytes(iface.ip)[:4] + b"\x01"
    body += _rtattr(IFLA_ADDRESS, mac)
    return _nlmsg(RTM_NEWLINK, NLM_F_MULTI, seq, pid, body)


def _addr_entry(iface: _Iface, seq: int, pid: int) -> bytes:
    # struct ifaddrmsg: u8 family, u8 prefixlen, u8 flags, u8 scope,
    # u32 index
    body = struct.pack("<BBBBI", AF_INET, iface.prefix, 0, iface.scope,
                       iface.index)
    body += _rtattr(IFA_ADDRESS, _ip_bytes(iface.ip))
    body += _rtattr(IFA_LOCAL, _ip_bytes(iface.ip))
    body += _rtattr(IFA_LABEL, iface.name.encode() + b"\x00")
    if iface.arphrd == ARPHRD_ETHER:
        parts = iface.ip.split(".")
        bcast = ".".join(parts[:3]) + ".255"
        body += _rtattr(IFA_BROADCAST, _ip_bytes(bcast))
    return _nlmsg(RTM_NEWADDR, NLM_F_MULTI, seq, pid, body)


class NetlinkSocket(StatefulFile):
    """One NETLINK_ROUTE endpoint."""

    def __init__(self, host, protocol: int = NETLINK_ROUTE):
        if protocol != NETLINK_ROUTE:
            raise errors.SyscallError(errors.EPROTONOSUPPORT)
        super().__init__(FileState.ACTIVE | FileState.WRITABLE)
        self.host = host
        self.nonblocking = False
        self.pid: Optional[int] = None  # netlink port id, not process pid
        self.groups = 0
        self._recv: deque[bytes] = deque()
        self._overflow = False  # a reply was dropped; next recv -> ENOBUFS
        self._closed = False

    # -- address plumbing ------------------------------------------------

    def _autobind(self) -> None:
        if self.pid is None:
            counter = getattr(self.host, "_netlink_pid_counter", 0) + 1
            self.host._netlink_pid_counter = counter
            self.pid = counter

    def bind(self, addr) -> None:
        # addr is ("netlink", pid, groups); pid 0 = kernel-assigned
        _fam, pid, groups = addr
        if self.pid is not None and pid not in (0, self.pid):
            raise errors.SyscallError(errors.EINVAL)
        if pid:
            self.pid = pid
        else:
            self._autobind()
        self.groups = groups

    def getsockname(self):
        return ("netlink", self.pid or 0, self.groups)

    def getpeername(self):
        return ("netlink", 0, 0)  # the "kernel"

    def connect(self, addr) -> None:
        # connect(2) on netlink just pins the peer (normally pid 0, the
        # kernel); all our replies come from the kernel anyway
        if addr[0] != "netlink":
            raise errors.SyscallError(errors.EINVAL)
        self._autobind()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._recv.clear()
        self.update_state(
            FileState.ACTIVE | FileState.READABLE | FileState.WRITABLE
            | FileState.CLOSED,
            FileState.CLOSED,
        )

    # -- request processing ---------------------------------------------

    def send(self, data: bytes) -> int:
        return self.sendto(data, None)

    def sendto(self, data: bytes, addr) -> int:
        if self._closed:
            raise errors.SyscallError(errors.EBADF)
        self._autobind()
        off = 0
        n = len(data)
        while off + 16 <= n:
            ln, msg_type, flags, seq, _pid = struct.unpack_from(
                "<IHHII", data, off)
            if ln < 16 or off + ln > n:
                break
            self._handle_request(msg_type, flags, seq,
                                 data[off + 16:off + ln])
            off += _align4(ln)
        return n

    def _handle_request(self, msg_type: int, flags: int, seq: int,
                        payload: bytes) -> None:
        if not flags & NLM_F_REQUEST or msg_type < RTM_NEWLINK:
            return  # NOOP/DONE/ERROR from userspace: ignored, like Linux
        pid = self.pid or 0
        if msg_type in (RTM_GETLINK, RTM_GETADDR) and flags & NLM_F_DUMP:
            # One multipart datagram with every entry, then DONE — the
            # same framing the reference emits (netlink.rs dump path).
            make = _link_entry if msg_type == RTM_GETLINK else _addr_entry
            parts = [make(i, seq, pid) for i in host_interfaces(self.host)]
            self._push(b"".join(parts))
            self._push(_nlmsg(NLMSG_DONE, NLM_F_MULTI, seq, pid,
                              struct.pack("<i", 0)))
            return
        # Unsupported request (including non-dump GETLINK/GETADDR):
        # NLMSG_ERROR carrying -EOPNOTSUPP and the offending header — an
        # honest failure rather than an empty ACK claiming success.
        echo = struct.pack("<IHHII", 16 + len(payload), msg_type, flags,
                           seq, pid)
        self._push(_nlmsg(NLMSG_ERROR, 0, seq, pid,
                          struct.pack("<i", -errors.EOPNOTSUPP) + echo))

    # -- receive ---------------------------------------------------------

    def recvfrom(self, max_bytes: int, peek: bool = False):
        """Returns (data, src, full_len): `data` is the datagram clipped to
        the buffer, `full_len` the datagram's real length so the caller can
        apply MSG_TRUNC return-value and msg_flags semantics."""
        if self._closed:
            raise errors.SyscallError(errors.EBADF)
        if self._overflow:
            # a reply was dropped at queue-full: fail like Linux so the
            # caller can resync instead of hanging for a DONE that was
            # never queued. Ordering matches __skb_try_recv_datagram,
            # which consumes sock_error() BEFORE dequeuing ("Caller is
            # allowed not to check sk->sk_err before skb_recv_datagram()"
            # — net/core/datagram.c), so the error surfaces ahead of any
            # queued dump replies; libnl treats ENOBUFS as the immediate
            # restart-the-dump signal.
            self._overflow = False
            self._refresh()  # recompute READABLE now that sk_err is gone
            raise errors.SyscallError(errors.ENOBUFS)
        if not self._recv:
            if self.nonblocking:
                raise errors.SyscallError(errors.EWOULDBLOCK)
            raise errors.Blocked(self, FileState.READABLE)
        dgram = self._recv[0] if peek else self._recv.popleft()
        if not peek:
            self._refresh()
        return dgram[:max_bytes], ("netlink", 0, 0), len(dgram)

    def recv(self, max_bytes: int = 1 << 20) -> bytes:
        data, _src, _ln = self.recvfrom(max_bytes)
        return data

    # -- internals -------------------------------------------------------

    def _push(self, dgram: bytes) -> None:
        if len(self._recv) >= RECV_QUEUE_MAX:
            self._overflow = True  # surfaced as ENOBUFS on the next recv
            self._refresh()
            return
        self._recv.append(dgram)
        self._refresh()
        self.emit_signal(FileSignal.READ_BUFFER_GREW)

    def _refresh(self) -> None:
        if self._closed:
            return
        value = FileState.ACTIVE | FileState.WRITABLE
        if self._recv or self._overflow:
            value |= FileState.READABLE  # overflow: wake reader for ENOBUFS
        self.update_state(
            FileState.ACTIVE | FileState.READABLE | FileState.WRITABLE,
            value,
        )
