"""The simulated TCP socket: glue between the pure `TcpConnection` state
machine and the host plane (NIC association, packet priorities, timers,
file-state notifications).

Parity: reference `src/main/host/descriptor/tcp.c` socket surface +
`descriptor/socket/inet/mod.rs` association rules:
- listeners hold a wildcard-peer association and spawn one child socket per
  SYN, associated by exact 4-tuple (the NIC's exact-match-first lookup
  routes established traffic to the child);
- the accept queue holds children whose handshake completed (backlog-capped
  at SYN time);
- connect() picks loopback vs the public interface by destination and draws
  a deterministic ephemeral port;
- outgoing segments are staged one at a time, stamped with the host's
  monotone packet priority so qdisc ordering matches the reference
  (`host.rs:679-720`).

The wrapper converts between wire `Packet`s (addressed) and protocol
`Segment`s (pure), so `shadow_tpu.tcp` never learns about IPs.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Optional

from ...core.event import TaskRef
from ...net.packet import Packet, PacketStatus, Protocol, TcpHeader
from ...tcp.connection import Segment, TcpConfig, TcpConnection, TcpError, TcpFlags, TcpState
from .. import errors
from ..status import FileSignal, FileState, StatefulFile

# int twins of the FileState combos _refresh_state recomputes per
# packet (IntFlag | / & re-enter the enum machinery per op)
_READABLE = int(FileState.READABLE)
_WRITABLE = int(FileState.WRITABLE)
_ALLOW_CONNECT = int(FileState.SOCKET_ALLOWING_CONNECT)
_RWC = _READABLE | _WRITABLE | _ALLOW_CONNECT

UNSPECIFIED = "0.0.0.0"
LOCALHOST = "127.0.0.1"
DEFAULT_BACKLOG = 128


def packet_to_segment(packet: Packet) -> Segment:
    h = packet.header or TcpHeader()
    return Segment(
        flags=h.flags,  # plain int bits on the hot path
        seq=h.seq,
        ack=h.ack,
        window=h.window,
        payload=packet.payload,
        window_scale=h.window_scale,
        timestamp=h.timestamp,
        timestamp_echo=h.timestamp_echo,
        sack_permitted=h.sack_permitted,
        sack=tuple(h.sel_acks),
    )


def segment_to_packet(
    seg: Segment, src: tuple[str, int], dst: tuple[str, int], priority: int
) -> Packet:
    header = TcpHeader(
        seq=seg.seq,
        ack=seg.ack,
        window=seg.window,
        flags=int(seg.flags),
        window_scale=seg.window_scale,
        timestamp=seg.timestamp,
        timestamp_echo=seg.timestamp_echo,
        sel_acks=tuple(seg.sack),
        sack_permitted=seg.sack_permitted,
    )
    return Packet(
        Protocol.TCP, src, dst, payload=seg.payload, header=header, priority=priority
    )


class _ConnDeps:
    """Dependencies implementation backed by the owning host."""

    __slots__ = ("sock",)

    def __init__(self, sock: "TcpSocket"):
        self.sock = sock

    def now(self) -> int:
        return self.sock._host.now()

    def set_timer(self, delay_ns, callback) -> None:
        self.sock._host.schedule_task_with_delay(
            TaskRef(lambda host: callback(), "tcp-timer"), delay_ns
        )

    def random_u32(self) -> int:
        return self.sock._host.rng.next_u64() >> 32

    def notify(self) -> None:
        self.sock._on_conn_event()


class TcpSocket(StatefulFile):
    # Linux sysctl ceilings the reference hardcodes (`definitions.h:32-37`)
    RMEM_MAX = 6291456
    WMEM_MAX = 4194304
    SND_UNIT = 2404  # per-segment send-mem estimate (`tcp.c` autotune)

    def __init__(self, host, config: Optional[TcpConfig] = None):
        super().__init__(FileState.ACTIVE)
        self._host = host
        exp = getattr(host, "config_experimental", None)
        if config is None:
            config = TcpConfig(
                send_buffer=getattr(exp, "socket_send_buffer", 131072),
                recv_buffer=getattr(exp, "socket_recv_buffer", 174760),
            )
        else:
            # never mutate a caller-supplied template (the listener
            # passes its _config to every accepted child)
            config = dataclasses.replace(config)
        self._config = config
        # dynamic buffer autotuning (`tcp.c:587-649`): receive buffers
        # track 2x the bytes the app drains per smoothed RTT; send
        # buffers track the congestion window. setsockopt SO_RCVBUF/
        # SO_SNDBUF disables the respective direction, like Linux.
        self.autotune_recv = bool(getattr(exp, "socket_recv_autotune", True))
        self.autotune_send = bool(getattr(exp, "socket_send_autotune", True))
        self._at_bytes_copied = 0
        self._at_space = 0
        self._at_last_adjust: Optional[int] = None
        if self.autotune_recv and config.wscale_buffer is None:
            # wscale must cover where autotune may take the buffer
            config.wscale_buffer = self.RMEM_MAX
        self.conn: Optional[TcpConnection] = None  # None while unconnected/listening
        self.bound_addr: Optional[tuple[str, int]] = None
        self.peer_addr: Optional[tuple[str, int]] = None
        self.nonblocking = False
        # listener state
        self._backlog: Optional[int] = None
        self._accept_queue: deque[TcpSocket] = deque()
        self._pending_children: dict[tuple[str, int], TcpSocket] = {}
        self._listener: Optional[TcpSocket] = None  # back-pointer on children
        # one staged outbound packet so the NIC can peek its priority
        self._staged: Optional[Packet] = None
        self._app_closed = False

    # ==================================================================
    # application API
    # ==================================================================

    def bind(self, addr: tuple[str, int]) -> tuple[str, int]:
        if self.is_closed():
            raise errors.SyscallError(errors.EBADF)
        if self.bound_addr is not None:
            raise errors.SyscallError(errors.EINVAL, "already bound")
        ip, port = addr
        if ip != UNSPECIFIED and self._host.netns.interface_for(ip) is None:
            raise errors.SyscallError(errors.EADDRNOTAVAIL, ip)
        if port == 0:
            port = self._host.netns.get_random_free_port(
                Protocol.TCP, self._host.rng, ip
            )
        elif not self._host.netns.is_port_free(Protocol.TCP, port, ip):
            raise errors.SyscallError(errors.EADDRINUSE, f"{ip}:{port}")
        self._host.netns.associate(self, Protocol.TCP, ip, port)
        self.bound_addr = (ip, port)
        return self.bound_addr

    def listen(self, backlog: int = DEFAULT_BACKLOG) -> None:
        if self.is_closed():
            raise errors.SyscallError(errors.EBADF)
        if self.conn is not None:
            raise errors.SyscallError(errors.EISCONN)
        if self.bound_addr is None:
            # Linux allows listen() on unbound sockets (ephemeral on ANY)
            self.bind((UNSPECIFIED, 0))
        self._backlog = max(1, backlog)

    def accept(self) -> "TcpSocket":
        if self.is_closed():
            raise errors.SyscallError(errors.EBADF)
        if self._backlog is None:
            raise errors.SyscallError(errors.EINVAL, "not listening")
        if not self._accept_queue:
            if self.nonblocking:
                raise errors.SyscallError(errors.EWOULDBLOCK)
            raise errors.Blocked(self, FileState.READABLE)
        child = self._accept_queue.popleft()
        self._refresh_state()
        return child

    def connect(self, addr: tuple[str, int]) -> None:
        if self.is_closed():
            raise errors.SyscallError(errors.EBADF)
        if self._backlog is not None:
            raise errors.SyscallError(errors.EOPNOTSUPP, "listening socket")
        if self.conn is not None:
            if self.conn.state == TcpState.SYN_SENT:
                raise errors.SyscallError(errors.EALREADY)
            raise errors.SyscallError(errors.EISCONN)
        dst_ip, _ = addr
        if self.bound_addr is None:
            local_ip = LOCALHOST if dst_ip == LOCALHOST else self._host.netns.public_ip
            port = self._host.netns.get_random_free_port(
                Protocol.TCP, self._host.rng, local_ip, peer=addr
            )
            self.bound_addr = (local_ip, port)
        else:
            # drop the wildcard-peer association from bind(); the exact
            # 4-tuple association below covers this connection
            local_ip, port = self.bound_addr
            self._host.netns.disassociate(Protocol.TCP, local_ip, port)
            if local_ip == UNSPECIFIED:
                local_ip = LOCALHOST if dst_ip == LOCALHOST else self._host.netns.public_ip
            self.bound_addr = (local_ip, port)
        self.peer_addr = addr
        # exact 4-tuple association: replies route straight to this socket
        self._host.netns.associate(self, Protocol.TCP, self.bound_addr[0],
                                   self.bound_addr[1], peer=addr)
        # per-connection config copy: autotune growth must not leak into
        # sibling sockets sharing the template
        self.conn = TcpConnection(_ConnDeps(self),
                                  dataclasses.replace(self._config))
        self.conn.open_active()
        self._pump_out()
        if self.nonblocking:
            raise errors.SyscallError(errors.EINPROGRESS)
        raise errors.Blocked(
            self, FileState.SOCKET_ALLOWING_CONNECT, restartable=False
        )

    def send(self, data: bytes) -> int:
        if self.is_closed():
            raise errors.SyscallError(errors.EBADF)
        if self.conn is None:
            raise errors.SyscallError(errors.ENOTCONN)
        if self.autotune_send:
            self._autotune_send()
        try:
            n = self.conn.write(data)
        except TcpError as e:
            raise errors.SyscallError(e.errno) from None
        if n == 0:
            if self.nonblocking:
                raise errors.SyscallError(errors.EWOULDBLOCK)
            raise errors.Blocked(self, FileState.WRITABLE)
        self._pump_out()
        self._refresh_state()
        return n

    def recv(self, max_bytes: int = 1 << 20, peek: bool = False) -> bytes:
        if self.is_closed():
            raise errors.SyscallError(errors.EBADF)
        if self.conn is None:
            raise errors.SyscallError(errors.ENOTCONN)
        try:
            data = (self.conn.peek(max_bytes) if peek
                    else self.conn.read(max_bytes))
        except TcpError as e:
            raise errors.SyscallError(e.errno) from None
        if not data and not self.conn.at_eof():
            if self.nonblocking:
                raise errors.SyscallError(errors.EWOULDBLOCK)
            raise errors.Blocked(self, FileState.READABLE)
        if not peek:
            if data and self.autotune_recv:
                self._autotune_recv(len(data))
            self._pump_out()  # reads can reopen the advertised window
            self._refresh_state()
        return data

    # -- buffer autotuning (`tcp.c:587-649`) ---------------------------

    def _autotune_recv(self, bytes_copied: int) -> None:
        """Input buffer tracks 2x the bytes the app drains per smoothed
        RTT: fast drains grow the window toward RMEM_MAX."""
        conn = self.conn
        self._at_bytes_copied += bytes_copied
        space = 2 * self._at_bytes_copied
        if space > self._at_space:
            self._at_space = space
            new = min(space, self.RMEM_MAX)
            if new > conn.config.recv_buffer:
                conn.config.recv_buffer = new
        now = self._host.now()
        if self._at_last_adjust is None:
            self._at_last_adjust = now
        elif conn.rtt.srtt_ms > 0 and \
                now - self._at_last_adjust > conn.rtt.srtt_ms * 1_000_000:
            self._at_last_adjust = now
            self._at_bytes_copied = 0

    def _autotune_send(self) -> None:
        """Output buffer tracks the congestion window (`tcp.c`'s
        2404-bytes-per-demanded-segment estimate)."""
        conn = self.conn
        demanded = max(conn.cong.cwnd, 1)
        new = min(self.SND_UNIT * 2 * demanded, self.WMEM_MAX)
        if new > conn.config.send_buffer:
            conn.config.send_buffer = new

    def set_buffer_size(self, direction: str, size: int) -> None:
        """SO_SNDBUF/SO_RCVBUF: Linux clamps the request to the sysctl
        ceiling as a u32 (so -1 means "the max"), doubles it, and pins it
        (disabling that direction's autotuning)."""
        cap = self.RMEM_MAX if direction == "recv" else self.WMEM_MAX
        size = max(4096, min(size & 0xFFFFFFFF, cap) * 2)
        target = self.conn.config if self.conn is not None else self._config
        if direction == "recv":
            self.autotune_recv = False
            self._config.recv_buffer = target.recv_buffer = size
        else:
            self.autotune_send = False
            self._config.send_buffer = target.send_buffer = size

    def close(self) -> None:
        if self._app_closed:
            return
        self._app_closed = True
        for child in list(self._accept_queue) + list(self._pending_children.values()):
            child.close()
        self._accept_queue.clear()
        if self.conn is not None and self.conn.state != TcpState.CLOSED:
            self.conn.close()
            self._pump_out()
        else:
            self._teardown()
        self.update_state(
            FileState.ACTIVE | FileState.READABLE | FileState.WRITABLE | FileState.CLOSED,
            FileState.CLOSED,
        )

    def getsockname(self):
        return self.bound_addr

    def getpeername(self):
        return self.peer_addr

    def is_connected(self) -> bool:
        return self.conn is not None and self.conn.is_established()

    # ==================================================================
    # InterfaceSocket protocol (NIC-facing)
    # ==================================================================

    def peek_next_priority(self) -> Optional[int]:
        return self._staged.priority if self._staged is not None else None

    def pull_out_packet(self) -> Optional[Packet]:
        packet = self._staged
        self._staged = None
        if packet is not None:
            packet.add_status(PacketStatus.SND_SOCKET_BUFFERED)
            self._stage_next()  # quiet restage; NIC requeues via peek
        return packet

    def push_in_packet(self, packet: Packet) -> None:
        if self._backlog is not None:
            self._listener_push(packet)
            return
        if self.conn is None:
            packet.add_status(PacketStatus.RCV_SOCKET_DROPPED)
            return
        packet.add_status(PacketStatus.RCV_SOCKET_PROCESSED)
        before = self.conn.readable_bytes()
        self.conn.on_segment(packet_to_segment(packet))
        if self.conn.readable_bytes() > before:
            self.emit_signal(FileSignal.READ_BUFFER_GREW)

    # ==================================================================
    # listener internals
    # ==================================================================

    def _listener_push(self, packet: Packet) -> None:
        seg = packet_to_segment(packet)
        key = packet.src
        if not seg.flags & TcpFlags.SYN or seg.flags & TcpFlags.ACK:
            packet.add_status(PacketStatus.RCV_SOCKET_DROPPED)
            return
        if key in self._pending_children:
            # duplicate SYN: the child's own association should normally win
            # the NIC lookup; re-deliver defensively
            self._pending_children[key].push_in_packet(packet)
            return
        if len(self._pending_children) + len(self._accept_queue) >= self._backlog:
            packet.add_status(PacketStatus.RCV_SOCKET_DROPPED)  # SYN drop
            return
        local = packet.dst
        child = TcpSocket(self._host, self._config)
        child.bound_addr = local
        child.peer_addr = key
        child._listener = self
        # Linux copies the buffer-lock flags to accepted sockets: an
        # explicit SO_*BUF pin on the listener binds its children too
        child.autotune_recv = self.autotune_recv
        child.autotune_send = self.autotune_send
        self._host.netns.associate(child, Protocol.TCP, local[0], local[1], peer=key)
        child.conn = TcpConnection(_ConnDeps(child),
                                   dataclasses.replace(self._config))
        child.conn.open_passive(seg)
        self._pending_children[key] = child
        child._pump_out()

    def _child_established(self, child: "TcpSocket") -> None:
        key = child.peer_addr
        if key in self._pending_children:
            del self._pending_children[key]
            self._accept_queue.append(child)
            self._refresh_state()

    def _child_died(self, child: "TcpSocket") -> None:
        self._pending_children.pop(child.peer_addr, None)
        try:
            self._accept_queue.remove(child)
        except ValueError:
            pass

    # ==================================================================
    # connection-event plumbing
    # ==================================================================

    def _on_conn_event(self) -> None:
        conn = self.conn
        if conn is None:
            return
        if (
            self._listener is not None
            and conn.state >= TcpState.ESTABLISHED
            and conn.state != TcpState.CLOSED
        ):
            listener, self._listener = self._listener, None
            listener._child_established(self)
        if conn.state == TcpState.CLOSED:
            if self._listener is not None:
                listener, self._listener = self._listener, None
                listener._child_died(self)
            self._teardown()
        self._pump_out()
        self._refresh_state()

    def _pump_out(self) -> None:
        """Stage one packet and wake the NIC if we went non-empty."""
        if self._staged is not None or self.conn is None:
            return
        if self._stage_next():
            iface_ip = self._staged.src[0]
            self._host.notify_socket_has_packets(iface_ip, self)

    def _stage_next(self) -> bool:
        if self.conn is None or self._staged is not None:
            return False
        seg = self.conn.next_segment()
        if seg is None:
            return False
        src = self._effective_src()
        self._staged = segment_to_packet(
            seg, src, self.peer_addr, self._host.get_next_packet_priority()
        )
        if getattr(self.conn, "last_segment_retransmit", False):
            self._staged.add_status(PacketStatus.SND_TCP_RETRANSMITTED)
        return True

    def _effective_src(self) -> tuple[str, int]:
        ip, port = self.bound_addr
        if ip == UNSPECIFIED:
            ip = (
                LOCALHOST
                if self.peer_addr and self.peer_addr[0] == LOCALHOST
                else self._host.netns.public_ip
            )
        return (ip, port)

    def _refresh_state(self) -> None:
        if self.is_closed():
            return
        values = 0
        if self._backlog is not None:
            if self._accept_queue:
                values |= _READABLE
            self.update_state(_READABLE, values)
            return
        conn = self.conn
        if conn is None:
            self.update_state(_RWC, 0)
            return
        if conn.readable_bytes() > 0 or conn.at_eof() or conn.error is not None:
            values |= _READABLE
        if conn.is_established() and conn.send_space() > 0 and not conn.fin_requested:
            values |= _WRITABLE
        if conn.is_established() or conn.error is not None:
            # error included: blocked connect()s must wake to see ECONNREFUSED
            values |= _ALLOW_CONNECT
        self.update_state(_RWC, values)

    def _teardown(self) -> None:
        """Connection fully dead: release the port association."""
        if self.bound_addr is not None and self.bound_addr[1] != 0:
            self._host.netns.disassociate(
                Protocol.TCP, self.bound_addr[0], self.bound_addr[1],
                peer=self.peer_addr if self.peer_addr else ("0.0.0.0", 0),
            )
        self._staged = None
