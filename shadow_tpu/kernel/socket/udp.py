"""The simulated UDP socket.

Parity: reference `src/main/host/descriptor/socket/inet/udp.rs` —
message-oriented soft-limited send/recv buffers; one packet per datagram (no
IP fragmentation; datagrams over 65507 bytes fail with EMSGSIZE,
`udp.rs:367-369`, `definitions.h:134`); implicit bind on first send chooses
loopback vs the default interface by destination (`udp.rs:381-387`);
received packets are dropped when the recv buffer is full (`udp.rs:140`);
connected sockets drop packets not from their peer (`udp.rs:736`);
READABLE/WRITABLE reflect buffer occupancy after every operation
(`udp.rs:984`).

The socket faces two planes: the NIC pulls outgoing packets via the
`InterfaceSocket` protocol (`pull_out_packet`/`peek_next_priority`/
`push_in_packet`), and applications call the bind/connect/send/recv API.
"""

from __future__ import annotations

from collections import deque
from typing import Optional

from ...net.packet import Packet, PacketStatus, Protocol
from .. import errors
from ..status import CallbackQueue, FileSignal, FileState, StatefulFile, queue_and_run

CONFIG_DATAGRAM_MAX_SIZE = 65507  # `definitions.h:134`

UNSPECIFIED = "0.0.0.0"
LOCALHOST = "127.0.0.1"


class _MessageBuffer:
    """Datagram buffer with a soft byte limit: a message may exceed the limit
    only when the buffer is empty (`udp.rs:1060-1100` MessageBuffer)."""

    __slots__ = ("soft_limit", "bytes", "queue")

    def __init__(self, soft_limit: int):
        self.soft_limit = soft_limit
        self.bytes = 0
        self.queue: deque = deque()

    def has_space(self) -> bool:
        return self.bytes < self.soft_limit

    def push(self, data, header, size: int) -> None:
        self.queue.append((data, header, size))
        self.bytes += size

    def pop(self):
        if not self.queue:
            return None
        item = self.queue.popleft()
        self.bytes -= item[2]
        return item

    def peek(self):
        return self.queue[0] if self.queue else None

    def __len__(self) -> int:
        return len(self.queue)


class UdpSocket(StatefulFile):
    def __init__(self, host, *, send_buf_size: Optional[int] = None,
                 recv_buf_size: Optional[int] = None):
        # A fresh UDP socket is writable immediately.
        super().__init__(FileState.ACTIVE | FileState.WRITABLE)
        self._host = host
        cfg = getattr(host, "config_experimental", None)
        send_default = getattr(cfg, "socket_send_buffer", 131072)
        recv_default = getattr(cfg, "socket_recv_buffer", 174760)
        # (data, header-tuple, size) entries
        self._send_buffer = _MessageBuffer(send_buf_size or send_default)
        self._recv_buffer = _MessageBuffer(recv_buf_size or recv_default)
        self.bound_addr: Optional[tuple[str, int]] = None
        self.peer_addr: Optional[tuple[str, int]] = None
        self.nonblocking = False
        self.drop_count = 0

    # ------------------------------------------------------------------
    # application API
    # ------------------------------------------------------------------

    def bind(self, addr: tuple[str, int]) -> tuple[str, int]:
        if self.is_closed():
            raise errors.SyscallError(errors.EBADF)
        if self.bound_addr is not None:
            raise errors.SyscallError(errors.EINVAL, "already bound")
        ip, port = addr
        if ip != UNSPECIFIED and self._host.netns.interface_for(ip) is None:
            raise errors.SyscallError(errors.EADDRNOTAVAIL, ip)
        if port == 0:
            port = self._host.netns.get_random_free_port(
                Protocol.UDP, self._host.rng, ip
            )
        elif not self._host.netns.is_port_free(Protocol.UDP, port, ip):
            raise errors.SyscallError(errors.EADDRINUSE, f"{ip}:{port}")
        self._host.netns.associate(self, Protocol.UDP, ip, port)
        self.bound_addr = (ip, port)
        return self.bound_addr

    def connect(self, addr: tuple[str, int]) -> None:
        """Set the default destination and filter inbound to that peer."""
        if self.is_closed():
            raise errors.SyscallError(errors.EBADF)
        if self.bound_addr is None:
            self._implicit_bind(addr[0])
        self.peer_addr = addr

    def sendto(
        self, data: bytes, dst: Optional[tuple[str, int]] = None
    ) -> int:
        if self.is_closed():
            raise errors.SyscallError(errors.EBADF)
        if dst is None:
            if self.peer_addr is None:
                raise errors.SyscallError(errors.EDESTADDRREQ)
            dst = self.peer_addr
        if len(data) > CONFIG_DATAGRAM_MAX_SIZE:
            raise errors.SyscallError(errors.EMSGSIZE)

        if self.bound_addr is None:
            self._implicit_bind(dst[0])

        if not self._send_buffer.has_space():
            if self.nonblocking:
                raise errors.SyscallError(errors.EWOULDBLOCK)
            raise errors.Blocked(self, FileState.WRITABLE)

        src = self._effective_src(dst)
        priority = self._host.get_next_packet_priority()
        self._send_buffer.push(bytes(data), (src, dst, priority), len(data))

        # Notify after state settles (`udp.rs:449-459` defers via cb_queue).
        with queue_and_run() as cq:
            self._refresh_readable_writable(cq)
            iface_ip = self.bound_addr[0]
            cq.add(
                lambda _cq: self._host.notify_socket_has_packets(
                    src[0] if iface_ip == UNSPECIFIED else iface_ip, self
                )
            )
        return len(data)

    def send(self, data: bytes) -> int:
        return self.sendto(data, None)

    def recvfrom(self, peek: bool = False) -> tuple[bytes, tuple[str, int]]:
        if self.is_closed():
            raise errors.SyscallError(errors.EBADF)
        entry = self._recv_buffer.peek() if peek else self._recv_buffer.pop()
        if entry is None:
            if self.nonblocking:
                raise errors.SyscallError(errors.EWOULDBLOCK)
            raise errors.Blocked(self, FileState.READABLE)
        data, (src, _dst, _prio), _size = entry
        if not peek:
            self._refresh_readable_writable(None)
        return data, src

    def recv(self) -> bytes:
        return self.recvfrom()[0]

    def close(self) -> None:
        if self.is_closed():
            return
        if self.bound_addr is not None:
            self._host.netns.disassociate(Protocol.UDP, *self.bound_addr)
            self.bound_addr = None
        # Buffered outbound datagrams die with the socket: the port is
        # released, so emitting them later would source from a reusable port.
        self._send_buffer.queue.clear()
        self._send_buffer.bytes = 0
        self.update_state(
            FileState.ACTIVE | FileState.READABLE | FileState.WRITABLE | FileState.CLOSED,
            FileState.CLOSED,
        )

    def getsockname(self) -> Optional[tuple[str, int]]:
        return self.bound_addr

    def getpeername(self) -> Optional[tuple[str, int]]:
        return self.peer_addr

    # ------------------------------------------------------------------
    # InterfaceSocket protocol (NIC-facing)
    # ------------------------------------------------------------------

    def peek_next_priority(self) -> Optional[int]:
        if not self._send_buffer.queue:
            return None
        return self._send_buffer.queue[0][1][2]

    def pull_out_packet(self) -> Optional[Packet]:
        entry = self._send_buffer.pop()
        if entry is None:
            return None
        data, (src, dst, priority), _size = entry
        self._refresh_readable_writable(None)
        packet = Packet(Protocol.UDP, src, dst, payload=data, priority=priority)
        packet.add_status(PacketStatus.SND_SOCKET_BUFFERED)
        return packet

    def push_in_packet(self, packet: Packet) -> None:
        if self.is_closed():
            packet.add_status(PacketStatus.RCV_SOCKET_DROPPED)
            return
        # Connected sockets accept only their peer (`udp.rs:736`): port must
        # match; a peer IP of LOCALHOST also matches our own public address
        # form, so compare ports strictly and IPs loosely via local aliases.
        if self.peer_addr is not None and not self._from_peer(packet):
            packet.add_status(PacketStatus.RCV_SOCKET_DROPPED)
            self.drop_count += 1
            return
        if not self._recv_buffer.has_space():
            packet.add_status(PacketStatus.RCV_SOCKET_DROPPED)
            self.drop_count += 1
            return
        self._recv_buffer.push(
            packet.payload,
            (packet.src, packet.dst, packet.priority),
            packet.payload_size(),
        )
        packet.add_status(PacketStatus.RCV_SOCKET_BUFFERED)
        packet.add_status(PacketStatus.RCV_SOCKET_DELIVERED)
        self._refresh_readable_writable(None)
        self.emit_signal(FileSignal.READ_BUFFER_GREW)

    # ------------------------------------------------------------------

    def _from_peer(self, packet: Packet) -> bool:
        peer_ip, peer_port = self.peer_addr
        if packet.src[1] != peer_port:
            return False
        if packet.src[0] == peer_ip:
            return True
        # our loopback alias: peer "127.0.0.1" == packets sourced from our own
        # public IP when both ends sit on this host
        aliases = {LOCALHOST, self._host.netns.public_ip}
        return peer_ip in aliases and packet.src[0] in aliases

    def _implicit_bind(self, dst_ip: str) -> None:
        """Bind to an ephemeral port on loopback (loopback destination) or the
        default interface (anything else) (`udp.rs:381-400`)."""
        local_ip = LOCALHOST if dst_ip == LOCALHOST else self._host.netns.public_ip
        port = self._host.netns.get_random_free_port(
            Protocol.UDP, self._host.rng, local_ip
        )
        self._host.netns.associate(self, Protocol.UDP, local_ip, port)
        self.bound_addr = (local_ip, port)

    def _effective_src(self, dst: tuple[str, int]) -> tuple[str, int]:
        ip, port = self.bound_addr
        if ip == UNSPECIFIED:
            ip = LOCALHOST if dst[0] == LOCALHOST else self._host.netns.public_ip
        return (ip, port)

    def _refresh_readable_writable(self, cb_queue: Optional[CallbackQueue]) -> None:
        if self.is_closed():
            return  # close() cleared READABLE/WRITABLE permanently
        values = FileState.NONE
        if len(self._recv_buffer):
            values |= FileState.READABLE
        if self._send_buffer.has_space():
            values |= FileState.WRITABLE
        self.update_state(FileState.READABLE | FileState.WRITABLE, values, cb_queue)
