"""AF_UNIX sockets in the simulated kernel.

Parity: reference `src/main/host/descriptor/socket/unix.rs` — stream and
dgram families, a per-host path namespace (filesystem + abstract names are
one flat map here; the simulated filesystem is virtual anyway), connected
pairs moving bytes directly between buffers (no network plane: unix
traffic never leaves the host), listener backlogs, socketpair, EOF/EPIPE
semantics, and SHUT_RD/SHUT_WR.

Design: a connected stream peer writes straight into this socket's receive
buffer (bounded by CAPACITY for backpressure); dgram sockets queue bounded
(data, src_path) messages at the receiver. All readiness goes through
FileState bits so poll/select/epoll and the blocking-syscall conditions
compose unchanged.
"""

from __future__ import annotations

from collections import deque
from typing import Optional

from .. import errors
from ..status import FileSignal, FileState, StatefulFile

CAPACITY = 212992  # Linux default wmem for unix sockets
DGRAM_QUEUE_MAX = 256
DEFAULT_BACKLOG = 128

UNIX_ADDR_FAMILY = "unix"  # marker in ("unix", path) sockaddr tuples


def unix_namespace(host) -> dict:
    ns = getattr(host, "unix_ns", None)
    if ns is None:
        ns = {}
        host.unix_ns = ns
    return ns


class UnixSocket(StatefulFile):
    """One AF_UNIX endpoint (stream or dgram)."""

    def __init__(self, host, stream: bool):
        super().__init__(FileState.ACTIVE)
        self.host = host
        self.stream = stream
        self.nonblocking = False
        self.bound_path: Optional[str] = None
        self.listening = False
        self._backlog_cap = DEFAULT_BACKLOG
        self._accept_queue: deque[UnixSocket] = deque()
        self.peer: Optional[UnixSocket] = None
        self.connected_path: Optional[str] = None  # dgram default dst
        self._recv: deque = deque()  # stream: bytes; dgram: (data, src)
        self._recv_bytes = 0
        self._eof = False  # peer closed / shut down its write side
        self._shut_wr = False
        self._shut_rd = False
        self._closed = False
        self._refresh()

    # -- address plumbing ------------------------------------------------

    def getsockname(self):
        return (UNIX_ADDR_FAMILY, self.bound_path or "")

    def getpeername(self):
        if self.stream:
            if self.peer is None:
                return None
            return (UNIX_ADDR_FAMILY, self.peer.bound_path or "")
        if self.connected_path is None:
            return None
        return (UNIX_ADDR_FAMILY, self.connected_path)

    # -- lifecycle -------------------------------------------------------

    def bind(self, addr) -> None:
        fam, path = addr
        if fam != UNIX_ADDR_FAMILY:
            raise errors.SyscallError(errors.EINVAL)
        if self.bound_path is not None:
            raise errors.SyscallError(errors.EINVAL)
        ns = unix_namespace(self.host)
        if path in ns:
            raise errors.SyscallError(errors.EADDRINUSE)
        ns[path] = self
        self.bound_path = path

    def listen(self, backlog: int = DEFAULT_BACKLOG) -> None:
        if not self.stream:
            raise errors.SyscallError(errors.EOPNOTSUPP)
        if self.bound_path is None:
            raise errors.SyscallError(errors.EINVAL)
        self.listening = True
        self._backlog_cap = max(1, backlog)
        self._refresh()

    def connect(self, addr) -> None:
        fam, path = addr
        if fam != UNIX_ADDR_FAMILY:
            raise errors.SyscallError(errors.EINVAL)
        ns = unix_namespace(self.host)
        if not self.stream:
            if path not in ns:
                raise errors.SyscallError(errors.ECONNREFUSED)
            self.connected_path = path
            return
        if self.peer is not None:
            raise errors.SyscallError(errors.EISCONN)
        listener = ns.get(path)
        if listener is None or not listener.listening or listener._closed:
            raise errors.SyscallError(errors.ECONNREFUSED)
        if len(listener._accept_queue) >= listener._backlog_cap:
            raise errors.SyscallError(errors.ECONNREFUSED)
        child = UnixSocket(self.host, stream=True)
        child.bound_path = listener.bound_path  # children share the name
        link(self, child)
        listener._accept_queue.append(child)
        listener._refresh()

    def accept(self) -> "UnixSocket":
        if not self.listening:
            raise errors.SyscallError(errors.EINVAL)
        if not self._accept_queue:
            if self.nonblocking:
                raise errors.SyscallError(errors.EWOULDBLOCK)
            raise errors.Blocked(self, FileState.READABLE)
        child = self._accept_queue.popleft()
        self._refresh()
        return child

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.bound_path is not None:
            ns = unix_namespace(self.host)
            if ns.get(self.bound_path) is self:
                del ns[self.bound_path]
        for child in self._accept_queue:
            child.close()
        self._accept_queue.clear()
        if self.peer is not None:
            # sever BOTH directions: the survivor keeps reading buffered
            # bytes but its sends must fail with EPIPE, not black-hole
            # into this dead socket's buffer
            peer, self.peer = self.peer, None
            peer._eof = True
            peer.peer = None
            peer._refresh()
        self.update_state(
            FileState.ACTIVE | FileState.READABLE | FileState.WRITABLE
            | FileState.CLOSED,
            FileState.CLOSED,
        )

    def shutdown(self, rd: bool, wr: bool) -> None:
        if wr and not self._shut_wr:
            self._shut_wr = True
            if self.peer is not None:
                self.peer._eof = True
                self.peer._refresh()
        if rd:
            self._shut_rd = True
        self._refresh()

    # -- data ------------------------------------------------------------

    def send(self, data: bytes) -> int:
        if self._closed:
            raise errors.SyscallError(errors.EBADF)
        if not self.stream:
            return self.sendto(data, None)
        if self.peer is None:
            raise errors.SyscallError(
                errors.EPIPE if self._eof or self._shut_wr
                else errors.ENOTCONN)
        if self._shut_wr:
            raise errors.SyscallError(errors.EPIPE)
        room = CAPACITY - self.peer._recv_bytes
        n = min(len(data), room)
        if n <= 0:
            if self.nonblocking:
                raise errors.SyscallError(errors.EWOULDBLOCK)
            raise errors.Blocked(self, FileState.WRITABLE)
        self.peer._push(bytes(data[:n]))
        self._refresh()
        return n

    def sendto(self, data: bytes, addr) -> int:
        path = addr[1] if addr is not None else self.connected_path
        if path is None:
            raise errors.SyscallError(errors.ENOTCONN)
        dst = unix_namespace(self.host).get(path)
        if dst is None or dst._closed or dst.stream:
            raise errors.SyscallError(errors.ECONNREFUSED)
        if len(dst._recv) >= DGRAM_QUEUE_MAX:
            if self.nonblocking:
                raise errors.SyscallError(errors.EWOULDBLOCK)
            # park on the RECEIVER's space bit: the sender's own WRITABLE
            # is statically on for dgram and would wake immediately
            raise errors.Blocked(dst, FileState.DGRAM_SPACE)
        dst._recv.append((bytes(data), self.bound_path or ""))
        dst._recv_bytes += len(data)
        dst._refresh()
        dst.emit_signal(FileSignal.READ_BUFFER_GREW)
        return len(data)

    def recv(self, max_bytes: int = 1 << 20, peek: bool = False) -> bytes:
        data, _src = self.recvfrom(max_bytes, peek)
        return data

    def recvfrom(self, max_bytes: int = 1 << 20, peek: bool = False):
        if self._closed:
            raise errors.SyscallError(errors.EBADF)
        if self.stream:
            if not self._recv:
                if self._eof or self._shut_rd:
                    return b"", self.getpeername()
                if self.peer is None:
                    raise errors.SyscallError(errors.ENOTCONN)
                if self.nonblocking:
                    raise errors.SyscallError(errors.EWOULDBLOCK)
                raise errors.Blocked(self, FileState.READABLE)
            if peek:
                out = []
                need = max_bytes
                for chunk in self._recv:
                    if need <= 0:
                        break
                    out.append(chunk[:need])
                    need -= min(need, len(chunk))
                return b"".join(out), self.getpeername()
            out = []
            need = max_bytes
            while need > 0 and self._recv:
                chunk = self._recv[0]
                if len(chunk) <= need:
                    out.append(chunk)
                    self._recv.popleft()
                    need -= len(chunk)
                else:
                    out.append(chunk[:need])
                    self._recv[0] = chunk[need:]
                    need = 0
            got = b"".join(out)
            self._recv_bytes -= len(got)
            self._refresh()
            if self.peer is not None:
                self.peer._refresh()  # our drain reopened their window
            return got, self.getpeername()
        # dgram
        if not self._recv:
            if self.nonblocking:
                raise errors.SyscallError(errors.EWOULDBLOCK)
            raise errors.Blocked(self, FileState.READABLE)
        if peek:
            data, src = self._recv[0]
        else:
            data, src = self._recv.popleft()
            self._recv_bytes -= len(data)
            self._refresh()
        return data[:max_bytes], (UNIX_ADDR_FAMILY, src)

    # -- internals -------------------------------------------------------

    def _push(self, data: bytes) -> None:
        self._recv.append(data)
        self._recv_bytes += len(data)
        self._refresh()
        self.emit_signal(FileSignal.READ_BUFFER_GREW)

    def _refresh(self) -> None:
        if self._closed:
            return
        readable = bool(self._recv) or self._eof or self._shut_rd \
            or bool(self._accept_queue)
        if self.stream:
            writable = (self.peer is not None and not self._shut_wr
                        and self.peer._recv_bytes < CAPACITY) or self._eof
            space = False
        else:
            writable = True
            space = len(self._recv) < DGRAM_QUEUE_MAX
        value = FileState.ACTIVE
        if readable:
            value |= FileState.READABLE
        if writable:
            value |= FileState.WRITABLE
        if space:
            value |= FileState.DGRAM_SPACE
        self.update_state(
            FileState.ACTIVE | FileState.READABLE | FileState.WRITABLE
            | FileState.DGRAM_SPACE,
            value,
        )


def link(a: UnixSocket, b: UnixSocket) -> None:
    """Join two stream sockets as peers (connect / socketpair)."""
    a.peer, b.peer = b, a
    a._refresh()
    b._refresh()


def make_socketpair(host, stream: bool = True):
    a, b = UnixSocket(host, stream), UnixSocket(host, stream)
    if stream:
        link(a, b)
    else:
        # dgram socketpair: autobind both to hidden names and cross-connect
        ns = unix_namespace(host)
        for i, s in enumerate((a, b)):
            name = f"\x00socketpair.{id(a):x}.{i}"
            ns[name] = s
            s.bound_path = name
        a.connected_path = b.bound_path
        b.connected_path = a.bound_path
    return a, b
