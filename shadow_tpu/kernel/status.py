"""File state bits, state-change listeners, and the deferred callback queue.

Parity: reference `FileState` bitflags
(`src/lib/shadow-shim-helper-rs/src/shim_shmem.rs` / `descriptor/mod.rs`),
`StateEventSource`/`StatusListener` (`src/main/host/status_listener.{c,rs}`,
`descriptor/listener.rs`), and `CallbackQueue`
(`src/main/utility/callback_queue.rs`): state transitions never invoke
listeners re-entrantly — notifications are queued and run after the state
change that caused them has fully settled.
"""

from __future__ import annotations

import enum
from collections import deque
from contextlib import contextmanager
from typing import Callable, Iterator, Optional


class FileState(enum.IntFlag):
    """Observable state bits of a file/socket (`descriptor/mod.rs` FileState)."""

    NONE = 0
    ACTIVE = 1 << 0  # file is open / usable
    READABLE = 1 << 1
    WRITABLE = 1 << 2
    CLOSED = 1 << 3
    # TCP-specific: a listener is able to accept (backlog non-empty) or a
    # connecting socket finished the handshake.
    SOCKET_ALLOWING_CONNECT = 1 << 4
    FUTEX_WAKEUP = 1 << 5
    CHILD_EVENTS = 1 << 6
    # eventfd-internal: room for the SMALLEST value a blocked writer is
    # waiting to add (distinct from WRITABLE, which keeps poll's "a write
    # of 1 won't block" meaning). Wakeups may be spurious for larger
    # waiters — they must retry and re-block — but are never missed.
    EVENTFD_WRITE_SPACE = 1 << 7
    # unix-dgram-internal: the RECEIVER's queue has room. A blocked dgram
    # sender parks on the destination socket's bit (its own WRITABLE is
    # static for dgram and would livelock the condition).
    DGRAM_SPACE = 1 << 8



# plain-int twin of FileState.CLOSED for the hottest predicates —
# IntFlag arithmetic re-enters the enum machinery per operation (see
# tcp/connection.py's flag twins); state words flow through
# StatusListener as plain ints and compare equal to FileState members
_CLOSED_I = int(FileState.CLOSED)

class FileSignal(enum.IntFlag):
    """Edge events that are not state-bit transitions (reference
    `FileSignals`): e.g. more bytes arriving while a file is already
    READABLE — invisible to state-change listeners, but exactly what
    edge-triggered epoll must see (`epoll(7)`)."""

    NONE = 0
    READ_BUFFER_GREW = 1 << 0


class ListenerFilter(enum.Enum):
    """When a listener fires, relative to the monitored bits' transition
    (`descriptor/listener.rs` StateListenerFilter)."""

    NEVER = 0
    OFF_TO_ON = 1
    ON_TO_OFF = 2
    ALWAYS = 3


class CallbackQueue:
    """FIFO of deferred callbacks (`utility/callback_queue.rs`).

    State-change handlers are pushed here and run once the mutation that
    triggered them has unwound, so a listener observing a state change can
    itself mutate files without re-entering their notification paths.
    """

    __slots__ = ("_queue",)

    def __init__(self):
        self._queue: deque[Callable[["CallbackQueue"], None]] = deque()

    def add(self, callback: Callable[["CallbackQueue"], None]) -> None:
        self._queue.append(callback)

    def run(self) -> None:
        while self._queue:
            self._queue.popleft()(self)

    def __len__(self) -> int:
        return len(self._queue)


@contextmanager
def queue_and_run() -> Iterator[CallbackQueue]:
    """Run a mutation with a fresh callback queue, flushing it afterwards —
    the standard entry point for any externally-triggered state change
    (`callback_queue.rs` queue_and_run)."""
    cq = CallbackQueue()
    try:
        yield cq
    finally:
        cq.run()


class StateEventSource:
    """A file's listener registry.

    Listeners are keyed by insertion-ordered integer handles so notification
    order is deterministic and independent of object identity.
    """

    __slots__ = ("_listeners", "_next_handle")

    def __init__(self):
        # handle -> (state mask, signal mask, filter, cb(state, changed, cq))
        self._listeners: dict[
            int,
            tuple[
                FileState,
                FileSignal,
                ListenerFilter,
                Callable[[FileState, FileState, CallbackQueue], None],
            ],
        ] = {}
        self._next_handle = 0

    def add_listener(
        self,
        monitoring: FileState,
        filter: ListenerFilter,
        callback: Callable[[FileState, FileState, CallbackQueue], None],
        signals: FileSignal = FileSignal.NONE,
    ) -> int:
        handle = self._next_handle
        self._next_handle += 1
        self._listeners[handle] = (monitoring, signals, filter, callback)
        return handle

    def remove_listener(self, handle: int) -> None:
        self._listeners.pop(handle, None)

    def has_listeners(self) -> bool:
        return bool(self._listeners)

    def notify(
        self,
        state: FileState,
        changed: FileState,
        cb_queue: CallbackQueue,
        signals: FileSignal = FileSignal.NONE,
    ) -> None:
        """Queue notifications for every listener whose monitored bits
        intersect `changed` in the direction its filter requires, or whose
        monitored signals intersect `signals`."""
        for monitoring, want_sig, filt, callback in list(self._listeners.values()):
            if signals & want_sig:
                cb_queue.add(
                    lambda cq, cb=callback, s=state, c=changed: cb(s, c, cq)
                )
                continue
            hit = monitoring & changed
            if not hit:
                continue
            if filt == ListenerFilter.NEVER:
                continue
            if filt == ListenerFilter.OFF_TO_ON and not (state & hit):
                continue
            if filt == ListenerFilter.ON_TO_OFF and (state & hit):
                continue
            cb_queue.add(lambda cq, cb=callback, s=state, c=changed: cb(s, c, cq))


class StatefulFile:
    """Base for anything with observable `FileState` — sockets, pipes,
    eventfds, timerfds, epoll instances.

    Subclasses mutate state exclusively through `update_state`, which
    computes the changed bits and queues listener notifications.
    """

    def __init__(self, initial: FileState = FileState.ACTIVE):
        self._state = initial
        self._event_source = StateEventSource()

    @property
    def state(self) -> FileState:
        return self._state

    def add_listener(
        self,
        monitoring: FileState,
        filter: ListenerFilter,
        callback: Callable[[FileState, FileState, CallbackQueue], None],
        signals: FileSignal = FileSignal.NONE,
    ) -> int:
        return self._event_source.add_listener(monitoring, filter, callback, signals)

    def remove_listener(self, handle: int) -> None:
        self._event_source.remove_listener(handle)

    def emit_signal(
        self, signals: FileSignal, cb_queue: Optional[CallbackQueue] = None
    ) -> None:
        """Fire signal-only listeners (no state bits changed) — e.g. the
        read buffer grew while already READABLE."""
        if not signals:
            return
        if cb_queue is None:
            with queue_and_run() as cq:
                self._event_source.notify(self._state, FileState.NONE, cq, signals)
        else:
            self._event_source.notify(self._state, FileState.NONE, cb_queue, signals)

    def update_state(
        self,
        mask: FileState,
        values: FileState,
        cb_queue: Optional[CallbackQueue] = None,
    ) -> None:
        """Set the bits selected by `mask` to `values`; notify listeners of
        any bits that actually changed. With no queue supplied, notifications
        run before this returns (a fresh queue is flushed)."""
        mask = int(mask)
        values = int(values)
        assert values & ~mask == 0, "values outside mask"
        state = int(self._state)
        new_state = (state & ~mask) | values
        changed = state ^ new_state
        if not changed:
            return
        self._state = new_state
        if cb_queue is None:
            with queue_and_run() as cq:
                self._event_source.notify(new_state, changed, cq)
        else:
            self._event_source.notify(new_state, changed, cb_queue)

    def is_closed(self) -> bool:
        return bool(int(self._state) & _CLOSED_I)
