"""timerfd + the underlying per-host Timer.

Parity: reference `src/main/host/timer.rs` (one-shot/interval timers
scheduling TaskRefs on the host, generation-guarded against stale fires)
and `descriptor/timerfd.rs` (a file whose read returns the expiration
count; READABLE while count > 0).
"""

from __future__ import annotations

from typing import Callable, Optional

from ..core.event import TaskRef
from . import errors
from .status import FileState, StatefulFile


class Timer:
    """One-shot or periodic emulated-time timer on a host."""

    def __init__(self, host, on_expire: Callable[[], None]):
        self._host = host
        self._on_expire = on_expire
        self._gen = 0
        self.expire_at: Optional[int] = None  # absolute ns
        self.interval: int = 0  # 0 = one-shot

    def arm(self, expire_at_ns: int, interval_ns: int = 0) -> None:
        self._gen += 1
        self.expire_at = expire_at_ns
        self.interval = interval_ns
        self._schedule()

    def disarm(self) -> None:
        self._gen += 1
        self.expire_at = None

    def remaining(self) -> Optional[int]:
        if self.expire_at is None:
            return None
        return max(0, self.expire_at - self._host.now())

    def _schedule(self) -> None:
        gen = self._gen
        delay = max(0, self.expire_at - self._host.now())
        self._host.schedule_task_with_delay(
            TaskRef(lambda h, g=gen: self._fire(g), "timer"), delay
        )

    def _fire(self, gen: int) -> None:
        if gen != self._gen or self.expire_at is None:
            return
        if self.interval > 0:
            self.expire_at = self.expire_at + self.interval
            self._schedule()
        else:
            self.expire_at = None
        self._on_expire()


class TimerFd(StatefulFile):
    def __init__(self, host):
        super().__init__(FileState.ACTIVE)
        self._host = host
        self.expirations = 0
        self.nonblocking = False
        self._timer = Timer(host, self._on_expire)

    def settime(self, initial_ns: int, interval_ns: int = 0,
                absolute: bool = False) -> None:
        """Arm (initial > 0) or disarm (initial == 0)."""
        if initial_ns == 0:
            self._timer.disarm()
            return
        at = initial_ns if absolute else self._host.now() + initial_ns
        self.expirations = 0
        self._refresh()
        self._timer.arm(at, interval_ns)

    def gettime(self) -> tuple[Optional[int], int]:
        return self._timer.remaining(), self._timer.interval

    def read_expirations(self) -> int:
        if self.is_closed():
            raise errors.SyscallError(errors.EBADF)
        if self.expirations == 0:
            if self.nonblocking:
                raise errors.SyscallError(errors.EWOULDBLOCK)
            raise errors.Blocked(self, FileState.READABLE)
        n, self.expirations = self.expirations, 0
        self._refresh()
        return n

    def close(self) -> None:
        if self.is_closed():
            return
        self._timer.disarm()
        self.update_state(
            FileState.ACTIVE | FileState.READABLE | FileState.CLOSED, FileState.CLOSED
        )

    def _on_expire(self) -> None:
        if self.is_closed():
            return
        self.expirations += 1
        self._refresh()

    def _refresh(self) -> None:
        if self.is_closed():
            return
        self.update_state(
            FileState.READABLE,
            FileState.READABLE if self.expirations > 0 else FileState.NONE,
        )
