"""Global name ↔ IP registry for the simulated internet.

Parity: reference `src/main/routing/dns.c` (C GHashTables + mutex;
`dns_resolveNameToAddress` / `dns_resolveIPToAddress`, `dns.c:180-268`) and
its `/etc/hosts`-style file generation mounted into managed processes.
"""

from __future__ import annotations

from typing import Optional


class DnsError(ValueError):
    pass


class Dns:
    def __init__(self):
        self._name_to_ip: dict[str, str] = {}
        self._ip_to_name: dict[str, str] = {}

    def register(self, name: str, ip: str) -> None:
        if name in self._name_to_ip:
            raise DnsError(f"hostname {name!r} already registered")
        if ip in self._ip_to_name:
            raise DnsError(f"address {ip} already registered")
        self._name_to_ip[name] = ip
        self._ip_to_name[ip] = name

    def deregister(self, name: str) -> None:
        ip = self._name_to_ip.pop(name, None)
        if ip is not None:
            self._ip_to_name.pop(ip, None)

    def name_to_ip(self, name: str) -> Optional[str]:
        if name == "localhost":
            return "127.0.0.1"
        return self._name_to_ip.get(name)

    def ip_to_name(self, ip: str) -> Optional[str]:
        return self._ip_to_name.get(ip)

    def hosts_file(self) -> str:
        """An /etc/hosts view of the simulation, for managed processes."""
        lines = ["127.0.0.1 localhost"]
        for name, ip in sorted(self._name_to_ip.items(), key=lambda kv: kv[0]):
            lines.append(f"{ip} {name}")
        return "\n".join(lines) + "\n"
