"""A small generic GML (Graph Modelling Language) parser.

Parity: the reference ships its own `gml-parser` crate (542 LoC,
`src/lib/gml-parser/`). This is an independent implementation of the same
grammar: a `graph [...]` block containing scalar key/value pairs and repeated
`node [...]` / `edge [...]` sub-blocks. Values are integers, floats, quoted
strings, or nested `[ ... ]` lists.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Union


class GmlError(ValueError):
    pass


_TOKEN = re.compile(
    r"""
    \s*(?:
      (?P<comment>\#[^\n]*)
    | (?P<lbracket>\[)
    | (?P<rbracket>\])
    | (?P<string>"(?:[^"\\]|\\.)*")
    | (?P<number>[+-]?(?:\d+\.\d*(?:[eE][+-]?\d+)?|\.?\d+(?:[eE][+-]?\d+)?|(?:nan|inf)\b))
    | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    )
    """,
    re.VERBOSE,
)


def _tokenize(text: str):
    pos = 0
    n = len(text)
    while pos < n:
        m = _TOKEN.match(text, pos)
        if not m:
            if text[pos:].strip() == "":
                return
            raise GmlError(f"unexpected character at offset {pos}: {text[pos:pos+20]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "comment":
            continue
        yield kind, m.group(kind)
    return


@dataclass
class GmlList:
    """An ordered multimap: GML allows repeated keys (every `node [...]`)."""

    items: list[tuple[str, Any]] = field(default_factory=list)

    def get(self, key: str, default=None):
        for k, v in self.items:
            if k == key:
                return v
        return default

    def get_all(self, key: str) -> list:
        return [v for k, v in self.items if k == key]

    def count(self, key: str) -> int:
        return sum(1 for k, _ in self.items if k == key)


Value = Union[int, float, str, GmlList]


def _parse_list(tokens) -> GmlList:
    out = GmlList()
    while True:
        try:
            kind, text = next(tokens)
        except StopIteration:
            return out
        if kind == "rbracket":
            return out
        if kind != "ident":
            raise GmlError(f"expected key, got {text!r}")
        key = text
        try:
            vkind, vtext = next(tokens)
        except StopIteration:
            raise GmlError(f"key {key!r} has no value") from None
        if vkind == "lbracket":
            value: Value = _parse_list(tokens)
        elif vkind == "string":
            value = vtext[1:-1].replace('\\"', '"').replace("\\\\", "\\")
        elif vkind == "number":
            if re.fullmatch(r"[+-]?\d+", vtext):
                value = int(vtext)
            else:
                value = float(vtext)
        else:
            raise GmlError(f"bad value for key {key!r}: {vtext!r}")
        out.items.append((key, value))


def parse(text: str) -> GmlList:
    """Parse GML text, returning the contents of the top-level `graph [...]`."""
    tokens = _tokenize(text)
    top = _parse_list(tokens)
    graph = top.get("graph")
    if not isinstance(graph, GmlList):
        raise GmlError("no top-level 'graph [...]' block")
    return graph
