"""The network graph: topology parsing, path computation, IP assignment.

Parity: reference `src/main/network/graph/mod.rs`.
- Nodes carry optional `host_bandwidth_up`/`host_bandwidth_down` unit strings.
- Edges carry `latency` (required, must be > 0), optional `jitter` (parsed but
  unused in routing — same as the reference), and `packet_loss` fraction.
- `use_shortest_path`: all-pairs shortest paths by (latency, then loss), with
  path composition latency_a + latency_b and loss 1-(1-a)(1-b)
  (`graph/mod.rs:322-331`). Every used node must have exactly one self-loop,
  which supplies the node→node path (`graph/mod.rs:210-217`).
- Otherwise: direct single-edge lookup between every used node pair
  (`graph/mod.rs:230-252`).
- IPs auto-assigned from 11.0.0.0 skipping .0/.255 octets
  (`graph/mod.rs:352-420`).

TPU-first: instead of per-source Dijkstra over an object graph, paths are
computed by vectorized Floyd–Warshall over dense latency/loss matrices — the
same [N,N] arrays the TPU network plane later keeps in HBM for per-packet
latency/loss lookup.
"""

from __future__ import annotations

import ipaddress
import lzma
import gzip
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..core import units
from . import gml


class GraphError(ValueError):
    pass


# The reference's built-in graph (`configuration.rs:1357-1370`).
ONE_GBIT_SWITCH_GRAPH = """graph [
  directed 0
  node [
    id 0
    host_bandwidth_up "1 Gbit"
    host_bandwidth_down "1 Gbit"
  ]
  edge [
    source 0
    target 0
    latency "1 ms"
    packet_loss 0.0
  ]
]"""


@dataclass(frozen=True)
class PathProperties:
    """Network characteristics of a path (`graph/mod.rs:295-331`)."""

    latency_ns: int
    packet_loss: float

    def compose(self, other: "PathProperties") -> "PathProperties":
        return PathProperties(
            self.latency_ns + other.latency_ns,
            1.0 - (1.0 - self.packet_loss) * (1.0 - other.packet_loss),
        )


@dataclass
class ShadowNode:
    id: int
    bandwidth_up: Optional[int]  # bits/sec
    bandwidth_down: Optional[int]


@dataclass
class ShadowEdge:
    source: int
    target: int
    latency_ns: int
    jitter_ns: int
    packet_loss: float


def _parse_node(raw: gml.GmlList) -> ShadowNode:
    node_id = raw.get("id")
    if not isinstance(node_id, int):
        raise GraphError("node requires an integer 'id'")

    def bw(key):
        v = raw.get(key)
        if v is None:
            return None
        if not isinstance(v, str):
            raise GraphError(f"node {node_id}: {key} must be a unit string")
        return units.parse_bits_per_sec(v)

    return ShadowNode(node_id, bw("host_bandwidth_up"), bw("host_bandwidth_down"))


def _parse_edge(raw: gml.GmlList) -> ShadowEdge:
    src, dst = raw.get("source"), raw.get("target")
    if not isinstance(src, int) or not isinstance(dst, int):
        raise GraphError("edge requires integer 'source' and 'target'")
    latency = raw.get("latency")
    if latency is None:
        raise GraphError(f"edge {src}->{dst}: 'latency' was not provided")
    latency_ns = units.parse_duration_ns(latency)
    if latency_ns <= 0:
        raise GraphError(f"edge {src}->{dst}: 'latency' must not be 0")
    jitter = raw.get("jitter")
    jitter_ns = units.parse_duration_ns(jitter) if jitter is not None else 0
    loss = float(raw.get("packet_loss", 0.0))
    if not 0.0 <= loss <= 1.0:
        raise GraphError(f"edge {src}->{dst}: packet_loss must be in [0,1]")
    return ShadowEdge(src, dst, latency_ns, jitter_ns, loss)


def load_graph_text(path: str) -> str:
    """Read GML from a path, transparently decompressing .xz/.gz
    (parity: reference compressed-graph support, `src/test/compressed-graph/`)."""
    if path.endswith(".xz"):
        with lzma.open(path, "rt") as fh:
            return fh.read()
    if path.endswith(".gz"):
        with gzip.open(path, "rt") as fh:
            return fh.read()
    with open(path) as fh:
        return fh.read()


class NetworkGraph:
    """Parsed topology with dense adjacency matrices."""

    def __init__(self, nodes: list[ShadowNode], edges: list[ShadowEdge], directed: bool):
        self.directed = directed
        self.nodes = nodes
        self.edges = edges
        self.node_id_to_index = {n.id: i for i, n in enumerate(nodes)}
        if len(self.node_id_to_index) != len(nodes):
            raise GraphError("duplicate node ids")
        n = len(nodes)
        # Dense adjacency; +inf latency = no edge. float64 holds ns values
        # exactly (< 2^53) and supports inf sentinels.
        lat = np.full((n, n), np.inf)
        loss = np.full((n, n), np.inf)
        count = np.zeros((n, n), dtype=np.int64)
        for e in edges:
            try:
                i, j = self.node_id_to_index[e.source], self.node_id_to_index[e.target]
            except KeyError as missing:
                raise GraphError(f"edge endpoint {missing} doesn't exist") from None
            pairs = [(i, j)] if directed else ({(i, j), (j, i)})
            for a, b in pairs:
                count[a, b] += 1
                # parallel edges: keep the (latency, loss)-lexicographic min
                if (e.latency_ns, e.packet_loss) < (lat[a, b], loss[a, b]):
                    lat[a, b], loss[a, b] = e.latency_ns, e.packet_loss
        self._lat = lat
        self._loss = loss
        self._edge_count = count

    @staticmethod
    def parse(text: str) -> "NetworkGraph":
        g = gml.parse(text)
        directed = bool(g.get("directed", 0))
        nodes = [_parse_node(x) for x in g.get_all("node")]
        edges = [_parse_edge(x) for x in g.get_all("edge")]
        if not nodes:
            raise GraphError("graph has no nodes")
        return NetworkGraph(nodes, edges, directed)

    def node_by_id(self, node_id: int) -> ShadowNode:
        try:
            return self.nodes[self.node_id_to_index[node_id]]
        except KeyError:
            raise GraphError(f"graph node {node_id} doesn't exist") from None

    # -- path computation ---------------------------------------------------

    def _self_loop(self, idx: int) -> tuple[float, float]:
        if self._edge_count[idx, idx] != 1:
            raise GraphError(
                f"node id {self.nodes[idx].id} must have exactly one self-loop "
                f"(found {self._edge_count[idx, idx]})"
            )
        return self._lat[idx, idx], self._loss[idx, idx]

    def compute_shortest_paths(
        self, used_ids: list[int]
    ) -> tuple[np.ndarray, np.ndarray]:
        """All-pairs shortest paths over the FULL graph (unused nodes still
        relay), returned as dense [U,U] (latency_ns int64, loss float32)
        matrices over `used_ids` order. Lexicographic (latency, loss) metric,
        matching the reference's Dijkstra weight ordering
        (`graph/mod.rs:305-315`)."""
        lat = self._lat.copy()
        loss = self._loss.copy()
        n = lat.shape[0]
        # Self-loops must not act as intermediate steps: Floyd–Warshall on a
        # latency>0 graph never prefers adding a self-loop, but the initial
        # diagonal would; clear it and re-apply the self-loop contract at the
        # end (the reference replaces Dijkstra's trivial 0-cost self paths
        # with the mandatory self-loop edge, graph/mod.rs:210-217).
        np.fill_diagonal(lat, 0.0)
        np.fill_diagonal(loss, 0.0)
        for k in range(n):
            new_lat = lat[:, k, None] + lat[None, k, :]
            ok_k = 1.0 - (1.0 - loss[:, k, None]) * (1.0 - loss[None, k, :])
            better = (new_lat < lat) | ((new_lat == lat) & (ok_k < loss))
            lat = np.where(better, new_lat, lat)
            loss = np.where(better, ok_k, loss)
        idx = self._used_indices(used_ids)
        out_lat = lat[np.ix_(idx, idx)]
        out_loss = loss[np.ix_(idx, idx)]
        for u, i in enumerate(idx):
            out_lat[u, u], out_loss[u, u] = self._self_loop(i)
        if np.isinf(out_lat).any():
            bad = np.argwhere(np.isinf(out_lat))[0]
            raise GraphError(
                f"no path between graph nodes "
                f"{self.nodes[idx[bad[0]]].id} and {self.nodes[idx[bad[1]]].id}"
            )
        return out_lat.astype(np.int64), out_loss.astype(np.float32)

    def get_direct_paths(self, used_ids: list[int]) -> tuple[np.ndarray, np.ndarray]:
        """Single-edge paths between every used pair; exactly one edge must
        exist per pair (`graph/mod.rs:230-252,258-266`)."""
        idx = self._used_indices(used_ids)
        for a in idx:
            for b in idx:
                if self._edge_count[a, b] != 1:
                    raise GraphError(
                        f"expected exactly one edge between nodes "
                        f"{self.nodes[a].id} and {self.nodes[b].id}, "
                        f"found {self._edge_count[a, b]}"
                    )
        out_lat = self._lat[np.ix_(idx, idx)]
        out_loss = self._loss[np.ix_(idx, idx)]
        return out_lat.astype(np.int64), out_loss.astype(np.float32)

    def _used_indices(self, used_ids: list[int]) -> list[int]:
        try:
            return [self.node_id_to_index[i] for i in used_ids]
        except KeyError as missing:
            raise GraphError(f"graph node {missing} doesn't exist") from None


class IpAssignment:
    """IP ↔ graph-node registry (`graph/mod.rs:352-420`)."""

    def __init__(self):
        self._ip_to_node: dict[str, int] = {}
        self._counter = int(ipaddress.IPv4Address("11.0.0.0"))

    def assign_manual(self, ip: str, node_id: int) -> None:
        ip = str(ipaddress.IPv4Address(ip))
        if ip in self._ip_to_node:
            raise GraphError(f"IP {ip} previously assigned")
        self._ip_to_node[ip] = node_id

    def assign_auto(self, node_id: int) -> str:
        while True:
            self._counter += 1
            ip = ipaddress.IPv4Address(self._counter)
            last = int(ip) & 0xFF
            if last in (0, 255):
                continue  # skip .0 and .255
            s = str(ip)
            if s not in self._ip_to_node:
                self._ip_to_node[s] = node_id
                return s

    def node_for(self, ip: str) -> Optional[int]:
        return self._ip_to_node.get(str(ip))


class RoutingInfo:
    """(src_node, dst_node) → PathProperties as dense arrays, plus packet
    counters (`graph/mod.rs:428-460`). `used_ids` defines the row/col order —
    the same order the TPU plane uses for its HBM latency/loss matrices."""

    def __init__(self, latency_ns: np.ndarray, packet_loss: np.ndarray, used_ids: list[int]):
        self.latency_ns = latency_ns
        self.packet_loss = packet_loss
        self.used_ids = list(used_ids)
        self._pos = {nid: i for i, nid in enumerate(self.used_ids)}
        self.packet_counters = np.zeros_like(latency_ns, dtype=np.int64)

    def path(self, src_node: int, dst_node: int) -> PathProperties:
        i, j = self._pos[src_node], self._pos[dst_node]
        return PathProperties(int(self.latency_ns[i, j]), float(self.packet_loss[i, j]))

    def node_index(self, node_id: int) -> int:
        """Row/col index of a node id in the dense matrices (used_ids
        order) — the same index the TPU plane's host_node map uses."""
        return self._pos[node_id]

    def increment_packet_count(self, src_node: int, dst_node: int, n: int = 1) -> None:
        self.packet_counters[self._pos[src_node], self._pos[dst_node]] += n

    def get_smallest_latency_ns(self) -> int:
        return int(self.latency_ns.min())


def build_routing(
    graph: NetworkGraph, used_ids: list[int], use_shortest_path: bool
) -> RoutingInfo:
    # deterministic, deduplicated node order
    seen: dict[int, None] = {}
    for nid in used_ids:
        seen.setdefault(nid, None)
    ids = list(seen)
    if use_shortest_path:
        lat, loss = graph.compute_shortest_paths(ids)
    else:
        lat, loss = graph.get_direct_paths(ids)
    return RoutingInfo(lat, loss, ids)
