"""The simulated NIC: socket association, send-side queuing disciplines,
receive-side delivery.

Parity: reference `src/main/host/network/network_interface.c` (+ Rust wrapper
`interface.rs`, qdiscs in `network_queuing_disciplines.c`):
- sockets associate with the interface under a (protocol, local port, peer)
  key; receive-side delivery prefers an exact 4-tuple match and falls back to
  the wildcard-peer (listening) association;
- the send side multiplexes ready sockets through a queuing discipline:
  FIFO by per-packet host-assigned priority, or round-robin across sockets
  (`network_interface.c:205-303`, `QDiscMode` `configuration.rs:961`);
- a pcap hook observes both directions.
"""

from __future__ import annotations

import heapq
from collections import deque
from typing import Callable, Optional, Protocol as TypingProtocol

from ..core.config import QDiscMode
from .packet import Packet, PacketDevice, PacketStatus, Protocol


class InterfaceSocket(TypingProtocol):
    """What the NIC needs from a socket."""

    def pull_out_packet(self) -> Optional[Packet]:
        """Pop this socket's next outgoing packet (None if none)."""

    def peek_next_priority(self) -> Optional[int]:
        """Priority of the next outgoing packet (None if none)."""

    def push_in_packet(self, packet: Packet) -> None:
        """Deliver an inbound packet to this socket."""


class AssociationKey:
    __slots__ = ("protocol", "local_port", "peer")

    def __init__(self, protocol: Protocol, local_port: int, peer: tuple[str, int]):
        self.protocol = protocol
        self.local_port = local_port
        self.peer = peer  # ("0.0.0.0", 0) = wildcard (listening)

    def _key(self):
        return (self.protocol, self.local_port, self.peer)

    def __hash__(self):
        return hash(self._key())

    def __eq__(self, other):
        return isinstance(other, AssociationKey) and self._key() == other._key()


WILDCARD_PEER = ("0.0.0.0", 0)


class NetworkInterface(PacketDevice):
    def __init__(
        self,
        address: str,
        qdisc: QDiscMode = QDiscMode.FIFO,
        pcap_hook: Optional[Callable[[Packet, bool], None]] = None,
    ):
        self._address = address
        self._qdisc = qdisc
        self._pcap_hook = pcap_hook  # (packet, inbound) -> None
        # administrative link state (the fault plane's iface_down/up
        # events, faults/schedule.py): down = outbound pop() yields
        # nothing, inbound push() drops with FAULT_DROPPED
        self.link_up = True
        self.fault_dropped = 0
        self._associations: dict[AssociationKey, InterfaceSocket] = {}
        # send-side: sockets with data, managed per qdisc
        self._ready_fifo: list[tuple[int, int, InterfaceSocket]] = []  # heap by priority
        self._ready_rr: deque[InterfaceSocket] = deque()
        self._ready_set: set[int] = set()  # id(socket) guards double-queueing
        self._fifo_counter = 0
        self.recv_bytes = 0
        self.send_bytes = 0

    # -- association (protocol, port, peer) ---------------------------------

    def associate(
        self,
        socket: InterfaceSocket,
        protocol: Protocol,
        local_port: int,
        peer: tuple[str, int] = WILDCARD_PEER,
    ) -> None:
        key = AssociationKey(protocol, local_port, peer)
        if key in self._associations:
            raise ValueError(
                f"association exists for {protocol.name} port {local_port} peer {peer}"
            )
        self._associations[key] = socket

    def disassociate(
        self,
        protocol: Protocol,
        local_port: int,
        peer: tuple[str, int] = WILDCARD_PEER,
    ) -> None:
        self._associations.pop(AssociationKey(protocol, local_port, peer), None)

    def is_associated(
        self, protocol: Protocol, local_port: int, peer: tuple[str, int] = WILDCARD_PEER
    ) -> bool:
        return AssociationKey(protocol, local_port, peer) in self._associations

    def socket_for(
        self, protocol: Protocol, local_port: int, peer: tuple[str, int]
    ) -> Optional[InterfaceSocket]:
        """Exact 4-tuple match first, then wildcard-peer (listening) match."""
        sock = self._associations.get(AssociationKey(protocol, local_port, peer))
        if sock is None:
            sock = self._associations.get(
                AssociationKey(protocol, local_port, WILDCARD_PEER)
            )
        return sock

    # -- send side ----------------------------------------------------------

    def add_data_source(self, socket: InterfaceSocket) -> None:
        """Socket announces it has packets to send; NIC queues it per qdisc."""
        if id(socket) in self._ready_set:
            return
        self._ready_set.add(id(socket))
        if self._qdisc == QDiscMode.FIFO:
            prio = socket.peek_next_priority()
            self._fifo_counter += 1
            heapq.heappush(
                self._ready_fifo,
                (prio if prio is not None else 0, self._fifo_counter, socket),
            )
        else:
            self._ready_rr.append(socket)

    def set_link_up(self, up: bool) -> None:
        """Administrative link flap (fault plane). Sockets keep queueing
        while the link is down; on restore the caller kicks the relays
        so the backlog forwards again."""
        self.link_up = bool(up)

    def has_data_to_send(self) -> bool:
        return self.link_up and bool(self._ready_fifo or self._ready_rr)

    def pop(self) -> Optional[Packet]:
        """Dequeue the next outgoing packet per the queuing discipline."""
        if not self.link_up:
            return None  # administratively down: nothing leaves
        while self._ready_fifo or self._ready_rr:
            if self._qdisc == QDiscMode.FIFO:
                _, _, socket = heapq.heappop(self._ready_fifo)
            else:
                socket = self._ready_rr.popleft()
            self._ready_set.discard(id(socket))
            packet = socket.pull_out_packet()
            if packet is None:
                continue  # socket had nothing after all; try next
            # requeue if the socket still has data (RR moves to tail; FIFO
            # reinserts keyed by its next packet's priority)
            if socket.peek_next_priority() is not None:
                self.add_data_source(socket)
            packet.add_status(PacketStatus.SND_INTERFACE_SENT)
            self.send_bytes += packet.total_size()
            if self._pcap_hook is not None:
                self._pcap_hook(packet, False)
            return packet
        return None

    # -- receive side -------------------------------------------------------

    def push(self, packet: Packet) -> None:
        if not self.link_up:
            # inbound during a link-down window: the NIC never sees it
            packet.add_status(PacketStatus.FAULT_DROPPED)
            self.fault_dropped += 1
            return
        self.recv_bytes += packet.total_size()
        packet.add_status(PacketStatus.RCV_INTERFACE_RECEIVED)
        if self._pcap_hook is not None:
            self._pcap_hook(packet, True)
        sock = self.socket_for(packet.protocol, packet.dst[1], packet.src)
        if sock is None:
            packet.add_status(PacketStatus.RCV_INTERFACE_DROPPED)
            return
        sock.push_in_packet(packet)

    def get_address(self) -> str:
        return self._address
